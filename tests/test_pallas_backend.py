"""Pallas tile lowering of the kernel language (kernel/pallas_backend.py):
elementwise kernels must produce bit-identical results to the vectorized
XLA lowering (codegen.py), and kernels outside the subset must be rejected
with PallasUnsupported so the registry falls back.

Runs in Pallas interpret mode on the CPU rig; the compiled-Mosaic path is
exercised on the real chip by bench.py (codegen_mpix)."""

import numpy as np
import pytest

from cekirdekler_tpu.kernel import codegen, lang
from cekirdekler_tpu.kernel.pallas_backend import (
    PallasUnsupported,
    build_kernel_fn_pallas,
)

SAXPY = """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = a * x[i] + y[i];
}
"""

MANDEL = """
__kernel void mandel(__global float* out, float x0, float dx, int maxIter) {
    int i = get_global_id(0);
    float cx = x0 + dx * (float)i;
    float zx = 0.0f;
    float zy = 0.0f;
    int it = 0;
    while (zx*zx + zy*zy < 4.0f && it < maxIter) {
        float t = zx*zx - zy*zy + cx;
        zy = 2.0f*zx*zy + 0.1f;
        zx = t;
        it++;
    }
    out[i] = (float)it;
}
"""

MASKED = """
__kernel void maskedset(__global float* o, __global float* a) {
    int i = get_global_id(0);
    if (a[i] > 0.5f) {
        o[i] = a[i] * 2.0f;
    } else {
        o[i] = -1.0f;
    }
}
"""

GATHER = """
__kernel void gather(__global float* x, __global int* idx, __global float* o) {
    int i = get_global_id(0);
    o[i] = x[idx[i]];
}
"""

SHIFTED = """
__kernel void shift(__global float* x, __global float* o) {
    int i = get_global_id(0);
    o[i] = x[i + 1];
}
"""


def _kdef(src: str) -> lang.KernelDef:
    return lang.parse_kernels(src)[0]


def _both(src: str, arrays, values=(), chunk=None, offset=0, global_size=None):
    """Run a kernel through the XLA lowering and the Pallas tile lowering
    (interpret mode) on identical inputs; return (xla_out, pallas_out)."""
    import jax.numpy as jnp

    kdef = _kdef(src)
    chunk = chunk or arrays[0].shape[0]
    gs = global_size or chunk
    xla_fn, _ = codegen.build_kernel_fn(kdef, chunk, 64, gs)
    pl_fn, _ = build_kernel_fn_pallas(kdef, chunk, 64, gs, interpret=True)
    jarr = tuple(jnp.asarray(a) for a in arrays)
    out_x = xla_fn(offset, jarr, values)
    out_p = pl_fn(offset, jarr, values)
    return out_x, out_p


def test_saxpy_matches_xla():
    n = 1024
    x = np.linspace(-2, 2, n).astype(np.float32)
    y = np.ones(n, np.float32)
    out_x, out_p = _both(SAXPY, (x, y), values=(3.0,))
    # 1-ulp differences allowed: the two lowerings may contract a*x+y
    # into fma differently
    np.testing.assert_allclose(np.asarray(out_x[1]), np.asarray(out_p[1]), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out_p[1]), 3.0 * x + 1.0, rtol=1e-6, atol=1e-6)


def test_while_loop_kernel_matches_xla():
    n = 512
    out = np.zeros(n, np.float32)
    out_x, out_p = _both(MANDEL, (out,), values=(-2.0, 0.004, 64))
    np.testing.assert_array_equal(np.asarray(out_x[0]), np.asarray(out_p[0]))
    got = np.asarray(out_p[0])
    assert got.min() >= 0 and got.max() <= 64 and len(np.unique(got)) > 3


def test_masked_branch_matches_xla():
    n = 256
    rng = np.random.default_rng(7)
    a = rng.random(n).astype(np.float32)
    o = np.zeros(n, np.float32)
    out_x, out_p = _both(MASKED, (o, a))
    np.testing.assert_array_equal(np.asarray(out_x[0]), np.asarray(out_p[0]))
    want = np.where(a > 0.5, a * 2.0, -1.0).astype(np.float32)
    np.testing.assert_allclose(np.asarray(out_p[0]), want, rtol=1e-6)


def test_offset_window_into_larger_buffer():
    """chunk < buffer: the Pallas path slices the window at a runtime
    offset and update-slices the result back (multi-chip range slices)."""
    n, chunk, off = 1024, 256, 384
    x = np.arange(n, dtype=np.float32)
    y = np.zeros(n, np.float32)
    out_x, out_p = _both(SAXPY, (x, y), values=(2.0,), chunk=chunk,
                         offset=off, global_size=n)
    np.testing.assert_allclose(np.asarray(out_x[1]), np.asarray(out_p[1]), rtol=1e-6, atol=1e-6)
    got = np.asarray(out_p[1])
    assert np.all(got[:off] == 0) and np.all(got[off + chunk:] == 0)
    np.testing.assert_allclose(got[off:off + chunk], 2.0 * x[off:off + chunk])


@pytest.mark.parametrize("src,name", [(GATHER, "gather"), (SHIFTED, "shift")])
def test_non_elementwise_rejected(src, name):
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(src), 256, 64, 256, interpret=True)


def test_chunk_not_lane_aligned_rejected():
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(SAXPY), 200, 50, 200, interpret=True)


def test_registry_falls_back_off_tpu():
    """launcher(platform='cpu') must use the XLA path (no Mosaic on CPU);
    platform='tpu' on a gather kernel must also fall back rather than
    fail."""
    from cekirdekler_tpu.kernel.registry import KernelProgram

    prog = KernelProgram(SAXPY + GATHER)
    fn_cpu, _ = prog.launcher("saxpy", 256, 64, 256, platform="cpu")
    assert fn_cpu is not None
    fn_gather, _ = prog.launcher("gather", 256, 64, 256, platform="tpu")
    assert fn_gather is not None  # fell back to the XLA lowering
