"""Pallas tile lowering of the kernel language (kernel/pallas_backend.py):
elementwise kernels must produce bit-identical results to the vectorized
XLA lowering (codegen.py), and kernels outside the subset must be rejected
with PallasUnsupported so the registry falls back.

Runs in Pallas interpret mode on the CPU rig; the compiled-Mosaic path is
exercised on the real chip by bench.py (codegen_mpix)."""

import numpy as np
import pytest

from cekirdekler_tpu.kernel import codegen, lang
from cekirdekler_tpu.kernel.pallas_backend import (
    PallasUnsupported,
    build_kernel_fn_pallas,
)

import jax.experimental.pallas as _pl

# env capability, not a code property: these cases build real Pallas
# tile programs, which need pl.Element (pallas_backend.py:469) — absent
# from this container's jax, so they failed identically every run.  The
# subset-REJECTION tests (PallasUnsupported raised before any tile
# program is built) run everywhere.
requires_pl_element = pytest.mark.skipif(
    not hasattr(_pl, "Element"),
    reason="jax.experimental.pallas lacks pl.Element in this environment "
           "(pre-0.5-era pallas) — the widened tile lowering cannot build",
)

SAXPY = """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = a * x[i] + y[i];
}
"""

MANDEL = """
__kernel void mandel(__global float* out, float x0, float dx, int maxIter) {
    int i = get_global_id(0);
    float cx = x0 + dx * (float)i;
    float zx = 0.0f;
    float zy = 0.0f;
    int it = 0;
    while (zx*zx + zy*zy < 4.0f && it < maxIter) {
        float t = zx*zx - zy*zy + cx;
        zy = 2.0f*zx*zy + 0.1f;
        zx = t;
        it++;
    }
    out[i] = (float)it;
}
"""

MASKED = """
__kernel void maskedset(__global float* o, __global float* a) {
    int i = get_global_id(0);
    if (a[i] > 0.5f) {
        o[i] = a[i] * 2.0f;
    } else {
        o[i] = -1.0f;
    }
}
"""

GATHER = """
__kernel void gather(__global float* x, __global int* idx, __global float* o) {
    int i = get_global_id(0);
    o[i] = x[idx[i]];
}
"""

SHIFTED = """
__kernel void shift(__global float* x, __global float* o) {
    int i = get_global_id(0);
    o[i] = x[i + 1];
}
"""

STENCIL = """
__kernel void wave(__global float* p, __global float* pold, __global float* pnew) {
    int i = get_global_id(0);
    float lap = p[i-1] + p[i+1] + p[i-128] + p[i+128] + p[i-129] + p[i+129]
              + p[i-127] + p[i+127] - 8.0f*p[i];
    pnew[i] = 2.0f*p[i] - pold[i] + 0.2f*lap;
}
"""

UNIFORM_LOOP = """
__kernel void dotrow(__global float* w, __global float* x, __global float* o, int m) {
    int i = get_global_id(0);
    float acc = 0.0f;
    for (int j = 0; j < m; j++) {
        acc = acc + w[j] * x[i];
    }
    o[i] = acc + w[0];
}
"""

STORE_SHIFT_MIX = """
__kernel void m(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i + 1] * 2.0f;
}
"""


def _kdef(src: str) -> lang.KernelDef:
    return lang.parse_kernels(src)[0]


def _both(src: str, arrays, values=(), chunk=None, offset=0, global_size=None):
    """Run a kernel through the XLA lowering and the Pallas tile lowering
    (interpret mode) on identical inputs; return (xla_out, pallas_out)."""
    import jax.numpy as jnp

    kdef = _kdef(src)
    chunk = chunk or arrays[0].shape[0]
    gs = global_size or chunk
    xla_fn, _ = codegen.build_kernel_fn(kdef, chunk, 64, gs)
    pl_fn, _ = build_kernel_fn_pallas(kdef, chunk, 64, gs, interpret=True,
                                     force=True)
    jarr = tuple(jnp.asarray(a) for a in arrays)
    out_x = xla_fn(offset, jarr, values)
    out_p = pl_fn(offset, jarr, values)
    return out_x, out_p


@requires_pl_element
def test_saxpy_matches_xla():
    n = 1024
    x = np.linspace(-2, 2, n).astype(np.float32)
    y = np.ones(n, np.float32)
    out_x, out_p = _both(SAXPY, (x, y), values=(3.0,))
    # 1-ulp differences allowed: the two lowerings may contract a*x+y
    # into fma differently
    np.testing.assert_allclose(np.asarray(out_x[1]), np.asarray(out_p[1]), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out_p[1]), 3.0 * x + 1.0, rtol=1e-6, atol=1e-6)


@requires_pl_element
def test_while_loop_kernel_matches_xla():
    n = 512
    out = np.zeros(n, np.float32)
    out_x, out_p = _both(MANDEL, (out,), values=(-2.0, 0.004, 64))
    np.testing.assert_array_equal(np.asarray(out_x[0]), np.asarray(out_p[0]))
    got = np.asarray(out_p[0])
    assert got.min() >= 0 and got.max() <= 64 and len(np.unique(got)) > 3


@requires_pl_element
def test_masked_branch_matches_xla():
    n = 256
    rng = np.random.default_rng(7)
    a = rng.random(n).astype(np.float32)
    o = np.zeros(n, np.float32)
    out_x, out_p = _both(MASKED, (o, a))
    np.testing.assert_array_equal(np.asarray(out_x[0]), np.asarray(out_p[0]))
    want = np.where(a > 0.5, a * 2.0, -1.0).astype(np.float32)
    np.testing.assert_allclose(np.asarray(out_p[0]), want, rtol=1e-6)


@requires_pl_element
def test_offset_window_into_larger_buffer():
    """chunk < buffer: the Pallas path slices the window at a runtime
    offset and update-slices the result back (multi-chip range slices)."""
    n, chunk, off = 1024, 256, 384
    x = np.arange(n, dtype=np.float32)
    y = np.zeros(n, np.float32)
    out_x, out_p = _both(SAXPY, (x, y), values=(2.0,), chunk=chunk,
                         offset=off, global_size=n)
    np.testing.assert_allclose(np.asarray(out_x[1]), np.asarray(out_p[1]), rtol=1e-6, atol=1e-6)
    got = np.asarray(out_p[1])
    assert np.all(got[:off] == 0) and np.all(got[off + chunk:] == 0)
    np.testing.assert_allclose(got[off:off + chunk], 2.0 * x[off:off + chunk])


def test_per_lane_gather_rejected():
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(GATHER), 256, 64, 256, interpret=True)


def test_store_plus_shift_read_rejected():
    """A store into an array that is also shift-read would see stale
    neighbor tiles; must fall back to the XLA lowering."""
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(STORE_SHIFT_MIX), 256, 64, 256, interpret=True)


@requires_pl_element
def test_shifted_window_matches_xla():
    """a[i+1] now lowers to a halo block + lane roll (widened subset)."""
    n = 1024
    x = np.arange(n, dtype=np.float32)
    o = np.zeros(n, np.float32)
    out_x, out_p = _both(SHIFTED, (x, o))
    np.testing.assert_array_equal(np.asarray(out_x[1]), np.asarray(out_p[1]))
    got = np.asarray(out_p[1])
    # edge clamp: last element reads x[n-1] (nearest valid), same as the
    # XLA padded-view semantics
    assert got[-1] == x[-1]
    np.testing.assert_array_equal(got[:-1], x[1:])


@requires_pl_element
def test_stencil_multi_tap_matches_xla_across_offsets():
    """8-tap wave stencil: row- and lane-crossing shifts, offset launches
    into a larger buffer, edge-clamp agreement at both ends."""
    n, chunk = 2048, 512
    rng = np.random.default_rng(11)
    arrays = tuple(rng.standard_normal(n).astype(np.float32) for _ in range(3))
    for off in (0, 512, n - chunk):
        out_x, out_p = _both(STENCIL, arrays, chunk=chunk, offset=off,
                             global_size=n)
        np.testing.assert_allclose(
            np.asarray(out_x[2]), np.asarray(out_p[2]), rtol=1e-5, atol=1e-5)


@requires_pl_element
def test_uniform_gather_loop_matches_xla():
    """The n-body shape: a lane-uniform loop index streaming a second
    buffer (SMEM operand) plus a constant-index broadcast w[0]."""
    n = 512
    rng = np.random.default_rng(13)
    w = rng.standard_normal(n).astype(np.float32)
    x = rng.standard_normal(n).astype(np.float32)
    o = np.zeros(n, np.float32)
    out_x, out_p = _both(UNIFORM_LOOP, (w, x, o), values=(17,))
    np.testing.assert_allclose(
        np.asarray(out_x[2]), np.asarray(out_p[2]), rtol=1e-5, atol=1e-5)


@requires_pl_element
def test_nbody_kernel_matches_xla():
    """The full NBODY_SRC kernel (uniform x[j]/y[j]/z[j] loads + elementwise
    velocity updates) through both lowerings."""
    from cekirdekler_tpu.workloads import NBODY_SRC

    n = 256
    rng = np.random.default_rng(17)
    arrays = tuple(rng.standard_normal(n).astype(np.float32) for _ in range(6))
    kdef = {k.name: k for k in lang.parse_kernels(NBODY_SRC)}["nBody"]
    import jax.numpy as jnp

    xla_fn, _ = codegen.build_kernel_fn(kdef, n, 64, n)
    pl_fn, _ = build_kernel_fn_pallas(kdef, n, 64, n, interpret=True)
    jarr = tuple(jnp.asarray(a) for a in arrays)
    vals = (np.int32(n), np.float32(1e-3))
    out_x = xla_fn(0, jarr, vals)
    out_p = pl_fn(0, jarr, vals)
    for a, b in zip(out_x, out_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


@requires_pl_element
def test_smem_limit_falls_back_inside_fn(monkeypatch):
    """Uniform-read buffers beyond the SMEM budget delegate to the XLA
    lowering at trace time — same results, no failure."""
    from cekirdekler_tpu.kernel import pallas_backend

    monkeypatch.setattr(pallas_backend, "SMEM_UNIFORM_LIMIT", 64)  # bytes
    n = 512
    rng = np.random.default_rng(19)
    w = rng.standard_normal(n).astype(np.float32)
    x = rng.standard_normal(n).astype(np.float32)
    o = np.zeros(n, np.float32)
    out_x, out_p = _both(UNIFORM_LOOP, (w, x, o), values=(9,))
    np.testing.assert_allclose(
        np.asarray(out_x[2]), np.asarray(out_p[2]), rtol=1e-5, atol=1e-5)


def test_chunk_not_lane_aligned_rejected():
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(SAXPY), 200, 50, 200, interpret=True)


def test_registry_falls_back_off_tpu():
    """launcher(platform='cpu') must use the XLA path (no Mosaic on CPU);
    platform='tpu' on a gather kernel must also fall back rather than
    fail."""
    from cekirdekler_tpu.kernel.registry import KernelProgram

    prog = KernelProgram(SAXPY + GATHER)
    fn_cpu, _ = prog.launcher("saxpy", 256, 64, 256, platform="cpu")
    assert fn_cpu is not None
    fn_gather, _ = prog.launcher("gather", 256, 64, 256, platform="tpu")
    assert fn_gather is not None  # fell back to the XLA lowering


@requires_pl_element
def test_shift_only_routing_veto():
    """Measured routing policy: shift-only kernels prefer the XLA lowering
    (faster on HBM-bound single-pass stencils); force=True overrides for
    direct measurement."""
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(STENCIL), 512, 64, 512, interpret=True)
    fn, _ = build_kernel_fn_pallas(_kdef(STENCIL), 512, 64, 512,
                                   interpret=True, force=True)
    assert fn is not None


@requires_pl_element
def test_multi_tile_grid_halo_and_smem():
    """grid > 1 coverage for the widened paths: small block_rows force
    multiple tiles, so the pl.Element halo index map, the 8-row alignment
    rounding in _halo_rows, and per-tile SMEM loads all execute — with an
    offset launch into a larger buffer on top."""
    import jax.numpy as jnp

    MIXED = """
    __kernel void mx(__global float* w, __global float* p, __global float* o, int m) {
        int i = get_global_id(0);
        float acc = p[i-1] + p[i+1] + p[i-130] + p[i+130];
        for (int j = 0; j < m; j++) {
            acc = acc + w[j] * 0.125f;
        }
        o[i] = acc;
    }"""
    kdef = _kdef(MIXED)
    n, chunk, off = 16384, 8192, 4096
    rng = np.random.default_rng(23)
    arrays = tuple(
        jnp.asarray(rng.standard_normal(n).astype(np.float32)) for _ in range(3)
    )
    vals = (np.int32(11),)
    xla_fn, _ = codegen.build_kernel_fn(kdef, chunk, 64, n)
    # block_rows=16 -> rows=16, grid=4 (multi-tile); halo h rounds to 4
    pl_fn, _ = build_kernel_fn_pallas(kdef, chunk, 64, n, block_rows=16,
                                      interpret=True, force=True)
    for o in (0, off, n - chunk):
        got_x = xla_fn(o, arrays, vals)
        got_p = pl_fn(o, arrays, vals)
        np.testing.assert_allclose(
            np.asarray(got_x[2]), np.asarray(got_p[2]), rtol=1e-5, atol=1e-5,
            err_msg=f"grid>1 divergence at offset {o}")


@requires_pl_element
def test_f16_arrays_delegate_to_xla_inside_fn():
    """float16 tiles fail the Mosaic compile on the real chip AFTER the
    registry's build-time fallback window, so the launch fn itself must
    delegate f16 arrays to the XLA lowering at trace time (probed
    on-device, r4) — including kernels whose LOOP CARRIES are seeded from
    the mismatched-dtype load (loads cast to the declared ctype; stores
    cast back to the storage dtype)."""
    n = 512
    x = np.linspace(-2, 2, n).astype(np.float16)
    y = np.ones(n, np.float16)
    out_x, out_p = _both(SAXPY, (x, y), values=(3.0,))
    np.testing.assert_allclose(np.asarray(out_x[1]), np.asarray(out_p[1]),
                               rtol=1e-2, atol=1e-2)
    # loop carry seeded from the f16 load: float-declared local must run
    # the while in f32 (declared), store back f16
    LOOPY = """
    __kernel void lp(__global float* x, __global float* o, float a) {
        int i = get_global_id(0);
        float t = x[i];
        while (t < a) {
            t = t + a * 0.25f;
        }
        o[i] = t;
    }"""
    x = (np.linspace(-2, 2, n)).astype(np.float16)
    o = np.zeros(n, np.float16)
    out_x, out_p = _both(LOOPY, (x, o), values=(1.0,))
    np.testing.assert_allclose(np.asarray(out_x[1]), np.asarray(out_p[1]),
                               rtol=1e-2, atol=1e-2)


@requires_pl_element
def test_half_declared_kernel_vetoed_for_mosaic():
    """A kernel that DECLARES half (param/local/cast) creates f16 tiles
    internally regardless of the caller's array dtypes — vetoed at build
    time for compiled Mosaic, allowed in interpret mode."""
    HALFY = """
    __kernel void h(__global float* x, __global float* o) {
        int i = get_global_id(0);
        half t = (half)(x[i]);
        o[i] = (float)(t) * 2.0f;
    }"""
    with pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(_kdef(HALFY), 256, 64, 256, interpret=False,
                               force=True)
    fn, _ = build_kernel_fn_pallas(_kdef(HALFY), 256, 64, 256,
                                   interpret=True, force=True)
    assert fn is not None


@requires_pl_element
def test_bf16_arrays_through_real_pallas_path():
    """bfloat16 arrays against a float-declared kernel exercise the
    actual-dtype out_shape + load/store casts on the PALLAS path (bf16 is
    not delegated — Mosaic handles it)."""
    import jax.numpy as jnp

    n = 512
    x = jnp.asarray(np.linspace(-2, 2, n), jnp.bfloat16)
    y = jnp.ones(n, jnp.bfloat16)
    kdef = _kdef(SAXPY)
    xla_fn, _ = codegen.build_kernel_fn(kdef, n, 64, n)
    pl_fn, _ = build_kernel_fn_pallas(kdef, n, 64, n, interpret=True,
                                      force=True)
    gx = xla_fn(0, (x, y), (3.0,))
    gp = pl_fn(0, (x, y), (3.0,))
    assert gp[1].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(gx[1], dtype=np.float32), np.asarray(gp[1], dtype=np.float32),
        rtol=2e-2, atol=2e-2)
