"""Differential-oracle corpus for the kernel partition-safety verifier.

Two halves:

1. **The corpus** (:data:`CORPUS`): ≥20 kernels with declared
   :class:`TransferFlags` per array — a dozen safe shapes covering the
   supported surface (elementwise, uniform gathers under full reads,
   stencils under full reads, helpers, branches, private arrays,
   epw>1, covered write-only), and ≥8 deliberately unsafe shapes (halo
   and gathered reads under ``partial_read``, scatter and shifted
   writes, read-before-write under ``write_only``, a cross-kernel
   window RAW hazard, a clipped ``write_all``, a uniform-index write).
   Each entry names the error kinds ``analysis.verify_launch`` must
   emit (empty = must be clean).

2. **The differential oracle** (:func:`run_lanes` / :func:`run_pure` /
   :func:`ground_truth_unsafe`): a flag-faithful lane simulator built
   on the scalar reference interpreter (``tests/kernel_oracle.py`` —
   itself differentially fuzzed against both compiled lowerings).  It
   stages device buffers per lane exactly like ``Worker.upload``
   (full copy for full reads; the lane's slice over zeros for
   ``partial_read``; zeros for never-uploaded arrays), runs the kernel
   sequence per lane over its range, and writes back each lane's slice
   (or the owner's whole array under ``write_all``) exactly like the
   flush path.  **Ground truth**: a (kernels, flags) launch is unsafe
   iff a ≥2-lane split differs bit-exactly from the unsplit run, or
   the unsplit run differs from the pure language semantics (all
   arrays visible — the flag-lie detector for ``write_only``
   read-before-write).

tools/ckprove's corpus scan deliberately excludes ``tests/`` — the
unsafe kernels here are planted on purpose.
"""

from __future__ import annotations

import os
import sys
import zlib
from dataclasses import dataclass, field

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from cekirdekler_tpu.arrays.clarray import TransferFlags  # noqa: E402
from cekirdekler_tpu.kernel import lang  # noqa: E402
from tests.kernel_oracle import Oracle  # noqa: E402


@dataclass(frozen=True)
class CorpusKernel:
    """One corpus entry: kernels + flags + the expected error kinds."""

    name: str
    source: str
    flags: tuple                 # TransferFlags kwargs per call param
    expect: tuple = ()           # expected ERROR kinds (empty = safe)
    values: tuple = ()           # positional scalar args (all kernels)
    global_range: int = 192
    local_range: int = 32
    iters: int = 1               # window iterations (enqueue semantics)
    window: bool = False         # verdict treats the sequence as cyclic
    init: dict = field(default_factory=dict)   # pos -> fn(rng, n) -> arr
    sizes: tuple | None = None   # per-param element counts


# ---------------------------------------------------------------------------
# the flag-faithful lane simulator
# ---------------------------------------------------------------------------

def _split(global_range: int, lanes: int, step: int):
    """Equal split in step quanta (offsets, sizes) — the first-call
    split shape; WHICH equal split is irrelevant to the oracle (it
    compares split vs unsplit of the same simulator)."""
    units = global_range // step
    base, rem = divmod(units, lanes)
    sizes = [(base + (1 if i < rem else 0)) * step for i in range(lanes)]
    offs, acc = [], 0
    for s in sizes:
        offs.append(acc)
        acc += s
    return offs, sizes


def _vals_for(kdef, values):
    names = [p.name for p in kdef.params if not p.is_pointer]
    if isinstance(values, dict):
        vals = values.get(kdef.name, ())
    else:
        vals = tuple(values)
    return dict(zip(names, vals))


def _bind_arrays(kdef, bufs):
    pnames = [p.name for p in kdef.params if p.is_pointer]
    return {name: bufs[j] for j, name in enumerate(pnames)}


def run_lanes(
    kdefs, host_arrays, flags, values, global_range, local_range,
    lanes, iters=1,
):
    """Simulate the staged/split/write-back machine semantics on
    ``lanes`` virtual lanes; returns the final host arrays (copies)."""
    host = [np.array(a, copy=True) for a in host_arrays]
    offs, sizes = _split(global_range, lanes, local_range)
    active = [i for i in range(lanes) if sizes[i] > 0]
    single = len(active) == 1
    # stage per-lane device buffers (Worker.upload semantics)
    dev: list[list[np.ndarray]] = []
    for li in range(lanes):
        bufs = []
        for a, fl in zip(host, flags):
            epw = fl.elements_per_work_item
            if fl.read and not fl.write_only:
                if single or not fl.partial_read:
                    bufs.append(a.copy())
                else:
                    b = np.zeros_like(a)
                    s = slice(offs[li] * epw, (offs[li] + sizes[li]) * epw)
                    b[s] = a[s]
                    bufs.append(b)
            else:
                bufs.append(np.zeros_like(a))  # ensure_resident: zeros
        dev.append(bufs)
    # run the window per lane (kernel-major, like Worker.launch)
    for li in active:
        for _ in range(iters):
            for kdef in kdefs:
                oracle = Oracle(kdef, local_size=local_range)
                arrays = _bind_arrays(kdef, dev[li])
                vals = _vals_for(kdef, values)
                for gid in range(offs[li], offs[li] + sizes[li]):
                    oracle._run_item(gid, arrays, vals, global_range)
    # write back (flush semantics): slices per lane, whole from the
    # write_all owner ("device i writes array (i mod numDevices)")
    owner = {
        idx: active[idx % len(active)]
        for idx, fl in enumerate(flags) if fl.write_all
    } if active else {}
    for idx, (a, fl) in enumerate(zip(host, flags)):
        if fl.write and not fl.read_only:
            if fl.write_all:
                a[:] = dev[owner[idx]][idx]
            else:
                epw = fl.elements_per_work_item
                for li in active:
                    s = slice(offs[li] * epw, (offs[li] + sizes[li]) * epw)
                    a[s] = dev[li][idx][s]
    return host


def run_pure(kdefs, host_arrays, values, global_range, local_range,
             iters=1):
    """The language's own semantics: every array fully visible, every
    store lands — what the kernel MEANS, flags aside."""
    host = [np.array(a, copy=True) for a in host_arrays]
    for _ in range(iters):
        for kdef in kdefs:
            oracle = Oracle(kdef, local_size=local_range)
            arrays = _bind_arrays(kdef, host)
            vals = _vals_for(kdef, values)
            for gid in range(global_range):
                oracle._run_item(gid, arrays, vals, global_range)
    return host


def build(entry: CorpusKernel):
    """``(kdefs, flags_objs, host_arrays)`` for one corpus entry —
    deterministic per entry name."""
    kdefs = lang.parse_kernels(entry.source)
    flags = []
    for kw in entry.flags:
        f = TransferFlags(**kw)
        f.validate()
        flags.append(f)
    rng = np.random.default_rng(zlib.crc32(entry.name.encode()))
    host = []
    for pos, fl in enumerate(flags):
        n = (entry.sizes[pos] if entry.sizes is not None
             else entry.global_range * fl.elements_per_work_item)
        if pos in entry.init:
            host.append(np.asarray(entry.init[pos](rng, n), np.float32))
        else:
            # nonzero everywhere: a staged-zero leaking into a result
            # must CHANGE it, never coincide
            host.append(
                rng.uniform(0.5, 1.5, n).astype(np.float32))
    return kdefs, flags, host


def ground_truth_unsafe(entry: CorpusKernel, lanes: int = 2) -> bool:
    """True iff the differential oracle refutes the launch: the
    ``lanes``-way split differs from unsplit, or unsplit differs from
    the pure semantics (see module doc)."""
    kdefs, flags, host = build(entry)
    args = (kdefs, host, entry.values, entry.global_range,
            entry.local_range)
    pure = run_pure(*args, iters=entry.iters)
    unsplit = run_lanes(
        kdefs, host, flags, entry.values, entry.global_range,
        entry.local_range, lanes=1, iters=entry.iters)
    split = run_lanes(
        kdefs, host, flags, entry.values, entry.global_range,
        entry.local_range, lanes=lanes, iters=entry.iters)
    for p, u, s in zip(pure, unsplit, split):
        if not (np.array_equal(u, s) and np.array_equal(p, u)):
            return True
    return False


def verdict_for(entry: CorpusKernel):
    """The verifier's launch verdict for one entry."""
    from cekirdekler_tpu import analysis

    kdefs, flags, _host = build(entry)
    sums = {k.name: analysis.summarize_kernel(k) for k in kdefs}
    rows = tuple(analysis.flag_row(f) for f in flags)
    return analysis.verify_launch(
        sums, tuple(k.name for k in kdefs), rows, window=entry.window,
        where=f"corpus:{entry.name}")


# ---------------------------------------------------------------------------
# the corpus
# ---------------------------------------------------------------------------

def _rev_idx(rng, n):
    return np.arange(n - 1, -1, -1, dtype=np.float32)


def _cross_idx(rng, n):
    return ((np.arange(n) + n // 2) % n).astype(np.float32)


CORPUS = (
    # -- safe: the supported surface -------------------------------------
    CorpusKernel(
        "saxpy", """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = a * x[i] + y[i];
}""", (dict(partial_read=True, read_only=True), dict(partial_read=True)),
        values=(1.5,)),
    CorpusKernel(
        "vadd_wo", """
__kernel void vadd(__global float* a, __global float* b, __global float* c) {
    int i = get_global_id(0);
    c[i] = a[i] + b[i];
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, read_only=True), dict(write_only=True))),
    CorpusKernel(
        "escape_loop", """
__kernel void esc(__global float* cx, __global float* out, int maxIter) {
    int i = get_global_id(0);
    float z = 0.0f;
    int it = 0;
    while (z < 4.0f && it < maxIter) {
        z = z * z + cx[i];
        it++;
    }
    out[i] = (float)it;
}""", (dict(partial_read=True, read_only=True),
       dict(read=False, write=True)), values=(12,)),
    CorpusKernel(
        "gather_full", """
__kernel void nb(__global float* x, __global float* v, int n, float dt) {
    int i = get_global_id(0);
    float acc = 0.0f;
    for (int j = 0; j < n; j++) {
        acc = acc + x[j] - x[i];
    }
    v[i] = v[i] + acc * dt;
}""", (dict(read_only=True), dict(partial_read=True)),
        values=(192, 0.25), global_range=192),
    CorpusKernel(
        "stencil_full", """
__kernel void st(__global float* p, __global float* out) {
    int i = get_global_id(0);
    out[i] = p[i-1] + 2.0f*p[i] + p[i+1];
}""", (dict(read_only=True), dict(write_only=True))),
    CorpusKernel(
        "helper_safe", """
float sq(float v) {
    float w = v * v;
    return w;
}
__kernel void hs(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = sq(x[i]) + sq(2.0f);
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True))),
    CorpusKernel(
        "branch_safe", """
__kernel void br(__global float* x, __global float* y) {
    int i = get_global_id(0);
    if (x[i] > 1.0f) {
        y[i] = x[i] * 2.0f;
    } else {
        y[i] = x[i] + 0.5f;
    }
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True))),
    CorpusKernel(
        "private_array", """
__kernel void pa(__global float* x, __global float* y) {
    int i = get_global_id(0);
    float acc[4];
    for (int k = 0; k < 4; k++) { acc[k] = x[i] * (float)k; }
    y[i] = acc[0] + acc[1] + acc[2] + acc[3];
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True))),
    CorpusKernel(
        "epw2", """
__kernel void e2(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[2*i] = x[2*i] + x[2*i+1];
    y[2*i+1] = x[2*i] - x[2*i+1];
}""", (dict(partial_read=True, read_only=True, elements_per_work_item=2),
       dict(partial_read=True, write_only=True, elements_per_work_item=2)),
        global_range=96),
    CorpusKernel(
        "do_while_safe", """
__kernel void dw(__global float* x, __global float* y, int reps) {
    int i = get_global_id(0);
    float acc = x[i];
    int k = 0;
    do {
        acc = acc * 0.5f + 0.25f;
        k++;
    } while (k < reps);
    y[i] = acc;
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True)), values=(5,)),
    CorpusKernel(
        "wo_covered", """
__kernel void cov(__global float* a, __global float* c) {
    int i = get_global_id(0);
    c[i] = 0.0f;
    c[i] += a[i];
    c[i] += a[i] * 0.5f;
}""", (dict(partial_read=True, read_only=True), dict(write_only=True))),
    CorpusKernel(
        "seq_safe", """
__kernel void stage1(__global float* a, __global float* t, __global float* b) {
    int i = get_global_id(0);
    t[i] = a[i] * 2.0f;
}
__kernel void stage2(__global float* a, __global float* t, __global float* b) {
    int i = get_global_id(0);
    b[i] = t[i] + 1.0f;
}""", (dict(partial_read=True, read_only=True), dict(partial_read=True),
       dict(partial_read=True)), iters=2, window=True),
    CorpusKernel(
        "const_branch", """
__kernel void cb(__global float* x, __global float* y) {
    int i = get_global_id(0);
    if (i == 0) {
        y[i] = x[i];
    } else {
        y[i] = x[i] * 3.0f;
    }
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True))),

    # -- unsafe: each caught with a named finding ------------------------
    CorpusKernel(
        "halo_partial", """
__kernel void sh(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i+1] + x[i];
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True)),
        expect=("partial-read-halo",)),
    CorpusKernel(
        "halo_neg", """
__kernel void shn(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i] - x[i-1];
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True)),
        expect=("partial-read-halo",)),
    CorpusKernel(
        "gather_partial", """
__kernel void gp(__global float* x, __global float* v, int n) {
    int i = get_global_id(0);
    float acc = 0.0f;
    for (int j = 0; j < n; j++) { acc = acc + x[j]; }
    v[i] = acc;
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True)),
        values=(192,), expect=("partial-read-gather",)),
    CorpusKernel(
        "indirect_read", """
__kernel void ir(__global float* idx, __global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[(int)idx[i]];
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True)),
        init={0: _cross_idx}, expect=("partial-read-gather",)),
    CorpusKernel(
        "scatter_write", """
__kernel void sw(__global float* idx, __global float* x, __global float* out) {
    int i = get_global_id(0);
    out[(int)idx[i]] = x[i];
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, read_only=True), dict(write_only=True)),
        init={0: _rev_idx}, expect=("scatter-write",)),
    CorpusKernel(
        "shift_write", """
__kernel void shw(__global float* x, __global float* out) {
    int i = get_global_id(0);
    out[i+1] = x[i];
}""", (dict(partial_read=True, read_only=True), dict(write_only=True)),
        expect=("off-partition-write",)),
    CorpusKernel(
        "uniform_write", """
__kernel void uw(__global float* x, __global float* out) {
    int i = get_global_id(0);
    out[5] = x[i];
}""", (dict(partial_read=True, read_only=True), dict(write_only=True)),
        expect=("off-partition-write",)),
    CorpusKernel(
        "wo_rbw", """
__kernel void rbw(__global float* a, __global float* c) {
    int i = get_global_id(0);
    c[i] = c[i] * 0.5f + a[i];
}""", (dict(partial_read=True, read_only=True), dict(write_only=True)),
        expect=("write-only-read",)),
    CorpusKernel(
        "window_raw", """
__kernel void wrA(__global float* p, __global float* q, __global float* s) {
    int i = get_global_id(0);
    p[i] = p[i] + q[i];
}
__kernel void wrB(__global float* p, __global float* q, __global float* s) {
    int i = get_global_id(0);
    s[i] = s[i] + p[i+1];
}""", (dict(), dict(partial_read=True, read_only=True),
       dict(partial_read=True)),
        iters=2, window=True, expect=("window-raw",)),
    CorpusKernel(
        "write_all_clipped", """
__kernel void wac(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i] * 2.0f;
}""", (dict(partial_read=True, read_only=True), dict(write_all=True)),
        expect=("write-all-clipped",)),
)

SAFE = tuple(e for e in CORPUS if not e.expect)
UNSAFE = tuple(e for e in CORPUS if e.expect)
