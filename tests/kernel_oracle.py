"""Scalar oracle interpreter for the kernel language.

Executes a parsed kernel ONE WORK ITEM AT A TIME with real Python control
flow — no vectorization, no masks, no lowering tricks. This is the
semantic reference the compiled lowerings (vectorized XLA and Pallas
tiles) are differentially fuzzed against: any divergence is a compiler
bug, because per-item sequential execution IS the language's definition
(each kernel invocation describes one work item; cross-item hazards are
excluded by the test generators, as OpenCL leaves them undefined anyway).

Matches the lowerings' documented edge choices: C truncating integer
division/remainder, clamped out-of-bounds loads, clamped private-array
indices, f32 arithmetic for float locals.
"""

from __future__ import annotations

import math

import numpy as np

from cekirdekler_tpu.kernel.lang import (
    Assign,
    BinOp,
    Break,
    Call,
    Cast,
    Continue,
    CrementStmt,
    Decl,
    DoWhile,
    For,
    If,
    Index,
    KernelDef,
    Num,
    Return,
    ReturnValue,
    Ternary,
    UnOp,
    Var,
    While,
)

_NPT = {
    "bool": np.bool_, "char": np.int8, "uchar": np.uint8,
    "short": np.int16, "ushort": np.uint16, "int": np.int32,
    "uint": np.uint32, "long": np.int64, "ulong": np.uint64,
    "half": np.float16, "float": np.float32, "double": np.float64,
}
_INT = {"bool", "char", "uchar", "short", "ushort", "int", "uint", "long", "ulong"}


class _Return(Exception):
    pass


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


_UNARY = {
    "sqrt": math.sqrt, "rsqrt": lambda x: 1.0 / math.sqrt(x),
    "cbrt": lambda x: math.copysign(abs(x) ** (1.0 / 3.0), x),
    "exp": math.exp, "exp2": lambda x: 2.0 ** x, "exp10": lambda x: 10.0 ** x,
    "log": math.log, "log2": math.log2, "log10": math.log10,
    "sin": math.sin, "cos": math.cos, "tan": math.tan,
    "asin": math.asin, "acos": math.acos, "atan": math.atan,
    "sinh": math.sinh, "cosh": math.cosh, "tanh": math.tanh,
    "asinh": math.asinh, "acosh": math.acosh, "atanh": math.atanh,
    "fabs": abs, "floor": math.floor, "ceil": math.ceil,
    "round": lambda x: float(np.round(np.float64(x))), "rint": lambda x: float(np.round(np.float64(x))),
    "trunc": math.trunc, "erf": math.erf, "erfc": math.erfc,
    "degrees": math.degrees, "radians": math.radians,
    "sign": lambda x: float(np.sign(x)),
}
_BINARY = {
    "pow": math.pow, "powr": math.pow, "atan2": math.atan2,
    "fmod": math.fmod, "remainder": math.remainder, "hypot": math.hypot,
    "copysign": math.copysign,
    "fdim": lambda a, b: max(a - b, 0.0),
    "nextafter": math.nextafter,
}


class Oracle:
    """Per-item executor: ``run(arrays, values, global_size)`` mutates the
    numpy arrays in place, looping items sequentially."""

    def __init__(self, kernel: KernelDef, local_size: int = 64):
        self.kernel = kernel
        self.local_size = local_size

    def run(self, arrays: dict[str, np.ndarray], values: dict[str, float],
            global_size: int, offset: int = 0) -> None:
        for i in range(offset, offset + global_size):
            self._run_item(i, arrays, values, global_size)

    # -- one work item -------------------------------------------------------
    def _run_item(self, gid, arrays, values, gsize) -> None:
        env: dict = {}
        priv: dict[str, np.ndarray] = {}
        ctypes: dict[str, str] = {}
        for p in self.kernel.params:
            if not p.is_pointer:
                env[p.name] = _NPT[p.ctype](values[p.name])
                ctypes[p.name] = p.ctype
        state = (env, priv, ctypes, arrays, gid, gsize)
        try:
            self._block(self.kernel.body, state)
        except _Return:
            pass

    def _block(self, stmts, state) -> None:
        for s in stmts:
            self._stmt(s, state)

    def _stmt(self, s, state) -> None:
        env, priv, ctypes, arrays, gid, gsize = state
        if isinstance(s, Decl):
            for name, init in s.names:
                if name in s.arrays:
                    priv[name] = np.zeros(s.arrays[name], _NPT[s.ctype])
                    ctypes[name] = s.ctype
                else:
                    v = self._expr(init, state) if init is not None else 0
                    env[name] = _NPT[s.ctype](v)
                    ctypes[name] = s.ctype
        elif isinstance(s, Assign):
            if s.target is None:
                self._expr(s.value, state)
                return
            rhs = self._expr(s.value, state)
            if s.op != "=":
                cur = self._expr(s.target, state)
                rhs = self._binval(s.op[:-1], cur, rhs)
            self._store(s.target, rhs, state)
        elif isinstance(s, CrementStmt):
            cur = self._expr(s.target, state)
            self._store(s.target, cur + (1 if s.op == "++" else -1), state)
        elif isinstance(s, If):
            if isinstance(s.cond, Num) and s.cond.value == 1 and not s.other:
                self._block(s.then, state)
            elif self._truthy(self._expr(s.cond, state)):
                self._block(s.then, state)
            else:
                self._block(s.other, state)
        elif isinstance(s, For):
            if s.init is not None:
                self._stmt(s.init, state)
            while s.cond is None or self._truthy(self._expr(s.cond, state)):
                try:
                    self._block(s.body, state)
                except _Break:
                    break
                except _Continue:
                    pass  # C: continue still runs the step
                if s.step is not None:
                    self._stmt(s.step, state)
        elif isinstance(s, While):
            while self._truthy(self._expr(s.cond, state)):
                try:
                    self._block(s.body, state)
                except _Break:
                    break
                except _Continue:
                    continue
        elif isinstance(s, DoWhile):
            while True:
                try:
                    self._block(s.body, state)
                except _Break:
                    break
                except _Continue:
                    pass
                if not self._truthy(self._expr(s.cond, state)):
                    break
        elif isinstance(s, Break):
            raise _Break()
        elif isinstance(s, Continue):
            raise _Continue()
        elif isinstance(s, Return):
            raise _Return()
        else:
            raise AssertionError(f"oracle: unhandled stmt {type(s).__name__}")

    def _store(self, target, val, state) -> None:
        env, priv, ctypes, arrays, gid, gsize = state
        if isinstance(target, Var):
            env[target.name] = _NPT[ctypes[target.name]](val)
            return
        assert isinstance(target, Index)
        idx = int(self._expr(target.index, state))
        if target.base in priv:
            arr = priv[target.base]
            arr[np.clip(idx, 0, arr.shape[0] - 1)] = val
        else:
            arr = arrays[target.base]
            # matches the lowering: masked scatter drops OOB; in-range writes land
            if 0 <= idx < arr.shape[0]:
                arr[idx] = val

    def _expr(self, node, state):
        env, priv, ctypes, arrays, gid, gsize = state
        if isinstance(node, Num):
            return _NPT[node.ctype](node.value)
        if isinstance(node, Var):
            return env[node.name]
        if isinstance(node, Index):
            idx = int(self._expr(node.index, state))
            if node.base in priv:
                arr = priv[node.base]
            else:
                arr = arrays[node.base]
            return arr[np.clip(idx, 0, arr.shape[0] - 1)]  # clamped loads
        if isinstance(node, UnOp):
            v = self._expr(node.operand, state)
            if node.op == "+":
                return v
            if node.op == "-":
                return -v
            if node.op == "!":
                return np.bool_(not self._truthy(v))
            if node.op == "~":
                return ~np.int32(v) if not isinstance(v, np.integer) else ~v
        if isinstance(node, Ternary):
            c = self._truthy(self._expr(node.cond, state))
            return self._expr(node.then if c else node.other, state)
        if isinstance(node, Cast):
            return _NPT[node.ctype](self._expr(node.operand, state))
        if isinstance(node, BinOp):
            if node.op == "&&":
                return np.bool_(
                    self._truthy(self._expr(node.left, state))
                    and self._truthy(self._expr(node.right, state))
                )
            if node.op == "||":
                return np.bool_(
                    self._truthy(self._expr(node.left, state))
                    or self._truthy(self._expr(node.right, state))
                )
            a = self._expr(node.left, state)
            b = self._expr(node.right, state)
            return self._binval(node.op, a, b)
        if isinstance(node, Call):
            return self._call(node, state)
        raise AssertionError(f"oracle: unhandled expr {type(node).__name__}")

    def _binval(self, op, a, b):
        # promote like the lowering: float wins; ints promote to >= int32
        if isinstance(a, np.floating) or isinstance(b, np.floating):
            fa = np.float32(a) if not isinstance(a, np.float64) and not isinstance(b, np.float64) else np.float64(a)
            fb = type(fa)(b)
            if op == "+":
                return fa + fb
            if op == "-":
                return fa - fb
            if op == "*":
                return fa * fb
            if op == "/":
                return fa / fb
            if op == "%":
                return type(fa)(math.fmod(float(fa), float(fb)))
            return self._cmp(op, fa, fb)
        ia, ib = np.int64(a), np.int64(b)
        if op == "+":
            return np.int32(ia + ib)
        if op == "-":
            return np.int32(ia - ib)
        if op == "*":
            return np.int32(ia * ib)
        if op == "/":
            q = abs(ia) // abs(ib)
            return np.int32(q if (ia >= 0) == (ib >= 0) else -q)  # C trunc
        if op == "%":
            return np.int32(ia - np.int64(self._binval("/", a, b)) * ib)
        if op == "&":
            return np.int32(ia & ib)
        if op == "|":
            return np.int32(ia | ib)
        if op == "^":
            return np.int32(ia ^ ib)
        if op == "<<":
            return np.int32(ia << ib)
        if op == ">>":
            return np.int32(ia >> ib)
        return self._cmp(op, ia, ib)

    @staticmethod
    def _cmp(op, a, b):
        return np.bool_(
            {"==": a == b, "!=": a != b, "<": a < b, ">": a > b,
             "<=": a <= b, ">=": a >= b}[op]
        )

    @staticmethod
    def _truthy(v) -> bool:
        return bool(v)

    def _call(self, node: Call, state):
        env, priv, ctypes, arrays, gid, gsize = state
        name = node.name
        helpers = getattr(self.kernel, "helpers", {}) or {}
        if name in helpers:
            fdef = helpers[name]
            vals = [self._expr(a, state) for a in node.args]
            henv = {
                p.name: _NPT[p.ctype](v) for p, v in zip(fdef.params, vals)
            }
            hctypes = {p.name: p.ctype for p in fdef.params}
            hstate = (henv, {}, hctypes, {}, gid, gsize)  # no buffer access
            self._block(fdef.body[:-1], hstate)
            assert isinstance(fdef.body[-1], ReturnValue)
            return _NPT[fdef.ret_ctype](self._expr(fdef.body[-1].value, hstate))
        if name.startswith(("native_", "half_")):
            name = name.split("_", 1)[1]
        args = [self._expr(a, state) for a in node.args]
        if name == "get_global_id":
            return np.int32(gid)
        if name == "get_global_size":
            return np.int32(gsize)
        if name == "get_local_size":
            return np.int32(self.local_size)
        if name == "get_local_id":
            return np.int32(gid % self.local_size)
        if name == "get_group_id":
            return np.int32(gid // self.local_size)
        if name == "get_num_groups":
            return np.int32(gsize // self.local_size)
        if name == "get_global_offset":
            return np.int32(0)
        if name == "get_work_dim":
            return np.int32(1)
        if name in _UNARY:
            if name in ("fabs", "sign") and isinstance(args[0], np.integer):
                return abs(args[0]) if name == "fabs" else np.int32(np.sign(args[0]))
            return np.float32(_UNARY[name](float(np.float32(args[0]))))
        if name in _BINARY:
            return np.float32(_BINARY[name](float(np.float32(args[0])),
                                            float(np.float32(args[1]))))
        if name == "abs":
            return abs(args[0])
        if name in ("min", "fmin"):
            return min(args[0], args[1])
        if name in ("max", "fmax"):
            return max(args[0], args[1])
        if name == "clamp":
            return min(max(args[0], args[1]), args[2])
        if name in ("mad", "fma"):
            return np.float32(np.float32(args[0]) * np.float32(args[1]) + np.float32(args[2]))
        if name == "mix":
            a, b, w = (np.float32(x) for x in args)
            return np.float32(a + (b - a) * w)
        if name == "step":
            return np.float32(0.0 if float(args[1]) < float(args[0]) else 1.0)
        if name == "smoothstep":
            e0, e1, x = (float(x) for x in args)
            u = min(max((x - e0) / (e1 - e0), 0.0), 1.0)
            return np.float32(u * u * (3.0 - 2.0 * u))
        if name == "select":
            return args[1] if self._truthy(args[2]) else args[0]
        if name == "isnan":
            return np.bool_(math.isnan(float(args[0])))
        if name == "isinf":
            return np.bool_(math.isinf(float(args[0])))
        if name == "isfinite":
            return np.bool_(math.isfinite(float(args[0])))
        raise AssertionError(f"oracle: unknown function {node.name}")
