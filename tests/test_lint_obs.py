"""tools/lint_obs.py as a tier-1 gate: docs/OBSERVABILITY.md and the
code's observability surface (registered ``ck_*`` series, SPAN_KINDS)
may not drift — this test IS the enforcement, so a PR adding an
undocumented metric (or documenting a removed one) fails here with the
diff.  Plus unit pins on the linter's own extraction rules, and the
``tools/metrics_dump.py --watch`` HTTP poller against a live debug
server."""

import importlib.util
import os

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _load(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


lint = _load("ck_lint_obs", "tools/lint_obs.py")


# ---------------------------------------------------------------------------
# the gate itself
# ---------------------------------------------------------------------------

def test_doc_and_code_observability_surfaces_agree():
    problems = lint.run()
    assert problems == [], "\n".join(problems)


def test_lint_inventories_are_nonempty():
    # a regex that silently matched nothing would make the gate vacuous
    assert len(lint.code_metric_names()) >= 20
    assert len(lint.code_span_kinds()) >= 10
    assert len(lint.code_decision_kinds()) >= 8


def test_decision_kinds_parsed_statically_match_import():
    from cekirdekler_tpu.obs.decisions import DECISION_KINDS

    assert lint.code_decision_kinds() == set(DECISION_KINDS)


# ---------------------------------------------------------------------------
# extraction-rule unit pins
# ---------------------------------------------------------------------------

def test_doc_metric_extraction_drops_truncated_prefixes():
    text = "uses `ck_upload_bytes_total` and files ck_postmortem_<pid>.json"
    assert lint.doc_metric_names(text) == {"ck_upload_bytes_total"}


def test_doc_metric_extraction_collapses_exposition_suffixes():
    text = "`ck_fence_seconds` renders `ck_fence_seconds_bucket` lines"
    assert lint.doc_metric_names(text) == {"ck_fence_seconds"}


def test_doc_span_kind_table_extraction():
    text = (
        "## The tracer (x)\n"
        "| kind | layer |\n"
        "| `enqueue` | cores |\n"
        "| `upload-chunk`   | worker |\n"
        "not-a-row `fused`\n"
        "## Next section\n"
    )
    assert lint.doc_span_kinds(text) == {"enqueue", "upload-chunk"}


def test_span_kinds_parsed_statically_match_import():
    from cekirdekler_tpu.trace.spans import SPAN_KINDS

    assert lint.code_span_kinds() == set(SPAN_KINDS)


# ---------------------------------------------------------------------------
# metrics_dump --watch: poll a live debug server over HTTP
# ---------------------------------------------------------------------------

def test_metrics_dump_watch_polls_live_endpoint(capsys):
    from cekirdekler_tpu.metrics import REGISTRY
    from cekirdekler_tpu.obs.debugserver import DebugServer

    # guarantee a lane-labeled series exists whatever ran before
    REGISTRY.counter(
        "ck_upload_bytes_total", "H2D bytes uploaded", lane=0).inc(0)
    srv = DebugServer(cores=None, port=0)
    try:
        md = _load("ck_metrics_dump", "tools/metrics_dump.py")
        rc = md.main([
            "--url", srv.url + "/metrics", "--watch", "0.05", "--count", "2",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert out.count("lane") >= 2            # two rendered polls
        assert "health" in out and "up/s" in out  # the top-like columns
    finally:
        srv.close()


def test_metrics_dump_watch_requires_url():
    import pytest

    md = _load("ck_metrics_dump2", "tools/metrics_dump.py")
    with pytest.raises(SystemExit):
        md.main(["--watch", "1"])


# ---------------------------------------------------------------------------
# replayer registry cross-check (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

def test_replayer_registry_clean_on_head():
    assert lint.replayer_problems() == []


def test_replayer_kinds_parsed_statically_match_import():
    from cekirdekler_tpu.obs import replay

    assert lint.code_replayer_kinds() == set(replay._REPLAYERS)


def test_replayable_and_context_partition_decision_kinds():
    from cekirdekler_tpu.obs.decisions import (
        CONTEXT_KINDS,
        DECISION_KINDS,
        REPLAYABLE_KINDS,
    )

    assert set(REPLAYABLE_KINDS) | set(CONTEXT_KINDS) == \
        set(DECISION_KINDS)
    assert not set(REPLAYABLE_KINDS) & set(CONTEXT_KINDS)


def test_replayer_drift_fixtures_are_caught():
    """The motivating failure: a decision kind in NEITHER bucket
    silently skipped verification; a replayable kind without a
    registered replayer did too.  Both are findings now."""
    decisions_src = (
        'DECISION_KINDS = ("a", "b", "c")\n'
        'REPLAYABLE_KINDS = ("a",)\n'
        'CONTEXT_KINDS = ("b",)\n'
    )
    replay_src = "_REPLAYERS = {\n    \"a\": _replay_a,\n}\n"
    assert lint.replayer_problems(decisions_src, replay_src) == [
        "decision kind 'c' is in neither REPLAYABLE_KINDS nor "
        "CONTEXT_KINDS — place it deliberately (a kind in neither "
        "bucket silently skips verification)",
    ]
    # a replayable kind with no registered replayer
    missing = lint.replayer_problems(
        decisions_src.replace('REPLAYABLE_KINDS = ("a",)',
                              'REPLAYABLE_KINDS = ("a", "c")'),
        replay_src)
    assert any("has no registered replayer" in p for p in missing)
    # an undeclared replayer
    extra = lint.replayer_problems(
        decisions_src,
        "_REPLAYERS = {\"a\": _f, \"z\": _g}\n")
    assert any("not in REPLAYABLE_KINDS" in p for p in extra)
    # a kind in both buckets
    both = lint.replayer_problems(
        decisions_src.replace('CONTEXT_KINDS = ("b",)',
                              'CONTEXT_KINDS = ("a", "b")'),
        replay_src)
    assert any("BOTH" in p for p in both)


def test_replayer_registry_refuses_non_literal_keys():
    import pytest

    with pytest.raises(AssertionError, match="non-literal"):
        lint.code_replayer_kinds("_REPLAYERS = {KIND: _f}\n")
