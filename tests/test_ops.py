"""Pallas ops tests (interpreter mode on the CPU rig) — parity with host
references and integration with the compute path."""

import jax
import jax.numpy as jnp
import numpy as np

import cekirdekler_tpu as ct
from cekirdekler_tpu.ops import map_blocks, mandelbrot_pallas, saxpy
from cekirdekler_tpu.workloads import mandelbrot_host, run_mandelbrot


def test_mandelbrot_pallas_matches_host():
    w, h, it = 256, 64, 48
    got = mandelbrot_pallas(
        w * h, -2.0, -1.25, 2.5 / w, 2.5 / h, w, it, interpret=True
    )
    want = mandelbrot_host(w, h, -2.0, -1.25, 2.5 / w, 2.5 / h, it)
    frac = float(np.mean(np.asarray(got) == want))
    assert frac > 0.999, f"only {frac:.4f} pixels agree"


def test_mandelbrot_pallas_offset_chunk():
    """A chunk [offset, offset+n) must equal that slice of the full image."""
    w, h, it = 128, 64, 32
    full = mandelbrot_pallas(w * h, -2.0, -1.25, 2.5 / w, 2.5 / h, w, it, interpret=True)
    chunk = mandelbrot_pallas(
        1024, -2.0, -1.25, 2.5 / w, 2.5 / h, w, it,
        offset=jnp.int32(2048), interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(chunk), np.asarray(full)[2048:3072])


def test_saxpy_and_map_blocks():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(1024), jnp.float32)
    y = jnp.asarray(rng.standard_normal(1024), jnp.float32)
    got = saxpy(2.5, x, y, interpret=True)
    # rtol 1e-5: the Pallas kernel and the numpy reference may fuse the
    # multiply-add differently (fma vs separate rounding) — 1-ulp f32 drift
    np.testing.assert_allclose(np.asarray(got), np.asarray(y + 2.5 * x), rtol=1e-5)
    got2 = map_blocks(lambda a, b: jnp.maximum(a, b), x, y, interpret=True)
    np.testing.assert_array_equal(np.asarray(got2), np.maximum(np.asarray(x), np.asarray(y)))


def test_run_mandelbrot_pallas_path_multichip():
    """The Pallas kernel rides the same compute()/balancer machinery."""
    devs = ct.all_devices().cpus().subset(4)
    res = run_mandelbrot(
        devs, width=256, height=128, max_iter=32,
        iters=3, warmup=0, keep_image=True, local_range=128, use_pallas=True,
    )
    want = mandelbrot_host(256, 128, -2.0, -1.25, 2.5 / 256, 2.5 / 128, 32)
    frac = float(np.mean(res.image.ravel() == want))
    assert frac > 0.999
    assert sum(res.ranges_per_iter[-1]) == 256 * 128
