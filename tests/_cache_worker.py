"""Worker process for the persistent executable cache: one cold
interpreter over its own ``NumberCruncher`` (the ``tests/_dcn_worker.py``
JSON-lines idiom: the parent spawns it, reads a READY sentinel, then
drives a one-JSON-object-per-line command protocol on stdin/stdout).
Used by ``tests/test_compilecache.py`` — process A populates the cache
through the LIVE engage-time recorder, process B starts cold, replays
``warm_from_disk`` and proves its first live batch compiles nothing.

``CK_COMPILE_CACHE`` comes from the parent's env (that is the product
seam under test — no flag shadowing it).

Protocol (every command gets one reply):

- ``{"op": "warm_disk"}`` — ``warm_from_disk(cores)`` →
  ``{"op": "warmed", "warmed", "hits", "misses", "skipped"}``
- ``{"op": "batch", "n", "lr", "iters", "scale"}`` — one live
  ``compute_fused_batch`` of the ``scl`` kernel (baked float value →
  the JSON value-roundtrip is on the key path) →
  ``{"op": "done", "fused_compiles", "call_compiles", "value",
  "uniform"}`` — the compile counters are the DELTA this batch caused
- ``{"op": "stats"}`` — the cache ``stats()`` doc (empty when
  disarmed) → ``{"op": "stats", "stats": {...}}``
- ``{"op": "exit"}`` → ``{"op": "bye"}`` and a clean close.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SRC = """
__kernel void scl(__global float* a, float s) {
    int i = get_global_id(0);
    a[i] = a[i] + s;
}
"""

CID = 7100


def main() -> None:
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.compilecache import CACHE, warm_from_disk
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import all_devices

    devs = all_devices().cpus().subset(1)
    cr = NumberCruncher(devs, SRC)
    cores = cr.cores
    arrays: dict = {}
    print(json.dumps({"op": "ready", "cache": CACHE.enabled,
                      "pid": os.getpid()}), flush=True)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        cmd = json.loads(line)
        op = cmd.get("op")
        if op == "exit":
            print(json.dumps({"op": "bye"}), flush=True)
            break
        elif op == "warm_disk":
            out = warm_from_disk(cores)
            print(json.dumps({"op": "warmed", **out}), flush=True)
        elif op == "batch":
            n, lr = int(cmd["n"]), int(cmd["lr"])
            iters = int(cmd.get("iters", 3))
            scale = float(cmd.get("scale", 1.0))
            if n not in arrays:
                a = ClArray(np.zeros(n, np.float32), name=f"a{n}")
                a.partial_read = True
                arrays[n] = a
            a = arrays[n]
            before = (cores.program.fused_compiled_count,
                      cores.program.compiled_count)
            cr.enqueue_mode = True
            cores.compute_fused_batch(
                ["scl"], [a], CID, n, lr, iters,
                value_args={"scl": (scale,)})
            cr.barrier()
            cr.enqueue_mode = False  # flush deferred readbacks
            img = np.asarray(a)
            print(json.dumps({
                "op": "done",
                "fused_compiles":
                    cores.program.fused_compiled_count - before[0],
                "call_compiles":
                    cores.program.compiled_count - before[1],
                "value": float(img[0]),
                "uniform": bool(np.all(img == img[0])),
            }), flush=True)
        elif op == "stats":
            stats = CACHE.stats() if CACHE.enabled else {}
            print(json.dumps({"op": "stats", "stats": stats}),
                  flush=True)
        else:
            print(json.dumps({"op": "error", "error": f"bad op {op!r}"}),
                  flush=True)
    cr.dispose()


if __name__ == "__main__":
    main()
