"""Live introspection plane (cekirdekler_tpu/obs/): debug HTTP
endpoints against a live enqueue workload, the always-on flight
recorder + crash postmortems, and the lane-health degradation detector.

Budget discipline mirrors tests/test_metrics.py: the flight recorder is
the only NEW always-on instrument family, so its disabled cost is
pinned to the same PR 4 budget (< 100 ns marginal over the bare
method-call floor), and the enqueue HOT path (the fused deferral)
carries zero obs instrumentation — the integration test hammers
/metrics from a scraper thread while deferrals run to prove the server
cannot slow the path it observes."""

import glob
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.metrics import REGISTRY, parse_prometheus_text
from cekirdekler_tpu.obs import flight as flight_mod
from cekirdekler_tpu.obs.flight import (
    FLIGHT,
    FlightRecorder,
    dump_postmortem,
    load_postmortem,
)
from cekirdekler_tpu.obs.health import (
    HealthMonitor,
    cluster_health_table,
    registry_health_summary,
)
from cekirdekler_tpu.trace.attribution import window_report
from cekirdekler_tpu.trace.export import from_chrome_trace, to_chrome_trace
from cekirdekler_tpu.trace.spans import TRACER, Tracer

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# flight recorder: ring semantics + the overhead budget
# ---------------------------------------------------------------------------

class _NoopShape:
    """Same call shape as FlightRecorder.event with the body removed —
    the interpreter's bound-method + kwargs floor."""

    def event(self, kind, **fields):
        pass


def _best_per_call(fn, n=100_000, trials=3) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        best = min(best, (time.perf_counter() - t0) / n)
    return best


def _best_pair(fn_floor, fn_probe, n=100_000, trials=10):
    """Best-of per-call costs with the two measurements INTERLEAVED:
    a scheduler burst landing between two separate measurement blocks
    would skew the margin one way; alternating trials gives both sides
    the same weather and best-of keeps the clean trials."""
    best_f = best_p = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(n):
            fn_floor()
        best_f = min(best_f, (time.perf_counter() - t0) / n)
        t0 = time.perf_counter()
        for _ in range(n):
            fn_probe()
        best_p = min(best_p, (time.perf_counter() - t0) / n)
    return best_f, best_p


def test_flight_ring_bounded_oldest_first():
    fr = FlightRecorder(capacity=16)
    for i in range(40):
        fr.event("probe", i=i)
    events = fr.snapshot()
    assert len(events) == 16
    assert fr.total_recorded == 40
    assert [e.fields["i"] for e in events] == list(range(24, 40))
    fr.clear()
    assert fr.snapshot() == [] and fr.total_recorded == 0


def test_disabled_flight_event_overhead_under_budget():
    """The PR 4 pin, applied to the new always-on family: a disabled
    flight event costs < 100 ns marginal over the identical no-op call,
    and < 1 µs absolute (the tracer-discipline bound)."""
    from functools import partial

    fr = FlightRecorder()
    fr.enabled = False
    noop = _NoopShape()
    # partial, not lambda: a lambda adds a second Python frame per call
    # (~2x the work under measurement), and its variance under suite
    # load swamps the 100 ns margin being pinned
    floor, per = _best_pair(
        partial(noop.event, "probe"), partial(fr.event, "probe"))
    net = per - floor
    assert net < 100e-9, (
        f"disabled event adds {net*1e9:.0f} ns over the call floor "
        f"({per*1e9:.0f} ns total, floor {floor*1e9:.0f} ns)"
    )
    assert per < 1e-6, f"disabled event absolute {per*1e9:.0f} ns >= 1 µs"
    assert fr.total_recorded == 0  # truly a no-op


def test_enabled_flight_event_stays_cheap():
    """Enabled is one deque.append + one clock read — window-granularity
    sites can afford it thousands of times over; 20 µs is an order of
    magnitude of slack on the slowest container."""
    fr = FlightRecorder(capacity=1024)
    per = _best_per_call(lambda: fr.event("probe", lane=0), n=20_000)
    assert per < 20e-6, f"enabled event costs {per*1e6:.2f} µs"


def test_flight_metric_sampling_is_throttled():
    fr = FlightRecorder(sample_interval_s=3600.0)
    assert fr.maybe_sample_metrics() is True
    assert fr.maybe_sample_metrics() is False  # inside the interval
    samples = [e for e in fr.snapshot() if e.kind == "metrics-sample"]
    assert len(samples) == 1
    assert isinstance(samples[0].fields["values"], dict)


# ---------------------------------------------------------------------------
# trace ring span loss (satellite: ck_trace_dropped_spans_total)
# ---------------------------------------------------------------------------

def test_tracer_dropped_spans_counted_and_exported():
    tr = Tracer(capacity=16)
    tr.enable(clear=True)
    c = REGISTRY.counter(
        "ck_trace_dropped_spans_total",
        "spans lost to tracer ring wrap (attribution undercounts)",
    )
    before = c.value
    t = time.perf_counter()
    for _ in range(40):
        tr.record("launch", t)
    assert tr.dropped_spans == 24
    spans = tr.snapshot()  # snapshot() syncs the counter
    assert len(spans) == 16
    assert c.value - before == 24
    tr.snapshot()  # delta-based: a second snapshot must not double-count
    assert c.value - before == 24
    tr.clear()
    assert tr.dropped_spans == 0


def test_tracer_resize_exports_pending_drops_first():
    """Raising capacity (the wrap report's own advice) resets the ring
    counters — losses that happened BEFORE the resize must reach
    ck_trace_dropped_spans_total anyway, not vanish with the baseline."""
    tr = Tracer(capacity=16)
    tr.enable(clear=True)
    c = REGISTRY.counter(
        "ck_trace_dropped_spans_total",
        "spans lost to tracer ring wrap (attribution undercounts)",
    )
    before = c.value
    t = time.perf_counter()
    for _ in range(40):
        tr.record("launch", t)
    tr.enable(capacity=64, clear=False)  # no snapshot() ran in between
    assert c.value - before == 24
    tr.clear()


def test_tracer_keep_resize_does_not_deadlock():
    """enable(capacity=..., clear=False) migrates spans while HOLDING
    the tracer lock; it must use the lock-free span copy, not
    snapshot() (whose dropped-metric sync takes the same non-reentrant
    lock — the deadlock a review pass reproduced)."""
    done = threading.Event()

    def run():
        tr = Tracer(capacity=32)
        tr.enable(clear=True)
        tr.record("launch", time.perf_counter())
        tr.enable(capacity=64, clear=False)  # the keep path
        assert len(tr.snapshot()) == 1
        done.set()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout=10.0)
    assert done.is_set(), "keep-path capacity resize deadlocked"


def test_window_report_carries_dropped_spans():
    rep = window_report([], 0.0, 1.0, dropped_spans=7)
    assert rep.ring_wrapped is True          # nonzero loss implies wrap
    d = rep.to_dict()
    assert d["dropped_spans"] == 7
    assert "7 oldest spans" in rep.table()
    clean = window_report([], 0.0, 1.0)
    assert clean.to_dict()["dropped_spans"] == 0
    assert clean.ring_wrapped is False


# ---------------------------------------------------------------------------
# health detector (the acceptance pin: 5x on one lane, zero false
# positives on the steady run, degraded within 3 windows, hysteresis)
# ---------------------------------------------------------------------------

def _feed_window(hm, lane, values):
    for v in values:
        hm.observe(lane, "fence", v)


def test_health_detector_flags_only_the_degraded_lane():
    # default threshold/confirm/min_history — the shipped detector is
    # what must satisfy the acceptance bound, not a tuned-down variant
    hm = HealthMonitor(window=4)
    # pinned steady run: deterministic jitter around 10 ms on both lanes
    steady = [0.010, 0.011, 0.0095, 0.0105]
    for _wnd in range(6):
        _feed_window(hm, 0, steady)
        _feed_window(hm, 1, steady)
        # zero false positives: every closed window stays ok
        assert hm.verdict(0) == "ok" and hm.verdict(1) == "ok"
    # inject a 5x fence-time degradation on lane 1 only
    degraded_by = None
    for wnd in range(3):
        _feed_window(hm, 0, steady)
        _feed_window(hm, 1, [v * 5.0 for v in steady])
        assert hm.verdict(0) == "ok"
        if hm.verdict(1) == "degraded":
            degraded_by = wnd + 1
            break
    assert degraded_by is not None and degraded_by <= 3, (
        f"lane 1 not degraded within 3 windows: {hm.report()}")
    assert hm.verdict(1) == "degraded" and hm.verdict(0) == "ok"
    assert hm.suggest_drain() == [1]
    assert hm.healthy() is False
    # the gauge carries the verdict
    assert REGISTRY.gauge("ck_lane_health", lane=1).value == 2.0
    assert REGISTRY.gauge("ck_lane_health", lane=0).value == 0.0
    # evidence names the signal with baseline/current/ratio
    ev = hm.report()[1]["evidence"]["fence"]
    assert ev["state"] == "degraded"
    assert ev["ratio"] == pytest.approx(5.0, rel=0.3)
    # hysteresis: one window back at baseline (ratio ~1 <= release 1.5)
    # releases the verdict
    _feed_window(hm, 1, steady)
    assert hm.verdict(1) == "ok"
    assert hm.healthy() is True


def test_health_detector_suspect_before_confirm():
    hm = HealthMonitor(threshold=3.0, window=4, confirm=2, min_history=2)
    steady = [0.010] * 4
    for _ in range(4):
        _feed_window(hm, 0, steady)
    _feed_window(hm, 0, [0.05] * 4)  # first strike
    assert hm.verdict(0) == "suspect"
    assert hm.suggest_drain() == []  # suspect is a warning, not an outage
    assert hm.healthy() is True


def test_health_hysteresis_no_flapping_at_threshold():
    """A lane oscillating just around the threshold must not flap
    ok/degraded every window: once degraded, only a clear return to
    baseline (<= release) releases it."""
    hm = HealthMonitor(threshold=3.0, window=2, confirm=2, min_history=2)
    for _ in range(4):
        _feed_window(hm, 0, [0.010, 0.010])
    for _ in range(2):
        _feed_window(hm, 0, [0.031, 0.031])  # 3.1x: strike, strike
    assert hm.verdict(0) == "degraded"
    _feed_window(hm, 0, [0.025, 0.025])      # 2.5x: above release (1.5x)
    assert hm.verdict(0) == "degraded", "flapped below threshold"
    _feed_window(hm, 0, [0.011, 0.011])      # back to baseline
    assert hm.verdict(0) == "ok"


def test_health_zero_baseline_evidence_is_json_safe():
    """A zero-cost baseline followed by real work must not put
    float('inf') in the evidence: json serializes it as the bare token
    `Infinity`, which every RFC-8259 consumer of /healthz and the DCN
    health payload rejects."""
    hm = HealthMonitor(window=2, min_history=2, confirm=2)
    for _ in range(3):
        _feed_window(hm, 0, [0.0, 0.0])
    _feed_window(hm, 0, [0.1, 0.1])  # nonzero over a zero baseline
    rep = hm.report()
    text = json.dumps(rep)
    assert "Infinity" not in text
    assert rep[0]["evidence"]["fence"]["ratio"] is None
    assert rep[0]["evidence"]["fence"]["state"] == "suspect"


def test_health_peak_gauge_survives_later_monitors():
    """The whole-run artifact contract: a later section's fresh monitor
    re-exports ck_lane_health for the same lane index, but the PEAK
    gauge is monotone, so the earlier degradation stays visible as
    worst_seen."""
    lane = 7  # distinct index: other tests own lanes 0/1
    first = HealthMonitor(window=2, min_history=2, confirm=2)
    for _ in range(4):
        _feed_window(first, lane, [0.010, 0.010])
    for _ in range(2):
        _feed_window(first, lane, [0.05, 0.05])
    assert first.verdict(lane) == "degraded"
    second = HealthMonitor(window=2, min_history=2, confirm=2)
    for _ in range(4):
        _feed_window(second, lane, [0.010, 0.010])
    assert second.verdict(lane) == "ok"  # the gauge got overwritten...
    s = registry_health_summary()
    assert s["lanes"][str(lane)]["verdict"] == "ok"
    assert s["lanes"][str(lane)]["peak_verdict"] == "degraded"  # ...peak not
    assert s["worst_seen"] == "degraded"


def test_registry_health_summary_reads_gauges():
    reg_snapshot = {
        "counters": {}, "histograms": {},
        "gauges": {
            'ck_lane_health{lane="0"}': 0.0,
            'ck_lane_health{lane="3"}': 2.0,
            'ck_stream_chunk_count{lane="0"}': 4.0,
        },
    }
    s = registry_health_summary(reg_snapshot)
    assert s["lanes"]["3"]["verdict"] == "degraded"
    assert s["lanes"]["0"]["verdict"] == "ok"
    assert s["worst"] == "degraded" and s["healthy"] is False


def test_cluster_health_table_merges_processes():
    snap = {
        "health": [
            {"0": {"verdict": "ok", "score": 0, "evidence": {}}},
            {"0": {"verdict": "degraded", "score": 2,
                   "evidence": {"fence": {"ratio": 5.0}}},
             "1": {"verdict": "ok", "score": 0, "evidence": {}}},
            {},  # a process that shipped no report stays visible as {}
        ],
    }
    table = cluster_health_table(snap)
    assert len(table["processes"]) == 3
    assert table["worst"] == "degraded"
    assert [(d["process"], d["lane"]) for d in table["degraded"]] == [(1, "0")]
    assert table["processes"][2]["lanes"] == {}


# ---------------------------------------------------------------------------
# debug server: all five endpoints against a live enqueue workload
# ---------------------------------------------------------------------------

def test_debug_server_endpoints_during_live_workload(devs):
    cr = NumberCruncher(devs.subset(2), INC)
    srv = cr.serve_debug(port=0)
    assert srv is cr.serve_debug(port=0)  # idempotent per Cores
    n = 4096
    a = ClArray(np.zeros(n, np.float32), name="obs_a", partial_read=True)
    stop = threading.Event()
    errs: list = []

    def drive():
        try:
            cr.enqueue_mode = True
            while not stop.is_set():
                for _ in range(8):
                    a.compute(cr, 901, "inc", n, 64)
                cr.barrier()
            cr.enqueue_mode = False
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    was_tracing = TRACER.enabled
    TRACER.enable(clear=True)
    t = threading.Thread(target=drive)
    t.start()
    try:
        deadline = time.time() + 30.0
        while time.time() < deadline:
            # wait until the workload visibly flows (a fused window has
            # dispatched) before asserting on live state
            if cr.cores.fused_stats["windows"] >= 1:
                break
            time.sleep(0.05)

        code, text = _get(srv.url + "/metrics")
        assert code == 200
        parsed = parse_prometheus_text(text)  # parses as Prometheus text
        assert any(k.startswith("ck_") for k in parsed["series"])
        # worker-lifetime series exist from construction, whatever the
        # workload has reached by scrape time
        assert parsed["types"].get("ck_upload_bytes_total") == "counter"
        assert parsed["types"].get("ck_fence_seconds") == "histogram"

        code, body = _get(srv.url + "/statusz")
        st = json.loads(body)
        assert code == 200 and st["uptime_s"] >= 0
        assert len(st["lanes"]) == 2
        assert "901" in st["shares"]
        assert st["fused"]["deferred_iters"] >= 0

        code, body = _get(srv.url + "/tracez")
        tz = json.loads(body)
        assert code == 200 and tz["enabled"] is True
        assert "dropped_spans" in tz
        assert tz["total_recorded"] > 0 and len(tz["spans"]) > 0
        code, body = _get(srv.url + "/tracez?chrome=1")
        chrome = json.loads(body)
        assert any(e.get("ph") == "X" for e in chrome["traceEvents"])

        code, body = _get(srv.url + "/healthz")
        hz = json.loads(body)
        assert code == 200 and hz["healthy"] is True  # healthy run = 200

        code, body = _get(srv.url + "/flightz")
        fz = json.loads(body)
        assert code == 200 and fz["total_recorded"] > 0
        kinds = {e["kind"] for e in fz["events"]}
        assert "fused-engage" in kinds or "fused-window" in kinds

        # 404 contract
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get(srv.url + "/nope")
        assert exc_info.value.code == 404

        # hot-path overhead while a scraper hammers /metrics: the fused
        # deferral path carries ZERO obs instrumentation, so per-call
        # cost with the server under load stays at interpreter scale
        scrape_stop = threading.Event()

        def scrape():
            while not scrape_stop.is_set():
                try:
                    _get(srv.url + "/metrics", timeout=5)
                except Exception:  # noqa: BLE001 - scraper best-effort
                    pass

        s = threading.Thread(target=scrape)
        s.start()
        try:
            time.sleep(0.3)  # overlap scraping with the live workload
        finally:
            scrape_stop.set()
            s.join()
    finally:
        stop.set()
        t.join(timeout=60)
        if not was_tracing:
            TRACER.disable()
        cr.dispose()
    assert not errs, errs
    # the enqueue workload survived concurrent scraping bit-exactly:
    # every iteration landed (inc adds exactly 1.0f)
    assert float(a.host()[0]) == float(a.host()[-1]) > 0


def test_healthz_returns_503_when_a_lane_degrades(devs):
    cr = NumberCruncher(devs.subset(2), INC)
    srv = cr.serve_debug(port=0)
    try:
        hm = cr.cores.health
        steady = [0.010] * hm.window
        for _ in range(hm.min_history + 1):
            _feed_window(hm, 0, steady)
            _feed_window(hm, 1, steady)
        for _ in range(hm.confirm):
            _feed_window(hm, 1, [0.05] * hm.window)
        assert cr.health_report()[1]["verdict"] == "degraded"
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get(srv.url + "/healthz")
        assert exc_info.value.code == 503
        body = json.loads(exc_info.value.read().decode())
        assert body["healthy"] is False
        assert body["suggest_drain"] == [1]
        assert body["lanes"]["1"]["verdict"] == "degraded"
    finally:
        cr.dispose()


def test_debug_server_env_autostart(devs, monkeypatch):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("CK_DEBUG_PORT", str(port))
    cr = NumberCruncher(devs.subset(1), INC)
    cr2 = None
    try:
        srv = cr.cores._debug_server
        assert srv is not None and srv.port == port
        code, _body = _get(srv.url + "/")
        assert code == 200
        # one plane per process: a second Cores finds the port busy and
        # skips (flight-recorded), it does not crash construction
        cr2 = NumberCruncher(devs.subset(1), INC)
        assert cr2.cores._debug_server is None
        assert any(e.kind == "debug-port-skipped"
                   for e in FLIGHT.snapshot())
    finally:
        if cr2 is not None:
            cr2.dispose()
        cr.dispose()
    assert cr.cores._debug_server is None  # dispose closed it


def test_debug_server_env_rejects_ephemeral_zero(devs, monkeypatch):
    """CK_DEBUG_PORT=0 would bind a fresh random-port server per Cores
    (bind on 0 never fails, so the busy-port guard never fires) — the
    env knob accepts fixed ports only."""
    monkeypatch.setenv("CK_DEBUG_PORT", "0")
    cr = NumberCruncher(devs.subset(1), INC)
    try:
        assert cr.cores._debug_server is None
    finally:
        cr.dispose()


# ---------------------------------------------------------------------------
# postmortems
# ---------------------------------------------------------------------------

def test_dump_postmortem_unarmed_is_noop(monkeypatch):
    monkeypatch.delenv("CK_POSTMORTEM_DIR", raising=False)
    assert dump_postmortem() is None


def test_record_crash_dedupes_nested_boundaries(tmp_path, monkeypatch):
    """One exception propagating through nested wired boundaries (a
    pipeline stage's Cores.compute re-raising into ClPipeline.push)
    writes ONE black box, at the innermost boundary."""
    from cekirdekler_tpu.obs.flight import record_crash

    monkeypatch.setenv("CK_POSTMORTEM_DIR", str(tmp_path))
    exc = RuntimeError("nested crash")
    p1 = record_crash("inner", exc)
    p2 = record_crash("outer", exc)
    assert p1 is not None and p2 is None
    assert len(glob.glob(str(tmp_path / "ck_postmortem_*.json"))) == 1
    # both boundaries still left a crash event (the propagation path)
    wheres = [e.fields.get("where") for e in FLIGHT.snapshot()
              if e.kind == "crash"]
    assert "inner" in wheres and "outer" in wheres


def test_parse_prometheus_text_timestamp_form():
    """The exposition spec allows `series value timestamp_ms`; the
    timestamp must be ignored, not swallowed as the value (with the
    real value folded into the series key)."""
    text = (
        'ck_up{lane="0"} 5 1712345678901\n'
        "ck_plain 7\n"
        'ck_spacey{tag="a b"} 2.5\n'
    )
    parsed = parse_prometheus_text(text)
    assert parsed["series"]['ck_up{lane="0"}'] == 5.0
    assert parsed["series"]["ck_plain"] == 7.0
    assert parsed["series"]['ck_spacey{tag="a b"}'] == 2.5
    with pytest.raises(ValueError):
        parse_prometheus_text("ck_bad 1 2 3\n")  # value + ts only


def test_postmortem_on_injected_driver_failure(devs, tmp_path, monkeypatch):
    """The acceptance pin: an injected worker driver-queue failure
    leaves a black box containing the failing span, the last >= 50
    flight events (including the engage that preceded it), and a
    metrics snapshot — and the dump round-trips through the
    Chrome-trace exporter."""
    monkeypatch.setenv("CK_POSTMORTEM_DIR", str(tmp_path))
    FLIGHT.clear()
    cr = NumberCruncher(devs.subset(2), INC)
    n = 2048
    a = ClArray(np.zeros(n, np.float32), name="pm_a", partial_read=True)
    was_tracing = TRACER.enabled
    TRACER.enable(clear=True)
    try:
        cr.enqueue_mode = True
        cr.fused_batch = 4
        # enough windows that the ring holds a real decision history
        for _ in range(15):
            for _ in range(9):
                a.compute(cr, 902, "inc", n, 64)
            cr.barrier()
        assert FLIGHT.total_recorded >= 50, FLIGHT.total_recorded
        # open a fresh fused window, then poison lane 0's driver queue
        for _ in range(3):
            a.compute(cr, 902, "inc", n, 64)

        def boom():
            raise RuntimeError("injected driver-queue failure")

        cr.cores.workers[0].dispatch_async(boom)
        with pytest.raises(RuntimeError, match="injected driver-queue"):
            cr.barrier()
    finally:
        cr.cores._enqueued.clear()  # poisoned run: skip the flush drain
        cr.cores.enqueue_mode = False
        if not was_tracing:
            TRACER.disable()
        cr.dispose()

    dumps = glob.glob(str(tmp_path / "ck_postmortem_*.json"))
    assert len(dumps) == 1, dumps
    pm = load_postmortem(dumps[0])
    assert pm["schema"] == "ck-postmortem-v2"
    assert pm["exc"]["type"] == "RuntimeError"
    assert "injected driver-queue" in pm["exc"]["message"]
    # the last >= 50 flight events, with the decision history intact
    assert len(pm["events"]) >= 50
    kinds = [e["kind"] for e in pm["events"]]
    assert "fused-engage" in kinds and "fused-window" in kinds
    assert "driver-error" in kinds and "crash" in kinds
    assert kinds.index("driver-error") < len(kinds) - 1  # precedes crash
    driver_err = next(e for e in pm["events"] if e["kind"] == "driver-error")
    assert "injected" in driver_err["exc"]
    # the failing span is in the ring
    fail_spans = [s for s in pm["spans"] if s.kind == "driver-error"]
    assert fail_spans and "injected" in (fail_spans[0].tag or "")
    assert any(s.kind == "launch" for s in pm["spans"])
    # metrics snapshot + lane config + versions ride along
    assert any(
        k.startswith("ck_fused_windows_total")
        for k in pm["metrics"]["counters"]
    )
    assert len(pm["lanes"]["devices"]) == 2
    assert pm["versions"]["python"]
    # round trip through the Chrome-trace exporter
    chrome = to_chrome_trace(pm["spans"])
    back = from_chrome_trace(chrome)
    assert len(back) == len(pm["spans"])
    assert any(s.kind == "driver-error" for s in back)


# ---------------------------------------------------------------------------
# health observations flow from the real runtime
# ---------------------------------------------------------------------------

def test_barrier_feeds_fence_health(devs):
    cr = NumberCruncher(devs.subset(2), INC)
    n = 2048
    a = ClArray(np.zeros(n, np.float32), name="hf_a", partial_read=True)
    try:
        cr.enqueue_mode = True
        for _ in range(3):
            for _ in range(4):
                a.compute(cr, 903, "inc", n, 64)
            cr.barrier()
        cr.enqueue_mode = False
        rep = cr.health_report()
        assert set(rep) == {0, 1}
        assert all("fence" in rec["evidence"] for rec in rep.values())
        assert all(rec["verdict"] == "ok" for rec in rep.values())
    finally:
        cr.dispose()
