"""Serving-tier resilience (ISSUE 15, serve/resilience.py): pure
breaker/shed/retry machines, blast-radius containment down to the
faulty request, retry budgets under a seeded flaky fault, circuit
breakers wired into admission, brownout shedding, dispatcher crash
containment, shutdown racing an in-flight retry, the chaos drill, and
decision replay for every new kind.

The inc kernel adds exactly 1.0f — small-integer f32 arithmetic is
exact, so every lost, duplicated, or half-applied request shows as an
integer-sized error and the assertions demand bit equality (the
test_serve.py discipline, applied to the failure paths)."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.errors import (
    CekirdeklerError,
    FusedBatchError,
    InjectedFaultError,
)
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.metrics.registry import REGISTRY
from cekirdekler_tpu.obs.decisions import DECISIONS
from cekirdekler_tpu.obs.replay import verify_records
from cekirdekler_tpu.serve import (
    AdmissionController,
    ResilienceConfig,
    ServeFrontend,
    ServeJob,
    ServeRejected,
    TenantQuota,
    admit_decision,
    breaker_admit,
    breaker_transition,
    brownout_transition,
    containment_plan,
    retry_decision,
)
from cekirdekler_tpu.serve.admission import (
    REJECT_BREAKER,
    REJECT_BROWNOUT,
    REJECT_HEALTH,
    REJECT_QUEUE,
    REJECT_QUOTA,
)
from cekirdekler_tpu.serve.resilience import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    breaker_init,
)
from cekirdekler_tpu.utils.faultinject import FAULTS

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


@pytest.fixture(autouse=True)
def _disarm():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _mk(devs, n=1024, lanes=2, **fe_kw):
    cr = NumberCruncher(devs.subset(lanes), INC)
    x = ClArray(np.zeros(n, np.float32), name="rx")
    x.partial_read = True
    job = ServeJob(params=[x], kernels=["inc"], compute_id=800,
                   global_range=n, local_range=64)
    fe = ServeFrontend(cr, autostart=False, name="resil", **fe_kw)
    return cr, x, job, fe


# ---------------------------------------------------------------------------
# the pure machines
# ---------------------------------------------------------------------------

def test_breaker_lifecycle_pure():
    st = breaker_init()
    # 4 failures at threshold 5: still closed
    for k in range(4):
        r = breaker_transition(st, "failure", float(k), 5, 1.0)
        st = r["state"]
        assert st["state"] == BREAKER_CLOSED and r["action"] is None
    r = breaker_transition(st, "failure", 4.0, 5, 1.0)
    st = r["state"]
    assert st["state"] == BREAKER_OPEN and r["action"] == "opened"
    # inside the open window: refused with the HONEST remaining time
    a = breaker_admit(st, 4.25, 1.0)
    assert a["allow"] is False
    assert a["retry_after_s"] == pytest.approx(0.75)
    # past the window: the next admit IS the probe, exactly one
    a = breaker_admit(st, 5.5, 1.0)
    assert a["allow"] is True and a["probe"] is True
    st = a["state"]
    assert st["state"] == BREAKER_HALF_OPEN
    a2 = breaker_admit(st, 5.6, 1.0)
    assert a2["allow"] is False  # one probe in flight
    # probe success closes; probe failure re-opens
    r = breaker_transition(st, "success", 5.7, 5, 1.0)
    assert r["state"]["state"] == BREAKER_CLOSED and r["action"] == "closed"
    r = breaker_transition(st, "failure", 5.7, 5, 1.0)
    assert r["state"]["state"] == BREAKER_OPEN and r["action"] == "reopened"
    # a success mid-run resets the consecutive count
    st = breaker_init()
    st = breaker_transition(st, "failure", 0.0, 2, 1.0)["state"]
    st = breaker_transition(st, "success", 0.1, 2, 1.0)["state"]
    st = breaker_transition(st, "failure", 0.2, 2, 1.0)["state"]
    assert st["state"] == BREAKER_CLOSED


def test_breaker_open_rearm_past_window_pure():
    """A failure arriving AFTER the open window expired re-arms it:
    lane breakers are never admit-gated, so without the re-arm a
    persistently failing lane would read timed-out-open forever and
    its brownout pressure signal would die after one window."""
    st = breaker_init()
    for k in range(2):
        st = breaker_transition(st, "failure", float(k), 2, 1.0)["state"]
    assert st["state"] == BREAKER_OPEN and st["opened_t"] == 1.0
    # inside the window: stale outcome, window NOT extended
    r = breaker_transition(st, "failure", 1.5, 2, 1.0)
    assert r["action"] is None and r["state"]["opened_t"] == 1.0
    # past the window: re-armed, visible as a transition
    r = breaker_transition(st, "failure", 2.5, 2, 1.0)
    assert r["action"] == "reopened" and r["state"]["opened_t"] == 2.5


def test_brownout_hysteresis_pure():
    st = {"active": False, "streak": 0}
    # one pressured evaluation does not engage (engage_streak=2)
    r = brownout_transition(st, 10, 8, 4, 0, 0, engage_streak=2)
    assert r["active"] is False and r["streak"] == 1 and r["pressure"]
    r = brownout_transition(r, 10, 8, 4, 0, 0, engage_streak=2)
    assert r["active"] is True and r["changed"] is True
    # secondary signals need a non-trivial queue: open breakers with an
    # EMPTY queue are not pressure
    r2 = brownout_transition(
        {"active": False, "streak": 0}, 0, 8, 4, 3, 1, engage_streak=2)
    assert r2["pressure"] is False
    r2 = brownout_transition(
        {"active": False, "streak": 0}, 5, 8, 4, 1, 0, engage_streak=2)
    assert r2["pressure"] is True  # breaker + queue past clear mark
    # release needs the same streak of clear evaluations
    r = brownout_transition(r, 0, 8, 4, 0, 0, engage_streak=2)
    assert r["active"] is True and r["streak"] == 1
    r = brownout_transition(r, 0, 8, 4, 0, 0, engage_streak=2)
    assert r["active"] is False and r["changed"] is True


def test_retry_decision_pure():
    # deterministic: the jitter rides as an input
    a = retry_decision(0, 2, 5.0, None, 0.01, 0.08, 0.5)
    assert a == retry_decision(0, 2, 5.0, None, 0.01, 0.08, 0.5)
    assert a["retry"] is True
    assert a["delay_s"] == pytest.approx(0.01)  # base * (0.5 + 0.5)
    # exponential, capped at cap_s (pre-jitter)
    b = retry_decision(4, 9, 5.0, None, 0.01, 0.08, 0.999)
    assert b["delay_s"] <= 1.5 * 0.08
    # the three named refusals
    assert retry_decision(2, 2, 5.0, None, 0.01, 0.08, 0.0)["reason"] \
        == "attempts-exhausted"
    assert retry_decision(0, 2, 0.5, None, 0.01, 0.08, 0.0)["reason"] \
        == "budget-exhausted"
    assert retry_decision(0, 2, 5.0, 0.001, 0.01, 0.08, 0.0)["reason"] \
        == "deadline"


def test_containment_plan_pure():
    assert containment_plan(8) == {"mode": "bisect", "parts": [4, 4]}
    assert containment_plan(7) == {"mode": "bisect", "parts": [4, 3]}
    assert containment_plan(1) == {"mode": "per-request", "parts": [1]}
    assert containment_plan(3, leaf=4) == {
        "mode": "per-request", "parts": [1, 1, 1]}
    for k in range(1, 40):
        assert sum(containment_plan(k)["parts"]) == k


# ---------------------------------------------------------------------------
# admission gates: breaker + brownout order and hints
# ---------------------------------------------------------------------------

def test_admit_decision_breaker_and_brownout_gates():
    kw = dict(tenant_inflight=0, quota=4, queue_depth=0,
              max_queue_depth=8, healthy=True, est_batch_s=0.02)
    # breaker outranks queue/brownout/quota; health outranks breaker
    d = admit_decision(**dict(kw, breaker_open=True,
                              breaker_retry_after_s=0.7, queue_depth=99,
                              tenant_inflight=99, brownout=True))
    assert d["reason"] == REJECT_BREAKER
    assert d["retry_after_s"] == pytest.approx(0.7)  # the honest window
    d = admit_decision(**dict(kw, breaker_open=True, healthy=False))
    assert d["reason"] == REJECT_HEALTH
    # queue outranks brownout
    d = admit_decision(**dict(kw, queue_depth=8, brownout=True,
                              tenant_inflight=2))
    assert d["reason"] == REJECT_QUEUE
    # brownout sheds over the reduced share, before the quota reason
    d = admit_decision(**dict(kw, brownout=True, tenant_inflight=2))
    assert d["reason"] == REJECT_BROWNOUT
    assert d["retry_after_s"] >= 0.005
    # ...but never a tenant with nothing in flight (the floor)
    d = admit_decision(**dict(kw, brownout=True, tenant_inflight=0))
    assert d["admit"] is True
    # lowest priority keeps exactly one in flight under brownout
    d = admit_decision(**dict(kw, brownout=True, tenant_inflight=1,
                              priority=0))
    assert d["reason"] == REJECT_BROWNOUT
    d = admit_decision(**dict(kw, brownout=True, tenant_inflight=0,
                              priority=0))
    assert d["admit"] is True
    # quota still binds without brownout
    d = admit_decision(**dict(kw, tenant_inflight=4))
    assert d["reason"] == REJECT_QUOTA


# ---------------------------------------------------------------------------
# FusedBatchError: the structured per-window failure cause (core layer)
# ---------------------------------------------------------------------------

def test_compute_fused_batch_surfaces_clean_failure(devs):
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(1024, np.float32), name="fb")
    x.partial_read = True
    try:
        cr.enqueue_mode = True
        # first hit lands on the FIRST per-call iteration's lane
        # preflight: nothing dispatched at all — applied 0, clean
        FAULTS.arm("driver-submit:times=1")
        with pytest.raises(FusedBatchError) as ei:
            cr.cores.compute_fused_batch(["inc"], [x], 800, 1024, 64, 8)
        e = ei.value
        assert e.clean is True
        assert e.applied_iters == 0 and e.requested_iters == 8
        assert e.cause == "injected:driver-submit"
        assert isinstance(e.original, InjectedFaultError)
        FAULTS.disarm()
        cr.cores.barrier()
        cr.cores.flush()
        np.testing.assert_array_equal(np.asarray(x), 0.0)
        # skip past every per-call preflight hit (2 lanes × up to 2
        # per-call iterations): the next fire lands on the fused
        # FLUSH preflight — the residue after the applied per-call
        # iterations is still CLEAN (no lane was handed the ladder)
        FAULTS.arm("driver-submit:after=4,times=1")
        with pytest.raises(FusedBatchError) as ei:
            cr.cores.compute_fused_batch(["inc"], [x], 800, 1024, 64, 8)
        e = ei.value
        assert e.clean is True
        assert e.applied_iters == 2 and e.requested_iters == 8
        FAULTS.disarm()
        # the applied count is bit-exact: finishing the window shows
        # exactly the applied per-call iterations
        cr.cores.barrier()
        cr.cores.flush()
        np.testing.assert_array_equal(np.asarray(x), 2.0)
    finally:
        FAULTS.disarm()
        cr.dispose()


# ---------------------------------------------------------------------------
# blast-radius containment end-to-end
# ---------------------------------------------------------------------------

def test_containment_recovers_transient_fault_bit_exact(devs):
    """A transient driver-submit fault mid-batch: containment bisects,
    the residue re-dispatches, and EVERY request completes bit-exactly
    — the fault is invisible to the callers."""
    cr, x, job, fe = _mk(devs)
    try:
        futs = [fe.submit("tA", job) for _ in range(8)]
        FAULTS.arm("driver-submit:times=1")
        out = fe.step()
        assert out["requests"] == 8 and out["failed"] == 0
        recs = [f.result(timeout=30) for f in futs]
        assert len(recs) == 8
        np.testing.assert_array_equal(np.asarray(x), 8.0)
        evs = [e for e in __import__(
            "cekirdekler_tpu.obs.flight", fromlist=["FLIGHT"]
        ).FLIGHT.snapshot() if e.kind == "serve-contain"]
        assert any(e.fields.get("outcome") == "bisect" for e in evs)
    finally:
        FAULTS.disarm()
        fe.close()
        cr.dispose()


def test_containment_isolates_exactly_the_faulty_request(devs):
    """A persistent-enough fault with retries disabled: bisection
    isolates EXACTLY one request, which fails with the named injected
    cause; its 7 coalesced neighbors complete bit-identically."""
    cr, x, job, fe = _mk(
        devs, resilience=ResilienceConfig(retry_max_attempts=0))
    try:
        futs = [fe.submit("tA", job) for _ in range(8)]
        # fires on: batch(8), part(4), part(2), part(1) — the fourth
        # hit lands on a single isolated request
        FAULTS.arm("serve-dispatch:times=4")
        out = fe.step()
        assert out["requests"] == 8 and out["failed"] == 1
        done = [f for f in futs if f.exception(timeout=30) is None]
        failed = [f for f in futs if f.exception(timeout=30) is not None]
        assert len(done) == 7 and len(failed) == 1
        err = failed[0].exception()
        assert isinstance(err, InjectedFaultError)
        assert err.point == "serve-dispatch"
        # bit-exact: exactly the 7 surviving requests applied
        np.testing.assert_array_equal(np.asarray(x), 7.0)
        assert REGISTRY.counter(
            "ck_serve_contained_total",
            "fused-batch failures handled by blast-radius containment",
            outcome="isolated").value >= 1
    finally:
        FAULTS.disarm()
        fe.close()
        cr.dispose()


def test_retry_budget_contains_flaky_faults_p_mode(devs):
    """The satellite's p= flaky mode: a seeded probabilistic
    serve-dispatch fault; the retry budget re-dispatches isolated
    failures and the workload stays bit-exact (completed == array,
    failures named)."""
    cr, x, job, fe = _mk(devs)
    m_retries = REGISTRY.counter(
        "ck_serve_retries_total",
        "serve request re-dispatch attempts granted by the retry budget")
    r0 = m_retries.value
    try:
        futs = [fe.submit("tA", job) for _ in range(12)]
        FAULTS.arm("seed=2;serve-dispatch:p=0.6,times=12")
        fe.step()
        fired = FAULTS.snapshot()["clauses"][0]["fired"]
        FAULTS.disarm()
        assert fired > 0, "the flaky clause never fired"
        ok = sum(1 for f in futs if f.exception(timeout=30) is None)
        bad = [f.exception() for f in futs
               if f.exception(timeout=30) is not None]
        assert ok + len(bad) == 12
        assert all(isinstance(e, CekirdeklerError) for e in bad)
        np.testing.assert_array_equal(np.asarray(x), float(ok))
        # the budget granted re-dispatches (seeded draws — this plan's
        # fault sequence is deterministic, and seed=2 lands several
        # single-request failures that retry to success)
        assert m_retries.value > r0
    finally:
        FAULTS.disarm()
        fe.close()
        cr.dispose()


def test_containment_decisions_replay_and_tamper(devs):
    """breaker/retry/containment decisions recorded by a contained run
    replay bit-identically; a tampered output names its seq."""
    cr, x, job, fe = _mk(
        devs, resilience=ResilienceConfig(
            retry_max_attempts=0, breaker_threshold=1,
            breaker_open_s=0.05))
    DECISIONS.clear()
    try:
        futs = [fe.submit("tA", job) for _ in range(4)]
        FAULTS.arm("serve-dispatch:times=3")
        fe.step()
        FAULTS.disarm()
        assert sum(1 for f in futs
                   if f.exception(timeout=30) is not None) == 1
        rows = [r.to_row() for r in DECISIONS.snapshot()
                if r.kind in ("breaker", "retry", "containment", "shed")]
        kinds = {r["kind"] for r in rows}
        assert "containment" in kinds and "retry" in kinds \
            and "breaker" in kinds
        verdict = verify_records(rows)
        assert verdict["ok"] is True, verdict
        assert verdict["replayed"] == len(rows)
        bad = json.loads(json.dumps(
            next(r for r in rows if r["kind"] == "breaker")))
        bad["outputs"]["state"]["failures"] += 1
        v2 = verify_records([bad])
        assert v2["ok"] is False
        assert v2["first_divergence"]["seq"] == bad["seq"]
    finally:
        FAULTS.disarm()
        fe.close()
        cr.dispose()


# ---------------------------------------------------------------------------
# circuit breaker end-to-end
# ---------------------------------------------------------------------------

def test_breaker_opens_rejects_probes_and_recovers(devs):
    cr, x, job, fe = _mk(
        devs, resilience=ResilienceConfig(
            retry_max_attempts=0, breaker_threshold=2,
            breaker_open_s=0.2))
    try:
        # two failed requests open the (tenant, signature) breaker
        futs = [fe.submit("tB", job) for _ in range(2)]
        FAULTS.arm("serve-dispatch:times=8")
        fe.step()
        FAULTS.disarm()
        assert all(isinstance(f.exception(timeout=30),
                              InjectedFaultError) for f in futs)
        with pytest.raises(ServeRejected) as ei:
            fe.submit("tB", job)
        assert ei.value.reason == REJECT_BREAKER
        assert 0.0 < ei.value.retry_after_s <= 0.2
        # a different tenant's breaker is untouched
        f_ok = fe.submit("tC", job)
        fe.step()
        assert f_ok.exception(timeout=30) is None
        # after the open window: the next submit is the half-open
        # probe; its success closes the breaker
        time.sleep(0.25)
        f_probe = fe.submit("tB", job)
        fe.step()
        assert f_probe.exception(timeout=30) is None
        f2 = fe.submit("tB", job)  # closed again: admits freely
        fe.step()
        assert f2.exception(timeout=30) is None
    finally:
        FAULTS.disarm()
        fe.close()
        cr.dispose()


# ---------------------------------------------------------------------------
# brownout shedding end-to-end
# ---------------------------------------------------------------------------

def test_brownout_sheds_over_quota_but_never_starves(devs):
    cr, x, job, fe = _mk(
        devs,
        admission=AdmissionController(max_queue_depth=8, default_quota=4),
        resilience=ResilienceConfig(brownout_engage_streak=1))
    fe.admission.set_quota("low", TenantQuota(max_inflight=4, priority=0))
    try:
        # queue at the watermark (6 of 8): one evaluation engages
        # (engage_streak=1)
        futs = [fe.submit(t, job) for t in ("tA", "tA", "tA",
                                            "tB", "tB", "tB")]
        out = fe._evaluate_brownout()
        assert out["active"] is True
        # over the brownout share (quota 4 -> shed_quota 2): shed, named
        with pytest.raises(ServeRejected) as ei:
            fe.submit("tA", job)
        assert ei.value.reason == REJECT_BROWNOUT
        assert ei.value.retry_after_s >= 0.005
        # a tenant with NOTHING in flight still gets one in (the floor)
        f_new = fe.submit("tFresh", job)
        fe.step()
        for f in futs + [f_new]:
            assert f.exception(timeout=30) is None
        # the queue drained but brownout stays engaged until an
        # all-clear EVALUATION (hysteresis, not instant)
        assert fe._brownout_active is True
        # lowest priority keeps exactly one in flight: the second sheds
        f_low = fe.submit("low", job)
        with pytest.raises(ServeRejected) as ei:
            fe.submit("low", job)
        assert ei.value.reason == REJECT_BROWNOUT
        fe.step()
        assert f_low.exception(timeout=30) is None
        # all-clear evaluation releases the brownout
        fe._evaluate_brownout()
        assert fe._brownout_active is False
        fe.submit("tA", job)
        fe.step()
    finally:
        fe.close()
        cr.dispose()


def test_brownout_releases_while_idle(devs):
    """Brownout release must not wait for traffic: with the dispatcher
    idle (no pending requests → no cycles), the loop itself runs the
    release evaluation — an engaged brownout over an idle tier would
    otherwise shed the FIRST burst after hours of idleness."""
    cr, x, job, fe = _mk(
        devs,
        admission=AdmissionController(max_queue_depth=8, default_quota=4),
        resilience=ResilienceConfig(brownout_engage_streak=1))
    try:
        futs = [fe.submit(t, job) for t in ("tA", "tA", "tA",
                                            "tB", "tB", "tB")]
        assert fe._evaluate_brownout()["active"] is True
        fe.step()
        for f in futs:
            assert f.exception(timeout=30) is None
        assert fe._brownout_active is True  # queue drained, still engaged
        fe.start()  # dispatcher idles (nothing pending) — and releases
        deadline = time.perf_counter() + 5.0
        while fe._brownout_active and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert fe._brownout_active is False
    finally:
        fe.close()
        cr.dispose()


def test_cancelled_future_cannot_kill_the_cycle(devs):
    """A client legally cancels its queued future; the dispatch cycle
    must settle everyone else and survive (one tenant's cancel must
    never become a tier-wide dispatcher death)."""
    cr, x, job, fe = _mk(devs)
    try:
        futs = [fe.submit("tA", job) for _ in range(4)]
        assert futs[1].cancel() is True
        out = fe.step()
        assert out["requests"] == 4
        for i, f in enumerate(futs):
            if i == 1:
                assert f.cancelled()
            else:
                assert f.exception(timeout=30) is None
        # the cancelled request's ITERATION still ran (it was popped
        # with the batch) — the cancel settles the future, not the work
        np.testing.assert_array_equal(np.asarray(x), 4.0)
        assert fe.stats()["resilience"]["dead"] is None
    finally:
        fe.close()
        cr.dispose()


def test_retry_past_inline_budget_requeues_to_next_cycle(devs):
    """Backoff past the cycle's inline-sleep budget must re-queue the
    request instead of stalling the dispatcher; the next cycle
    re-dispatches it to completion."""
    cr, x, job, fe = _mk(
        devs, resilience=ResilienceConfig(
            retry_max_attempts=4, retry_base_s=0.02, retry_cap_s=0.1,
            retry_inline_budget_s=0.0))  # every granted retry defers
    try:
        futs = [fe.submit("tA", job) for _ in range(4)]
        FAULTS.arm("serve-dispatch:times=3")  # batch, 2x bisect parts
        out = fe.step()
        FAULTS.disarm()
        assert out["requeued"] >= 1
        # the deferred request is back in the table, still in flight
        assert fe._pending >= 1
        out2 = fe.step()
        assert out2["requeued"] == 0
        for f in futs:
            assert f.exception(timeout=30) is None
        np.testing.assert_array_equal(np.asarray(x), 4.0)
    finally:
        FAULTS.disarm()
        fe.close()
        cr.dispose()


def test_cycle_crash_settles_popped_requests_named(devs):
    """An exception escaping the cycle AFTER requests were popped out
    of the group table must still settle every popped future with the
    named error — popped requests are in neither the table nor a
    result, and used to hang forever."""
    cr, x, job, fe = _mk(devs)
    real_note_done = fe.tenants.note_done
    try:
        futs = [fe.submit("tA", job) for _ in range(3)]

        def boom(*a, **kw):
            raise RuntimeError("resolution boom")

        fe.tenants.note_done = boom
        with pytest.raises(RuntimeError, match="resolution boom"):
            fe.step()
        for f in futs:
            exc = f.exception(timeout=10)
            assert isinstance(exc, CekirdeklerError)
            assert "dispatch cycle failed" in str(exc)
    finally:
        fe.tenants.note_done = real_note_done
        fe.close(drain=False)
        cr.dispose()


# ---------------------------------------------------------------------------
# dispatcher crash containment (satellite 1)
# ---------------------------------------------------------------------------

def test_dispatcher_crash_fails_futures_and_rejects_submits(
        devs, tmp_path, monkeypatch):
    monkeypatch.setenv("CK_POSTMORTEM_DIR", str(tmp_path))
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(512, np.float32), name="cx")
    x.partial_read = True
    job = ServeJob(params=[x], kernels=["inc"], compute_id=801,
                   global_range=512, local_range=64)
    fe = ServeFrontend(cr, name="crash")  # autostart: the real thread
    m_crashes = REGISTRY.counter(
        "ck_serve_dispatcher_crashes_total",
        "serve dispatcher threads lost to an escaping exception "
        "(in-flight futures failed with the named error)")
    c0 = m_crashes.value
    try:
        fe.step = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        fut = fe.submit("tA", job)
        # the in-flight future fails with the NAMED error — no hang
        with pytest.raises(CekirdeklerError, match="dispatcher died"):
            fut.result(timeout=10)
        # submit after death rejects immediately, also named
        with pytest.raises(CekirdeklerError, match="dispatcher died"):
            fe.submit("tA", job)
        assert m_crashes.value == c0 + 1
        assert fe.stats()["resilience"]["dead"] is not None
        # the black box dumped (CK_POSTMORTEM_DIR armed)
        assert any(f.startswith("ck_postmortem")
                   for f in os.listdir(tmp_path))
    finally:
        fe.close(drain=False)
        cr.dispose()


# ---------------------------------------------------------------------------
# shutdown racing an in-flight retry/bisection (satellite 3)
# ---------------------------------------------------------------------------

def test_close_races_inflight_retry_16_threads_no_dispatch_after_halt(
        devs):
    """16 submitting threads, every dispatch failing (so the cycle is
    mid-retry/bisection when close lands): every future resolves
    (result or NAMED error, never a hang), and no dispatch follows the
    halt."""
    cr, x, job, fe = _mk(
        devs, resilience=ResilienceConfig(
            retry_max_attempts=2, retry_base_s=0.02, retry_cap_s=0.08))
    dispatches = [0]
    last_dispatch_t = [0.0]
    halt_t = [None]
    real = cr.cores.compute_fused_batch

    def counting(*a, **kw):
        dispatches[0] += 1
        last_dispatch_t[0] = time.perf_counter()
        return real(*a, **kw)

    cr.cores.compute_fused_batch = counting
    futs = []
    mu = threading.Lock()

    def client():
        try:
            f = fe.submit("tA", job)
            with mu:
                futs.append(f)
        except CekirdeklerError:
            pass  # closed-race rejections are fine (named)

    try:
        FAULTS.arm("serve-dispatch:times=1000")
        threads = [threading.Thread(target=client) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        stepper = threading.Thread(target=lambda: fe.step())
        stepper.start()
        time.sleep(0.05)  # let the cycle get into retry/bisection
        fe.close(drain=False)
        halt_t[0] = time.perf_counter()
        stepper.join(30)
        assert not stepper.is_alive()
        # every future resolved, each with a NAMED framework error
        # (injected fault, shutdown, or a successful early part)
        for f in futs:
            exc = f.exception(timeout=10)
            if exc is not None:
                assert isinstance(exc, CekirdeklerError), exc
        # no dispatch after the halt: the containment loop checks the
        # halt flag before every part
        n_at_close = dispatches[0]
        time.sleep(0.2)
        assert dispatches[0] == n_at_close
        assert last_dispatch_t[0] <= halt_t[0]
    finally:
        FAULTS.disarm()
        cr.cores.compute_fused_batch = real
        fe.close(drain=False)
        cr.dispose()


# ---------------------------------------------------------------------------
# the chaos drill (the acceptance criterion)
# ---------------------------------------------------------------------------

def _load_loadgen():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ck_loadgen_chaos_test", os.path.join(here, "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    return lg


def test_chaos_drill_32_clients_goodput_floor(devs):
    """The ISSUE 15 acceptance drill: a seeded CK_FAULTS plan
    (driver-submit failures + lane stall + slow link) under a 32-client
    mixed-tenant coalesced workload — zero hung futures, bit-exact
    results, named failures only, and >= 0.5 goodput retained vs the
    fault-free control."""
    lg = _load_loadgen()
    out = lg.run_chaos(devs, clients=32, tenants=4, signatures=4,
                       requests_per_client=4, n=4096)
    brief = {k: v for k, v in out["chaos"].items()
             if k not in ("closed",)}
    assert out["hangs"] == 0, brief
    assert out["unnamed_failures"] == 0, brief
    assert out["chaos"]["checked"] is True, brief  # bit-exact under faults
    assert out["control"]["checked"] is True
    assert out["goodput_frac"] is not None
    assert out["goodput_frac"] >= 0.5, out
    assert out["checked"] is True, {
        k: out[k] for k in ("goodput_frac", "hangs", "unnamed_failures")}
