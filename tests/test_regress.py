"""tools/regress.py — the bench regression sentinel's acceptance gates
(ISSUE 4): nonzero on an injected 20% headline regression, nonzero on a
bare-null watched section WITH the starvation reason surfaced, zero on
an unchanged artifact pair; plus the truncated-tail recovery and the
noise-aware tolerance widening.  Also pins the bench.SectionScheduler
side of the contract: skipped/starved sections write structured
``{"null_reason", "budget_spent_s"}`` records into the artifact.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

spec = importlib.util.spec_from_file_location(
    "ck_regress", os.path.join(ROOT, "tools", "regress.py"))
regress = importlib.util.module_from_spec(spec)
spec.loader.exec_module(regress)


HEADLINE = {
    "mandelbrot_mpix": 240.0,
    "vs_tuned_loop": 1.0,
    "repeat_mode_mpix": 430.0,
    "flash_T8192_mfu_default": 0.30,
    "flash_T8192_speedup_highest": 1.2,
    "nbody_e2e_enqueue_gpairs": 15.0,
    "dispatch_floor_collapse": 5.0,
    "overlap_balanced_raw": 0.80,
    "serve_p50_ms": 8.0,
    "serve_p99_ms": 40.0,
    "serve_goodput_rps": 400.0,
    "serve_coalesce_ratio": 4.0,
    "serve_chaos_goodput_frac": 0.9,
    "serve_chaos_p99_ms": 60.0,
    "serve_p99_queue_frac": 0.5,
    "serve_p99_device_frac": 0.4,
    "fabric_chaos_goodput_frac": 0.8,
    "drain_recover_ms": 900.0,
    "rejoin_converge_iters": 4.0,
    "cold_start_warm_speedup": 20.0,
    "hetero_speedup_vs_best_homog": 1.12,
}


def _art(headline, errors=None, sections=None):
    return {"path": "<mem>", "headline": headline, "errors": errors,
            "sections": sections}


def test_unchanged_pair_is_healthy():
    v = regress.diff_headlines(_art(HEADLINE), _art(dict(HEADLINE)))
    assert v["ok"] and v["exit_code"] == 0
    assert v["checked"] == len(regress.WATCHED_KEYS)
    assert v["findings"] == []


def test_injected_20pct_regression_fails_with_exit_2():
    bad = dict(HEADLINE)
    bad["flash_T8192_mfu_default"] *= 0.8 - 1e-6
    v = regress.diff_headlines(_art(HEADLINE), _art(bad))
    assert not v["ok"] and v["exit_code"] == 2
    keys = [f["key"] for f in v["findings"]]
    assert keys == ["flash_T8192_mfu_default"]
    assert v["findings"][0]["drop_frac"] > 0.19


def test_lower_direction_latency_regression_fails():
    """The serve latency keys watch LOWER-is-better: p50 doubling is a
    regression; p50 halving is an improvement and never fails."""
    bad = dict(HEADLINE)
    bad["serve_p50_ms"] *= 2.0
    v = regress.diff_headlines(_art(HEADLINE), _art(bad))
    assert not v["ok"] and v["exit_code"] == 2
    assert [f["key"] for f in v["findings"]] == ["serve_p50_ms"]
    good = dict(HEADLINE)
    good["serve_p50_ms"] *= 0.5
    good["serve_goodput_rps"] *= 2.0
    v = regress.diff_headlines(_art(HEADLINE), _art(good))
    assert v["ok"] and v["findings"] == []


def test_improvements_never_fail():
    # "better" respects each key's direction: higher-is-better keys
    # double, lower-is-better keys (the serve latencies) halve
    lower = {k for k, _a, d, _t in regress.WATCHED_KEYS if d == "lower"}
    better = {
        k: (v * 0.5 if k in lower else v * 2) for k, v in HEADLINE.items()
    }
    v = regress.diff_headlines(_art(HEADLINE), _art(better))
    assert v["ok"]


def test_bare_null_watched_key_is_hard_failure_with_reason():
    starved = dict(HEADLINE)
    starved["flash_T8192_mfu_default"] = None
    v = regress.diff_headlines(
        _art(HEADLINE),
        _art(starved, errors={
            "flash_train": "skipped: 1500s bench budget spent"}),
    )
    assert v["exit_code"] == 3
    f = v["findings"][0]
    assert f["kind"] == "starved" and f["key"] == "flash_T8192_mfu_default"
    assert "budget spent" in f["reason"]


def test_null_reason_record_preferred_over_errors_map():
    starved = dict(HEADLINE)
    starved["dispatch_floor_collapse"] = None
    sections = {
        "dispatch_floor": {
            "null_reason": "skipped: budget spent", "budget_spent_s": 1432.1,
        },
    }
    v = regress.diff_headlines(
        _art(HEADLINE), _art(starved, sections=sections))
    assert v["exit_code"] == 3
    assert "budget_spent_s=1432.1" in v["findings"][0]["reason"]


def test_hetero_key_watched_and_exactness_starves():
    """ISSUE 20: hetero_speedup_vs_best_homog is regression-watched
    (higher is better, wide 30% floor) and exactness-gated — the bench
    nulls it whenever the four arms' digests diverge, and the sentinel
    must surface that null as STARVED with the hetero section's reason,
    not as a silent pass."""
    assert any(k == "hetero_speedup_vs_best_homog"
               for k, _a, _d, _t in regress.WATCHED_KEYS)
    assert regress.KEY_SECTION["hetero_speedup_vs_best_homog"] == "hetero"
    bad = dict(HEADLINE)
    bad["hetero_speedup_vs_best_homog"] *= 0.6  # past the 30% floor
    v = regress.diff_headlines(_art(HEADLINE), _art(bad))
    assert not v["ok"] and v["exit_code"] == 2
    assert [f["key"] for f in v["findings"]] == [
        "hetero_speedup_vs_best_homog"]
    starved = dict(HEADLINE)
    starved["hetero_speedup_vs_best_homog"] = None
    sections = {"hetero": {
        "null_reason": "inexact: mixed arm digest diverged",
        "budget_spent_s": 12.0}}
    v = regress.diff_headlines(
        _art(HEADLINE), _art(starved, sections=sections))
    assert v["exit_code"] == 3
    f = v["findings"][0]
    assert f["kind"] == "starved"
    assert f["key"] == "hetero_speedup_vs_best_homog"
    assert "digest diverged" in f["reason"]


def test_missing_headline_block_entirely_is_starved():
    v = regress.diff_headlines(_art(HEADLINE), _art(None))
    assert v["exit_code"] == 3
    assert v["findings"][0]["key"] == "headline"


def test_key_aliases_bridge_artifact_generations():
    old = dict(HEADLINE)
    old["nbody_e2e_gpairs"] = old.pop("nbody_e2e_enqueue_gpairs")
    v = regress.diff_headlines(_art(old), _art(HEADLINE))
    assert v["ok"]
    # and a drop through the alias still fires
    bad = dict(HEADLINE)
    bad["nbody_e2e_enqueue_gpairs"] *= 0.5
    v = regress.diff_headlines(_art(old), _art(bad))
    assert v["exit_code"] == 2


def test_noisy_trajectory_widens_tolerance_stable_one_does_not():
    hist_noisy = [
        _art({**HEADLINE, "mandelbrot_mpix": m})
        for m in (160.0, 300.0, 170.0, 290.0, 240.0)
    ]
    hist_stable = [
        _art({**HEADLINE, "mandelbrot_mpix": m})
        for m in (238.0, 241.0, 240.0, 239.5, 240.0)
    ]
    cand = dict(HEADLINE)
    cand["mandelbrot_mpix"] *= 0.82  # 18% drop: above the 10% floor
    v = regress.diff_headlines(
        _art(HEADLINE), _art(cand), history=hist_noisy)
    assert v["ok"], v  # link-weather key: 2x CV tolerance absorbs it
    v = regress.diff_headlines(
        _art(HEADLINE), _art(cand), history=hist_stable)
    assert v["exit_code"] == 2  # historically stable key: the drop is real


def test_extract_tail_object_from_truncated_json():
    """Driver artifacts hold only the LAST 2000 chars of output; the
    headline block prints last so it survives — recovery must work from
    text whose front is cut mid-object."""
    full = json.dumps({
        "metric": "x", "value": 1.0, "big": list(range(500)),
        "errors": {"dtype_matrix": "skipped: budget"},
        "headline": {"mandelbrot_mpix": 240.0, "n_errors": 1},
    })
    tail = full[-300:]
    h = regress.extract_tail_object(tail, "headline")
    assert h == {"mandelbrot_mpix": 240.0, "n_errors": 1}
    e = regress.extract_tail_object(tail, "errors")
    assert e == {"dtype_matrix": "skipped: budget"}
    assert regress.extract_tail_object("no such thing", "headline") is None
    # braces inside strings must not confuse the scanner
    tricky = '"headline": {"note": "a { b } c", "v": 2}'
    assert regress.extract_tail_object(tricky, "headline")["v"] == 2


def test_starvation_reason_survives_driver_tail_truncation():
    """The end-to-end tail contract: a driver artifact whose front
    (including the annotated sections AND a large metrics snapshot) is
    cut must still yield the starvation reason — errors/null_sections/
    headline print last, and the sentinel reads null_sections first."""
    doc = {
        "metric": "x",
        "flash_train": {"null_reason": "skipped: budget", "x": 1},
        "metrics": {"counters": {f"ck_big_{i}": i for i in range(200)}},
        "regression": {"ok": True},
        "errors": {"flash_train": "skipped: budget"},
        "null_sections": {"flash_train": {
            "null_reason": "skipped: budget", "budget_spent_s": 1430.0}},
        "headline": {**HEADLINE, "flash_T8192_mfu_default": None},
    }
    tail = json.dumps(doc)[-2000:]
    art = {"n": 6, "cmd": "python bench.py", "rc": 0, "tail": tail,
           "parsed": None}
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(art, f)
        p = f.name
    loaded = regress.load_headline(p)
    os.unlink(p)
    assert loaded["headline"]["mandelbrot_mpix"] == HEADLINE[
        "mandelbrot_mpix"]
    assert loaded["null_sections"]["flash_train"]["budget_spent_s"] == 1430.0
    v = regress.diff_headlines(_art(HEADLINE), loaded)
    assert v["exit_code"] == 3
    assert "budget_spent_s=1430.0" in v["findings"][0]["reason"]


def test_artifact_round_ordering_is_numeric(tmp_path):
    """r100 is newer than r99 — lexicographic basename ordering would
    gate the fresh artifact against the wrong round."""
    for r, m in (("98", 240.0), ("99", 240.0), ("100", 120.0)):
        (tmp_path / f"BENCH_r{r}.json").write_text(json.dumps(
            {"headline": {**HEADLINE, "mandelbrot_mpix": m}}))
    paths = [os.path.basename(p)
             for p in regress._artifact_paths(str(tmp_path))]
    assert paths == ["BENCH_r98.json", "BENCH_r99.json", "BENCH_r100.json"]
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--against", str(tmp_path / "BENCH_r99.json"),
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    # r100 (the 50% drop) must be the picked candidate — exit 2
    assert r.returncode == 2, r.stdout + r.stderr
    assert "BENCH_r100" in r.stdout or "mandelbrot" in r.stdout


def test_load_headline_real_r5_artifact():
    art = regress.load_headline(os.path.join(ROOT, "BENCH_r05.json"))
    assert isinstance(art["headline"], dict)
    assert "mandelbrot_mpix" in art["headline"]
    assert isinstance(art["errors"], dict)


def test_cli_acceptance_pair(tmp_path):
    """The acceptance criterion end-to-end through the CLI: r5 baseline
    vs (a) itself → 0, (b) 20% injected regression → nonzero, (c) a
    bare-null section → nonzero."""
    r5 = regress.load_headline(os.path.join(ROOT, "BENCH_r05.json"))
    h = dict(r5["headline"])

    def run(candidate_doc):
        p = tmp_path / "cand.json"
        p.write_text(json.dumps(candidate_doc))
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
             "--against", os.path.join(ROOT, "BENCH_r05.json"),
             "--candidate", str(p)],
            capture_output=True, text=True,
        )

    ok = run({"headline": h, "errors": {}})
    assert ok.returncode == 0, ok.stdout + ok.stderr

    bad = dict(h)
    bad["mandelbrot_mpix"] *= 0.79
    r = run({"headline": bad, "errors": {}})
    assert r.returncode == 2, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout

    starved = dict(h)
    starved["flash_T8192_mfu_default"] = None
    r = run({"headline": starved,
             "errors": {"flash_train": "skipped: budget spent"}})
    assert r.returncode == 3, r.stdout + r.stderr
    assert "STARVED" in r.stdout and "budget spent" in r.stdout


def test_cli_candidate_excluded_from_noise_model(tmp_path):
    """A regressed candidate must not feed the trajectory noise model:
    before the fix, a 30% drop inflated the CV enough to widen its own
    tolerance past the drop and exit 0."""
    for r, m in (("01", 240.0), ("02", 240.0), ("03", 239.0)):
        (tmp_path / f"BENCH_r{r}.json").write_text(json.dumps(
            {"headline": {**HEADLINE, "mandelbrot_mpix": m}}))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"headline": {**HEADLINE, "mandelbrot_mpix": 168.0}}))  # -30%
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--against", str(tmp_path / "BENCH_r03.json"),
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 2, r.stdout + r.stderr
    assert "mandelbrot_mpix" in r.stdout


def test_cli_default_candidate_never_diffs_backwards(tmp_path):
    """--against the NEWEST artifact with no --candidate must refuse
    (a time-reversed diff reads improvements as regressions), not
    silently pick an older round."""
    for r, m in (("01", 200.0), ("02", 240.0)):
        (tmp_path / f"BENCH_r{r}.json").write_text(json.dumps(
            {"headline": {**HEADLINE, "mandelbrot_mpix": m}}))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--against", str(tmp_path / "BENCH_r02.json"),
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "no artifact newer" in r.stderr
    # a baseline outside BENCH_r<N> naming has no round to compare:
    # refuse (the -1 fallback key would mark every artifact "newer")
    (tmp_path / "fresh.json").write_text(json.dumps(
        {"headline": dict(HEADLINE)}))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--against", str(tmp_path / "fresh.json"),
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "BENCH_r<N> naming" in r.stderr
    # and with an older baseline the newer artifact is picked forward
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--against", str(tmp_path / "BENCH_r01.json"),
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_epilogue_embeds_verdict(tmp_path):
    root = str(tmp_path)
    base = {"headline": dict(HEADLINE), "errors": {}}
    (tmp_path / "BENCH_r90.json").write_text(json.dumps(base))
    result = {"headline": dict(HEADLINE), "errors": {}}
    v = regress.bench_epilogue(result, repo_root=root)
    assert v["ok"] and v["against"] == "BENCH_r90.json"
    result_bad = {"headline": {**HEADLINE,
                               "nbody_e2e_enqueue_gpairs": 1.0},
                  "errors": {}}
    v = regress.bench_epilogue(result_bad, repo_root=root)
    assert v["exit_code"] == 2
    # no artifacts -> no verdict, never a crash
    assert regress.bench_epilogue(result, repo_root=str(tmp_path / "x")) is None


def test_bench_epilogue_skips_headline_less_newest_artifact(tmp_path):
    """A truncated previous round (no recoverable headline) must not
    silently disable the sentinel (0 keys checked would read ok:true);
    the epilogue falls back to the newest artifact WITH a headline."""
    (tmp_path / "BENCH_r90.json").write_text(json.dumps(
        {"headline": dict(HEADLINE)}))
    (tmp_path / "BENCH_r91.json").write_text(json.dumps(
        {"n": 91, "rc": 1, "tail": "crashed before the tail block",
         "parsed": None}))
    bad = {"headline": {**HEADLINE,
                        "nbody_e2e_enqueue_gpairs": 1.0}, "errors": {}}
    v = regress.bench_epilogue(bad, repo_root=str(tmp_path))
    assert v["exit_code"] == 2 and v["against"] == "BENCH_r90.json"
    # and when NO artifact has a headline: ok None, never ok true
    only_bad = tmp_path / "only"
    only_bad.mkdir()
    (only_bad / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "rc": 1, "tail": "x", "parsed": None}))
    v = regress.bench_epilogue(bad, repo_root=str(only_bad))
    assert v["ok"] is None and "no on-disk artifact" in v["error"]


# ---------------------------------------------------------------------------
# bench.SectionScheduler: structured null records (the producer side)
# ---------------------------------------------------------------------------

def _bench():
    sys.path.insert(0, ROOT)
    import bench

    return bench


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_scheduler_records_structured_skip_reason():
    bench = _bench()
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 95.0
    assert s.run("overlap", lambda: "x", default=None) is None
    rec = s.skips["overlap"]
    assert "skipped" in rec["null_reason"]
    assert rec["budget_spent_s"] == 95.0


def test_scheduler_records_structured_exception_reason():
    bench = _bench()
    s = bench.SectionScheduler(100.0, {})

    def boom():
        raise RuntimeError("tunnel died")

    assert s.run("flash_train", boom, default=None) is None
    rec = s.skips["flash_train"]
    assert rec["null_reason"].startswith("RuntimeError")
    assert "budget_spent_s" in rec


def test_finalize_result_tail_order_and_embeds():
    """The artifact epilogue: null records written, metrics snapshot +
    regression verdict embedded, headline LAST (tail survival) with
    regression_ok mirrored into it."""
    bench = _bench()
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 60.0}, clock=clock)
    clock.t = 99.0
    dt = s.run("dtype_matrix_like", lambda: None, default=None)
    result = {
        "metric": "mandelbrot_throughput",
        "dtype_matrix_like": dt,
        "errors": s.errors,
        "headline": dict(HEADLINE),
    }
    out = bench.finalize_result(result, s)
    keys = list(out)
    # tail-critical order: the (possibly large) metrics snapshot comes
    # FIRST of the appended blocks; errors + null_sections + headline
    # close the artifact so a 2000-char tail cut cannot lose the
    # starvation evidence or the headline
    assert keys[-5:] == ["metrics", "regression", "errors",
                         "null_sections", "headline"]
    assert isinstance(out["metrics"], dict)
    assert out["null_sections"]["dtype_matrix_like"][
        "null_reason"].startswith("skipped")
    assert out["dtype_matrix_like"]["null_reason"].startswith("skipped")
    # the on-disk trajectory ends at r5, whose artifact predates several
    # watched keys — the verdict must exist either way, and its ok flag
    # is mirrored into the tail-surviving headline block
    assert out["headline"]["regression_ok"] == (
        out["regression"].get("ok")
        if isinstance(out["regression"], dict) else None
    )


def test_failed_ratio_sections_surface_as_starved_not_improvement():
    """A failed tuned_loop leaves vs_tuned_loop null in the headline
    (bench emits None instead of a /1e-9 garbage ratio); the sentinel
    must hard-fail it with the section's reason — not read a 1e9+
    'improvement' and exit 0."""
    cand = dict(HEADLINE)
    cand["vs_tuned_loop"] = None
    cand["repeat_mode_mpix"] = None
    v = regress.diff_headlines(
        _art(HEADLINE),
        _art(cand, errors={
            "tuned_loop": "RuntimeError: tunnel died",
            "repeat_mode": "skipped: budget spent",
        }),
    )
    assert v["exit_code"] == 3
    reasons = {f["key"]: f["reason"] for f in v["findings"]}
    assert "tunnel died" in reasons["vs_tuned_loop"]
    assert "budget spent" in reasons["repeat_mode_mpix"]


def test_critical_failure_artifact_still_finalized():
    """The early-exit path (headline measurement died) must still ship
    a finalized artifact: headline block present with a null
    mandelbrot_mpix, metrics + null_sections embedded, and the sentinel
    reports the framework section's reason."""
    bench = _bench()
    s = bench.SectionScheduler(100.0, {})
    full = s.run("framework", lambda: (_ for _ in ()).throw(
        RuntimeError("tunnel died")), default=None, critical=True)
    assert full is None
    result = {
        "metric": "mandelbrot_throughput", "value": 0.0,
        "unit": "Mpixels/sec", "vs_baseline": 0.0, "errors": s.errors,
        "headline": {"mandelbrot_mpix": None, "n_errors": len(s.errors)},
    }
    bench.finalize_result(result, s)
    assert list(result)[-1] == "headline"
    assert list(result)[-2] == "null_sections"
    assert isinstance(result["metrics"], dict)
    assert result["null_sections"]["framework"]["null_reason"].startswith(
        "RuntimeError")
    # the EMBEDDED verdict (diffed against the on-disk trajectory, where
    # r5 carries mandelbrot_mpix) reads the same null_sections source as
    # the standalone CLI: reason arrives with budget_spent_s attached
    emb = result["regression"]
    if isinstance(emb, dict) and emb.get("findings"):
        by_key = {f["key"]: f for f in emb["findings"]}
        if "mandelbrot_mpix" in by_key:
            assert "budget_spent_s=" in by_key["mandelbrot_mpix"]["reason"]
    v = regress.diff_headlines(
        _art(HEADLINE),
        {"path": "<mem>", "headline": result["headline"],
         "errors": result["errors"],
         "null_sections": result["null_sections"], "sections": result},
    )
    assert v["exit_code"] == 3
    by_key = {f["key"]: f for f in v["findings"]}
    assert "tunnel died" in by_key["mandelbrot_mpix"]["reason"]


def test_annotate_nulls_replaces_bare_nulls_only():
    bench = _bench()
    clock = _Clock()
    s = bench.SectionScheduler(
        100.0, {"dtype_matrix": 60.0, "marker_overhead": 10.0}, clock=clock)
    clock.t = 90.0
    dt = s.run("dtype_sweepish", lambda: None, default=None)
    nb = s.run("nbody", lambda: {"gpairs_per_sec": 0.0},
               default={"gpairs_per_sec": 0.0})
    result = {"dtype_sweepish": dt, "nbody": nb, "untouched": None}
    s.annotate_nulls(result)
    assert result["dtype_sweepish"]["null_reason"].startswith("skipped")
    assert result["dtype_sweepish"]["budget_spent_s"] == 90.0
    assert result["nbody"] == {"gpairs_per_sec": 0.0}  # real value kept
    assert result["untouched"] is None  # not a recorded section


# ---------------------------------------------------------------------------
# --history: the per-key trajectory table
# ---------------------------------------------------------------------------

def _write_round(root, n, headline):
    path = os.path.join(root, f"BENCH_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump({"headline": headline}, f)
    return path


def test_history_table_values_cv_and_tolerance(tmp_path):
    root = str(tmp_path)
    # three rounds so the CV column engages for a stable key; one key
    # goes null in the last round and must render as null, not vanish
    for n, mpix in ((1, 240.0), (2, 250.0), (3, 245.0)):
        h = dict(HEADLINE)
        h["mandelbrot_mpix"] = mpix
        if n == 3:
            h["vs_tuned_loop"] = None
        _write_round(root, n, h)
    table = regress.history_table(root)
    lines = table.splitlines()
    assert lines[0].split()[:1] == ["key"]
    assert "r01" in lines[0] and "r03" in lines[0]
    assert "CV" in lines[0] and "tol" in lines[0]
    mandel = next(ln for ln in lines if ln.startswith("mandelbrot_mpix"))
    assert "240" in mandel and "250" in mandel and "245" in mandel
    tuned = next(ln for ln in lines if ln.startswith("vs_tuned_loop"))
    assert "null" in tuned
    # stable trajectory: CV small, tolerance stays at the floor (0.10)
    cv, tol = mandel.split()[-2:]
    assert float(cv) < 0.05 and float(tol) == 0.1


def test_history_table_empty_root(tmp_path):
    assert "no BENCH_r*.json" in regress.history_table(str(tmp_path))


def test_history_table_renders_missing_rounds_as_gaps(tmp_path):
    """r03/r04 absent between r02 and r05 → gap columns with `-`
    cells, DISTINCT from `null` (the round ran but starved the key)."""
    root = str(tmp_path)
    for n, mpix in ((1, 240.0), (2, 250.0), (5, None)):
        _write_round(root, n, {"mandelbrot_mpix": mpix,
                               "vs_tuned_loop": 1.0})
    table = regress.history_table(root)
    header = table.splitlines()[0]
    for col in ("r01", "r02", "r03", "r04", "r05"):
        assert col in header, table
    mandel = next(ln for ln in table.splitlines()
                  if ln.startswith("mandelbrot_mpix"))
    cells = mandel.split()
    # key, r01, r02, gap, gap, null, CV, tol
    assert cells[1:6] == ["240", "250", "-", "-", "null"], table


def test_cli_empty_trajectory_is_actionable_single_line(tmp_path):
    """(ISSUE 8 satellite) No parseable artifact → ONE actionable line
    on stderr and exit 1, never a traceback — for both the gating flow
    and --history."""
    root = str(tmp_path)
    # a binary/corrupt artifact: the shape that used to traceback
    # (UnicodeDecodeError inside load_headline)
    with open(os.path.join(root, "BENCH_r01.json"), "wb") as f:
        f.write(b"\x80\x81\xffnot json")

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
             "--root", root, *args],
            capture_output=True, text=True,
        )

    r = run("--against", os.path.join(root, "BENCH_r01.json"))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "Traceback" not in r.stderr and "Traceback" not in r.stdout
    assert "parses to a headline" in r.stderr
    assert len([ln for ln in r.stderr.splitlines() if ln.strip()]) == 1

    h = run("--history")
    assert h.returncode == 0, h.stdout + h.stderr
    assert "Traceback" not in h.stderr
    assert "parses to a headline" in h.stdout

    # a genuinely EMPTY root names the bootstrap action
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--root", empty, "--against", "BENCH_r99.json"],
        capture_output=True, text=True,
    )
    assert r2.returncode == 1
    assert "no BENCH_r*.json artifacts" in r2.stderr
    assert "bench.py" in r2.stderr and "Traceback" not in r2.stderr


def test_cli_explicit_candidate_bypasses_trajectory_check(tmp_path):
    """--candidate is an explicit pair diff: it must keep working even
    when the ROOT trajectory is empty/corrupt."""
    root = str(tmp_path)
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(json.dumps({"headline": dict(HEADLINE)}))
    cand.write_text(json.dumps({"headline": dict(HEADLINE)}))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "regress.py"),
         "--root", root, "--against", str(base), "--candidate", str(cand)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_main_history_flag_short_circuits(tmp_path, capsys):
    _write_round(str(tmp_path), 1, HEADLINE)
    rc = regress.main(["--history", "--root", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mandelbrot_mpix" in out and "tol" in out
