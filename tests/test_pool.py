"""Task/device pool scheduler tests (reference semantics:
ClPipeline.cs:3241-5080) on the 8-virtual-device rig."""

import threading

import numpy as np
import pytest

import cekirdekler_tpu as ct
from cekirdekler_tpu.arrays.clarray import ClArray
from cekirdekler_tpu.pipeline.pool import ClDevicePool, ClTask, ClTaskPool, PoolType

SRC = """
__kernel void addOne(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
__kernel void scale2(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] * 2.0f;
}
"""


def _cpus(n=4):
    return ct.all_devices().cpus().subset(n)


def _task(arr, kernel, cid):
    return ClTask(
        params=[arr], kernel_names=[kernel], compute_id=cid,
        global_range=arr.size, local_range=64,
    )


def test_pool_runs_all_tasks_greedily():
    n = 256
    arrays = [ClArray(np.zeros(n, np.float32)) for _ in range(12)]
    pool = ClTaskPool()
    for i, a in enumerate(arrays):
        pool.add(_task(a, "addOne", 100 + i))
    with ClDevicePool(_cpus(4), SRC) as dp:
        dp.enqueue_task_pool(pool)
        dp.finish()
        done = dp.tasks_done_per_device()
    assert sum(done) == 12
    for a in arrays:
        np.testing.assert_array_equal(a.host(), np.ones(n, np.float32))


def test_device_select_pins_tasks():
    n = 128
    arrays = [ClArray(np.zeros(n, np.float32)) for _ in range(4)]
    pool = ClTaskPool()
    pool.add(ClTask.device_select_begin(1))
    for i, a in enumerate(arrays):
        pool.add(_task(a, "addOne", 200 + i))
    pool.add(ClTask.device_select_end())
    with ClDevicePool(_cpus(3), SRC) as dp:
        dp.enqueue_task_pool(pool)
        dp.finish()
        done = dp.tasks_done_per_device()
    assert done == [0, 4, 0]


def test_global_synchronization_orders_phases():
    """addOne on every array, global sync, then scale2: result must be
    (0+1)*2 = 2 everywhere — without the barrier a scale2 could run before
    its addOne."""
    n = 128
    a = ClArray(np.zeros(n, np.float32))
    pool = ClTaskPool()
    pool.add(_task(a, "addOne", 300))
    pool.add(ClTask.global_synchronization())
    pool.add(_task(a, "scale2", 301))
    with ClDevicePool(_cpus(2), SRC) as dp:
        dp.enqueue_task_pool(pool)
        dp.finish()
    np.testing.assert_array_equal(a.host(), np.full(n, 2.0, np.float32))


def test_broadcast_runs_on_every_device():
    n = 64
    a = ClArray(np.zeros(n, np.float32))
    counter = []
    t = _task(a, "addOne", 400).as_broadcast()
    t.callback = lambda task: counter.append(1)
    with ClDevicePool(_cpus(3), SRC) as dp:
        dp.enqueue_task_pool(ClTaskPool([t]))
        dp.finish()
        done = dp.tasks_done_per_device()
    assert done == [1, 1, 1]
    assert len(counter) == 3


def test_serial_mode_executes_in_order():
    n = 64
    a = ClArray(np.zeros(n, np.float32))
    order = []
    pool = ClTaskPool()
    pool.add(ClTask.serial_mode_begin())
    for i in range(6):
        kernel = "addOne" if i % 2 == 0 else "scale2"
        t = _task(a, kernel, 500 + i)
        t.callback = lambda task, i=i: order.append(i)
        pool.add(t)
    pool.add(ClTask.serial_mode_end())
    with ClDevicePool(_cpus(3), SRC) as dp:
        dp.enqueue_task_pool(pool)
        dp.finish()
    assert order == list(range(6))
    # ((((0+1)*2)+1)*2+1)*2 = 14
    np.testing.assert_array_equal(a.host(), np.full(n, 14.0, np.float32))


def test_hot_add_device():
    n = 128
    arrays = [ClArray(np.zeros(n, np.float32)) for _ in range(8)]
    pool = ClTaskPool()
    for i, a in enumerate(arrays):
        pool.add(_task(a, "addOne", 600 + i))
    with ClDevicePool(_cpus(1), SRC) as dp:
        dp.add_device(ct.all_devices().cpus()[1])
        assert dp.num_devices == 2
        dp.enqueue_task_pool(pool)
        dp.finish()
        assert sum(dp.tasks_done_per_device()) == 8
    for a in arrays:
        np.testing.assert_array_equal(a.host(), np.ones(n, np.float32))


def test_round_robin_rejected():
    with pytest.raises(Exception):
        ClDevicePool(_cpus(1), SRC, pool_type=PoolType.DEVICE_ROUND_ROBIN)


def test_callbacks_and_errors_surface():
    bad = ClTask(params=[ClArray(np.zeros(64, np.float32))],
                 kernel_names=["nope"], compute_id=700, global_range=64, local_range=64)
    with ClDevicePool(_cpus(1), SRC) as dp:
        dp.enqueue_task_pool(ClTaskPool([bad]))
        with pytest.raises(Exception):
            dp.finish()


def test_task_factory_from_array():
    a = ClArray(np.zeros(64, np.float32))
    t = a.task(800, "addOne", 64, 64)
    assert t.kernel_names == ["addOne"]
    assert t.global_range == 64


def test_task_storm_bounded_inflight():
    """Under a storm of tasks with fine-grained queue control, each chip's
    marker-observed in-flight depth stays bounded by queue_limit (+ one
    task's dispatch burst) — the reference's markersRemaining() < queueLimit
    throttle (ClPipeline.cs:4899-4909; VERDICT r1 #6)."""
    devs = _cpus(2)
    pool = ClDevicePool(
        devs, SRC, fine_grained_queue_control=True, queue_limit=4,
        max_queues_per_device=8,
    )
    arrs = [ClArray(np.zeros(128, np.float32), name=f"s{i}") for i in range(40)]
    tp = ClTaskPool()
    for i, a in enumerate(arrs):
        a.partial_read = True
        tp.add(_task(a, "addOne", i + 1))
    pool.enqueue_task_pool(tp)
    pool.finish()
    for a in arrs:
        np.testing.assert_allclose(np.asarray(a), 1.0)
    # one task dispatches ~3 markers (upload+launch+download); depth may
    # overshoot the limit by one task's burst but not unboundedly
    assert pool.max_inflight_depth() <= 4 + 3, pool.max_inflight_depth()
    pool.dispose()


def test_adaptive_queue_depth_spreads_tail():
    """With many tasks, every chip gets work (the adaptive depth heuristic
    doesn't let one chip claim everything)."""
    devs = _cpus(4)
    pool = ClDevicePool(devs, SRC, max_queues_per_device=16)
    arrs = [ClArray(np.zeros(128, np.float32), name=f"t{i}") for i in range(48)]
    tp = ClTaskPool()
    for i, a in enumerate(arrs):
        a.partial_read = True
        tp.add(_task(a, "addOne", i + 1))
    pool.enqueue_task_pool(tp)
    pool.finish()
    done = pool.tasks_done_per_device()
    assert sum(done) == 48
    # adaptive depth caps a claim at remaining/(2*n) — no chip can claim
    # everything, and most chips participate (thread wake timing may
    # occasionally idle one)
    assert max(done) < 40, done
    assert sum(1 for d in done if d > 0) >= 3, done
    pool.dispose()
