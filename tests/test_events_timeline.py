"""Dedicated coverage for the seed-era ``utils/events.py`` and
``utils/timeline.py`` (neither had its own tests; the flight recorder
and debug plane build on their idioms, so their semantics are pinned
here first).

UserEvent runs against whichever tier loaded (native condition-variable
or the pure-Python fallback) — the CONTRACT is identical either way:
trigger fires, the pending counter fires at zero, waits time out.
Timeline analysis is pinned against a synthetic Xprof trace file so the
reduction (device tracks → busy/span) is deterministic."""

import gzip
import json
import os
import threading
import time
from contextlib import contextmanager

import pytest

from cekirdekler_tpu.utils import timeline as tl
from cekirdekler_tpu.utils.events import UserEvent
from cekirdekler_tpu.utils.timeline import (
    DeviceTimeline,
    _merged_busy,
    analyze_trace_dir,
)


# ---------------------------------------------------------------------------
# UserEvent (ClUserEvent parity semantics)
# ---------------------------------------------------------------------------

def test_user_event_trigger_and_fired():
    ev = UserEvent()
    try:
        assert ev.fired() is False
        assert ev.wait(timeout=0.05) is False  # untriggered wait times out
        ev.trigger()
        assert ev.fired() is True
        assert ev.wait(timeout=0.05) is True   # already fired: immediate
    finally:
        ev.close()


def test_user_event_counter_fires_at_zero():
    ev = UserEvent()
    try:
        ev.increment()
        ev.increment()
        assert ev.pending() == 2
        ev.decrement()
        assert ev.fired() is False  # one contributor still pending
        assert ev.pending() == 1
        ev.decrement()
        assert ev.fired() is True   # last decrement fires
    finally:
        ev.close()


def test_user_event_releases_a_blocked_waiter():
    ev = UserEvent()
    released = threading.Event()

    def waiter():
        if ev.wait(timeout=10.0):
            released.set()

    t = threading.Thread(target=waiter)
    t.start()
    try:
        time.sleep(0.05)
        assert not released.is_set()  # genuinely blocked
        ev.trigger()
        t.join(timeout=10.0)
        assert released.is_set()
    finally:
        t.join(timeout=1.0)
        ev.close()


def test_user_event_close_is_idempotent():
    ev = UserEvent()
    ev.close()
    ev.close()  # double close must be harmless (the __del__ path)


# ---------------------------------------------------------------------------
# timeline: interval union + trace-dir reduction
# ---------------------------------------------------------------------------

def test_merged_busy_unions_overlaps():
    assert _merged_busy([]) == 0.0
    assert _merged_busy([(0.0, 10.0)]) == 10.0
    # overlapping + disjoint + contained
    assert _merged_busy(
        [(0.0, 5.0), (3.0, 8.0), (20.0, 25.0), (21.0, 22.0)]
    ) == pytest.approx(13.0)


def test_device_timeline_busy_fraction():
    assert DeviceTimeline().compute_busy_fraction == 0.0  # no div-by-zero
    t = DeviceTimeline(compute_busy_ms=3.0, span_ms=4.0)
    assert t.compute_busy_fraction == pytest.approx(0.75)


def _write_trace(dirpath, events, name="host.trace.json.gz"):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def test_analyze_trace_dir_reduces_device_tracks(tmp_path):
    events = [
        # device process + its XLA Ops track
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 2,
         "args": {"name": "XLA Ops"}},
        # a second device
        {"ph": "M", "name": "process_name", "pid": 8,
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "name": "thread_name", "pid": 8, "tid": 2,
         "args": {"name": "XLA Ops"}},
        # a host process that must be IGNORED
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "python"}},
        # device ops (ts/dur in µs): overlapping on dev 0
        {"ph": "X", "pid": 7, "tid": 2, "ts": 0.0, "dur": 1000.0},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 500.0, "dur": 1000.0},
        {"ph": "X", "pid": 8, "tid": 2, "ts": 2000.0, "dur": 500.0},
        # an event on the device pid but a non-op track: ignored
        {"ph": "X", "pid": 7, "tid": 9, "ts": 0.0, "dur": 9999.0},
        # a host event: ignored
        {"ph": "X", "pid": 1, "tid": 0, "ts": 0.0, "dur": 9999.0},
    ]
    _write_trace(str(tmp_path / "plugins"), events)
    result = analyze_trace_dir(str(tmp_path))
    assert result.n_devices == 2
    assert result.n_events == 3
    # dev0 union = 1.5 ms, dev1 = 0.5 ms
    assert result.compute_busy_ms == pytest.approx(2.0)
    assert result.span_ms == pytest.approx(2.5)  # 0 .. 2500 µs
    assert result.per_device_busy_ms["/device:TPU:0"] == pytest.approx(1.5)
    assert result.compute_busy_fraction == pytest.approx(0.8)
    assert result.trace_path and result.trace_path.endswith(".trace.json.gz")


def test_analyze_trace_dir_picks_newest_and_survives_empty(tmp_path):
    assert analyze_trace_dir(str(tmp_path)).n_events == 0  # empty: empty
    old = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 0.0, "dur": 100.0},
    ]
    new = list(old) + [
        {"ph": "X", "pid": 7, "tid": 2, "ts": 200.0, "dur": 100.0},
    ]
    p_old = _write_trace(str(tmp_path), old, name="a.trace.json.gz")
    os.utime(p_old, (1, 1))  # force mtime ordering regardless of fs clock
    _write_trace(str(tmp_path), new, name="b.trace.json.gz")
    result = analyze_trace_dir(str(tmp_path))
    assert result.n_events == 2  # the NEWEST file won


def test_capture_runs_region_when_profiler_unavailable(monkeypatch):
    import jax

    def broken_trace(_dir):
        raise RuntimeError("profiler unavailable on this backend")

    monkeypatch.setattr(jax.profiler, "trace", broken_trace)
    ran = []
    with tl.capture("/tmp/ck_never_written") as result:
        ran.append(True)  # the region still runs, untraced
    assert ran and result().n_events == 0


def test_capture_propagates_region_exception(monkeypatch, tmp_path):
    import jax

    exited = []

    class FakeProf:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            exited.append(exc[0])

    monkeypatch.setattr(jax.profiler, "trace", lambda d: FakeProf())
    with pytest.raises(ValueError, match="inside region"):
        with tl.capture(str(tmp_path)):
            raise ValueError("inside region")
    # the profiler was stopped best-effort even though the region raised
    assert len(exited) == 1


def test_real_profiler_capture_reduces(tmp_path):
    """The REAL ``jax.profiler.trace`` format, alongside the synthetic
    fixture: capture actual jitted work with a ``TraceAnnotation``,
    then assert the shared loader and both reducers handle the genuine
    dump.  Gated on a NAMED capability — a jax build whose profiler
    cannot emit a trace-event dump skips, it does not fail."""
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "real")
    try:
        with jax.profiler.trace(d):
            with jax.profiler.TraceAnnotation("ck|k=mm|c=1|l=0|s=1"):
                x = jnp.ones((128, 128))
                for _ in range(2):
                    x = (x @ x).block_until_ready()
    except Exception as e:  # noqa: BLE001 - capability, not correctness
        pytest.skip(f"rig lacks capability:jax-profiler-trace ({e!r})")
    path, events = tl.load_trace_events(d)
    if path is None or not events:
        pytest.skip(
            "rig lacks capability:xprof-trace-json (profiler ran but "
            "wrote no trace-event dump)")
    # the real format reduces without error; on a deviceless CPU rig
    # that means ZERO device events (the named-absence contract), on an
    # accelerator rig a consistent busy/span pair
    result = analyze_trace_dir(d)
    assert result.n_events >= 0
    if result.n_events:
        assert 0.0 < result.compute_busy_ms <= result.span_ms
        assert result.n_devices >= 1
    else:
        assert result.compute_busy_ms == 0.0 and result.n_devices == 0
    # the annotation is discoverable by the device-attribution parser —
    # the correlation seam trace/device.py builds on
    from cekirdekler_tpu.trace.device import parse_trace_dump

    dump = parse_trace_dump(d)
    assert dump.n_events == len(events)
    assert 1 in dump.dump_marks, (
        "TraceAnnotation did not surface in the real dump — the mark "
        "correlation contract diverged from this jax's trace format")
    assert dump.dump_marks[1]["kernel"] == "mm"


def test_timeline_tracer_regions_and_report(monkeypatch, tmp_path):
    fake = DeviceTimeline(compute_busy_ms=1.0, span_ms=2.0, n_events=3)

    @contextmanager
    def fake_capture(_dir):
        yield lambda: fake

    monkeypatch.setattr(tl, "capture", fake_capture)
    tr = tl.Tracer(str(tmp_path))
    with tr.region("warmup"):
        pass
    with tr.region("steady"):
        pass
    assert set(tr.regions) == {"warmup", "steady"}
    assert tr.regions["steady"].compute_busy_fraction == pytest.approx(0.5)
    rep = tr.report()
    assert "warmup" in rep and "50.0% busy" in rep
    assert tl.Tracer(str(tmp_path)).report() == "(no regions captured)"
