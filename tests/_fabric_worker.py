"""Worker process for the serving fabric: ONE shard of a multi-process
fabric — a ``ServeFrontend`` over its own ``NumberCruncher`` in its own
interpreter (the ``tests/_dcn_worker.py`` idiom: parent spawns N of
these, each prints a READY sentinel, then obeys a JSON-lines command
protocol on stdin/stdout).  Used by ``tests/test_fabric.py`` (the
seeded kill-and-reroute drill SIGKILLs one of these mid-stream) and by
``tools/loadgen.py --fabric N`` (the multi-process goodput run).

Run as ``python tests/_fabric_worker.py <member> <n> <local_range>
[max_queue_depth] [gather_window_ms]`` — the optional queue bound makes
the shard run the SAME per-process admission configuration the
single-process baseline runs (per-process queue bounds are exactly the
state sharding scales); the gather window is per-shard config (a shard
seeing 1/N of the clients gathers ~N× longer to fill the same fused
batch — the equal-batch-size normalization).

Protocol (one JSON object per line; every command gets one reply):

- ``{"op": "warm", "sigs": [si, ...]}`` — precompile the ladder set
  for those signatures via ``ServeFrontend.warmup`` →
  ``{"op": "warmed", "warmed": k}``
- ``{"op": "serve", "assignments": [[tenant, si, clients, requests],
  ...]}`` — closed-loop client threads against the local frontend →
  ``{"op": "done", "completed", "per_sig", "latencies_ms", "wall_s",
  "hangs", "failed", "unnamed_failures", "failure_causes", "rejected",
  "checked"}``
- ``{"op": "run", "rid": i, "tenant": t, "sig": si, "iters": k}`` —
  k sequential blocking requests (the kill-test unit of work; the
  reply IS the ack — a SIGKILLed worker never acks, so the parent
  re-routes exactly the unacked rids) → ``{"op": "done", "rid", "sig",
  "count"}``.  Optional ``"trace_rid": "r..."`` propagates the
  parent's request-lifecycle id onto every submit, so one rid's chain
  (``obs/reqtrace.py``) survives a shard hop: the survivor's
  admitted → ... → resolved events carry the SAME rid the parent
  stamped ``diverted``/``rerouted`` under.
- ``{"op": "value", "sig": si}`` — the signature array's value (bit-
  exactness evidence: every element must equal the applied count) →
  ``{"op": "value", "sig", "value", "uniform": bool}``
- ``{"op": "stats"}`` — the frontend ``stats()`` doc (the shard-health
  input) → ``{"op": "stats", "stats": {...}}``
- ``{"op": "reqtrace"}`` — this shard's request-lifecycle ring as
  plain rows → ``{"op": "reqtrace", "events": [[t, rid, kind,
  fields], ...]}`` (wall-clock stamps — the parent concatenates the
  shards' rows straight into one merged timeline)
- ``{"op": "exit"}`` → ``{"op": "bye"}`` and a clean close.

The workload kernel is loadgen's ``lg_inc`` (+1.0f per request):
small-integer f32 math is exact, so lost or double-applied requests
are integer-visible in the array.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SRC = """
__kernel void lg_inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


def main(member: str, n: int, local_range: int,
         max_queue_depth: int = 0,
         gather_window_ms: float = 4.0) -> None:
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.errors import CekirdeklerError
    from cekirdekler_tpu.hardware import all_devices
    from cekirdekler_tpu.serve import (
        AdmissionController,
        ServeFrontend,
        ServeJob,
        ServeRejected,
    )

    devs = all_devices().cpus()
    devs = devs.subset(min(2, len(devs)) or 1)
    cr = NumberCruncher(devs, SRC)
    admission = None
    if max_queue_depth > 0:
        admission = AdmissionController(
            max_queue_depth=max_queue_depth,
            health=cr.cores.drain.healthy_with_drains)
    fe = ServeFrontend(cr, admission=admission, max_batch=512,
                       gather_window_s=gather_window_ms / 1000.0,
                       name=f"fabric-{member}")
    arrays: dict = {}
    jobs: dict = {}

    def job_for(si: int):
        if si not in jobs:
            a = ClArray(np.zeros(n, np.float32), name=f"w{member}_{si}")
            a.partial_read = True
            arrays[si] = a
            jobs[si] = ServeJob(
                params=[a], kernels=["lg_inc"], compute_id=9100 + si,
                global_range=n, local_range=local_range)
        return jobs[si]

    def op_serve(cmd: dict) -> dict:
        completed: dict = {}
        latencies: list = []
        rejected = [0]
        failed = [0]
        hangs = [0]
        unnamed = [0]
        causes: dict = {}
        mu = threading.Lock()
        # build jobs up front: array construction must not ride the
        # timed section
        for tenant, si, n_clients, requests in cmd["assignments"]:
            job_for(int(si))

        def client(tenant: str, si: int, requests: int):
            job = jobs[si]
            for _ in range(int(requests)):
                fut = None
                for _attempt in range(50):
                    try:
                        fut = fe.submit(tenant, job)
                        break
                    except ServeRejected as e:
                        with mu:
                            rejected[0] += 1
                        time.sleep(min(e.retry_after_s, 0.25))
                if fut is None:
                    continue
                try:
                    r = fut.result(timeout=60.0)
                except Exception as e:  # noqa: BLE001 - counted below
                    with mu:
                        if isinstance(e, TimeoutError) or \
                                type(e).__name__ == "TimeoutError":
                            hangs[0] += 1
                        else:
                            failed[0] += 1
                            cause = type(e).__name__
                            causes[cause] = causes.get(cause, 0) + 1
                            if not isinstance(e, CekirdeklerError):
                                unnamed[0] += 1
                    continue
                with mu:
                    latencies.append(r["latency_s"])
                    completed[si] = completed.get(si, 0) + 1

        threads = []
        for tenant, si, n_clients, requests in cmd["assignments"]:
            for _ in range(int(n_clients)):
                threads.append(threading.Thread(
                    target=client, args=(str(tenant), int(si),
                                         int(requests)), daemon=True))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        wall = time.perf_counter() - t0
        checked = all(
            bool(np.all(np.asarray(arrays[si]) == float(cnt)))
            for si, cnt in completed.items()
        )
        return {
            "op": "done", "member": member,
            "completed": sum(completed.values()),
            "per_sig": {str(k): v for k, v in sorted(completed.items())},
            "latencies_ms": [round(v * 1000.0, 3) for v in latencies],
            "wall_s": round(wall, 4),
            "hangs": hangs[0], "failed": failed[0],
            "unnamed_failures": unnamed[0],
            "failure_causes": dict(sorted(causes.items())),
            "rejected": rejected[0],
            "checked": checked,
        }

    def op_run(cmd: dict) -> dict:
        si = int(cmd["sig"])
        job = job_for(si)
        tenant = str(cmd.get("tenant", "t0"))
        trace_rid = cmd.get("trace_rid")
        done = 0
        for _ in range(int(cmd.get("iters", 1))):
            fe.call(tenant, job, timeout=60.0, rid=trace_rid)
            done += 1
        return {"op": "done", "rid": cmd.get("rid"), "sig": si,
                "count": done}

    def op_reqtrace(cmd: dict) -> dict:
        from cekirdekler_tpu.obs.reqtrace import REQTRACE

        return {"op": "reqtrace", "events": [
            [e.t, e.rid, e.kind, e.fields] for e in REQTRACE.snapshot()]}

    def op_value(cmd: dict) -> dict:
        si = int(cmd["sig"])
        a = np.asarray(arrays[si]) if si in arrays else np.zeros(1)
        return {"op": "value", "sig": si, "value": float(a[0]),
                "uniform": bool(np.all(a == a[0]))}

    print(f"FABRIC_READY member={member}", flush=True)
    try:
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            cmd = json.loads(line)
            op = cmd.get("op")
            if op == "warm":
                # scratch params: warmup EXECUTES its jobs (the warm
                # iteration mutates), so never warm the real arrays —
                # the shape-only executable cache makes the real jobs
                # compile hits anyway
                scratch = []
                for si in cmd["sigs"]:
                    a = ClArray(np.zeros(n, np.float32),
                                name=f"scratch{si}")
                    a.partial_read = True
                    scratch.append(ServeJob(
                        params=[a], kernels=["lg_inc"],
                        compute_id=9100 + int(si), global_range=n,
                        local_range=local_range))
                got = fe.warmup(scratch)
                reply = {"op": "warmed", "warmed": got["warmed"]}
            elif op == "serve":
                reply = op_serve(cmd)
            elif op == "run":
                reply = op_run(cmd)
            elif op == "value":
                reply = op_value(cmd)
            elif op == "reqtrace":
                reply = op_reqtrace(cmd)
            elif op == "stats":
                reply = {"op": "stats", "stats": {
                    k: v for k, v in fe.stats().items()
                    if k in ("queue_depth", "dispatcher_alive",
                             "requests_done", "batches")}}
            elif op == "exit":
                print(json.dumps({"op": "bye"}), flush=True)
                break
            else:
                reply = {"op": "error", "error": f"bad op {op!r}"}
            print(json.dumps(reply), flush=True)
    finally:
        fe.close(drain=False)
        cr.dispose()


if __name__ == "__main__":
    main(
        sys.argv[1] if len(sys.argv) > 1 else "m0",
        int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 13,
        int(sys.argv[3]) if len(sys.argv) > 3 else 64,
        int(sys.argv[4]) if len(sys.argv) > 4 else 0,
        float(sys.argv[5]) if len(sys.argv) > 5 else 4.0,
    )
