"""Kernel language tests: the C-subset → vectorized JAX compiler.

Modeled on the reference's correctness matrix (Tester.cs:6763-7065 runs
{array kinds} × {dtypes} × {devices} × {pipeline} × {kernels} with
element-wise host verification); here we verify the compiler itself against
host numpy references across dtypes, operators, control flow, and builtins.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from cekirdekler_tpu.errors import KernelCompileError, KernelLanguageError
from cekirdekler_tpu.kernel import KernelProgram, extract_kernel_names, kernel, parse_kernels


def run1(src, name, arrays, values=(), n=None, local=16, chunk=None, offset=0):
    """Compile + launch one kernel over the full range; returns list of numpy arrays."""
    n = n if n is not None else len(arrays[0])
    chunk = chunk or n
    prog = KernelProgram(src)
    fn, info = prog.launcher(name, chunk, local, n)
    out = fn(offset, tuple(jnp.asarray(a) for a in arrays), tuple(values))
    return [np.asarray(o) for o in out], info


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def test_extract_kernel_names():
    src = "__kernel void foo(__global float*a){} kernel void bar(__global int*b){}"
    assert extract_kernel_names(src) == ["foo", "bar"]


def test_parse_multiple_kernels():
    ks = parse_kernels(
        "__kernel void a(__global float* x){ x[0] = 1.0f; }\n"
        "__kernel void b(__global float* x){ x[1] = 2.0f; }"
    )
    assert [k.name for k in ks] == ["a", "b"]


def test_parse_params():
    (k,) = parse_kernels(
        "__kernel void f(__global float* a, __global const int* b, float s, int n){}"
    )
    assert [p.name for p in k.params] == ["a", "b", "s", "n"]
    assert [p.is_pointer for p in k.params] == [True, True, False, False]
    assert k.params[0].ctype == "float" and k.params[3].ctype == "int"


def test_parse_errors():
    with pytest.raises(KernelCompileError):
        parse_kernels("__kernel void f(__global float* a){ a[0] = ; }")
    with pytest.raises(KernelCompileError):
        parse_kernels("void notkernel(){}")
    with pytest.raises(KernelLanguageError):
        parse_kernels("__kernel int f(__global float* a){}")
    with pytest.raises(KernelCompileError):
        parse_kernels("")


def test_unsupported_constructs():
    with pytest.raises(KernelLanguageError):
        parse_kernels("__kernel void f(__local float* s){}")
    with pytest.raises(KernelLanguageError):
        parse_kernels("#define F(x) (x)\n__kernel void f(__global float* a){}")


def test_define_substitution():
    src = """
    #define SCALE 3.0f
    #define N2 (SCALE + 1.0f)
    __kernel void f(__global float* a){
        int i = get_global_id(0);
        a[i] = a[i] * SCALE + N2;
    }"""
    (out,), _ = run1(src, "f", [np.ones(32, np.float32)])
    np.testing.assert_allclose(out, 3.0 + 4.0)


# ---------------------------------------------------------------------------
# basic compute + dtypes
# ---------------------------------------------------------------------------

DTYPES = [
    ("float", np.float32),
    ("double", np.float64),
    ("int", np.int32),
    ("uint", np.uint32),
    ("long", np.int64),
    ("uchar", np.uint8),
]


@pytest.mark.parametrize("cname,npdt", DTYPES)
def test_copy_add_matrix(cname, npdt):
    """The reference's core test pattern: c = a + b element-wise per dtype."""
    src = f"""
    __kernel void addk(__global {cname}* a, __global {cname}* b, __global {cname}* c) {{
        int i = get_global_id(0);
        c[i] = a[i] + b[i];
    }}"""
    n = 128
    a = (np.arange(n) % 17).astype(npdt)
    b = (np.arange(n) % 5).astype(npdt)
    (ra, rb, rc), info = run1(src, "addk", [a, b, np.zeros(n, npdt)])
    np.testing.assert_array_equal(rc, a + b)
    assert info.stored_params == ["c"]


def test_value_params_and_mad():
    src = """
    __kernel void saxpy(__global float* x, __global float* y, float alpha, int n) {
        int i = get_global_id(0);
        if (i < n) y[i] = mad(alpha, x[i], y[i]);
    }"""
    n = 64
    x = np.arange(n, dtype=np.float32)
    y = np.ones(n, dtype=np.float32)
    (rx, ry), _ = run1(src, "saxpy", [x, y], values=(2.5, 40))
    exp = y.copy()
    exp[:40] = 2.5 * x[:40] + 1
    np.testing.assert_allclose(ry, exp)


def test_int_division_c_semantics():
    src = """
    __kernel void divk(__global int* a, __global int* b, __global int* q, __global int* r) {
        int i = get_global_id(0);
        q[i] = a[i] / b[i];
        r[i] = a[i] % b[i];
    }"""
    a = np.array([7, -7, 7, -7, 0, 5], np.int32)
    b = np.array([2, 2, -2, -2, 3, 5], np.int32)
    (out, _, q, r), _ = run1(src, "divk", [a, b, np.zeros(6, np.int32), np.zeros(6, np.int32)], local=1)
    # C truncates toward zero
    np.testing.assert_array_equal(q, np.array([3, -3, -3, 3, 0, 1]))
    np.testing.assert_array_equal(r, np.array([1, -1, 1, -1, 0, 0]))


def test_bitwise_and_shifts():
    src = """
    __kernel void bits(__global uint* a, __global uint* out) {
        int i = get_global_id(0);
        out[i] = ((a[i] << 2) | 3u) & 255u ^ 16u;
    }"""
    a = np.arange(64, dtype=np.uint32)
    (_, out), _ = run1(src, "bits", [a, np.zeros(64, np.uint32)])
    np.testing.assert_array_equal(out, (((a << 2) | 3) & 255) ^ 16)


def test_casts():
    src = """
    __kernel void castk(__global float* a, __global int* b) {
        int i = get_global_id(0);
        b[i] = (int)(a[i] * 1.5f);
    }"""
    a = np.array([1.0, -1.0, 2.5, -2.5], np.float32)
    (_, b), _ = run1(src, "castk", [a, np.zeros(4, np.int32)], local=1)
    np.testing.assert_array_equal(b, np.array([1, -1, 3, -3]))  # trunc toward zero


def test_ternary_and_comparison():
    src = """
    __kernel void t(__global float* a, __global float* out) {
        int i = get_global_id(0);
        out[i] = a[i] > 0.0f ? a[i] : -2.0f * a[i];
    }"""
    a = np.linspace(-4, 4, 32).astype(np.float32)
    (_, out), _ = run1(src, "t", [a, np.zeros(32, np.float32)])
    np.testing.assert_allclose(out, np.where(a > 0, a, -2 * a), rtol=1e-6)


# ---------------------------------------------------------------------------
# control flow
# ---------------------------------------------------------------------------


def test_if_else_chain():
    src = """
    __kernel void f(__global int* a, __global int* out) {
        int i = get_global_id(0);
        if (a[i] < 10) { out[i] = 1; }
        else if (a[i] < 20) { out[i] = 2; }
        else { out[i] = 3; }
    }"""
    a = np.arange(30, dtype=np.int32)
    (_, out), _ = run1(src, "f", [a, np.zeros(30, np.int32)], local=1)
    np.testing.assert_array_equal(out, np.where(a < 10, 1, np.where(a < 20, 2, 3)))


def test_early_return_guard():
    src = """
    __kernel void f(__global float* a, int n) {
        int i = get_global_id(0);
        if (i >= n) return;
        a[i] = 7.0f;
    }"""
    (out,), _ = run1(src, "f", [np.zeros(64, np.float32)], values=(40,))
    assert np.all(out[:40] == 7) and np.all(out[40:] == 0)


def test_nested_if_masked_store():
    src = """
    __kernel void f(__global int* a) {
        int i = get_global_id(0);
        if (i % 2 == 0) {
            if (i % 4 == 0) { a[i] = 4; } else { a[i] = 2; }
        }
    }"""
    (out,), _ = run1(src, "f", [np.full(32, -1, np.int32)])
    exp = np.full(32, -1)
    exp[::2] = 2
    exp[::4] = 4
    np.testing.assert_array_equal(out, exp)


def test_for_loop_accumulate():
    src = """
    __kernel void f(__global float* x, __global float* out, int reps) {
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int j = 0; j < reps; j++) {
            acc += x[i] * (float)j;
        }
        out[i] = acc;
    }"""
    x = np.arange(16, dtype=np.float32)
    (_, out), _ = run1(src, "f", [x, np.zeros(16, np.float32)], values=(10,))
    np.testing.assert_allclose(out, x * 45.0)


def test_data_dependent_while():
    """Collatz-ish per-item trip counts — the mandelbrot pattern."""
    src = """
    __kernel void collatz(__global int* seed, __global int* steps) {
        int i = get_global_id(0);
        int x = seed[i];
        int s = 0;
        while (x != 1 && s < 1000) {
            if (x % 2 == 0) { x = x / 2; } else { x = 3 * x + 1; }
            s++;
        }
        steps[i] = s;
    }"""
    seed = np.arange(1, 65, dtype=np.int32)

    def host(v):
        s = 0
        while v != 1 and s < 1000:
            v = v // 2 if v % 2 == 0 else 3 * v + 1
            s += 1
        return s

    (_, steps), _ = run1(src, "collatz", [seed, np.zeros(64, np.int32)])
    np.testing.assert_array_equal(steps, [host(int(v)) for v in seed])


def test_nested_loops():
    src = """
    __kernel void f(__global float* out, int n) {
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int a = 0; a < n; a++) {
            for (int b = 0; b < a; b++) {
                acc += 1.0f;
            }
        }
        out[i] = acc;
    }"""
    (out,), _ = run1(src, "f", [np.zeros(8, np.float32)], values=(5,))
    np.testing.assert_allclose(out, 10.0)  # sum_{a<5} a = 10


def test_mandelbrot_exact_vs_host():
    src = """
    __kernel void mandel(__global float* out, int width, int maxIter) {
        int i = get_global_id(0);
        float cx = ((float)(i % width)) / ((float)width) * 3.0f - 2.0f;
        float cy = ((float)(i / width)) / ((float)width) * 3.0f - 1.5f;
        float zx = 0.0f; float zy = 0.0f;
        int it = 0;
        while (zx*zx + zy*zy < 4.0f && it < maxIter) {
            float t = zx*zx - zy*zy + cx;
            zy = 2.0f*zx*zy + cy;
            zx = t;
            it++;
        }
        out[i] = (float)it;
    }"""
    W, H, MAXIT = 32, 32, 40
    (out,), _ = run1(src, "mandel", [np.zeros(W * H, np.float32)], values=(W, MAXIT), local=32)

    exp = np.zeros(W * H, np.float32)
    for i in range(W * H):
        cx = (i % W) / W * 3.0 - 2.0
        cy = (i // W) / W * 3.0 - 1.5
        zx = zy = 0.0
        it = 0
        while zx * zx + zy * zy < 4.0 and it < MAXIT:
            zx, zy = np.float32(zx * zx - zy * zy + cx), np.float32(2 * zx * zy + cy)
            it += 1
        exp[i] = it
    np.testing.assert_array_equal(out, exp)


# ---------------------------------------------------------------------------
# indexing patterns
# ---------------------------------------------------------------------------


def test_stencil_shifted_reads():
    src = """
    __kernel void st(__global float* a, __global float* b) {
        int i = get_global_id(0);
        b[i] = a[i-1] + a[i] + a[i+1];
    }"""
    n = 64
    a = np.arange(n, dtype=np.float32)
    (_, b), _ = run1(src, "st", [a, np.zeros(n, np.float32)])
    exp = np.zeros(n)
    # out-of-range shifted reads CLAMP to the nearest element — the same
    # policy as the gather path (kept consistent by the oracle fuzz)
    ap = np.pad(a, 1, mode="edge")
    for i in range(n):
        exp[i] = ap[i] + ap[i + 1] + ap[i + 2]
    np.testing.assert_allclose(b, exp)


def test_chunked_launch_equals_full():
    src = """
    __kernel void st(__global float* a, __global float* b) {
        int i = get_global_id(0);
        b[i] = a[i+1] - a[i];
    }"""
    n = 128
    a = np.cumsum(np.random.RandomState(0).rand(n)).astype(np.float32)
    (_, full), _ = run1(src, "st", [a, np.zeros(n, np.float32)])
    prog = KernelProgram(src)
    fn, _ = prog.launcher("st", 32, 16, n)
    buf = jnp.zeros(n, jnp.float32)
    for off in range(0, n, 32):
        buf = fn(off, (jnp.asarray(a), buf))[1]
    np.testing.assert_allclose(np.asarray(buf), full)


def test_gather_indirect_index():
    src = """
    __kernel void g(__global int* idx, __global float* src, __global float* dst) {
        int i = get_global_id(0);
        dst[i] = src[idx[i]];
    }"""
    n = 32
    rng = np.random.RandomState(1)
    idx = rng.randint(0, n, n).astype(np.int32)
    srcv = rng.rand(n).astype(np.float32)
    (_, _, dst), _ = run1(src, "g", [idx, srcv, np.zeros(n, np.float32)])
    np.testing.assert_allclose(dst, srcv[idx])


def test_strided_access():
    src = """
    __kernel void s(__global float* a, __global float* out) {
        int i = get_global_id(0);
        out[i] = a[2*i];
    }"""
    a = np.arange(64, dtype=np.float32)
    (_, out), _ = run1(src, "s", [a, np.zeros(32, np.float32)], n=32)
    np.testing.assert_allclose(out, a[::2])


def test_elements_per_work_item_pattern():
    """Multi-element work items (reference: numberOfElementsPerWorkItem)."""
    src = """
    __kernel void two(__global float* a, __global float* b) {
        int i = get_global_id(0);
        b[2*i] = a[2*i] * 2.0f;
        b[2*i+1] = a[2*i+1] * 3.0f;
    }"""
    a = np.arange(64, dtype=np.float32)
    (_, b), _ = run1(src, "two", [a, np.zeros(64, np.float32)], n=32)
    exp = a.copy()
    exp[::2] *= 2
    exp[1::2] *= 3
    np.testing.assert_allclose(b, exp)


# ---------------------------------------------------------------------------
# builtins
# ---------------------------------------------------------------------------


def test_math_builtins():
    src = """
    __kernel void m(__global float* x, __global float* out) {
        int i = get_global_id(0);
        out[i] = sqrt(fabs(x[i])) + exp(clamp(x[i], -1.0f, 1.0f)) + fmin(x[i], 0.5f)
               + pow(fabs(x[i]) + 1.0f, 2.0f) + atan2(x[i], 2.0f);
    }"""
    x = np.linspace(-3, 3, 64).astype(np.float32)
    (_, out), _ = run1(src, "m", [x, np.zeros(64, np.float32)])
    exp = (np.sqrt(np.abs(x)) + np.exp(np.clip(x, -1, 1)) + np.minimum(x, 0.5)
           + (np.abs(x) + 1) ** 2 + np.arctan2(x, 2.0))
    np.testing.assert_allclose(out, exp, rtol=1e-5)


def test_workitem_builtins():
    src = """
    __kernel void w(__global int* gid, __global int* lid, __global int* grp, __global int* gsz) {
        int i = get_global_id(0);
        gid[i] = get_global_id(0);
        lid[i] = get_local_id(0);
        grp[i] = get_group_id(0);
        gsz[i] = get_global_size(0);
    }"""
    n, local = 64, 16
    outs, _ = run1(src, "w", [np.zeros(n, np.int32) for _ in range(4)], local=local)
    np.testing.assert_array_equal(outs[0], np.arange(n))
    np.testing.assert_array_equal(outs[1], np.arange(n) % local)
    np.testing.assert_array_equal(outs[2], np.arange(n) // local)
    np.testing.assert_array_equal(outs[3], n)


def test_select_builtin():
    src = """
    __kernel void s(__global float* a, __global float* b, __global float* out) {
        int i = get_global_id(0);
        out[i] = select(a[i], b[i], a[i] < b[i]);
    }"""
    rng = np.random.RandomState(2)
    a, b = rng.rand(32).astype(np.float32), rng.rand(32).astype(np.float32)
    (_, _, out), _ = run1(src, "s", [a, b, np.zeros(32, np.float32)])
    np.testing.assert_allclose(out, np.maximum(a, b))


def test_atomic_rejected():
    src = """
    __kernel void a(__global int* x) {
        atomic_add(x, 1);
    }"""
    prog = KernelProgram(src)
    with pytest.raises(KernelLanguageError, match="atomic"):
        fn, _ = prog.launcher("a", 8, 4, 8)
        fn(0, (jnp.zeros(8, jnp.int32),))


def test_barrier_rejected():
    src = """
    __kernel void b(__global float* x) {
        int i = get_global_id(0);
        barrier(0);
        x[i] = 1.0f;
    }"""
    prog = KernelProgram(src)
    with pytest.raises(KernelLanguageError, match="barrier"):
        fn, _ = prog.launcher("b", 8, 4, 8)
        fn(0, (jnp.zeros(8, jnp.float32),))


# ---------------------------------------------------------------------------
# python-kernel path
# ---------------------------------------------------------------------------


def test_python_kernel():
    @kernel
    def doubler(gid, a, factor=2.0):
        return a.at[gid].multiply(factor)

    prog = KernelProgram(doubler)
    fn, info = prog.launcher("doubler", 16, 4, 16)
    out = fn(0, (jnp.arange(16, dtype=jnp.float32),), (3.0,))
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(16) * 3.0)
    assert info.array_params == ["a"] and info.value_params == ["factor"]


def test_mixed_program():
    @kernel
    def pyk(gid, a):
        return a.at[gid].add(1.0)

    src = "__kernel void ck(__global float* a){ int i = get_global_id(0); a[i] = a[i] * 2.0f; }"
    prog = KernelProgram([src, pyk])
    assert sorted(prog.kernel_names) == ["ck", "pyk"]
    f1, _ = prog.launcher("ck", 8, 4, 8)
    f2, _ = prog.launcher("pyk", 8, 4, 8)
    x = jnp.ones(8, jnp.float32)
    np.testing.assert_allclose(np.asarray(f2(0, (f1(0, (x,))[0],))[0]), 3.0)


def test_freerun_loop_var_read_in_else_branch():
    """Free-run elimination regression: a loop-carried var assigned inside
    a then-branch loop but read in the ELSE branch must stay where-merged —
    else-branch lanes keep their original value."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void k(__global float* c, __global float* out, __global float* xs) {
        int gid = get_global_id(0);
        float x = xs[gid];
        int i = 0;
        if (c[gid] > 0.0f) {
            while (i < 3) {
                x = x + 1.0f;
                i = i + 1;
                out[gid] = x;
            }
        } else {
            out[gid] = x;
        }
    }"""
    cr = NumberCruncher(platforms().cpus().subset(1), src)
    try:
        c = ClArray(np.array([1, -5, 2, -7] * 16, np.float32), name="c")
        xs = ClArray(np.array([1, -5, 2, -7] * 16, np.float32), name="xs")
        out = ClArray(64, np.float32, name="out")
        c.next_param(out, xs).compute(cr, 1, "k", 64, 16)
        want = np.where(
            np.array([1, -5, 2, -7] * 16) > 0,
            np.array([1, -5, 2, -7] * 16, np.float32) + 3.0,
            np.array([1, -5, 2, -7] * 16, np.float32),
        )
        np.testing.assert_allclose(np.asarray(out), want)
    finally:
        cr.dispose()


def test_freerun_inner_loop_in_do_while_body():
    """Free-run elimination regression: an inner loop inside a do-while's
    first (unconditional) body pass must NOT free-run — the body re-runs
    and reads the variable at its top."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void k(__global float* out) {
        int gid = get_global_id(0);
        float x = 0.0f;
        int n = 0;
        do {
            out[gid] = x;
            int i = 0;
            while (i < gid) {
                x = x + 1.0f;
                i = i + 1;
            }
            n = n + 1;
        } while (n < 2);
    }"""
    cr = NumberCruncher(platforms().cpus().subset(1), src)
    try:
        out = ClArray(4, np.float32, name="out")
        out.compute(cr, 1, "k", 4, 2)
        # second body pass records x after ONE inner-loop run: x = gid
        np.testing.assert_allclose(np.asarray(out), [0.0, 1.0, 2.0, 3.0])
    finally:
        cr.dispose()


def test_private_array_polynomial():
    """Private fixed-size arrays (``float c[4];``): constant-index stores,
    loop-variable gathers, and loop carry — evaluated against numpy."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void poly(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float c[4];
        c[0] = 1.0f;
        c[1] = 2.0f;
        c[2] = 3.0f;
        c[3] = 4.0f;
        float acc = 0.0f;
        float p = 1.0f;
        for (int j = 0; j < 4; j++) {
            acc = acc + c[j] * p;
            p = p * x[i];
        }
        out[i] = acc;
    }"""
    cr = NumberCruncher(platforms().cpus().subset(2), src)
    try:
        xs = np.linspace(-1, 1, 256).astype(np.float32)
        x = ClArray(xs.copy(), name="x", partial_read=True)
        out = ClArray(256, np.float32, name="out")
        x.next_param(out).compute(cr, 1, "poly", 256, 64)
        want = 1 + 2 * xs + 3 * xs**2 + 4 * xs**3
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)
    finally:
        cr.dispose()


def test_private_array_dynamic_store_per_lane():
    """Per-lane dynamic element stores: each work item writes its own
    bucket of a private array, then reads it back."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void buck(__global int* sel, __global float* out) {
        int i = get_global_id(0);
        float slots[4];
        int b = sel[i];
        slots[b] = 10.0f + (float)b;
        out[i] = slots[b] + slots[0];
    }"""
    cr = NumberCruncher(platforms().cpus().subset(2), src)
    try:
        sel_np = (np.arange(128) % 4).astype(np.int32)
        sel = ClArray(sel_np.copy(), name="sel", partial_read=True)
        out = ClArray(128, np.float32, name="out")
        sel.next_param(out).compute(cr, 1, "buck", 128, 64)
        slots0 = np.where(sel_np == 0, 10.0, 0.0)
        want = (10.0 + sel_np) + slots0
        np.testing.assert_allclose(np.asarray(out), want)
    finally:
        cr.dispose()


def test_private_array_in_masked_branch():
    """Element stores under an if-mask only land for active lanes."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void mk(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float t[2];
        t[0] = -1.0f;
        if (x[i] > 0.0f) {
            t[0] = x[i];
        }
        out[i] = t[0];
    }"""
    cr = NumberCruncher(platforms().cpus().subset(1), src)
    try:
        xs = np.array([-2.0, 3.0, -0.5, 7.0] * 16, np.float32)
        x = ClArray(xs.copy(), name="x")
        out = ClArray(64, np.float32, name="out")
        x.next_param(out).compute(cr, 1, "mk", 64, 16)
        np.testing.assert_allclose(np.asarray(out), np.where(xs > 0, xs, -1.0))
    finally:
        cr.dispose()


def test_private_array_rejected_by_pallas_subset():
    from cekirdekler_tpu.kernel import lang
    from cekirdekler_tpu.kernel.pallas_backend import (
        PallasUnsupported,
        build_kernel_fn_pallas,
    )
    import pytest as _pytest

    src = """
    __kernel void p(__global float* o) {
        int i = get_global_id(0);
        float t[2];
        t[0] = 1.0f;
        o[i] = t[0];
    }"""
    kdef = lang.parse_kernels(src)[0]
    with _pytest.raises(PallasUnsupported):
        build_kernel_fn_pallas(kdef, 256, 64, 256, interpret=True)


def test_private_array_whole_use_rejected():
    """Using a private array without an index — read or whole-assignment —
    is a language error, not silent stack corruption."""
    import numpy as np
    import pytest as _pytest

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.errors import KernelLanguageError
    from cekirdekler_tpu.hardware import platforms

    for body in ("t = 5.0f;", "out[i] = t;"):
        src = f"""
        __kernel void k(__global float* out) {{
            int i = get_global_id(0);
            float t[2];
            t[0] = 1.0f;
            {body}
            out[i] = t[0];
        }}"""
        cr = NumberCruncher(platforms().cpus().subset(1), src)
        try:
            out = ClArray(64, np.float32, name="out")
            with _pytest.raises(KernelLanguageError):
                out.compute(cr, 1, "k", 64, 16)
            cr.reset_errors()
        finally:
            cr.dispose()


def test_private_array_loop_local_scopes_out():
    """A loop-local private array must not shadow a same-named buffer
    parameter after the loop ends."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void k(__global float* t, __global float* out) {
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int j = 0; j < 2; j++) {
            float t[2];
            t[0] = (float)j;
            acc = acc + t[0];
        }
        out[i] = acc + t[i];
    }"""
    cr = NumberCruncher(platforms().cpus().subset(1), src)
    try:
        t = ClArray(np.full(64, 10.0, np.float32), name="t")
        out = ClArray(64, np.float32, name="out")
        t.next_param(out).compute(cr, 1, "k", 64, 16)
        np.testing.assert_allclose(np.asarray(out), 1.0 + 10.0)
    finally:
        cr.dispose()


def test_uniform_analysis_disabled_by_early_return():
    """Regression (confirmed miscompilation): a lane-divergent early
    return suppresses later assignments per-lane, so a variable assigned
    after it is NOT uniform — any `return` disables scalarized loads."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void k(__global float* x, __global float* y) {
        int i = get_global_id(0);
        int j = 0;
        if (i == 0) {
            return;
        }
        j = 1;
        y[i] = x[j];
    }"""
    cr = NumberCruncher(platforms().cpus().subset(1), src)
    try:
        x = ClArray(np.array([10.0, 20.0, 30.0, 40.0], np.float32), name="x")
        y = ClArray(np.zeros(4, np.float32), name="y")
        x.next_param(y).compute(cr, 1, "k", 4, 2)
        np.testing.assert_allclose(np.asarray(y), [0.0, 20.0, 20.0, 20.0])
    finally:
        cr.dispose()


def test_uniform_scalarized_gather_loop_matches():
    """The n-body pattern: a gather loop with a uniform counter must
    scalarize and still match the per-lane reference."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void dotrow(__global float* w, __global float* x, __global float* out,
                         int n) {
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int j = 0; j < n; j++) {
            acc = acc + w[j] * x[i];
        }
        out[i] = acc;
    }"""
    cr = NumberCruncher(platforms().cpus().subset(2), src)
    try:
        rng = np.random.default_rng(3)
        # w sized to the global range (validation requires it); only the
        # first 16 entries participate in the loop
        w = ClArray(rng.standard_normal(128).astype(np.float32), name="w")
        x = ClArray(rng.standard_normal(128).astype(np.float32), name="x", partial_read=True)
        out = ClArray(128, np.float32, name="out")
        w.next_param(x, out).compute(cr, 1, "dotrow", 128, 64, values=(16,))
        want = np.float32(w.host()[:16].sum()) * x.host()
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)
    finally:
        cr.dispose()


def test_break_outside_loop_is_parse_error():
    with pytest.raises(KernelLanguageError):
        parse_kernels("__kernel void f(__global float* a){ break; }")
    with pytest.raises(KernelLanguageError):
        parse_kernels(
            "__kernel void f(__global float* a){ if (a[0] > 0.0f) { continue; } }"
        )
