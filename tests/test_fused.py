"""Fused-iteration dispatch path (the enqueue dispatch-floor collapse):
deferral correctness, bit-identity with per-iteration dispatch, the
executable-cache invariant across balancer re-partitioning, named
disengage reasons, and the window-scoped coverage-epoch fix for the r7
KNOWN LIMIT (multi-threaded enqueue windows + sync-point rebalance).

The inc kernel adds exactly 1.0f — small-integer f32 arithmetic is exact,
so every lost/duplicated iteration (and every lost REGION update across a
range move) shows as an integer-sized error and the assertions can demand
bit equality.  Value-varying math is covered by the mandelbrot and n-body
bit-identity tests, which compare the fused path against the per-iteration
path rather than against a host emulation."""

import threading
import time

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.hardware import platforms

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
__kernel void dbl(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] * 1.001f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def laggy(orig, secs=0.2):
    def f():
        time.sleep(secs)
        orig()

    return f


# ---------------------------------------------------------------------------
# deferral + correctness
# ---------------------------------------------------------------------------

def test_fused_window_defers_and_is_exact(devs):
    """An enqueue window repeating one cid defers everything after the
    first call and still produces exactly the per-iteration result."""
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    iters = 12
    for _ in range(iters):
        x.compute(cr, 1, "inc", 1024, 64)
    # call 1 seeds the candidate, call 2 engages, calls 3..N defer
    assert cr.fused_stats["deferred_iters"] == iters - 2, cr.fused_stats
    # host untouched while deferred (enqueue semantics hold)
    assert np.all(np.asarray(x) == 0.0)
    cr.enqueue_mode = False  # flush dispatches the residue
    assert cr.fused_stats["fused_iters"] == iters - 2
    np.testing.assert_array_equal(np.asarray(x), float(iters))
    cr.dispose()


def test_fused_batches_dispatch_eagerly(devs):
    """Deferral dispatches every fused_batch iterations (device starts
    working mid-window), not only at the barrier."""
    cr = NumberCruncher(devs.subset(2), INC)
    cr.fused_batch = 4
    x = ClArray(np.zeros(512, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    # call 1 seeds, call 2 engages, calls 3..11 defer -> 2 eager batches
    # of 4 mid-window, residue 1 at the barrier
    for _ in range(11):
        x.compute(cr, 1, "inc", 512, 64)
    assert cr.fused_stats["windows"] == 2
    assert cr.fused_stats["fused_iters"] == 8
    cr.barrier()  # residue (1) dispatches at the window close
    assert cr.fused_stats["fused_iters"] == 9
    cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), 11.0)
    cr.dispose()


def test_fused_is_one_dispatch_per_batch(devs):
    """Marker accounting: a 32-iteration window costs O(1) dispatches,
    not O(iterations) — the dispatch-floor collapse made observable
    (same methodology as test_repeat_is_one_fused_dispatch)."""
    cr = NumberCruncher(devs.subset(1), INC)
    cr.fine_grained_queue_control = True
    cr.fused_batch = 32
    x = ClArray(np.zeros(256, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    for _ in range(32):
        x.compute(cr, 1, "inc", 256, 64)
    cr.enqueue_mode = False
    w = cr.cores.workers[0]
    # 1 upload + 1 per-call launch + 1 fused ladder + 1 download = 4
    assert w.markers.added <= 5, w.markers.added
    np.testing.assert_array_equal(np.asarray(x), 32.0)
    cr.dispose()


def test_fused_bit_identical_mandelbrot_image(devs):
    """The acceptance gate: the fused path's mandelbrot image is
    BIT-identical to the per-iteration path's."""
    from cekirdekler_tpu.workloads import MANDELBROT_SRC

    w = h = 256
    n = w * h
    vals = (-2.0, -1.25, 2.5 / w, 2.5 / h, w, 64)
    images = {}
    for fused in (False, True):
        cr = NumberCruncher(devs.subset(2), MANDELBROT_SRC)
        cr.fused_dispatch = fused
        out = ClArray(n, np.float32, name=f"m{fused}", read=False, write=True)
        cr.enqueue_mode = True
        for _ in range(5):
            out.compute(cr, 31, "mandelbrot", n, 256, values=vals)
        cr.enqueue_mode = False
        if fused:
            assert cr.fused_stats["fused_iters"] > 0
        else:
            assert cr.fused_stats["fused_iters"] == 0
        images[fused] = np.asarray(out).copy()
        cr.dispose()
    np.testing.assert_array_equal(images[True], images[False])


def test_fused_bit_identical_accumulating_nbody(devs):
    """Accumulating state (the n-body velocity integral): K fused
    iterations equal K per-iteration dispatches bit-for-bit."""
    from cekirdekler_tpu.workloads import NBODY_SRC, _nbody_rig

    n, iters = 512, 8
    results = {}
    for fused in (False, True):
        _, (x, y, z), vel = _nbody_rig(n, f"f{int(fused)}")
        cr = NumberCruncher(devs.subset(2), NBODY_SRC)
        cr.fused_dispatch = fused
        g = x.next_param(y, z, *vel)
        cr.enqueue_mode = True
        for _ in range(iters):
            g.compute(cr, 32, "nBody", n, 64, values=(n, 1e-4))
        cr.enqueue_mode = False
        results[fused] = [np.asarray(v).copy() for v in vel]
        cr.dispose()
    for a, b in zip(results[True], results[False]):
        np.testing.assert_array_equal(a, b)


def test_fused_mixed_cids_and_fence_split(devs):
    """Alternating cids breaks fusion per switch (signature-change) but
    stays exact; fence_split's per-cid completion probes survive the
    fused launches (donation is disabled while probes are pinned)."""
    cr = NumberCruncher(devs.subset(2), INC)
    cr.fence_split = True
    x = ClArray(np.zeros(512, np.float32), name="x")
    x.partial_read = True
    y = ClArray(np.ones(512, np.float32), name="y")
    y.partial_read = True
    cr.enqueue_mode = True
    for _ in range(3):
        for _ in range(4):
            x.compute(cr, 41, "inc", 512, 64)
        for _ in range(4):
            y.compute(cr, 42, "dbl", 512, 64)
    cr.barrier()
    cr.enqueue_mode = False
    dis = cr.fused_stats["disengaged"]
    assert dis.get("signature-change", 0) >= 5, dis
    assert cr.fused_stats["fused_iters"] > 0
    np.testing.assert_array_equal(np.asarray(x), 12.0)
    np.testing.assert_allclose(
        np.asarray(y), np.float32(1.001) ** 12, rtol=1e-5
    )
    cr.dispose()


# ---------------------------------------------------------------------------
# executable-cache keying (satellite: compile-count invariant)
# ---------------------------------------------------------------------------

def test_fused_executable_cache_survives_rebalance(devs):
    """Compile count stays FLAT across a forced rebalance (range shift,
    unchanged shapes) and the fused executable count increments exactly
    once on a genuine shape change — the executable-cache keying
    contract (offset/units/iteration-count are runtime arguments of one
    cached ladder)."""
    cr = NumberCruncher(devs.subset(2), INC)
    prog = cr.cores.program
    x = ClArray(np.zeros(4096, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    slow = cr.cores.workers[0]
    orig_fence = slow.fence
    total = 0
    try:
        for _ in range(3):
            x.compute(cr, 51, "inc", 4096, 64)
            total += 1
        cr.barrier()
        warm_fused = prog.fused_compiled_count
        warm_total = prog.compiled_count
        assert warm_fused == 1
        # force a genuine range shift: the slow chip must lose share
        slow.fence = laggy(orig_fence)
        for _ in range(3):
            x.compute(cr, 51, "inc", 4096, 64)
            total += 1
        cr.barrier()
        slow.fence = orig_fence
        before_move = cr.ranges_of(51)
        for _ in range(3):  # first call rebalances (armed), then re-fuses
            x.compute(cr, 51, "inc", 4096, 64)
            total += 1
        cr.barrier()
        moved = cr.ranges_of(51)
        assert moved != before_move, (before_move, moved)
        # the invariant: re-partitioning hit the cache, no recompile —
        # neither a new fused ladder nor any new per-chunk geometry
        assert prog.fused_compiled_count == warm_fused
        assert prog.compiled_count == warm_total
        # a genuine shape change compiles exactly one new fused ladder
        y = ClArray(np.zeros(8192, np.float32), name="y")
        y.partial_read = True
        for _ in range(3):
            y.compute(cr, 52, "inc", 8192, 64)
        cr.barrier()  # fused build happens at the window dispatch
        assert prog.fused_compiled_count == warm_fused + 1
    finally:
        slow.fence = orig_fence
        cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), float(total))
    cr.dispose()


# ---------------------------------------------------------------------------
# named disengage reasons (satellite: no silent fallback)
# ---------------------------------------------------------------------------

def _tracer_disengages():
    from cekirdekler_tpu.trace.spans import TRACER

    return [
        s.tag for s in TRACER.snapshot()
        if s.kind == "fused" and (s.tag or "").startswith("disengage:")
    ]


def test_disengage_range_change_is_named(devs):
    """An armed rebalance (range change at the window boundary) breaks
    the fused run with reason "range-change" — and emits a trace
    instant."""
    from cekirdekler_tpu.trace.spans import TRACER

    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(4096, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    slow = cr.cores.workers[0]
    orig = slow.fence
    slow.fence = laggy(orig)
    TRACER.enable(clear=True)
    try:
        for _ in range(3):
            x.compute(cr, 61, "inc", 4096, 64)
        cr.barrier()  # arms the rebalance
        slow.fence = orig
        # sig from the new window's first call matches nothing (window
        # closed at the barrier), so re-engage, then defer, then break on
        # the SECOND window boundary?  No: the armed flag is consumed by
        # the first call after the barrier — which therefore cannot have
        # an active fused sig.  Drive one engage + one armed break:
        x.compute(cr, 61, "inc", 4096, 64)  # armed rebalance consumed here
        x.compute(cr, 61, "inc", 4096, 64)  # defers
        cr.cores._enqueue_rebalance.add(61)  # re-arm mid-window (as a
        # concurrent thread's barrier would)
        x.compute(cr, 61, "inc", 4096, 64)  # breaks: range-change
        assert cr.fused_stats["disengaged"].get("range-change", 0) == 1
        assert any("range-change" in t for t in _tracer_disengages())
    finally:
        TRACER.disable()
        slow.fence = orig
        cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), 6.0)
    cr.dispose()


def test_disengage_non_resident_is_named(devs):
    """A coverage-epoch bump mid-window (what every reset_coverage()
    does) disengages with reason "non-resident" and results stay exact."""
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    for _ in range(3):
        x.compute(cr, 62, "inc", 1024, 64)
    assert cr.cores._fused_sig is not None
    for w in cr.cores.workers:
        w.coverage_epoch += 1  # the observable effect of reset_coverage()
    x.compute(cr, 62, "inc", 1024, 64)
    assert cr.fused_stats["disengaged"].get("non-resident", 0) == 1
    cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), 4.0)
    cr.dispose()


def test_disengage_pipeline_and_repeat_are_named(devs):
    """Pipelined enqueue calls and repeat-mode calls refuse fusion with
    their own reasons (each already fuses internally or blobs)."""
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(2048, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    x.compute(cr, 63, "inc", 2048, 64)  # seeds
    x.compute(cr, 63, "inc", 2048, 64)  # engages
    x.compute(cr, 63, "inc", 2048, 64, pipeline=True, pipeline_blobs=4)
    assert cr.fused_stats["disengaged"].get("pipeline", 0) >= 1
    cr.repeat_count = 3
    x.compute(cr, 63, "inc", 2048, 64)  # refused while repeat-mode is on
    assert cr.fused_stats["disengaged"].get("repeat-mode", 0) >= 1
    cr.repeat_count = 1
    cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), 6.0)
    cr.dispose()


def test_disengage_mode_change_mid_window(devs):
    """Runtime mode toggles are NOT in the window signature — flipping
    one mid-window must break the run ("mode-change"), not defer a call
    whose semantics changed.  repeat_count=3 mid-window must apply 3
    on-device repeats (deferred, it would count as ONE); no_compute_mode
    mid-window must skip compute entirely; a dispatch_gate must hold."""
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(512, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    x.compute(cr, 66, "inc", 512, 64)  # engages
    x.compute(cr, 66, "inc", 512, 64)  # defers
    cr.repeat_count = 3
    x.compute(cr, 66, "inc", 512, 64)  # 3 repeats, must NOT defer as 1
    assert cr.fused_stats["disengaged"].get("mode-change", 0) == 1
    cr.repeat_count = 1
    x.compute(cr, 66, "inc", 512, 64)  # re-engages
    x.compute(cr, 66, "inc", 512, 64)  # defers
    cr.no_compute_mode = True
    x.compute(cr, 66, "inc", 512, 64)  # I/O only, must NOT defer
    assert cr.fused_stats["disengaged"].get("mode-change", 0) == 2
    cr.no_compute_mode = False
    cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), 7.0)  # 1+1+3+1+1+0
    cr.dispose()


def test_disengage_partial_upload_guard(devs):
    """The engage-time coverage guard: a read param whose chip range is
    not fully covered refuses engagement with reason "partial-upload"
    (unit-level: the builtin upload path leaves ranges covered, so the
    refusal is rigged via a shrunk coverage record)."""
    cr = NumberCruncher(devs.subset(2), INC)
    cores = cr.cores
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    x.compute(cr, 64, "inc", 1024, 64)  # seeds the candidate
    x.compute(cr, 64, "inc", 1024, 64)  # consecutive repeat -> engages
    assert cores._fused_sig is not None
    cores._fused_close()
    w = cores.workers[0]
    with w.lock:
        off, _ = w._uploaded[id(x)]
        w._uploaded[id(x)] = (off, 1)
    cores._fused_try_engage(
        ["inc"], [x], 64, 1024, 64, 0, (),
        cores.global_ranges[64], cores.global_references[64], 64,
    )
    assert cores._fused_sig is None
    assert cr.fused_stats["disengaged"].get("partial-upload", 0) == 1
    cr.enqueue_mode = False
    cr.dispose()


def test_disengage_unhashable_values(devs):
    """Unhashable value args cannot bake into the fused executable —
    refusal reason "unhashable-values", per-iteration results exact."""
    src = """
    __kernel void axb(__global float* x, float aa) {
        int i = get_global_id(0);
        x[i] = x[i] + aa;
    }"""
    cr = NumberCruncher(devs.subset(2), src)
    x = ClArray(np.zeros(256, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True

    class UnhashableFloat(float):
        __hash__ = None

    for _ in range(3):
        x.compute(cr, 65, "axb", 256, 64, values=(UnhashableFloat(2.0),))
    assert cr.fused_stats["disengaged"].get("unhashable-values", 0) >= 1
    assert cr.fused_stats["fused_iters"] == 0
    cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(x), 6.0)
    cr.dispose()


# ---------------------------------------------------------------------------
# the r7 KNOWN LIMIT: multi-threaded windows + sync-point rebalance
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fused", [False, True])
def test_threaded_enqueue_windows_no_lost_updates(devs, fused):
    """Regression for the KNOWN LIMIT the r7 trace hammer surfaced (lost
    updates, 10-12/12 arrays at seed): one thread drives barriers + armed
    rebalances (its chip share forced to oscillate) while another thread
    enqueues a different cid through the same Cores.  The armed
    rebalance's flush+reset must be atomic against the other thread's
    in-flight window — exact final values on BOTH arrays, with the fused
    path on and off (off reproduces the seed code shape)."""
    cr = NumberCruncher(devs.subset(2), INC)
    cr.fused_dispatch = fused
    n = 4096
    x = ClArray(np.zeros(n, np.float32), name="x")  # thread B's array
    x.partial_read = True
    y = ClArray(np.zeros(n, np.float32), name="y")  # thread A's array
    y.partial_read = True
    cr.enqueue_mode = True
    w0, w1 = cr.cores.workers
    f0, f1 = w0.fence, w1.fence
    phases = 6
    per_phase_a = 2
    errors: list = []
    b_iters = 0
    stop = threading.Event()

    def thread_a():
        # alternate which chip lags so the armed rebalance MOVES ranges
        # (flush+reset fires on thread A's next compute each phase)
        try:
            for ph in range(phases):
                slow, orig = (w0, f0) if ph % 2 == 0 else (w1, f1)
                slow.fence = laggy(orig, 0.15)
                for _ in range(per_phase_a):
                    y.compute(cr, 71, "inc", n, 64)
                cr.barrier()
                w0.fence, w1.fence = f0, f1
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)
        finally:
            w0.fence, w1.fence = f0, f1
            stop.set()

    def thread_b():
        nonlocal b_iters
        try:
            while not stop.is_set() and b_iters < 400:
                x.compute(cr, 72, "inc", n, 64)
                b_iters += 1
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    ta = threading.Thread(target=thread_a)
    tb = threading.Thread(target=thread_b)
    ta.start()
    tb.start()
    ta.join(timeout=120.0)
    tb.join(timeout=120.0)
    assert not errors, errors
    cr.enqueue_mode = False
    # +1.0f on small integers is exact in f32: ANY lost iteration (or a
    # lost region update across a range move) is an integer-sized error
    np.testing.assert_array_equal(np.asarray(x), float(b_iters))
    np.testing.assert_array_equal(np.asarray(y), float(phases * per_phase_a))
    cr.dispose()
