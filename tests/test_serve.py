"""Serving tier (serve/): admission purity + exact quota accounting,
coalescing plans (EDF + starvation fairness), the externally-assembled
fused-batch entry, 32-thread mixed-signature contention coalescing into
fewer ladder launches than requests, decision replay, and /servez.

The inc kernel adds exactly 1.0f — small-integer f32 arithmetic is
exact, so every lost or duplicated request shows as an integer-sized
error and the assertions demand bit equality (the test_fused.py
discipline, applied to the serving path)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.errors import ComputeValidationError
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.metrics.registry import REGISTRY
from cekirdekler_tpu.obs.decisions import DECISIONS
from cekirdekler_tpu.obs.replay import replay_record, verify_records
from cekirdekler_tpu.serve import (
    AdmissionController,
    ServeFrontend,
    ServeJob,
    ServeRejected,
    admit_decision,
    plan_coalesce,
    servez_payload,
)
from cekirdekler_tpu.serve.admission import (
    REJECT_HEALTH,
    REJECT_QUEUE,
    REJECT_QUOTA,
)

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
__kernel void dbl(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] * 1.001f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def _mk(devs, n=1024, sigs=1, lanes=2):
    cr = NumberCruncher(devs.subset(lanes), INC)
    arrays = []
    jobs = []
    for s in range(sigs):
        a = ClArray(np.zeros(n, np.float32), name=f"s{s}")
        a.partial_read = True
        arrays.append(a)
        jobs.append(ServeJob(params=[a], kernels=["inc"],
                             compute_id=700 + s, global_range=n,
                             local_range=64))
    return cr, arrays, jobs


# ---------------------------------------------------------------------------
# admission: the pure decision + the controller
# ---------------------------------------------------------------------------

def test_admit_decision_check_order_and_retry_after():
    """health gates first, then queue depth, then quota; retry-after is
    deterministic and scales with the batch-wall estimate."""
    kw = dict(tenant_inflight=0, quota=4, queue_depth=0,
              max_queue_depth=8, healthy=True, est_batch_s=0.02)
    assert admit_decision(**kw) == {
        "admit": True, "reason": None, "retry_after_s": None}
    d = admit_decision(**dict(kw, healthy=False, queue_depth=99,
                              tenant_inflight=99))
    assert d["reason"] == REJECT_HEALTH  # health outranks the others
    assert d["retry_after_s"] == pytest.approx(0.08)
    d = admit_decision(**dict(kw, queue_depth=8, tenant_inflight=99))
    assert d["reason"] == REJECT_QUEUE   # queue outranks quota
    d = admit_decision(**dict(kw, tenant_inflight=4))
    assert d["reason"] == REJECT_QUOTA
    assert d["retry_after_s"] == pytest.approx(0.02)
    # determinism: same inputs, same floats (the replay contract)
    assert admit_decision(**dict(kw, tenant_inflight=4)) == d


def test_admission_controller_records_replayable_decisions():
    ctrl = AdmissionController(max_queue_depth=2, default_quota=1)
    DECISIONS.clear()
    assert ctrl.check("a", 0, 0, 0.01)["admit"] is True
    assert ctrl.check("a", 1, 0, 0.01)["reason"] == REJECT_QUOTA
    assert ctrl.check("b", 0, 5, 0.01)["reason"] == REJECT_QUEUE
    recs = [r for r in DECISIONS.snapshot() if r.kind == "admission"]
    assert len(recs) == 3
    for r in recs:
        rep = replay_record(r)
        assert rep["ok"] is True, rep


def test_admission_health_gate_flips():
    healthy = [False]
    ctrl = AdmissionController(health=lambda: healthy[0], health_ttl_s=0.0)
    assert ctrl.check("a", 0, 0, 0.01)["reason"] == REJECT_HEALTH
    healthy[0] = True
    assert ctrl.check("a", 0, 0, 0.01)["admit"] is True


# ---------------------------------------------------------------------------
# coalescer: the pure plan
# ---------------------------------------------------------------------------

def _group(key, pending=1, deadline=None, age=0.0, starved=0):
    return {"key": key, "pending": pending, "deadline_in_s": deadline,
            "oldest_age_s": age, "starved_rounds": starved}


def test_plan_edf_then_age_then_key():
    plan = plan_coalesce([
        _group("a", age=0.5),
        _group("b", deadline=0.2, age=0.1),
        _group("c", deadline=0.1, age=0.1),
        _group("d", age=0.9),
    ], round_idx=0)
    # deadlined groups first (earliest first), then oldest arrival
    assert plan["order"] == ["c", "b", "d", "a"]
    assert plan["picked"] == plan["order"]  # unbounded cycle picks all
    assert plan["promoted"] == []


def test_plan_fairness_promotion_and_rotation():
    groups = [
        _group("urgent", deadline=0.01),
        _group("x", starved=2),
        _group("y", starved=3),
    ]
    p0 = plan_coalesce(groups, round_idx=0, max_picks=1)
    p1 = plan_coalesce(groups, round_idx=1, max_picks=1)
    # both streak members are promoted AHEAD of the deadlined group,
    # LONGEST-starved first (the ckmodel-checked bound: a whole-list
    # round rotation let arrivals re-aim the anchor past the same
    # member — see serve/coalescer.py MODEL_INVARIANTS); with distinct
    # streaks the head does NOT rotate
    assert p0["promoted"] == ["y", "x"]
    assert p1["promoted"] == ["y", "x"]
    assert p0["order"][-1] == "urgent"
    assert p0["picked"] == [p0["order"][0]]
    # determinism (the replay contract)
    assert plan_coalesce(groups, 0, 1) == p0


def test_plan_equal_streak_ties_share_the_head_by_rotation():
    """Only the leading TIE class rotates with the round count: equal
    suffering shares the head slot; unequal suffering is strictly
    longest-first."""
    groups = [_group("x", starved=2), _group("y", starved=2)]
    p0 = plan_coalesce(groups, round_idx=0, max_picks=1)
    p1 = plan_coalesce(groups, round_idx=1, max_picks=1)
    assert p0["promoted"] == ["x", "y"]
    assert p1["promoted"] == ["y", "x"]
    # a longer-starved member outranks the rotating tie class
    groups.append(_group("z", starved=5))
    for rnd in range(4):
        p = plan_coalesce(groups, round_idx=rnd, max_picks=1)
        assert p["promoted"][0] == "z"
        assert p["picked"] == ["z"]


def test_plan_zero_pending_groups_drop_out():
    plan = plan_coalesce([_group("a", pending=0), _group("b")], 0)
    assert plan["order"] == ["b"]


# ---------------------------------------------------------------------------
# Cores.compute_fused_batch: the externally-assembled batch entry
# ---------------------------------------------------------------------------

def test_compute_fused_batch_exact_and_one_ladder(devs):
    cr, (x,), (job,) = _mk(devs)
    try:
        cr.enqueue_mode = True
        info = cr.cores.compute_fused_batch(
            ["inc"], [x], 700, 1024, 64, 12)
        cr.cores.barrier()
        cr.cores.flush()
        np.testing.assert_array_equal(np.asarray(x), 12.0)
        # first batch: seed + engage per-call, the residue as ONE ladder
        assert info == {"iters": 12, "fused": True, "ladder_iters": 10,
                        "per_call_iters": 2}
        # warm candidate: the next batch pays ONE per-call iteration
        info2 = cr.cores.compute_fused_batch(
            ["inc"], [x], 700, 1024, 64, 12)
        cr.cores.barrier()
        cr.cores.flush()
        np.testing.assert_array_equal(np.asarray(x), 24.0)
        assert info2["per_call_iters"] == 1
        assert info2["ladder_iters"] == 11
    finally:
        cr.dispose()


def test_compute_fused_batch_requires_enqueue_and_falls_back(devs):
    cr, (x,), (job,) = _mk(devs)
    try:
        with pytest.raises(ComputeValidationError):
            cr.cores.compute_fused_batch(["inc"], [x], 700, 1024, 64, 4)
        # fusion off: per-call fallback stays bit-exact
        cr.fused_dispatch = False
        cr.enqueue_mode = True
        info = cr.cores.compute_fused_batch(["inc"], [x], 700, 1024, 64, 5)
        cr.cores.barrier()
        cr.cores.flush()
        assert info["fused"] is False and info["per_call_iters"] == 5
        np.testing.assert_array_equal(np.asarray(x), 5.0)
    finally:
        cr.dispose()


# ---------------------------------------------------------------------------
# frontend: exactness, quotas, contention, replay
# ---------------------------------------------------------------------------

def test_frontend_coalesces_and_resolves_exact(devs):
    cr, (x,), (job,) = _mk(devs)
    fe = ServeFrontend(cr, autostart=False, name="exact")
    try:
        w0 = cr.cores.fused_stats["windows"]
        futs = [fe.submit("tA", job) for _ in range(16)]
        out = fe.step()
        assert out["batches"] == 1 and out["requests"] == 16
        recs = [f.result(timeout=30) for f in futs]
        np.testing.assert_array_equal(np.asarray(x), 16.0)
        assert all(r["batch_requests"] == 16 for r in recs)
        assert cr.cores.fused_stats["windows"] - w0 == 1  # ONE ladder
    finally:
        fe.close()
        cr.dispose()


def test_frontend_quota_rejections_exact_under_contention(devs):
    """32 threads, one tenant, quota 6, dispatcher paused: EXACTLY
    quota admits and the rest reject with retry-after — the admission
    transition is atomic under the frontend lock."""
    cr, (x,), (job,) = _mk(devs)
    fe = ServeFrontend(cr, autostart=False, name="quota")
    fe.admission.set_quota("tQ", 6)
    rejected = []
    futs = []
    mu = threading.Lock()

    def client():
        try:
            f = fe.submit("tQ", job)
            with mu:
                futs.append(f)
        except ServeRejected as e:
            assert e.reason == REJECT_QUOTA
            assert e.retry_after_s > 0
            with mu:
                rejected.append(e)

    threads = [threading.Thread(target=client) for _ in range(32)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(futs) == 6
        assert len(rejected) == 26
        snap = fe.tenants.snapshot()["tQ"]
        assert snap["admitted"] == 6 and snap["rejected"] == 26
        assert REGISTRY.counter(
            "ck_serve_rejected_total", "serve submits rejected",
            tenant="tQ", reason=REJECT_QUOTA,
        ).value >= 26
        fe.step()
        for f in futs:
            f.result(timeout=30)
        np.testing.assert_array_equal(np.asarray(x), 6.0)
    finally:
        fe.close()
        cr.dispose()


def test_frontend_32_threads_mixed_signatures_coalesce(devs):
    """The ISSUE 11 contention pin: 32 concurrent clients × mixed
    signatures complete bit-exactly AND coalesce into measurably fewer
    ladder launches than requests (ck_fused_windows + per-call count)."""
    cr, arrays, jobs = _mk(devs, sigs=4)
    fe = ServeFrontend(cr, gather_window_s=0.01, name="contention")
    n_clients, per_client = 32, 6
    m_windows = REGISTRY.counter(
        "ck_fused_windows_total", "fused ladder dispatch batches")
    m_iters = REGISTRY.counter(
        "ck_fused_iters_total", "iterations dispatched via fused ladders")
    w0, i0 = m_windows.value, m_iters.value
    per_sig = [0] * len(jobs)
    mu = threading.Lock()

    def client(ci):
        for k in range(per_client):
            s = (ci + k) % len(jobs)
            fe.submit(f"t{ci % 4}", jobs[s]).result(timeout=60)
            with mu:
                per_sig[s] += 1

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(n_clients)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not any(t.is_alive() for t in threads)
        requests = n_clients * per_client
        assert sum(per_sig) == requests
        # bit-exact per signature: every request applied +1 exactly once
        for s, a in enumerate(arrays):
            np.testing.assert_array_equal(np.asarray(a), float(per_sig[s]))
        # the coalescing evidence: ladder launches < requests
        windows = m_windows.value - w0
        per_call = requests - (m_iters.value - i0)
        assert windows + per_call < requests, (
            f"no coalescing: {windows} windows + {per_call} per-call "
            f">= {requests} requests")
    finally:
        fe.close()
        cr.dispose()


def test_frontend_deadline_ordering_and_miss_flag(devs):
    cr, arrays, jobs = _mk(devs, sigs=2)
    fe = ServeFrontend(cr, autostart=False, name="deadline")
    try:
        f_slow = fe.submit("tA", jobs[0])               # no deadline
        f_urgent = fe.submit("tB", jobs[1], deadline=5.0)
        out = fe.step()
        plan = out["plan"]
        # the deadlined group dispatches first
        assert plan["order"][0].endswith("cid701")
        assert f_urgent.result(10)["deadline_missed"] is False
        f_slow.result(10)
        # an already-expired deadline completes and is FLAGGED, not dropped
        f_late = fe.submit("tA", jobs[0], deadline=-0.001)
        fe.step()
        assert f_late.result(10)["deadline_missed"] is True
        assert fe.tenants.snapshot()["tA"]["deadline_missed"] == 1
    finally:
        fe.close()
        cr.dispose()


def test_frontend_unhealthy_rejects_with_retry_after(devs):
    cr, _arrays, (job,) = _mk(devs)
    healthy = [False]
    fe = ServeFrontend(
        cr, admission=AdmissionController(health=lambda: healthy[0],
                                          health_ttl_s=0.0),
        autostart=False, name="health")
    try:
        with pytest.raises(ServeRejected) as exc:
            fe.submit("tA", job)
        assert exc.value.reason == REJECT_HEALTH
        assert exc.value.retry_after_s > 0
        healthy[0] = True
        fe.submit("tA", job)
        fe.step()
    finally:
        fe.close()
        cr.dispose()


def test_frontend_queue_backpressure(devs):
    cr, _arrays, (job,) = _mk(devs)
    fe = ServeFrontend(
        cr, admission=AdmissionController(max_queue_depth=3,
                                          default_quota=100),
        autostart=False, name="backpressure")
    try:
        for _ in range(3):
            fe.submit("tA", job)
        with pytest.raises(ServeRejected) as exc:
            fe.submit("tA", job)
        assert exc.value.reason == REJECT_QUEUE
        fe.step()  # drains; admission opens again
        fe.submit("tA", job)
        fe.step()
    finally:
        fe.close()
        cr.dispose()


def test_serve_decisions_replay_green_and_tamper_diverges(devs):
    """Every admission/coalesce decision a serve run records replays
    bit-identically; a tampered output names its seq (the acceptance
    criterion's replay half)."""
    cr, _arrays, (job,) = _mk(devs)
    fe = ServeFrontend(cr, autostart=False, name="replay")
    DECISIONS.clear()
    try:
        for _ in range(8):
            fe.submit("tA", job)
        fe.step()
        fe.admission.set_quota("tB", 1)
        fe.submit("tB", job)
        with pytest.raises(ServeRejected):
            fe.submit("tB", job)
        fe.step()
        rows = [r.to_row() for r in DECISIONS.snapshot()
                if r.kind in ("admission", "coalesce")]
        assert len([r for r in rows if r["kind"] == "admission"]) == 10
        assert len([r for r in rows if r["kind"] == "coalesce"]) == 2
        verdict = verify_records(rows)
        assert verdict["ok"] is True, verdict
        assert verdict["replayed"] == len(rows)
        # tamper: a rewritten admission outcome must diverge at its seq
        bad = json.loads(json.dumps(rows[0]))
        bad["outputs"]["admit"] = not bad["outputs"]["admit"]
        v2 = verify_records([bad])
        assert v2["ok"] is False
        assert v2["first_divergence"]["seq"] == bad["seq"]
    finally:
        fe.close()
        cr.dispose()


def test_frontend_close_fails_leftovers_with_named_error(devs):
    cr, _arrays, (job,) = _mk(devs)
    fe = ServeFrontend(cr, autostart=False, name="shutdown")
    fut = fe.submit("tA", job)
    fe.close(drain=False)
    with pytest.raises(Exception, match="closed"):
        fut.result(timeout=5)
    with pytest.raises(Exception, match="closed"):
        fe.submit("tA", job)
    cr.dispose()


# ---------------------------------------------------------------------------
# /servez + dispatcher thread
# ---------------------------------------------------------------------------

def test_servez_payload_and_endpoint(devs):
    cr, _arrays, (job,) = _mk(devs)
    fe = ServeFrontend(cr, gather_window_s=0.001, name="servez")
    try:
        for _ in range(6):
            fe.submit("tZ", job).result(timeout=30)
        doc = servez_payload()
        mine = [f for f in doc["frontends"] if f["name"] == "servez"]
        assert mine and mine[0]["requests_done"] == 6
        assert mine[0]["tenants"]["tZ"]["completed"] == 6
        srv = cr.serve_debug(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/servez", timeout=10) as r:
            body = json.loads(r.read())
        assert any(f["name"] == "servez" for f in body["frontends"])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/", timeout=10) as r:
            assert "/servez" in json.loads(r.read())["endpoints"]
    finally:
        fe.close()
        cr.dispose()


# ---------------------------------------------------------------------------
# satellites riding this module: pool tenant tags, loadgen smoke
# ---------------------------------------------------------------------------

def test_pool_tenant_tag_passthrough(devs):
    from cekirdekler_tpu.pipeline.pool import ClDevicePool, ClTask, ClTaskPool

    n = 512
    x = ClArray(np.zeros(n, np.float32), name="pt")
    x.partial_read = True
    staged = ClTaskPool([
        x.task(31, "inc", n, 64),
        x.task(31, "inc", n, 64),
    ])
    tagged = ClTaskPool()
    tagged.feed(staged, tenant="tP")
    assert all(t.tenant == "tP" for t in tagged.snapshot())
    # a pre-tagged task keeps its own tenant through an untagged feed
    own = ClTask(params=[x], kernel_names=["inc"], compute_id=31,
                 global_range=n, local_range=64, tenant="keep")
    keep = ClTaskPool([own])
    merged = ClTaskPool()
    merged.feed(keep, tenant="tP")
    assert merged.snapshot()[0].tenant == "keep"
    # untagged feed changes nothing (the no-behavior-change contract)
    plain = ClTaskPool()
    plain.feed(staged)
    assert all(t.tenant is None for t in plain.snapshot())
    with ClDevicePool(devs.subset(1), INC) as pool:
        pool.enqueue_task_pool(tagged)
        pool.finish()
    np.testing.assert_array_equal(np.asarray(x), 2.0)
    snap = REGISTRY.snapshot()
    assert any(
        'ck_pool_tasks_total{' in k and 'tenant="tP"' in k
        for k in (snap.get("counters") or {})
    ), "tenant-labeled pool-task series missing"


def test_loadgen_smoke(devs):
    import importlib.util
    import os

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ck_loadgen_test", os.path.join(here, "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    out = lg.run_loadgen(devs, clients=8, tenants=2, signatures=2,
                         requests_per_client=4, n=2048)
    assert out["completed"] == 32 and out["failed"] == 0
    assert out["checked"] is True
    assert out["coalesced"] is True, out
    assert out["ladder_launches"] < out["completed"]
    assert out["p50_ms"] > 0 and out["p99_ms"] >= out["p50_ms"]
    assert out["goodput_rps"] > 0
