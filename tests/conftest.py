"""Test rig: 8 virtual CPU devices so multi-chip scheduling, the load
balancer, pipelines, and sharding are all testable without TPU hardware —
the fake-backend capability the reference lacks (SURVEY.md §4)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
# XLA's DEFAULT matmul precision may decompose f32 matmuls into bf16 passes;
# parity tests (sharded vs single-device) need true-f32 products so rounding
# doesn't depend on how GSPMD partitions the contraction
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, "virtual device rig failed to initialize"
    return devs
