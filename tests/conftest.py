"""Test rig: 8 virtual CPU devices so multi-chip scheduling, the load
balancer, pipelines, and sharding are all testable without TPU hardware —
the fake-backend capability the reference lacks (SURVEY.md §4).

The rig FORCES its backend.  ``setdefault`` is not enough: the host env may
pin an accelerator platform (``JAX_PLATFORMS=axon`` + a sitecustomize-registered
PJRT plugin, whose registration overrides in-process env changes), in which
case default-placement ops in every test would ride a tunneled TPU — the
round-2 suite "passed" that way but took 8m18s and proved nothing about the
rig.  Repair strategy, cheapest first:

- plugin not registered and jax backends not yet initialized → rewrite the
  env vars in-process (no re-exec needed; platform selection is read at
  first backend init);
- otherwise → re-exec pytest ONCE with a cleaned env (plugin disabled, cpu
  platform, 8 virtual devices).  A sentinel makes a second failure loud
  instead of looping.  The re-exec happens in ``pytest_configure`` so
  pytest's fd-level capture can be torn down first — an execve under active
  capture would write the whole child run into a doomed temp file.  NOTE:
  the re-exec replaces the invocation with plain ``python -m pytest <args>``;
  interpreter flags and wrappers (coverage, -W, -X) are dropped on this
  path — export the rig env vars yourself if you need them preserved.
"""

import os
import sys

_SENTINEL = "CK_TEST_RIG"
_N_DEVICES = 8
_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _forced_device_count(flags: str) -> int:
    for f in flags.split():
        if f.startswith(_COUNT_FLAG + "="):
            try:
                return int(f.split("=", 1)[1])
            except ValueError:
                return 0
    return 0


def _rig_env_ok() -> bool:
    return (
        os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
        and _forced_device_count(os.environ.get("XLA_FLAGS", "")) >= _N_DEVICES
        and not os.environ.get("PALLAS_AXON_POOL_IPS")
    )


def _rig_env(base: dict) -> dict:
    env = dict(base)
    # sitecustomize registers the accelerator PJRT plugin (pinning platform
    # selection for the whole process) when this var is set; tests must run
    # on a plain CPU interpreter
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split() if not f.startswith(_COUNT_FLAG)
    ]
    flags.append(f"{_COUNT_FLAG}={_N_DEVICES}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def pytest_configure(config):
    if not _rig_env_ok():
        if not os.environ.get("PALLAS_AXON_POOL_IPS") and "jax" not in sys.modules:
            # cheap path: no platform-pinning plugin and jax is not even
            # imported yet (an import captures JAX_PLATFORMS into config) —
            # fixing the env in this process is enough
            os.environ.update(_rig_env(os.environ))
        elif os.environ.get(_SENTINEL):
            raise RuntimeError(
                f"test rig env still wrong after re-exec: "
                f"JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r} "
                f"XLA_FLAGS={os.environ.get('XLA_FLAGS')!r}"
            )
        else:
            env = _rig_env(os.environ)
            env[_SENTINEL] = "1"
            capman = config.pluginmanager.getplugin("capturemanager")
            if capman is not None:
                capman.stop_global_capturing()
            os.execve(
                sys.executable,
                [sys.executable, "-m", "pytest"]
                + list(config.invocation_params.args),
                env,
            )

    os.environ.setdefault("JAX_ENABLE_X64", "1")
    import jax

    jax.config.update("jax_enable_x64", True)
    # XLA's DEFAULT matmul precision may decompose f32 matmuls into bf16
    # passes; parity tests (sharded vs single-device) need true-f32 products
    # so rounding doesn't depend on how GSPMD partitions the contraction
    jax.config.update("jax_default_matmul_precision", "highest")

    # fail fast if the rig didn't come up — a suite that silently runs on a
    # different backend measures nothing
    assert jax.default_backend() == "cpu", (
        f"rig requires cpu default backend, got {jax.default_backend()}"
    )
    assert len(jax.devices()) >= _N_DEVICES, (
        f"virtual device rig failed to initialize: {len(jax.devices())} devices"
    )

    # dynamic lock-order witness (opt-in: CK_LOCK_WITNESS=1): wrap the
    # package's named locks, record actual acquisition orders during the
    # run, and cross-check them against tools/ckcheck's static graph at
    # session end (tests/_artifacts/lock_witness.json).  Disagreements
    # are a report, not a failure — see docs/STATIC_ANALYSIS.md.
    global _WITNESS
    if os.environ.get("CK_LOCK_WITNESS") == "1" and _WITNESS is None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        try:
            from tools.ckcheck.witness import install

            _WITNESS = install(os.path.join(repo, "cekirdekler_tpu"))
        except Exception as e:  # noqa: BLE001 - witness must never sink a run
            print(f"[ck-lock-witness] install failed: {e!r}", file=sys.stderr)


_WITNESS = None


def pytest_sessionfinish(session, exitstatus):
    global _WITNESS
    if _WITNESS is None:
        return
    try:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        from tools.ckcheck import lock_order_edges, scan_package

        pkg = scan_package(os.path.join(repo, "cekirdekler_tpu"))
        static = set(lock_order_edges(pkg))
        path = os.path.join(repo, "tests", "_artifacts", "lock_witness.json")
        _WITNESS.write_report(static, path)
        rep = _WITNESS.report(static)
        print(
            f"\n[ck-lock-witness] {len(rep['dynamic_edges'])} dynamic / "
            f"{len(rep['static_edges'])} static order edges; "
            f"{len(rep['dynamic_only'])} dynamic-only (static blind spots), "
            f"{len(rep['static_only'])} static-only (unexercised) "
            f"-> {path}"
        )
    except Exception as e:  # noqa: BLE001
        print(f"[ck-lock-witness] report failed: {e!r}", file=sys.stderr)
    finally:
        _WITNESS.uninstall()
        _WITNESS = None


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    return jax.devices("cpu")[:_N_DEVICES]
