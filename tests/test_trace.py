"""The span-based attribution subsystem (cekirdekler_tpu/trace/):
overhead budget, ring-buffer semantics, spans from every runtime layer,
per-cid fence splitting on a skewed two-kernel window, Chrome-trace
schema round-trip, and the per-rep overlap ceiling's structural bounds.
"""

import json
import time

import numpy as np
import pytest

import cekirdekler_tpu as ct
from cekirdekler_tpu.arrays.clarray import ClArray
from cekirdekler_tpu.core.cruncher import NumberCruncher
from cekirdekler_tpu.trace import (
    TRACER,
    RepSample,
    Span,
    Tracer,
    ceiling_report,
    from_chrome_trace,
    rep_ceiling,
    split_fence_benches,
    to_chrome_trace,
    tracing,
    window_report,
)

SAXPY = """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = y[i] + a * x[i];
}
"""

TWO_KERNELS = """
__kernel void heavy(__global float* x, __global float* y) {
    int i = get_global_id(0);
    float acc = x[i];
    for (int k = 0; k < 40000; k++) { acc = acc + x[i] * 0.25f; }
    y[i] = acc;
}
__kernel void light(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i] + 1.0f;
}
"""


def _cpus(k=2):
    return ct.platforms().cpus().subset(k)


@pytest.fixture(autouse=True)
def _tracer_off():
    """Every test starts and ends with the global tracer disabled — a
    test that leaks an enabled tracer would tax the whole suite."""
    TRACER.disable()
    TRACER.clear()
    yield
    TRACER.disable()
    TRACER.clear()


# -- overhead budget ---------------------------------------------------------

def test_disabled_tracer_overhead_under_budget():
    """The ISSUE's stated budget: a disabled tracer's would-be span costs
    < 1 µs.  Measured over 50k t0()/record() pairs (the hot-site
    convention), best of 3 runs to shrug off scheduler noise."""
    tr = Tracer()
    assert not tr.enabled
    n = 50_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            t = tr.t0()
            tr.record("launch", t, cid=1, lane=0)
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"disabled span cost {best*1e9:.0f} ns >= 1 µs"
    assert tr.total_recorded == 0  # truly a no-op: nothing stored


def test_enabled_tracer_records_and_costs_sanely():
    tr = Tracer(capacity=1024)
    tr.enable()
    n = 1000
    t0 = time.perf_counter()
    for i in range(n):
        t = tr.t0()
        tr.record("launch", t, cid=i, lane=0, tag="x")
    per = (time.perf_counter() - t0) / n
    assert tr.total_recorded == n
    assert per < 5e-5  # sanity only; the hard budget is the disabled path


# -- ring buffer -------------------------------------------------------------

def test_ring_buffer_wraps_keeping_newest():
    tr = Tracer(capacity=16)
    tr.enable()
    for i in range(40):
        tr.instant("launch", cid=i)
    spans = tr.snapshot()
    assert len(spans) == 16
    assert tr.total_recorded == 40
    assert sorted(s.cid for s in spans) == list(range(24, 40))


def test_record_ignores_disabled_open():
    """A span opened while disabled must not record even if the tracer
    was enabled mid-span (t0 == 0.0 sentinel)."""
    tr = Tracer()
    t = tr.t0()
    tr.enable()
    tr.record("launch", t)
    assert tr.total_recorded == 0


def test_tracing_scope_disables_on_exit():
    with tracing() as tr:
        assert tr.enabled
        tr.instant("split")
    assert not TRACER.enabled
    assert len(TRACER.snapshot()) == 1  # spans survive the scope


# -- spans from the runtime layers ------------------------------------------

def test_spans_from_worker_cores_and_both_engines():
    from cekirdekler_tpu.core.cores import PIPELINE_DRIVER, PIPELINE_EVENT

    n = 1024
    x = ClArray(np.arange(n, dtype=np.float32), partial_read=True,
                read_only=True)
    y = ClArray(np.ones(n, np.float32), partial_read=True)
    cr = NumberCruncher(_cpus(2), SAXPY)
    try:
        with tracing() as tr:
            t0 = time.perf_counter()
            g = x.next_param(y)
            g.compute(cr, 11, "saxpy", n, 64, values=(2.0,))
            g.compute(cr, 11, "saxpy", n, 64, pipeline=True,
                      pipeline_blobs=4, pipeline_type=PIPELINE_EVENT,
                      values=(2.0,))
            g.compute(cr, 11, "saxpy", n, 64, pipeline=True,
                      pipeline_blobs=4, pipeline_type=PIPELINE_DRIVER,
                      values=(2.0,))
            cr.barrier()
            t1 = time.perf_counter()
        spans = tr.snapshot()
        kinds = {s.kind for s in spans}
        # worker layer
        assert {"upload", "launch", "download", "fence"} <= kinds
        # cores layer: the compute() entry + the first range split
        assert {"enqueue", "split"} <= kinds
        # both pipeline engines emitted their engine spans
        engine_tags = {s.tag.split()[0] for s in spans
                       if s.kind == "pipeline-stage" and s.tag}
        assert {"EVENT", "DRIVER"} <= engine_tags
        # cid threading: every launch span carries the compute id
        launches = [s for s in spans if s.kind == "launch"]
        assert launches and all(s.cid == 11 for s in launches)
        assert all(s.lane in (0, 1) for s in launches)
        # the window report reconciles: coverage cannot exceed wall
        rep = window_report(spans, t0, t1)
        assert 0 <= rep.covered_ms <= rep.wall_ms + 1e-6
        assert rep.gap_ms >= 0
        assert rep.per_cid[11]["launch"] > 0
    finally:
        cr.dispose()


def test_spans_from_device_pipeline_and_pool():
    from cekirdekler_tpu.pipeline.device_pipeline import ClPipeline, PipelineStage
    from cekirdekler_tpu.pipeline.pool import ClDevicePool, ClTask, ClTaskPool

    n = 256
    with tracing() as tr:
        # device pipeline stage spans
        st1 = PipelineStage(SAXPY, "saxpy", n, 64, values=(1.0,))
        st1.add_input(np.arange(n, dtype=np.float32))
        st1.add_output(np.zeros(n, np.float32))
        st2 = PipelineStage(SAXPY, "saxpy", n, 64, values=(1.0,))
        st2.add_input(np.zeros(n, np.float32))
        st2.add_output(np.zeros(n, np.float32))
        pipe = ClPipeline.make([st1, st2], list(_cpus(2)))
        try:
            pipe.push([np.arange(n, dtype=np.float32)])
            pipe.push([np.arange(n, dtype=np.float32)])
        finally:
            pipe.dispose()
        stage_spans = [s for s in tr.snapshot() if s.kind == "pipeline-stage"]
        assert len(stage_spans) >= 4  # 2 stages x 2 pushes

        # pool task spans
        x = ClArray(np.arange(n, dtype=np.float32), read_only=True)
        y = ClArray(np.zeros(n, np.float32))
        pool = ClTaskPool()
        for _ in range(3):
            pool.add(ClTask(params=[x, y], kernel_names=["saxpy"],
                            compute_id=5, global_range=n, local_range=64,
                            values=(1.0,)))
        with ClDevicePool(_cpus(2), SAXPY) as dp:
            dp.enqueue_task_pool(pool)
            dp.finish()
        pool_spans = [s for s in tr.snapshot() if s.kind == "pool-task"]
        assert len(pool_spans) == 3
        assert all(s.cid == 5 for s in pool_spans)


# -- fence split -------------------------------------------------------------

def test_split_fence_benches_marginals():
    t0 = 100.0
    comps = [(1, 100.010), (2, 100.011), (3, 100.050)]
    b = split_fence_benches(comps, t0)
    assert b[1] == pytest.approx(10.0, abs=1e-6)
    assert b[2] == pytest.approx(1.0, abs=1e-6)
    assert b[3] == pytest.approx(39.0, abs=1e-6)
    # out-of-order clock jitter clamps at 0, never negative
    b2 = split_fence_benches([(1, 100.010), (2, 100.009)], t0)
    assert b2[2] == 0.0


def test_fence_split_attributes_skewed_two_kernel_window():
    """The VERDICT r5 #8 distortion, measured and closed: a mixed
    enqueue window of a heavy and a light kernel.  Without the split
    both compute ids inherit the whole-window fence time (the documented
    approximation); with ``fence_split`` the light kernel's bench must
    come out a small fraction of the heavy one's."""
    n = 8192
    x = ClArray(np.arange(n, dtype=np.float32) % 7, partial_read=True,
                read_only=True)
    yh = ClArray(n, np.float32, name="tyh", partial_read=True)
    yl = ClArray(n, np.float32, name="tyl", partial_read=True)

    def window(split: bool):
        cr = NumberCruncher(_cpus(2), TWO_KERNELS)
        try:
            cr.fence_split = split
            cr.enqueue_mode = True
            for _ in range(3):
                x.next_param(yh).compute(cr, 31, "heavy", n, 256)
            for _ in range(3):
                x.next_param(yl).compute(cr, 32, "light", n, 256)
            cr.barrier()
            heavy = cr.benchmarks_of(31)
            light = cr.benchmarks_of(32)
            cr.enqueue_mode = False
            return heavy, light
        finally:
            if cr.enqueue_mode:
                cr.enqueue_mode = False
            cr.dispose()

    heavy0, light0 = window(split=False)
    # the documented default: one fence time for every id in the window
    assert heavy0 == light0
    heavy1, light1 = window(split=True)
    for h, l in zip(heavy1, light1):
        assert h > 0 and l >= 0
        # the skew is ~1000x on this kernel pair; 5x is a safe floor
        # that still fails hard if the split regresses to whole-window
        assert l < h / 5.0, (heavy1, light1)
    # correctness survives the split path (flush after the barrier)
    np.testing.assert_allclose(
        np.asarray(yl.host()), np.asarray(x.host()) + 1.0
    )


def test_fence_split_correct_results_and_rebalance_arming():
    """The split path must leave the sync-point rebalance machinery
    working: ids still arm, ranges still move on the next call."""
    n = 4096
    x = ClArray(np.arange(n, dtype=np.float32), partial_read=True,
                read_only=True)
    y = ClArray(np.ones(n, np.float32), partial_read=True)
    cr = NumberCruncher(_cpus(2), SAXPY)
    try:
        cr.fence_split = True
        cr.enqueue_mode = True
        for _ in range(4):
            x.next_param(y).compute(cr, 41, "saxpy", n, 64, values=(1.0,))
        cr.barrier()
        assert 41 in cr.cores._enqueue_rebalance
        x.next_param(y).compute(cr, 41, "saxpy", n, 64, values=(1.0,))
        cr.enqueue_mode = False
        np.testing.assert_allclose(
            np.asarray(y.host()),
            1.0 + 5.0 * np.arange(n, dtype=np.float32),
        )
    finally:
        if cr.enqueue_mode:
            cr.enqueue_mode = False
        cr.dispose()


# -- chrome export -----------------------------------------------------------

def test_chrome_trace_roundtrip_schema():
    base = time.perf_counter()
    spans = [
        Span("launch", base, base + 0.005, cid=7, lane=0, tag="k1 x2"),
        Span("upload", base + 0.001, base + 0.002, cid=7, lane=1, tag="a"),
        Span("fence", base + 0.006, base + 0.009, cid=None, lane=None,
             tag="barrier"),
    ]
    trace = to_chrome_trace(spans)
    # schema facts chrome://tracing / Perfetto rely on
    blob = json.dumps(trace)
    parsed = json.loads(blob)
    evs = parsed["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == len(spans)
    for e in xs:
        assert {"name", "pid", "tid", "ts", "dur"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    names = {e["args"]["name"] for e in evs if e["ph"] == "M"
             and e["name"] == "thread_name"}
    assert {"host", "lane 0", "lane 1"} <= names
    # round trip: kinds, cids, lanes, tags, durations survive
    back = from_chrome_trace(parsed)
    assert len(back) == len(spans)
    orig = sorted(spans, key=lambda s: s.t0)
    for a, b in zip(orig, back):
        assert a.kind == b.kind and a.cid == b.cid and a.lane == b.lane
        assert a.tag == b.tag
        assert b.dur_ms == pytest.approx(a.dur_ms, rel=1e-6)


# -- overlap ceiling ---------------------------------------------------------

def test_rep_ceiling_witness_clamp_and_bounds():
    # good engine: achieved lands near the model's prediction
    s = RepSample(r=10.0, c=30.0, w=10.0, p=33.0, h2d=10.0, d2h=10.0,
                  dup=12.0)
    r = rep_ceiling(s, blobs=8)
    assert r["achieved_vs_ceiling"] is not None
    assert 0.9 <= r["achieved_vs_ceiling"] <= 1.0
    # engine beats the model (the r5 1.15 case): ratio saturates at 1.0,
    # flagged — never above
    s2 = RepSample(r=10.0, c=30.0, w=10.0, p=29.0, h2d=10.0, d2h=10.0,
                   dup=20.0)
    r2 = rep_ceiling(s2, blobs=8)
    assert r2["model_beaten"]
    assert r2["achieved_vs_ceiling"] == pytest.approx(1.0)
    # poor engine: honestly below — no clipping upward
    s3 = RepSample(r=10.0, c=30.0, w=10.0, p=48.0, h2d=10.0, d2h=10.0,
                   dup=12.0)
    r3 = rep_ceiling(s3, blobs=8)
    assert r3["achieved_vs_ceiling"] < 0.9


def test_rep_ceiling_ratio_in_unit_interval_under_noise():
    """Property sweep: whatever the (noisy) inputs, the per-rep ratio is
    a [0, 1] fraction — the structural guarantee that fixes the
    broken-ruler finding (negative-overlap reps floor at 0 and are
    counted by ceiling_report, never fed raw into the median)."""
    rng = np.random.default_rng(0)
    for _ in range(500):
        vals = rng.uniform(0.1, 50.0, size=7)
        s = RepSample(*[float(v) for v in vals])
        r = rep_ceiling(s, blobs=int(rng.integers(2, 17)))
        if r["achieved_vs_ceiling"] is not None:
            assert 0.0 <= r["achieved_vs_ceiling"] <= 1.0 + 1e-9


def test_ceiling_report_counts_negative_overlap_reps():
    # p > serial: pipelining ran SLOWER than serial — achieved < 0
    bad = RepSample(r=1.0, c=5.0, w=1.0, p=8.0, h2d=1.0, d2h=1.0, dup=1.2)
    rep = ceiling_report([bad], blobs=4)
    assert rep["negative_overlap_reps"] == 1
    assert rep["achieved_vs_ceiling"] == 0.0  # floored, not negative


def test_ceiling_report_medians_and_spread():
    reps = [
        RepSample(r=10, c=30, w=10, p=33, h2d=10, d2h=10, dup=12),
        RepSample(r=11, c=31, w=9, p=32, h2d=10, d2h=10, dup=13),
        RepSample(r=9, c=29, w=11, p=34, h2d=10, d2h=10, dup=11),
    ]
    rep = ceiling_report(reps, blobs=8)
    assert rep["n_reps"] == 3
    assert len(rep["per_rep_achieved_vs_ceiling"]) == 3
    assert rep["achieved_vs_ceiling"] <= 1.0
    assert rep["achieved_vs_ceiling_spread"] >= 0.0
    assert 0.9 <= rep["achieved_vs_ceiling"] <= 1.0


def test_measure_stream_overlap_per_rep_ceiling_keys():
    """Live rig smoke: the overlap measurement carries the per-rep
    ceiling keys with their structural bounds (the rig's memcpy
    'transfers' make the absolute numbers meaningless — the BOUNDS and
    the schema are what the artifact contract pins)."""
    from cekirdekler_tpu.workloads import measure_stream_overlap

    ov = measure_stream_overlap(
        _cpus(1), n=1 << 14, blobs=4, reps=2, heavy_iters=2000,
        duplex_probe=True,
    )
    assert ov["n_reps"] == 2
    assert len(ov["per_rep_achieved_vs_ceiling"]) <= 2
    avc = ov["achieved_vs_ceiling"]
    if avc is not None:
        assert avc <= 1.0 + 1e-9  # the ruler bounds from above, always
        assert ov["achieved_vs_ceiling_spread"] is not None
    assert 0.0 <= ov["duplex_capacity"] <= 1.0
    assert 0.0 <= ov["overlap_ceiling"] <= 1.0


# -- nbody e2e attribution ---------------------------------------------------

def test_nbody_e2e_attribution_names_the_factors():
    from cekirdekler_tpu.workloads import nbody_e2e

    out = nbody_e2e(
        _cpus(2), n=512, iters=12, window=4, attribution=True,
        probe_iters=4,
    )
    assert out["checked"]
    att = out["attribution"]
    f = att["factors"]
    for name in ("window_rtt", "ladder_launch", "upload",
                 "download_flush", "scheduler_dispatch", "host_gap"):
        assert name in f, f.keys()
        assert f[name]["ms"] >= 0.0
        assert f[name]["frac"] is None or f[name]["frac"] >= 0.0
    # 12 iters / window 4 → 3 barriers
    assert f["window_rtt"]["count"] == 3
    assert f["ladder_launch"]["count"] >= 12  # ≥1 dispatch span per iter
    li = att["lane_interference"]
    assert "factor" in li, li
    assert li["factor"] > 0
    assert li["lanes"] == 2
    # the attribution run must not leave the global tracer enabled
    assert not TRACER.enabled


def test_fori_chain_bench_fallback_refuses_dceable_feedback():
    import jax.numpy as jnp

    from cekirdekler_tpu.workloads import fori_chain_bench

    a = jnp.ones((8, 8), jnp.float32)
    b = jnp.ones((4, 4), jnp.float32)

    # two output leaves that do not pair with the carries: leaves[1:]
    # would silently DCE out of the loop — must refuse
    def bad_step(x, y):
        return x * 1.0001, jnp.sum(y, keepdims=True)

    with pytest.raises(ValueError, match="DCE-able"):
        fori_chain_bench(bad_step, (a, b), reps=2, trials=1)

    # single output leaf matching a carry: the documented fallback works
    def ok_step(x, y):
        return x * 1.0001 + y[:1, :1].sum()

    dt = fori_chain_bench(ok_step, (a, b), reps=2, trials=1)
    assert dt > 0
