"""Aux subsystem tests: checkpoint/resume (atomic, sharded pytrees),
marker counters, perf history."""

import os
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import cekirdekler_tpu as ct
from cekirdekler_tpu.arrays.clarray import ClArray
from cekirdekler_tpu.core.cruncher import NumberCruncher
from cekirdekler_tpu.utils.checkpoint import (
    latest_step,
    load_arrays,
    load_pytree,
    save_arrays,
    save_pytree,
)
from cekirdekler_tpu.utils.markers import MarkerCounter


def _cpus(n=2):
    return ct.all_devices().cpus().subset(n)


# -- checkpoint --------------------------------------------------------------

def test_array_checkpoint_roundtrip(tmp_path):
    root = str(tmp_path / "ck")
    a = ClArray(np.arange(100, dtype=np.float32))
    save_arrays(root, 5, {"a": a, "b": np.ones(3)})
    save_arrays(root, 9, {"a": a, "b": np.zeros(3)})
    assert latest_step(root) == 9
    got = load_arrays(root)  # latest
    np.testing.assert_array_equal(got["a"], a.host())
    np.testing.assert_array_equal(got["b"], np.zeros(3))
    got5 = load_arrays(root, 5)
    np.testing.assert_array_equal(got5["b"], np.ones(3))


def test_pytree_checkpoint_roundtrip_with_sharding(tmp_path):
    from cekirdekler_tpu import parallel as par
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    root = str(tmp_path / "ck")
    cfg = TransformerConfig(vocab=32, d_model=16, n_layers=1, n_heads=2,
                            d_ff=32, dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    mesh = par.make_mesh(jax.devices("cpu")[:4], dp=2, tp=2)
    sharded = model.shard_params(params, mesh)
    save_pytree(root, 100, sharded)

    fresh = model.shard_params(model.init(jax.random.PRNGKey(1)), mesh)
    restored = load_pytree(
        root, fresh, sharding_fn=lambda l, x: jax.device_put(x, l.sharding)
    )
    for a, b in zip(jax.tree_util.tree_leaves(sharded),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding == a.sharding


def test_checkpoint_atomicity_no_partial_dirs(tmp_path):
    root = str(tmp_path / "ck")
    save_arrays(root, 1, {"x": np.ones(4)})
    leftovers = [d for d in os.listdir(root) if d.startswith(".ckpt_tmp_")]
    assert leftovers == []


def test_load_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_arrays(str(tmp_path / "none"))


# -- markers -----------------------------------------------------------------

def test_marker_counter_basics():
    m = MarkerCounter(window=4)
    m.add(3)
    assert m.remaining() == 3
    m.reach()
    m.reach()
    assert m.reached == 2 and m.remaining() == 1
    m.reach()
    assert m.reach_speed() >= 0.0
    m.reset()
    assert m.added == 0 and m.remaining() == 0


def test_fine_grained_queue_control_counts_ops():
    n = 256
    a = ClArray(np.zeros(n, np.float32))
    cr = NumberCruncher(
        _cpus(2),
        "__kernel void f(__global float* a){ int i=get_global_id(0); a[i]+=1.0f; }",
    )
    try:
        cr.fine_grained_queue_control = True
        a.compute(cr, 1, "f", n, 64)
        assert cr.count_markers_reached() > 0
        # compute() is synchronous, but "reached" is observed by the
        # marker counter's COMPLETION THREAD (reach_when_ready joins on
        # a daemon thread by design) — give the drain a bounded window
        # before asserting in-flight depth hit zero, else a loaded rig
        # races the thread and flakes
        deadline = _time.time() + 5.0
        while cr.count_markers_remaining() and _time.time() < deadline:
            _time.sleep(0.01)
        assert cr.count_markers_remaining() == 0
        cr.fine_grained_queue_control = False
        assert not cr.fine_grained_queue_control
    finally:
        cr.dispose()


# -- perf history ------------------------------------------------------------

def test_performance_history_accumulates():
    n = 256
    a = ClArray(np.zeros(n, np.float32))
    cr = NumberCruncher(
        _cpus(2),
        "__kernel void f(__global float* a){ int i=get_global_id(0); a[i]+=1.0f; }",
    )
    try:
        for _ in range(4):
            a.compute(cr, 7, "f", n, 64)
        hist = cr.performance_history(7)
        assert len(hist) == 4
        assert all(p.compute_id == 7 for p in hist)
        assert sum(hist[-1].device_items) == n
    finally:
        cr.dispose()


def test_timeline_merged_busy_math():
    from cekirdekler_tpu.utils.timeline import _merged_busy

    # disjoint + overlapping + contained intervals
    assert _merged_busy([(0.0, 10.0), (20.0, 30.0)]) == 20.0
    assert _merged_busy([(0.0, 10.0), (5.0, 15.0)]) == 15.0
    assert _merged_busy([(0.0, 10.0), (2.0, 3.0)]) == 10.0
    assert _merged_busy([]) == 0.0


def test_timeline_capture_graceful_without_device_events(tmp_path):
    """On the CPU rig the profiler exposes no '/device:' process — the
    capture must still run the region and return an empty analysis (the
    tunneled-TPU path is exercised by bench.py's timeline_evidence)."""
    import jax.numpy as jnp
    import numpy as np

    from cekirdekler_tpu.utils import timeline

    with timeline.capture(str(tmp_path / "tr")) as result:
        x = jnp.arange(1024, dtype=jnp.float32) * 2
        np.asarray(x)
    tl = result()
    assert tl.span_ms >= 0.0
    assert 0.0 <= tl.compute_busy_fraction <= 1.0 or tl.n_events == 0


def test_tracer_report_runs(tmp_path):
    import jax.numpy as jnp
    import numpy as np

    from cekirdekler_tpu.utils.timeline import Tracer

    tr = Tracer(str(tmp_path / "traces"))
    with tr.region("warm"):
        np.asarray(jnp.ones(64) + 1)
    assert "warm" in tr.report()


def test_timeline_capture_propagates_region_exception(tmp_path):
    """An exception raised inside the traced region must surface unchanged
    (regression: the generator used to yield a second time, masking the
    real error as RuntimeError)."""
    import pytest

    from cekirdekler_tpu.utils import timeline

    with pytest.raises(ValueError, match="real error"):
        with timeline.capture(str(tmp_path / "tr")):
            raise ValueError("real error")


def test_user_event_counter_semantics():
    """ClUserEvent parity: fires on explicit trigger OR when the pending
    counter decrements to zero; waiters release (native path when the
    toolchain is present, threading fallback otherwise)."""
    from cekirdekler_tpu.utils.events import UserEvent

    ev = UserEvent()
    assert not ev.fired()
    ev.increment()
    ev.increment()
    assert ev.pending() == 2
    ev.decrement()
    assert not ev.fired()
    ev.decrement()
    assert ev.fired()
    assert ev.wait(timeout=1.0)
    ev.close()

    ev2 = UserEvent()
    assert not ev2.wait(timeout=0.05)  # times out untriggered
    ev2.trigger()
    assert ev2.wait(timeout=1.0)
    ev2.close()


def test_native_copy_engine_async_and_parallel():
    import numpy as np

    from cekirdekler_tpu import native
    from cekirdekler_tpu.utils.events import UserEvent

    lib = native.load()
    if lib is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    src = np.arange(1 << 21, dtype=np.float32)  # 8 MiB
    dst = np.zeros_like(src)
    ev = UserEvent()
    lib.ck_copyAsync(dst.ctypes.data, src.ctypes.data, src.nbytes, ev._id)
    assert ev.wait(timeout=5.0)
    np.testing.assert_array_equal(dst, src)
    dst2 = np.zeros_like(src)
    lib.ck_copyParallel(dst2.ctypes.data, src.ctypes.data, src.nbytes, 4)
    np.testing.assert_array_equal(dst2, src)
    ev.close()


def test_marker_counter_concurrent_stress_and_close_races():
    """The drain thread's batching/close discipline under stress: many
    producers enqueue completion joins while another thread closes the
    counter mid-flight — no deadlock, no lost counts before close, clean
    repeated close()."""
    import threading
    import jax.numpy as jnp

    from cekirdekler_tpu.utils.markers import MarkerCounter

    for round_ in range(5):
        mc = MarkerCounter()
        xs = [jnp.zeros(4) + i for i in range(8)]
        race_close = round_ % 2 == 1  # odd rounds: close WHILE producing

        def producer(k):
            for i in range(25):
                try:
                    mc.add()
                    mc.reach_when_ready(xs[(k + i) % len(xs)])
                except Exception:
                    if not race_close:
                        raise  # only a racing close may interrupt

        threads = [threading.Thread(target=producer, args=(k,)) for k in range(4)]
        for t in threads:
            t.start()
        if race_close:
            mc.close()  # concurrent with live producers: no crash, no UAF
        for t in threads:
            t.join()
        if not race_close:
            mc.drain(timeout=20.0)
            assert mc.added == 100
            assert mc.remaining() == 0, mc.remaining()
            assert mc.reach_speed() >= 0.0
        # queries after close must keep answering (snapshot semantics)
        mc.close()
        mc.close()  # idempotent
        assert mc.added >= 0 and mc.reached >= 0 and mc.remaining() >= 0


def test_marker_counter_close_with_pending_completions():
    """close() while completions are still queued must return promptly
    (bounded join) and not crash at interpreter teardown — the r4 bug was
    an orphan drain thread dying inside PJRT teardown."""
    import jax.numpy as jnp

    from cekirdekler_tpu.utils.markers import MarkerCounter

    mc = MarkerCounter()
    x = jnp.zeros(16)
    for i in range(200):
        mc.add()
        mc.reach_when_ready(x + i)
    mc.close()  # must not hang on 200 queued joins
    assert mc.remaining() >= 0  # counts consistent, no exception
