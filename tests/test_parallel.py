"""Parallel tier tests on the 8-virtual-device rig: mesh construction,
collectives, and ring/Ulysses attention vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from cekirdekler_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from cekirdekler_tpu import parallel as par


def _cpu_devices(n):
    devs = jax.devices("cpu")
    assert len(devs) >= n
    return devs[:n]


# -- mesh ------------------------------------------------------------------

def test_make_mesh_axis_order_and_sizes():
    mesh = par.make_mesh(_cpu_devices(8), dp=2, tp=2, sp=2)
    assert mesh.axis_names == par.AXIS_NAMES
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2 and mesh.shape["sp"] == 2
    assert mesh.shape["pp"] == 1


def test_make_mesh_rejects_bad_product():
    with pytest.raises(ValueError):
        par.make_mesh(_cpu_devices(8), dp=3)


def test_auto_mesh_fills_dp():
    mesh = par.auto_mesh(_cpu_devices(8), tp=4)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


def test_shard_batch_places_leading_dim():
    mesh = par.auto_mesh(_cpu_devices(8))
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    gx = par.shard_batch(mesh, {"x": x})["x"]
    assert gx.sharding.spec[0] == ("dp", "fsdp")
    np.testing.assert_array_equal(np.asarray(gx), x)


# -- collectives -----------------------------------------------------------

def test_psum_and_ring_permute():
    mesh = par.make_mesh(_cpu_devices(4), sp=4)

    def inner(x):
        total = par.psum(x.sum(), "sp")
        nxt = par.ring_next(x, "sp")
        return total * jnp.ones_like(x), nxt

    fn = shard_map(inner, mesh=mesh, in_specs=P("sp"), out_specs=(P("sp"), P("sp")))
    x = jnp.arange(8.0)
    total, rotated = fn(x)
    np.testing.assert_allclose(np.asarray(total), np.full(8, x.sum()))
    # shard i moves to shard i+1: [6,7] wraps to front
    np.testing.assert_array_equal(np.asarray(rotated), [6, 7, 0, 1, 2, 3, 4, 5])


def test_reduce_scatter_matches_psum_slice():
    mesh = par.make_mesh(_cpu_devices(4), tp=4)

    def inner(x):
        return par.reduce_scatter(x, "tp")

    fn = shard_map(inner, mesh=mesh, in_specs=P(None), out_specs=P("tp"))
    x = jnp.arange(16.0).reshape(16)
    out = fn(x)  # every shard holds x replicated; reduce-scatter sums then splits
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 4)


# -- long-context attention -------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = par.make_mesh(_cpu_devices(4), sp=4)
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32) for _ in range(3))
    want = par.attention_reference(q, k, v, causal=causal)
    got = par.ring_attention_sharded(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    mesh = par.make_mesh(_cpu_devices(4), sp=4)
    rng = np.random.default_rng(1)
    B, T, H, D = 2, 32, 4, 8  # H divisible by sp
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32) for _ in range(3))
    want = par.attention_reference(q, k, v, causal=causal)
    got = par.ulysses_attention_sharded(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_ring_attention_jits_under_mesh():
    mesh = par.make_mesh(_cpu_devices(8), sp=8)
    B, T, H, D = 1, 64, 2, 4
    rng = np.random.default_rng(2)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32) for _ in range(3))
    jitted = jax.jit(lambda a, b, c: par.ring_attention_sharded(mesh, a, b, c, causal=True))
    got = jitted(q, k, v)
    want = par.attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_inner_matches_reference(causal):
    """Ulysses with the Pallas flash kernel as the per-chip attention:
    values match the dense reference, and gradients flow (custom_vjp
    composes with shard_map's all_to_all)."""
    import jax

    mesh = par.make_mesh(_cpu_devices(4), sp=4)
    rng = np.random.default_rng(7)
    B, T, H, D = 1, 32, 4, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32) for _ in range(3))
    want = par.attention_reference(q, k, v, causal=causal)
    got = par.ulysses_attention_sharded(mesh, q, k, v, causal=causal, flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def loss_fl(q):
        return (par.ulysses_attention_sharded(mesh, q, k, v, causal=causal, flash=True) ** 2).sum()

    g = jax.grad(loss_fl)(q)
    g_ref = jax.grad(lambda q: (par.attention_reference(q, k, v, causal=causal) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_inner_matches_reference(causal):
    """Ring attention with the Pallas parts kernel per step (forward
    only): the merged unnormalized accumulators must reproduce the dense
    reference, including global-position causal masking across ring
    rotations."""
    mesh = par.make_mesh(_cpu_devices(4), sp=4)
    rng = np.random.default_rng(11)
    B, T, H, D = 1, 64, 2, 8  # T/n = 16 -> blocks of 16 per chip
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32) for _ in range(3))
    want = par.attention_reference(q, k, v, causal=causal)
    got = par.ring_attention_sharded(mesh, q, k, v, causal=causal, flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients(causal):
    """ring_attention(flash=True) is differentiable (r3 ADVICE: it used
    to die inside pallas_call): the custom_vjp backward is the tiled
    Pallas ring backward (r5 — per-step bwd kernels off the ring-global
    logsumexp, dk/dv accumulators rotating with their blocks), so grads
    must match the dense reference."""
    mesh = par.make_mesh(_cpu_devices(4), sp=4)
    rng = np.random.default_rng(13)
    B, T, H, D = 1, 64, 2, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
               for _ in range(3))

    def loss_fl(q, k, v):
        return (par.ring_attention_sharded(
            mesh, q, k, v, causal=causal, flash=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (par.attention_reference(q, k, v, causal=causal) ** 2).sum()

    g = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4,
            err_msg=f"ring-flash grad d{name} mismatch")


def test_ring_flash_gradients_bf16():
    """Low-precision inputs: the per-ring-step backward partials are
    emitted in f32 (flash_attention_bwd_parts), so bf16 ring grads must
    NOT stack one rounding per ring step.  The discriminating baseline
    is the SINGLE-CHIP flash vjp on the same bf16 inputs — it pays the
    same one-rounding costs (bf16 inputs, bf16 cotangent) but no
    per-step partial rounding, so ring grads must agree with it tightly;
    against a dense-reference baseline the stacked-rounding regression
    hides inside the input-quantization budget (r5 review finding: the
    original 3e-2-vs-dense form still passed with the regression
    reintroduced).  sp=8 so a regression stacks 8 roundings.  Measured
    separation on this exact configuration: f32 partials ≤ 3e-8 rel,
    per-step-rounded partials 1.6–4.3e-3 — the 5e-4 bound sits an order
    of magnitude from each side."""
    from cekirdekler_tpu.ops.flash_attention import flash_attention

    mesh = par.make_mesh(_cpu_devices(8), sp=8)
    rng = np.random.default_rng(17)
    B, T, H, D = 1, 128, 2, 8  # 16 rows per chip
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        .astype(jnp.bfloat16)
        for _ in range(3)
    )

    def loss_ring(q, k, v):
        return (par.ring_attention_sharded(
            mesh, q, k, v, causal=True, flash=True).astype(jnp.float32) ** 2
        ).sum()

    def loss_single(q, k, v):
        return (flash_attention(
            q, k, v, True, 16, 16).astype(jnp.float32) ** 2).sum()

    g = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_single, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        bf = b.astype(jnp.float32)
        rel = float(
            jnp.abs(a.astype(jnp.float32) - bf).max()
            / (jnp.abs(bf).max() + 1e-9)
        )
        assert rel < 5e-4, f"bf16 ring-flash grad d{name} rel={rel:.5f}"


def test_ring_flash_long_context_16k():
    """Long-context smoke: T=16384 over sp=8 (2048 per chip), flash inner.
    Dense attention would build an 8*16k*16k f32 score tensor (~8 GiB);
    the ring+flash path holds O(T/n * block) per chip — this test passing
    on the CPU rig is the memory claim, exactness vs the einsum ring body
    on a strided sample is the correctness claim."""
    mesh = par.make_mesh(_cpu_devices(8), sp=8)
    rng = np.random.default_rng(21)
    B, T, H, D = 1, 16384, 2, 32
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32) * 0.3)
               for _ in range(3))
    got = par.ring_attention_sharded(mesh, q, k, v, causal=True, flash=True)
    assert got.shape == (B, T, H, D)
    assert np.isfinite(np.asarray(got)).all()
    # exactness on a strided subsample of queries vs the dense reference
    # computed only for those rows (full dense would be the 8 GiB tensor
    # this path exists to avoid)
    idx = np.arange(63, T, 1024)
    qs = q[:, idx]
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", qs * scale, k)
    kpos = jnp.arange(T)[None, :]
    mask = kpos <= jnp.asarray(idx)[:, None]
    s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    want = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(
        np.asarray(got[:, idx]), np.asarray(want), atol=5e-4)
