"""tools/ckcheck as a tier-1 gate, plus regression tests for the live
findings it surfaced and this PR fixed.

Three layers:

1. **The gate itself** — the analyzer must exit 0 on HEAD against the
   checked-in baseline (re-introducing any fixed finding, or fixing a
   grandfathered one without shrinking the baseline, fails tier-1
   here).
2. **Fixture pins** — each historical bug shape (the PR 6 tracer-lock
   deadlock, the seed-era enqueue/rebalance lost-update race, the
   hot-path registry get-or-create, the RFC-8259 Infinity leak, an
   ABBA lock-order cycle) is planted in ``tests/fixtures_ckcheck/`` and
   must be FOUND, while its clean twin stays silent; plus the
   baseline-ratchet lifecycle (new finding fails → --update-baseline
   refuses growth without --allow-grow → fixing shrinks).
3. **Runtime regressions** — behavior tests for the fixes: bench-dict
   writes hold the worker lock, the fused deferral allocates no
   telemetry when the tracer is off, export paths emit strict
   RFC-8259 JSON, and ``ClTaskPool.feed`` no longer nests two pool
   locks.
"""

import json
import math
import os
import sys
import threading

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures_ckcheck")
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.ckcheck import (  # noqa: E402
    AnalyzerConfig,
    load_baseline,
    lock_order_edges,
    ratchet,
    run_passes,
    save_baseline,
    scan_package,
)
from tools.ckcheck.cli import main as ckcheck_main  # noqa: E402


def _fixture_findings(cfg=None):
    pkg = scan_package(FIXTURES, pkg_name="fixtures_ckcheck",
                       repo_root=ROOT)
    cfg = cfg or AnalyzerConfig(
        hot_roots=("hot_bad.Engine.defer", "hot_ok.Engine.defer"),
    )
    return run_passes(pkg, cfg)


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# 1. the gate itself
# ---------------------------------------------------------------------------

def test_live_tree_is_clean_against_baseline(capsys):
    """THE gate: ckcheck exits 0 on HEAD.  A new concurrency/hot-path/
    invariant finding anywhere in cekirdekler_tpu/, bench.py, or
    tools/ fails tier-1 right here with the finding printed."""
    rc = ckcheck_main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "clean" in out


def test_live_lock_order_graph_is_nonempty():
    # a graph that silently resolved nothing would make the deadlock
    # pass vacuous — the known Worker.lock -> Cores._lock edge must be
    # present (the _run_worker phase takes the scheduler lock inside)
    pkg = scan_package(os.path.join(ROOT, "cekirdekler_tpu"))
    edges = set(lock_order_edges(pkg))
    assert ("core.worker.Worker.lock", "core.cores.Cores._lock") in edges
    assert len(edges) >= 3


# ---------------------------------------------------------------------------
# 2a. fixture pins: each historical shape is FOUND, its twin is silent
# ---------------------------------------------------------------------------

def test_fixture_tracer_deadlock_shape_found():
    found = _by_rule(_fixture_findings(), "reacquire")
    assert any("deadlock_bad" in f.path for f in found), found
    assert not any("deadlock_ok" in f.path for f in found), found


def test_fixture_lost_update_race_found():
    found = _by_rule(_fixture_findings(), "mixed-guard")
    assert any(f.subject == "race_bad.Scheduler.pending" for f in found), found
    assert not any("race_ok" in f.subject for f in found), found


def test_fixture_hot_get_or_create_found():
    found = _by_rule(_fixture_findings(), "get-or-create")
    assert any("hot_bad" in f.subject for f in found), found
    assert not any("hot_ok" in f.subject for f in found), found


def test_fixture_order_cycle_found():
    found = _by_rule(_fixture_findings(), "order-cycle")
    assert any("cycle_bad._lock_a" in f.subject for f in found), found
    assert not any("cycle_ok" in f.subject for f in found), found


def test_fixture_unbounded_blocking_found():
    """Pass 5: the zero-arg get()/wait()/join() shutdown-hang shapes
    are FOUND in blocking_bad; the bounded/annotated twin is silent."""
    found = _by_rule(_fixture_findings(), "unbounded-blocking")
    methods = {(f.path.rsplit("/", 1)[-1], f.subject.rsplit(":", 1)[-1])
               for f in found}
    assert ("blocking_bad.py", "get") in methods, found
    assert ("blocking_bad.py", "wait") in methods, found
    assert ("blocking_bad.py", "join") in methods, found
    assert not any("blocking_ok" in f.path for f in found), found


def test_blocking_skips_bounded_and_operand_calls(tmp_path):
    """str.join(parts) / dict.get(key) / wait(timeout) carry operands
    or bounds — never findings (the rule is the ZERO-arg form)."""
    (tmp_path / "mod.py").write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "        self._t = threading.Thread(target=min)\n"
        "        self._t.start()\n"
        "    def go(self, d, parts):\n"
        "        s = ' '.join(parts)\n"
        "        v = d.get('k')\n"
        "        with self._cond:\n"
        "            self._cond.wait(0.5)\n"
        "        self._t.join(timeout=1.0)\n"
        "        return s, v\n")
    pkg = scan_package(str(tmp_path), pkg_name="fx",
                       repo_root=str(tmp_path))
    findings = run_passes(pkg, AnalyzerConfig())
    assert not _by_rule(findings, "unbounded-blocking"), findings


def test_fixture_invariants_found():
    findings = _fixture_findings()
    ju = _by_rule(findings, "json-unsafe")
    assert any("invariant_bad" in f.path for f in ju), ju
    assert not any("invariant_ok" in f.path for f in ju), ju
    hl = _by_rule(findings, "headline-last")
    assert any("invariant_bad" in f.path for f in hl), hl
    assert not any("invariant_ok" in f.path for f in hl), hl


def test_cli_fails_naming_each_historical_shape(tmp_path, monkeypatch,
                                                capsys):
    """The acceptance demo: re-introducing each historical bug shape in
    a fixture module makes `python -m tools.ckcheck` exit nonzero,
    NAMING the finding — the PR 6 tracer-lock deadlock (reacquire), the
    seed-era lost-update race (mixed-guard), and the hot-path
    get-or-create."""
    import tools.ckcheck.cli as cli

    monkeypatch.setattr(cli, "_repo_extra_paths", lambda: [])
    monkeypatch.setattr(cli, "repo_config", lambda: AnalyzerConfig(
        hot_roots=("hot_bad.Engine.defer", "hot_ok.Engine.defer")))
    rc = cli.main(["--root", FIXTURES,
                   "--baseline", str(tmp_path / "empty.json")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "reacquire" in out and "deadlock_bad" in out
    assert "mixed-guard" in out and "race_bad.Scheduler.pending" in out
    assert "get-or-create" in out and "hot_bad" in out


def test_fixture_suppression_comment_silences(tmp_path):
    bad = open(os.path.join(FIXTURES, "race_bad.py")).read()
    bad = bad.replace(
        "        self.pending = self.pending // 2  # unlocked RMW: lost update",
        "        # ckcheck: ok rebalance runs quiescent in this variant\n"
        "        self.pending = self.pending // 2",
    )
    (tmp_path / "race_bad.py").write_text(bad)
    pkg = scan_package(str(tmp_path), pkg_name="fx", repo_root=str(tmp_path))
    findings = run_passes(pkg, AnalyzerConfig())
    assert not _by_rule(findings, "mixed-guard"), findings


# ---------------------------------------------------------------------------
# 2b. the ratchet lifecycle
# ---------------------------------------------------------------------------

def _mini_repo(tmp_path, planted: bool):
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    body = open(os.path.join(
        FIXTURES, "race_bad.py" if planted else "race_ok.py")).read()
    (d / "mod.py").write_text(body)
    pkg = scan_package(str(d), pkg_name="pkg", repo_root=str(tmp_path))
    return run_passes(pkg, AnalyzerConfig())


def test_ratchet_lifecycle(tmp_path):
    baseline_path = str(tmp_path / "baseline.json")

    # (1) a finding with an empty baseline is NEW -> the run must fail
    findings = _mini_repo(tmp_path, planted=True)
    assert findings
    new, grand, stale = ratchet(findings, load_baseline(baseline_path))
    assert new and not grand and not stale

    # (2) grandfather it; the same findings are now covered
    save_baseline(baseline_path, findings)
    new, grand, stale = ratchet(findings, load_baseline(baseline_path))
    assert not new and grand and not stale

    # (3) fixing the finding WITHOUT shrinking the baseline is stale ->
    # the run must fail until --update-baseline rewrites it
    fixed = _mini_repo(tmp_path, planted=False)
    new, grand, stale = ratchet(fixed, load_baseline(baseline_path))
    assert not new and stale

    # (4) the shrink: rewrite from current findings -> clean
    save_baseline(baseline_path, fixed)
    new, grand, stale = ratchet(fixed, load_baseline(baseline_path))
    assert not new and not grand and not stale


def test_update_baseline_refuses_growth_without_allow_grow(
        tmp_path, monkeypatch, capsys):
    """CLI semantics: --update-baseline with NEW findings refuses unless
    --allow-grow rides along (adding debt is deliberate, never a
    reflex)."""
    import tools.ckcheck.cli as cli

    d = tmp_path / "pkg"
    d.mkdir()
    (d / "mod.py").write_text(
        open(os.path.join(FIXTURES, "race_bad.py")).read())
    monkeypatch.setattr(cli, "_repo_extra_paths", lambda: [])
    monkeypatch.setattr(
        cli, "repo_config", lambda: AnalyzerConfig())
    baseline = str(tmp_path / "b.json")
    args = ["--root", str(d), "--baseline", baseline]

    assert cli.main(args) == 1                       # new finding fails
    assert cli.main(args + ["--update-baseline"]) == 1   # refuses growth
    assert "REFUSING" in capsys.readouterr().out
    assert cli.main(
        args + ["--update-baseline", "--allow-grow"]) == 0
    assert cli.main(args) == 0                       # grandfathered now

    # fingerprints survive line drift: prepend a comment, still clean
    (d / "mod.py").write_text(
        "# an unrelated edit above the finding\n"
        + open(os.path.join(FIXTURES, "race_bad.py")).read())
    assert cli.main(args) == 0


def test_explain_prints_rule_documentation(tmp_path, monkeypatch, capsys):
    import tools.ckcheck.cli as cli

    d = tmp_path / "pkg"
    d.mkdir()
    (d / "mod.py").write_text(
        open(os.path.join(FIXTURES, "race_bad.py")).read())
    monkeypatch.setattr(cli, "_repo_extra_paths", lambda: [])
    monkeypatch.setattr(cli, "repo_config", lambda: AnalyzerConfig())
    baseline = str(tmp_path / "b.json")
    rc = cli.main(["--root", str(d), "--baseline", baseline, "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    fp = json.loads(out)["new"][0]["fingerprint"]
    rc = cli.main(["--root", str(d), "--baseline", baseline,
                   "--explain", fp])
    out = capsys.readouterr().out
    assert rc == 0
    assert "lost update" in out or "read-modify-write" in out


# ---------------------------------------------------------------------------
# 2c. the dynamic lock-order witness
# ---------------------------------------------------------------------------

def test_witness_records_nested_named_acquisitions():
    from tools.ckcheck.witness import Witness, _NamedLock

    w = Witness({})
    a = _NamedLock(threading.Lock(), "pkg.A", w)
    b = _NamedLock(threading.Lock(), "pkg.B", w)
    with a:
        with b:
            pass
    with b:  # second, non-nested acquisition adds no edge
        pass
    assert w.dynamic_edges() == {("pkg.A", "pkg.B")}
    rep = w.report({("pkg.A", "pkg.B"), ("pkg.X", "pkg.Y")})
    assert rep["dynamic_only"] == []
    assert rep["static_only"] == [["pkg.X", "pkg.Y"]]


def test_witness_install_wraps_package_locks():
    from tools.ckcheck.witness import install, _NamedLock

    w = install(os.path.join(ROOT, "cekirdekler_tpu"))
    try:
        from cekirdekler_tpu.metrics.registry import MetricsRegistry

        reg = MetricsRegistry()
        assert isinstance(reg._lock, _NamedLock)
        with reg._lock:
            pass
        # a lock created OUTSIDE the package stays a plain lock
        plain = threading.Lock()
        assert not isinstance(plain, _NamedLock)
        assert "metrics.registry.MetricsRegistry._lock" in \
            w._seen_locks
    finally:
        w.uninstall()


# ---------------------------------------------------------------------------
# 3. regression tests for the live findings fixed in this PR
# ---------------------------------------------------------------------------

class _LockAssertingDict(dict):
    """A bench dict that refuses unlocked writes: every mutation must
    hold the owning worker's RLock (the ckcheck mixed-guard contract)."""

    def __init__(self, lock, *a):
        super().__init__(*a)
        self._lock = lock

    def _check(self):
        assert self._lock._is_owned(), (
            "bench dict written without holding the worker lock")

    def __setitem__(self, k, v):
        self._check()
        super().__setitem__(k, v)

    def update(self, *a, **kw):
        self._check()
        super().update(*a, **kw)


@pytest.fixture(scope="module")
def devs():
    from cekirdekler_tpu.hardware import platforms

    return platforms().cpus()


_INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


def test_bench_dict_writes_hold_worker_lock(devs):
    """PR 7 fix: the barrier's bench feed, the zero-share decay, and the
    flush drain's transfer feed all hold w.lock now — instrumented
    dicts assert it on every write through a real enqueue window."""
    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core import NumberCruncher

    cr = NumberCruncher(devs.subset(2), _INC)
    try:
        for w in cr.cores.workers:
            w.benchmarks = _LockAssertingDict(w.lock, w.benchmarks)
            w.transfer_benchmarks = _LockAssertingDict(
                w.lock, w.transfer_benchmarks)
        x = ClArray(np.zeros(4096, np.float32), name="ck_x")
        x.partial_read = True
        cr.enqueue_mode = True
        for phase in range(3):
            for _ in range(4):
                x.compute(cr, 901, "inc", 4096, 64)
            cr.barrier()          # bench feed must lock
        cr.enqueue_mode = False   # flush: transfer feed must lock
        np.testing.assert_array_equal(np.asarray(x), 12.0)
    finally:
        cr.dispose()


def test_fused_defer_records_no_telemetry_when_disabled(devs):
    """PR 7 hot-path fix: with the tracer off, the deferral must not
    even CALL TRACER.record (the tag concat allocated per deferral
    before the guard)."""
    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core import NumberCruncher
    from cekirdekler_tpu.trace import spans

    calls = []
    orig = spans.TRACER.record
    cr = NumberCruncher(devs.subset(1), _INC)
    try:
        assert not spans.TRACER.enabled
        x = ClArray(np.zeros(1024, np.float32), name="ck_t")
        x.partial_read = True
        cr.enqueue_mode = True
        x.compute(cr, 902, "inc", 1024, 64)  # per-call (engage seed)
        x.compute(cr, 902, "inc", 1024, 64)  # engages
        spans.TRACER.record = lambda *a, **kw: calls.append(a)
        for _ in range(6):                   # pure deferrals
            x.compute(cr, 902, "inc", 1024, 64)
        assert cr.fused_stats["deferred_iters"] >= 6
        assert calls == [], (
            "fused deferral called TRACER.record with the tracer off")
    finally:
        spans.TRACER.record = orig
        cr.enqueue_mode = False
        cr.dispose()


def test_taskpool_feed_does_not_nest_pool_locks():
    """PR 7 deadlock fix: feed() snapshots BEFORE locking, so
    self-feeding (the degenerate same-instance case of the ABBA shape)
    completes instead of deadlocking on the non-reentrant lock."""
    from cekirdekler_tpu.pipeline.pool import ClTask, ClTaskPool

    pool = ClTaskPool([ClTask()])
    done = []

    def run():
        pool.feed(pool)  # pre-fix: self-deadlock, forever
        done.append(len(pool))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=5.0)
    assert done == [2], "feed() still nests ClTaskPool locks"


def test_debug_endpoints_emit_strict_rfc8259_json():
    """PR 7 invariant fix (the generalized /healthz bug): an inf gauge
    anywhere in the registry must come back as null, never as the bare
    `Infinity` token a strict parser rejects."""
    import urllib.request

    from cekirdekler_tpu.metrics import REGISTRY
    from cekirdekler_tpu.obs.debugserver import DebugServer

    g = REGISTRY.gauge("ck_lane_health", "verdict", lane=998877)
    srv = DebugServer(cores=None, port=0)
    try:
        g.set(float("inf"))
        body = urllib.request.urlopen(srv.url + "/flightz").read().decode()

        def reject(_):  # json.loads accepts Infinity unless told not to
            raise AssertionError("non-RFC-8259 constant in payload")

        doc = json.loads(body, parse_constant=reject)
        assert doc["metrics"]["gauges"]['ck_lane_health{lane="998877"}'] \
            is None
    finally:
        g.set(0.0)
        srv.close()


def test_json_safe_sanitizes_everything():
    from cekirdekler_tpu.utils.jsonsafe import dumps_safe, json_safe

    weird = {
        "inf": float("inf"),
        "ninf": float("-inf"),
        "nan": float("nan"),
        "np_scalar": np.float32("inf"),
        "np_int": np.int64(7),
        "np_arr": np.asarray([1.0, float("inf")]),
        np.int32(3): ("tuple", {"nested_nan": float("nan")}),
        "plain": [1, "x", True, None, 2.5],
    }
    out = json_safe(weird)
    assert out["inf"] is None and out["ninf"] is None and out["nan"] is None
    assert out["np_scalar"] is None
    assert out["np_int"] == 7
    assert out["np_arr"] == [1.0, None]
    assert out["3"] == ["tuple", {"nested_nan": None}]
    assert out["plain"] == [1, "x", True, None, 2.5]

    def reject(_):
        raise AssertionError("non-RFC-8259 constant survived json_safe")

    assert json.loads(dumps_safe(weird), parse_constant=reject)
    # cycles degrade to a placeholder instead of recursing forever
    cyc: dict = {}
    cyc["self"] = cyc
    assert json_safe(cyc) == {"self": "<cycle>"}
    # finite floats pass through untouched
    assert json_safe({"x": 1.5}) == {"x": 1.5}
    assert math.isfinite(json.loads(dumps_safe({"v": 2.25}))["v"])


def test_bench_artifact_print_is_strict(capsys):
    """bench.py's one-JSON-line contract survives an inf/numpy payload
    (pre-fix: TypeError killed the artifact or `Infinity` corrupted
    it)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ck_bench_jsontest", os.path.join(ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._print_artifact({
        "value": float("inf"),
        "np": np.float64("nan"),
        "headline": {"k": np.int64(3)},
    })
    out = capsys.readouterr().out.strip()

    def reject(_):
        raise AssertionError("artifact line is not strict JSON")

    doc = json.loads(out, parse_constant=reject)
    assert doc["value"] is None and doc["np"] is None
    assert doc["headline"]["k"] == 3
