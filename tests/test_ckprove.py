"""ckprove — kernel partition-safety & flag-soundness verifier: the
differential-oracle acceptance suite.

Layers:

1. **Differential oracle agreement** — every corpus verdict
   (tests/kernel_corpus.py) is checked against ground truth: each of
   the ≥8 unsafe kernels is caught with its named finding + source
   line AND provably corrupts under a ≥2-lane split (or lies about its
   flags) per the lane simulator; every safe kernel is clean AND
   bit-identical split vs unsplit.  Zero false negatives on the
   corpus, false positives only as advisories.
2. **Runtime gates** — ``CK_KERNEL_VERIFY=strict`` makes
   ``Cores.compute`` raise :class:`KernelVerifyError` with the named
   finding, and serve admission reject with the named
   ``kernel-unsafe`` reason whose decision record replays
   bit-identically through the ``ckreplay verify`` engine.  A real
   2-chip vs 1-chip run anchors the simulator to the actual machine.
3. **CLI lifecycle** — ``python -m tools.ckprove`` exits 0 on HEAD
   against the checked-in baseline; new findings fail;
   ``--update-baseline`` refuses growth without ``--allow-grow``;
   ``// ckprove: ok`` suppresses; the docs' verdict table matches
   :data:`VERDICT_KINDS` (the lint_obs two-way discipline).
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from cekirdekler_tpu import ClArray, analysis  # noqa: E402
from cekirdekler_tpu.core.cruncher import NumberCruncher  # noqa: E402
from cekirdekler_tpu.errors import KernelVerifyError  # noqa: E402
from cekirdekler_tpu.hardware import platforms  # noqa: E402
from tests.kernel_corpus import (  # noqa: E402
    CORPUS,
    SAFE,
    UNSAFE,
    build,
    ground_truth_unsafe,
    run_lanes,
    verdict_for,
)

import tools.ckprove as ckprove  # noqa: E402


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


# ---------------------------------------------------------------------------
# 1. the differential oracle
# ---------------------------------------------------------------------------

def test_corpus_shape():
    """The acceptance floor: ≥20 kernels, ≥8 deliberately unsafe."""
    assert len(CORPUS) >= 20
    assert len(UNSAFE) >= 8


@pytest.mark.parametrize("entry", CORPUS, ids=lambda e: e.name)
def test_verdict_matches_differential_oracle(entry):
    """Per kernel: the verifier's error kinds are exactly the declared
    expectation, each finding carries a real source line, and the
    split-vs-unsplit oracle confirms the verdict bit-exactly."""
    v = verdict_for(entry)
    kinds = {f.kind for f in v.errors}
    assert set(entry.expect) <= kinds, (
        f"{entry.name}: expected {entry.expect}, verifier found {kinds}")
    assert bool(kinds) == bool(entry.expect), (
        f"{entry.name}: unexpected error kinds {kinds - set(entry.expect)}")
    for f in v.errors:
        assert f.line > 0, f"{entry.name}: finding without a source line"
        assert f.kernel, f
    assert ground_truth_unsafe(entry) == bool(entry.expect), (
        f"{entry.name}: differential oracle disagrees with the verdict")


def test_zero_false_negatives_across_corpus():
    """THE contract: no kernel the oracle proves unsafe escapes with a
    clean verdict — at 2 AND 3 lanes."""
    for entry in CORPUS:
        for lanes in (2, 3):
            if ground_truth_unsafe(entry, lanes=lanes):
                assert not verdict_for(entry).ok, (
                    f"FALSE NEGATIVE: {entry.name} corrupts at "
                    f"{lanes} lanes but the verifier passed it")


def test_false_positives_only_as_advisories():
    """A clean-by-oracle kernel may collect advisories (partial-safe,
    unread-upload) but never an error-severity finding."""
    for entry in SAFE:
        v = verdict_for(entry)
        assert v.ok, (
            f"FALSE POSITIVE: {entry.name} is oracle-clean but got "
            f"errors {[f.kind for f in v.errors]}")


def test_suppression_comment_silences_finding():
    from tests.kernel_corpus import CorpusKernel

    entry = CorpusKernel(
        "halo_suppressed", """
__kernel void sh(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i+1];  // ckprove: ok halo is caller-padded in this app
}""", (dict(partial_read=True, read_only=True),
       dict(partial_read=True, write_only=True)))
    assert verdict_for(entry).ok


def test_partial_safe_advisory_names_free_h2d():
    """An over-broad full read on a gid-confined access surfaces as
    the partial-safe advisory (the satellite-fix detector)."""
    from tests.kernel_corpus import CorpusKernel

    entry = CorpusKernel(
        "overbroad", """
__kernel void ob(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i] * 2.0f;
}""", (dict(read_only=True), dict(partial_read=True, write_only=True)))
    v = verdict_for(entry)
    assert v.ok
    assert any(f.kind == "partial-safe" and f.param == "x"
               for f in v.advisories)


# ---------------------------------------------------------------------------
# 2. runtime gates
# ---------------------------------------------------------------------------

_HALO_SRC = """
__kernel void sh(__global float* x, __global float* y) {
    int i = get_global_id(0);
    y[i] = x[i+1] + x[i];
}
"""

_SAXPY_SRC = """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = a * x[i] + y[i];
}
"""


def _halo_args(n=256):
    x = ClArray(np.arange(n, dtype=np.float32), name="vx",
                partial_read=True, read_only=True)
    y = ClArray(n, np.float32, name="vy", partial_read=True)
    return x, y


def test_strict_gate_raises_named_finding(devs, monkeypatch):
    monkeypatch.setenv("CK_KERNEL_VERIFY", "strict")
    cr = NumberCruncher(devs.subset(2), _HALO_SRC)
    try:
        x, y = _halo_args()
        with pytest.raises(KernelVerifyError) as ei:
            x.next_param(y).compute(cr, 70, "sh", 256, 32)
        assert ei.value.finding.kind == "partial-read-halo"
        assert ei.value.finding.line == 4
        assert "partial-read-halo" in str(ei.value)
    finally:
        cr.dispose()


def test_advisory_default_computes_and_flight_records(devs, monkeypatch):
    """Advisory (default) mode: the unsafe launch still runs (legacy
    behavior preserved) but the flight ring records the named finding
    ONCE per launch shape."""
    from cekirdekler_tpu.obs.flight import FLIGHT

    monkeypatch.delenv("CK_KERNEL_VERIFY", raising=False)
    cr = NumberCruncher(devs.subset(2), _HALO_SRC)
    try:
        x, y = _halo_args()
        for _ in range(3):
            x.next_param(y).compute(cr, 71, "sh", 256, 32)
        evs = [e for e in FLIGHT.snapshot()
               if e.kind == "kernel-verify"
               and e.fields.get("kernels") == "sh"]
        assert len(evs) == 1, evs
        assert evs[0].fields["finding"] == "partial-read-halo"
    finally:
        cr.dispose()


def test_verify_off_skips_gate(devs, monkeypatch):
    monkeypatch.setenv("CK_KERNEL_VERIFY", "off")
    cr = NumberCruncher(devs.subset(2), _HALO_SRC)
    try:
        x, y = _halo_args()
        x.next_param(y).compute(cr, 72, "sh", 256, 32)
        assert not cr.cores.program._verdict_cache
    finally:
        cr.dispose()


def test_real_split_anchors_the_simulator(devs):
    """The lane simulator's verdicts hold on the REAL machine: the
    halo-under-partial kernel diverges 2-chip vs 1-chip bit-exactly
    where the simulator says it does, and the safe saxpy is
    bit-identical."""
    n = 256
    results = {}
    for lanes in (1, 2):
        cr = NumberCruncher(devs.subset(lanes), _HALO_SRC)
        try:
            x, y = _halo_args(n)
            x.next_param(y).compute(cr, 73, "sh", n, 32)
            results[lanes] = np.array(y, copy=True)
        finally:
            cr.dispose()
    assert not np.array_equal(results[1], results[2]), (
        "halo-under-partial should corrupt on a real 2-chip split")
    # and the simulator predicts the same divergence pattern
    from tests.kernel_corpus import UNSAFE

    entry = next(e for e in UNSAFE if e.name == "halo_partial")
    assert ground_truth_unsafe(entry, lanes=2)

    safe = {}
    for lanes in (1, 2):
        cr = NumberCruncher(devs.subset(lanes), _SAXPY_SRC)
        try:
            x, y = _halo_args(n)
            x.next_param(y).compute(cr, 74, "saxpy", n, 32, values=(1.5,))
            safe[lanes] = np.array(y, copy=True)
        finally:
            cr.dispose()
    np.testing.assert_array_equal(safe[1], safe[2])


def test_partial_read_fix_is_bit_identical(devs):
    """Satellite pin (workloads.marker_overhead flag fix): the saxpy
    input under partial_read produces bit-identical results to the
    over-broad full read on a real 2-chip split — the H2D saving is
    free."""
    n = 256
    out = {}
    for label, kw in (("full", dict(read_only=True)),
                      ("partial", dict(partial_read=True, read_only=True))):
        cr = NumberCruncher(devs.subset(2), _SAXPY_SRC)
        try:
            x = ClArray(np.arange(n, dtype=np.float32), name="px", **kw)
            y = ClArray(n, np.float32, name="py", partial_read=True)
            x.next_param(y).compute(cr, 75, "saxpy", n, 32, values=(2.0,))
            out[label] = np.array(y, copy=True)
        finally:
            cr.dispose()
    np.testing.assert_array_equal(out["full"], out["partial"])


def test_program_verdict_is_cached_per_shape(devs):
    from cekirdekler_tpu.analysis import flag_row
    from cekirdekler_tpu.kernel.registry import KernelProgram

    prog = KernelProgram(_HALO_SRC)
    x, y = _halo_args()
    rows = (flag_row(x.flags), flag_row(y.flags))
    v1 = prog.verify(("sh",), rows)
    v2 = prog.verify(("sh",), rows)
    assert v1 is v2
    assert [f.kind for f in v1.errors] == ["partial-read-halo"]


def test_serve_strict_rejects_and_replays(devs, monkeypatch):
    """Acceptance: under strict verification, serve admission rejects
    the unsafe job with the named ``kernel-unsafe`` reason, records
    the verdict inputs in the replayable admission decision, and the
    rejection replays bit-identically through the ckreplay-verify
    engine."""
    from cekirdekler_tpu.obs.decisions import DECISIONS
    from cekirdekler_tpu.obs.replay import verify_records
    from cekirdekler_tpu.serve.admission import REJECT_KERNEL, ServeRejected
    from cekirdekler_tpu.serve.frontend import ServeFrontend, ServeJob

    monkeypatch.setenv("CK_KERNEL_VERIFY", "strict")
    cr = NumberCruncher(devs.subset(2), _HALO_SRC)
    fe = ServeFrontend(cr, autostart=False)
    try:
        mark = max((r.seq for r in DECISIONS.snapshot()), default=0)
        x, y = _halo_args()
        job = ServeJob(params=[x, y], kernels=("sh",), compute_id=76,
                       global_range=256, local_range=32)
        with pytest.raises(ServeRejected) as ei:
            fe.submit("tenant-a", job)
        assert ei.value.reason == REJECT_KERNEL
        assert ei.value.retry_after_s == 0.0
        recs = [r for r in DECISIONS.snapshot()
                if r.seq > mark and r.kind == "admission"]
        assert recs, "no admission decision recorded"
        rec = recs[-1]
        assert rec.inputs["kernel_unsafe"] is True
        assert rec.inputs["kernel_finding"] == "partial-read-halo"
        assert rec.outputs["reason"] == REJECT_KERNEL
        rep = verify_records(recs)
        assert rep["ok"], rep
        assert rep["replayed"] >= 1
    finally:
        fe.close()
        cr.dispose()


def test_serve_default_mode_admits(devs, monkeypatch):
    """Without strict verification the frontend admits (legacy
    behavior): the kernel gate is opt-in at the serving tier."""
    from cekirdekler_tpu.serve.frontend import ServeFrontend, ServeJob

    monkeypatch.delenv("CK_KERNEL_VERIFY", raising=False)
    cr = NumberCruncher(devs.subset(2), _HALO_SRC)
    fe = ServeFrontend(cr, autostart=False)
    try:
        x, y = _halo_args()
        job = ServeJob(params=[x, y], kernels=("sh",), compute_id=77,
                       global_range=256, local_range=32)
        fut = fe.submit("tenant-b", job)
        fe.step()
        assert fut.result(timeout=10.0)["tenant"] == "tenant-b"
    finally:
        fe.close()
        cr.dispose()


# ---------------------------------------------------------------------------
# 3. CLI lifecycle
# ---------------------------------------------------------------------------

def test_cli_clean_on_head(capsys):
    """THE gate: ckprove exits 0 on HEAD against the checked-in
    baseline — a new split-unsafe kernel anywhere in the scanned
    corpus fails tier-1 right here."""
    rc = ckprove.main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "clean" in out


def test_cli_scan_finds_the_repo_kernels():
    """A scan that silently matched nothing would make the gate
    vacuous: the known workload kernels must be inventoried."""
    found = {(rel, src.count("__kernel"))
             for rel, _line, src in ckprove.iter_kernel_sources()}
    names = {rel for rel, _ in found}
    assert any("workloads.py" in p for p in names)
    assert any("examples/" in p or "examples\\" in p for p in names)
    _findings, facts = ckprove.analyze_corpus()
    kernels = {r["kernel"] for r in facts if "arrays" in r}
    assert {"mandelbrot", "nBody", "streamAdd", "wave"} <= kernels


def _corpus_repo(tmp_path, planted: bool):
    d = tmp_path / "repo"
    (d / "cekirdekler_tpu").mkdir(parents=True, exist_ok=True)
    body = (
        'SRC = """\n'
        "__kernel void k(__global float* x, __global float* out) {\n"
        "    int i = get_global_id(0);\n"
        + ("    out[i+1] = x[i];\n" if planted else "    out[i] = x[i];\n")
        + '}\n"""\n'
    )
    (d / "cekirdekler_tpu" / "mod.py").write_text(body)
    return str(d)


def test_cli_ratchet_lifecycle(tmp_path, capsys):
    baseline = str(tmp_path / "b.json")
    root = _corpus_repo(tmp_path, planted=True)
    args = ["--root", root, "--baseline", baseline]

    # (1) new finding fails, naming the kind
    assert ckprove.main(args) == 1
    out = capsys.readouterr().out
    assert "off-partition-write" in out

    # (2) --update-baseline refuses growth without --allow-grow
    assert ckprove.main(args + ["--update-baseline"]) == 1
    assert "REFUSING" in capsys.readouterr().out
    assert ckprove.main(
        args + ["--update-baseline", "--allow-grow"]) == 0
    capsys.readouterr()
    assert ckprove.main(args) == 0  # grandfathered
    capsys.readouterr()

    # (3) --explain renders the rule documentation
    rc = ckprove.main(args + ["--json"])
    doc = json.loads(capsys.readouterr().out)
    fp = doc["grandfathered"][0]["fingerprint"]
    assert rc == 0
    assert ckprove.main(args + ["--explain", fp]) == 0
    assert "partition" in capsys.readouterr().out

    # (4) fixing without shrinking the baseline is stale -> fail
    _corpus_repo(tmp_path, planted=False)
    assert ckprove.main(args) == 1
    assert "STALE" in capsys.readouterr().out

    # (5) the shrink: clean again
    assert ckprove.main(args + ["--update-baseline"]) == 0
    capsys.readouterr()
    assert ckprove.main(args) == 0


def test_cli_source_suppression(tmp_path, capsys):
    baseline = str(tmp_path / "b.json")
    root = _corpus_repo(tmp_path, planted=True)
    mod = os.path.join(root, "cekirdekler_tpu", "mod.py")
    body = open(mod).read().replace(
        "out[i+1] = x[i];",
        "out[i+1] = x[i];  // ckprove: ok ghost cell, range excludes tail")
    open(mod, "w").write(body)
    assert ckprove.main(["--root", root, "--baseline", baseline]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_runs_without_jax(tmp_path):
    """The run-anywhere discipline: the CLI completes on a rig where
    importing jax raises (the stub package loader path)."""
    import subprocess

    script = (
        "import sys\n"
        "class B:\n"
        "    def find_module(self, name, path=None):\n"
        "        if name=='jax' or name.startswith('jax.'): return self\n"
        "    def load_module(self, name):\n"
        "        raise ImportError('jax broken')\n"
        "sys.meta_path.insert(0, B())\n"
        f"sys.path.insert(0, {ROOT!r})\n"
        "import tools.ckprove as ck\n"
        "sys.exit(ck.main([]))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_doc_verdict_table_matches_declared_kinds():
    """lint_obs-style two-way check: the verdict table in
    docs/STATIC_ANALYSIS.md lists exactly the declared VERDICT_KINDS —
    a new kind must be documented, a removed one un-documented."""
    doc = ckprove.doc_verdict_kinds()
    assert doc == set(analysis.VERDICT_KINDS), (
        f"doc-only: {doc - set(analysis.VERDICT_KINDS)}, "
        f"code-only: {set(analysis.VERDICT_KINDS) - doc}")


def test_doc_flag_table_matches_flag_row():
    """docs/KERNEL_LANGUAGE.md's flag-soundness table covers every
    flag the verdict reads (FlagRow fields)."""
    text = open(os.path.join(ROOT, "docs", "KERNEL_LANGUAGE.md")).read()
    for fld in analysis.verdict.FlagRow._fields:
        name = ("elements_per_work_item" if fld == "epw" else fld)
        assert f"`{name}`" in text, (
            f"flag {name!r} missing from the KERNEL_LANGUAGE.md "
            "flag-soundness table")
