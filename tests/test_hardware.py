"""Hardware query DSL tests (reference behavior: ClObjectApi.cs selection
semantics — copies on select, + concat dedupe, filters)."""

import numpy as np
import pytest

from cekirdekler_tpu.hardware import (
    AcceleratorType,
    Devices,
    Platforms,
    all_devices,
    devices_for_type,
    platforms,
)
from cekirdekler_tpu.errors import DeviceSelectionError


def test_platforms_enumerate():
    plats = Platforms.all()
    assert len(plats) >= 1
    names = [p.name for p in plats]
    assert "cpu" in names


def test_cpu_devices_present_in_rig(cpu_devices):
    devs = platforms().cpus()
    assert len(devs) >= 8


def test_indexing_returns_copies():
    devs = platforms().cpus()
    a = devs[0]
    b = devs[0]
    assert a is not b
    assert a.jax_device is b.jax_device


def test_concat_dedupes():
    devs = platforms().cpus()
    both = devs + devs
    assert len(both) == len(devs)


def test_subset_and_slice():
    devs = platforms().cpus()
    assert len(devs.subset(3)) == 3
    assert len(devs[1:4]) == 3


def test_filters():
    devs = all_devices()
    cpus = devs.cpus()
    assert all(d.is_cpu for d in cpus)
    shared = cpus.with_host_memory_sharing()
    assert len(shared) == len(cpus)  # CPU devices share host memory
    assert len(cpus.with_dedicated_memory()) == 0


def test_with_most_compute_units_nonempty():
    devs = platforms().cpus()
    best = devs.with_most_compute_units()
    assert len(best) >= 1


def test_devices_for_type_cpu():
    devs = devices_for_type(AcceleratorType.CPU)
    assert len(devs) >= 8
    devs2 = devices_for_type(AcceleratorType.CPU, max_devices=2)
    assert len(devs2) == 2


def test_devices_for_type_no_match_raises():
    with pytest.raises(DeviceSelectionError):
        Devices([]).require_nonempty("empty")


def test_log_info_runs():
    text = platforms().log_info()
    assert "cpu" in text
    dtext = platforms().cpus().subset(1).log_info()
    assert "Device:" in dtext
