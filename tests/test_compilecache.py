"""Persistent executable cache + AOT warmup (`core/compilecache.py`,
ISSUE 18).

The pins, in the order the autoscale story needs them:

- **warmup = live key**: after `Cores.warmup` / `ServeFrontend.warmup`
  the FIRST live fused call compiles nothing (`fused_compiled_count`
  AND `compiled_count` flat) and warmup never touches the jobs' arrays
  (scratch buffers only).
- **cross-process**: process A populates the cache through the LIVE
  engage-time recorder; process B (a cold `tests/_cache_worker.py`
  interpreter) replays `warm_from_disk` and its first live batch
  compiles nothing — the kill-cold-start acceptance.
- **degradation**: torn manifest rows and corrupt entry payloads are
  NAMED misses, never exceptions; concurrent writers converge; an
  unset `CK_COMPILE_CACHE` and every miss path are bit-invisible
  (results pinned fused on AND off, cache off/on/warm).
- **operator surface**: `tools/ckcache.py` ls/stats/prune/--verify and
  the `tools/coldstart.py` cold/populate/warm trio smoke in-tree.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import types

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.core.compilecache import (
    CACHE,
    CACHE_ENV,
    CompileCache,
    WarmupSpec,
    program_fingerprint,
    warm_from_disk,
)
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.serve import ServeFabric, ServeFrontend, ServeJob

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

SRC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
__kernel void dbl(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] * 1.001f;
}
"""

N, LR = 1024, 64


def _load_tool(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


@pytest.fixture()
def cache_root(tmp_path, monkeypatch):
    """Arm the process-wide CACHE singleton at a fresh root; disarm on
    teardown so the suite's other tests never write XLA cache files."""
    root = str(tmp_path / "cache")
    monkeypatch.setenv(CACHE_ENV, root)
    CACHE._seen.clear()
    CACHE.miss_reasons.clear()
    yield root
    CACHE._seen.clear()
    CACHE._armed_dir = None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001 - knob absent on this jax
        pass


def _fused_batch(cr, arr, cid, iters, kernel="inc"):
    cr.enqueue_mode = True
    cr.cores.compute_fused_batch([kernel], [arr], cid, arr.size, LR, iters)
    cr.cores.barrier()
    cr.cores.flush()
    cr.enqueue_mode = False


def _spec(kernels=("inc",), n=N, lr=LR, values=()):
    return WarmupSpec(kernels=tuple(kernels), params=((n, "float32"),),
                      global_range=n, local_range=lr, values=values)


# ---------------------------------------------------------------------------
# warmup key = live key (the satellite-1 compile-counter pins)
# ---------------------------------------------------------------------------

def test_cores_warmup_then_first_live_fused_call_is_hit(devs, cache_root):
    cr = NumberCruncher(devs.subset(1), SRC)
    try:
        out = cr.cores.warmup([_spec()])
        assert out["warmed"] == 1 and out["skipped"] == 0
        assert out["misses"] == 1 and out["hits"] == 0  # cold cache
        prog = cr.cores.program
        before = (prog.fused_compiled_count, prog.compiled_count)
        assert before[0] >= 1  # warmup really built the ladder
        x = ClArray(np.zeros(N, np.float32), name="cw")
        x.partial_read = True
        _fused_batch(cr, x, 7300, 5)
        np.testing.assert_array_equal(np.asarray(x), 5.0)
        # the acceptance pin: the first live call after warmup compiles
        # NOTHING — neither the fused ladder nor a per-call chunk
        assert (prog.fused_compiled_count, prog.compiled_count) == before
        # and the warmed entry is now on disk for other processes
        cache = CompileCache(root=cache_root)
        assert len(cache.load_specs()) == 1
        assert cache.stats()["write"] >= 1
    finally:
        cr.dispose()


def test_cores_warmup_without_cache_env_still_precompiles(devs,
                                                          monkeypatch):
    monkeypatch.delenv(CACHE_ENV, raising=False)
    assert not CACHE.enabled
    cr = NumberCruncher(devs.subset(1), SRC)
    try:
        out = cr.cores.warmup([_spec()])
        assert out["warmed"] == 1
        assert out["hits"] == 0 and out["misses"] == 0  # no cache layer
        prog = cr.cores.program
        before = (prog.fused_compiled_count, prog.compiled_count)
        x = ClArray(np.zeros(N, np.float32), name="nc")
        x.partial_read = True
        _fused_batch(cr, x, 7301, 4)
        np.testing.assert_array_equal(np.asarray(x), 4.0)
        assert (prog.fused_compiled_count, prog.compiled_count) == before
    finally:
        cr.dispose()


def test_frontend_warmup_matches_live_key_and_never_mutates(devs):
    cr = NumberCruncher(devs.subset(1), SRC)
    fe = ServeFrontend(cr, autostart=False, name="warmkeys")
    try:
        a = ClArray(np.zeros(N, np.float32), name="wk")
        a.partial_read = True
        job = ServeJob(params=[a], kernels=["inc"], compute_id=7302,
                       global_range=N, local_range=LR)
        out = fe.warmup([job])
        assert out["warmed"] == 1
        # scratch buffers only: the job's live array is untouched
        assert np.all(np.asarray(a) == 0.0)
        prog = cr.cores.program
        before = (prog.fused_compiled_count, prog.compiled_count)
        futs = [fe.submit("t0", job) for _ in range(8)]
        fe.step()
        for f in futs:
            f.result(timeout=30)
        np.testing.assert_array_equal(np.asarray(a), 8.0)
        assert (prog.fused_compiled_count, prog.compiled_count) == before
    finally:
        fe.close()
        cr.dispose()


def test_fabric_add_member_zero_fresh_compiles_when_cache_holds_mix(
        devs, cache_root):
    """The warm-on-join acceptance: live traffic persists the fleet's
    signature mix (engage-time recorder), so a joining member's warmup
    is ALL disk hits — zero fresh ladder compiles — and its first live
    batch after the join compiles nothing either."""
    crunchers = {m: NumberCruncher(devs.subset(1), SRC)
                 for m in ("m0", "m1")}
    fab = ServeFabric(crunchers, autostart=False, gather_window_s=0.0,
                      max_batch=64)
    a = ClArray(np.zeros(N, np.float32), name="fz")
    a.partial_read = True
    job = ServeJob(params=[a], kernels=["inc"], compute_id=9300,
                   global_range=N, local_range=LR)
    try:
        futs = [fab.submit("t0", job) for _ in range(6)]
        for _ in range(40):
            fab.step()
            if all(f.done() for f in futs):
                break
        assert np.all(np.asarray(a) == 6.0)
        cache = CompileCache(root=cache_root)
        assert cache.stats()["write"] >= 1  # the engage recorder fired
        before = cache.stats()
        fab.add_member("m2", NumberCruncher(devs.subset(1), SRC), step=1)
        after = cache.stats()
        assert after["miss"] == before["miss"]  # ZERO fresh compiles
        assert after["hit"] > before["hit"]
        # the joined shard's first live batch compiles nothing
        fe2 = fab.shards["m2"]
        prog2 = fe2.cores.program
        warmed = (prog2.fused_compiled_count, prog2.compiled_count)
        b = ClArray(np.zeros(N, np.float32), name="fz2")
        b.partial_read = True
        cr2 = fe2.cruncher
        cr2.enqueue_mode = True
        fe2.cores.compute_fused_batch(["inc"], [b], 9300, N, LR, 4)
        fe2.cores.barrier()
        fe2.cores.flush()
        cr2.enqueue_mode = False
        np.testing.assert_array_equal(np.asarray(b), 4.0)
        assert (prog2.fused_compiled_count, prog2.compiled_count) == warmed
    finally:
        fab.close()


# ---------------------------------------------------------------------------
# cross-process: populate cold, hit cold (tests/_cache_worker.py)
# ---------------------------------------------------------------------------

def _worker(env):
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, "_cache_worker.py")],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env)


def _rpc(proc, obj, timeout=120.0):
    proc.stdin.write(json.dumps(obj) + "\n")
    proc.stdin.flush()
    line = proc.stdout.readline()
    assert line, f"worker died: {proc.stderr.read()[-800:]}"
    return json.loads(line)


def test_cross_process_populate_then_cold_process_hits(cache_root):
    env = os.environ.copy()
    env[CACHE_ENV] = cache_root
    env.setdefault("JAX_PLATFORMS", "cpu")
    batch = {"op": "batch", "n": N, "lr": LR, "iters": 4, "scale": 1.0}
    a = _worker(env)
    try:
        ready = json.loads(a.stdout.readline())
        assert ready["op"] == "ready" and ready["cache"] is True
        done = _rpc(a, batch)
        assert done["op"] == "done"
        assert done["fused_compiles"] >= 1  # A was genuinely cold
        assert done["value"] == 4.0 and done["uniform"]
        stats = _rpc(a, {"op": "stats"})["stats"]
        assert stats["write"] >= 1 and stats["entries"] >= 1
        _rpc(a, {"op": "exit"})
    finally:
        a.kill()
        a.wait()
    b = _worker(env)
    try:
        assert json.loads(b.stdout.readline())["op"] == "ready"
        warmed = _rpc(b, {"op": "warm_disk"})
        assert warmed["warmed"] >= 1
        assert warmed["hits"] >= 1 and warmed["misses"] == 0
        done = _rpc(b, batch)
        # the kill-cold-start pin: B's first live batch compiles NOTHING
        assert done["fused_compiles"] == 0 and done["call_compiles"] == 0
        assert done["value"] == 4.0 and done["uniform"]  # bit-identical
        _rpc(b, {"op": "exit"})
    finally:
        b.kill()
        b.wait()


# ---------------------------------------------------------------------------
# degradation: torn rows, corrupt payloads, racing writers, LRU cap
# ---------------------------------------------------------------------------

def _fake_program():
    return types.SimpleNamespace(source=SRC, _py_kernels={})


def _record_n(cache, count):
    prog = _fake_program()
    keys = []
    for i in range(count):
        spec = _spec(n=N * (i + 1))
        key = cache.ladder_key(prog, spec, "cpu", False, "cpu")
        cache.record(key, spec, "cpu", False, "cpu")
        keys.append(key)
    return keys


def test_torn_manifest_row_and_corrupt_entry_are_named_misses(cache_root):
    cache = CompileCache(root=cache_root)
    keys = _record_n(cache, 2)
    rows = cache.manifest_rows()
    assert len(rows) == 2
    # a crashed writer's torn half-row: skipped with a named reason
    with open(cache._manifest(), "a") as f:
        f.write('{"op": "write", "key": "tor')
    assert len(cache.manifest_rows()) == 2  # parseable rows survive
    assert cache.stats()["entries"] == 2  # stats never raises
    # a corrupt entry payload: lookup degrades to a NAMED miss
    bad = os.path.join(cache._entries_dir(), keys[0] + ".json")
    with open(bad, "w") as f:
        f.write("{this is not json")
    assert cache.lookup(keys[0]) is False
    assert cache.miss_reasons.get("corrupt-entry", 0) >= 1
    assert cache.lookup(keys[1]) is True  # neighbors unharmed
    # load_specs skips the corrupt entry, returns the good one
    assert [k for k, _s in cache.load_specs()] == [keys[1]]
    # verify names the corrupt key
    v = cache.verify()
    assert keys[0] in v["corrupt"] and keys[1] in v["ok"]
    # an absent key is the OTHER named miss
    assert cache.lookup("0" * 32) is False
    assert cache.miss_reasons.get("absent", 0) >= 1


def test_concurrent_writers_converge(cache_root):
    cache = CompileCache(root=cache_root)
    prog = _fake_program()
    specs = [_spec(n=N * (i + 1)) for i in range(4)]
    keys = [cache.ladder_key(prog, s, "cpu", False, "cpu") for s in specs]
    errors = []

    def writer(tid):
        try:
            for _ in range(10):
                for key, spec in zip(keys, specs):
                    cache.record(key, spec, "cpu", False, "cpu")
        except Exception as exc:  # noqa: BLE001 - the failure under test
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    # every entry is well-formed, every manifest row parseable
    assert sorted(k for k, _s in cache.load_specs()) == sorted(keys)
    assert cache.verify()["corrupt"] == []
    assert len(cache.manifest_rows()) >= 6 * 10 * len(keys)


def test_lru_prune_evicts_oldest_to_cap(cache_root):
    cache = CompileCache(root=cache_root)
    keys = _record_n(cache, 5)
    edir = cache._entries_dir()
    for i, key in enumerate(keys):  # deterministic LRU order
        os.utime(os.path.join(edir, key + ".json"), (1000 + i, 1000 + i))
    total = cache.total_bytes()
    assert total > 0
    evicted = cache.prune(max_bytes=total // 2)
    assert evicted >= 1
    assert cache.total_bytes() <= total // 2
    left = {k for k, _s in cache.load_specs()}
    assert keys[-1] in left and keys[0] not in left  # oldest went first
    assert cache.stats()["evict"] >= evicted
    assert os.path.exists(cache._manifest())  # the manifest never evicts


def test_spec_roundtrip_values_hashable_and_key_stable(cache_root):
    cache = CompileCache(root=cache_root)
    prog = _fake_program()
    job_param = types.SimpleNamespace(size=N, dtype="float32")
    spec = WarmupSpec.from_job(["inc"], [job_param], 7, N, LR, 0,
                               {"inc": (N, 0.0001)})
    rt = WarmupSpec.from_payload(json.loads(json.dumps(spec.to_payload())))
    assert rt == spec
    hash(rt)  # deep-frozen: dedup sets and dataclass hashing both work
    k1 = cache.ladder_key(prog, spec, "cpu", False, "cpu")
    k2 = cache.ladder_key(prog, rt, "cpu", False, "cpu")
    assert k1 == k2  # JSON round-trip cannot split the key
    # compute_id is a runtime scalar, never a key component
    other_cid = WarmupSpec.from_job(["inc"], [job_param], 99, N, LR, 0,
                                    {"inc": (N, 0.0001)})
    assert cache.ladder_key(prog, other_cid, "cpu", False, "cpu") == k1
    # a program-source change IS a key change
    prog2 = types.SimpleNamespace(source=SRC + "\n", _py_kernels={})
    assert cache.ladder_key(prog2, spec, "cpu", False, "cpu") != k1
    assert program_fingerprint(prog) != program_fingerprint(prog2)


def test_cache_is_bit_invisible_fused_on_and_off(devs, tmp_path,
                                                 monkeypatch):
    """The degradation acceptance: unset env, cold cache, warm cache —
    all bit-identical, on the fused path AND the per-call fallback
    (dbl's `*1.001f` makes any drift float-visible)."""
    root = str(tmp_path / "bitcache")
    rng = np.random.default_rng(7)
    seed = rng.standard_normal(N).astype(np.float32)
    images = {}
    for mode in ("env-off", "cache-cold", "cache-warm"):
        if mode == "env-off":
            monkeypatch.delenv(CACHE_ENV, raising=False)
        else:
            monkeypatch.setenv(CACHE_ENV, root)
        CACHE._seen.clear()
        for fused in (True, False):
            cr = NumberCruncher(devs.subset(1), SRC)
            try:
                if mode == "cache-warm":
                    warm_from_disk(cr.cores)
                cr.fused_dispatch = fused
                x = ClArray(seed.copy(), name=f"bi-{mode}-{fused}")
                x.partial_read = True
                _fused_batch(cr, x, 7400, 6, kernel="dbl")
                images[(mode, fused)] = np.asarray(x).copy()
            finally:
                cr.dispose()
    ref = images[("env-off", True)]
    for key, img in images.items():
        np.testing.assert_array_equal(img, ref, err_msg=str(key))
    CACHE._seen.clear()
    CACHE._armed_dir = None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001 - knob absent on this jax
        pass


# ---------------------------------------------------------------------------
# operator surface: tools/ckcache.py + tools/coldstart.py
# ---------------------------------------------------------------------------

ckcache = _load_tool("ck_cache_cli", "tools/ckcache.py")
coldstart = _load_tool("ck_coldstart_tool", "tools/coldstart.py")


def test_ckcache_cli_ls_stats_prune_verify(cache_root, capsys):
    cache = CompileCache(root=cache_root)
    keys = _record_n(cache, 3)
    assert ckcache.main(["ls", "--root", cache_root]) == 0
    assert "3 entries" in capsys.readouterr().out
    assert ckcache.main(["stats", "--root", cache_root, "--json"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["entries"] == 3 and stats["write"] == 3
    assert ckcache.main(["--verify", "--root", cache_root]) == 0
    capsys.readouterr()
    # corrupt one entry: --verify fails the exit code and names it
    with open(os.path.join(cache._entries_dir(), keys[0] + ".json"),
              "w") as f:
        f.write("garbage")
    assert ckcache.main(["--verify", "--root", cache_root]) == 1
    assert keys[0] in capsys.readouterr().out
    # prune to zero cap: everything LRU-evicts, stats still works
    assert ckcache.main(["prune", "--root", cache_root,
                         "--max-mb", "0"]) == 0
    capsys.readouterr()
    assert ckcache.main(["stats", "--root", cache_root, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["entries"] == 0


def test_ckcache_cli_without_root_exits_2(monkeypatch, capsys):
    monkeypatch.delenv(CACHE_ENV, raising=False)
    assert ckcache.main(["stats"]) == 2
    capsys.readouterr()


def test_coldstart_trio_smoke(tmp_path):
    """The bench section's unit: tiny cold/populate/warm subprocess trio
    — exactness and the warm child's all-hits warmup are deterministic
    pins; the speedup magnitude is the bench's job, not this test's."""
    out = coldstart._trio("nbody", str(tmp_path), 512, 64, 2, 64)
    assert out["cold"].get("error") is None
    assert out["warm"].get("error") is None
    assert out["exact"] is True
    assert out["warm"]["warm"]["hits"] >= 1
    assert out["warm"]["warm"]["misses"] == 0
    assert out["warm_speedup"] is not None and out["warm_speedup"] > 0


def test_coldstart_section_shape(tmp_path):
    """coldstart_section carries the watched key + the resilience
    rider without re-running anything resilience-shaped."""
    sec = coldstart.coldstart_section(
        None, resilience={"rejoin_converge_iters": 3, "exact": True},
        n=512, local_range=64, iters=2, include_flash=False,
        cache_root=str(tmp_path))
    assert sec["rejoin_converge_iters"] == 3
    assert "cold_start_warm_speedup" in sec
    assert sec["flash"] == {"skipped": "disabled"}
