"""bench.py's SectionScheduler: the starvation-proofing contract
(VERDICT r5 #1 — dtype_matrix/marker_overhead shipped null two rounds
running because one global budget had no reservations).  Pure host
logic, driven with a fake clock."""

import bench


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_reserved_sections_run_after_budget_exhausted():
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 500.0  # way past budget
    assert s.run("dtype_matrix", lambda: "ran") == "ran"
    assert "dtype_matrix" not in s.errors


def test_nonreserved_section_skips_when_only_reserve_remains():
    clock = _Clock()
    s = bench.SectionScheduler(
        100.0, {"dtype_matrix": 30.0, "marker_overhead": 10.0}, clock=clock)
    clock.t = 65.0  # 35s left < 40s reserved -> non-reserved must skip
    assert s.run("expensive_middle", lambda: "ran", default=None) is None
    assert "reserved" in s.errors["expensive_middle"]
    # the reserved sections still run afterwards
    assert s.run("marker_overhead", lambda: "m") == "m"
    assert s.run("dtype_matrix", lambda: "d") == "d"


def test_nonreserved_section_runs_inside_budget():
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 50.0  # 50s left > 30s reserved
    assert s.run("mid", lambda: 42) == 42
    assert s.errors == {}


def test_critical_sections_always_run():
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 500.0
    assert s.run("framework", lambda: 1, critical=True) == 1


def test_section_exception_recorded_not_raised():
    s = bench.SectionScheduler(100.0, {})

    def boom():
        raise RuntimeError("tunnel died")

    assert s.run("overlap", boom, default="dflt") == "dflt"
    assert s.errors["overlap"].startswith("RuntimeError")


def test_reserved_sections_registered_in_bench():
    # the two verdict-ordered sections AND the r6/r8 acceptance-gate
    # metrics must stay must-run
    assert "dtype_matrix" in bench.RESERVED_SECTIONS
    assert "marker_overhead" in bench.RESERVED_SECTIONS
    assert "flash_train" in bench.RESERVED_SECTIONS
    assert "dispatch_floor" in bench.RESERVED_SECTIONS


def test_small_budget_override_still_runs_best_effort_sections():
    # CK_BENCH_BUDGET_SEC below the reservation sum must not skip
    # everything from t=0 — reservations cap at 60% of the budget
    clock = _Clock()
    s = bench.SectionScheduler(600.0, dict(bench.RESERVED_SECTIONS),
                               clock=clock)
    assert s.run("baseline", lambda: "ran") == "ran"
    clock.t = 500.0  # past the capped 60% window -> best-effort skips
    assert s.run("overlap", lambda: "ran", default=None) is None
