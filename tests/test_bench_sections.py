"""bench.py's SectionScheduler: the starvation-proofing contract
(VERDICT r5 #1 — dtype_matrix/marker_overhead shipped null two rounds
running because one global budget had no reservations).  Pure host
logic, driven with a fake clock."""

import bench


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_reserved_sections_run_after_budget_exhausted():
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 500.0  # way past budget
    assert s.run("dtype_matrix", lambda: "ran") == "ran"
    assert "dtype_matrix" not in s.errors


def test_nonreserved_section_skips_when_only_reserve_remains():
    clock = _Clock()
    s = bench.SectionScheduler(
        100.0, {"dtype_matrix": 30.0, "marker_overhead": 10.0}, clock=clock)
    clock.t = 65.0  # 35s left < 40s reserved -> non-reserved must skip
    assert s.run("expensive_middle", lambda: "ran", default=None) is None
    assert "reserved" in s.errors["expensive_middle"]
    # the reserved sections still run afterwards
    assert s.run("marker_overhead", lambda: "m") == "m"
    assert s.run("dtype_matrix", lambda: "d") == "d"


def test_nonreserved_section_runs_inside_budget():
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 50.0  # 50s left > 30s reserved
    assert s.run("mid", lambda: 42) == 42
    assert s.errors == {}


def test_critical_sections_always_run():
    clock = _Clock()
    s = bench.SectionScheduler(100.0, {"dtype_matrix": 30.0}, clock=clock)
    clock.t = 500.0
    assert s.run("framework", lambda: 1, critical=True) == 1


def test_section_exception_recorded_not_raised():
    s = bench.SectionScheduler(100.0, {})

    def boom():
        raise RuntimeError("tunnel died")

    assert s.run("overlap", boom, default="dflt") == "dflt"
    assert s.errors["overlap"].startswith("RuntimeError")


def test_reserved_sections_registered_in_bench():
    # the two verdict-ordered sections AND the r6/r8 acceptance-gate
    # metrics must stay must-run
    assert "dtype_matrix" in bench.RESERVED_SECTIONS
    assert "marker_overhead" in bench.RESERVED_SECTIONS
    assert "flash_train" in bench.RESERVED_SECTIONS
    assert "dispatch_floor" in bench.RESERVED_SECTIONS


def test_small_budget_override_still_runs_best_effort_sections():
    # CK_BENCH_BUDGET_SEC below the reservation sum must not skip
    # everything from t=0 — reservations cap at 60% of the budget
    clock = _Clock()
    s = bench.SectionScheduler(600.0, dict(bench.RESERVED_SECTIONS),
                               clock=clock)
    assert s.run("baseline", lambda: "ran") == "ran"
    clock.t = 500.0  # past the capped 60% window -> best-effort skips
    assert s.run("overlap", lambda: "ran", default=None) is None


# ---------------------------------------------------------------------------
# fairness rotation (ISSUE 5 satellite): no section starves > 2 rounds
# ---------------------------------------------------------------------------

def test_rotation_promotes_two_round_starved_section():
    clock = _Clock()
    s = bench.SectionScheduler(
        100.0, {}, clock=clock,
        starvation_history=[{"marker_overhead"}, {"marker_overhead"}])
    assert s.rotation["promoted"] == ["marker_overhead"]
    assert s.rotation["starved_streak"] == ["marker_overhead"]
    assert s.reserved["marker_overhead"] == bench.FAIRNESS_SLICE_SEC
    # the promotion is a REAL must-run slice: it runs past budget
    clock.t = 500.0
    assert s.run("marker_overhead", lambda: "ran") == "ran"
    assert "marker_overhead" not in s.errors


def test_rotation_needs_two_consecutive_rounds():
    for hist in ([], [{"a"}], [{"a"}, {"b"}], [{"a"}, set(), {"a"}]):
        s = bench.SectionScheduler(100.0, {}, starvation_history=hist)
        assert s.rotation["promoted"] is None, hist
        assert s.rotation["starved_streak"] == []


def test_rotation_promotes_whole_multi_member_streak():
    """EVERY member of a multi-member streak is promoted the same round
    — a one-per-round rotation would leave a k-member streak's last
    member starving k+1 consecutive rounds, breaking the 'no section
    starves more than 2 consecutive rounds' guarantee for the
    motivating case itself (marker_overhead AND dtype_matrix starved
    together).  The rotation anchor only orders the list."""
    h2 = [{"a", "b"}, {"a", "b"}]
    s2 = bench.SectionScheduler(100.0, {}, starvation_history=h2)
    s3 = bench.SectionScheduler(100.0, {}, starvation_history=h2 + [{"a", "b"}])
    assert set(s2.rotation["promoted"]) == {"a", "b"}
    assert set(s3.rotation["promoted"]) == {"a", "b"}
    assert s2.reserved["a"] == s2.reserved["b"] == bench.FAIRNESS_SLICE_SEC
    # the anchor rotates with round count; same trajectory, same order
    assert s2.rotation["promoted"] != s3.rotation["promoted"]
    again = bench.SectionScheduler(100.0, {}, starvation_history=h2)
    assert again.rotation["promoted"] == s2.rotation["promoted"]


def test_rotation_never_shrinks_an_explicit_reservation():
    s = bench.SectionScheduler(
        1000.0, {"dtype_matrix": 430.0}, 
        starvation_history=[{"dtype_matrix"}, {"dtype_matrix"}])
    assert s.reserved["dtype_matrix"] == 430.0


def test_rotation_decision_lands_in_artifact():
    s = bench.SectionScheduler(
        100.0, {}, starvation_history=[{"ov"}, {"ov"}])
    result = {"headline": {}}
    bench.finalize_result(result, s)
    rot = result["scheduler_rotation"]
    assert rot["promoted"] == ["ov"]
    assert rot["slice_s"] == bench.FAIRNESS_SLICE_SEC
    assert rot["rounds_seen"] == 2


def test_starvation_history_reads_budget_skips_only(tmp_path):
    """History counts BUDGET starvation, not crashes: a must-run slice
    cannot fix a RuntimeError, so error nulls stay out of the streak."""
    import json

    for r in (1, 2):
        (tmp_path / f"BENCH_r0{r}.json").write_text(json.dumps({
            "null_sections": {
                "ov": {"null_reason": "skipped: 1500s bench budget spent",
                        "budget_spent_s": 1430.0},
                "boom": {"null_reason": "RuntimeError: tunnel died",
                          "budget_spent_s": 100.0},
            },
            "headline": {"mandelbrot_mpix": 1.0},
        }))
    hist = bench.starvation_history(str(tmp_path))
    assert hist == [{"ov"}, {"ov"}]
    s = bench.SectionScheduler(100.0, {}, starvation_history=hist)
    assert s.rotation["promoted"] == ["ov"]
