"""``trace/device.py`` — the device-timeline attribution subsystem
(ISSUE 8 acceptance gates).

Pinned here, against the synthetic-Xprof fixture format the CPU
container can produce deterministically:

- the per-kernel device report RECONCILES: per-kernel device time sums
  to ≤ the window wall, and the coverage fraction is explicit (never a
  silently-partial report);
- a two-kernel skewed window attributes ≥ 90% of device time to the
  correct kernel (through each correlation tier);
- the merged Perfetto trace round-trips with host spans and device ops
  on ONE timeline;
- profiler-off and CPU-only paths degrade to a NAMED absence, never a
  crash, and the disabled mark plane is free at the launch site;
- the persistent kernel-profile store keys by (signature, shape,
  blocks), survives torn lines, and answers best()/history();
- ``/profilez`` serves the last capture and the store index.
"""

import gzip
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from cekirdekler_tpu.trace import device as dv
from cekirdekler_tpu.trace.device import (
    DeviceMarks,
    DeviceWindowReport,
    Mark,
    ProfileStore,
    correlate,
    parse_mark_name,
    parse_trace_dump,
    roofline_row,
    split_unified_trace,
    unified_chrome_trace,
)
from cekirdekler_tpu.trace.spans import Span


# ---------------------------------------------------------------------------
# fixture builders: the synthetic-Xprof format
# ---------------------------------------------------------------------------

def _device_meta(pid=7, name="/device:TPU:0", tid=2, track="XLA Ops"):
    return [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": name}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
         "args": {"name": track}},
    ]


def _mark_event(seq, kernel, cid=None, lane=None, ts=0.0, dur=50.0, pid=1):
    name = (f"ck|k={kernel}|c={'-' if cid is None else cid}"
            f"|l={'-' if lane is None else lane}|s={seq}")
    return {"ph": "X", "pid": pid, "tid": 0, "ts": ts, "dur": dur,
            "name": name}


def _op(ts, dur, name="fusion.1", pid=7, tid=2, args=None):
    e = {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
         "name": name}
    if args:
        e["args"] = args
    return e


def _write_dump(dirpath, events, gz=True):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(
        dirpath, "host.trace.json.gz" if gz else "host.trace.json")
    if gz:
        with gzip.open(path, "wt") as f:
            json.dump({"traceEvents": events}, f)
    else:
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
    return path


# ---------------------------------------------------------------------------
# mark names
# ---------------------------------------------------------------------------

def test_mark_name_round_trip():
    name = dv._mark_name("nBody", 7, 3, 42)
    f = parse_mark_name(name)
    assert f == {"kernel": "nBody", "cid": 7, "lane": 3, "seq": 42}
    # None cid/lane render as '-' and parse back to None
    f2 = parse_mark_name(dv._mark_name("k", None, None, 1))
    assert f2["cid"] is None and f2["lane"] is None and f2["seq"] == 1
    assert parse_mark_name("not a mark") is None
    assert parse_mark_name("ck|k=x") is None  # no seq: not a usable mark


# ---------------------------------------------------------------------------
# parse + correlate: reconciliation
# ---------------------------------------------------------------------------

def test_report_reconciles_against_window(tmp_path):
    """Per-kernel device time ≤ per-track union ≤ window wall; the
    coverage fraction is explicit."""
    t0 = time.perf_counter()
    events = _device_meta() + [
        _mark_event(1, "nBody", cid=5, lane=0, ts=0.0),
        # 3 ops, overlapping pair: union = 1.5 + 0.5 = 2.0 ms
        _op(100.0, 1000.0), _op(600.0, 1000.0), _op(2000.0, 500.0),
    ]
    _write_dump(str(tmp_path), events)
    dump = parse_trace_dump(str(tmp_path))
    assert len(dump.ops) == 3 and dump.n_events == len(events)
    marks = [Mark(1, "nBody", 5, 0, t0, t0 + 0.00005)]
    wall_s = 0.010
    rep = correlate(dump, marks, window=(t0, t0 + wall_s))
    assert rep.absent is None
    assert rep.device_busy_ms == pytest.approx(2.0)
    per_kernel_sum = sum(k.device_ms for k in rep.kernels)
    assert per_kernel_sum <= rep.wall_ms
    assert per_kernel_sum == pytest.approx(rep.attributed_ms)
    assert rep.coverage_frac == pytest.approx(1.0)
    assert rep.unattributed_ms == pytest.approx(0.0)
    nb = rep.kernel("nBody")
    assert nb.op_count == 3 and nb.cids == [5]
    # inter-op idle: span 0.1..2.5 ms = 2.4, busy 2.0 → 0.4 idle
    assert nb.idle_ms == pytest.approx(0.4)
    assert rep.per_lane_overlap[0] == pytest.approx(2.0 / 10.0)
    # the serialized form carries the same reconciliation keys
    d = rep.to_dict()
    assert d["coverage_frac"] == pytest.approx(1.0)
    assert d["kernels"][0]["kernel"] == "nBody"


def test_unmatched_ops_are_explicit_not_silent(tmp_path):
    """Ops matching no mark stay unattributed: coverage < 1 and the
    remainder is carried in unattributed_ms — never silently dropped."""
    events = _device_meta() + [
        _op(100.0, 1000.0, name="mystery.op"),
    ]
    _write_dump(str(tmp_path), events)
    rep = correlate(parse_trace_dump(str(tmp_path)), [])  # no marks at all
    assert rep.absent is None
    assert rep.coverage_frac == 0.0
    assert rep.unattributed_ms == pytest.approx(1.0)
    assert rep.kernels == []


def test_two_kernel_skewed_window_attributes_90pct(tmp_path):
    """The acceptance gate: a 10:1 skewed two-kernel window puts ≥ 90%
    of device time on the correct kernel — via the kernel-name tier
    here (op names mention the kernels, as real XLA op names do)."""
    t0 = 1000.0  # fake perf_counter epoch; anchor comes from mark pairs
    events = _device_meta() + [
        _mark_event(1, "heavy", cid=3, lane=0, ts=0.0),
        _mark_event(2, "light", cid=4, lane=0, ts=100.0),
        # heavy: 10 ms total; light: 1 ms — interleaved late (async skew:
        # light's ops land AFTER heavy's even though dispatch overlapped)
        _op(200.0, 6000.0, name="fusion.heavy.1"),
        _op(6300.0, 4000.0, name="fusion.heavy.2"),
        _op(10400.0, 1000.0, name="fusion.light.1"),
    ]
    _write_dump(str(tmp_path), events)
    marks = [Mark(1, "heavy", 3, 0, t0 + 0.0000, t0 + 0.00005),
             Mark(2, "light", 4, 0, t0 + 0.0001, t0 + 0.00015)]
    rep = correlate(parse_trace_dump(str(tmp_path)), marks,
                    window=(t0, t0 + 0.02))
    heavy, light = rep.kernel("heavy"), rep.kernel("light")
    assert heavy is not None and light is not None
    assert heavy.device_ms / (heavy.device_ms + light.device_ms) >= 0.90
    assert heavy.device_ms == pytest.approx(10.0)
    assert light.device_ms == pytest.approx(1.0)
    assert rep.matched_by == {"kernel-name": 3}
    assert rep.anchor == "marks"


def test_explicit_tier_beats_name_and_stream_order(tmp_path):
    """An op carrying ck-seq attaches to THAT mark even when its name
    mentions another kernel and a later mark precedes it in time."""
    events = _device_meta() + [
        _mark_event(1, "a", cid=1, lane=0, ts=0.0),
        _mark_event(2, "b", cid=2, lane=0, ts=100.0),
        _op(5000.0, 1000.0, name="fusion.b.99", args={"ck-seq": 1}),
    ]
    _write_dump(str(tmp_path), events)
    rep = correlate(parse_trace_dump(str(tmp_path)), [])
    assert rep.kernel("a").op_count == 1
    assert rep.kernel("b") is None
    assert rep.matched_by == {"explicit": 1}


def test_stream_order_tier_is_the_fallback(tmp_path):
    """Anonymous ops attach to the latest mark dispatched at or before
    their start — the documented stream-order bound.  An op BEFORE the
    first mark was dispatched by something unmarked: it must stay
    unattributed (else coverage_frac could never read below 1.0)."""
    events = _device_meta() + [
        _mark_event(1, "first", ts=1000.0),
        _mark_event(2, "second", ts=5000.0),
        _op(100.0, 500.0, name="warmup.spill"),  # BEFORE every mark
        _op(2000.0, 500.0, name="anon.1"),   # after mark 1, before mark 2
        _op(6000.0, 500.0, name="anon.2"),   # after mark 2
    ]
    _write_dump(str(tmp_path), events)
    rep = correlate(parse_trace_dump(str(tmp_path)), [])
    assert rep.kernel("first").op_count == 1
    assert rep.kernel("second").op_count == 1
    assert rep.matched_by == {"stream-order": 2}
    assert rep.unattributed_ms == pytest.approx(0.5)
    assert rep.coverage_frac == pytest.approx(1.0 / 1.5)


def test_kernel_name_tier_prefers_longest_match(tmp_path):
    """Substring-ambiguous names resolve to the most specific kernel:
    'fusion.add_fused.3' belongs to 'add_fused', never 'add'."""
    events = _device_meta() + [
        _mark_event(1, "add", ts=0.0),
        _mark_event(2, "add_fused", ts=100.0),
        _op(1000.0, 500.0, name="fusion.add_fused.3"),
        _op(2000.0, 300.0, name="fusion.add.1"),
    ]
    _write_dump(str(tmp_path), events)
    rep = correlate(parse_trace_dump(str(tmp_path)), [])
    assert rep.kernel("add_fused").op_count == 1
    assert rep.kernel("add").op_count == 1
    assert rep.kernel("add_fused").device_ms == pytest.approx(0.5)
    assert rep.kernel("add").device_ms == pytest.approx(0.3)


def test_window_clipping_counts_clipped_ops(tmp_path):
    t0 = 50.0
    events = _device_meta() + [
        _mark_event(1, "k", ts=0.0),
        _op(100.0, 1000.0, name="in.window"),
        _op(50_000.0, 1000.0, name="past.window"),
    ]
    _write_dump(str(tmp_path), events)
    marks = [Mark(1, "k", None, None, t0, t0 + 0.00005)]
    rep = correlate(parse_trace_dump(str(tmp_path)), marks,
                    window=(t0, t0 + 0.010))  # 10 ms window
    assert rep.n_ops == 1            # the out-of-window op was dropped
    assert rep.clipped_ops == 1
    assert rep.kernel("k").device_ms == pytest.approx(1.0)


def test_module_track_fallback_no_double_count(tmp_path):
    """A dump with BOTH "XLA Ops" and "XLA Modules" tracks must count
    only the op track; a dump with only a module track uses it."""
    both = (
        _device_meta(tid=2, track="XLA Ops")
        + [{"ph": "M", "name": "thread_name", "pid": 7, "tid": 3,
            "args": {"name": "XLA Modules"}}]
        + [_op(0.0, 1000.0, tid=2), _op(0.0, 1000.0, tid=3)]
    )
    _write_dump(str(tmp_path / "both"), both)
    rep = correlate(parse_trace_dump(str(tmp_path / "both")), [])
    assert rep.device_busy_ms == pytest.approx(1.0)  # not 2.0

    mod_only = (
        _device_meta(tid=3, track="XLA Modules") + [_op(0.0, 1000.0, tid=3)]
    )
    _write_dump(str(tmp_path / "mod"), mod_only)
    rep2 = correlate(parse_trace_dump(str(tmp_path / "mod")), [])
    assert rep2.device_busy_ms == pytest.approx(1.0)


def test_empty_dump_is_named_absence(tmp_path):
    rep = correlate(parse_trace_dump(str(tmp_path)), [])
    assert rep.absent is not None and "profiler" in rep.absent
    # events but no device tracks (the CPU-container shape)
    _write_dump(str(tmp_path), [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "python"}},
        _op(0.0, 100.0, pid=1, tid=0),
    ])
    rep2 = correlate(parse_trace_dump(str(tmp_path)), [])
    assert rep2.absent is not None and "device" in rep2.absent


# ---------------------------------------------------------------------------
# unified Perfetto export round trip
# ---------------------------------------------------------------------------

def test_unified_trace_round_trips_host_and_device(tmp_path):
    t0 = 2000.0
    events = _device_meta() + [
        _mark_event(1, "heavy", cid=3, lane=0, ts=0.0),
        _mark_event(2, "light", cid=4, lane=1, ts=100.0),
        _op(200.0, 5000.0, name="fusion.heavy.1"),
        _op(5400.0, 800.0, name="fusion.light.1", tid=2),
    ]
    _write_dump(str(tmp_path), events)
    marks = [Mark(1, "heavy", 3, 0, t0, t0 + 0.0001),
             Mark(2, "light", 4, 1, t0 + 0.0001, t0 + 0.0002)]
    rep = correlate(parse_trace_dump(str(tmp_path)), marks,
                    window=(t0, t0 + 0.02))
    spans = [
        Span("launch", t0 + 0.0000, t0 + 0.0001, cid=3, lane=0, tag="heavy"),
        Span("fence", t0 + 0.010, t0 + 0.012, lane=1),
    ]
    doc = unified_chrome_trace(spans, rep, ops=rep.ops, marks=marks)
    # serializes under the strict-JSON contract every exporter obeys
    json.dumps(doc, allow_nan=False)
    back_spans, back_ops = split_unified_trace(doc)
    assert [s.kind for s in back_spans] == ["launch", "fence"]
    assert {o.kernel for o in back_ops} == {"heavy", "light"}
    assert {o.lane for o in back_ops} == {0, 1}  # per-lane device tracks
    # ONE clock: every ts is relative to the common base — the heavy
    # device op starts AFTER the launch span that dispatched it
    launch = next(s for s in back_spans if s.kind == "launch")
    heavy_op = next(o for o in back_ops if o.kernel == "heavy")
    assert heavy_op.ts * 1e-6 >= launch.t0
    # device processes are named device:* and host pid survives
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert any(n.startswith("device:") for n in names)
    # the mark instants replay with the declared device-mark kind
    kinds = {e["args"].get("kind") for e in doc["traceEvents"]
             if e.get("ph") in ("i", "X") and "args" in e}
    assert "device-mark" in kinds and "device-op" in kinds


def test_unified_trace_without_device_side_is_plain_host_trace():
    spans = [Span("launch", 1.0, 1.01, lane=0)]
    doc = unified_chrome_trace(spans, None, ops=[], marks=[])
    back_spans, back_ops = split_unified_trace(doc)
    assert len(back_spans) == 1 and back_ops == []


# ---------------------------------------------------------------------------
# marks: disabled is free; enabled records
# ---------------------------------------------------------------------------

def test_disabled_marks_overhead_under_budget():
    """The launch-site guard (`if MARKS.enabled:`) must keep the
    disabled path at attribute-read cost — same pin discipline as the
    tracer's 1 µs budget."""
    m = DeviceMarks()
    assert not m.enabled
    n = 50_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            tok = m.begin(("k",), 1, 0) if m.enabled else None
            if tok is not None:
                m.end(tok)
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"disabled mark cost {best*1e9:.0f} ns >= 1 µs"
    assert m.total_recorded == 0


def test_enabled_marks_record_host_side_without_jax_annotation():
    m = DeviceMarks()
    m.enable()
    m._ann_cls = None  # simulate a rig with no jax profiler at all
    tok = m.begin(["a", "b"], cid=9, lane=2)
    assert tok is not None
    m.end(tok)
    m.disable()
    (mark,) = m.snapshot()
    assert mark.kernel == "a+b" and mark.cid == 9 and mark.lane == 2
    assert mark.t1 >= mark.t0 > 0.0
    assert m.begin(("k",), None, None) is None  # disabled again
    m.end(None)  # no-op by contract


def test_worker_launch_records_marks(cpu_devices):
    """The integration seam: a real framework compute() under MARKS
    produces host-side marks tagged with kernel/cid/lane."""
    import cekirdekler_tpu as ct
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.trace.device import MARKS
    from cekirdekler_tpu.workloads import mandelbrot_pallas_kernel

    devs = ct.all_devices().cpus().subset(1)
    cr = NumberCruncher(devs, mandelbrot_pallas_kernel(interpret=True))
    out = ClArray(1024, np.float32, name="dm", read=False, write=True)
    vals = (-2.0, -1.25, 2.5 / 32, 2.5 / 32, 32, 8)
    try:
        MARKS.enable(clear=True)
        out.compute(cr, 4242, "mandelbrot", 1024, 256, values=vals)
        cr.barrier()
    finally:
        MARKS.disable()
        cr.dispose()
    marks = [m for m in MARKS.snapshot() if m.cid == 4242]
    assert marks, "launch recorded no device mark"
    assert marks[0].kernel == "mandelbrot" and marks[0].lane == 0


# ---------------------------------------------------------------------------
# capture degradation
# ---------------------------------------------------------------------------

def test_capture_profiler_off_degrades_to_named_absence(monkeypatch):
    from cekirdekler_tpu.obs.flight import FLIGHT
    from cekirdekler_tpu.utils import timeline

    monkeypatch.setattr(
        timeline, "start_profiler",
        lambda d: (None, "RuntimeError: no profiler on this backend"))
    ran = []
    with dv.capture_device("/tmp/ck_never_written_dev") as cap:
        ran.append(True)
    assert ran
    assert cap.report.absent is not None
    assert "profiler unavailable" in cap.report.absent
    assert cap.report.wall_ms > 0  # the window wall is still measured
    kinds = [e.kind for e in FLIGHT.snapshot()]
    assert "profiler-start" in kinds and "profiler-stop" in kinds
    # the named absence is what /profilez will serve
    assert dv.last_report() is cap.report


def test_capture_region_exception_propagates_and_names_absence(
        monkeypatch, tmp_path):
    from cekirdekler_tpu.utils import timeline

    monkeypatch.setattr(timeline, "start_profiler",
                        lambda d: (None, "unavailable"))
    with pytest.raises(ValueError, match="inside"):
        with dv.capture_device(str(tmp_path)):
            raise ValueError("inside")
    assert dv.last_report().absent is not None
    assert "ValueError" in dv.last_report().absent


def test_capture_parses_prewritten_dump(monkeypatch, tmp_path):
    """A capture whose profiler 'worked' (fake) and whose dir holds a
    synthetic dump produces a full report with marks correlated."""
    from cekirdekler_tpu.utils import timeline

    class FakeProf:
        pass

    monkeypatch.setattr(timeline, "start_profiler",
                        lambda d: (FakeProf(), None))
    monkeypatch.setattr(timeline, "stop_profiler", lambda h: None)
    with dv.capture_device(str(tmp_path)) as cap:
        # record one mark through the REAL plane while the window is open
        tok = dv.MARKS.begin("synthk", 11, 0)
        dv.MARKS.end(tok)
        seq = dv.MARKS.snapshot()[-1].seq
        _write_dump(str(tmp_path), _device_meta() + [
            _op(100.0, 2000.0, name="x", args={"ck-seq": seq}),
        ])
    rep = cap.report
    assert rep.absent is None
    prof = rep.kernel("synthk")
    # the synthetic 2 ms op is LONGER than the real (fast) window — the
    # reconciliation clips it to the wall instead of overcounting
    assert 0.0 < prof.device_ms <= rep.wall_ms
    assert prof.cids == [11]
    assert rep.anchor == "capture-start"  # mark absent from dump: fallback


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def test_roofline_row_bounds_and_mfu():
    # memory-bound: intensity below the ridge; roof slanted by bandwidth
    r = roofline_row(flops=1e12, bytes_moved=1e11, device_ms=1000.0,
                     peak_tflops=200.0, peak_gbps=800.0)
    assert r["bound"] == "memory"
    assert r["intensity_flop_per_byte"] == pytest.approx(10.0)
    assert r["ridge_flop_per_byte"] == pytest.approx(250.0)
    assert r["attained_tflops"] == pytest.approx(1.0)
    assert r["roof_tflops"] == pytest.approx(8.0)  # 10 flop/B × 800 GB/s
    assert r["mfu"] == pytest.approx(1.0 / 200.0)
    assert r["frac_of_roof"] == pytest.approx(1.0 / 8.0)
    # compute-bound: intensity past the ridge caps at the flat roof
    r2 = roofline_row(flops=1e15, bytes_moved=1e9, device_ms=10_000.0,
                      peak_tflops=200.0, peak_gbps=800.0)
    assert r2["bound"] == "compute" and r2["roof_tflops"] == 200.0


# ---------------------------------------------------------------------------
# the persistent store
# ---------------------------------------------------------------------------

def test_store_disabled_without_root(monkeypatch):
    monkeypatch.delenv(dv.PROFILE_STORE_ENV, raising=False)
    st = ProfileStore()
    assert not st.enabled
    assert st.put("k", (8,), ("256",), {"device_ms": 1.0}) is None
    assert st.get("k", (8,), ("256",)) is None
    assert st.keys() == []


def test_store_put_get_history_best(tmp_path):
    st = ProfileStore(str(tmp_path))
    key = ("flash_attention.bf16_default", (2, 8192, 8, 64), (512, 512))
    p1 = st.put(*key, {"device_ms": 12.5, "mfu": 0.18})
    p2 = st.put(*key, {"device_ms": 9.75, "mfu": 0.24})
    p3 = st.put(*key, {"device_ms": 11.0, "mfu": 0.21})
    assert p1 == p2 == p3 and os.path.exists(p1)
    hist = st.history(*key)
    assert [r["device_ms"] for r in hist] == [12.5, 9.75, 11.0]
    assert all(r["schema"] == dv.STORE_SCHEMA for r in hist)
    assert st.get(*key)["device_ms"] == 11.0          # newest
    assert st.best(*key)["device_ms"] == 9.75         # measured floor
    # a DIFFERENT blocks geometry is a different key file
    st.put("flash_attention.bf16_default", (2, 8192, 8, 64), (1024, 512),
           {"device_ms": 1.0})
    assert len(st.keys()) == 2
    # rows carry the key fields the BlockTuner will filter on
    assert hist[0]["blocks"] == [512, 512]
    assert hist[0]["shape"] == [2, 8192, 8, 64]


def test_store_skips_torn_tail_line(tmp_path):
    st = ProfileStore(str(tmp_path))
    st.put("k", (1,), ("b",), {"device_ms": 3.0})
    path = st.path_for("k", (1,), ("b",))
    with open(path, "a") as f:
        f.write('{"schema": "ck-kernel-profile-v1", "device_ms": 1.0')
    assert [r["device_ms"] for r in st.history("k", (1,), ("b",))] == [3.0]
    assert st.best("k", (1,), ("b",))["device_ms"] == 3.0


# ---------------------------------------------------------------------------
# /profilez
# ---------------------------------------------------------------------------

def test_profilez_endpoint_serves_last_report_and_store(tmp_path):
    from cekirdekler_tpu.obs.debugserver import serve_debug

    dv._set_last_report(DeviceWindowReport(
        wall_ms=5.0, absent="no device op events in the dump (test)"))
    st = ProfileStore(str(tmp_path))
    st.put("k", (1,), ("b",), {"device_ms": 3.0})
    payload = dv.profilez_payload(store=st)
    assert payload["last_capture"]["absent"].startswith("no device op")
    assert payload["store"]["enabled"] and len(payload["store"]["keys"]) == 1

    srv = serve_debug(None)
    try:
        body = json.load(
            urllib.request.urlopen(srv.url + "/profilez", timeout=10))
        assert set(body) == {"last_capture", "marks", "store"}
        assert body["last_capture"]["wall_ms"] == 5.0
        # the index page advertises the endpoint
        idx = json.load(urllib.request.urlopen(srv.url + "/", timeout=10))
        assert "/profilez" in idx["endpoints"]
    finally:
        srv.close()


def test_nbody_e2e_embeds_kernel_profile_block(monkeypatch, cpu_devices):
    """The bench-artifact contract: with a device capture that produced
    ops, the nbody attribution carries the per-kernel report AND the
    roofline/MFU row (faked capture — the CPU rig has no device
    tracks; the absent path is covered by the CLI/absence tests)."""
    import cekirdekler_tpu as ct
    from cekirdekler_tpu import workloads
    from cekirdekler_tpu.trace import device as dvmod

    rep = DeviceWindowReport(
        wall_ms=100.0, device_busy_ms=50.0, attributed_ms=50.0)
    rep.kernels = [dv.KernelDeviceProfile(
        "nBody", device_ms=50.0, op_count=5, launches=5)]

    class FakeCap:
        def __init__(self, trace_dir):
            self.report = rep

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return None

    monkeypatch.setattr(dvmod, "DeviceCapture", FakeCap)
    out = workloads.nbody_e2e(
        ct.all_devices().cpus().subset(2), n=2048, iters=4, window=2,
        attribution=True, device_timeline_dir="/tmp/ck_faked")
    kp = out["attribution"]["kernel_profile"]
    assert kp["kernels"][0]["kernel"] == "nBody"
    assert kp["coverage_frac"] == pytest.approx(1.0)
    rl = kp["roofline"]
    # n-body is heavily compute-slanted: ~20n/36 flop per byte
    assert rl["bound"] == "compute"
    assert rl["intensity_flop_per_byte"] == pytest.approx(
        20.0 * 2048 / 36.0, rel=1e-3)
    assert rl["device_ms"] == pytest.approx(50.0)
    assert out["attribution"]["device_busy_ms"] == pytest.approx(50.0)


def test_plan_signature_blocks_component():
    from cekirdekler_tpu.core.stream import chunk_plan, plan_signature
    from cekirdekler_tpu.core.worker import _ladder

    assert plan_signature(chunk_plan(12 * 256, 256, 3)) == "1024+1024+1024"
    assert plan_signature(_ladder(12 * 256, 256)) == "2048+1024"
    assert plan_signature([]) == "0"
