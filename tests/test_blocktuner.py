"""BlockTuner (core/blocktuner.py): the measured Pallas tile autotuner
that replaced the static ``default_blocks`` heuristic as the flash
default-argument block chooser (ISSUE 16).

Lifecycle coverage mirrors tests/test_stream.py's TransferTuner suite:
determinism, wall monotonicity, hysteresis no-flap, measuring-run ->
engage -> retune, ProfileStore-seeded warm start, executable-geometry
stability across a hysteresis hold — plus the flash integration pins
(explicit blocks bypass the tuner bit-identically, cold default-arg
equals the static pair bit-identically), the fused-QKV / one-shot
kernel variants, the hardware.py roofline-peak table (ISSUE 16
satellite), and the replayable ``block-retune`` decision provenance
(golden fixture green, tampered fixture names the first divergent
seq)."""

import importlib.util
import json
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from cekirdekler_tpu.core import blocktuner as bt  # noqa: E402
from cekirdekler_tpu.core.blocktuner import (  # noqa: E402
    BLOCK_CANDIDATES,
    HYSTERESIS_FRAC,
    BlockTuner,
    block_transition,
    clamp_blocks,
    legal_block_grid,
    orient_block_grid,
)
from cekirdekler_tpu.obs import replay as replay_mod  # noqa: E402
from cekirdekler_tpu.obs.decisions import (  # noqa: E402
    DECISIONS,
    load_decision_log,
)
from cekirdekler_tpu.ops.flash_attention import (  # noqa: E402
    default_blocks,
    flash_attention,
    fused_qkv,
    fused_qkv_attention,
)
from cekirdekler_tpu.parallel.attention import attention_reference  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "fixtures_decisions",
                      "golden_block_retune.jsonl")
SIG = "flash_attention.bf16_default"
#: the key a default-precision ("highest") flash call asks the tuner for
HSIG = "flash_attention.highest"


def _load_tool(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _qkv(B=1, T=256, H=1, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _tuner(**kw):
    kw.setdefault("device_kind", "test-rig")
    return BlockTuner(**kw)


# ---------------------------------------------------------------------------
# the pure surface: grid legality, orientation, clamping, transition
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("T", [96, 128, 200, 256, 640, 999, 1024, 4096, 4104])
def test_legal_grid_empty_iff_static_policy_falls_dense(T):
    """The equivalence the default path is built on: the tuner's legal
    grid is empty exactly when ``default_blocks`` returns None — the
    two policies agree on WHEN tiling is legal and only ever disagree
    on WHICH legal tile to run."""
    assert (not legal_block_grid(T, T)) == (default_blocks(T, T) is None)


def test_legal_grid_contents():
    assert legal_block_grid(256, 256) == (
        (128, 128), (128, 256), (256, 128), (256, 256))
    # per-axis legality: Tq and Tk divide independently
    assert legal_block_grid(128, 256) == ((128, 128), (128, 256))
    assert legal_block_grid(640, 640) == ((128, 128),)  # only 128 | 640
    assert legal_block_grid(96, 96) == ()               # sub-floor only


def test_orient_block_grid():
    grid = legal_block_grid(512, 512)
    comp = orient_block_grid(grid, "compute")
    mem = orient_block_grid(grid, "memory")
    assert set(comp) == set(mem) == set(grid)  # reorders, never drops
    assert comp[0] == (512, 512) and mem[0] == (128, 128)
    areas = [p[0] * p[1] for p in comp]
    assert areas == sorted(areas, reverse=True)
    assert orient_block_grid(grid, None) == tuple(grid)


def test_clamp_blocks():
    grid = legal_block_grid(512, 512)
    assert clamp_blocks((256, 256), grid) == (256, 256)  # member
    assert clamp_blocks((1024, 256), grid) == (512, 256)  # nearest
    assert clamp_blocks((2048, 2048), grid) == (512, 512)
    assert clamp_blocks(None, grid) is None
    assert clamp_blocks((256, 256), ()) is None


def test_transition_deterministic_and_order_free():
    grid = legal_block_grid(512, 512)
    walls = [((256, 256), 1.0), ((128, 128), 2.0), ((512, 512), 1.5)]
    got = block_transition((128, 128), walls, grid)
    for _ in range(3):
        assert block_transition((128, 128), walls, grid) == got
    assert block_transition((128, 128), list(reversed(walls)), grid) == got


def test_transition_cold_vocabulary():
    grid = legal_block_grid(512, 512)
    assert block_transition(None, [], ()) == (None, "no-legal-grid")
    assert block_transition(None, [], grid) == (None, "cold")
    assert block_transition(None, [], grid, fallback=(256, 256)) == \
        ((256, 256), "cold-fallback")
    # the seed outranks the fallback, and clamps onto the grid
    assert block_transition(None, [], grid, seed=(2048, 256),
                            fallback=(256, 256)) == ((512, 256), "store-seed")
    # a wall for a pair OUTSIDE the grid is ignored (stale geometry)
    assert block_transition(None, [((64, 64), 0.1)], grid,
                            fallback=(256, 256)) == \
        ((256, 256), "cold-fallback")


def test_transition_wall_monotonicity():
    """Raising a loser's wall never flips the choice toward it;
    lowering the winner's wall never unseats it."""
    grid = legal_block_grid(512, 512)
    cur = (256, 256)
    walls = {(256, 256): 1.0, (128, 128): 2.0, (512, 512): 1.5}
    assert block_transition(cur, walls.items(), grid)[0] == cur
    for worse in (2.5, 5.0, 50.0):
        w = dict(walls)
        w[(128, 128)] = worse
        assert block_transition(cur, w.items(), grid)[0] == cur
    for better in (0.9, 0.5, 0.01):
        w = dict(walls)
        w[(256, 256)] = better
        assert block_transition(cur, w.items(), grid) == (cur, "steady")


def test_transition_hysteresis_no_flap():
    """±noise inside the hysteresis band can NEVER flap an engaged,
    measured choice; a real cliff still switches it."""
    grid = legal_block_grid(512, 512)
    cur = (256, 256)
    band = 1.0 - HYSTERESIS_FRAC
    for frac in (1.0, 0.99, band + 1e-9):
        walls = [((256, 256), 1.0), ((512, 512), frac)]
        choice, why = block_transition(cur, walls, grid)
        assert (choice, why) == (cur, "hysteresis-hold" if frac < 1.0
                                 else "steady"), frac
    choice, why = block_transition(
        cur, [((256, 256), 1.0), ((512, 512), band - 0.01)], grid)
    assert (choice, why) == ((512, 512), "model")


def test_transition_unmeasured_incumbent_yields_to_first_measurement():
    """A store-seeded or fallback-engaged incumbent has no wall of its
    own: the first measurement set takes over without hysteresis (there
    is no incumbent wall to defend)."""
    grid = legal_block_grid(512, 512)
    choice, why = block_transition(
        (512, 512), [((256, 256), 1.0)], grid)
    assert (choice, why) == ((256, 256), "measuring")
    choice, why = block_transition(
        (512, 512), [((512, 512), 1.0)], grid)
    assert (choice, why) == ((512, 512), "steady")


# ---------------------------------------------------------------------------
# the stateful wrapper: lifecycle, measuring run, store seam, metrics
# ---------------------------------------------------------------------------

def test_tuner_cold_fallback_then_measured_takeover():
    t = _tuner()
    assert t.choose(SIG, 512, 512, fallback=(512, 512)) == (512, 512)
    assert t.retunes == 1  # first engagement counts
    t.observe(SIG, 512, 512, (256, 256), 1.0)
    assert t.choose(SIG, 512, 512) == (256, 256)
    assert t.retunes == 2
    # steady re-asks don't retune
    assert t.choose(SIG, 512, 512) == (256, 256)
    assert t.retunes == 2


def test_tuner_hysteresis_hold_keeps_retunes_flat():
    t = _tuner()
    t.observe(SIG, 512, 512, (256, 256), 1.0)
    t.choose(SIG, 512, 512, fallback=(512, 512))
    before = t.retunes
    for noise in (0.97, 1.02, 0.95, 1.04):
        t.observe(SIG, 512, 512, (512, 512), noise)
        assert t.choose(SIG, 512, 512) == (256, 256)
    assert t.retunes == before


def test_tuner_ema_tracks_weather():
    t = _tuner(ema=0.5)
    t.observe(SIG, 512, 512, (256, 256), 2.0)
    t.observe(SIG, 512, 512, (256, 256), 1.0)
    snap = t.snapshot()
    (key,) = snap
    assert snap[key]["walls"][(256, 256)] == pytest.approx(1.5)


def test_measuring_run_engages_then_cliff_retunes():
    walls = {(128, 128): 2.0, (128, 256): 1.8, (128, 512): 1.6,
             (256, 128): 1.7, (256, 256): 0.9, (256, 512): 1.1}
    t = _tuner()
    out = t.measuring_run(SIG, 512, 512,
                          lambda bq, bk: walls[(bq, bk)])
    assert out["skipped"] is None
    assert [m["block_q"] for m in out["measured"]] == \
        [p[0] for p in list(legal_block_grid(512, 512))[:6]]
    assert out["chosen"] == (256, 256)
    # a later cliff on another candidate retunes past hysteresis
    t.observe(SIG, 512, 512, (512, 512), 0.5)
    assert t.choose(SIG, 512, 512) == (512, 512)


def test_measuring_run_orients_by_bound_under_cap():
    seen = []

    def runner(bq, bk):
        seen.append((bq, bk))
        return 1.0

    t = _tuner()
    t.measuring_run(SIG, 2048, 2048, runner, bound="compute", limit=3)
    assert len(seen) == 3
    areas = [p[0] * p[1] for p in seen]
    assert areas == sorted(areas, reverse=True)  # big tiles first


def test_store_seeded_warm_start_skips_measuring_run(tmp_path):
    """The whole point of persisting profiles: a key with store rows
    engages the stored best WITHOUT paying the measuring walk."""
    from cekirdekler_tpu.trace.device import ProfileStore

    store = ProfileStore(str(tmp_path))
    shape = (2, 4096, 8, 64)
    store.put(SIG, shape, (512, 512), {"device_ms": 1.4})
    store.put(SIG, shape, (1024, 512), {"device_ms": 0.9})
    store.put(SIG, shape, (256, 256), {"device_ms": 2.2})
    assert store.best_blocks(SIG, shape) == (1024, 512)

    t = _tuner(store=store)

    def must_not_run(bq, bk):  # pragma: no cover - the assertion
        raise AssertionError("store-seeded key paid a measuring walk")

    out = t.measuring_run(SIG, 4096, 4096, must_not_run, shape=shape)
    assert out["skipped"] == "store-seed"
    assert out["chosen"] == (1024, 512)
    assert out["measured"] == []


def test_store_seed_clamps_foreign_geometry(tmp_path):
    """Rows inherited from a rig whose best pair is illegal HERE snap
    onto the legal grid instead of being trusted verbatim."""
    from cekirdekler_tpu.trace.device import ProfileStore

    store = ProfileStore(str(tmp_path))
    shape = (1, 640, 8, 64)
    store.put(SIG, shape, (512, 512), {"device_ms": 1.0})
    t = _tuner(store=store)
    # only (128, 128) is legal at T=640
    assert t.choose(SIG, 640, 640, shape=shape) == (128, 128)


def test_invalidate_drops_state_and_reengages():
    t = _tuner()
    t.observe(SIG, 512, 512, (256, 256), 1.0)
    t.choose(SIG, 512, 512)
    t.observe("other.sig", 512, 512, (128, 128), 1.0)
    t.choose("other.sig", 512, 512)
    t.on_invalidate(SIG)
    snap = t.snapshot()
    assert all(k[0] == "other.sig" for k in snap)
    # the dropped key re-engages from scratch
    assert t.choose(SIG, 512, 512, fallback=(512, 512)) == (512, 512)


def test_tuner_metrics_move():
    from cekirdekler_tpu.metrics.registry import REGISTRY

    c_choose = REGISTRY.counter("ck_block_choose_total")
    c_ret = REGISTRY.counter("ck_block_retunes_total")
    c_meas = REGISTRY.counter("ck_block_measure_runs_total")
    v0, r0, m0 = c_choose.value, c_ret.value, c_meas.value
    t = _tuner()
    t.measuring_run(SIG, 512, 512, lambda bq, bk: 1.0, limit=2)
    assert c_choose.value > v0
    assert c_ret.value > r0
    assert c_meas.value == m0 + 1


def test_concurrent_choose_observe_consistent():
    """The TransferTuner lock discipline: concurrent observers and
    choosers never tear state, and the final choice is the measured
    best."""
    import threading

    t = _tuner()
    errs = []

    def obs():
        try:
            for i in range(200):
                t.observe(SIG, 512, 512, (256, 256), 1.0 + (i % 3) * 0.01)
                t.observe(SIG, 512, 512, (512, 512), 3.0)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def cho():
        try:
            for _ in range(200):
                c = t.choose(SIG, 512, 512, fallback=(512, 512))
                assert c in ((512, 512), (256, 256))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=f) for f in (obs, obs, cho, cho)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    assert t.choose(SIG, 512, 512) == (256, 256)


# ---------------------------------------------------------------------------
# flash integration: default-arg engages the tuner, explicit bypasses
# ---------------------------------------------------------------------------

def test_flash_explicit_blocks_bypass_tuner(monkeypatch):
    calls = []
    t = _tuner()
    orig = t.choose

    def spy(*a, **kw):
        calls.append(a)
        return orig(*a, **kw)

    monkeypatch.setattr(t, "choose", spy)
    monkeypatch.setattr(bt, "TUNER", t)
    q, k, v = _qkv(T=256)
    flash_attention(q, k, v, False, 128, 128, True)
    assert calls == []  # explicit blocks never consult the tuner
    flash_attention(q, k, v, False, None, None, True)
    assert len(calls) == 1  # the default-arg path does


def test_flash_cold_default_arg_bit_identical_to_static(monkeypatch):
    """Acceptance pin: with no measurements and no store rows, the
    default-argument call runs EXACTLY the static ``default_blocks``
    geometry — bit-identical output, not merely close."""
    monkeypatch.setattr(bt, "TUNER", _tuner())
    q, k, v = _qkv(T=256, D=16, seed=3)
    fb = default_blocks(256, 256)
    got = flash_attention(q, k, v, True, None, None, True)
    want = flash_attention(q, k, v, True, fb[0], fb[1], True)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_flash_default_arg_follows_engaged_choice(monkeypatch):
    """A tuned choice changes what the default path runs: bit-identical
    to the SAME geometry called explicitly."""
    t = _tuner()
    monkeypatch.setattr(bt, "TUNER", t)
    t.observe(HSIG, 256, 256, (128, 256), 0.5)
    t.observe(HSIG, 256, 256, (256, 256), 2.0)
    q, k, v = _qkv(T=256, D=16, seed=4)
    got = flash_attention(q, k, v, False, None, None, True)
    want = flash_attention(q, k, v, False, 128, 256, True)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_hold_keeps_lowered_geometry_retune_changes_it(monkeypatch):
    """Executable-cache accounting across the tuner lifecycle: a
    hysteresis hold keeps the traced block geometry (same lowering →
    the jit cache stays warm), a past-band retune changes it (ONE new
    executable, bought by a real cliff, not noise)."""
    import re

    t = _tuner()
    monkeypatch.setattr(bt, "TUNER", t)
    q, k, v = _qkv(T=256, D=8)

    def jaxpr():
        s = str(jax.make_jaxpr(lambda q, k, v: flash_attention(
            q, k, v, False, None, None, True))(q, k, v))
        return re.sub(r"0x[0-9a-f]+", "0x", s)  # drop object addresses

    j0 = jaxpr()  # cold: engages default_blocks (256, 256)
    r0 = t.retunes
    t.observe(HSIG, 256, 256, (256, 256), 1.0)
    t.observe(HSIG, 256, 256, (128, 128), 0.95)  # 5% < the 8% band
    assert jaxpr() == j0  # hold → identical lowering
    assert t.retunes == r0
    t.observe(HSIG, 256, 256, (128, 128), 0.5)
    t.observe(HSIG, 256, 256, (128, 128), 0.5)
    j1 = jaxpr()
    assert t.retunes == r0 + 1
    assert j1 != j0  # the retune IS a new geometry


def test_flash_tuner_failure_degrades_to_static(monkeypatch):
    """Telemetry plumbing must never sink the math: a tuner that raises
    leaves the default path on the static pair."""
    t = _tuner()

    def boom(*a, **kw):
        raise RuntimeError("tuner plumbing failure")

    monkeypatch.setattr(t, "choose", boom)
    monkeypatch.setattr(bt, "TUNER", t)
    q, k, v = _qkv(T=256, D=16, seed=5)
    got = flash_attention(q, k, v, True, None, None, True)
    fb = default_blocks(256, 256)
    want = flash_attention(q, k, v, True, fb[0], fb[1], True)
    assert np.array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# kernel-level MFU variants: fused QKV, one-shot softmax
# ---------------------------------------------------------------------------

def test_fused_qkv_bit_identical_to_separate_projections():
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((2, 64, 32)), jnp.float32)
    wq = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    wk = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    wv = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    q, k, v = fused_qkv(x, wq, wk, wv)
    for got, w, name in ((q, wq, "q"), (k, wk, "k"), (v, wv, "v")):
        assert np.array_equal(np.asarray(got), np.asarray(x @ w)), name


def test_fused_qkv_attention_matches_reference():
    rng = np.random.default_rng(8)
    B, T, E, H, D = 1, 256, 32, 2, 16
    x = jnp.asarray(rng.standard_normal((B, T, E)) * 0.3, jnp.float32)
    mk = lambda: jnp.asarray(rng.standard_normal((E, H * D)) * 0.3,
                             jnp.float32)
    wq, wk, wv = mk(), mk(), mk()
    got = fused_qkv_attention(x, wq, wk, wv, H, causal=True,
                              interpret=True)
    q = (x @ wq).reshape(B, T, H, D)
    k = (x @ wk).reshape(B, T, H, D)
    v = (x @ wv).reshape(B, T, H, D)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_one_shot_softmax_single_kblock_matches_reference(causal):
    """block_k == Tk runs the one-shot softmax re-materialization (no
    running-max rescale) — values and grads must match the dense
    reference like any other geometry."""
    q, k, v = _qkv(T=128, D=8, seed=11)
    got = flash_attention(q, k, v, causal, 128, 128, True)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss_fl(q, k, v):
        return (flash_attention(q, k, v, causal, 128, 128, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) ** 2).sum()

    g = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"one-shot grad d{name}")


def test_one_shot_agrees_with_two_step_geometry():
    q, k, v = _qkv(T=128, D=8, seed=12)
    one = flash_attention(q, k, v, False, 128, 128, True)
    two = flash_attention(q, k, v, False, 128, 64, True)
    np.testing.assert_allclose(np.asarray(one), np.asarray(two),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# roofline peaks from the hardware table (ISSUE 16 satellite)
# ---------------------------------------------------------------------------

def test_device_peak_table_pins_v5e_numbers():
    from cekirdekler_tpu.hardware import (
        DEVICE_PEAKS, device_peaks)
    from cekirdekler_tpu.trace.device import (
        V5E_HBM_GBPS, V5E_PEAK_BF16_TFLOPS)

    assert DEVICE_PEAKS["TPU v5e"] == (197.0, 819.0)
    assert DEVICE_PEAKS["TPU v5 lite"] == (197.0, 819.0)
    # the historical module constants still pin the same numbers
    assert (V5E_PEAK_BF16_TFLOPS, V5E_HBM_GBPS) == (197.0, 819.0)
    tf, gb, kind = device_peaks("TPU v4")
    assert (tf, gb, kind) == (275.0, 1228.0, "TPU v4")
    # unknown kinds (CPU containers) fall back to v5e, NAMED as such
    tf, gb, kind = device_peaks("cpu")
    assert (tf, gb) == (197.0, 819.0)
    assert kind == "TPU v5e (fallback for cpu)"


def test_roofline_row_defaults_unchanged_vs_explicit_v5e():
    """Satellite pin: sourcing peaks from the device table leaves the
    default (v5e-on-this-container) roofline numbers bit-unchanged vs
    the old hardcoded constants."""
    from cekirdekler_tpu.trace.device import roofline_row

    auto = roofline_row(1e12, 1e9, 5.0)
    pinned = roofline_row(1e12, 1e9, 5.0, peak_tflops=197.0,
                          peak_gbps=819.0)
    assert pinned["peak_kind"] == "override"
    assert auto["peak_kind"].startswith("TPU v5e")
    for key in ("attained_tflops", "mfu", "bound", "frac_of_roof",
                "intensity_flop_per_byte"):
        assert auto[key] == pinned[key], key
    v4 = roofline_row(1e12, 1e9, 5.0, device_kind="TPU v4")
    assert v4["peak_kind"] == "TPU v4"
    assert v4["mfu"] < auto["mfu"]  # judged against a taller roof


# ---------------------------------------------------------------------------
# decision provenance: live records replay, golden fixture, tamper
# ---------------------------------------------------------------------------

def _mark() -> int:
    recs = DECISIONS.snapshot()
    return recs[-1].seq if recs else 0


def _since(mark: int):
    return [r for r in DECISIONS.snapshot() if r.seq > mark]


def test_live_retunes_replay_bit_identically():
    mark = _mark()
    t = _tuner()
    t.choose(SIG, 512, 512, fallback=(512, 512))     # cold-fallback
    t.observe(SIG, 512, 512, (256, 256), 1.0)
    t.choose(SIG, 512, 512)                          # measuring takeover
    t.observe(SIG, 512, 512, (512, 512), 0.5)
    t.observe(SIG, 512, 512, (512, 512), 0.5)
    t.choose(SIG, 512, 512)                          # model retune
    rows = [r for r in _since(mark) if r.kind == "block-retune"]
    assert [r.outputs["why"] for r in rows] == \
        ["cold-fallback", "measuring", "model"]
    verdict = replay_mod.verify_records(rows)
    assert verdict["ok"], verdict["first_divergence"]
    assert verdict["replayed"] == 3


def test_hold_records_nothing():
    mark = _mark()
    t = _tuner()
    t.observe(SIG, 512, 512, (256, 256), 1.0)
    t.choose(SIG, 512, 512)
    after_engage = len([r for r in _since(mark)
                        if r.kind == "block-retune"])
    t.observe(SIG, 512, 512, (512, 512), 0.95)
    t.choose(SIG, 512, 512)  # hysteresis-hold
    t.choose(SIG, 512, 512)  # steady
    held = [r for r in _since(mark) if r.kind == "block-retune"]
    assert len(held) == after_engage  # no choice change -> no record


def test_golden_block_fixture_replays_bit_identically():
    rows = load_decision_log(GOLDEN)
    assert len(rows) == 6
    whys = [r.outputs["why"] for r in rows]
    assert "store-seed" in whys and "measuring" in whys \
        and "model" in whys and "cold-fallback" in whys
    verdict = replay_mod.verify_records(rows)
    assert verdict["ok"], verdict["first_divergence"]
    assert verdict["replayed"] == len(rows)


def test_tampered_block_fixture_names_first_divergent_seq():
    rows = [r.to_row() for r in load_decision_log(GOLDEN)]
    tampered = json.loads(json.dumps(rows))
    victim = next(r for r in tampered
                  if r["outputs"]["why"] == "model")
    victim["outputs"]["block_q"] = 128  # the transition chose 512
    verdict = replay_mod.verify_records(tampered)
    assert not verdict["ok"]
    assert verdict["first_divergence"]["seq"] == victim["seq"]
    assert verdict["first_divergence"]["kind"] == "block-retune"


def test_perturbed_hysteresis_knob_is_divergence(monkeypatch):
    """The recorded hysteresis travels IN the record, so replay is
    knob-proof there — but a grid-arithmetic change (the candidate
    table) must fail replay and name the seq."""
    rows = load_decision_log(GOLDEN)
    assert replay_mod.verify_records(rows)["ok"]
    monkeypatch.setattr(bt, "BLOCK_CANDIDATES", (128,))
    # the recorded grid also travels in the record: replay rebuilds the
    # transition from recorded inputs, so even this stays green — the
    # record is self-contained by design
    assert replay_mod.verify_records(rows)["ok"]


def test_ckreplay_cli_verify_and_whatif_block_grid(capsys):
    ckreplay = _load_tool("ck_replay_tool_bt", "tools/ckreplay.py")
    assert ckreplay.main(["verify", GOLDEN]) == 0
    out = capsys.readouterr().out
    assert "block-retune=6" in out
    assert ckreplay.main(
        ["whatif", GOLDEN, "--set", "block_grid=128x256"]) == 0
    out = capsys.readouterr().out
    assert "block choices:" in out
    with pytest.raises(SystemExit):
        ckreplay.parse_overrides("block_grid=bogus")


def test_whatif_block_grid_counterfactual():
    rows = load_decision_log(GOLDEN)
    rep = replay_mod.whatif(rows, {"block_grid": (128, 256)})
    assert len(rep["block_choices"]) == 6
    assert rep["block_choices_changed"] >= 1
    for ch in rep["block_choices"]:
        assert set(ch) >= {"seq", "kernel_sig", "factual",
                           "counterfactual", "why"}
    # restricting the grid to the factual candidates changes nothing
    same = replay_mod.whatif(rows, {"block_grid": BLOCK_CANDIDATES})
    assert same["block_choices_changed"] == 0
