"""DCN multi-host tier (cluster/dcn.py): real processes x virtual CPU
devices joined through a jax.distributed coordinator, computing one
balanced global range with results exchanged over XLA collectives
(SURVEY.md §7 step 6; VERDICT r4 next-round #4).

Two jobs:
- symmetric 2 processes x 4 devices (the original parity proof);
- ASYMMETRIC 3 processes x (4, 2, 2) devices (VERDICT r5 #6): the
  configuration `_allgather`'s design argument rests on — per-process
  steps differ, the LCM-step table must reflect them, and shares must
  snap to each process's own step.  Skip-guarded for constrained CI via
  ``CK_SKIP_DCN_ASYM=1``.

The in-job assertions (correctness, share agreement, LCM-step table,
balancer movement) live in tests/_dcn_worker.py — this file owns process
lifecycle only.
"""

import os
import socket
import subprocess
import sys

import pytest

#: tests/_dcn_elastic_worker.py's os._exit code for the simulated
#: preemption (tests/ is not a package — the constant is mirrored here).
EXIT_PREEMPTED = 17


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(n_devices: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # production default: x64 OFF — the worker's 64-bit exchange check
    # must run against real canonicalization, not the rig's x64 override
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env


def _run_job(counts: list[int], timeout: float = 240.0) -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "_dcn_worker.py")
    port = _free_port()
    nproc = len(counts)
    counts_arg = ",".join(str(c) for c in counts)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port),
             counts_arg],
            env=_worker_env(counts[pid]), cwd=os.path.dirname(here),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"DCN_OK pid={pid}" in out, out[-3000:]


def test_two_process_distributed_compute():
    _run_job([4, 4])


@pytest.mark.skipif(
    os.environ.get("CK_SKIP_DCN_ASYM") == "1",
    reason="asymmetric DCN job disabled (CK_SKIP_DCN_ASYM=1)",
)
def test_asymmetric_three_process_distributed_compute():
    """4+2+2 virtual devices across 3 processes (VERDICT r5 #6): unequal
    per-process steps through the same SPMD balancer — the share table,
    LCM-step table, and exchange must all hold without the symmetric
    reshape `multihost_utils.process_allgather` would need."""
    _run_job([4, 2, 2])


# ---------------------------------------------------------------------------
# kill-and-rejoin (ISSUE 13): preemption-safe elastic resume
# ---------------------------------------------------------------------------

def _run_elastic_job(counts, ckpt_root, phase, windows, kill_after,
                     decision_dir, expect_rc=0, expect_ok=True,
                     timeout=240.0):
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "_dcn_elastic_worker.py")
    port = _free_port()
    nproc = len(counts)
    counts_arg = ",".join(str(c) for c in counts)
    procs = []
    for pid in range(nproc):
        env = _worker_env(counts[pid])
        env["CK_DECISION_LOG"] = decision_dir + os.sep
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port),
             counts_arg, ckpt_root, phase, str(windows), str(kill_after)],
            env=env, cwd=os.path.dirname(here),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc, \
            f"worker {pid} rc={p.returncode} (want {expect_rc}):\n{out[-3000:]}"
        if expect_ok:
            assert f"DCN_ELASTIC_OK pid={pid}" in out, out[-3000:]
    return outs


@pytest.mark.skipif(
    os.environ.get("CK_SKIP_DCN_ELASTIC") == "1",
    reason="elastic DCN job disabled (CK_SKIP_DCN_ELASTIC=1)",
)
def test_kill_and_rejoin_converges_bit_identical(tmp_path):
    """The ISSUE 13 acceptance harness: a 2x2-device DCN job is
    preempted (every process os._exit's with no cleanup) after window
    3 of 6, a TORN newest checkpoint is planted, and a NEW job with a
    DIFFERENT membership (2+1 devices — one process resized, so
    member-leave/member-join re-splits are recorded) resumes from the
    last complete window and finishes.  The worker asserts the final
    image is bit-identical to the undisturbed run's and that the
    spilled decision log — membership transitions and checkpoint
    restore included — replays green through verify_records."""
    ckpt_root = str(tmp_path / "ckpt")
    decisions = str(tmp_path / "decisions")
    os.makedirs(decisions, exist_ok=True)
    windows, kill_after = 6, 3
    # phase 1: run + die mid-job (preemption — rc is the _exit code)
    _run_elastic_job([2, 2], ckpt_root, "first", windows, kill_after,
                     decisions, expect_rc=EXIT_PREEMPTED, expect_ok=False)
    # the checkpoints the preempted run left are complete through
    # kill_after (atomic rename — no half-windows)
    steps = sorted(os.listdir(ckpt_root))
    assert f"step_{kill_after:012d}" in steps, steps
    # plant a TORN newest step: the resume must fall back past it
    torn = os.path.join(ckpt_root, f"step_{kill_after + 1:012d}")
    os.makedirs(torn, exist_ok=True)
    with open(os.path.join(torn, "arrays.npz"), "wb") as f:
        f.write(b"definitely not a zip file")
    # phase 2: rejoin with a CHANGED membership (2+1 devices)
    outs = _run_elastic_job([2, 1], ckpt_root, "rejoin", windows,
                            kill_after, decisions)
    assert any("DCN_ELASTIC_REPLAY pid=0 ok=True" in o for o in outs), \
        outs[0][-2000:]
