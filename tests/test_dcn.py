"""DCN multi-host tier (cluster/dcn.py): 2 real processes × 4 virtual CPU
devices each, joined through a jax.distributed coordinator, computing one
balanced global range with results exchanged over XLA collectives
(SURVEY.md §7 step 6; VERDICT r4 next-round #4).

The in-job assertions (correctness, share agreement, balancer movement)
live in tests/_dcn_worker.py — this file owns process lifecycle only.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(n_devices: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # production default: x64 OFF — the worker's 64-bit exchange check
    # must run against real canonicalization, not the rig's x64 override
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env


def test_two_process_distributed_compute():
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "_dcn_worker.py")
    port = _free_port()
    nproc = 2
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port)],
            env=_worker_env(4), cwd=os.path.dirname(here),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"DCN_OK pid={pid}" in out, out[-3000:]
