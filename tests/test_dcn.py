"""DCN multi-host tier (cluster/dcn.py): real processes x virtual CPU
devices joined through a jax.distributed coordinator, computing one
balanced global range with results exchanged over XLA collectives
(SURVEY.md §7 step 6; VERDICT r4 next-round #4).

Two jobs:
- symmetric 2 processes x 4 devices (the original parity proof);
- ASYMMETRIC 3 processes x (4, 2, 2) devices (VERDICT r5 #6): the
  configuration `_allgather`'s design argument rests on — per-process
  steps differ, the LCM-step table must reflect them, and shares must
  snap to each process's own step.  Skip-guarded for constrained CI via
  ``CK_SKIP_DCN_ASYM=1``.

The in-job assertions (correctness, share agreement, LCM-step table,
balancer movement) live in tests/_dcn_worker.py — this file owns process
lifecycle only.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(n_devices: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # production default: x64 OFF — the worker's 64-bit exchange check
    # must run against real canonicalization, not the rig's x64 override
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env


def _run_job(counts: list[int], timeout: float = 240.0) -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "_dcn_worker.py")
    port = _free_port()
    nproc = len(counts)
    counts_arg = ",".join(str(c) for c in counts)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port),
             counts_arg],
            env=_worker_env(counts[pid]), cwd=os.path.dirname(here),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"DCN_OK pid={pid}" in out, out[-3000:]


def test_two_process_distributed_compute():
    _run_job([4, 4])


@pytest.mark.skipif(
    os.environ.get("CK_SKIP_DCN_ASYM") == "1",
    reason="asymmetric DCN job disabled (CK_SKIP_DCN_ASYM=1)",
)
def test_asymmetric_three_process_distributed_compute():
    """4+2+2 virtual devices across 3 processes (VERDICT r5 #6): unequal
    per-process steps through the same SPMD balancer — the share table,
    LCM-step table, and exchange must all hold without the symmetric
    reshape `multihost_utils.process_allgather` would need."""
    _run_job([4, 2, 2])
