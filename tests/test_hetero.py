"""Heterogeneous lanes (ISSUE 20): TPU + host-CPU device kinds in ONE
``Cores`` — the prior-seeded split math, the Cores integration (seed,
provenance, warmup rollup), cross-kind compile-cache isolation, the
per-lane-kind attribution rollup, and the hetero_sweep bench section.

The CPU-only container cannot mint real mixed silicon, so the tests
exercise the same seams the sweep does: ``Cores.lane_kinds`` /
``Cores.rate_priors`` are overridable state (the emulation seam — a real
mixed rig fills them from ``jax.Device.device_kind``), and the
compile-cache side is pinned at the key level (``ladder_key`` must
differ in ``device_kind`` alone) plus the live launcher-cache platform
counters."""

import importlib.util
import os
import time

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu import hardware as hw
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.core.balance import equal_split, prior_split
from cekirdekler_tpu.core.compilecache import CACHE, WarmupSpec
from cekirdekler_tpu.hardware import device_rank, platforms, rate_prior
from cekirdekler_tpu.obs.decisions import DECISIONS
from cekirdekler_tpu.trace.attribution import window_report
from cekirdekler_tpu.trace.spans import Span

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

SRC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def _mark() -> int:
    recs = DECISIONS.snapshot()
    return recs[-1].seq if recs else 0


def _since(mark: int) -> list:
    return [r for r in DECISIONS.snapshot() if r.seq > mark]


# ---------------------------------------------------------------------------
# the rate-prior table (hardware.py)
# ---------------------------------------------------------------------------

def test_rate_prior_table_and_ranking_from_one_table():
    """Satellite 6: device ranking and the balancer prior export from
    ONE table — every accelerator kind beats the host-CPU baseline and
    the rank order is exactly the prior order."""
    assert rate_prior("cpu") == 1.0
    assert rate_prior("host") == 1.0
    assert rate_prior("TPU v5p") > rate_prior("TPU v4") > 1.0
    kinds = ["TPU v5p", "TPU v6e", "TPU v4", "TPU v5e", "cpu"]
    priors = [rate_prior(k) for k in kinds]
    assert priors == sorted(priors, reverse=True)
    ranks = [device_rank(k) for k in kinds]
    assert ranks == sorted(ranks)  # faster kind, better (lower) rank
    # unknown accelerator kinds fall back to the default peak kind's
    # prior, never to the CPU baseline
    assert rate_prior("TPU vNext") > 1.0
    for name in ("rate_prior", "device_rank"):
        assert name in hw.__all__


# ---------------------------------------------------------------------------
# prior_split math + provenance
# ---------------------------------------------------------------------------

def test_prior_split_quantized_within_one_step_of_share():
    for total, step, priors in [
        (1024, 64, [8.0, 1.0]),
        (3072, 128, [55.3, 1.0, 1.0]),
        (8192, 64, [100.0, 1.0]),
        (2048, 256, [2.0, 3.0, 5.0]),
        (640, 64, [1.0, 1.0, 1.0, 1.0, 1.0]),
    ]:
        got = prior_split(total, step, priors)
        assert sum(got) == total
        assert all(r % step == 0 for r in got)
        s = sum(priors)
        for r, p in zip(got, priors):
            assert abs(r - total * p / s) <= step, (got, priors)


def test_prior_split_equal_priors_reproduce_equal_split_bitwise():
    """The homogeneous degenerate case must be BIT-identical to
    equal_split — a same-kind fleet's decision history cannot change
    shape when the prior plumbing is present."""
    for total, step, n in [(1024, 64, 2), (3072, 128, 3), (896, 64, 7)]:
        assert prior_split(total, step, [1.0] * n) == \
            equal_split(total, n, step)
        assert prior_split(total, step, [3.5] * n) == \
            equal_split(total, n, step)


def test_prior_split_records_replayable_decision():
    mark = _mark()
    got = prior_split(1024, 64, [8.0, 1.0], cid=41)
    recs = [r for r in _since(mark) if r.kind == "prior-split"]
    assert len(recs) == 1
    r = recs[0]
    assert r.inputs["priors"] == [8.0, 1.0]
    assert r.inputs["total"] == 1024 and r.inputs["step"] == 64
    assert r.inputs["cid"] == 41
    assert r.outputs["ranges"] == got == [896, 128]


# ---------------------------------------------------------------------------
# Cores integration: seed, provenance, warmup rollup
# ---------------------------------------------------------------------------

def test_cores_prior_seeds_first_split_and_records_provenance(devs):
    """A skewed-prior fleet's FIRST split is the rate-implied one (no
    equal-split warm-up shard), and every load-balance record carries
    the priors so replay/what-if can reconstruct the seeding."""
    n, lr = 4096, 64
    cr = NumberCruncher(devs.subset(2), SRC)
    try:
        # the emulation seam: a real mixed rig gets these from
        # jax.Device.device_kind via hardware.rate_prior
        cr.cores.lane_kinds = ["tpu-emu", "cpu"]
        cr.cores.rate_priors = [8.0, 1.0]
        mark = _mark()
        x = ClArray(np.zeros(n, np.float32), name="hx")
        x.partial_read = True
        for _ in range(3):
            x.compute(cr, 71, "inc", n, lr)
        np.testing.assert_array_equal(np.asarray(x), 3.0)
        expect = prior_split(n, lr, [8.0, 1.0])
        seeds = [r for r in _since(mark) if r.kind == "prior-split"
                 and r.inputs.get("cid") == 71]
        assert len(seeds) == 1
        assert seeds[0].outputs["ranges"] == expect == [3648, 448]
        lbs = [r for r in _since(mark) if r.kind == "load-balance"
               and r.inputs.get("cid") == 71]
        assert lbs, "no load-balance records for the computed cid"
        assert all(r.inputs["rate_prior"] == [8.0, 1.0] for r in lbs)
        # the first balance step starts FROM the seed
        assert lbs[0].inputs["ranges"] == expect
    finally:
        cr.dispose()


def test_cores_homogeneous_fleet_keeps_equal_split_history(devs):
    """Equal priors (the default on a same-kind fleet) must leave the
    decision history EXACTLY as before ISSUE 20: equal first split, no
    prior-split record, rate_prior=None on the balance records."""
    n, lr = 4096, 64
    cr = NumberCruncher(devs.subset(2), SRC)
    try:
        assert cr.cores._skewed_priors() is None
        mark = _mark()
        x = ClArray(np.zeros(n, np.float32), name="hh")
        x.partial_read = True
        for _ in range(3):
            x.compute(cr, 72, "inc", n, lr)
        assert not [r for r in _since(mark) if r.kind == "prior-split"]
        lbs = [r for r in _since(mark) if r.kind == "load-balance"
               and r.inputs.get("cid") == 72]
        assert lbs and all(r.inputs["rate_prior"] is None for r in lbs)
        assert lbs[0].inputs["ranges"] == equal_split(n, 2, lr)
    finally:
        cr.dispose()


def test_cores_lane_kind_state_and_prior_gauges(devs):
    """Every lane gets a kind label and a table-derived prior at
    construction, exported as the ck_lane_rate_prior gauge with the
    ck_lane_kind label (docs/OBSERVABILITY.md)."""
    from cekirdekler_tpu.metrics.registry import REGISTRY

    cr = NumberCruncher(devs.subset(2), SRC)
    try:
        cores = cr.cores
        assert len(cores.lane_kinds) == cores.num_devices
        assert cores.rate_priors == [rate_prior(k)
                                     for k in cores.lane_kinds]
        g = REGISTRY.gauge(
            "ck_lane_rate_prior",
            "table-derived relative-rate prior per lane",
            lane=0, ck_lane_kind=cores.lane_kinds[0])
        assert g.value == cores.rate_priors[0]
    finally:
        cr.dispose()


def test_warmup_rolls_up_ladders_per_device_kind(devs):
    """Mixed-fleet AOT warmup proof: the warmup report counts ladders
    per DEVICE KIND, so a fleet with a cold kind is visible before
    traffic arrives.  Kind variants are emulated by widening the warm
    target list the same way a real mixed fleet would."""
    n, lr = 1024, 64
    cr = NumberCruncher(devs.subset(2), SRC)
    try:
        cores = cr.cores
        out = cores.warmup(
            [WarmupSpec(kernels=("inc",), params=((n, "float32"),),
                        global_range=n, local_range=lr, values=())])
        assert out["warmed"] == 1 and out["skipped"] == 0
        # homogeneous fleet: one kind, one AOT pass
        assert sum(out["kinds"].values()) == 1
        real = cores._warm_targets()
        (platform, donate, kind, device) = real[0]
        cores._warm_targets = lambda: [
            (platform, donate, kind, device),
            (platform, donate, "tpu-emu", device),
        ]
        out2 = cores.warmup(
            [WarmupSpec(kernels=("inc",), params=((n, "float32"),),
                        global_range=n, local_range=lr, values=())])
        assert out2["kinds"] == {kind: 1, "tpu-emu": 1}
    finally:
        cr.dispose()


# ---------------------------------------------------------------------------
# cross-kind compile-cache isolation
# ---------------------------------------------------------------------------

def test_ladder_key_isolates_device_kinds():
    """The persistent-cache key must differ in device_kind ALONE —
    a CPU lane's ladder can never serve (or evict) a TPU lane's."""
    from cekirdekler_tpu.kernel.registry import KernelProgram

    prog = KernelProgram(SRC)
    spec = WarmupSpec(kernels=("inc",), params=((1024, "float32"),),
                      global_range=1024, local_range=64, values=())
    k_cpu = CACHE.ladder_key(prog, spec, "cpu", False, "cpu")
    k_tpu = CACHE.ladder_key(prog, spec, "cpu", False, "TPU v5p")
    assert k_cpu != k_tpu
    # ... and is stable per kind (the warmup==live pin rides on this)
    assert k_cpu == CACHE.ladder_key(prog, spec, "cpu", False, "cpu")


def test_mixed_fleet_compile_counters_pinned_per_platform(devs):
    """One Cores over emulated cpu+tpu kinds: the launcher cache keys
    by PLATFORM, the mixed fleet's CPU lanes only ever grow the cpu
    counter, nothing is evicted cross-kind, and results stay
    bit-identical with the homogeneous fleet — fused on AND off."""
    n, lr = 4096, 64

    def run(kinds, priors, fused):
        cr = NumberCruncher(devs.subset(2), SRC)
        try:
            if kinds:
                cr.cores.lane_kinds = list(kinds)
                cr.cores.rate_priors = list(priors)
            cr.fused_dispatch = fused
            x = ClArray(np.zeros(n, np.float32), name="mx")
            x.partial_read = True
            cr.enqueue_mode = True
            for _ in range(6):
                x.compute(cr, 73, "inc", n, lr)
            cr.enqueue_mode = False
            counts = cr.cores.program.compiled_counts_by_platform()
            return np.asarray(x).copy(), counts
        finally:
            cr.dispose()

    for fused in (True, False):
        homog, _ = run(None, None, fused)
        mixed, counts = run(["tpu-emu", "cpu"], [8.0, 1.0], fused)
        np.testing.assert_array_equal(mixed, homog)
        np.testing.assert_array_equal(mixed, 6.0)
        # a CPU-platform lane never mints a tpu-platform executable:
        # the only platform key the launcher cache grew is "cpu"
        assert set(counts) == {"cpu"}, counts
        assert counts["cpu"] >= 1


# ---------------------------------------------------------------------------
# per-lane-kind attribution rollup
# ---------------------------------------------------------------------------

def test_window_report_rolls_up_per_lane_kind():
    t0 = 100.0
    spans = [
        Span(kind="kernel", t0=t0 + 0.00, t1=t0 + 0.10, cid=1, lane=0,
             tag=None),
        Span(kind="kernel", t0=t0 + 0.00, t1=t0 + 0.02, cid=1, lane=1,
             tag=None),
        Span(kind="h2d", t0=t0 + 0.10, t1=t0 + 0.15, cid=1, lane=0,
             tag=None),
        # lane-less host span: counted per kind, absent from the rollup
        Span(kind="fence", t0=t0 + 0.15, t1=t0 + 0.20, cid=1, lane=None,
             tag=None),
    ]
    # list form (Cores.lane_kinds by position) and dict form agree
    for lane_kinds in (["TPU v5p", "cpu"], {0: "TPU v5p", 1: "cpu"}):
        rep = window_report(spans, t0, t0 + 0.25, lane_kinds=lane_kinds)
        assert set(rep.per_lane_kind) == {"TPU v5p", "cpu"}
        tpu = rep.per_lane_kind["TPU v5p"]
        assert tpu["count"] == 2 and tpu["lanes"] == {0}
        assert tpu["ms"] == pytest.approx(150.0, abs=1e-6)
        cpu = rep.per_lane_kind["cpu"]
        assert cpu["count"] == 1 and cpu["lanes"] == {1}
        assert cpu["ms"] == pytest.approx(20.0, abs=1e-6)
        d = rep.to_dict()["per_lane_kind"]
        assert d["TPU v5p"]["lanes"] == [0]
        assert "device kind" in rep.table()
    # without the map the rollup stays empty (no lane->kind guess)
    assert window_report(spans, t0, t0 + 0.25).per_lane_kind == {}


def test_nbody_attribution_accepts_lane_kinds():
    """workloads._nbody_attribution forwards Cores.lane_kinds into the
    report: the per_lane_kind_ms block names each kind.  probe_devs is
    None on purpose — the single-lane interference probe fails closed
    and must not block the per-kind rollup."""
    from cekirdekler_tpu.workloads import _nbody_attribution

    t0 = 50.0
    spans = [
        Span(kind="launch", t0=t0, t1=t0 + 0.010, cid=5, lane=0, tag=None),
        Span(kind="launch", t0=t0, t1=t0 + 0.002, cid=5, lane=1, tag=None),
    ]
    out = _nbody_attribution(
        spans, t0, t0 + 0.02, wall=0.02, iters=4, lanes=2,
        probe_devs=None, n=64, dt=0.01, local_range=32, window=2,
        probe_iters=1, lane_kinds=["tpu-emu", "cpu"])
    blk = out["per_lane_kind_ms"]
    assert set(blk) == {"tpu-emu", "cpu"}
    assert blk["tpu-emu"]["ms"] >= blk["cpu"]["ms"]
    assert blk["tpu-emu"]["lanes"] == [0]
    assert "error" in out["lane_interference"]


# ---------------------------------------------------------------------------
# the hetero_sweep bench section (small-n smoke)
# ---------------------------------------------------------------------------

def test_hetero_sweep_section_smoke(devs):
    spec = importlib.util.spec_from_file_location(
        "ck_hetero_sweep", os.path.join(ROOT, "tools", "hetero_sweep.py"))
    sweep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sweep)

    out = sweep.hetero_section(devices=devs, n=16384, local_range=64,
                               iters=3, skew=8.0)
    assert "skipped" not in out, out
    assert out["pinned_model"] is True
    assert out["exact"] is True
    # all four arms computed the same bits — the gate the headline
    # rides on — so the key is minted
    sp = out["hetero_speedup_vs_best_homog"]
    assert sp is not None and sp > 1.0
    # model walls: mixed beats BOTH homogeneous subsets
    assert out["walls"]["mixed"] < out["walls"]["fast_only"]
    assert out["walls"]["mixed"] < out["walls"]["slow_only"]
    # the seed landed within one quantization step of rate-implied
    assert out["prior_split_within_one_step"] is True
    # the traced mixed arm attributed time to BOTH device kinds
    kinds = out["per_lane_kind"]
    assert set(kinds) == {sweep.EMU_FAST_KIND, sweep.EMU_SLOW_KIND}
    assert all(v["count"] > 0 for v in kinds.values())
