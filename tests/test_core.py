"""Core runtime tests: NumberCruncher + Cores scheduler over the 8-device
virtual rig (reference test pattern: Tester.cs correctness matrix — verify
element-wise against host references for every transfer-flag combination,
device count, and pipeline mode)."""

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import PIPELINE_DRIVER, PIPELINE_EVENT, NumberCruncher
from cekirdekler_tpu.errors import ComputeValidationError
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.kernel import kernel

VADD = """
__kernel void vadd(__global float* a, __global float* b, __global float* c) {
    int i = get_global_id(0);
    c[i] = a[i] + b[i];
}
__kernel void scale2(__global float* a, __global float* b, __global float* c) {
    int i = get_global_id(0);
    c[i] = c[i] * 2.0f;
}
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def make_abc(n=1024, partial=True):
    a = ClArray(np.arange(n, dtype=np.float32), name="a")
    b = ClArray(np.ones(n, dtype=np.float32), name="b")
    c = ClArray(n, name="c")
    if partial:
        a.partial_read = True
        b.partial_read = True
    return a, b, c


@pytest.mark.parametrize("ndev", [1, 2, 3, 8])
def test_vadd_device_counts(devs, ndev):
    cr = NumberCruncher(devs.subset(ndev), VADD)
    a, b, c = make_abc()
    a.next_param(b).next_param(c).compute(cr, 1, "vadd", 1024, 64)
    np.testing.assert_allclose(np.asarray(c), np.arange(1024) + 1)
    assert sum(cr.ranges_of(1)) == 1024
    cr.dispose()


@pytest.mark.parametrize("ptype", [PIPELINE_EVENT, PIPELINE_DRIVER])
def test_vadd_pipelined(devs, ptype):
    cr = NumberCruncher(devs.subset(4), VADD)
    a, b, c = make_abc(4096)
    c.write = True
    g = a.next_param(b).next_param(c)
    g.compute(cr, 1, "vadd", 4096, 64, pipeline=True, pipeline_blobs=4, pipeline_type=ptype)
    np.testing.assert_allclose(np.asarray(c), np.arange(4096) + 1)
    cr.dispose()


def test_multi_kernel_sequence(devs):
    """'vadd scale2' runs kernels in order over the same args."""
    cr = NumberCruncher(devs.subset(2), VADD)
    a, b, c = make_abc()
    a.next_param(b).next_param(c).compute(cr, 7, "vadd scale2", 1024, 64)
    np.testing.assert_allclose(np.asarray(c), (np.arange(1024) + 1) * 2)
    cr.dispose()


def test_single_array_inplace(devs):
    cr = NumberCruncher(devs.subset(4), VADD)
    a = ClArray(np.zeros(512, np.float32), name="x")
    a.partial_read = True
    for it in range(3):
        a.compute(cr, 3, "inc", 512, 64)
    np.testing.assert_allclose(np.asarray(a), 3.0)
    cr.dispose()


def test_balancer_iterates_on_virtual_devices(devs):
    cr = NumberCruncher(devs.subset(4), VADD)
    a, b, c = make_abc(4096)
    g = a.next_param(b).next_param(c)
    for _ in range(8):
        g.compute(cr, 1, "vadd", 4096, 64)
    r = cr.ranges_of(1)
    assert sum(r) == 4096 and all(x % 64 == 0 for x in r)
    bench = cr.benchmarks_of(1)
    assert all(m > 0 for m in bench)
    rep = cr.performance_report(1)
    assert "workitems" in rep and "load" in rep
    cr.dispose()


def test_full_read_non_partial(devs):
    """Without partial_read every chip gets the whole input (needed for
    gather-style kernels reading outside their range)."""
    src = """
    __kernel void rev(__global float* a, __global float* b, int n) {
        int i = get_global_id(0);
        b[i] = a[n - 1 - i];
    }"""
    cr = NumberCruncher(devs.subset(4), src)
    n = 512
    a = ClArray(np.arange(n, dtype=np.float32), name="a")  # full read (default)
    b = ClArray(n, name="b")
    a.next_param(b).compute(cr, 1, "rev", n, 64, values=(n,))
    np.testing.assert_allclose(np.asarray(b), np.arange(n)[::-1])
    cr.dispose()


def test_write_all(devs):
    """write_all: one owning chip writes the entire array back."""
    src = """
    __kernel void fill(__global float* out) {
        int i = get_global_id(0);
        if (i == 0) {
            for (int j = 0; j < 64; j++) { out[j] = 5.0f; }
        }
    }"""
    cr = NumberCruncher(devs.subset(2), src)
    out = ClArray(np.zeros(64, np.float32), name="o")
    out.read = False
    out.write_all = True
    out.compute(cr, 1, "fill", 64, 32)
    np.testing.assert_allclose(np.asarray(out), 5.0)
    cr.dispose()


def test_read_only_not_written_back(devs):
    cr = NumberCruncher(devs.subset(2), VADD)
    a, b, c = make_abc()
    a.read_only = True
    b.read_only = True
    a.next_param(b).next_param(c).compute(cr, 1, "vadd", 1024, 64)
    np.testing.assert_allclose(np.asarray(c), np.arange(1024) + 1)
    np.testing.assert_allclose(np.asarray(a), np.arange(1024))  # untouched
    cr.dispose()


def test_write_only_skips_upload(devs):
    src = """
    __kernel void seven(__global float* o) {
        int i = get_global_id(0);
        o[i] = 7.0f;
    }"""
    cr = NumberCruncher(devs.subset(2), src)
    o = ClArray(np.full(256, -1, np.float32), name="o")
    o.write_only = True
    o.compute(cr, 1, "seven", 256, 64)
    np.testing.assert_allclose(np.asarray(o), 7.0)
    cr.dispose()


def test_enqueue_mode_defers_readback(devs):
    cr = NumberCruncher(devs.subset(2), VADD)
    x = ClArray(np.zeros(256, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    for _ in range(5):
        x.compute(cr, 1, "inc", 256, 64)
    # host not yet updated (results still in HBM)
    assert np.all(np.asarray(x) == 0.0)
    cr.enqueue_mode = False  # leaving enqueue mode flushes
    np.testing.assert_allclose(np.asarray(x), 5.0)
    cr.dispose()


def test_repeat_count_on_device(devs):
    cr = NumberCruncher(devs.subset(2), VADD)
    x = ClArray(np.zeros(256, np.float32), name="x")
    x.partial_read = True
    cr.repeat_count = 10
    x.compute(cr, 1, "inc", 256, 64)
    np.testing.assert_allclose(np.asarray(x), 10.0)
    cr.dispose()


def test_value_args_passthrough(devs):
    src = """
    __kernel void axpb(__global float* x, float aa, float bb) {
        int i = get_global_id(0);
        x[i] = aa * x[i] + bb;
    }"""
    cr = NumberCruncher(devs.subset(2), src)
    x = ClArray(np.ones(128, np.float32), name="x")
    x.partial_read = True
    x.compute(cr, 1, "axpb", 128, 64, values=(2.0, 5.0))
    np.testing.assert_allclose(np.asarray(x), 7.0)
    cr.dispose()


def test_fixed_compute_powers(devs):
    cr = NumberCruncher(devs.subset(2), VADD)
    cr.normalized_compute_powers_of_devices = [3, 1]
    a, b, c = make_abc()
    a.next_param(b).next_param(c).compute(cr, 1, "vadd", 1024, 64)
    r = cr.ranges_of(1)
    assert r[0] == 768 and r[1] == 256
    np.testing.assert_allclose(np.asarray(c), np.arange(1024) + 1)
    cr.dispose()


def test_separate_compute_ids_independent(devs):
    cr = NumberCruncher(devs.subset(4), VADD)
    a, b, c = make_abc(512)
    g = a.next_param(b).next_param(c)
    g.compute(cr, 1, "vadd", 512, 64)
    g.compute(cr, 2, "vadd", 512, 64)
    assert cr.ranges_of(1) == cr.ranges_of(2)
    assert 1 in cr.cores.perf and 2 in cr.cores.perf
    cr.dispose()


def test_validation_errors(devs):
    cr = NumberCruncher(devs.subset(2), VADD)
    a, b, c = make_abc(128)
    g = a.next_param(b).next_param(c)
    with pytest.raises(ComputeValidationError):
        g.compute(cr, 1, "vadd", 100, 64)  # not divisible
    with pytest.raises(ComputeValidationError):
        g.compute(cr, 1, "nosuch", 128, 64)
    with pytest.raises(ComputeValidationError):
        g.compute(cr, 1, "vadd", 256, 64)  # arrays too small
    cr.dispose()


def test_python_kernel_through_cruncher(devs):
    @kernel
    def triple(gid, a):
        return a.at[gid].multiply(3.0)

    cr = NumberCruncher(devs.subset(2), triple)
    x = ClArray(np.ones(256, np.float32), name="x")
    x.partial_read = True
    x.compute(cr, 1, "triple", 256, 64)
    np.testing.assert_allclose(np.asarray(x), 3.0)
    cr.dispose()


def test_fastarr_backed_compute(devs):
    cr = NumberCruncher(devs.subset(2), VADD)
    a, b, c = make_abc()
    a.fast_arr = True
    c.fast_arr = True
    a.next_param(b).next_param(c).compute(cr, 1, "vadd", 1024, 64)
    np.testing.assert_allclose(np.asarray(c), np.arange(1024) + 1)
    cr.dispose()


def test_repeat_is_one_fused_dispatch(devs):
    """repeat_count=100 issues O(1) dispatches (lax.fori_loop on device) —
    asserted via marker counts (VERDICT r1 #9; reference: computeRepeated,
    Worker.cs:36-46)."""
    cr = NumberCruncher(devs.subset(1), VADD)
    cr.fine_grained_queue_control = True
    x = ClArray(np.zeros(256, np.float32), name="x")
    x.partial_read = True
    cr.repeat_count = 100
    x.compute(cr, 1, "inc", 256, 64)
    np.testing.assert_allclose(np.asarray(x), 100.0)
    w = cr.cores.workers[0]
    # markers: 1 upload + 1 fused launch + 1 download = 3, NOT ~100
    assert w.markers.added <= 4, w.markers.added
    cr.dispose()


def test_repeat_with_sync_kernel_fused(devs):
    cr = NumberCruncher(devs.subset(1), VADD)
    x = ClArray(np.zeros(128, np.float32), name="x")
    x.partial_read = True
    cr.repeat_count = 5
    cr.repeat_kernel_name = "inc"  # sync kernel between repeats
    x.compute(cr, 1, "inc", 128, 64)
    # 5 repeats of inc + 4 interleaved sync incs = 9 total
    np.testing.assert_allclose(np.asarray(x), 9.0)
    cr.dispose()


def test_zero_copy_changes_transfer_path(devs):
    """flags.zero_copy takes the dlpack import path on the CPU backend
    (the CL_MEM_USE_HOST_PTR analogue, SURVEY.md §7) — observable via
    Worker.last_upload_path (VERDICT r1 #8)."""
    cr = NumberCruncher(devs.subset(1), VADD)
    w = cr.cores.workers[0]
    x = ClArray(np.zeros(256, np.float32), name="x")
    x.compute(cr, 1, "inc", 256, 64)
    assert w.last_upload_path == "staged-dma"
    y = ClArray(np.zeros(256, np.float32), name="y")
    y.zero_copy = True
    y.compute(cr, 2, "inc", 256, 64)
    assert w.last_upload_path.startswith("dlpack"), w.last_upload_path
    np.testing.assert_allclose(np.asarray(y), 1.0)
    cr.dispose()


def test_compute_error_gates_further_work(devs):
    """A failed compute trips number_of_errors_happened and subsequent
    computes refuse to run until reset_errors() (reference:
    ClNumberCruncher.cs:374-392, ClArray.cs:1610-1623)."""
    cr = NumberCruncher(devs.subset(1), VADD)
    x = ClArray(np.zeros(256, np.float32), name="x")
    with pytest.raises(Exception):
        # unknown kernel -> validation error inside cores.compute
        x.compute(cr, 1, "nonexistent_kernel", 256, 64)
    assert cr.number_of_errors_happened == 1
    with pytest.raises(ComputeValidationError, match="previous error"):
        x.compute(cr, 1, "inc", 256, 64)
    cr.reset_errors()
    x.compute(cr, 1, "inc", 256, 64)  # works again
    np.testing.assert_allclose(np.asarray(x), 1.0)
    cr.dispose()


@pytest.mark.parametrize("ptype", [PIPELINE_EVENT, PIPELINE_DRIVER])
def test_pipeline_engines_multi_blob_multi_kernel(devs, ptype):
    """Both engines produce identical results over many blobs with a
    2-kernel sequence and partial reads/writes."""
    cr = NumberCruncher(devs.subset(2), VADD)
    n = 8192
    a, b, c = make_abc(n)
    c.write = True
    g = a.next_param(b).next_param(c)
    g.compute(cr, 1, "vadd scale2", n, 64, pipeline=True,
              pipeline_blobs=8, pipeline_type=ptype)
    np.testing.assert_allclose(np.asarray(c), (np.arange(n) + 1) * 2)
    cr.dispose()


def test_markers_observe_real_retirement(devs):
    """Markers retire via completion threads: after a compute fully
    drains, added == reached; marker_reach_speed reflects retirement."""
    cr = NumberCruncher(devs.subset(2), VADD)
    cr.fine_grained_queue_control = True
    a, b, c = make_abc(1024)
    a.next_param(b).next_param(c).compute(cr, 1, "vadd", 1024, 64)
    for w in cr.cores.workers:
        if w.markers is not None:
            w.markers.drain(timeout=10.0)
    assert cr.count_markers_remaining() == 0
    assert cr.count_markers_reached() > 0
    cr.dispose()


def test_enqueue_mode_rebalances_at_barrier(devs):
    """Enqueue mode must NOT pin ranges forever: barrier() measures each
    chip's fence-retire time and arms a rebalance for the next call
    (VERDICT r2 #4 — sync-granularity analogue of the reference feeding
    event benches into loadBalance, HelperFunctions.cs:190-280).  A chip
    made artificially slow at the fence loses share, and results stay
    correct after the boundary moves."""
    cr = NumberCruncher(devs.subset(2), VADD)
    x = ClArray(np.zeros(4096, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    slow = cr.cores.workers[0]
    orig_fence = slow.fence

    def laggy_fence():
        import time as _t

        _t.sleep(0.25)  # pretend this chip retires late
        orig_fence()

    slow.fence = laggy_fence
    try:
        for _ in range(3):
            x.compute(cr, 9, "inc", 4096, 64)
        first = cr.ranges_of(9)
        cr.barrier()  # measures per-chip retirement, arms rebalance
        for _ in range(3):
            x.compute(cr, 9, "inc", 4096, 64)
        second = cr.ranges_of(9)
    finally:
        slow.fence = orig_fence
    assert first[0] == first[1], "first split should be equal"
    assert second[0] < first[0], "slow chip should lose share after barrier"
    assert sum(second) == 4096
    cr.enqueue_mode = False  # flush
    np.testing.assert_allclose(np.asarray(x), 6.0)
    cr.dispose()


def test_enqueue_rebalance_write_only_image(devs):
    """Write-only output stays correct across an enqueue-mode range move:
    the grown chip recomputes its acquired region and flush() lands host
    writes chronologically (newest record wins)."""
    src = """
    __kernel void fillidx(__global float* o) {
        int i = get_global_id(0);
        o[i] = (float)i;
    }"""
    cr = NumberCruncher(devs.subset(2), src)
    o = ClArray(4096, np.float32, name="o")
    o.write_only = True
    cr.enqueue_mode = True
    slow = cr.cores.workers[1]
    orig_fence = slow.fence

    def laggy_fence():
        import time as _t

        _t.sleep(0.25)
        orig_fence()

    slow.fence = laggy_fence
    try:
        o.compute(cr, 11, "fillidx", 4096, 64)
        cr.barrier()
        o.compute(cr, 11, "fillidx", 4096, 64)
        moved = cr.ranges_of(11)
    finally:
        slow.fence = orig_fence
    assert moved[1] < 2048, "slow chip should have lost share"
    cr.enqueue_mode = False
    np.testing.assert_allclose(np.asarray(o), np.arange(4096, dtype=np.float32))
    cr.dispose()


def test_enqueue_rebalance_reacquired_range_not_stale(devs):
    """A chip that loses a region and later RE-acquires it must re-fetch
    it (coverage records are reset on every range move): alternate which
    chip is slow so ranges oscillate across barriers, and verify the
    read+write array stays exact."""
    import time as _t

    cr = NumberCruncher(devs.subset(2), VADD)
    x = ClArray(np.zeros(4096, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    w0, w1 = cr.cores.workers
    f0, f1 = w0.fence, w1.fence
    total = 0
    try:
        for phase in range(3):
            slow = w0 if phase % 2 == 0 else w1
            orig = f0 if phase % 2 == 0 else f1
            slow.fence = lambda orig=orig: (_t.sleep(0.2), orig())[1]
            for _ in range(2):
                x.compute(cr, 13, "inc", 4096, 64)
                total += 1
            cr.barrier()
            w0.fence, w1.fence = f0, f1
    finally:
        w0.fence, w1.fence = f0, f1
    cr.enqueue_mode = False
    np.testing.assert_allclose(np.asarray(x), float(total))
    cr.dispose()


def test_dispatch_gate_synchronized_start(devs):
    """Host-gated dispatch (ClUserEvent analogue): compute() issued from a
    worker thread holds until the host triggers the gate, then all lanes
    start (reference: Worker.cs:487-557 synchronized queue start)."""
    import threading
    import time as _t

    from cekirdekler_tpu.utils.events import UserEvent

    cr = NumberCruncher(devs.subset(2), VADD)
    x = ClArray(np.zeros(512, np.float32), name="x")
    x.partial_read = True
    gate = UserEvent()
    cr.dispatch_gate = gate
    done = threading.Event()

    def run():
        x.compute(cr, 21, "inc", 512, 64)
        done.set()

    t = threading.Thread(target=run)
    t.start()
    _t.sleep(0.15)
    assert not done.is_set(), "compute must hold until the gate fires"
    assert np.all(np.asarray(x) == 0.0)
    gate.trigger()
    t.join(timeout=10.0)
    assert done.is_set()
    np.testing.assert_allclose(np.asarray(x), 1.0)
    cr.dispatch_gate = None
    gate.close()
    cr.dispose()


def test_facade_compat_toggles(devs):
    """Reference facade parity: enqueue_mode_async_enable (always-on
    compatibility flag) and last_compute_performance_report."""
    cr = NumberCruncher(devs.subset(2), VADD)
    assert cr.enqueue_mode_async_enable is True
    cr.enqueue_mode_async_enable = False
    assert cr.enqueue_mode_async_enable is False
    a, b, c = make_abc()
    a.next_param(b).next_param(c).compute(cr, 1, "vadd", 1024, 64)
    rep = cr.last_compute_performance_report
    assert "compute id 1" in rep and "workitems" in rep
    cr.dispose()


def test_concurrent_compute_distinct_ids(devs):
    """VERDICT r3 #6: the reference's kernelWithId clones kernels per
    (name, computeId) so several host threads can drive one cruncher with
    different compute ids concurrently (Worker.cs:291-316).  Here the
    per-worker phase lock provides the same guarantee: 4 threads x distinct
    compute ids x many iterations on the 8-device rig, exact results and a
    recorded bench for every id."""
    import threading

    cr = NumberCruncher(devs.subset(8), VADD)
    n = 4096
    n_threads = 4
    iters = 6
    shared_b = ClArray(n, np.float32, name="sb", read_only=True)
    shared_b.host()[:] = 1.0
    errors: list = []

    def work(tid: int):
        try:
            cid = 900 + tid
            a = ClArray(n, np.float32, name=f"a{tid}", partial_read=True,
                        read_only=True)
            c = ClArray(n, np.float32, name=f"c{tid}", write=True)
            host_a = np.full(n, float(tid), np.float32)
            a.host()[:] = host_a
            for k in range(iters):
                a.next_param(shared_b, c).compute(cr, cid, "vadd", n, 64)
                np.testing.assert_allclose(
                    np.asarray(c), host_a + 1.0, rtol=1e-6,
                    err_msg=f"thread {tid} iter {k}",
                )
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    assert not errors, errors
    # no lost benches: every compute id has a measured per-chip time
    for tid in range(n_threads):
        cid = 900 + tid
        assert cid in cr.cores.perf, f"compute id {cid} lost its perf record"
        assert any(
            w.benchmarks.get(cid, 0.0) > 0.0 for w in cr.cores.workers
        ), f"compute id {cid} lost its benches"
    cr.dispose()


def test_concurrent_fence_during_compute(devs):
    """fence() snapshots the buffer dict under the worker lock — a barrier
    racing a compute from another thread must not crash on dict mutation."""
    import threading

    cr = NumberCruncher(devs.subset(4), VADD)
    n = 2048
    stop = threading.Event()
    errors: list = []

    def hammer_barrier():
        try:
            while not stop.is_set():
                cr.barrier()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=hammer_barrier)
    t.start()
    try:
        for k in range(8):
            # fresh arrays each iteration -> new buffer-dict insertions
            a = ClArray(n, np.float32, name=f"fa{k}", read_only=True)
            b = ClArray(n, np.float32, name=f"fb{k}", read_only=True)
            c = ClArray(n, np.float32, name=f"fc{k}", write=True)
            a.host()[:] = float(k)
            b.host()[:] = 1.0
            a.next_param(b, c).compute(cr, 950 + k, "vadd scale2", n, 64)
            np.testing.assert_allclose(np.asarray(c), (float(k) + 1.0) * 2.0,
                                       rtol=1e-6)
    finally:
        stop.set()
        t.join(timeout=30.0)
    assert not errors, errors
    cr.dispose()


def test_event_pipeline_lookahead_depths_exact(devs):
    """The EVENT engine must stay exact at every read-lookahead depth
    (1 = the reference's wavefront, deeper = r4 DMA-latency hiding)."""
    n = 4096
    src = """
    __kernel void sa(__global float* a, __global float* b, __global float* c) {
        int i = get_global_id(0);
        c[i] = a[i] + 2.0f * b[i];
    }
    """
    av = np.arange(n, dtype=np.float32)
    bv = (np.arange(n, dtype=np.float32) % 13)
    want = av + 2.0 * bv
    for look in (1, 2, 4):
        cr = NumberCruncher(devs.subset(2), src)
        cr.pipeline_lookahead = look
        a = ClArray(av.copy(), name="la", partial_read=True, read_only=True)
        b = ClArray(bv.copy(), name="lb", partial_read=True, read_only=True)
        c = ClArray(n, np.float32, name="lc", write_only=True)
        a.next_param(b, c).compute(
            cr, 601 + look, "sa", n, 128, pipeline=True, pipeline_blobs=8)
        np.testing.assert_allclose(c.host(), want, rtol=1e-6,
                                   err_msg=f"lookahead={look}")
        cr.dispose()
