"""ckmodel — bounded exhaustive model checker: the acceptance suite.

Layers:

1. **The gate** — ``check_all()`` is clean on HEAD at tier-1 bounds,
   explores ≥ 10k canonical states across the four machines inside the
   tier-1 wall budget, and every declared invariant is exercised.
2. **Deliberately-broken fixture machines** — every invariant in every
   controller module's ``MODEL_INVARIANTS`` is refuted by at least one
   injected-broken transition/masker/planner, producing a minimal
   counterexample trace (the table is completeness-checked against the
   declared invariant ids).
3. **The counterexample→replay bridge** — broken-machine drain traces
   DIVERGE under ``verify_counterexample`` naming the first divergent
   seq (the regression drill); real-machine balance traces spill as
   ``ck-decision-log-v1`` jsonl that ``ckreplay verify`` replays green
   and ``ckreplay explain`` renders end-to-end.
4. **Violations fixed in this PR, pinned** — the balancer ±1-step swap
   limit cycle (two equal-rate lanes + one slow lane flipped the
   repair step forever; fixed by the REPAIR_TIE_BAND incumbent
   tie-break) via the committed trace
   ``tests/fixtures_decisions/model_swap_cycle.jsonl`` plus a live
   re-drive, and the coalescer rotation starvation (a G=4 all-present
   schedule starved one group 6 consecutive rounds under the old
   whole-list rotation; fixed by longest-starved-first promotion) via
   the concrete schedule + a randomized property sweep.
5. **CLI lifecycle** — clean-on-HEAD gate, ratchet refuses growth
   without ``--allow-grow``, stale entries name the burn commit
   (shared provenance header), ``--json`` schema pinned,
   ``--save-trace`` spills replayable jsonl.
6. **Purity lint** — the model-checked functions are clean on HEAD;
   clock/RNG/mutable-global reads in fixtures are flagged.
"""

import json
import os
import random
import sys
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from cekirdekler_tpu.analysis import model as M  # noqa: E402
from cekirdekler_tpu.cluster import elastic as E  # noqa: E402
from cekirdekler_tpu.core import balance as B  # noqa: E402
from cekirdekler_tpu.obs import drain as D  # noqa: E402
from cekirdekler_tpu.obs.decisions import (  # noqa: E402
    CONTEXT_KINDS,
    DECISION_KINDS,
    REPLAYABLE_KINDS,
    load_decision_log,
)
from cekirdekler_tpu.obs.replay import (  # noqa: E402
    save_counterexample,
    verify_counterexample,
    verify_records,
)
from cekirdekler_tpu.core import blocktuner as BT  # noqa: E402
from cekirdekler_tpu.serve import admission as A  # noqa: E402
from cekirdekler_tpu.serve import coalescer as C  # noqa: E402
from cekirdekler_tpu.serve import fabric as F  # noqa: E402
from cekirdekler_tpu.serve import resilience as R  # noqa: E402

import tools.ckmodel.cli as ckmodel_cli  # noqa: E402
from tools.ckmodel import purity  # noqa: E402

SWAP_CYCLE_FIXTURE = os.path.join(
    HERE, "fixtures_decisions", "model_swap_cycle.jsonl")


# ---------------------------------------------------------------------------
# 1. the gate: clean on HEAD, >= 10k states, every invariant exercised
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def head_report():
    t0 = time.perf_counter()
    rep = M.check_all()
    rep["_wall_s"] = time.perf_counter() - t0
    return rep


def test_clean_on_head_at_tier1_bounds(head_report):
    assert head_report["ok"], [
        v.render() for v in head_report["violations"]]
    assert not head_report["violations"]


def test_states_explored_floor_and_wall(head_report):
    """The acceptance bar: >= 10k canonical states across the four
    machines, inside the tier-1 wall budget (< 10 s excluding the
    package import, with a wide margin on this container)."""
    assert head_report["states_explored"] >= 10_000
    assert set(head_report["machines"]) == set(M.MACHINE_NAMES)
    for name, r in head_report["machines"].items():
        assert r["states_explored"] > 0, name
        assert not r["truncated"], name
    assert head_report["_wall_s"] < 10.0


def test_every_declared_invariant_exercised(head_report):
    for name, r in head_report["machines"].items():
        for sub, doc in r["sub_machines"].items():
            for inv_id, row in doc["invariants"].items():
                assert row["exercised"], (name, sub, inv_id)


def test_quick_profile_is_subsecond_and_jsonable():
    t0 = time.perf_counter()
    doc = M.tier1_check(quick=True)
    assert time.perf_counter() - t0 < 2.0
    assert doc["ok"] is True
    assert doc["states_explored"] > 0
    json.dumps(doc, allow_nan=False)  # the bench-artifact contract


def test_machines_declare_exactly_their_checks():
    """The _REPLAYERS discipline: a machine whose implemented checks
    drift from the module's MODEL_INVARIANTS refuses to construct."""

    class Drifted(M.DrainMachine):
        checks = ("availability-floor",)  # subset

    with pytest.raises(AssertionError, match="MODEL_INVARIANTS"):
        Drifted(lanes=2)


# ---------------------------------------------------------------------------
# 2. deliberately-broken fixture machines, one per declared invariant
# ---------------------------------------------------------------------------

def _no_floor(verdicts, states, hold, streak, hb, cc, probe_grace=2):
    res = D.drain_transition(verdicts, states, hold, streak, hb, cc,
                             probe_grace=probe_grace)
    for lane, v in {str(k): v for k, v in verdicts.items()}.items():
        if v == "degraded" and res["states"].get(lane) == D.LANE_ACTIVE:
            res["states"][lane] = D.LANE_QUARANTINED
            res["hold"][lane] = hb
            res["drained"].append(lane)
    return res


def _leaky_masker(ranges, step, drained, probation):
    out = list(D.apply_quarantine(ranges, step, drained, probation))
    if drained or probation:
        out[-1] += step  # invented share
    return out


def _double_probe_masker(ranges, step, drained, probation):
    # conservation-preserving but the probe share is 2 steps
    return D.apply_quarantine(ranges, 2 * step, drained, probation)


def _silent_drain(verdicts, states, hold, streak, hb, cc, probe_grace=2):
    res = D.drain_transition(verdicts, states, hold, streak, hb, cc,
                             probe_grace=probe_grace)
    if res["drained"]:
        res = dict(res, drained=res["drained"][:-1])  # hide one
    return res


def _never_readmit(verdicts, states, hold, streak, hb, cc, probe_grace=2):
    res = D.drain_transition(verdicts, states, hold, streak, hb, cc,
                             probe_grace=probe_grace)
    if res["readmitted"]:
        states_out = dict(res["states"])
        streak_out = dict(res["clear_streak"])
        for lane in res["readmitted"]:
            states_out[lane] = D.LANE_PROBATION
            streak_out[lane] = 0
        res = dict(res, states=states_out, clear_streak=streak_out,
                   readmitted=[])
    return res


def _flappy(verdicts, states, hold, streak, hb, cc, probe_grace=2):
    """Re-quarantines a probation lane even on an ok verdict — the
    stale-verdict relapse loop PR 12's probe_grace exists to prevent,
    taken to its extreme (no readmission path survives)."""
    res = D.drain_transition(verdicts, states, hold, streak, hb, cc,
                             probe_grace=probe_grace)
    vmap = {str(k): v for k, v in verdicts.items()}
    pre = {str(k): v for k, v in states.items()}
    for lane, st in pre.items():
        if st == D.LANE_PROBATION and vmap.get(lane, "ok") == "ok":
            res["states"][lane] = D.LANE_QUARANTINED
            res["hold"][lane] = hb
            res["clear_streak"][lane] = 0
            res["drained"].append(lane)
            if lane in res["readmitted"]:
                res["readmitted"].remove(lane)
    return res


def _drain_machine(**kw):
    return M.DrainMachine(lanes=2, hold_barriers=1, confirm_clear=1,
                          probe_grace=1, **kw)


class _DoubleEpoch(E.Membership):
    def _transition(self, kind, member, step, total):
        out = super()._transition(kind, member, step, total)
        with self._mu:
            self.epoch += 1  # a skipped number between records
        return out


class _NoJoins(E.Membership):
    def sync(self, present, total=None):
        present = {k: v for k, v in present.items()
                   if k in self.members}
        return super().sync(present, total)


class _FlakyOrder(E.Membership):
    """Keeps the roster outcome and the leaves-before-joins phase
    order, but flips the order WITHIN each phase on alternate drives —
    the exact nondeterminism deterministic-order exists to refuse."""

    FLIP = [False]

    def sync(self, present, total=None):
        _FlakyOrder.FLIP[0] = not _FlakyOrder.FLIP[0]
        rev = _FlakyOrder.FLIP[0]
        with self._mu:
            current = dict(self.members)
        resized = sorted(m for m in present
                         if m in current and present[m] != current[m])
        out = []
        for m in sorted(set(current) - set(present), reverse=rev) \
                + resized:
            out.append(self.leave(m, total))
        for m in sorted(set(present) - set(current), reverse=rev) \
                + resized:
            out.append(self.join(m, present[m], total))
        return out


def _elastic_machine(**kw):
    return M.ElasticMachine(member_ids=("p0", "p2"), steps=(2, 3), **kw)


def _quota_off_by_one(**kw):
    if (not kw["kernel_unsafe"] and kw["healthy"]
            and kw["queue_depth"] < kw["max_queue_depth"]
            and kw["tenant_inflight"] == kw["quota"]):
        return {"admit": True, "reason": None, "retry_after_s": None}
    return A.admit_decision(**kw)


def _no_queue_gate(**kw):
    return A.admit_decision(**dict(kw, queue_depth=0))


def _wrong_order(**kw):
    dec = A.admit_decision(**kw)
    if not dec["admit"] and not kw["healthy"] \
            and kw["tenant_inflight"] >= kw["quota"]:
        return {"admit": False, "reason": A.REJECT_QUOTA,
                "retry_after_s": dec["retry_after_s"]}
    return dec


def _kernel_backoff(**kw):
    dec = A.admit_decision(**kw)
    if dec.get("reason") == A.REJECT_KERNEL:
        return dict(dec, retry_after_s=1.0)
    return dec


def _moody(**kw):
    dec = A.admit_decision(**kw)
    if dec["admit"] and kw["tenant_inflight"] == 1:
        return {"admit": False, "reason": A.REJECT_QUOTA,
                "retry_after_s": 0.1}
    return dec


def _admission_machine(**kw):
    return M.AdmissionMachine(tenants=("a", "b"), quota=2,
                              max_queue_depth=2, **kw)


def _overpromote(groups, rnd, mp):
    plan = C.plan_coalesce(groups, rnd, mp)
    keys = [str(g["key"]) for g in groups if int(g.get("pending", 0))]
    if keys and not plan["promoted"]:
        plan = dict(plan, promoted=[keys[0]])
    return plan


def _order_dropper(groups, rnd, mp):
    plan = C.plan_coalesce(groups, rnd, mp)
    if len(plan["order"]) > 1:
        order = plan["order"][:-1]
        plan = dict(plan, order=order,
                    picked=order[:mp] if mp > 0 else list(order))
    return plan


_jitter_seen: dict = {}


def _jitter(groups, rnd, mp):
    """Nondeterministic per SNAPSHOT: the first plan of a given
    snapshot is real, every replan of the same snapshot is tampered —
    exactly the replay-breaking drift plan-deterministic refuses."""
    plan = C.plan_coalesce(groups, rnd, mp)
    key = (rnd, tuple(sorted(
        (g["key"], g.get("starved_rounds", 0)) for g in groups)))
    n = _jitter_seen.get(key, 0)
    _jitter_seen[key] = n + 1
    if n > 0 and len(plan["order"]) > 1:
        order = list(plan["order"])
        order[0], order[-1] = order[-1], order[0]
        plan = dict(plan, order=order,
                    picked=order[:mp] if mp > 0 else list(order))
    return plan


def _no_fairness(groups, rnd, mp):
    """The pre-r10 strawman: EDF/age only, no promotion — the youngest
    group starves unboundedly behind fixed older/deadlined peers."""
    rows = [g for g in groups if int(g.get("pending", 0)) > 0]
    order = [str(g["key"]) for g in sorted(rows, key=C._edf_key)]
    picked = order[:mp] if mp > 0 else list(order)
    return {"order": order, "picked": picked, "promoted": [],
            "max_picks": mp if mp > 0 else 0}


def _coalesce_machine(**kw):
    return M.CoalesceMachine(keys=("ga", "gb", "gc"), max_picks=1, **kw)


def _lossy_balance(bench, ranges, total, step, hist, **kw):
    out = list(B.load_balance(bench, ranges, total, step, hist, **kw))
    if out[0] >= step:
        out[0] -= step
    return out


def _unquantized_balance(bench, ranges, total, step, hist, **kw):
    out = list(B.load_balance(bench, ranges, total, step, hist, **kw))
    if len(out) > 1:
        out[0] += 1
        out[-1] -= 1
    return out


def _rejump_balance(bench, ranges, total, step, hist, state=None, **kw):
    out = B.load_balance(bench, ranges, total, step, hist,
                         state=state, **kw)
    if state is not None and state.jumped:
        state.jumped = False  # the one-shot latch filed off
    return out


def _freeze_mover(bench, ranges, total, step, hist, **kw):
    src = list(ranges)
    out = list(B.load_balance(bench, ranges, total, step, hist, **kw))
    if out == src and len(out) > 1 and out[0] >= step:
        out[0] -= step
        out[1] += step
    return out


_osc_flip = [False]


def _oscillator(bench, ranges, total, step, hist, **kw):
    out = list(B.load_balance(bench, ranges, total, step, hist, **kw))
    _osc_flip[0] = not _osc_flip[0]
    if len(out) > 1:
        i, j = (0, 1) if _osc_flip[0] else (1, 0)
        if out[i] >= step:
            out[i] -= step
            out[j] += step
    return out


def _equal_seeder(total, step, priors, cid=None):
    """Prior seeding filed off: ignores the device-kind priors and
    hands back the equal split — from there, a 100x-skewed fleet's
    first damped rebalance lands far outside one step of the
    rate-implied split, which is exactly the churn the prior-seeded
    invariant exists to forbid."""
    return B.equal_split(int(total), len(priors), int(step))


def _balance_machine(alphabet=(1.0, 5.0), **kw):
    return M.BalanceMachine(rate_alphabet=alphabet, lane_counts=(2,),
                            horizon=24, **kw)


# -- resilience (serve/resilience.py) fixtures ------------------------------

def _double_probe_admit(state, now, open_s):
    """Half-open admits a SECOND probe while one is in flight."""
    out = R.breaker_admit(state, now, open_s)
    if state.get("state") == R.BREAKER_HALF_OPEN \
            and state.get("probe_inflight"):
        st = dict(out["state"])
        return dict(out, allow=True, probe=True, retry_after_s=None,
                    state=st)
    return out


def _eager_open(state, event, now, threshold, open_s):
    """Opens on the FIRST failure (threshold filed down to 1)."""
    out = R.breaker_transition(state, event, now, threshold, open_s)
    if state.get("state") == R.BREAKER_CLOSED and event == "failure" \
            and out["action"] is None:
        st = dict(out["state"], state=R.BREAKER_OPEN, opened_t=now)
        return {"state": st, "action": "opened"}
    return out


def _dishonest_hint(state, now, open_s):
    """Refusals carry a made-up hint instead of the remaining window."""
    out = R.breaker_admit(state, now, open_s)
    if not out["allow"]:
        return dict(out, retry_after_s=999.0)
    return out


def _never_half_open(state, now, open_s):
    """The open window never times out — admits are refused forever."""
    if state.get("state") == R.BREAKER_OPEN:
        return {"allow": False, "probe": False,
                "retry_after_s": float(open_s) / 2.0,
                "state": dict(state), "action": None}
    return R.breaker_admit(state, now, open_s)


def _probe_never_closes(state, event, now, threshold, open_s):
    """A successful probe re-opens instead of closing (permanent open
    under all-ok inputs)."""
    out = R.breaker_transition(state, event, now, threshold, open_s)
    if state.get("state") == R.BREAKER_HALF_OPEN and event == "success":
        st = dict(out["state"], state=R.BREAKER_OPEN, opened_t=now,
                  probe_inflight=False)
        return {"state": st, "action": "reopened"}
    return out


def _breaker_machine(**kw):
    return M.BreakerMachine(threshold=2, open_ticks=2, **kw)


def _hair_trigger_shed(state, qd, wm, cm, ob, dl, engage_streak=2):
    """Engages on the FIRST pressured evaluation — the hysteresis the
    pressure gate exists to enforce, filed off."""
    out = R.brownout_transition(state, qd, wm, cm, ob, dl,
                                engage_streak=engage_streak)
    if not state.get("active") and out["pressure"] and not out["active"]:
        return dict(out, active=True, streak=0, changed=True)
    return out


def _sticky_shed(state, qd, wm, cm, ob, dl, engage_streak=2):
    """Never releases: degraded mode is permanent."""
    out = R.brownout_transition(state, qd, wm, cm, ob, dl,
                                engage_streak=engage_streak)
    if state.get("active"):
        return dict(out, active=True, changed=False)
    return out


def _shed_everyone(**kw):
    """Sheds even a tenant with ZERO requests in flight."""
    dec = A.admit_decision(**kw)
    if kw.get("brownout") and dec["admit"]:
        return {"admit": False, "reason": A.REJECT_BROWNOUT,
                "retry_after_s": 0.1}
    return dec


def _anonymous_shed(**kw):
    """Brownout rejections renamed to the quota reason (and a
    busy-loop hint)."""
    dec = A.admit_decision(**kw)
    if dec.get("reason") == A.REJECT_BROWNOUT:
        return dict(dec, reason=A.REJECT_QUOTA, retry_after_s=0.0)
    return dec


def _shed_machine(**kw):
    return M.ShedMachine(engage_streak=2, **kw)


def _budgetless_retry(attempt, max_attempts, tokens, deadline_left_s,
                      base_s, cap_s, jitter_u):
    """Grants retries with an empty budget and past max_attempts —
    the retry storm the budget exists to prevent."""
    rd = R.retry_decision(attempt, max_attempts, tokens,
                          deadline_left_s, base_s, cap_s, jitter_u)
    if not rd["retry"] and rd["reason"] in ("budget-exhausted",
                                            "attempts-exhausted"):
        return {"retry": True, "delay_s": base_s, "reason": None}
    return rd


def _unbounded_backoff(attempt, max_attempts, tokens, deadline_left_s,
                       base_s, cap_s, jitter_u):
    """Backoff cap filed off: granted delays blow past 1.5×cap (and
    any deadline)."""
    rd = R.retry_decision(attempt, max_attempts, tokens,
                          deadline_left_s, base_s, cap_s, jitter_u)
    if rd["retry"]:
        return dict(rd, delay_s=10.0 * cap_s)
    return rd


def _retry_machine(**kw):
    return M.RetryMachine(max_attempts=2, budget_cap=2, **kw)


def _illegal_block_decide(current, walls, grid, hysteresis=0.08,
                          seed=None, fallback=None):
    """Engages a tile pair outside the legal grid — the unclamped
    store-inherited pair the clamp exists to snap."""
    choice, why = BT.block_transition(current, walls, grid,
                                      hysteresis=hysteresis, seed=seed,
                                      fallback=fallback)
    if choice is not None:
        return (64, 96), why
    return choice, why


def _flappy_block_decide(current, walls, grid, hysteresis=0.08,
                         seed=None, fallback=None):
    """Hysteresis filed off: always engages the instantaneous argmin,
    so a ±noise re-measure flaps the choice (and the executable cache
    behind it)."""
    gset = set(grid)
    known = sorted((tuple(p), float(w)) for p, w in walls
                   if tuple(p) in gset)
    if not known:
        return BT.block_transition(current, walls, grid,
                                   hysteresis=hysteresis, seed=seed,
                                   fallback=fallback)
    best = min(known, key=lambda kv: (kv[1], kv[0]))
    cur = None if current is None else tuple(current)
    return best[0], ("steady" if best[0] == cur else "model")


def _stale_block_emit(row):
    """Records the OUTGOING pair on a retune — the decision log
    misstates what actually engaged (a retune that is visible in name
    only; retune-visibility demands the row match the new choice)."""
    cur = row["inputs"].get("current") or [0, 0]
    return [dict(row, outputs=dict(row["outputs"],
                                   block_q=cur[0], block_k=cur[1]))]


def _block_machine(**kw):
    return M.BlockMachine(**kw)


class _FlipRoute:
    """Alternate calls bounce the same key between members — the
    drive/re-drive comparison (and any replay) diverges."""

    def __init__(self):
        self.calls = 0

    def __call__(self, tenant, key, members, unhealthy=(), epoch=0):
        out = F.route_decision(tenant, key, members, unhealthy, epoch)
        self.calls += 1
        roster = sorted(set(str(m) for m in members),
                        key=lambda m: (len(m), m))
        if out["shard"] is not None and len(roster) > 1 and \
                self.calls % 2:
            alt = roster[(roster.index(out["shard"]) + 1) % len(roster)]
            return dict(out, shard=alt, owner=alt)
        return out


def _modulo_route(tenant, key, members, unhealthy=(), epoch=0):
    """Placement by hash MOD roster size — the NON-consistent hash
    minimal-reshuffle exists to forbid: one departure reshuffles keys
    between the survivors."""
    import hashlib as _hl

    roster = sorted(set(str(m) for m in members),
                    key=lambda m: (len(m), m))
    if not roster:
        return F.route_decision(tenant, key, members, unhealthy, epoch)
    h = int(_hl.sha256(f"{tenant}|{key}".encode()).hexdigest()[:16], 16)
    owner = roster[h % len(roster)]
    bad = set(str(m) for m in unhealthy)
    shard, hops = None, 0
    for i in range(len(roster)):
        m = roster[(h + i) % len(roster)]
        if m not in bad:
            shard = m
            break
        hops += 1
    if shard is None:
        return {"shard": None, "owner": owner, "diverted": True,
                "hops": hops, "reason": F.REJECT_SHARD,
                "epoch": int(epoch)}
    return {"shard": shard, "owner": owner, "diverted": shard != owner,
            "hops": hops, "reason": None, "epoch": int(epoch)}


def _offroster_route(tenant, key, members, unhealthy=(), epoch=0):
    """Names a shard that is not in the roster."""
    out = F.route_decision(tenant, key, members, unhealthy, epoch)
    if out["shard"] is not None:
        return dict(out, shard="zz", owner="zz")
    return out


def _silent_divert_route(tenant, key, members, unhealthy=(), epoch=0):
    """Diverts off a sick owner WITHOUT the diverted flag / hop count
    — the silent diversion the named-decision rule forbids."""
    out = F.route_decision(tenant, key, members, unhealthy, epoch)
    if out["shard"] is not None and out["diverted"]:
        return dict(out, diverted=False, hops=0)
    return out


def _router_machine(**kw):
    return M.RouterMachine(member_ids=("p0", "p2"), **kw)


#: invariant id -> machine factory with the broken seam injected.
BROKEN_FIXTURES = {
    "breaker-half-open-one-probe":
        lambda: _breaker_machine(admit=_double_probe_admit),
    "breaker-opens-on-threshold":
        lambda: _breaker_machine(transition=_eager_open),
    "breaker-honest-hint":
        lambda: _breaker_machine(admit=_dishonest_hint),
    "breaker-open-times-out":
        lambda: _breaker_machine(admit=_never_half_open),
    "breaker-recovers-on-ok":
        lambda: _breaker_machine(transition=_probe_never_closes),
    "shed-pressure-gated":
        lambda: _shed_machine(transition=_hair_trigger_shed),
    "shed-quota-floor": lambda: _shed_machine(decide=_shed_everyone),
    "shed-named-hint": lambda: _shed_machine(decide=_anonymous_shed),
    "shed-releases": lambda: _shed_machine(transition=_sticky_shed),
    "retry-budget-bounded":
        lambda: _retry_machine(decide=_budgetless_retry),
    "retry-backoff-bounded":
        lambda: _retry_machine(decide=_unbounded_backoff),
    "availability-floor": lambda: _drain_machine(transition=_no_floor),
    "share-conservation": lambda: _drain_machine(masker=_leaky_masker),
    "quarantine-masked":
        lambda: _drain_machine(masker=_double_probe_masker),
    "action-visibility": lambda: _drain_machine(transition=_silent_drain),
    "eventual-readmission":
        lambda: _drain_machine(transition=_never_readmit),
    "no-silent-flap": lambda: _drain_machine(transition=_flappy),
    "epoch-monotone":
        lambda: _elastic_machine(membership_cls=_DoubleEpoch),
    "resplit-conservation": "monkeypatch",  # handled below
    "resplit-quantized": "monkeypatch",
    "sync-converges": lambda: _elastic_machine(membership_cls=_NoJoins),
    # needs >= 2 simultaneous departures for the within-phase order to
    # vary, so a 3-member alphabet
    "deterministic-order": lambda: M.ElasticMachine(
        member_ids=("p0", "p2", "p10"), steps=(2, 3),
        membership_cls=_FlakyOrder),
    "quota-exact": lambda: _admission_machine(decide=_quota_off_by_one),
    "queue-bounded": lambda: _admission_machine(decide=_no_queue_gate),
    "reject-order": lambda: _admission_machine(decide=_wrong_order),
    "retry-hint": lambda: _admission_machine(decide=_kernel_backoff),
    "admit-iff": lambda: _admission_machine(decide=_moody),
    "promoted-are-starved": lambda: _coalesce_machine(plan=_overpromote),
    "plan-complete": lambda: _coalesce_machine(plan=_order_dropper),
    "plan-deterministic": lambda: _coalesce_machine(plan=_jitter),
    "bounded-starvation": lambda: _coalesce_machine(plan=_no_fairness),
    "range-conservation":
        lambda: _balance_machine(balance=_lossy_balance),
    "range-quantized":
        lambda: _balance_machine(balance=_unquantized_balance),
    "jump-one-shot": lambda: _balance_machine(balance=_rejump_balance),
    "freeze-legal":
        lambda: _balance_machine(alphabet=(1.0,), balance=_freeze_mover),
    "converges": lambda: _balance_machine(balance=_oscillator),
    "prior-seeded-jump-within-one-step":
        lambda: _balance_machine(alphabet=(1.0, 100.0),
                                 seeder=_equal_seeder),
    "choice-legality":
        lambda: _block_machine(decide=_illegal_block_decide),
    "hysteresis-bound":
        lambda: _block_machine(decide=_flappy_block_decide),
    "retune-visibility":
        lambda: _block_machine(emit=_stale_block_emit),
    "placement-deterministic":
        lambda: _router_machine(route=_FlipRoute()),
    # mod-N reshuffling only shows between SURVIVORS, so a 3-member
    # alphabet (a 2-member roster's departure leaves nothing to
    # reshuffle between)
    "minimal-reshuffle": lambda: M.RouterMachine(
        member_ids=("p0", "p2", "p10"), route=_modulo_route),
    "routes-to-members":
        lambda: _router_machine(route=_offroster_route),
    "diversion-named":
        lambda: _router_machine(route=_silent_divert_route),
}


def test_fixture_table_covers_every_declared_invariant():
    declared = set()
    for mod in (D, E, A, C, B, R, BT, F):
        declared |= {row[0] for row in mod.MODEL_INVARIANTS}
    assert set(BROKEN_FIXTURES) == declared


@pytest.mark.parametrize("inv_id", sorted(BROKEN_FIXTURES))
def test_broken_fixture_produces_counterexample(inv_id, monkeypatch):
    factory = BROKEN_FIXTURES[inv_id]
    if factory == "monkeypatch":
        _real_resplit = E.member_resplit

        if inv_id == "resplit-conservation":
            def tampered(steps, total):
                out = _real_resplit(steps, total)
                if len(out["ranges"]) >= 2 and \
                        out["ranges"][0] >= out["lcm"]:
                    out = dict(out, ranges=[
                        out["ranges"][0] - out["lcm"],
                        *out["ranges"][1:]])
                return out
        else:
            def tampered(steps, total):
                out = _real_resplit(steps, total)
                if len(out["ranges"]) >= 2 and out["ranges"][0] >= 1:
                    rs = list(out["ranges"])
                    rs[0] -= 1
                    rs[-1] += 1
                    out = dict(out, ranges=rs)
                return out
        monkeypatch.setattr(E, "member_resplit", tampered)
        machine = _elastic_machine()
    else:
        machine = factory()
    report = machine.explore()
    hit = [v for v in report["violations"] if v.invariant == inv_id]
    assert hit, (
        f"broken fixture for {inv_id} produced no violation; got "
        f"{[v.invariant for v in report['violations']]}")
    v = hit[0]
    assert v.fingerprint and v.machine and v.kind in ("safety",
                                                      "liveness")
    assert v.trace, f"{inv_id}: counterexample trace is empty"
    assert all({"seq", "kind", "inputs", "outputs"} <= set(r)
               for r in v.trace)


# ---------------------------------------------------------------------------
# 3. the counterexample -> replay bridge
# ---------------------------------------------------------------------------

def test_broken_drain_trace_diverges_under_replay():
    """A counterexample from a broken fixture machine carries the
    BROKEN outputs; replaying it through the real drain_transition
    names the first divergent seq — the ckreplay tamper drill, fed by
    the model checker."""
    report = _drain_machine(transition=_no_floor).explore()
    v = next(x for x in report["violations"]
             if x.invariant == "availability-floor")
    verdict = verify_counterexample(v)
    assert verdict["ok"] is False
    assert verdict["first_divergence"] is not None
    assert verdict["first_divergence"]["seq"] >= 1
    assert verdict["first_divergence"]["kind"] in ("drain-apply",
                                                   "readmit")


def test_broken_block_trace_diverges_under_replay():
    """The block tamper drill: a hysteresis-free chooser's
    counterexample carries flapped outputs; replaying through the real
    block_transition names the first divergent seq."""
    report = _block_machine(decide=_flappy_block_decide).explore()
    v = next(x for x in report["violations"]
             if x.invariant == "hysteresis-bound")
    verdict = verify_counterexample(v)
    assert verdict["ok"] is False
    assert verdict["first_divergence"] is not None
    assert verdict["first_divergence"]["seq"] >= 1
    assert verdict["first_divergence"]["kind"] == "block-retune"


def test_real_machine_trace_replays_green():
    """A trace assembled from the REAL controller functions replays
    bit-identically — committing one as a fixture pins fixed behavior."""
    report = _balance_machine(balance=_oscillator).explore()
    v = next(x for x in report["violations"]
             if x.invariant == "converges")
    # the records are the real load_balance emissions (the oscillator
    # tampers only the fed-back ranges, which become the next record's
    # INPUTS) — so the trace itself must verify clean
    verdict = verify_counterexample(v)
    assert verdict["ok"] is True
    assert verdict["replayed"] == len(v.trace)


def test_counterexample_spills_and_rides_ckreplay(tmp_path, capsys):
    """End-to-end acceptance pin: a counterexample trace saved by the
    bridge is a ck-decision-log-v1 jsonl that `ckreplay verify` exits 0
    on and `ckreplay explain` renders a causality table from."""
    import tools.ckreplay as ckreplay

    report = _balance_machine(balance=_oscillator).explore()
    v = next(x for x in report["violations"]
             if x.invariant == "converges")
    path = str(tmp_path / "counterexample.jsonl")
    assert save_counterexample(path, v) == path
    # the decision-log loader reads it (schema header + rows)
    records = load_decision_log(path)
    assert len(records) == len(v.trace)
    assert ckreplay.main(["verify", path]) == 0
    out = capsys.readouterr().out
    assert "replay-verify" in out or "OK" in out or "ok" in out.lower()
    assert ckreplay.main(["explain", path]) == 0
    out = capsys.readouterr().out
    assert "lane" in out  # the per-lane causality table rendered


def test_save_counterexample_normalizes_partial_rows(tmp_path):
    """The ONE trace normalizer (obs/replay + DecisionRecord.from_row):
    partial rows — no clocks, no inputs — spill and load cleanly."""
    p = str(tmp_path / "t.jsonl")
    save_counterexample(p, {"trace": [
        {"kind": "coalesce", "seq": 1, "inputs": {"a": 1},
         "outputs": {"b": 2}},
        {"kind": "coalesce", "seq": 2},
    ]})
    records = load_decision_log(p)
    assert [r.seq for r in records] == [1, 2]
    assert records[1].inputs == {} and records[1].outputs == {}


# ---------------------------------------------------------------------------
# 4. real violations found by the checker, fixed in this PR, pinned
# ---------------------------------------------------------------------------

def test_swap_cycle_fixture_replays_bit_identically():
    """The balancer ±1-step swap limit cycle (found by ckmodel, fixed
    by REPAIR_TIE_BAND's incumbent tie-break): the committed trace was
    recorded from the FIXED code, so replaying it fails if anyone
    reverts the repair-loop semantics."""
    records = load_decision_log(SWAP_CYCLE_FIXTURE)
    assert len(records) >= 10
    verdict = verify_records(records)
    assert verdict["ok"] is True, verdict["first_divergence"]
    assert verdict["replayed"] == len(records)


def test_swap_cycle_scenario_converges_live():
    """Live re-drive of the counterexample scenario: two equal-rate
    lanes + one 8x-slower lane, jump on.  Pre-fix, the repair step
    flipped between the equal pair forever ([1536,1408,128] <->
    [1408,1536,128]); the split must now settle and stay."""
    total, step, rates = 3072, 128, (1.0, 1.0, 8.0)
    state = B.BalanceState()
    ranges = B.equal_split(total, 3, step)
    state.reset(ranges, B.DAMPING)
    tail = []
    for _ in range(40):
        bench = [rates[i] * max(ranges[i], step) for i in range(3)]
        ranges = B.load_balance(bench, list(ranges), total, step, None,
                                state=state, jump_start=True, cid=0)
        tail.append(tuple(ranges))
    assert len(set(tail[-10:])) == 1, tail[-10:]
    assert sum(tail[-1]) == total


#: The concrete G=4 schedule the checker's probe found: all four
#: groups pending for six rounds starved g1 SIX consecutive cycles
#: under the old whole-list rotation (anchor re-aimed as the streak
#: resized).  The fixed longest-starved-first promotion bounds it.
OLD_ROTATION_SCHEDULE = [
    ("g0", "g1", "g2", "g3")] * 6 + [
    ("g0",), ("g0", "g2", "g3"), ("g0", "g2"), ("g0", "g2")]


def _drive_coalesce(schedule, mp, G=4):
    keys = [f"g{i}" for i in range(G)]
    ages = {k: float(G - i) for i, k in enumerate(keys)}
    starved = {k: 0 for k in keys}
    worst = 0
    for rnd, present in enumerate(schedule):
        rows = sorted(
            ({"key": k, "pending": 1, "deadline_in_s": None,
              "oldest_age_s": ages[k], "starved_rounds": starved[k]}
             for k in present), key=lambda r: r["key"])
        picked = set(C.plan_coalesce(rows, rnd, mp)["picked"])
        for k in keys:
            if k not in present or k in picked:
                starved[k] = 0
            else:
                starved[k] += 1
            worst = max(worst, starved[k])
    return worst


def test_rotation_starvation_counterexample_now_bounded():
    worst = _drive_coalesce(OLD_ROTATION_SCHEDULE, mp=1)
    bound = C.STARVE_ROUNDS + (4 - 1)
    assert worst <= bound, (
        f"the pinned G=4 schedule starved a group {worst} consecutive "
        f"cycles (bound {bound}) — the longest-starved-first promotion "
        "regressed")


def test_plan_coalesce_fairness_property():
    """Satellite: randomized arrival/desertion/deadline histories must
    respect the capacity-aware starvation bound — STARVE_ROUNDS when
    max_picks covers the streak, STARVE_ROUNDS + (G-1) at max_picks=1
    (the exact guarantee the r10-era k-member rotation violated)."""
    for G, mp, seeds in ((3, 1, 6), (4, 1, 6), (5, 2, 4), (4, 3, 4)):
        bound = C.STARVE_ROUNDS + (G - 1 if mp < G - 1 else 0)
        keys = [f"g{i}" for i in range(G)]
        for seed in range(seeds):
            rng = random.Random(seed * 37 + G * 5 + mp)
            present = set(keys)
            schedule = []
            for _ in range(400):
                for k in keys[1:]:
                    if rng.random() < 0.3:
                        present.symmetric_difference_update({k})
                present.add(keys[0])
                schedule.append(tuple(sorted(present)))
            worst = _drive_coalesce(schedule, mp=mp, G=G)
            assert worst <= bound, (G, mp, seed, worst, bound)


# ---------------------------------------------------------------------------
# 5. CLI lifecycle (ratchet, provenance, --json, --save-trace)
# ---------------------------------------------------------------------------

def _fake_violation():
    return M.ModelViolation(
        "drain", "availability-floor", "safety",
        "fixture: no active lane left", {"lanes": {"0": "quarantined"}},
        [{"kind": "drain-apply", "inputs": {"verdicts": {}},
          "outputs": {"drained": ["0"]}}])


def _patch_analyze(monkeypatch, findings):
    def fake(machine=None, scale=None):
        report = {
            "ok": not findings,
            "states_explored": 123, "transitions": 45,
            "machines": {"drain": {
                "states_explored": 123, "transitions": 45,
                "truncated": False, "violations": list(findings),
                "sub_machines": {}}},
            "violations": list(findings),
        }
        return list(findings), report
    monkeypatch.setattr(ckmodel_cli, "analyze", fake)


def test_cli_ratchet_lifecycle(tmp_path, monkeypatch, capsys):
    baseline = str(tmp_path / "b.json")
    v = _fake_violation()
    _patch_analyze(monkeypatch, [v])
    args = ["--baseline", baseline]

    # (1) a new finding fails, naming machine + invariant
    assert ckmodel_cli.main(args) == 1
    out = capsys.readouterr().out
    assert "availability-floor" in out and "NEW" in out

    # (2) --update-baseline refuses growth without --allow-grow
    assert ckmodel_cli.main(args + ["--update-baseline"]) == 1
    assert "REFUSING" in capsys.readouterr().out
    assert ckmodel_cli.main(
        args + ["--update-baseline", "--allow-grow"]) == 0
    capsys.readouterr()
    assert ckmodel_cli.main(args) == 0  # grandfathered
    capsys.readouterr()

    # (3) --explain renders the counterexample + rule doc
    assert ckmodel_cli.main(args + ["--explain", v.fingerprint]) == 0
    out = capsys.readouterr().out
    assert "counterexample" in out and "drain-apply" in out
    assert "grandfathered" in out

    # (4) fixing without shrinking -> stale, naming the burn commit
    _patch_analyze(monkeypatch, [])
    assert ckmodel_cli.main(args) == 1
    out = capsys.readouterr().out
    assert "STALE" in out and "baseline burned by ckmodel" in out

    # (5) the shrink: clean again
    assert ckmodel_cli.main(args + ["--update-baseline"]) == 0
    capsys.readouterr()
    assert ckmodel_cli.main(args) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_json_schema_and_save_trace(tmp_path, monkeypatch, capsys):
    baseline = str(tmp_path / "b.json")
    v = _fake_violation()
    _patch_analyze(monkeypatch, [v])
    tr = str(tmp_path / "traces")
    rc = ckmodel_cli.main(["--baseline", baseline, "--json",
                           "--save-trace", tr])
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out[out.index("{"):])
    assert {"new", "grandfathered", "stale_baseline",
            "states_explored", "transitions", "machines"} <= set(doc)
    row = doc["new"][0]
    assert {"fingerprint", "machine", "invariant", "kind", "message",
            "state", "trace_len"} <= set(row)
    # the spilled trace is a loadable decision log
    spilled = os.path.join(tr, f"{v.fingerprint}.jsonl")
    assert os.path.exists(spilled)
    assert len(load_decision_log(spilled)) == 1


def test_cli_explain_provenance(capsys):
    assert ckmodel_cli.main(["--explain", "provenance"]) == 0
    out = capsys.readouterr().out
    assert "baseline burned by ckmodel" in out


def test_checked_in_baselines_carry_provenance():
    """Satellite: all three ratchet baselines (ckcheck, ckprove,
    ckmodel) share the provenance header naming tool + burn commit."""
    for rel, tool in (("tools/ckcheck/baseline.json", "ckcheck"),
                      ("tools/ckprove_baseline.json", "ckprove"),
                      ("tools/ckmodel/baseline.json", "ckmodel")):
        with open(os.path.join(ROOT, rel)) as f:
            doc = json.load(f)
        prov = doc.get("provenance")
        assert prov, f"{rel} has no provenance header"
        assert prov["tool"] == tool
        assert prov["head"] and prov["head"] != "unknown"
        assert prov["updated_at"]
        assert doc["findings"] == []  # all three expected-empty


def test_stale_baseline_names_burn_commit(tmp_path, monkeypatch, capsys):
    """The satellite's motivating failure: a stale ratchet entry now
    names the commit the baseline was burned at."""
    from tools.ckcheck.baseline import provenance_note, save_baseline

    b = str(tmp_path / "b.json")
    save_baseline(b, [_fake_violation()], tool="ckmodel")
    note = provenance_note(json.load(open(b)))
    assert "baseline burned by ckmodel" in note
    assert "at commit" in note
    # a pre-provenance baseline degrades with a named reason
    legacy = str(tmp_path / "old.json")
    json.dump({"schema": "ckcheck-baseline-v1", "findings": []},
              open(legacy, "w"))
    assert "no provenance header" in provenance_note(
        json.load(open(legacy)))


# ---------------------------------------------------------------------------
# 6. purity lint
# ---------------------------------------------------------------------------

def test_purity_clean_on_head():
    findings = purity.run(ROOT)
    assert findings == [], [f.render() for f in findings]


def test_purity_flags_clock_and_global_reads():
    src = (
        "import time\n"
        "from x import DECISIONS\n"
        "_cache = {}\n"
        "def trans(a):\n"
        "    _cache[a] = time.time()\n"
        "    DECISIONS.record('x')\n"
        "    return helper(a)\n"
        "def helper(a):\n"
        "    return a + perf_counter()\n"
    )
    findings = purity.scan_module(src, "mod.py", ("trans",), ())
    rules = {(f.func, f.rule) for f in findings}
    assert ("trans", "impure-call") in rules
    assert ("trans", "impure-global") in rules
    assert ("helper", "impure-call") in rules  # transitive closure
    msgs = " ".join(f.message for f in findings)
    assert "_cache" in msgs and "DECISIONS" in msgs


def test_purity_seam_allows_declared_dependency():
    src = (
        "from other import Helper\n"
        "def trans(a):\n"
        "    return Helper(a).go()\n"
    )
    assert purity.scan_module(src, "m.py", ("trans",), ("Helper",)) == []
    flagged = purity.scan_module(src, "m.py", ("trans",), ())
    assert flagged and flagged[0].rule == "impure-global"


def test_purity_missing_declared_function_is_a_finding(tmp_path):
    mod = tmp_path / "pkg.py"
    mod.write_text("def exists(a):\n    return a\n")
    findings = purity.run(str(tmp_path), table=(
        ("pkg.py", ("exists", "vanished"), ()),))
    assert any(f.rule == "missing" and f.func == "vanished"
               for f in findings)


def test_purity_constants_and_helpers_allowed():
    src = (
        "LIMIT = 3\n"
        "_FLOOR_S = 0.5\n"
        "def trans(a):\n"
        "    return [clip(v) for v in a][:LIMIT]\n"
        "def clip(v):\n"
        "    return max(v, _FLOOR_S)\n"
    )
    assert purity.scan_module(src, "m.py", ("trans",), ()) == []


# ---------------------------------------------------------------------------
# 7. bench + regress wiring
# ---------------------------------------------------------------------------

def _bench():
    sys.path.insert(0, ROOT)
    import bench

    return bench


def test_bench_artifact_embeds_model_block():
    bench = _bench()
    sched = bench.SectionScheduler(100.0, {})
    result = {"headline": {"mandelbrot_mpix": 1.0}}
    out = bench.finalize_result(result, sched)
    assert out["model"]["ok"] is True
    assert out["model"]["states_explored"] > 0
    assert set(out["model"]["machines"]) == set(M.MACHINE_NAMES)
    assert out["headline"]["model_ok"] is True
    assert out["headline"]["model_states_explored"] == \
        out["model"]["states_explored"]
    # tail-order contract intact: model slots in before the
    # tail-critical block
    keys = list(out)
    assert keys[-4:] == ["metrics", "regression",
                         "null_sections", "headline"]
    assert keys.index("model") < keys.index("metrics")


def test_regress_hard_fails_model_false():
    import tools.regress as regress

    base = {"path": "b", "headline": {"mandelbrot_mpix": 10.0},
            "errors": None, "null_sections": None, "sections": None}
    good = {"path": "c", "headline": {"mandelbrot_mpix": 10.0,
                                      "model_ok": True},
            "errors": None, "null_sections": None, "sections": None}
    assert regress.diff_headlines(base, good)["exit_code"] == 0
    bad = {"path": "c", "headline": {"mandelbrot_mpix": 10.0,
                                     "model_ok": False},
           "errors": None, "null_sections": None, "sections": None}
    v = regress.diff_headlines(base, bad)
    assert v["exit_code"] == 3 and not v["ok"]
    finding = next(f for f in v["findings"]
                   if f["kind"] == "model-drift")
    assert "ckmodel" in finding["reason"]
    # absent (pre-model artifact) passes
    legacy = {"path": "c", "headline": {"mandelbrot_mpix": 10.0},
              "errors": None, "null_sections": None, "sections": None}
    assert regress.diff_headlines(base, legacy)["exit_code"] == 0


# ---------------------------------------------------------------------------
# 8. decisions capture seam (the checker's isolation contract)
# ---------------------------------------------------------------------------

def test_capture_isolates_the_live_ring():
    from cekirdekler_tpu.obs.decisions import DECISIONS

    before = DECISIONS.snapshot()
    total_before = DECISIONS.total_recorded
    with DECISIONS.capture() as ring:
        DECISIONS.record("coalesce", {"groups": []}, {"order": []})
        assert len(ring) == 1
        assert DECISIONS.snapshot()[-1].kind == "coalesce"
    after = DECISIONS.snapshot()
    assert [r.seq for r in after] == [r.seq for r in before]
    assert DECISIONS.total_recorded == total_before
