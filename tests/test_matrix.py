"""Dtype-breadth correctness matrix (VERDICT r2 #7).

TPU-native analogue of the reference's generated test grid
(Tester.cs:6763-7065): {simple C# arrays | fast native FastArr} x
{byte,char,int,uint,long,double,float} x {device counts} x
{no pipeline | EventPipeline | DriverPipeline} x {1 | 2 | 3 kernels},
each case verified element-wise against a host reference.

Here: 7 numpy dtypes x {simple | fast} x {1, 2, 3, 8} virtual devices x
{none, EVENT, DRIVER} x {1..3 kernels} = 504 cases, sharing one compiled
cruncher per (dtype, device count) so the grid stays fast on the rig.
"""

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import PIPELINE_DRIVER, PIPELINE_EVENT, NumberCruncher
from cekirdekler_tpu.hardware import platforms

N = 4096
LOCAL = 64
BLOBS = 4

# dtype -> kernel-language element type (reference: the 7 ClXxxArray clones,
# CSpaceArrays.cs:48-78; our ClArray is dtype-generic so one grid covers all)
DTYPES = {
    "float32": "float",
    "float64": "double",
    "int32": "int",
    "int64": "long",
    "uint8": "uchar",
    "int16": "short",
    "uint16": "ushort",
}

MODES = {
    "none": dict(pipeline=False),
    "event": dict(pipeline=True, pipeline_blobs=BLOBS, pipeline_type=PIPELINE_EVENT),
    "driver": dict(pipeline=True, pipeline_blobs=BLOBS, pipeline_type=PIPELINE_DRIVER),
}


def _src(ct: str) -> str:
    # values kept tiny so every dtype (incl. uint8) stays in range
    return f"""
    __kernel void k1(__global {ct}* a, __global {ct}* c) {{
        int i = get_global_id(0);
        c[i] = a[i] + ({ct})3;
    }}
    __kernel void k2(__global {ct}* a, __global {ct}* c) {{
        int i = get_global_id(0);
        c[i] = c[i] * ({ct})2;
    }}
    __kernel void k3(__global {ct}* a, __global {ct}* c) {{
        int i = get_global_id(0);
        c[i] = c[i] + ({ct})1;
    }}
    """


_crunchers: dict = {}


@pytest.fixture(scope="module")
def cruncher_for():
    devs = platforms().cpus()

    def get(dtype_name: str, ndev: int) -> NumberCruncher:
        key = (dtype_name, ndev)
        if key not in _crunchers:
            _crunchers[key] = NumberCruncher(
                devs.subset(ndev), _src(DTYPES[dtype_name])
            )
        return _crunchers[key]

    yield get
    for cr in _crunchers.values():
        cr.dispose()
    _crunchers.clear()


def _host_reference(a: np.ndarray, n_kernels: int) -> np.ndarray:
    dt = a.dtype
    c = (a + dt.type(3)).astype(dt)
    if n_kernels >= 2:
        c = (c * dt.type(2)).astype(dt)
    if n_kernels >= 3:
        c = (c + dt.type(1)).astype(dt)
    return c


@pytest.mark.parametrize("dtype_name", list(DTYPES))
@pytest.mark.parametrize("fast", [False, True], ids=["simple", "fast"])
@pytest.mark.parametrize("ndev", [1, 2, 3, 8])
@pytest.mark.parametrize("mode", list(MODES))
@pytest.mark.parametrize("n_kernels", [1, 2, 3])
def test_matrix(cruncher_for, dtype_name, fast, ndev, mode, n_kernels):
    dt = np.dtype(dtype_name)
    cr = cruncher_for(dtype_name, ndev)
    rng = np.random.default_rng(hash((dtype_name, ndev)) % 2**32)
    host_a = rng.integers(0, 8, N).astype(dt)
    a = ClArray(N, dt, name="a", fast=fast, partial_read=True, read_only=True)
    c = ClArray(N, dt, name="c", fast=fast, write=True)
    a.host()[:] = host_a
    names = " ".join(["k1", "k2", "k3"][:n_kernels])
    a.next_param(c).compute(cr, 77, names, N, LOCAL, **MODES[mode])
    want = _host_reference(host_a, n_kernels)
    got = np.asarray(c)
    if dt.kind == "f":
        np.testing.assert_allclose(got, want, rtol=1e-6)
    else:
        np.testing.assert_array_equal(got, want)
