"""Property fuzz: random gid-affine kernels through the real parser,
every verdict checked both ways against the differential oracle.

The generator emits kernels from the gid-affine family the verifier
models *exactly* — linear accumulations of shifted reads
(``x[i + d]``, d ∈ [-2, 2]) behind always-taken branches and
constant-bound loops, written to ``y[i + dw]`` — under random flag
assignments (partial/full reads, write_only, occasional write_all).
Construction guarantees divergence is *visible* whenever it is
possible: every array is initialized strictly positive, every term
adds with a positive coefficient, so a staged zero leaking into a
boundary item always changes the result.

For every sample the assertion is bidirectional:

- verdict **safe** → the split-vs-unsplit oracle is bit-identical
  (zero false negatives by construction);
- oracle **diverges** → the verdict names an error (the same
  property, stated from the oracle's side).
"""

import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tests.kernel_corpus import (  # noqa: E402
    CorpusKernel,
    ground_truth_unsafe,
    verdict_for,
)

N_SAMPLES = 60
GLOBAL_RANGE = 96
LOCAL_RANGE = 8


def _gen_kernel(rng) -> CorpusKernel:
    def c(lo=0.5, hi=1.5):
        return round(float(rng.uniform(lo, hi)), 3)

    def d():
        # weighted toward 0 so BOTH sides of the property are
        # exercised: all-offsets-hot would make nearly every sample
        # unsafe under a partial flag
        return 0 if rng.random() < 0.6 else int(rng.integers(-2, 3))

    d0, d1, d2, d3 = d(), d(), d(), d()
    dw = 0 if rng.random() < 0.75 else int(rng.integers(-1, 2))
    use_branch = bool(rng.integers(0, 2))
    use_loop = bool(rng.integers(0, 2))
    k_iters = int(rng.integers(1, 4))

    def idx(delta):
        return f"i{'+' if delta >= 0 else '-'}{abs(delta)}" \
            if delta else "i"

    lines = [
        "__kernel void fz(__global float* x0, __global float* x1, "
        "__global float* y) {",
        "    int i = get_global_id(0);",
        f"    float t = {c()}f;",
        f"    t = t + {c()}f * x0[{idx(d0)}];",
        f"    t = t + {c()}f * x1[{idx(d1)}];",
    ]
    if use_branch:
        # t >= 0.5 by construction, so the branch is ALWAYS taken —
        # the generated read genuinely executes (a dead halo read
        # would be a deliberate false positive, out of family)
        lines += [
            "    if (t > 0.1f) {",
            f"        t = t + {c()}f * x0[{idx(d2)}];",
            "    }",
        ]
    if use_loop:
        lines += [
            f"    for (int k = 0; k < {k_iters}; k++) " + "{",
            f"        t = t + x1[{idx(d3)}] * {c()}f;",
            "    }",
        ]
    lines += [f"    y[{idx(dw)}] = t;", "}"]

    x0_partial = bool(rng.integers(0, 2))
    x1_partial = bool(rng.integers(0, 2))
    y_wo = bool(rng.integers(0, 2))
    y_wa = rng.integers(0, 8) == 0  # occasional write_all
    y_flags = dict(write_all=True) if y_wa else (
        dict(write_only=True) if y_wo else dict(partial_read=True))
    return CorpusKernel(
        name=f"fuzz-{rng.integers(1 << 30)}",
        source="\n".join(lines),
        flags=(
            dict(partial_read=x0_partial, read_only=True),
            dict(partial_read=x1_partial, read_only=True),
            y_flags,
        ),
        global_range=GLOBAL_RANGE,
        local_range=LOCAL_RANGE,
    )


def test_fuzz_safe_verdicts_confirmed_by_oracle():
    rng = np.random.default_rng(0xCEC1)
    n_safe = n_unsafe = 0
    for _ in range(N_SAMPLES):
        entry = _gen_kernel(rng)
        v = verdict_for(entry)
        unsafe = any(
            ground_truth_unsafe(entry, lanes=lanes) for lanes in (2, 3))
        if v.ok:
            n_safe += 1
            assert not unsafe, (
                f"FALSE NEGATIVE (fuzz): verdict safe but oracle "
                f"diverges\n{entry.source}\nflags={entry.flags}")
        else:
            n_unsafe += 1
            assert unsafe, (
                f"error-severity false positive (fuzz): verdict "
                f"{[f.kind for f in v.errors]} but oracle is "
                f"bit-identical\n{entry.source}\nflags={entry.flags}")
    # the generator must actually exercise both sides
    assert n_safe >= 5, f"degenerate fuzz run: only {n_safe} safe"
    assert n_unsafe >= 5, f"degenerate fuzz run: only {n_unsafe} unsafe"
