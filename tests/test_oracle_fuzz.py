"""Oracle-based differential testing of the full kernel language.

tests/kernel_oracle.py executes kernels one work item at a time with real
Python control flow — the language's semantic definition.  The compiled
vectorized lowering must match it on: gather loops (uniform AND per-lane
indices), private arrays, divergent branches with early returns, shifted
windows, and integer arithmetic with C division semantics.

Every case is ALSO pushed through the Pallas tile lowering
(kernel/pallas_backend.py, interpret mode) whenever the kernel is inside
its subset — since the round-4 widening that includes shifted windows and
lane-uniform gathers, so most of these now fuzz three implementations
against each other (oracle / XLA / Pallas); per-lane gathers and private
arrays still fall back and are only two-way.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from cekirdekler_tpu.kernel import codegen, lang  # noqa: E402
from tests.kernel_oracle import Oracle  # noqa: E402

import jax.experimental.pallas as _pl  # noqa: E402

# env capability, not a code property (same guard as
# tests/test_lowering_fuzz.py): cases whose kernels fall inside the
# widened Pallas subset fuzz the tile lowering three-way, and that
# lowering needs pl.Element — absent from this container's jax.  Only
# the in-subset cases are marked; per-lane-gather/private-array cases
# never touch Pallas and run everywhere.
requires_pl_element = pytest.mark.skipif(
    not hasattr(_pl, "Element"),
    reason="jax.experimental.pallas lacks pl.Element in this environment "
           "(pre-0.5-era pallas) — the widened tile lowering cannot build",
)

N = 128


def _run_both(src: str, arrays: dict, values: dict, atol=1e-4):
    from cekirdekler_tpu.kernel.pallas_backend import (
        PallasUnsupported,
        build_kernel_fn_pallas,
    )

    kdef = lang.parse_kernels(src)[0]
    order = [p.name for p in kdef.params if p.is_pointer]
    vals = tuple(values[p.name] for p in kdef.params if not p.is_pointer)

    fn, _ = codegen.build_kernel_fn(kdef, N, 64, N)
    jarrs = tuple(jnp.asarray(arrays[n]) for n in order)
    out_c = {n: np.asarray(a) for n, a in zip(order, fn(0, jarrs, vals))}

    oracle_arrays = {n: arrays[n].copy() for n in order}
    Oracle(kdef).run(oracle_arrays, values, N)

    for n in order:
        np.testing.assert_allclose(
            out_c[n], oracle_arrays[n], rtol=1e-4, atol=atol,
            err_msg=f"compiled vs oracle divergence in array {n!r}:\n{src}",
        )

    # three-way: the Pallas tile lowering, when the kernel is in-subset
    try:
        pl_fn, _ = build_kernel_fn_pallas(kdef, N, 64, N, interpret=True,
                                         force=True)
    except PallasUnsupported:
        return
    for n, a in zip(order, pl_fn(0, jarrs, vals)):
        np.testing.assert_allclose(
            np.asarray(a), oracle_arrays[n], rtol=1e-4, atol=atol,
            err_msg=f"pallas vs oracle divergence in array {n!r}:\n{src}",
        )


@requires_pl_element
def test_oracle_uniform_gather_loop():
    src = """
    __kernel void k(__global float* w, __global float* x, __global float* out, int m) {
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int j = 0; j < m; j++) {
            acc = acc + w[j] * x[i];
        }
        out[i] = acc;
    }"""
    rng = np.random.default_rng(0)
    _run_both(src, {
        "w": rng.standard_normal(N).astype(np.float32),
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {"m": 12})


def test_oracle_per_lane_gather_and_shifted_window():
    src = """
    __kernel void k(__global int* idx, __global float* x, __global float* out) {
        int i = get_global_id(0);
        out[i] = x[idx[i]] + x[i + 1] * 0.5f;
    }"""
    rng = np.random.default_rng(1)
    _run_both(src, {
        "idx": rng.integers(0, N, N).astype(np.int32),
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


def test_oracle_divergent_return_then_gather():
    """The exact shape that once miscompiled: assignment after a
    divergent early return feeding a gather index."""
    src = """
    __kernel void k(__global float* x, __global float* y) {
        int i = get_global_id(0);
        int j = 0;
        if (i % 3 == 0) {
            return;
        }
        j = 2;
        y[i] = x[j] + (float)i;
    }"""
    rng = np.random.default_rng(2)
    _run_both(src, {
        "x": rng.standard_normal(N).astype(np.float32),
        "y": np.zeros(N, np.float32),
    }, {})


def test_oracle_private_array_histogramish():
    src = """
    __kernel void k(__global int* sel, __global float* out) {
        int i = get_global_id(0);
        float slots[4];
        for (int j = 0; j < 4; j++) {
            slots[j] = (float)j;
        }
        int b = sel[i];
        slots[b] = slots[b] + 100.0f;
        float s = 0.0f;
        for (int j = 0; j < 4; j++) {
            s = s + slots[j];
        }
        out[i] = s;
    }"""
    rng = np.random.default_rng(3)
    _run_both(src, {
        "sel": (rng.integers(0, 4, N)).astype(np.int32),
        "out": np.zeros(N, np.float32),
    }, {})


@requires_pl_element
def test_oracle_integer_division_semantics():
    """C truncating division/remainder with mixed signs."""
    src = """
    __kernel void k(__global int* a, __global int* b, __global int* q, __global int* r) {
        int i = get_global_id(0);
        q[i] = a[i] / b[i];
        r[i] = a[i] % b[i];
    }"""
    rng = np.random.default_rng(4)
    b = rng.integers(1, 7, N).astype(np.int32) * rng.choice([-1, 1], N).astype(np.int32)
    _run_both(src, {
        "a": rng.integers(-50, 50, N).astype(np.int32),
        "b": b,
        "q": np.zeros(N, np.int32),
        "r": np.zeros(N, np.int32),
    }, {})


@requires_pl_element
def test_oracle_divergent_while_with_builtins():
    src = """
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float v = fabs(x[i]);
        int steps = 0;
        while (v > 0.1f && steps < 50) {
            v = v * 0.6f + sin(v) * 0.05f;
            steps = steps + 1;
        }
        out[i] = v + (float)steps;
    }"""
    rng = np.random.default_rng(5)
    _run_both(src, {
        "x": (rng.standard_normal(N) * 3).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


@pytest.mark.parametrize("seed", range(12))
def test_oracle_random_gather_kernels(seed):
    """Randomized gather/branch kernels vs the oracle."""
    rng = np.random.default_rng(seed)
    shift = int(rng.integers(-2, 3))
    mod = int(rng.integers(2, 6))
    scale = float(rng.uniform(0.25, 2.0))
    src = f"""
    __kernel void k(__global int* idx, __global float* x, __global float* out) {{
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int j = 0; j < {mod}; j++) {{
            acc = acc + x[idx[i] + j] * {scale}f;
        }}
        if (i % {mod} == 0) {{
            acc = acc - x[i + {shift}];
        }}
        out[i] = acc;
    }}"""
    _run_both(src, {
        "idx": rng.integers(0, N, N).astype(np.int32),
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


@requires_pl_element
def test_oracle_break_in_divergent_loop():
    src = """
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float acc = 0.0f;
        for (int j = 0; j < 20; j++) {
            acc = acc + x[i] * 0.1f;
            if (acc > 1.0f) {
                break;
            }
            acc = acc + 0.01f;
        }
        out[i] = acc;
    }"""
    rng = np.random.default_rng(10)
    _run_both(src, {
        "x": (rng.standard_normal(N) * 2).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


@requires_pl_element
def test_oracle_continue_skips_rest_but_runs_step():
    src = """
    __kernel void k(__global float* out) {
        int i = get_global_id(0);
        float s = 0.0f;
        for (int j = 0; j < 10; j++) {
            if (j % 2 == (i % 2)) {
                continue;
            }
            s = s + (float)j;
        }
        out[i] = s;
    }"""
    _run_both(src, {"out": np.zeros(N, np.float32)}, {})


@requires_pl_element
def test_oracle_break_continue_mixed_while():
    src = """
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float v = x[i];
        int n = 0;
        while (n < 30) {
            n = n + 1;
            if (v < 0.0f) {
                v = v + 0.5f;
                continue;
            }
            v = v * 0.8f;
            if (v < 0.05f) {
                break;
            }
        }
        out[i] = v + (float)n;
    }"""
    rng = np.random.default_rng(11)
    _run_both(src, {
        "x": (rng.standard_normal(N) * 3).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


@requires_pl_element
def test_oracle_break_in_do_while_first_pass():
    src = """
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float v = x[i];
        int n = 0;
        do {
            if (v > 1.0f) {
                break;
            }
            v = v + 0.3f;
            n = n + 1;
        } while (n < 8);
        out[i] = v + 10.0f * (float)n;
    }"""
    rng = np.random.default_rng(12)
    _run_both(src, {
        "x": (rng.standard_normal(N) * 2).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


def test_oracle_divergent_break_poisons_uniform_gather():
    """A divergent break changes per-lane trip counts: a counter in such a
    loop must NOT be treated as uniform for scalarized gathers."""
    src = """
    __kernel void k(__global float* x, __global float* w, __global float* out) {
        int i = get_global_id(0);
        int j = 0;
        float acc = 0.0f;
        while (j < 16) {
            if (x[i] * (float)j > 4.0f) {
                break;
            }
            acc = acc + w[j];
            j = j + 1;
        }
        out[i] = acc;
    }"""
    rng = np.random.default_rng(13)
    _run_both(src, {
        "x": (rng.standard_normal(N) * 2).astype(np.float32),
        "w": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


@requires_pl_element
def test_oracle_helper_functions():
    """Non-kernel helper functions inline at call sites: scalar params,
    locals, loops inside the helper, nested helper calls."""
    src = """
    float sq(float v) {
        return v * v;
    }
    float powsum(float base, int n) {
        float acc = 0.0f;
        float p = 1.0f;
        for (int k = 0; k < n; k++) {
            p = p * base;
            acc = acc + sq(p);
        }
        return acc;
    }
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        out[i] = powsum(x[i] * 0.5f, 4) + sq(x[i]);
    }"""
    rng = np.random.default_rng(21)
    _run_both(src, {
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


@requires_pl_element
def test_oracle_helper_under_divergent_branch():
    src = """
    float pick(float a, float b) {
        float r = a;
        if (b > a) {
            r = b;
        }
        return r;
    }
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        if (x[i] > 0.0f) {
            out[i] = pick(x[i], 2.0f);
        } else {
            out[i] = pick(-x[i], 1.0f) * 0.5f;
        }
    }"""
    rng = np.random.default_rng(22)
    _run_both(src, {
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})


def test_oracle_helper_scoping_regressions():
    """Helpers must not see caller buffers, caller private arrays, or
    inherit kernel uniformity facts for same-named locals (review-found
    miscompilations)."""
    import pytest as _pytest

    from cekirdekler_tpu.errors import KernelCompileError, KernelLanguageError

    # same-named helper local must not inherit kernel-level uniformity
    src = """
    int tri(int idx) {
        int u = idx * (idx + 1) / 2;
        return u;
    }
    __kernel void k(__global float* x, __global float* out, int base) {
        int i = get_global_id(0);
        int u = base;
        out[i] = x[tri(i) % 8 + u];
    }"""
    rng = np.random.default_rng(31)
    _run_both(src, {
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {"base": 3})

    # helper param may shadow a caller private array's name
    src2 = """
    float pick(float w) {
        return w * 2.0f;
    }
    __kernel void k(__global float* x, __global float* out) {
        int i = get_global_id(0);
        float w[2];
        w[0] = x[i];
        out[i] = pick(w[0]);
    }"""
    _run_both(src2, {
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})

    # buffer access inside a helper is rejected (documented contract)
    src3 = """
    float bad(float v) {
        float t = q[0];
        return v + t;
    }
    __kernel void k(__global float* q, __global float* out) {
        int i = get_global_id(0);
        out[i] = bad(q[i]);
    }"""
    from cekirdekler_tpu.kernel import codegen as _cg, lang as _lang

    kdef = _lang.parse_kernels(src3)[0]
    fn, _ = _cg.build_kernel_fn(kdef, N, 64, N)
    with _pytest.raises((KernelCompileError, KernelLanguageError)):
        fn(0, (jnp.zeros(N, jnp.float32), jnp.zeros(N, jnp.float32)), ())

    # duplicate helper definition is a parse error
    with _pytest.raises(KernelLanguageError):
        _lang.parse_kernels(
            "float f(float v){ return v; }\n"
            "float f(float v){ return v + 1.0f; }\n"
            "__kernel void k(__global float* a){}"
        )


@pytest.mark.parametrize("seed", range(8, 14))
def test_oracle_random_control_flow_kernels(seed):
    """Randomized kernels mixing helpers, break/continue, private arrays,
    and gathers — full-language oracle fuzzing."""
    rng = np.random.default_rng(100 + seed)
    trips = int(rng.integers(3, 9))
    thresh = float(rng.uniform(0.5, 3.0))
    karr = int(rng.integers(2, 5))
    src = f"""
    float fold(float a, float b) {{
        float r = a * 0.5f + b * 0.25f;
        if (r > {thresh}f) {{
            r = r - {thresh}f;
        }}
        return r;
    }}
    __kernel void k(__global int* idx, __global float* x, __global float* out) {{
        int i = get_global_id(0);
        float t[{karr}];
        for (int j = 0; j < {karr}; j++) {{
            t[j] = x[idx[i] + j] * 0.5f;
        }}
        float acc = 0.0f;
        int n = 0;
        while (n < {trips}) {{
            n = n + 1;
            float c = fold(acc, t[n % {karr}]);
            if (c < 0.0f) {{
                acc = acc + 0.25f;
                continue;
            }}
            acc = c + x[i] * 0.125f;
            if (acc > {thresh * 2}f) {{
                break;
            }}
        }}
        out[i] = acc + t[0];
    }}"""
    _run_both(src, {
        "idx": rng.integers(0, N, N).astype(np.int32),
        "x": rng.standard_normal(N).astype(np.float32),
        "out": np.zeros(N, np.float32),
    }, {})
