"""Request-lifecycle tracing (obs/reqtrace.py): ring semantics + the
overhead pins, the telescoping phase fold and its coverage contract,
tail-anatomy percentile decomposition, per-request Perfetto tracks and
their round-trip exclusion from host spans, the 128-client live
coverage pin (phase sums explain >= 95% of every measured wall), the
/servez windowed-latency two-regime snapshot, /reqz, and the
rid-filtered decision explain.

The inc kernel adds exactly 1.0f per request — the test_serve.py
bit-exactness discipline — so the live pin runs a REAL contended
frontend, not a mock timeline."""

import inspect
import json
import threading
import time
import urllib.request
from functools import partial

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.obs.reqtrace import (
    QUEUE_PHASES,
    REQ_EVENT_KINDS,
    REQTRACE,
    TERMINAL_KINDS,
    ReqTrace,
    anatomy_table,
    fold_phases,
    phase_fracs,
    request_chrome_events,
    reqz_payload,
    slowest_requests,
    tail_anatomy,
    tenant_percentiles,
)
from cekirdekler_tpu.serve import ServeFrontend, ServeJob

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


# ---------------------------------------------------------------------------
# recorder: ring semantics, mint uniqueness, the overhead pins
# ---------------------------------------------------------------------------

class _NoopShape:
    """Same call shape as ReqTrace.event with the body removed — the
    interpreter's bound-method + kwargs floor (test_obs.py idiom)."""

    def event(self, rid, kind, **fields):
        pass


def _best_per_call(fn, n=20_000, trials=10) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        best = min(best, (time.perf_counter() - t0) / n)
    return best


def _best_pair(fn_floor, fn_probe, n=100_000, trials=10):
    """Interleaved best-of (test_obs.py): both sides get the same
    scheduler weather, best-of keeps the clean trials."""
    best_f = best_p = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(n):
            fn_floor()
        best_f = min(best_f, (time.perf_counter() - t0) / n)
        t0 = time.perf_counter()
        for _ in range(n):
            fn_probe()
        best_p = min(best_p, (time.perf_counter() - t0) / n)
    return best_f, best_p


def test_reqtrace_ring_bounded_oldest_first():
    rt = ReqTrace(capacity=16)
    for i in range(40):
        rt.event(f"r{i}", "queued", i=i)
    events = rt.snapshot()
    assert len(events) == 16
    assert rt.total_recorded == 40
    assert [e.fields["i"] for e in events] == list(range(24, 40))
    rt.clear()
    assert rt.snapshot() == [] and rt.total_recorded == 0


def test_mint_is_unique_under_contention():
    rt = ReqTrace()
    out: list = []
    mu = threading.Lock()

    def worker():
        local = [rt.mint() for _ in range(500)]
        with mu:
            out.extend(local)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(out) == len(set(out)) == 4000
    assert all(r.startswith("r") and "-" in r for r in out)


def test_disabled_reqtrace_event_overhead_under_budget():
    """The ISSUE 19 pin, same family as the flight recorder's: a
    disabled request event costs < 100 ns marginal over the identical
    no-op call, and < 1 µs absolute."""
    rt = ReqTrace()
    rt.enabled = False
    noop = _NoopShape()
    floor, per = _best_pair(
        partial(noop.event, "r1", "probe"), partial(rt.event, "r1", "probe"))
    net = per - floor
    assert net < 100e-9, (
        f"disabled reqtrace event adds {net*1e9:.0f} ns over the call "
        f"floor ({per*1e9:.0f} ns total, floor {floor*1e9:.0f} ns)")
    assert per < 1e-6, f"disabled absolute {per*1e9:.0f} ns >= 1 µs"
    assert rt.total_recorded == 0


def test_enabled_reqtrace_append_under_microsecond():
    """Enabled is one clock read + one tuple build + one GIL-atomic
    deque append: < 1 µs per append, best-of — the always-on budget the
    serve submit path rides."""
    rt = ReqTrace(capacity=1024)
    per = _best_per_call(partial(rt.event, "r1", "queued"))
    assert per < 1e-6, f"enabled reqtrace append costs {per*1e9:.0f} ns"


def test_fused_defer_hot_path_has_zero_reqtrace_code():
    """The deepest hot path stays untouched: request-lifecycle stamps
    live at the SERVE layer (submit/coalesce/dispatch), never inside
    the fused deferral fast path."""
    from cekirdekler_tpu.core.cores import Cores

    src = inspect.getsource(Cores._fused_defer)
    assert "reqtrace" not in src.lower()
    assert "REQTRACE" not in src


# ---------------------------------------------------------------------------
# the pure fold: telescoping phases, terminal-chain rule, coverage
# ---------------------------------------------------------------------------

def _chain_a():
    return [
        (100.000, "rA", "admitted", {"wait_s": 0.005, "tenant": "tA"}),
        (100.001, "rA", "queued", {}),
        (100.003, "rA", "coalesce-wait", {}),
        (100.004, "rA", "dispatched", {}),
        (100.010, "rA", "device", {}),
        (100.011, "rA", "resolved", {"latency_s": 0.016}),
    ]


def test_fold_phases_telescopes_gaps_onto_the_closing_kind():
    (rec,) = fold_phases(_chain_a())
    assert rec["rid"] == "rA" and rec["tenant"] == "tA"
    assert rec["outcome"] == "resolved"
    assert rec["phases_s"]["admitted"] == pytest.approx(0.005)  # lead wait
    assert rec["phases_s"]["queued"] == pytest.approx(0.001)
    assert rec["phases_s"]["coalesce-wait"] == pytest.approx(0.002)
    assert rec["phases_s"]["dispatched"] == pytest.approx(0.001)
    assert rec["phases_s"]["device"] == pytest.approx(0.006)
    assert rec["phases_s"]["resolved"] == pytest.approx(0.001)
    # wall prefers the terminal event's measured latency_s, and the
    # telescoped phases cover it exactly here
    assert rec["wall_s"] == pytest.approx(0.016)
    assert rec["coverage"] == pytest.approx(1.0)
    assert rec["kinds"] == ["admitted", "queued", "coalesce-wait",
                            "dispatched", "device", "resolved"]


def test_fold_phases_accepts_wire_rows_and_dicts():
    """The three transports (ReqEvent, [t, rid, kind, fields] off the
    _fabric_worker wire, /reqz dict) fold identically."""
    as_tuples = fold_phases(_chain_a())
    as_lists = fold_phases([list(e) for e in _chain_a()])
    as_dicts = fold_phases([
        {"t": t, "rid": rid, "kind": kind, "fields": f}
        for t, rid, kind, f in _chain_a()])
    assert as_tuples == as_lists == as_dicts


def test_fold_phases_terminal_chain_rule():
    """A mid-chain `failed` followed by a reroute hop is NOT an
    outcome — the chain continues on a survivor; only a chain ENDING
    in resolved/failed is terminal."""
    hop = [
        (10.0, "rB", "admitted", {"wait_s": 0.0}),
        (10.1, "rB", "failed", {"latency_s": 0.1}),
        (10.2, "rB", "diverted", {}),
        (10.3, "rB", "rerouted", {}),
    ]
    (rec,) = fold_phases(hop)
    assert rec["outcome"] is None
    assert rec["wall_s"] == pytest.approx(0.3)  # stamp extent fallback
    done = hop + [
        (10.4, "rB", "admitted", {}),
        (10.5, "rB", "resolved", {"latency_s": 0.5}),
    ]
    (rec,) = fold_phases(done)
    assert rec["outcome"] == "resolved"
    assert rec["wall_s"] == pytest.approx(0.5)
    # the whole cross-shard story stays one record
    assert rec["kinds"] == ["admitted", "failed", "diverted", "rerouted",
                            "admitted", "resolved"]


def test_tail_anatomy_nearest_rank_and_phase_fracs():
    events = []
    for i in range(100):
        wall = (i + 1) * 1e-3
        events.append((float(i), f"r{i:03d}", "admitted",
                       {"wait_s": 0.0, "tenant": "tA"}))
        events.append((float(i) + wall, f"r{i:03d}", "resolved",
                       {"latency_s": wall}))
    records = fold_phases(events)
    doc = tail_anatomy(records)
    assert doc["count"] == 100
    # nearest-rank over 100 sorted walls: p50 -> index 50, p99 -> 98
    assert doc["pcts"]["p50"]["wall_ms"] == pytest.approx(51.0)
    assert doc["pcts"]["p99"]["wall_ms"] == pytest.approx(99.0)
    assert doc["pcts"]["p99"]["rid"] == "r098"
    assert doc["mean"]["wall_ms"] == pytest.approx(50.5)
    (rec_a,) = fold_phases(_chain_a())
    fr = phase_fracs(rec_a)
    assert fr["queue_frac"] == pytest.approx(0.008 / 0.016)
    assert fr["device_frac"] == pytest.approx(0.006 / 0.016)
    assert set(QUEUE_PHASES) == {"admitted", "queued", "coalesce-wait"}
    # empty guard
    assert phase_fracs({}) == {"queue_frac": 0.0, "device_frac": 0.0}


def test_tenant_percentiles_and_slowest():
    events = []
    for i, tenant in enumerate(["tA", "tB"] * 5):
        wall = (i + 1) * 1e-3
        events.append((float(i), f"r{i}", "admitted",
                       {"wait_s": 0.0, "tenant": tenant}))
        events.append((float(i) + wall, f"r{i}", "resolved",
                       {"latency_s": wall}))
    records = fold_phases(events)
    per = tenant_percentiles(records)
    assert per["tA"]["count"] == per["tB"]["count"] == 5
    assert per["tB"]["p99_ms"] == pytest.approx(10.0)
    slow = slowest_requests(records, n=3)
    assert [r["rid"] for r in slow] == ["r9", "r8", "r7"]


def test_anatomy_table_renders_every_phase_column():
    doc = tail_anatomy(fold_phases(_chain_a()))
    text = anatomy_table(doc)
    for kind in ("admitted", "coalesce-wait", "device", "resolved"):
        assert kind in text
    assert "cover" in text
    assert anatomy_table({}) == \
        "tail anatomy: no completed requests recorded"


def test_reqz_payload_shape_from_explicit_events():
    doc = reqz_payload(events=_chain_a())
    assert doc["requests"] == 1 and doc["events"] == 6
    assert doc["anatomy"]["count"] == 1
    assert doc["slowest"][0]["rid"] == "rA"
    assert doc["tenants"]["tA"]["count"] == 1
    assert doc["recent"][0]["kinds"][-1] == "resolved"


# ---------------------------------------------------------------------------
# Perfetto tracks: one thread per rid, round-trip exclusion
# ---------------------------------------------------------------------------

def test_request_chrome_events_one_track_per_rid():
    events = _chain_a() + [
        (100.002, "rB", "admitted", {"wait_s": 0.001}),
        (100.006, "rB", "resolved", {"latency_s": 0.005}),
    ]
    out = request_chrome_events(events)
    slices = [e for e in out if e.get("ph") == "X"]
    assert all(e["cat"] == "ck-req" for e in slices)
    assert all(e["args"]["rid"] in ("rA", "rB") for e in slices)
    # one tid per rid, stable across its slices
    tids = {}
    for e in slices:
        tids.setdefault(e["args"]["rid"], set()).add(e["tid"])
    assert all(len(v) == 1 for v in tids.values())
    assert tids["rA"] != tids["rB"]
    # the lead wait_s slice ENDS at the first stamp
    lead = min((e for e in slices if e["args"]["rid"] == "rA"),
               key=lambda e: e["ts"])
    assert lead["name"] == "admitted"
    assert lead["dur"] == pytest.approx(0.005 * 1e6)


def test_unified_trace_carries_req_tracks_and_split_ignores_them():
    from cekirdekler_tpu.trace.device import (
        split_unified_trace,
        unified_chrome_trace,
    )

    doc = unified_chrome_trace([], None, req_events=_chain_a())
    req = [e for e in doc["traceEvents"] if e.get("cat") == "ck-req"]
    assert req, "request tracks missing from the unified trace"
    spans, ops = split_unified_trace(doc)
    assert spans == [] and ops == []  # ck-req never masquerades as host


# ---------------------------------------------------------------------------
# /servez windowed latency: the two-regime snapshot
# ---------------------------------------------------------------------------

def test_window_latency_shows_the_current_regime():
    """512 slow walls followed by 512 fast ones: the last-N window
    reports the FAST regime while a cumulative mean would still be
    dominated by the slow one — the reason /servez carries the window
    next to the lifetime tenant accounting."""
    from cekirdekler_tpu.serve.frontend import _window_latency

    values = [0.100] * 512 + [0.001] * 512
    doc = _window_latency(values, window=512)
    assert doc["count"] == 512
    assert doc["p50_ms"] == pytest.approx(1.0, rel=0.01)
    assert doc["p99_ms"] == pytest.approx(1.0, rel=0.01)
    # flip the regimes: the window sees the slow tail instead
    doc = _window_latency(list(reversed(values)), window=512)
    assert doc["p50_ms"] == pytest.approx(100.0, rel=0.01)
    assert _window_latency([])["count"] == 0
    assert _window_latency([])["p50_ms"] is None


# ---------------------------------------------------------------------------
# the live pin: 128 contended clients, coverage >= 0.95 per request
# ---------------------------------------------------------------------------

def test_live_128_clients_phase_sums_cover_the_wall(devs):
    """The acceptance pin: under a 128-client contended run every
    completed request's telescoped phase sum explains >= 95% of its
    measured wall — no unexplained milliseconds — and the live
    surfaces (/reqz, the /servez latency window) see the run."""
    n = 2048
    cr = NumberCruncher(devs.subset(2), INC)
    a = ClArray(np.zeros(n, np.float32), name="cov")
    a.partial_read = True
    job = ServeJob(params=[a], kernels=["inc"], compute_id=7300,
                   global_range=n, local_range=64)
    fe = ServeFrontend(cr, max_batch=256, gather_window_s=0.002,
                       name="covpin")
    requests_each = 2
    t_wall0 = time.time()
    errs: list = []
    try:
        def client(tenant):
            for _ in range(requests_each):
                try:
                    fe.call(tenant, job, timeout=60.0)
                except Exception as e:  # noqa: BLE001 - assert below
                    errs.append(e)

        threads = [threading.Thread(target=client, args=(f"t{i % 4}",))
                   for i in range(128)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not errs, errs[:3]
        # wall-clock bound: only THIS run's events (REQTRACE is
        # process-global and other tests may have written to it)
        events = [e for e in REQTRACE.snapshot() if e.t >= t_wall0]
        records = [r for r in fold_phases(events)
                   if r["outcome"] == "resolved"]
        assert len(records) >= 128 * requests_each
        bad = [(r["rid"], r["coverage"]) for r in records
               if r["coverage"] < 0.95]
        assert not bad, (
            f"{len(bad)}/{len(records)} requests have phase sums "
            f"covering < 95% of their wall: {bad[:5]}")
        # every request's story uses the declared vocabulary only
        assert {k for r in records for k in r["kinds"]} <= \
            set(REQ_EVENT_KINDS)
        doc = tail_anatomy(records)
        assert doc["count"] == len(records)
        assert doc["pcts"]["p99"]["coverage"] >= 0.95
        fr = phase_fracs(next(r for r in records
                              if r["rid"] == doc["pcts"]["p99"]["rid"]))
        assert 0.0 <= fr["queue_frac"] <= 1.0 + 1e-9
        assert 0.0 <= fr["device_frac"] <= 1.0 + 1e-9
        # the /servez windowed latency saw this run
        lat = fe.stats()["latency"]
        assert lat["count"] >= 256 and lat["p50_ms"] > 0
        # /reqz live over HTTP
        srv = cr.serve_debug(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/reqz?slow=3", timeout=10) as r:
            body = json.loads(r.read())
        assert body["enabled"] is True
        assert body["requests"] >= len(records)
        assert len(body["slowest"]) == 3
        assert body["anatomy"]["count"] >= len(records)
    finally:
        fe.close()
        cr.dispose()


# ---------------------------------------------------------------------------
# rid-filtered decision explain (ckreplay explain --rid)
# ---------------------------------------------------------------------------

def test_explain_rid_matches_all_three_input_shapes():
    """The rid appears in decision inputs three ways — scalar `rid`
    (admission/route/retry), flat `rids` (containment), nested
    `groups[i].rids` (coalesce) — and explain_rid finds every one,
    excluding other rids' decisions."""
    from cekirdekler_tpu.obs.replay import explain_rid

    records = [
        {"kind": "admission", "seq": 1, "t": 1.0,
         "inputs": {"rid": "rX", "tenant": "tA"},
         "outputs": {"admit": True}},
        {"kind": "coalesce", "seq": 2, "t": 2.0,
         "inputs": {"groups": [{"key": "g0", "rids": ["rQ", "rX"]}]},
         "outputs": {"picked": ["g0"]}},
        {"kind": "containment", "seq": 3, "t": 3.0,
         "inputs": {"rids": ["rX", "rY"]},
         "outputs": {"mode": "bisect"}},
        {"kind": "route", "seq": 4, "t": 4.0,
         "inputs": {"rid": "rZ"}, "outputs": {"shard": "m1"}},
    ]
    doc = explain_rid(records, "rX")
    assert doc["rid"] == "rX" and doc["decisions"] == 3
    assert doc["kinds"] == {"admission": 1, "coalesce": 1,
                            "containment": 1}
    assert [s["seq"] for s in doc["steps"]] == [1, 2, 3]
    assert explain_rid(records, "rZ")["decisions"] == 1
    assert explain_rid(records, "r-nowhere")["decisions"] == 0


def test_ckreplay_render_explain_rid():
    from cekirdekler_tpu.obs.replay import explain_rid
    from tools.ckreplay import render_explain_rid

    doc = explain_rid([
        {"kind": "admission", "seq": 1, "t": 1.0,
         "inputs": {"rid": "rX"},
         "outputs": {"admit": False, "reason": "queue-full"}},
    ], "rX")
    text = render_explain_rid(doc)
    assert "rX" in text and "admission" in text and "queue-full" in text
