"""Differential fuzzing of the two kernel lowerings.

Random kernels (arithmetic, builtins with safe domains, branches, bounded
loops with accumulators, statically-shifted window loads, lane-uniform
gather loops) are compiled through BOTH the vectorized XLA lowering
(kernel/codegen.py) and the Pallas tile lowering (kernel/pallas_backend.py,
interpret mode) and must agree on random inputs — any divergence is a
compiler bug in one of them.  The generator stays inside the (round-4
widened) Pallas subset so every case exercises both backends.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from cekirdekler_tpu.kernel import codegen, lang  # noqa: E402
from cekirdekler_tpu.kernel.pallas_backend import (  # noqa: E402
    PallasUnsupported,
    build_kernel_fn_pallas,
)

import jax.experimental.pallas as _pl  # noqa: E402

# env capability, not a code property: every fuzz case drives the
# round-4 widened Pallas tile lowering, which needs pl.Element
# (pallas_backend.py:469) — absent from the jax this CPU container
# ships, so the whole file failed identically every run.  On capable
# rigs the condition is False and the fuzz runs unchanged.
pytestmark = pytest.mark.skipif(
    not hasattr(_pl, "Element"),
    reason="jax.experimental.pallas lacks pl.Element in this environment "
           "(pre-0.5-era pallas) — the widened tile lowering cannot build",
)

N = 256


def _gen_expr(rng, depth, vars_):
    """A numerically tame float expression over the given variable names."""
    if depth <= 0 or rng.random() < 0.3:
        choices = list(vars_) + ["1.5f", "0.25f", "-2.0f", "3.0f"]
        return str(rng.choice(choices))
    kind = rng.integers(0, 5)
    a = _gen_expr(rng, depth - 1, vars_)
    b = _gen_expr(rng, depth - 1, vars_)
    if kind == 0:
        return f"({a} + {b})"
    if kind == 1:
        return f"({a} - {b})"
    if kind == 2:
        return f"({a} * {b} * 0.125f)"  # damp growth
    if kind == 3:
        return f"({a} / (1.0f + {b} * {b}))"  # denominator >= 1
    fn = rng.choice(["sin", "cos", "tanh", "sqrt", "exp"])
    if fn == "sqrt":
        return f"sqrt(fabs({a}))"
    if fn == "exp":
        return f"exp(-fabs({a}))"
    return f"{fn}({a})"


def _gen_kernel(seed: int) -> str:
    rng = np.random.default_rng(seed)
    # optionally route one subexpression through an inlined helper
    use_helper = bool(rng.integers(0, 2))
    helper = (
        "float hmix(float p, float q) {\n"
        "    float r = p * 0.5f;\n"
        "    if (q > 0.0f) {\n"
        "        r = r + q * 0.25f;\n"
        "    }\n"
        "    return r;\n"
        "}\n"
        if use_helper else ""
    )
    body = ["int i = get_global_id(0);",
            "float x = a[i];", "float y = b[i];"]
    vars_ = ["x", "y"]
    # statically-shifted window load (halo-block path): row- and/or
    # lane-crossing shifts, clamped at the buffer edge
    if rng.integers(0, 2):
        c = int(rng.choice([-257, -129, -128, -3, -1, 1, 2, 127, 128, 200]))
        body.append(f"float ws = b[i + ({c})] * 0.5f;")
        vars_.append("ws")
    # lane-uniform gather loop (SMEM operand path): streams `a` at a
    # uniform index, the n-body inner-loop shape
    if rng.integers(0, 2):
        k = int(rng.integers(3, 9))
        d = int(rng.integers(0, 4))
        body.append("float us = 0.0f;")
        body.append(
            f"for (int uj = 0; uj < {k}; uj++) "
            f"{{ us = us + a[uj + {d}] * 0.0625f; }}"
        )
        vars_.append("us")
    # a few straight-line statements
    for v in ("t0", "t1"):
        body.append(f"float {v} = {_gen_expr(rng, 3, vars_)};")
        vars_.append(v)
    if use_helper:
        body.append(f"float th = hmix({_gen_expr(rng, 2, vars_)}, y);")
        vars_.append("th")
    # a branch
    body.append(
        f"if ({_gen_expr(rng, 2, vars_)} > 0.0f) {{"
        f" t0 = {_gen_expr(rng, 2, vars_)}; }}"
        f" else {{ t1 = {_gen_expr(rng, 2, vars_)}; }}"
    )
    # a bounded loop with an accumulator (trip count varies per lane),
    # optionally with divergent break/continue
    trips = int(rng.integers(2, 6))
    exit_kind = int(rng.integers(0, 3))  # 0: none, 1: break, 2: continue
    body.append("float acc = t0;")
    body.append("int k = 0;")
    loop_body = f" acc = acc * 0.5f + {_gen_expr(rng, 2, vars_)} * 0.25f;"
    if exit_kind == 1:
        loop_body += " if (acc > 2.0f) { break; }"
    elif exit_kind == 2:
        loop_body += " k = k + 1; if (acc < 0.0f) { acc = acc + 0.125f; continue; }"
    if exit_kind != 2:
        loop_body += " k = k + 1;"
    body.append(
        f"while (k < {trips} && fabs(acc) < 50.0f) {{{loop_body} }}"
    )
    body.append("out[i] = acc + t1;")
    inner = "\n        ".join(body)
    return (
        helper
        + "__kernel void fz(__global float* a, __global float* b, "
        "__global float* out) {\n        " + inner + "\n}"
    )


@pytest.mark.parametrize("seed", range(32))
def test_lowerings_agree(seed):
    src = _gen_kernel(seed)
    kdef = lang.parse_kernels(src)[0]
    xla_fn, _ = codegen.build_kernel_fn(kdef, N, 64, N)
    try:
        pl_fn, _ = build_kernel_fn_pallas(kdef, N, 64, N, interpret=True,
                                         force=True)
    except PallasUnsupported:
        pytest.fail(f"generator left the elementwise subset:\n{src}")
    rng = np.random.default_rng(1000 + seed)
    a = jnp.asarray(rng.standard_normal(N).astype(np.float32))
    b = jnp.asarray(rng.standard_normal(N).astype(np.float32))
    out = jnp.zeros(N, jnp.float32)
    got_x = np.asarray(xla_fn(0, (a, b, out), ())[2])
    got_p = np.asarray(pl_fn(0, (a, b, out), ())[2])
    assert np.isfinite(got_x).all(), f"non-finite XLA output:\n{src}"
    np.testing.assert_allclose(
        got_p, got_x, rtol=1e-5, atol=1e-5,
        err_msg=f"lowering divergence for kernel:\n{src}",
    )


@pytest.mark.parametrize("seed", range(5))
def test_lowerings_agree_mixed_dtypes(seed):
    """The dtype-boundary contract (loads cast storage -> declared ctype,
    stores cast back) must hold for ANY caller array dtype against the
    float-declared generator kernels — output dtypes preserved, values
    within low-precision tolerance, both lowerings in agreement."""
    import jax.numpy as jnp2

    DTYPES = [jnp2.float32, jnp2.bfloat16, jnp2.float16, jnp2.int32]
    src = _gen_kernel(seed)
    kdef = lang.parse_kernels(src)[0]
    rng = np.random.default_rng(7000 + seed)
    dts = [DTYPES[rng.integers(0, len(DTYPES))] for _ in range(3)]
    arrs = tuple(
        jnp2.asarray((rng.standard_normal(N) * 2).astype(np.float32)).astype(dt)
        for dt in dts
    )
    xla_fn, _ = codegen.build_kernel_fn(kdef, N, 64, N)
    pl_fn, _ = build_kernel_fn_pallas(kdef, N, 64, N, interpret=True,
                                     force=True)
    gx = xla_fn(0, arrs, ())
    gp = pl_fn(0, arrs, ())
    for i, (a, b) in enumerate(zip(gx, gp)):
        assert a.dtype == b.dtype == arrs[i].dtype, (i, a.dtype, b.dtype)
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(b, dtype=np.float32),
            rtol=3e-2, atol=3e-2,
            err_msg=f"arr{i} dtype={a.dtype} kernel:\n{src}")
