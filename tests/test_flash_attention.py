"""Flash attention (ops/flash_attention.py) vs the dense reference: values
and gradients must agree; causal masking and uneven Tq/Tk supported.
Runs in Pallas interpret mode on the rig; compiled on TPU via bench/tools."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from cekirdekler_tpu.ops.flash_attention import flash_attention  # noqa: E402
from cekirdekler_tpu.parallel.attention import attention_reference  # noqa: E402


def _qkv(B=2, Tq=64, Tk=64, H=2, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda t: jnp.asarray(rng.standard_normal((B, t, H, D)), jnp.float32)
    return mk(Tq), mk(Tk), mk(Tk)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    want = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 32, 32, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_cross_attention_tq_ne_tk():
    q, k, v = _qkv(Tq=32, Tk=96)
    want = attention_reference(q, k, v, causal=False)
    got = flash_attention(q, k, v, False, 32, 32, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    q, k, v = _qkv(B=1, Tq=32, Tk=32, H=2, D=8)

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, 16, 16, True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ref, g_fl, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4,
            err_msg=f"grad d{name} mismatch",
        )


def test_flash_blocking_degrades_then_rejects():
    # blocks degrade by gcd (48 with a 32 request -> 16-wide tiles) ...
    q, k, v = _qkv(Tq=48, Tk=48)
    got = flash_attention(q, k, v, False, 32, 32, True)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # ... but truly degenerate lengths (gcd < 8) still raise
    q, k, v = _qkv(Tq=36, Tk=36)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, False, 32, 32, True)


def test_transformer_flash_attention_matches_dense():
    """The flagship transformer with attention='flash' must match the
    dense path in forward loss and gradients (tiny config, interpret).
    T=128 tokens: the r6 default_blocks policy keeps T>=128 on the
    tiled Pallas path (smaller T routes to dense — covered by
    test_transformer_flash_odd_seq_falls_back_to_dense)."""
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    def build(attn):
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq=128, dtype=jnp.float32, attention=attn,
        )
        return Transformer(cfg)

    tok = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (2, 129)), jnp.int32
    )
    dense = build("dense")
    params = dense.init(jax.random.PRNGKey(0))
    flash = build("flash")

    def loss(model, p):
        logits = model.apply(p, tok[:, :-1])
        tgt = tok[:, 1:]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, tgt[..., None], axis=-1).mean()

    l_d, g_d = jax.value_and_grad(lambda p: loss(dense, p))(params)
    l_f, g_f = jax.value_and_grad(lambda p: loss(flash, p))(params)
    np.testing.assert_allclose(float(l_f), float(l_d), rtol=1e-5)
    flat_d = jax.tree.leaves(g_d)
    flat_f = jax.tree.leaves(g_f)
    for a, b in zip(flat_d, flat_f):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-3, atol=1e-5)


def test_transformer_flash_non_multiple_seq_len():
    """Sequence lengths that aren't multiples of 128 must work (block is
    chosen to divide T), and a mesh'd model with attention='flash' must
    fall back to a partitionable path instead of crashing."""
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        max_seq=256, dtype=jnp.float32, attention="flash",
    )
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tok = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (1, 200)), jnp.int32
    )
    out = model.apply(params, tok)   # T=200: block gcd(200,128)=8
    assert out.shape == (1, 200, 64)
    assert np.isfinite(np.asarray(out)).all()


def test_transformer_flash_precision_follows_dtype(monkeypatch):
    """bf16 activations must select the r6 "default" (bf16-streamed)
    kernel path; f32 activations keep "highest" (the ~5e-5 dense
    agreement the parity tests pin); attention_precision overrides."""
    import cekirdekler_tpu.ops.flash_attention as fa
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    seen = []
    orig = fa.flash_attention

    def spy(q, k, v, causal=False, block_q=None, block_k=None,
            interpret=None, precision="highest"):
        seen.append(precision)
        return orig(q, k, v, causal, block_q, block_k, interpret, precision)

    monkeypatch.setattr(fa, "flash_attention", spy)
    tok = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, (1, 128)), jnp.int32
    )
    for dtype, override, want in (
        (jnp.bfloat16, None, "default"),
        (jnp.float32, None, "highest"),
        (jnp.float32, "default", "default"),
    ):
        seen.clear()
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq=128, dtype=dtype, attention="flash",
            attention_precision=override,
        )
        model = Transformer(cfg)
        out = model.apply(model.init(jax.random.PRNGKey(0)), tok)
        assert np.isfinite(np.asarray(out, np.float32)).all()
        assert seen and all(p == want for p in seen), (dtype, override, seen)


def test_auto_block_degenerate_lengths():
    from cekirdekler_tpu.ops.flash_attention import auto_block

    assert auto_block(2048) == 512   # default target: measured sweet spot
    assert auto_block(2048, 128) == 128
    assert auto_block(200) == 8
    assert auto_block(999) is None   # odd: gcd 1 — degenerate
    assert auto_block(17) is None


def test_default_blocks_policy():
    """Default-argument block policy: 512 target by gcd, dense fallback
    (None) whenever only sub-128 (sub-MXU) tiles divide T."""
    from cekirdekler_tpu.ops.flash_attention import default_blocks

    assert default_blocks(4096) == (512, 512)
    assert default_blocks(640) == (128, 128)
    assert default_blocks(2048, 1024) == (512, 512)
    assert default_blocks(96) is None     # 32-wide tiles: dense wins
    assert default_blocks(4104) is None   # 8-wide tiles: dense wins
    assert default_blocks(200) is None


@pytest.mark.parametrize("T", [96, 4104])
def test_flash_default_args_dense_fallback(T):
    """Degrade, don't raise (ADVICE r4 / VERDICT #7): default-argument
    calls at awkward lengths (only sub-128 tiles divide T) fall back to
    dense attention instead of ValueError — and still match the
    reference."""
    q, k, v = _qkv(B=1, Tq=T, Tk=T, H=1, D=8, seed=T)
    got = flash_attention(q, k, v, True)  # default blocks
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_default_args_dense_fallback_differentiable():
    """The dense fallback must be trainable too (plain autodiff)."""
    q, k, v = _qkv(B=1, Tq=96, Tk=96, H=1, D=8, seed=5)

    def loss_fl(q, k, v):
        return (flash_attention(q, k, v, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    g = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"fallback grad d{name}")


def test_flash_default_args_tiled_path_640():
    """T=640 under default args stays on the FLASH path (gcd with the
    512 target is 128 — a full MXU tile) and matches the reference."""
    q, k, v = _qkv(B=1, Tq=640, Tk=640, H=1, D=16, seed=6)
    got = flash_attention(q, k, v, True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("T", [512, 4096])
def test_flash_bf16_default_grad_agreement(T):
    """Regression gate for the r6 bf16 end-to-end default path: grads of
    the bf16-streamed kernels vs the dense f32 reference must stay
    within the documented ~1e-2 flash trade (measured ~3e-3 on this
    configuration)."""
    B, H, D = 1, (2 if T == 512 else 1), 32
    rng = np.random.default_rng(T)
    mk = lambda: jnp.asarray(
        rng.standard_normal((B, T, H, D)).astype(np.float32) * 0.3)
    q, k, v = mk(), mk(), mk()

    def loss_def(q, k, v):
        return flash_attention(q, k, v, True, None, None, None,
                               "default").sum()

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    gf = jax.grad(loss_def, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    rel = max(
        float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        for a, b in zip(gf, gd)
    )
    # 2e-2: the SAME regression gate bench.py applies (measured ~3e-3
    # here; the documented trade is ~1e-2, the gate leaves rig headroom)
    assert rel < 2e-2, f"bf16 default-path grads diverged: rel={rel:.2e}"


def _eqn_out_shapes(closed_jaxpr):
    """All eqn output shapes in a jaxpr, recursing into sub-jaxprs
    (pjit bodies, custom_vjp calls, pallas kernels)."""
    from jax.core import Jaxpr

    shapes = []

    def walk(jx):
        for eqn in jx.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if hasattr(aval, "shape"):
                    shapes.append(tuple(aval.shape))
            for p in eqn.params.values():
                for cand in (p if isinstance(p, (list, tuple)) else [p]):
                    if isinstance(cand, Jaxpr):
                        walk(cand)
                    elif isinstance(getattr(cand, "jaxpr", None), Jaxpr):
                        walk(cand.jaxpr)

    walk(closed_jaxpr.jaxpr)
    return shapes


def test_bwd_lse_delta_operands_compact():
    """The r6 bandwidth fix pinned: the fwd residual logsumexp is a
    compact [B*H, T, 1] column, and NO [B*H, T, 128] lane-broadcast
    tile appears anywhere in the backward graph (that layout carried
    128x the needed lse/delta HBM bytes in r5)."""
    from cekirdekler_tpu.ops.flash_attention import _flash_forward

    B, T, H, D = 1, 256, 2, 16
    q, k, v = _qkv(B=B, Tq=T, Tk=T, H=H, D=D, seed=8)
    out, lse, _ = _flash_forward(q, k, v, True, 128, 128, True, "highest",
                                 with_lse=True)
    assert lse.shape == (B * H, T, 1), lse.shape

    def loss(q, k, v):
        return flash_attention(q, k, v, True, 128, 128, True).sum()

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    shapes = _eqn_out_shapes(jaxpr)
    assert (B * H, T, 128) not in shapes, (
        "lane-broadcast lse/delta tile reappeared in the backward")
    # positive control: the compact operand layout IS present
    assert (B * H, T, 1) in shapes


def test_transformer_flash_odd_seq_falls_back_to_dense():
    """Odd sequence lengths must not explode the Pallas grid — the flash
    config silently uses the dense path and still matches it."""
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    def build(attn):
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq=64, dtype=jnp.float32, attention=attn,
        )
        return Transformer(cfg)

    tok = jnp.asarray(
        np.random.default_rng(9).integers(0, 64, (1, 33)), jnp.int32
    )
    dense = build("dense")
    params = dense.init(jax.random.PRNGKey(0))
    out_d = dense.apply(params, tok)
    out_f = build("flash").apply(params, tok)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=1e-6)
