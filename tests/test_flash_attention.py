"""Flash attention (ops/flash_attention.py) vs the dense reference: values
and gradients must agree; causal masking and uneven Tq/Tk supported.
Runs in Pallas interpret mode on the rig; compiled on TPU via bench/tools."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from cekirdekler_tpu.ops.flash_attention import flash_attention  # noqa: E402
from cekirdekler_tpu.parallel.attention import attention_reference  # noqa: E402


def _qkv(B=2, Tq=64, Tk=64, H=2, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda t: jnp.asarray(rng.standard_normal((B, t, H, D)), jnp.float32)
    return mk(Tq), mk(Tk), mk(Tk)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    want = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 32, 32, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_cross_attention_tq_ne_tk():
    q, k, v = _qkv(Tq=32, Tk=96)
    want = attention_reference(q, k, v, causal=False)
    got = flash_attention(q, k, v, False, 32, 32, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    q, k, v = _qkv(B=1, Tq=32, Tk=32, H=2, D=8)

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, 16, 16, True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ref, g_fl, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4,
            err_msg=f"grad d{name} mismatch",
        )


def test_flash_blocking_degrades_then_rejects():
    # blocks degrade by gcd (48 with a 32 request -> 16-wide tiles) ...
    q, k, v = _qkv(Tq=48, Tk=48)
    got = flash_attention(q, k, v, False, 32, 32, True)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # ... but truly degenerate lengths (gcd < 8) still raise
    q, k, v = _qkv(Tq=36, Tk=36)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, False, 32, 32, True)


def test_transformer_flash_attention_matches_dense():
    """The flagship transformer with attention='flash' must match the
    dense path in forward loss and gradients (tiny config, interpret)."""
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    def build(attn):
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, attention=attn,
        )
        return Transformer(cfg)

    tok = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (2, 17)), jnp.int32
    )
    dense = build("dense")
    params = dense.init(jax.random.PRNGKey(0))
    flash = build("flash")

    def loss(model, p):
        logits = model.apply(p, tok[:, :-1])
        tgt = tok[:, 1:]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, tgt[..., None], axis=-1).mean()

    l_d, g_d = jax.value_and_grad(lambda p: loss(dense, p))(params)
    l_f, g_f = jax.value_and_grad(lambda p: loss(flash, p))(params)
    np.testing.assert_allclose(float(l_f), float(l_d), rtol=1e-5)
    flat_d = jax.tree.leaves(g_d)
    flat_f = jax.tree.leaves(g_f)
    for a, b in zip(flat_d, flat_f):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-3, atol=1e-5)


def test_transformer_flash_non_multiple_seq_len():
    """Sequence lengths that aren't multiples of 128 must work (block is
    chosen to divide T), and a mesh'd model with attention='flash' must
    fall back to a partitionable path instead of crashing."""
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        max_seq=256, dtype=jnp.float32, attention="flash",
    )
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tok = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (1, 200)), jnp.int32
    )
    out = model.apply(params, tok)   # T=200: block gcd(200,128)=8
    assert out.shape == (1, 200, 64)
    assert np.isfinite(np.asarray(out)).all()


def test_auto_block_degenerate_lengths():
    from cekirdekler_tpu.ops.flash_attention import auto_block

    assert auto_block(2048) == 512   # default target: measured sweet spot
    assert auto_block(2048, 128) == 128
    assert auto_block(200) == 8
    assert auto_block(999) is None   # odd: gcd 1 — degenerate
    assert auto_block(17) is None


def test_transformer_flash_odd_seq_falls_back_to_dense():
    """Odd sequence lengths must not explode the Pallas grid — the flash
    config silently uses the dense path and still matches it."""
    from cekirdekler_tpu.models import Transformer, TransformerConfig

    def build(attn):
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq=64, dtype=jnp.float32, attention=attn,
        )
        return Transformer(cfg)

    tok = jnp.asarray(
        np.random.default_rng(9).integers(0, 64, (1, 33)), jnp.int32
    )
    dense = build("dense")
    params = dense.init(jax.random.PRNGKey(0))
    out_d = dense.apply(params, tok)
    out_f = build("flash").apply(params, tok)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=1e-6)
