"""DrainController (obs/drain.py): the actuator that turns
HealthMonitor drain advisories into quarantine/redistribute/readmit
actions (ISSUE 13).  Pure-transition properties, range masking,
hysteresis (no flapping), the availability floor, Cores integration at
barriers, decision replay, and the serving tier's drain-aware gate.

Health evidence is INJECTED (the DCN-test convention: loopback rigs
cannot produce deterministic per-lane degradation) — the chaos suite
(tests/test_faultinject.py) covers the same loop driven by real seeded
fault injection."""

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.obs.decisions import DECISIONS
from cekirdekler_tpu.obs.drain import (
    DrainController,
    apply_quarantine,
    drain_transition,
)
from cekirdekler_tpu.obs.health import HealthMonitor
from cekirdekler_tpu.obs.replay import replay_record, verify_records

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def _feed(mon: HealthMonitor, lane: int, value: float, windows: int = 1):
    for _ in range(windows * mon.window):
        mon.observe(lane, "fence", value)


def _degrade(mon: HealthMonitor, lane: int, base: float = 0.01):
    """Build a baseline then push the lane to a sticky degraded."""
    _feed(mon, lane, base, windows=mon.min_history)
    _feed(mon, lane, base * 10.0, windows=mon.confirm + 1)


# ---------------------------------------------------------------------------
# the pure transition
# ---------------------------------------------------------------------------

def test_transition_drains_degraded_lane():
    r = drain_transition(
        {"0": "ok", "1": "degraded"}, {"0": "active", "1": "active"},
        {}, {}, 2, 2)
    assert r["drained"] == ["1"]
    assert r["states"]["1"] == "quarantined"
    assert r["hold"]["1"] == 2


def test_transition_hold_then_probation_then_readmit():
    st = {"0": "active", "1": "quarantined"}
    hold = {"1": 2}
    streak = {}
    deg = {"0": "ok", "1": "degraded"}
    r = drain_transition(deg, st, hold, streak, 2, 2)
    assert r["states"]["1"] == "quarantined" and r["hold"]["1"] == 1
    r = drain_transition(deg, r["states"], r["hold"], r["clear_streak"], 2, 2)
    assert r["probed"] == ["1"] and r["states"]["1"] == "probation"
    ok = {"0": "ok", "1": "ok"}
    r = drain_transition(ok, r["states"], r["hold"], r["clear_streak"], 2, 2)
    assert r["readmitted"] == [] and r["clear_streak"]["1"] == 1
    r = drain_transition(ok, r["states"], r["hold"], r["clear_streak"], 2, 2)
    assert r["readmitted"] == ["1"] and r["states"]["1"] == "active"


def test_transition_probation_relapse_is_not_a_flap():
    """A still-degraded probation lane goes BACK to quarantine (hold
    reset) — it never touches active, so there is no drain/readmit
    flapping around the verdict boundary.  The relapse lands in
    `drained` (a re-quarantine IS a drain action: decision recorded,
    ck_drain_total moves — oscillation is never silent)."""
    st = {"0": "active", "1": "probation"}
    r = drain_transition({"1": "degraded"}, st, {}, {"1": 1}, 3, 2)
    assert r["states"]["1"] == "quarantined"
    assert r["hold"]["1"] == 3
    assert r["readmitted"] == [] and r["drained"] == ["1"]
    # suspect holds position and resets the clear streak
    r = drain_transition({"1": "suspect"}, st, {}, {"1": 1}, 3, 2)
    assert r["states"]["1"] == "probation"
    assert r["clear_streak"]["1"] == 0


def test_transition_never_drains_last_active_lane():
    r = drain_transition(
        {"0": "degraded", "1": "degraded"},
        {"0": "active", "1": "active"}, {}, {}, 2, 2)
    # one lane drains, the last active one is refused (availability)
    assert r["drained"] == ["0"]
    assert r["states"]["1"] == "active"
    r2 = drain_transition(
        {"0": "degraded", "1": "degraded"},
        r["states"], r["hold"], r["clear_streak"], 2, 2)
    assert r2["drained"] == []


def test_transition_stringified_keys_replay_identically():
    """JSON round-trips dict keys to strings: int-keyed and str-keyed
    inputs must produce the identical transition (the replay contract)."""
    a = drain_transition({1: "degraded", 0: "ok"},
                         {0: "active", 1: "active"}, {}, {}, 2, 2)
    b = drain_transition({"1": "degraded", "0": "ok"},
                         {"0": "active", "1": "active"}, {}, {}, 2, 2)
    assert a == b


# ---------------------------------------------------------------------------
# range masking
# ---------------------------------------------------------------------------

def test_apply_quarantine_redistributes_and_preserves_total():
    out = apply_quarantine([512, 256, 256], 64, {1}, set())
    assert sum(out) == 1024 and out[1] == 0
    assert out == [640, 0, 384]  # step quanta round-robin onto actives


def test_apply_quarantine_probe_share_is_one_step():
    out = apply_quarantine([1024, 0], 64, set(), {1})
    assert out == [960, 64]
    # idempotent: re-masking an already-masked table is a no-op
    assert apply_quarantine(out, 64, set(), {1}) == out


def test_apply_quarantine_no_active_lane_is_a_noop():
    assert apply_quarantine([512, 512], 64, {0, 1}, set()) == [512, 512]


def test_apply_quarantine_drain_and_probe_together():
    out = apply_quarantine([384, 384, 256], 64, {2}, {1})
    assert sum(out) == 1024
    assert out[2] == 0 and out[1] == 64


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

def test_controller_quarantines_and_readmits_with_hysteresis():
    mon = HealthMonitor(window=2, min_history=2, confirm=2)
    dc = DrainController(mon, lanes=2, hold_barriers=2, confirm_clear=2)
    _feed(mon, 0, 0.01, windows=6)
    _degrade(mon, 1)
    res = dc.evaluate()
    assert res["drained"] == ["1"]
    assert dc.drained_lanes() == {1}
    # hold: two more evaluates before probation
    dc.evaluate()
    res = dc.evaluate()
    assert res["probed"] == ["1"] and dc.probe_lanes() == {1}
    # verdict clears (ratio back to baseline releases the monitor)
    _feed(mon, 1, 0.01, windows=2)
    assert mon.verdict(1) == "ok"
    dc.evaluate()
    res = dc.evaluate()
    assert res["readmitted"] == ["1"]
    assert dc.lane_state(1) == "active"
    rep = dc.report()
    assert rep["drains"] == 1 and rep["readmits"] == 1


def test_controller_decisions_replay_green_and_tamper_diverges():
    mon = HealthMonitor(window=2, min_history=2, confirm=2)
    dc = DrainController(mon, lanes=2, hold_barriers=1, confirm_clear=1)
    _feed(mon, 0, 0.01, windows=6)
    _degrade(mon, 1)
    dc.evaluate()          # drain-apply
    dc.evaluate()          # hold -> probation
    _feed(mon, 1, 0.01, windows=2)
    dc.evaluate()          # readmit
    recs = [r for r in DECISIONS.snapshot()
            if r.kind in ("drain-apply", "readmit")]
    assert {r.kind for r in recs} >= {"drain-apply", "readmit"}
    v = verify_records(recs)
    assert v["ok"], v["first_divergence"]
    # tamper: a drain the inputs cannot produce must diverge, naming it
    row = recs[-1].to_row()
    row["outputs"] = dict(row["outputs"], drained=["0"])
    out = replay_record(row)
    assert out["ok"] is False and "drained" in out["mismatch"]


def test_controller_healthy_with_drains_gate():
    mon = HealthMonitor(window=2, min_history=2, confirm=2)
    dc = DrainController(mon, lanes=2, hold_barriers=1, confirm_clear=1)
    _feed(mon, 0, 0.01, windows=6)
    _degrade(mon, 1)
    # degraded and NOT yet quarantined: the tier is unhealthy
    assert not dc.healthy_with_drains()
    dc.evaluate()
    # same verdict, but quarantined: reduced capacity, not an outage
    assert dc.healthy_with_drains()


def test_controller_disabled_is_inert():
    mon = HealthMonitor(window=2, min_history=2, confirm=2)
    dc = DrainController(mon, lanes=2, enabled=False)
    _degrade(mon, 1)
    assert dc.evaluate() is None
    assert dc.drained_lanes() == set()


# ---------------------------------------------------------------------------
# Cores integration (synthetic health evidence, real scheduler)
# ---------------------------------------------------------------------------

def test_cores_barrier_drains_and_workload_stays_exact(devs):
    """The integration loop: injected health evidence flips lane 1
    degraded, the next barrier quarantines it, the next compute's range
    table reads [N, 0] (share redistributed), and after the verdict
    clears the lane is re-admitted — with the workload bit-exact
    throughout (no lost or duplicated window updates)."""
    cr = NumberCruncher(devs.subset(2), INC)
    cores = cr.cores
    cores.health = HealthMonitor(window=2, min_history=2, confirm=2)
    cores.drain = DrainController(
        cores.health, lanes=2, hold_barriers=1, confirm_clear=1)
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    iters = 0

    def window():
        nonlocal iters
        x.compute(cr, 1, "inc", 1024, 64)
        iters += 1
        cr.barrier()

    for _ in range(4):
        window()
    assert cores.drain.lane_state(1) == "active"
    # synthetic degradation far above ANY real fence wall on this rig
    # (the real barrier samples interleave with these; 100x the
    # observed ~100ms walls keeps the verdict unambiguous)
    _feed(cores.health, 1, 30.0, windows=cores.health.confirm + 1)
    assert cores.health.verdict(1) == "degraded"
    trace = []
    saw_drained_ranges = saw_probe_ranges = False
    for _ in range(12):
        window()
        st = cores.drain.lane_state(1)
        if not trace or trace[-1] != st:
            trace.append(st)
        r = cores.ranges_of(1)
        saw_drained_ranges |= r == [1024, 0]
        # the probe window's COMPUTE runs before the barrier that
        # advances the state, so the [960, 64] table shows up one
        # window after the probation flip
        saw_probe_ranges |= r == [960, 64]
        if st == "active" and len(trace) > 1:
            break
    # advice became action: quarantine -> probation -> re-admission,
    # in order, no flapping (each state appears once in the trace)
    assert trace == ["quarantined", "probation", "active"], trace
    assert saw_drained_ranges  # the share was fully redistributed
    assert saw_probe_ranges    # probation ran exactly one probe step
    window()
    cr.enqueue_mode = False  # flush
    np.testing.assert_array_equal(np.asarray(x), float(iters))
    cr.dispose()


def test_serve_frontend_admits_while_lane_is_drained(devs):
    """ISSUE 13's serving satellite: a drained lane's requests
    re-dispatch onto survivors instead of failing — admission keeps
    admitting while every degraded lane is quarantined (the raw
    HealthMonitor gate would 503 the tier)."""
    from cekirdekler_tpu.serve import ServeFrontend, ServeJob

    cr = NumberCruncher(devs.subset(2), INC)
    cores = cr.cores
    cores.health = HealthMonitor(window=2, min_history=2, confirm=2)
    cores.drain = DrainController(
        cores.health, lanes=2, hold_barriers=4, confirm_clear=2)
    cr.enqueue_mode = True
    _feed(cores.health, 0, 0.01, windows=6)
    _degrade(cores.health, 1)
    cores.drain.evaluate()
    assert cores.drain.drained_lanes() == {1}
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    fe = ServeFrontend(cr, autostart=False)
    fut = fe.submit("tenant-a", ServeJob(
        kernels=("inc",), params=(x,), compute_id=7,
        global_range=1024, local_range=64))
    fe.step()
    rec = fut.result(timeout=30)
    assert rec["tenant"] == "tenant-a"
    fe.close()
    # the drained lane ran nothing: the whole batch landed on lane 0
    assert cores.ranges_of(7) == [1024, 0]
    np.testing.assert_array_equal(np.asarray(x), 1.0)
    cr.dispose()
