"""Cluster-scale serving fabric (serve/fabric.py): pure consistent-hash
placement properties (determinism, minimal reshuffle, member-only
targets, named diversion), the recording ShardRouter and its replayable
``route`` decisions (spill -> `ckreplay verify` exit 0), in-process
``ServeFabric`` preemption re-routes over the ``autostart=False`` seam,
warm-on-join, merged shard serving stats, typed ``ServeRejected``
propagation over the cluster TCP path, and the seeded 3-process
kill-and-reroute drill over ``tests/_fabric_worker.py``.

The workload kernel adds exactly 1.0f — small-integer f32 arithmetic is
exact, so every lost, double-applied, or mis-routed request shows as an
integer-sized error and the assertions demand bit equality (the
test_serve.py discipline, applied across shards and processes)."""

import importlib.util
import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.cluster import CruncherClient, CruncherServer
from cekirdekler_tpu.cluster import server as server_mod
from cekirdekler_tpu.cluster.elastic import Membership
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.errors import CekirdeklerError
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.metrics.registry import REGISTRY
from cekirdekler_tpu.obs import replay as replay_mod
from cekirdekler_tpu.obs.decisions import DecisionLog
from cekirdekler_tpu.serve import ServeJob, ServeRejected
from cekirdekler_tpu.serve import fabric as fabric_mod
from cekirdekler_tpu.serve.fabric import (
    REJECT_SHARD,
    VNODES,
    ServeFabric,
    ShardRouter,
    fabric_key,
    merge_shard_serving,
    ring_points,
    route_decision,
    shard_health,
)

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


def _load_tool(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


ckreplay = _load_tool("ck_replay_tool_fabric", "tools/ckreplay.py")


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def _keys(count):
    return [(f"t{i % 5}", f"cid{9100 + i % 7}|inc|4096x64+0#{i}")
            for i in range(count)]


# ---------------------------------------------------------------------------
# pure placement properties
# ---------------------------------------------------------------------------

def test_route_decision_deterministic_over_random_rosters():
    """The same (tenant, key, roster, health, epoch) always yields the
    bit-identical verdict — regardless of roster ordering or set/list
    input shape (the replay oracle's precondition)."""
    rng = random.Random(2017)
    for _ in range(25):
        roster = [f"p{rng.randrange(40)}" for _ in range(rng.randrange(1, 7))]
        bad = tuple(m for m in set(roster) if rng.random() < 0.3)
        for tenant, key in _keys(8):
            a = route_decision(tenant, key, roster, bad, epoch=3)
            b = route_decision(tenant, key, list(reversed(roster)),
                               tuple(reversed(bad)), epoch=3)
            assert a == b
            if a["shard"] is not None:
                assert a["shard"] in set(roster)
                assert a["shard"] not in set(bad)
                assert a["reason"] is None
            else:
                assert a["reason"] == REJECT_SHARD


def test_route_minimal_reshuffle_on_leave_and_join():
    """Consistent hashing's promise, checked: a departure moves ONLY
    the departed member's keys (every other key keeps its owner
    bit-identically), and a join moves keys ONLY onto the joiner."""
    rng = random.Random(7)
    for _ in range(10):
        roster = sorted({f"p{rng.randrange(30)}"
                         for _ in range(rng.randrange(3, 7))})
        keys = _keys(60)
        before = {k: route_decision(t, k, roster)["shard"]
                  for t, k in keys}
        gone = rng.choice(roster)
        survivors = [m for m in roster if m != gone]
        for t, k in keys:
            after = route_decision(t, k, survivors)["shard"]
            if before[k] != gone:
                assert after == before[k], "untouched key reshuffled"
        joiner = "zz-new"
        grown = roster + [joiner]
        for t, k in keys:
            after = route_decision(t, k, grown)["shard"]
            assert after in (before[k], joiner), \
                "join moved a key between incumbents"


def test_route_unhealthy_diversion_is_named_and_lands_on_successor():
    roster = ["p0", "p1", "p2", "p3"]
    for tenant, key in _keys(24):
        owner = route_decision(tenant, key, roster)["owner"]
        d = route_decision(tenant, key, roster, (owner,))
        assert d["diverted"] and d["hops"] >= 1
        assert d["shard"] != owner and d["shard"] in roster
        assert d["owner"] == owner  # the ring owner stays on record
        refused = route_decision(tenant, key, roster, tuple(roster))
        assert refused["shard"] is None
        assert refused["reason"] == REJECT_SHARD
    assert route_decision("t0", "k", [])["reason"] == REJECT_SHARD


def test_ring_points_and_fabric_key_are_portable():
    pts = ring_points(["b", "a"])
    assert pts == sorted(pts) and len(pts) == 2 * VNODES
    assert pts == ring_points(("a", "b"))  # input shape-independent
    a1 = ClArray(np.zeros(256, np.float32), name="one")
    a2 = ClArray(np.zeros(256, np.float32), name="two")
    j1 = ServeJob(params=[a1], kernels=["inc"], compute_id=9100,
                  global_range=256, local_range=64)
    j2 = ServeJob(params=[a2], kernels=["inc"], compute_id=9100,
                  global_range=256, local_range=64)
    # different array OBJECTS, same logical job: identical routing key
    # (coalescing still keys on the identity-bearing signature)
    assert fabric_key(j1) == fabric_key(j2) == "cid9100|inc|256x64+0"
    assert j1.signature() != j2.signature()


def test_shard_health_reasons_in_check_order():
    assert shard_health({})["healthy"]
    doc = {"resilience": {"dead": True, "breakers_open": 2,
                          "brownout": {"active": True}},
           "admission": {"healthy": False}}
    assert shard_health(doc)["reasons"] == [
        "dispatcher-dead", "circuit-open", "brownout", "drain-degraded"]
    assert not shard_health({"admission": {"healthy": False}})["healthy"]


def test_merge_shard_serving_sums_the_fleet():
    merged = merge_shard_serving({
        "p1": {"queue_depth": 3, "batches": 10, "requests_done": 40,
               "rounds": 10, "resilience": {"breakers_open": 1}},
        "p0": {"queue_depth": 1, "batches": 4, "requests_done": 16,
               "rounds": 4,
               "resilience": {"dead": True, "brownout": {"active": True}}},
    })
    assert merged["shards"] == ["p0", "p1"]
    assert merged["queue_depth"] == 4 and merged["requests_done"] == 56
    assert merged["breakers_open"] == 1
    assert merged["brownouts_active"] == 1 and merged["dead"] == ["p0"]


# ---------------------------------------------------------------------------
# recording router: the replayable `route` decision
# ---------------------------------------------------------------------------

def test_shard_router_records_replayable_routes(monkeypatch):
    log = DecisionLog(capacity=512)
    monkeypatch.setattr(fabric_mod, "DECISIONS", log)
    ms = Membership()
    ms.establish({"p0": 2, "p1": 2, "p2": 2})
    router = ShardRouter(ms)
    router.mark("p1", ("circuit-open",))
    outs = [router.route(t, k) for t, k in _keys(12)]
    rows = [r for r in log.snapshot() if r.kind == "route"]
    assert len(rows) == 12
    for r, out in zip(rows, outs):
        assert r.outputs == out
        assert r.inputs["members"] == ["p0", "p1", "p2"]
        assert r.inputs["unhealthy"] == ["p1"]
        assert r.inputs["epoch"] == 1
        v = replay_mod.replay_record(r)
        assert v["ok"], v
    verdict = replay_mod.verify_records(rows)
    assert verdict["ok"] and verdict["replayed"] == 12


def test_shard_router_health_refresh_replaces_wholesale():
    ms = Membership()
    ms.establish({"p0": 1, "p1": 1})
    router = ShardRouter(ms)
    router.mark("p0")
    assert "p0" in router.health_view()
    bad = router.refresh_health({
        "p0": {"resilience": {}},
        "p1": {"resilience": {"breakers_open": 1}},
    })
    assert bad == {"p1": ["circuit-open"]}
    assert router.health_view() == {"p1": ["circuit-open"]}
    router.clear("p1")
    assert router.health_view() == {}


# ---------------------------------------------------------------------------
# in-process ServeFabric: exactness, preemption re-route, warm-on-join
# ---------------------------------------------------------------------------

def _mk_fabric(devs, members=("m0", "m1", "m2"), n=2048, **kw):
    crunchers = {m: NumberCruncher(devs.subset(1), INC) for m in members}
    fab = ServeFabric(crunchers, autostart=False, gather_window_s=0.0,
                      max_batch=64, **kw)
    a = ClArray(np.zeros(n, np.float32), name="fab")
    a.partial_read = True
    job = ServeJob(params=[a], kernels=["inc"], compute_id=9100,
                   global_range=n, local_range=64)
    return fab, a, job


def _drain(fab, futs, steps=40):
    done = []
    for _ in range(steps):
        fab.step()
        done = [f for f in futs if f.done()]
        if len(done) == len(futs):
            break
    return done


def test_fabric_routes_submits_and_computes_bit_exactly(devs):
    fab, a, job = _mk_fabric(devs)
    try:
        owner = route_decision("t0", fabric_key(job),
                               fab.shards.keys())["shard"]
        futs = [fab.submit("t0", job) for _ in range(6)]
        assert len(_drain(fab, futs)) == 6
        for f in futs:
            assert f.exception() is None
        assert np.all(np.asarray(a) == 6.0)
        st = fab.stats()
        assert st["merged"]["requests_done"] == 6
        # single signature -> exactly one shard (the ring owner) did
        # all the work; the others stayed idle
        assert st["shards"][owner]["requests_done"] == 6
        assert sum(doc["requests_done"]
                   for doc in st["shards"].values()) == 6
    finally:
        fab.close()


def test_fabric_preemption_reroutes_bit_exact_and_replays(
        devs, tmp_path, monkeypatch):
    """The acceptance drill, in-process and fully deterministic over
    the ``autostart=False`` seam: queue work on the ring owner, kill
    that member with the work still queued, and the outer futures
    re-route the named clean failures onto survivors — every request
    applies exactly once (bit-exact array), zero hung futures, and the
    spilled route + member-leave + retry decision log replays green
    through ``ckreplay verify``."""
    log = DecisionLog(capacity=2048)
    monkeypatch.setattr(fabric_mod, "DECISIONS", log)
    import cekirdekler_tpu.cluster.elastic as elastic_mod
    monkeypatch.setattr(elastic_mod, "DECISIONS", log)
    fab, a, job = _mk_fabric(devs)
    before_reroutes = REGISTRY.counter(
        "ck_serve_fabric_reroutes_total", "").value
    try:
        victim = route_decision("t0", fabric_key(job),
                                fab.shards.keys())["shard"]
        futs = [fab.submit("t0", job) for _ in range(8)]
        # no dispatcher is running: all 8 are still queued on the
        # victim when the preemption lands
        fab.remove_member(victim, drain=False)
        assert victim not in fab.shards
        done = _drain(fab, futs)
        assert len(done) == len(futs), "hung futures after preemption"
        for f in futs:
            assert f.exception() is None, f.exception()
        assert np.all(np.asarray(a) == 8.0), "re-route broke exactness"
        delta = REGISTRY.counter(
            "ck_serve_fabric_reroutes_total", "").value - before_reroutes
        assert delta == 8
        assert fab.membership.snapshot()["epoch"] == 2
    finally:
        fab.close()
    p = str(tmp_path / "fabric_decisions.jsonl")
    log.save_jsonl(p)
    kinds = {r.kind for r in log.snapshot()}
    assert {"route", "member-leave", "retry"} <= kinds
    assert ckreplay.main(["verify", p]) == 0


def test_fabric_warm_on_join_precompiles_observed_signatures(devs):
    fab, a, job = _mk_fabric(devs, members=("m0", "m1"))
    try:
        futs = [fab.submit("t0", job) for _ in range(2)]
        _drain(fab, futs)
        before = REGISTRY.counter("ck_serve_warmup_total", "").value
        fab.add_member("m2", NumberCruncher(devs.subset(1), INC), step=1)
        assert REGISTRY.counter(
            "ck_serve_warmup_total", "").value == before + 1
        assert "m2" in fab.shards and fab.membership.snapshot()["epoch"] == 2
        # warmup used scratch params: the live array is untouched
        assert np.all(np.asarray(a) == 2.0)
        futs = [fab.submit("t1", job) for _ in range(3)]
        assert len(_drain(fab, futs)) == 3
        assert np.all(np.asarray(a) == 5.0)
    finally:
        fab.close()


def test_fabric_no_members_and_closed_refuse_with_named_errors(devs):
    fab, a, job = _mk_fabric(devs, members=("m0",))
    try:
        fab.remove_member("m0")
        with pytest.raises(ServeRejected) as ei:
            fab.submit("t0", job)
        assert ei.value.reason == REJECT_SHARD
        assert ei.value.retry_after_s > 0
    finally:
        fab.close()
    with pytest.raises(CekirdeklerError, match="is closed"):
        fab.submit("t0", job)


# ---------------------------------------------------------------------------
# TCP: named rejection reasons survive the wire as the typed error
# ---------------------------------------------------------------------------

def test_tcp_propagates_typed_serve_rejection(devs, monkeypatch):
    """A serving-tier rejection raised server-side crosses the cluster
    TCP path and re-raises client-side as the SAME typed
    ``ServeRejected`` — named reason, tenant, and retry-after hint
    intact (not a stringly ``remote error``)."""
    def _reject(*a, **kw):
        raise ServeRejected("tenant-9", REJECT_SHARD, 0.125)

    monkeypatch.setattr(server_mod, "NumberCruncher", _reject)
    server = CruncherServer(devices=devs.subset(1))
    try:
        client = CruncherClient(server.host, server.port)
        try:
            with pytest.raises(ServeRejected) as ei:
                client.setup(INC)
            assert ei.value.reason == REJECT_SHARD
            assert ei.value.tenant == "tenant-9"
            assert ei.value.retry_after_s == 0.125
        finally:
            client.close()
    finally:
        server.stop()


def test_tcp_plain_errors_stay_untyped(devs, monkeypatch):
    """Only structurally-marked rejections get the typed re-raise;
    any other server-side failure stays the generic named remote
    error."""
    def _boom(*a, **kw):
        raise RuntimeError("kaboom")

    monkeypatch.setattr(server_mod, "NumberCruncher", _boom)
    server = CruncherServer(devices=devs.subset(1))
    try:
        client = CruncherClient(server.host, server.port)
        try:
            with pytest.raises(CekirdeklerError, match="remote error"):
                client.setup(INC)
        finally:
            client.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# the seeded 3-process kill-and-reroute drill
# ---------------------------------------------------------------------------

def _spawn_worker(member, n=2048, local_range=64):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "_fabric_worker.py"),
         member, str(n), str(local_range)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=ROOT)
    return proc


def _await_ready(proc, member, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"worker {member} died before READY")
        if line.startswith("FABRIC_READY"):
            return
    raise RuntimeError(f"worker {member} never became READY")


def _rpc(proc, cmd):
    """One JSON round-trip; None on EOF (the killed-member signal)."""
    try:
        proc.stdin.write(json.dumps(cmd) + "\n")
        proc.stdin.flush()
    except (BrokenPipeError, OSError):
        return None
    line = proc.stdout.readline()
    if not line:
        return None
    return json.loads(line)


def test_three_process_seeded_kill_and_reroute(devs):
    """3 real worker processes, a seeded mid-run SIGKILL of one, and
    the parent re-routing exactly the unacked requests onto the ring
    survivors: every request completes exactly once (zero hung
    futures), the only failure mode is the named member death (the
    victim's EOF), surviving members' placements never move (minimal
    reshuffle, observed — not just computed), and every survivor's
    array equals its applied count bit-exactly."""
    members = ["m0", "m1", "m2"]
    n, sigs, rids_per_sig = 2048, 3, 4
    seed = 2017
    procs = {m: _spawn_worker(m, n=n) for m in members}
    membership = Membership()
    membership.establish({m: 1 for m in members})
    try:
        ready = [threading.Thread(target=_await_ready,
                                  args=(procs[m], m)) for m in members]
        for t in ready:
            t.start()
        for t in ready:
            t.join(timeout=200.0)
        for m in members:
            assert procs[m].poll() is None, f"worker {m} did not start"

        # the parent-side routing table: one placement per rid, from
        # the SAME pure function the fabric runs
        work = []  # (rid, tenant, si, shard)
        rid = 0
        for si in range(sigs):
            key = f"cid{9100 + si}|lg_inc|{n}x64+0"
            for j in range(rids_per_sig):
                tenant = f"t{j % 2}"
                shard = route_decision(
                    tenant, key, members,
                    epoch=membership.snapshot()["epoch"])["shard"]
                work.append((rid, tenant, si, shard))
                rid += 1
        by_shard = {m: [w for w in work if w[3] == m] for m in members}
        victims = [m for m in members if len(by_shard[m]) >= 2]
        victim = random.Random(seed).choice(sorted(victims))
        survivors = [m for m in members if m != victim]

        for m in members:
            assert _rpc(procs[m], {
                "op": "warm",
                "sigs": sorted({w[2] for w in by_shard[m]}) or [0],
            })["op"] == "warmed"

        acked: dict = {}
        unacked: list = []
        failures: list = []
        kill_at = 1  # SIGKILL after the victim's first ack (seeded run)

        def feed(m):
            for w in by_shard[m]:
                r, tenant, si, _ = w
                reply = _rpc(procs[m], {"op": "run", "rid": r,
                                        "tenant": tenant, "sig": si,
                                        "iters": 1})
                if reply is None:
                    if m == victim:
                        unacked.append(w)  # the named member death
                    else:
                        failures.append((m, r, "eof"))
                    continue
                if reply.get("op") != "done":
                    failures.append((m, r, reply))
                    continue
                acked[r] = m
                if m == victim and len([v for v in acked.values()
                                        if v == victim]) == kill_at:
                    procs[m].kill()

        feeders = [threading.Thread(target=feed, args=(m,))
                   for m in members]
        for t in feeders:
            t.start()
        for t in feeders:
            t.join(timeout=240.0)
        assert not any(t.is_alive() for t in feeders), "hung worker rpc"
        assert failures == [], failures
        assert unacked, "the seeded kill landed after the victim drained"

        # the preemption: epoch-bumping leave, then re-route ONLY the
        # unacked rids over the survivor roster
        membership.leave(victim)
        epoch = membership.snapshot()["epoch"]
        for r, tenant, si, _ in unacked:
            key = f"cid{9100 + si}|lg_inc|{n}x64+0"
            d = route_decision(tenant, key, survivors, epoch=epoch)
            assert d["shard"] in survivors
            reply = _rpc(procs[d["shard"]], {
                "op": "run", "rid": r, "tenant": tenant, "sig": si,
                "iters": 1})
            assert reply is not None and reply["op"] == "done", reply
            acked[r] = d["shard"]
        # minimal reshuffle, observed: survivors' own rids never moved
        for r, tenant, si, shard in work:
            if shard != victim:
                assert acked[r] == shard
        assert sorted(acked) == [w[0] for w in work], "lost/dup rids"

        # bit-exactness: each survivor's per-sig array equals exactly
        # the number of requests it applied
        for m in survivors:
            applied: dict = {}
            for r, tenant, si, _ in work:
                if acked[r] == m:
                    applied[si] = applied.get(si, 0) + 1
            for si, count in applied.items():
                v = _rpc(procs[m], {"op": "value", "sig": si})
                assert v["uniform"], f"torn array on {m} sig {si}"
                assert v["value"] == float(count), (m, si, v, count)
        for m in survivors:
            assert _rpc(procs[m], {"op": "exit"}) == {"op": "bye"}
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=30.0)


def _subsequence(seq, sub):
    """Is ``sub`` an ordered (not necessarily contiguous) subsequence
    of ``seq``?"""
    it = iter(seq)
    return all(any(x == want for x in it) for want in sub)


def test_member_kill_rid_chain_survives_in_merged_cluster_trace(devs):
    """The chaos-observability contract (reqtrace across the fabric
    wire): 3 worker processes, a seeded mid-run SIGKILL, and the
    parent re-routing the unacked requests onto ring survivors under
    their ORIGINAL rids.  The merged cluster Perfetto trace must show
    each killed-shard request as ONE rid on ONE request track whose
    chain reads diverted → rerouted → … → resolved (subsequence), the
    fold must report those rids resolved with near-full phase
    coverage, and the survivors' arrays must stay bit-exact."""
    from cekirdekler_tpu.obs.reqtrace import REQTRACE, fold_phases
    from cekirdekler_tpu.trace.aggregate import (
        ClusterSnapshot,
        merged_chrome_trace,
    )

    members = ["m0", "m1", "m2"]
    n, sigs, rids_per_sig = 2048, 3, 4
    seed = 4099
    procs = {m: _spawn_worker(m, n=n) for m in members}
    membership = Membership()
    membership.establish({m: 1 for m in members})
    t_wall0 = time.time()
    try:
        ready = [threading.Thread(target=_await_ready,
                                  args=(procs[m], m)) for m in members]
        for t in ready:
            t.start()
        for t in ready:
            t.join(timeout=200.0)
        for m in members:
            assert procs[m].poll() is None, f"worker {m} did not start"

        # one placement per request, each carrying a parent-minted
        # trace rid that must survive the hop
        work = []  # (idx, trace_rid, tenant, si, shard)
        idx = 0
        for si in range(sigs):
            key = f"cid{9100 + si}|lg_inc|{n}x64+0"
            for j in range(rids_per_sig):
                tenant = f"t{j % 2}"
                shard = route_decision(
                    tenant, key, members,
                    epoch=membership.snapshot()["epoch"])["shard"]
                work.append((idx, f"rkill-{idx:x}", tenant, si, shard))
                idx += 1
        by_shard = {m: [w for w in work if w[4] == m] for m in members}
        victims = [m for m in members if len(by_shard[m]) >= 2]
        victim = random.Random(seed).choice(sorted(victims))
        survivors = [m for m in members if m != victim]

        for m in members:
            assert _rpc(procs[m], {
                "op": "warm",
                "sigs": sorted({w[3] for w in by_shard[m]}) or [0],
            })["op"] == "warmed"

        acked: dict = {}
        unacked: list = []
        failures: list = []
        kill_at = 1  # SIGKILL after the victim's first ack (seeded)

        def feed(m):
            for w in by_shard[m]:
                i, trid, tenant, si, _ = w
                reply = _rpc(procs[m], {
                    "op": "run", "rid": i, "trace_rid": trid,
                    "tenant": tenant, "sig": si, "iters": 1})
                if reply is None:
                    if m == victim:
                        unacked.append(w)
                    else:
                        failures.append((m, i, "eof"))
                    continue
                if reply.get("op") != "done":
                    failures.append((m, i, reply))
                    continue
                acked[i] = m
                if m == victim and len([v for v in acked.values()
                                        if v == victim]) == kill_at:
                    procs[m].kill()

        feeders = [threading.Thread(target=feed, args=(m,))
                   for m in members]
        for t in feeders:
            t.start()
        for t in feeders:
            t.join(timeout=240.0)
        assert not any(t.is_alive() for t in feeders), "hung worker rpc"
        assert failures == [], failures
        assert unacked, "the seeded kill landed after the victim drained"

        # re-route under the SAME rid, the parent (the fabric
        # coordinator's role) stamping the hop events the in-process
        # fabric would stamp in ServeFabric._reroute
        membership.leave(victim)
        epoch = membership.snapshot()["epoch"]
        for i, trid, tenant, si, _ in unacked:
            key = f"cid{9100 + si}|lg_inc|{n}x64+0"
            d = route_decision(tenant, key, survivors, epoch=epoch)
            assert d["shard"] in survivors
            if REQTRACE.enabled:
                REQTRACE.event(trid, "diverted", tenant=tenant,
                               owner=victim, shard=d["shard"], hops=1)
                REQTRACE.event(trid, "rerouted", tenant=tenant,
                               from_shard=victim, to_shard=d["shard"],
                               attempt=1)
            reply = _rpc(procs[d["shard"]], {
                "op": "run", "rid": i, "trace_rid": trid,
                "tenant": tenant, "sig": si, "iters": 1})
            assert reply is not None and reply["op"] == "done", reply
            acked[i] = d["shard"]
        assert sorted(acked) == [w[0] for w in work], "lost/dup rids"

        # bit-exactness on every survivor
        for m in survivors:
            applied: dict = {}
            for i, trid, tenant, si, _ in work:
                if acked[i] == m:
                    applied[si] = applied.get(si, 0) + 1
            for si, count in applied.items():
                v = _rpc(procs[m], {"op": "value", "sig": si})
                assert v["uniform"], f"torn array on {m} sig {si}"
                assert v["value"] == float(count), (m, si, v, count)

        # gather every surviving process's reqtrace ring (the victim's
        # died with it — the chain must still read whole) and merge
        parent_rows = [
            [e.t, e.rid, e.kind, e.fields]
            for e in REQTRACE.snapshot()
            if e.t >= t_wall0 and e.rid.startswith("rkill-")
        ]
        per_proc = [parent_rows]
        for m in survivors:
            r = _rpc(procs[m], {"op": "reqtrace"})
            assert r is not None and r["op"] == "reqtrace"
            per_proc.append(r["events"])
        for m in survivors:
            assert _rpc(procs[m], {"op": "exit"}) == {"op": "bye"}

        snap = ClusterSnapshot(
            offsets=[0.0] * len(per_proc),
            spans=[[] for _ in per_proc],
            metrics=[{} for _ in per_proc],
            health=[{} for _ in per_proc],
            serving=[{} for _ in per_proc],
            reqtrace=per_proc,
            nproc=len(per_proc),
        )
        trace = merged_chrome_trace(snap)
        req_slices = [e for e in trace["traceEvents"]
                      if e.get("cat") == "ck-req" and e.get("ph") == "X"]
        assert req_slices, "no request tracks in the merged trace"

        all_rows = [r for rows in per_proc for r in rows]
        records = {r["rid"]: r for r in fold_phases(all_rows)}
        for i, trid, tenant, si, _ in unacked:
            rec = records.get(trid)
            assert rec is not None, f"rid {trid} missing from the fold"
            assert rec["outcome"] == "resolved", (trid, rec["kinds"])
            assert _subsequence(
                rec["kinds"], ["diverted", "rerouted", "resolved"]), \
                (trid, rec["kinds"])
            # ONE rid → ONE merged request track: every slice of this
            # rid (parent hop stamps + survivor lifecycle) shares a tid
            tids = {e["tid"] for e in req_slices
                    if (e.get("args") or {}).get("rid") == trid}
            assert len(tids) == 1, (trid, tids)
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=30.0)
