"""Metrics registry (cekirdekler_tpu/metrics/): the always-on health
subsystem's contracts — overhead budget, histogram bucket semantics,
snapshot determinism under threads, the three exports, and the cluster
clock-alignment math (trace/aggregate.py) with injected skew.

The real-collective end of the aggregation (spans + metrics shipped
over live DCN all-gathers, offsets estimated through actual exchanges)
is exercised by tests/test_dcn.py's jobs via tests/_dcn_worker.py; here
the estimator and merge are driven with a simulated cluster so the
math is pinned deterministically.
"""

import json
import threading
import time

import numpy as np
import pytest

from cekirdekler_tpu.metrics import (
    REGISTRY,
    MetricsRegistry,
    chrome_counter_events,
    prometheus_text,
)
from cekirdekler_tpu.trace import aggregate
from cekirdekler_tpu.trace.export import from_chrome_trace, to_chrome_trace
from cekirdekler_tpu.trace.spans import Span


# ---------------------------------------------------------------------------
# overhead budget
# ---------------------------------------------------------------------------

class _NoopShape:
    """Same call shape as Counter.inc with the body removed: the
    interpreter's unavoidable bound-method floor (~120-250 ns on slow
    containers), which no registry design can remove."""

    def inc(self, amount=1):
        pass


def _best_per_call(fn, n=200_000, trials=3) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        best = min(best, (time.perf_counter() - t0) / n)
    return best


def test_disabled_counter_overhead_under_budget():
    """The ISSUE's budget: a disabled counter inc costs < 100 ns.  On a
    reference CPU the absolute cost meets that; this container's bare
    method-call floor alone exceeds 100 ns, so the pin is the MARGINAL
    cost over an identical no-op method (the part the registry
    controls), plus the tracer-discipline absolute bound of 1 µs."""
    reg = MetricsRegistry()
    reg.enabled = False
    c = reg.counter("ck_budget_probe_total")
    floor = _best_per_call(_NoopShape().inc)
    per = _best_per_call(c.inc)
    net = per - floor
    assert net < 100e-9, (
        f"disabled inc adds {net*1e9:.0f} ns over the call floor "
        f"({per*1e9:.0f} ns total, floor {floor*1e9:.0f} ns)"
    )
    assert per < 1e-6, f"disabled inc absolute cost {per*1e9:.0f} ns >= 1 µs"
    assert c.value == 0  # truly a no-op: nothing stored


def test_disabled_registry_drops_all_update_kinds():
    reg = MetricsRegistry()
    reg.enabled = False
    c, g = reg.counter("c_total"), reg.gauge("g")
    h = reg.histogram("h_seconds", buckets=(1.0,))
    c.inc(5)
    g.set(3.0)
    g.inc()
    h.observe(0.5)
    assert c.value == 0 and g.value == 0.0
    assert h.value["count"] == 0 and h.value["sum"] == 0.0


# ---------------------------------------------------------------------------
# histogram bucket semantics (property test)
# ---------------------------------------------------------------------------

def test_histogram_bucket_boundary_property():
    """Prometheus ``le`` semantics: an observation lands in the FIRST
    bucket whose upper bound is >= the value — checked against a brute
    reference over random values AND every exact boundary."""
    rng = np.random.default_rng(42)
    buckets = (0.001, 0.01, 0.1, 1.0, 10.0)
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", buckets=buckets)
    values = list(rng.uniform(0.0, 20.0, 500)) + list(buckets) + [0.0, 1e-9]
    for v in values:
        h.observe(v)
    expect = [0] * (len(buckets) + 1)
    for v in values:
        for i, ub in enumerate(buckets):
            if v <= ub:
                expect[i] += 1
                break
        else:
            expect[-1] += 1
    got = h.value
    assert got["counts"] == expect
    assert got["count"] == len(values)
    assert got["sum"] == pytest.approx(sum(values))
    # an observation exactly on a boundary belongs to that bucket
    reg2 = MetricsRegistry()
    h2 = reg2.histogram("h2", buckets=(1.0, 2.0))
    h2.observe(1.0)
    h2.observe(2.0)
    assert h2.value["counts"] == [1, 1, 0]


def test_histogram_rejects_unsorted_and_conflicting_buckets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad", buckets=(1.0, 0.5))
    reg.histogram("h", buckets=(1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(1.0, 3.0))


# ---------------------------------------------------------------------------
# registry identity + snapshot determinism
# ---------------------------------------------------------------------------

def test_get_or_create_returns_same_object_and_type_conflicts_raise():
    reg = MetricsRegistry()
    a = reg.counter("x_total", lane=0)
    b = reg.counter("x_total", lane=0)
    c = reg.counter("x_total", lane=1)
    assert a is b and a is not c
    with pytest.raises(TypeError):
        reg.gauge("x_total", lane=0)


def test_reset_zeroes_in_place_keeping_cached_handles_live():
    """reset() must not orphan cached handles (Worker/Cores hold them
    for the hot paths): the same objects keep feeding snapshots after a
    reset, at zero."""
    reg = MetricsRegistry()
    c = reg.counter("keep_total")
    h = reg.histogram("keep_seconds", buckets=(1.0,))
    c.inc(5)
    h.observe(0.5)
    reg.reset()
    assert reg.counter("keep_total") is c  # identity survives
    assert reg.snapshot()["counters"]["keep_total"] == 0
    c.inc(2)
    h.observe(2.0)
    snap = reg.snapshot()
    assert snap["counters"]["keep_total"] == 2
    assert snap["histograms"]["keep_seconds"]["counts"] == [0, 1]


def test_collective_consistency_refuses_vacuous_pass():
    """Zero probe-kind spans on some process must raise, not return a
    'perfectly aligned' +inf with no supporting evidence."""
    snap = aggregate.ClusterSnapshot(
        offsets=[0.0, 0.0], spans=[[Span("dcn-exchange", 1.0, 1.1)], []],
        metrics=[{}, {}], nproc=2,
    )
    with pytest.raises(ValueError, match="no 'dcn-exchange' spans"):
        aggregate.collective_consistency(snap)
    # unequal counts (ring wrap on one process) would index-pair
    # DIFFERENT collectives and report a false negative margin — raise
    uneq = aggregate.ClusterSnapshot(
        offsets=[0.0, 0.0],
        spans=[[Span("dcn-exchange", 1.0, 1.1),
                Span("dcn-exchange", 2.0, 2.1)],
               [Span("dcn-exchange", 2.0, 2.1)]],
        metrics=[{}, {}], nproc=2,
    )
    with pytest.raises(ValueError, match="unequal 'dcn-exchange'"):
        aggregate.collective_consistency(uneq)


def test_counter_tracks_relative_origin_without_spans():
    """Counters alone must still land on a window-relative origin, not
    at absolute perf_counter microseconds (hours past t=0)."""
    series = {"c": [(1000.5, 1.0), (1000.6, 2.0)]}
    doc = to_chrome_trace([], counters=series)
    cevents = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert cevents[0]["ts"] == 0.0
    assert cevents[1]["ts"] == pytest.approx(0.1e6)


def test_snapshot_determinism_under_threads():
    """N threads × K updates across all three metric kinds must land
    EXACTLY (the registry locks updates — unlike the tracer's
    overwrite-tolerant ring, metric values are exact), and two
    snapshots of the same state must serialize byte-identically."""
    reg = MetricsRegistry()
    c = reg.counter("thr_total")
    g = reg.gauge("thr_depth")
    h = reg.histogram("thr_seconds", buckets=(0.5,))
    T, K = 8, 5000

    def body(tid):
        for i in range(K):
            c.inc()
            c.inc(2)
            g.inc()
            h.observe(0.25 if i % 2 else 0.75)

    threads = [threading.Thread(target=body, args=(t,)) for t in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["thr_total"] == T * K * 3
    assert snap["gauges"]["thr_depth"] == T * K
    hv = snap["histograms"]["thr_seconds"]
    assert hv["count"] == T * K
    assert hv["counts"] == [T * K // 2, T * K // 2]
    assert json.dumps(snap, sort_keys=True) == json.dumps(
        reg.snapshot(), sort_keys=True
    )


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------

def test_snapshot_safe_during_concurrent_metric_creation():
    """A scrape thread iterating the registry while workers register
    first-ever series must never hit 'dictionary changed size during
    iteration' (the always-on use: prometheus_text on a live system)."""
    reg = MetricsRegistry()
    reg.enable_sampling()
    stop = threading.Event()
    errors: list[Exception] = []

    def creator():
        i = 0
        while not stop.is_set():
            reg.counter("churn_total", lane=i).inc()
            i += 1

    def scraper():
        try:
            while not stop.is_set():
                reg.snapshot()
                prometheus_text(reg)
                reg.counter_series()
        except Exception as e:  # noqa: BLE001 - the failure under test
            errors.append(e)

    threads = [threading.Thread(target=creator) for _ in range(2)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("ck_t_total", "a counter", lane=0).inc(3)
    reg.gauge("ck_d").set(2.5)
    h = reg.histogram("ck_l_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = prometheus_text(reg)
    assert "# TYPE ck_t_total counter" in text
    assert '# HELP ck_t_total a counter' in text
    assert 'ck_t_total{lane="0"} 3' in text
    assert "# TYPE ck_d gauge" in text and "ck_d 2.5" in text
    # cumulative buckets + +Inf + sum/count
    assert 'ck_l_seconds_bucket{le="0.1"} 1' in text
    assert 'ck_l_seconds_bucket{le="1"} 2' in text
    assert 'ck_l_seconds_bucket{le="+Inf"} 3' in text
    assert "ck_l_seconds_count 3" in text
    assert prometheus_text(reg) == text  # deterministic
    # the artifact replay path must render label-for-label identically
    # to the live scrape (modulo HELP lines, which only the live
    # registry knows)
    from cekirdekler_tpu.metrics import prometheus_from_snapshot

    replay = prometheus_from_snapshot(json.loads(json.dumps(reg.snapshot())))
    live_no_help = "\n".join(
        ln for ln in text.splitlines() if not ln.startswith("# HELP"))
    assert replay.strip() == live_no_help.strip()


def test_prometheus_text_survives_nonfinite_gauge():
    """One inf/nan gauge must not 500 the whole /metrics page: the
    exposition format spells them +Inf/-Inf/NaN (the int(inf) crash the
    ISSUE-8 verify drive surfaced)."""
    reg = MetricsRegistry()
    reg.gauge("ck_d", "drive").set(float("inf"))
    reg.gauge("ck_e", "drive").set(float("-inf"))
    reg.gauge("ck_f", "drive").set(float("nan"))
    reg.counter("ck_ok_total", "sane neighbor").inc()
    text = prometheus_text(reg)
    assert "ck_d +Inf" in text
    assert "ck_e -Inf" in text
    assert "ck_f NaN" in text
    assert "ck_ok_total 1" in text  # the rest of the page still renders


def test_counter_tracks_merge_into_chrome_trace():
    """Sampled series ride the span export as Perfetto counter events
    (ph C) on the same relative timeline; the span round-trip reader
    ignores them."""
    reg = MetricsRegistry()
    reg.enable_sampling()
    c = reg.counter("ck_bytes_total")
    c.inc(10)
    time.sleep(0.001)
    c.inc(5)
    series = reg.counter_series()
    assert list(series) == ["ck_bytes_total"]
    assert [v for _, v in series["ck_bytes_total"]] == [10, 15]
    spans = [Span("launch", series["ck_bytes_total"][0][0] - 0.001,
                  series["ck_bytes_total"][1][0] + 0.001, cid=1, lane=0)]
    doc = to_chrome_trace(spans, counters=series)
    cevents = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert len(cevents) == 2
    assert cevents[0]["ts"] <= cevents[1]["ts"]
    assert all(e["ts"] >= 0 for e in cevents)
    assert cevents[1]["args"]["value"] == 15
    # the span reader round-trips spans and skips counter events
    assert len(from_chrome_trace(doc)) == len(spans)


def test_counter_series_monotonic_under_threads():
    """Samples are recorded inside the update lock: a preempted thread
    must not append a stale smaller value after a newer larger one, or
    the Perfetto counter track would show a monotonic counter
    decreasing."""
    reg = MetricsRegistry(sample_capacity=100_000)
    reg.enable_sampling()
    c = reg.counter("mono_total")
    threads = [
        threading.Thread(target=lambda: [c.inc() for _ in range(3000)])
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    vals = [v for _, v in c.samples()]
    assert vals == sorted(vals)
    assert vals[-1] == 12000


def test_sampling_off_by_default_and_bounded():
    reg = MetricsRegistry(sample_capacity=4)
    c = reg.counter("ck_s_total")
    c.inc()
    assert reg.counter_series() == {}
    reg.enable_sampling()
    for _ in range(10):
        c.inc()
    assert len(reg.counter_series()["ck_s_total"]) == 4  # ring-bounded
    reg.disable_sampling(clear=True)
    c.inc()
    assert reg.counter_series() == {}


# ---------------------------------------------------------------------------
# runtime integration: the instrument sites actually feed the registry
# ---------------------------------------------------------------------------

def test_runtime_populates_registry_series():
    from cekirdekler_tpu import ClArray, all_devices
    from cekirdekler_tpu.core.cruncher import NumberCruncher

    src = """
    __kernel void inc1(__global float* x) {
        int i = get_global_id(0);
        x[i] = x[i] + 1.0f;
    }
    """
    devs = all_devices().cpus()
    if len(devs) < 2:
        pytest.skip("needs the multi-device rig")
    cr = NumberCruncher(devs.subset(2), src)
    try:
        n = 512
        x = ClArray(np.zeros(n, np.float32), partial_read=True)
        cr.enqueue_mode = True
        for _ in range(4):
            x.compute(cr, 91, "inc1", n, 64)
        cr.barrier()
        cr.enqueue_mode = False
        np.testing.assert_array_equal(np.asarray(x), np.full(n, 4.0))
    finally:
        cr.dispose()
    snap = REGISTRY.snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    assert any(k.startswith("ck_upload_bytes_total") for k in counters)
    assert any(k.startswith("ck_download_bytes_total") for k in counters)
    assert any(k.startswith("ck_fence_waits_total") for k in counters)
    assert counters.get("ck_barriers_total", 0) >= 1
    # fused path engaged for the repeated identical enqueue compute
    assert counters.get("ck_fused_iters_total", 0) >= 1
    assert any(k.startswith("ck_balance_share{cid=\"91\"") for k in gauges)
    assert any(k.startswith("ck_barrier_seconds")
               for k in snap["histograms"])


# ---------------------------------------------------------------------------
# cluster clock alignment (trace/aggregate.py) with injected skew
# ---------------------------------------------------------------------------

class _FakeCluster:
    """Simulated N-process job for the offset estimator: OUR process is
    pid 0 with clock skew ``skews[0]``; the fake all-gather answers the
    midpoint exchange with the other processes' (true collective
    instant + their skew) readings, plus bounded noise — exactly what a
    real RTT-symmetric probe would ship."""

    def __init__(self, skews, noise=0.0005, seed=7):
        self.skews = list(skews)
        self.rng = np.random.default_rng(seed)
        self.noise = noise

    def _allgather(self, value):
        n = len(self.skews)
        if float(np.asarray(value).reshape(-1)[0]) == 0.0:
            # the probe collective itself: the shared global instant
            return np.zeros((n,) + np.asarray(value).shape, value.dtype)
        g = time.perf_counter()  # ~the collective instant, true clock
        rows = [float(np.asarray(value).reshape(-1)[0])]
        for p in range(1, n):
            rows.append(g + self.skews[p]
                        + float(self.rng.uniform(-self.noise, self.noise)))
        return np.asarray(rows, np.float64).reshape(n, 1)


def test_clock_offset_estimation_recovers_injected_skew():
    skews = [3.0, -11.5, 40.25]
    acc = _FakeCluster(skews)
    offsets = aggregate.estimate_clock_offsets(
        acc, rounds=7, skew_s=skews[0])
    assert offsets[0] == 0.0
    for p in (1, 2):
        assert offsets[p] == pytest.approx(skews[p] - skews[0], abs=0.01), (
            p, offsets)


def _skewed_cluster_snapshot(skews, offsets):
    """Synthetic 3-process job: K collectives at known TRUE times, each
    process recording them on its own skewed clock; spans aligned with
    the given offsets (exact = the merge contract, zero = broken)."""
    true_windows = [(1.0 + 0.1 * k, 1.02 + 0.1 * k) for k in range(5)]
    per_proc = []
    for p, sk in enumerate(skews):
        rows = [
            {"kind": "dcn-exchange", "t0": t0 + sk, "t1": t1 + sk,
             "cid": None, "lane": None, "tag": f"x{k}"}
            for k, (t0, t1) in enumerate(true_windows)
        ]
        per_proc.append(aggregate._rows_to_spans(rows, offsets[p]))
    return aggregate.ClusterSnapshot(
        offsets=list(offsets), spans=per_proc,
        metrics=[{"counters": {}} for _ in skews], nproc=len(skews),
    )


def test_merged_trace_consistent_with_alignment_inconsistent_without():
    skews = [0.0, 7.5, 15.0]  # the worker test's deliberate skew shape
    snap = _skewed_cluster_snapshot(skews, offsets=skews)
    margin = aggregate.collective_consistency(snap)
    assert margin == pytest.approx(0.02, abs=1e-9)  # exact overlap back
    # without alignment the merged timeline is wildly inconsistent —
    # the 7.5 s skew dwarfs the 20 ms collectives
    broken = _skewed_cluster_snapshot(skews, offsets=[0.0, 0.0, 0.0])
    assert aggregate.collective_consistency(broken) < -7.0


def test_merged_chrome_trace_one_block_per_process():
    skews = [0.0, 7.5, 15.0]
    snap = _skewed_cluster_snapshot(skews, offsets=skews)
    doc = aggregate.merged_chrome_trace(snap)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {1, 2, 3}
    assert min(e["ts"] for e in xs) == 0.0  # shared origin
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"dcn process 0", "dcn process 1", "dcn process 2"}
    # aligned: every process's k-th collective lands at the same ts
    # (up to float cancellation in the skew-subtract — sub-ns here)
    by_pid = {}
    for e in xs:
        by_pid.setdefault(e["pid"], []).append(e["ts"])
    assert by_pid[1] == pytest.approx(by_pid[2], abs=1e-3)
    assert by_pid[1] == pytest.approx(by_pid[3], abs=1e-3)


def test_chrome_counter_events_drop_pre_window_samples():
    ev = chrome_counter_events({"c": [(0.5, 1.0), (2.0, 3.0)]}, t_base=1.0)
    assert len(ev) == 1 and ev[0]["args"]["value"] == 3.0
