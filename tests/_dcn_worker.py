"""Worker process for tests/test_dcn.py: one process of an N-process JAX
distributed job.  Run as
``python tests/_dcn_worker.py <pid> <nproc> <port> [counts]`` with a clean
CPU env; ``counts`` is the comma-separated per-process virtual device
count table (default ``4,4`` — the parent must set
``--xla_force_host_platform_device_count`` to counts[pid]).

Verifies, from inside the job:
- correct results after 6 balanced multi-process compute() calls,
- the share table sums to the global range and agrees across processes,
- the LCM-step table matches the (possibly ASYMMETRIC) per-process
  device counts — per-process step = devices_i x local_range, shares
  snapped to each process's own step (VERDICT r5 #6: `_allgather`'s
  design argument rests on supporting unequal device counts; the
  asymmetric job is what actually exercises it),
- the LCM-step balancer moved work away from the (deterministically)
  slow process,
- cluster aggregation (trace/aggregate.py): each process records spans
  under the tracer and per-process DCN metrics; `gather_cluster` with a
  DELIBERATE per-process clock skew (pid x 7.5 s — simulating the
  distinct monotonic epochs real multi-host jobs have, which a
  one-machine rig cannot produce naturally) must estimate and cancel
  the skew: the merged trace's cross-process `dcn-exchange` spans stay
  collective-consistent (every process's k-th collective overlaps every
  other's after alignment), the merged Perfetto dict carries one
  process block per DCN process, and process 0 receives every
  process's metric snapshot (nonzero exchange-byte counters).
"""

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SRC = """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = y[i] + a * x[i];
}
"""

LOCAL_RANGE = 64


def main(pid: int, nproc: int, port: int, counts: list[int]) -> None:
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.cluster.dcn import DistributedAccelerator, initialize

    initialize(f"localhost:{port}", nproc, pid)
    import jax

    assert jax.process_count() == nproc
    assert jax.local_device_count() == counts[pid], (
        jax.local_device_count(), counts,
    )

    # deterministic timing injection: process 1 reports 3x the per-item
    # cost, so the balancer must shift work away from it — wall time on a
    # shared-core rig is contention noise (see DistributedAccelerator doc)
    hook = lambda cid, share, wall: float(share) * (3.0 if pid == 1 else 1.0)
    acc = DistributedAccelerator(timing_hook=hook)
    try:
        from cekirdekler_tpu.trace.spans import TRACER

        TRACER.enable(clear=True)  # record dcn-exchange spans to aggregate
        acc.setup_nodes(SRC)
        # the agreed device-count table IS the asymmetry evidence
        assert acc.proc_device_counts == counts, acc.proc_device_counts
        n = 4096
        calls = 6
        x = ClArray(np.arange(n, dtype=np.float32), partial_read=True,
                    read_only=True)
        y = ClArray(np.ones(n, np.float32), partial_read=True)
        for _ in range(calls):
            acc.compute(["saxpy"], [x, y], compute_id=1, global_range=n,
                        local_range=LOCAL_RANGE, values=(2.0,))
            shares = acc.ranges_of(1)
            assert sum(shares) == n, shares
        np.testing.assert_array_equal(
            np.asarray(y), 1.0 + calls * 2.0 * np.arange(n, dtype=np.float32)
        )
        # LCM-step table: per-process step = its device count x
        # local_range; the balancer must carry exactly that table and
        # snap every non-mainframe share to its process's own step
        # (process 0 absorbs the remainder — the "mainframe" rule)
        steps = [c * LOCAL_RANGE for c in counts]
        bal = acc.balancers[1]
        assert bal.steps == steps, (bal.steps, steps)
        assert bal.lcm == math.lcm(*steps), (bal.lcm, steps)
        final = acc.ranges_of(1)
        for j in range(1, nproc):
            assert final[j] % steps[j] == 0, (final, steps)
        # share tables must agree across processes (SPMD balancer)
        agreed = acc._allgather(np.asarray(final, np.int64))
        assert (agreed == np.asarray(final)[None, :]).all(), agreed
        assert final[0] > final[1], f"balancer did not move: {final}"
        timings = acc.compute_timing(1)
        assert len(timings) == nproc, timings
        if nproc == 2:
            assert timings[1] > timings[0], timings
        # 64-bit payloads must survive the exchange even with x64 disabled
        # (the parent test clears JAX_ENABLE_X64): the gather moves raw
        # bytes, so device_put's int64->int32 canonicalization never sees
        # the data
        big = acc._allgather(np.asarray([2**40 + pid], np.int64))
        assert big.dtype == np.int64 and big[1, 0] == 2**40 + 1, big
        # write_all single-owner rule: everyone ends with process 0's copy
        # (owner-masked psum path), 64-bit payload again deliberate
        mine = np.arange(5, dtype=np.float64) + (100.0 if pid == 0 else -7.0)
        got = acc._broadcast0(mine)
        assert got.dtype == np.float64 and got[0] == 100.0, got

        # ---- lane health: a deterministic 5x fence degradation on
        # process 1's lane 0 (and ONLY there) must flip that lane to
        # `degraded` locally, ship through gather_cluster's health
        # payload, and appear in the DCN-merged cluster health table —
        # the observation half of ROADMAP item 4's eviction loop.
        # Injected samples (the skew_s convention: loopback rigs cannot
        # produce real per-lane degradation deterministically); the few
        # real transfer observations the 6 computes made cannot close a
        # window (6 < window size), so the fence signal decides alone.
        hm = acc.cruncher.cores.health
        n_lanes = len(acc.cruncher.cores.workers)
        for wnd in range(hm.min_history + hm.confirm + 1):
            for _ in range(hm.window):
                for lane in range(n_lanes):
                    v = 0.010 * (1.0 + 0.1 * lane)  # unequal lanes are OK
                    if pid == 1 and lane == 0 and wnd >= hm.min_history:
                        v *= 5.0
                    hm.observe(lane, "fence", v)
        local = acc.health_report()
        if pid == 1:
            assert local[0]["verdict"] == "degraded", local
            assert hm.suggest_drain() == [0], local
            assert all(local[ln]["verdict"] == "ok"
                       for ln in local if ln != 0), local
            # the advisory left decision PROVENANCE: suggest_drain's
            # non-empty answer is a recorded drain-advisory decision
            # carrying every lane's verdict + ratios — ROADMAP item 4's
            # eviction work starts with "why was this lane named"
            # answerable from the log alone
            from cekirdekler_tpu.obs.decisions import DECISIONS

            advisories = [r for r in DECISIONS.snapshot()
                          if r.kind == "drain-advisory"]
            assert advisories, "degraded drain produced no decision record"
            last = advisories[-1]
            assert last.outputs["drain"] == [0], last.outputs
            assert last.inputs["lanes"]["0"]["verdict"] == "degraded", \
                last.inputs
        else:
            assert all(r["verdict"] == "ok" for r in local.values()), local

        # ---- cluster aggregation: one merged timeline for the job ----
        from cekirdekler_tpu.metrics.registry import REGISTRY
        from cekirdekler_tpu.trace import aggregate

        spans = TRACER.snapshot()
        TRACER.disable()
        assert any(s.kind == "dcn-exchange" for s in spans), (
            [s.kind for s in spans][:10])
        # deliberate per-process clock skew (seconds — orders of
        # magnitude above the collectives' ms-scale durations): the
        # offset estimator must recover and cancel it, or the
        # consistency margin below goes hugely negative
        skew = pid * 7.5
        snap = aggregate.gather_cluster(acc, spans=spans, skew_s=skew)
        assert snap["nproc"] == nproc
        assert abs(snap["offsets"][0]) < 1e-9, snap["offsets"]
        # every process shipped nonzero DCN metrics to the collector
        for p in range(nproc):
            counters = snap["metrics"][p]["counters"]
            xbytes = sum(
                v for k, v in counters.items()
                if k.startswith("ck_dcn_exchange_bytes_total")
            )
            assert xbytes > 0, (p, counters)
        # cross-process monotonic consistency after alignment: each
        # collective's spans must mutually overlap.  Loopback gloo RTTs
        # are sub-ms and the probe error bound is RTT/2 per process;
        # 250 ms slack covers scheduler noise on a shared rig while
        # still catching an uncancelled skew (>= 7.5 s) 30x over.
        margin = aggregate.collective_consistency(snap)
        assert margin > -0.25, f"merged trace inconsistent: {margin}"
        # the DCN-merged cluster health table: process 1's degraded lane
        # 0 appears (JSON round-trip stringifies lane keys), every other
        # process reads ok, and absence would be visible (not implied ok)
        from cekirdekler_tpu.obs.health import cluster_health_table

        table = cluster_health_table(snap)
        assert len(table["processes"]) == nproc, table
        deg = {(d["process"], str(d["lane"])) for d in table["degraded"]}
        assert deg == {(1, "0")}, table
        assert table["worst"] == "degraded", table
        merged = aggregate.merged_chrome_trace(snap)
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == set(range(1, nproc + 1)), pids
        if pid == 0:
            import json as _json
            import tempfile

            path = os.path.join(tempfile.gettempdir(), "ck_dcn_merged.json")
            with open(path, "w") as f:
                _json.dump(merged, f)
            print(f"DCN_MERGED pid=0 events={len(merged['traceEvents'])} "
                  f"margin={margin:.4f} path={path}", flush=True)
        print(f"DCN_OK pid={pid} final={final}", flush=True)
    finally:
        acc.dispose()


if __name__ == "__main__":
    main(
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
        [int(c) for c in (sys.argv[4] if len(sys.argv) > 4 else "4,4").split(",")],
    )
