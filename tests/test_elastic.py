"""cluster/elastic.py — epoch membership, heartbeat liveness, the pure
member re-split, per-window checkpoints, and the replayability of
membership transitions (ISSUE 13)."""

import os
import time

import numpy as np
import pytest

from cekirdekler_tpu.cluster.balancer import ClusterLoadBalancer
from cekirdekler_tpu.cluster.elastic import (
    Heartbeat,
    Membership,
    alive_members,
    member_resplit,
    resume_window,
    save_window,
)
from cekirdekler_tpu.obs.decisions import DECISIONS
from cekirdekler_tpu.obs.replay import replay_record, verify_records


# ---------------------------------------------------------------------------
# the pure re-split
# ---------------------------------------------------------------------------

def test_member_resplit_matches_lcm_balancer():
    steps = [256, 128, 128]
    out = member_resplit(steps, 4096)
    bal = ClusterLoadBalancer(steps)
    shares, rem = bal.equal_split(4096)
    shares = list(shares)
    shares[0] += rem
    assert out["ranges"] == shares
    assert out["lcm"] == bal.lcm
    assert sum(out["ranges"]) == 4096


def test_balancer_resplit_active_masks_departed_nodes():
    bal = ClusterLoadBalancer([64, 64, 64])
    out, rem = bal.resplit_active(1920, [0, 2])
    assert out[1] == 0
    assert sum(out) + rem == 1920
    # shares stay step-quantized for the survivors
    assert out[0] % 64 == 0 and out[2] % 64 == 0
    with pytest.raises(ValueError):
        bal.resplit_active(1920, [0, 9])
    with pytest.raises(ValueError):
        bal.resplit_active(1920, [])


# ---------------------------------------------------------------------------
# membership epochs & decisions
# ---------------------------------------------------------------------------

def test_membership_leave_join_bump_epoch_and_record():
    m = Membership()
    assert m.establish({"p0": 256, "p1": 128}) == 1
    out = m.leave("p1", total=2048)
    assert out["epoch_after"] == 2
    assert out["ranges"] == member_resplit([256], 2048)["ranges"]
    out = m.join("p2", 128, total=2048)
    assert out["epoch_after"] == 3
    assert m.snapshot()["members"] == {"p0": 256, "p2": 128}
    recs = [r for r in DECISIONS.snapshot()
            if r.kind in ("member-leave", "member-join")][-2:]
    assert [r.kind for r in recs] == ["member-leave", "member-join"]
    v = verify_records(recs)
    assert v["ok"], v["first_divergence"]


def test_membership_sync_diffs_and_resizes():
    m = Membership()
    m.establish({"p0": 256, "p1": 128, "p2": 128})
    # p2 departs, p1 resizes (leave+join), p3 arrives
    out = m.sync({"p0": 256, "p1": 256, "p3": 64}, total=4096)
    snap = m.snapshot()
    assert snap["members"] == {"p0": 256, "p1": 256, "p3": 64}
    # p2 leave + p1 leave + p1 rejoin + p3 join = 4 transitions
    assert len(out) == 4
    assert snap["epoch"] == 5  # establish(1) + 4 transitions


def test_membership_steps_stay_in_process_order_past_ten_members():
    """Plain lexicographic sort would interleave 'p10' before 'p2':
    the positional steps_after/ranges in the decision record must
    follow process order (length-then-lex, the drain lane-key rule)."""
    m = Membership()
    m.establish({f"p{i}": 64 * (i + 1) for i in range(11)})
    out = m.join("p11", 64, total=0)
    # p0..p10 keep their 64*(i+1) steps positionally, p11 appends
    assert out["members_after"]["p11"] == 64
    rec = [r for r in DECISIONS.snapshot()
           if r.kind == "member-join"][-1]
    assert rec.inputs["steps_after"] == [64 * (i + 1)
                                         for i in range(11)] + [64]


def test_membership_tampered_resplit_diverges_on_replay():
    m = Membership()
    m.establish({"p0": 64, "p1": 64})
    m.leave("p1", total=1024)
    rec = [r for r in DECISIONS.snapshot()
           if r.kind == "member-leave"][-1]
    row = rec.to_row()
    out = replay_record(row)
    assert out["ok"] is True
    row["outputs"] = dict(row["outputs"], ranges=[512])
    out = replay_record(row)
    assert out["ok"] is False and "ranges" in out["mismatch"]


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------

def test_heartbeat_liveness_and_stale_detection(tmp_path):
    root = str(tmp_path)
    hb0 = Heartbeat(root, "p0", interval_s=0.05)
    hb1 = Heartbeat(root, "p1", interval_s=0.05, start=False)
    try:
        assert alive_members(root, timeout_s=5.0) == ["p0", "p1"]
        # p1 stops beating (a SIGKILL leaves exactly this): backdate its
        # file instead of sleeping the timeout out
        hb1.close()
        path = os.path.join(root, "hb_p1")
        past = time.time() - 60.0
        os.utime(path, (past, past))
        assert alive_members(root, timeout_s=1.0) == ["p0"]
        # a CLEAN leave retracts the file entirely
        hb0.close(remove=True)
        assert alive_members(root, timeout_s=1.0) == []
    finally:
        hb0.close()
        hb1.close()


def test_heartbeat_drives_membership_sync(tmp_path):
    """The detection half of preemption: a stale heartbeat reconciles
    into a recorded member-leave."""
    root = str(tmp_path)
    m = Membership()
    m.establish({"p0": 64, "p1": 64})
    hb0 = Heartbeat(root, "p0", start=False)
    hb1 = Heartbeat(root, "p1", start=False)
    hb1.close()
    past = time.time() - 60.0
    os.utime(os.path.join(root, "hb_p1"), (past, past))
    present = {mid: 64 for mid in alive_members(root, timeout_s=1.0)}
    out = m.sync(present, total=1024)
    assert len(out) == 1
    assert m.snapshot()["members"] == {"p0": 64}
    assert out[0]["ranges"] == [1024]
    hb0.close()


# ---------------------------------------------------------------------------
# per-window checkpoints
# ---------------------------------------------------------------------------

def test_save_resume_window_round_trip_with_metadata(tmp_path):
    root = str(tmp_path)
    y = np.arange(8, dtype=np.float32)
    save_window(root, 3, {"y": y}, member_steps=[128, 64])
    save_window(root, 4, {"y": y * 2}, member_steps=[128, 64])
    state = resume_window(root)
    assert state["window"] == 4
    np.testing.assert_array_equal(state["arrays"]["y"], y * 2)
    assert state["member_steps"] == [128, 64]
    # the restore is provenance: a checkpoint-restore decision recorded
    recs = [r for r in DECISIONS.snapshot()
            if r.kind == "checkpoint-restore"]
    assert recs and recs[-1].outputs["window"] == 4


def test_resume_window_falls_back_past_torn_newest(tmp_path):
    root = str(tmp_path)
    save_window(root, 1, {"y": np.full(4, 9.0, np.float32)},
                member_steps=[64])
    torn = os.path.join(root, f"step_{2:012d}")
    os.makedirs(torn)
    with open(os.path.join(torn, "arrays.npz"), "wb") as f:
        f.write(b"garbage")
    state = resume_window(root)
    assert state["window"] == 1
    np.testing.assert_array_equal(state["arrays"]["y"], 9.0)


def test_resume_window_empty_root_is_fresh_start(tmp_path):
    assert resume_window(str(tmp_path / "nope")) is None
