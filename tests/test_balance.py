"""Load balancer unit tests (reference behavior: Functions.loadBalance,
HelperFunctions.cs:190-280 — damped, step-quantized, sum-preserving)."""

import numpy as np
import pytest

from cekirdekler_tpu.core.balance import (
    DAMP_MAX,
    DAMP_MAX_SMOOTHED,
    BalanceHistory,
    BalanceState,
    equal_split,
    load_balance,
)


def test_equal_split_exact():
    assert equal_split(1024, 4, 64) == [256, 256, 256, 256]


def test_equal_split_remainder_spread():
    r = equal_split(1024, 3, 64)
    assert sum(r) == 1024
    assert all(x % 64 == 0 for x in r)
    assert max(r) - min(r) <= 64


def test_equal_split_rejects_nondivisible():
    with pytest.raises(ValueError):
        equal_split(1000, 4, 64)


def test_single_device_gets_all():
    assert load_balance([5.0], [512], 512, 64) == [512]


def test_balance_moves_work_to_faster_device():
    ranges = [512, 512]
    carry = []
    # device 0 twice as fast
    for _ in range(30):
        bench = [ranges[0] * 1.0, ranges[1] * 2.0]  # ms proportional to work×slowness
        ranges = load_balance(bench, ranges, 1024, 64, carry=carry)
    assert sum(ranges) == 1024
    assert all(r % 64 == 0 for r in ranges)
    # converged shares should be ~2:1
    assert ranges[0] > ranges[1]
    assert abs(ranges[0] - 683) <= 64  # 2/3 of 1024, step-quantized


def test_balance_without_carry_stalls_within_two_steps():
    """Reference-parity mode (no continuous carry): quantization hysteresis
    can stall up to ~2 steps from ideal — documents why `carry` exists."""
    ranges = [512, 512]
    for _ in range(30):
        bench = [ranges[0] * 1.0, ranges[1] * 2.0]
        ranges = load_balance(bench, ranges, 1024, 64)
    assert sum(ranges) == 1024
    assert abs(ranges[0] - 683) <= 2 * 64


def test_balance_converges_and_stays():
    """Convergence metric: max share delta < step after some iterations
    (BASELINE.md target: convergence iteration count)."""
    speeds = [1.0, 2.0, 4.0, 8.0]  # relative speeds of 4 chips
    total, step = 4096, 64
    ranges = equal_split(total, 4, step)
    converged_at = None
    for it in range(100):
        bench = [r / s if r else 0.01 for r, s in zip(ranges, speeds)]
        new = load_balance(bench, ranges, total, step)
        if max(abs(a - b) for a, b in zip(new, ranges)) < step and converged_at is None:
            converged_at = it
        ranges = new
    assert converged_at is not None and converged_at < 50
    # ideal shares 1:2:4:8
    ideal = [total * s / 15 for s in speeds]
    for r, i in zip(ranges, ideal):
        assert abs(r - i) <= 2 * step


def test_balance_zero_benchmark_guard():
    out = load_balance([0.0, 1.0], [512, 512], 1024, 64)
    assert sum(out) == 1024


def test_balance_sum_repair_with_rounding():
    # shares that don't quantize cleanly must still sum exactly
    out = load_balance([1.0, 1.1, 0.9], [320, 384, 320], 1024, 64)
    assert sum(out) == 1024
    assert all(r % 64 == 0 and r >= 0 for r in out)


def test_balance_can_starve_very_slow_device():
    ranges = [512, 512]
    for _ in range(60):
        bench = [max(ranges[0], 1) * 1.0, max(ranges[1], 64) * 1000.0]
        ranges = load_balance(bench, ranges, 1024, 64)
    assert ranges[1] <= 64  # slow chip pushed to (near) zero
    assert sum(ranges) == 1024


def test_history_smoothing_damps_noise():
    hist = BalanceHistory(depth=10)
    rng = np.random.RandomState(0)
    smoothed = []
    for _ in range(40):
        noisy = [0.5 + rng.uniform(-0.2, 0.2)]
        noisy.append(1.0 - noisy[0])
        smoothed.append(hist.smooth(noisy)[0])
    # late smoothed values vary less than raw noise
    late = smoothed[20:]
    assert np.std(late) < 0.07


def test_history_resets_on_device_count_change():
    hist = BalanceHistory()
    hist.smooth([0.5, 0.5])
    out = hist.smooth([0.2, 0.3, 0.5])
    assert len(out) == 3


# -- adaptive damping (BalanceState) -----------------------------------------

def _mandelbrot_cost_field():
    from cekirdekler_tpu.workloads import mandelbrot_host

    w = h = 256
    img = mandelbrot_host(w, h, -2.0, -1.25, 2.5 / w, 2.5 / h, 96)
    cost = img.astype(np.float64) + 2.0
    return np.concatenate([[0.0], np.cumsum(cost)]), w * h


def _run_sim(total, cum, ndev, step, iters, hist=None, state=None, carry=None):
    ranges = equal_split(total, ndev, step)
    traj = [list(ranges)]
    for _ in range(iters):
        offs = np.concatenate([[0], np.cumsum(ranges)]).astype(int)
        bench = [float(cum[offs[i + 1]] - cum[offs[i]]) for i in range(ndev)]
        ranges = load_balance(bench, ranges, total, step, hist,
                              carry=carry, state=state)
        traj.append(list(ranges))
    return traj


def test_adaptive_state_settles_without_limit_cycle():
    # fixed damping limit-cycles +-2-4 steps forever on the skewed
    # mandelbrot cost field; the adaptive state must come fully to rest
    cum, total = _mandelbrot_cost_field()
    step = 128
    traj = _run_sim(total, cum, 8, step, 40, state=BalanceState())
    tail = traj[-8:]
    assert all(t == tail[0] for t in tail), "ranges still moving at the tail"
    assert sum(tail[0]) == total


def test_adaptive_converges_faster_than_parity():
    from cekirdekler_tpu.workloads import _converged_at

    cum, total = _mandelbrot_cost_field()
    step = 128
    t_adapt = _run_sim(total, cum, 8, step, 48, hist=BalanceHistory(weighted=True),
                       state=BalanceState())
    t_parity = _run_sim(total, cum, 8, step, 48, hist=BalanceHistory(), carry=[])
    ca = _converged_at(t_adapt, step)
    cp = _converged_at(t_parity, step)
    assert ca is not None and ca < 25
    assert cp is None or ca < cp


def test_adaptive_damp_decays_on_oscillation_and_respects_caps():
    state = BalanceState()
    ranges = [512, 512]
    # alternate which chip looks slow -> every move flips sign
    for k in range(12):
        bench = [1.0, 2.0] if k % 2 == 0 else [2.0, 1.0]
        ranges = load_balance(bench, ranges, 1024, 64, state=state)
    assert all(d <= DAMP_MAX for d in state.damp)
    assert any(d < 0.3 for d in state.damp), "sign flips must decay damping"
    # smoothed cap is tighter
    state2 = BalanceState()
    hist = BalanceHistory(weighted=True)
    ranges = [768, 256]
    for _ in range(20):
        bench = [4.0, 1.0]  # consistent direction -> damp grows to the cap
        ranges = load_balance(bench, ranges, 1024, 64, hist, state=state2)
    assert all(d <= DAMP_MAX_SMOOTHED for d in state2.damp)


def test_adaptive_state_resets_on_device_count_change():
    state = BalanceState()
    load_balance([1.0, 2.0], [512, 512], 1024, 64, state=state)
    out = load_balance([1.0, 2.0, 3.0], [512, 256, 256], 1024, 64, state=state)
    assert len(out) == 3 and sum(out) == 1024
    assert len(state.cont) == 3


def test_weighted_history_weights_recent_rows_more():
    flat = BalanceHistory()
    tri = BalanceHistory(weighted=True)
    rows = [[0.9, 0.1]] * 5 + [[0.1, 0.9]]
    for r in rows:
        f = flat.smooth(list(r))
        t = tri.smooth(list(r))
    # triangular puts more weight on the last (flipped) row
    assert t[1] > f[1]


def test_adaptive_freeze_requantizes_on_step_change():
    # converge at step 64, then call with step 256 (pipeline mode changes
    # step to local*blobs): the freeze must not hold a 64-grain split that
    # is invalid for the new step
    state = BalanceState()
    ranges = [448, 576]  # multiples of 64, not of 256
    out = load_balance([1.0, 1.0], ranges, 1024, 256, state=state)
    assert all(r % 256 == 0 for r in out)
    assert sum(out) == 1024


def test_cores_adaptive_toggle_clears_balancer_state():
    from cekirdekler_tpu.core.cores import Cores  # noqa: F401 (import check)
    from cekirdekler_tpu.core import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    src = """
    __kernel void t(__global float* a) {
        int i = get_global_id(0);
        a[i] = a[i] + 1.0f;
    }
    """
    cr = NumberCruncher(platforms().cpus().subset(2), src)
    try:
        a_ = np.zeros(512, np.float32)
        from cekirdekler_tpu import ClArray
        a = ClArray(512, np.float32, name="tgl", read=True, write=True)
        for _ in range(3):
            a.compute(cr, 5, "t", 512, 64)
        assert cr.cores._balance_states  # adaptive state accumulated
        cr.adaptive_load_balancer = False
        assert not cr.cores._balance_states
        assert not cr.cores.histories
        for _ in range(2):
            a.compute(cr, 5, "t", 512, 64)
        hist = cr.cores.histories.get(5)
        assert hist is None or hist.weighted is False  # parity-mode history
        cr.adaptive_load_balancer = True
        assert not cr.cores.histories and not cr.cores._cont_ranges
    finally:
        cr.dispose()


def test_freeze_keeps_history_fresh():
    # during a freeze the smoothing window must keep receiving measured
    # shares; otherwise a post-freeze workload shift is steered by stale rows
    state = BalanceState()
    hist = BalanceHistory(weighted=True)
    ranges = [512, 512]
    for _ in range(6):
        ranges = load_balance([1.0, 1.0], ranges, 1024, 64, hist, state=state)
    assert ranges == [512, 512]  # balanced -> frozen
    assert len(hist.rows) == 6  # window kept filling during the freeze


def test_wrap_override_failure_leaves_flags_intact():
    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.arrays.clarray import wrap
    from cekirdekler_tpu.errors import ComputeValidationError

    b = ClArray(np.zeros(8, np.float64))
    before = b.flags
    with pytest.raises(ComputeValidationError):
        wrap(b, alignment_bytes=48)  # not a power of two
    with pytest.raises(ComputeValidationError):
        wrap(b, alignment_bytes=4)  # smaller than float64 itemsize
    assert b.flags == before


# ---------------------------------------------------------------------------
# transfer-aware balancing (ISSUE 5): link-time floor + warm-start jump
# ---------------------------------------------------------------------------

def test_transfer_floor_caps_slow_link_lane():
    """A lane whose separately-measured transfer time dwarfs its
    (overlapped, small-looking) compute bench must lose share: effective
    time is max(compute, transfer) — the link is a floor."""
    ranges = [512, 512]
    carry = []
    for _ in range(30):
        # identical compute speed; lane 1's link is 3x the compute time
        bench = [ranges[0] * 1.0, ranges[1] * 1.0]
        transfer = [0.0, ranges[1] * 3.0]
        ranges = load_balance(bench, ranges, 1024, 64, carry=carry,
                              transfer_ms=transfer)
    assert sum(ranges) == 1024
    # converged ~3:1 (lane 1 is effectively 3x slower end-to-end)
    assert abs(ranges[0] - 768) <= 64, ranges


def test_transfer_floor_noop_when_transfers_overlap_fully():
    """Transfer times below the compute bench change nothing — the floor
    only binds when the link is the bottleneck."""
    bench = [100.0, 100.0]
    with_t = load_balance(bench, [512, 512], 1024, 64,
                          transfer_ms=[10.0, 10.0])
    without = load_balance(bench, [512, 512], 1024, 64)
    assert with_t == without


def test_jump_start_converges_on_second_measured_iteration():
    """The transfer-aware warm start: the FIRST measured rebalance only
    ARMS the jump and runs damped (first-window benches routinely carry
    one lane's jit compile); the SECOND jumps straight to the
    rate-implied split (the r5 rig crept there over 17 damped
    iterations)."""
    state = BalanceState()
    ranges = [512, 512]
    # lane 0 twice as fast (bench = items x per-item cost)
    bench = [ranges[0] * 1.0, ranges[1] * 2.0]
    ranges = load_balance(bench, ranges, 1024, 64, state=state,
                          jump_start=True)
    assert state.warm is True and state.jumped is False  # armed, damped
    bench = [ranges[0] * 1.0, ranges[1] * 2.0]
    ranges = load_balance(bench, ranges, 1024, 64, state=state,
                          jump_start=True)
    assert state.jumped is True
    assert abs(ranges[0] - 683) <= 64, ranges  # 2/3 split on the jump
    # one-shot: later iterations run the damped loop (no oscillating
    # re-jumps on noise) and HOLD the converged split
    for _ in range(5):
        bench = [ranges[0] * 1.0, ranges[1] * 2.0]
        prev = ranges
        ranges = load_balance(bench, ranges, 1024, 64, state=state,
                              jump_start=True)
        assert abs(ranges[0] - prev[0]) <= 64
    assert abs(ranges[0] - 683) <= 64, ranges


def test_jump_start_survives_compile_contaminated_first_bench():
    """The reason the jump fires on the SECOND measured rebalance: the
    first window's bench routinely carries one lane's jit compile (the
    executable-cache miss lands on whichever lane dispatched first).  An
    undamped jump onto a 20x-inflated bench would hand that lane ~1/20
    of its fair share in one step; the damped first iteration bounds the
    damage, and the jump then fires on clean benches."""
    state = BalanceState()
    ranges = [512, 512]
    # lane 0 paid compile: equal true rates, bench inflated 20x
    bench = [ranges[0] * 20.0, ranges[1] * 1.0]
    ranges = load_balance(bench, ranges, 1024, 64, state=state,
                          jump_start=True)
    assert ranges[0] >= 256, ranges  # damped — not starved in one step
    # clean second window: the jump lands on the honest (equal) split
    bench = [ranges[0] * 1.0, ranges[1] * 1.0]
    ranges = load_balance(bench, ranges, 1024, 64, state=state,
                          jump_start=True)
    assert state.jumped is True
    assert abs(ranges[0] - 512) <= 128, ranges


def test_jump_start_resets_with_state():
    """BalanceState.reset re-arms the jump (a device-count change makes
    the old split meaningless — the next measured rebalances may arm and
    jump again)."""
    state = BalanceState()
    for _ in range(2):
        load_balance([1.0, 2.0], [512, 512], 1024, 64, state=state,
                     jump_start=True)
    assert state.jumped is True
    state.reset([256, 256, 256, 256], 0.5)
    assert state.jumped is False
    assert state.warm is False
