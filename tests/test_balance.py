"""Load balancer unit tests (reference behavior: Functions.loadBalance,
HelperFunctions.cs:190-280 — damped, step-quantized, sum-preserving)."""

import numpy as np
import pytest

from cekirdekler_tpu.core.balance import (
    BalanceHistory,
    equal_split,
    load_balance,
)


def test_equal_split_exact():
    assert equal_split(1024, 4, 64) == [256, 256, 256, 256]


def test_equal_split_remainder_spread():
    r = equal_split(1024, 3, 64)
    assert sum(r) == 1024
    assert all(x % 64 == 0 for x in r)
    assert max(r) - min(r) <= 64


def test_equal_split_rejects_nondivisible():
    with pytest.raises(ValueError):
        equal_split(1000, 4, 64)


def test_single_device_gets_all():
    assert load_balance([5.0], [512], 512, 64) == [512]


def test_balance_moves_work_to_faster_device():
    ranges = [512, 512]
    carry = []
    # device 0 twice as fast
    for _ in range(30):
        bench = [ranges[0] * 1.0, ranges[1] * 2.0]  # ms proportional to work×slowness
        ranges = load_balance(bench, ranges, 1024, 64, carry=carry)
    assert sum(ranges) == 1024
    assert all(r % 64 == 0 for r in ranges)
    # converged shares should be ~2:1
    assert ranges[0] > ranges[1]
    assert abs(ranges[0] - 683) <= 64  # 2/3 of 1024, step-quantized


def test_balance_without_carry_stalls_within_two_steps():
    """Reference-parity mode (no continuous carry): quantization hysteresis
    can stall up to ~2 steps from ideal — documents why `carry` exists."""
    ranges = [512, 512]
    for _ in range(30):
        bench = [ranges[0] * 1.0, ranges[1] * 2.0]
        ranges = load_balance(bench, ranges, 1024, 64)
    assert sum(ranges) == 1024
    assert abs(ranges[0] - 683) <= 2 * 64


def test_balance_converges_and_stays():
    """Convergence metric: max share delta < step after some iterations
    (BASELINE.md target: convergence iteration count)."""
    speeds = [1.0, 2.0, 4.0, 8.0]  # relative speeds of 4 chips
    total, step = 4096, 64
    ranges = equal_split(total, 4, step)
    converged_at = None
    for it in range(100):
        bench = [r / s if r else 0.01 for r, s in zip(ranges, speeds)]
        new = load_balance(bench, ranges, total, step)
        if max(abs(a - b) for a, b in zip(new, ranges)) < step and converged_at is None:
            converged_at = it
        ranges = new
    assert converged_at is not None and converged_at < 50
    # ideal shares 1:2:4:8
    ideal = [total * s / 15 for s in speeds]
    for r, i in zip(ranges, ideal):
        assert abs(r - i) <= 2 * step


def test_balance_zero_benchmark_guard():
    out = load_balance([0.0, 1.0], [512, 512], 1024, 64)
    assert sum(out) == 1024


def test_balance_sum_repair_with_rounding():
    # shares that don't quantize cleanly must still sum exactly
    out = load_balance([1.0, 1.1, 0.9], [320, 384, 320], 1024, 64)
    assert sum(out) == 1024
    assert all(r % 64 == 0 and r >= 0 for r in out)


def test_balance_can_starve_very_slow_device():
    ranges = [512, 512]
    for _ in range(60):
        bench = [max(ranges[0], 1) * 1.0, max(ranges[1], 64) * 1000.0]
        ranges = load_balance(bench, ranges, 1024, 64)
    assert ranges[1] <= 64  # slow chip pushed to (near) zero
    assert sum(ranges) == 1024


def test_history_smoothing_damps_noise():
    hist = BalanceHistory(depth=10)
    rng = np.random.RandomState(0)
    smoothed = []
    for _ in range(40):
        noisy = [0.5 + rng.uniform(-0.2, 0.2)]
        noisy.append(1.0 - noisy[0])
        smoothed.append(hist.smooth(noisy)[0])
    # late smoothed values vary less than raw noise
    late = smoothed[20:]
    assert np.std(late) < 0.07


def test_history_resets_on_device_count_change():
    hist = BalanceHistory()
    hist.smooth([0.5, 0.5])
    out = hist.smooth([0.2, 0.3, 0.5])
    assert len(out) == 3
