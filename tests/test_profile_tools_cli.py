"""End-to-end CLI coverage for the profiling tools (ISSUE 8):

- ``tools/profile_gap.py`` — rewritten in r7 on top of the trace
  subsystem but never exercised as a CLI until now: the layer-peeling
  run must print the attribution tables and the ``--chrome`` dump must
  parse back through the Chrome-trace reader.
- ``tools/kernel_profile.py`` — the device-profile CLI: run mode on
  the CPU rig (named absence + unified trace), ``--trace-dir`` mode on
  a synthetic-Xprof fixture (full per-kernel table + roofline), and
  the ``--store`` / ``--show-store`` persistence loop.

Subprocess invocations inherit the rig env (JAX_PLATFORMS=cpu) so the
children run on the same virtual-device rig as the suite.
"""

import gzip
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _run(tool, *args, timeout=240):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", tool), *args],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_profile_gap_cli_attribution_and_chrome_dump(tmp_path):
    chrome = str(tmp_path / "gap.json")
    r = _run("profile_gap.py", "--size", "64", "--iters", "1",
             "--chrome", chrome)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    # every layer printed its stopwatch line...
    for label in ("tuned pallas loop", "direct launcher fn",
                  "framework compute() enqueue",
                  "framework no_compute (sched only)"):
        assert label in out, f"missing segment {label!r}:\n{out}"
    # ...and the traced segments printed the attribution table
    assert out.count("-- attribution") == 2
    assert "wall" in out and "span-covered" in out and "gap" in out
    assert "kind" in out and "% wall" in out
    # the chrome dump parses back through the pinned reader with spans
    from cekirdekler_tpu.trace.export import from_chrome_trace

    doc = json.load(open(chrome))
    spans = from_chrome_trace(doc)
    assert spans, "chrome dump round-tripped to zero spans"
    assert {"launch", "fence"} & {s.kind for s in spans}


def _fixture_dump(dirpath):
    os.makedirs(dirpath, exist_ok=True)
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 0, "ts": 0.0, "dur": 40.0,
         "name": "ck|k=mandelbrot|c=7|l=0|s=1"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 100.0, "dur": 5000.0,
         "name": "fusion.1", "args": {"ck-seq": 1}},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 5300.0, "dur": 700.0,
         "name": "fusion.2", "args": {"ck-seq": 1}},
    ]
    with gzip.open(os.path.join(dirpath, "h.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_kernel_profile_cli_trace_dir_roofline_and_store(tmp_path):
    fix = str(tmp_path / "fix")
    store = str(tmp_path / "store")
    _fixture_dump(fix)
    r = _run("kernel_profile.py", "--trace-dir", fix, "--store", store,
             "--flops", "1e9", "--bytes", "1e8")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "mandelbrot" in r.stdout and "device ms" in r.stdout
    assert "5.700" in r.stdout          # 5.0 + 0.7 ms attributed
    assert "roofline mandelbrot" in r.stdout
    assert "memory-bound" in r.stdout or "compute-bound" in r.stdout
    assert os.listdir(store), "--store persisted nothing"

    s = _run("kernel_profile.py", "--show-store", "--store", store)
    assert s.returncode == 0, s.stdout + s.stderr
    assert "1 key(s)" in s.stdout and "device_ms=5.7" in s.stdout


def test_kernel_profile_cli_json_report(tmp_path):
    fix = str(tmp_path / "fix")
    _fixture_dump(fix)
    r = _run("kernel_profile.py", "--trace-dir", fix, "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["kernels"][0]["kernel"] == "mandelbrot"
    assert doc["coverage_frac"] == 1.0


def test_kernel_profile_cli_run_mode_named_absence_on_cpu(tmp_path):
    """Run mode on the CPU rig: the capture machinery runs end-to-end
    and the report degrades to a NAMED absence (no device tracks) with
    a unified chrome dump that still carries the host spans."""
    chrome = str(tmp_path / "uni.json")
    r = _run("kernel_profile.py", "--size", "64", "--iters", "1",
             "--capture-dir", str(tmp_path / "cap"), "--chrome", chrome)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "device profile absent" in r.stdout or "coverage" in r.stdout
    from cekirdekler_tpu.trace.device import split_unified_trace

    spans, ops = split_unified_trace(json.load(open(chrome)))
    assert spans, "unified dump lost the host spans"


def test_kernel_profile_cli_show_store_without_root():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("CK_PROFILE_STORE", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "kernel_profile.py"),
         "--show-store"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert r.returncode == 1
    assert "no store configured" in r.stderr
