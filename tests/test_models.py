"""Flagship transformer tests: forward determinism, loss decreases under
training, sharded multi-device parity with the single-device model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cekirdekler_tpu import parallel as par
from cekirdekler_tpu.parallel.mesh import set_mesh

# pre-0.6 jax (the 0.4.x CPU rigs) routes shard_map(axis_names=...) through
# experimental shard_map's PARTIAL auto-axes support — multi-device auto
# axes die under jit with "PartitionId ... UNIMPLEMENTED".  The paths are
# supported (and these tests run) on current jax; on old rigs they are
# declared unsupported rather than shipped red.
requires_full_auto_axes = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="pre-0.6 jax: shard_map auto-axes support is partial "
           "(PartitionId UNIMPLEMENTED under jit)",
)
from cekirdekler_tpu.models import Transformer, TransformerConfig


def _cfg(**kw):
    base = dict(
        vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64, max_seq=64,
        dtype=jnp.float32,  # f32 on the CPU rig for tight parity checks
    )
    base.update(kw)
    return TransformerConfig(**base)


def _batch(rng, B, T, vocab):
    return {"tokens": jnp.asarray(rng.integers(0, vocab, (B, T + 1)), jnp.int32)}


def test_forward_shapes_and_determinism():
    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.zeros((2, 16), jnp.int32)
    a = model.apply(params, toks)
    b = model.apply(params, toks)
    assert a.shape == (2, 16, cfg.vocab)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_training_reduces_loss():
    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(model.make_train_step(opt))
    rng = np.random.default_rng(0)
    batch = _batch(rng, 4, 16, cfg.vocab)  # one fixed batch: loss must drop
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.parametrize("attention", ["dense", "ring", "ulysses"])
def test_sharded_forward_matches_single_device(attention):
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, tp=2, sp=2)
    cfg = _cfg(attention=attention)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)

    want = Transformer(_cfg()).apply(params, toks)  # dense, unsharded

    sharded = model.shard_params(params, mesh)
    toks_s = par.shard_batch(mesh, toks)
    with set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(sharded, toks_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_train_step_sharded_runs_and_matches_loss():
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, fsdp=2, tp=2)
    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(2))
    opt = optax.adamw(1e-2)
    rng = np.random.default_rng(2)
    batch = _batch(rng, 4, 16, cfg.vocab)

    # unsharded reference
    step_ref = jax.jit(model.make_train_step(opt))
    p_ref, _, loss_ref = step_ref(params, opt.init(params), batch)

    sharded = model.shard_params(params, mesh)
    batch_s = par.shard_batch(mesh, batch)
    with set_mesh(mesh):
        step = jax.jit(model.make_train_step(opt, mesh))
        p_new, _, loss = step(sharded, opt.init(sharded), batch_s)
    np.testing.assert_allclose(float(loss), float(loss_ref), atol=1e-4)


def test_moe_forward_and_training():
    cfg = _cfg(n_experts=4, moe_every=2)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(4))
    assert "router" in params["blocks"][1] and "router" not in params["blocks"][0]
    opt = optax.adamw(1e-2)
    step = jax.jit(model.make_train_step(opt))
    rng = np.random.default_rng(4)
    batch = _batch(rng, 4, 16, cfg.vocab)
    p, s, l0 = step(params, opt.init(params), batch)
    losses = [float(l0)]
    for _ in range(9):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


@requires_full_auto_axes
def test_moe_sharded_matches_single_device():
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, tp=2, ep=2)
    cfg = _cfg(n_experts=4, moe_every=1)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(5))
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
    want = model.apply(params, toks)  # unsharded
    sharded = model.shard_params(params, mesh)
    with set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(sharded, par.shard_batch(mesh, toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


@requires_full_auto_axes
def test_pp_pipelined_matches_sequential():
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, pp=2, tp=2)
    cfg = _cfg(pp_stages=2, n_microbatches=2)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(6))  # blocks stacked [L, ...]
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
    want = model.apply(params, toks)  # mesh=None: sequential over the stack
    sharded = model.shard_params(params, mesh)
    with set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(sharded, par.shard_batch(mesh, toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


@requires_full_auto_axes
def test_pp_training_reduces_loss():
    devs = jax.devices("cpu")[:4]
    mesh = par.make_mesh(devs, pp=2, tp=2)
    cfg = _cfg(pp_stages=2, n_microbatches=2)
    model = Transformer(cfg)
    params = model.shard_params(model.init(jax.random.PRNGKey(7)), mesh)
    opt = optax.adamw(1e-2)
    rng = np.random.default_rng(7)
    batch = par.shard_batch(mesh, _batch(rng, 4, 16, cfg.vocab))
    with set_mesh(mesh):
        step = jax.jit(model.make_train_step(opt, mesh))
        s = opt.init(params)
        losses = []
        for _ in range(8):
            params, s, loss = step(params, s, batch)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_remat_matches_no_remat():
    cfg = _cfg(remat=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(3))
    toks = jnp.zeros((2, 8), jnp.int32)
    got = model.apply(params, toks)
    want = Transformer(_cfg()).apply(params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_checkpoint_resume_is_deterministic(tmp_path):
    """Checkpoint/resume (SURVEY §5.4 — the subsystem the reference lacks
    entirely): save params+opt_state mid-train, resume in a fresh
    optimizer/step, and the remaining steps must reproduce the original
    run's losses exactly."""
    from cekirdekler_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(7))
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(model.make_train_step(opt))
    rng = np.random.default_rng(7)
    batches = [_batch(rng, 4, 16, cfg.vocab) for _ in range(4)]

    losses = []
    for i, b in enumerate(batches):
        params, opt_state, loss = step(params, opt_state, b)
        losses.append(float(loss))
        if i == 1:
            ckpt.save_pytree(str(tmp_path), 2, {"params": params, "opt": opt_state})

    state = ckpt.load_pytree(
        str(tmp_path), {"params": params, "opt": opt_state}, step=2
    )
    p2, o2 = state["params"], state["opt"]
    resumed = []
    for b in batches[2:]:
        p2, o2, loss = step(p2, o2, b)
        resumed.append(float(loss))
    np.testing.assert_allclose(resumed, losses[2:], rtol=1e-6)


def test_moe_capacity_matches_dense_when_ample():
    """capacity_factor >= E makes dropping impossible: the capacity
    dispatch must reproduce the dense compute-all result exactly, both
    single-device and on the dp x ep mesh."""
    from cekirdekler_tpu.models.moe import moe_ffn, moe_ffn_capacity, moe_ffn_sharded

    rng = np.random.default_rng(7)
    B, T, d, f, E = 2, 16, 32, 64, 4
    x = jnp.asarray(rng.standard_normal((B, T, d)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((d, E)) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.1, jnp.float32)
    want = moe_ffn(x, router, w1, w2)
    got = moe_ffn_capacity(x, router, w1, w2, capacity_factor=float(E))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    mesh = par.make_mesh(jax.devices("cpu")[:4], ep=4)
    got_sh = moe_ffn_sharded(mesh, x, router, w1, w2,
                             capacity_factor=float(E))
    np.testing.assert_allclose(np.asarray(got_sh), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_moe_capacity_drops_overflow_tokens():
    """With capacity 1 token per expert, the FIRST token routed to each
    expert keeps its output and later ones contribute zero."""
    from cekirdekler_tpu.models.moe import moe_ffn_capacity

    rng = np.random.default_rng(8)
    B, T, d, f, E = 1, 8, 16, 32, 2
    x = jnp.asarray(rng.standard_normal((B, T, d)), jnp.float32)
    # zero router: all logits tie, argmax picks expert 0 for every token
    router = jnp.zeros((d, E), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.1, jnp.float32)
    # capacity_factor 2/E -> C = ceil(N/E * 2/E)... pick factor so C=1:
    # N=8, E=2 -> C = ceil(4 * cf); cf=0.25 -> C=1
    y = moe_ffn_capacity(x, router, w1, w2, capacity_factor=0.25)
    y = np.asarray(y)
    assert np.abs(y[0, 0]).max() > 0  # first token kept
    assert np.abs(y[0, 1:]).max() == 0  # the rest dropped


def test_moe_capacity_gradients_flow():
    from cekirdekler_tpu.models.moe import moe_ffn_capacity

    rng = np.random.default_rng(9)
    B, T, d, f, E = 2, 8, 16, 32, 4
    x = jnp.asarray(rng.standard_normal((B, T, d)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((d, E)) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.1, jnp.float32)
    g = jax.grad(lambda w1, w2: (
        moe_ffn_capacity(x, router, w1, w2, capacity_factor=2.0) ** 2).sum(),
        argnums=(0, 1))(w1, w2)
    assert all(np.isfinite(np.asarray(a)).all() for a in g)
    assert any(np.abs(np.asarray(a)).max() > 0 for a in g)


def test_moe_capacity_flop_win_on_ep_mesh():
    """The VERDICT r3 #8 criterion: lowered per-step FLOPs of the
    capacity formulation beat dense compute-all at E>=4 on the 8-device
    ep mesh."""
    from cekirdekler_tpu.models.moe import moe_ffn_sharded

    rng = np.random.default_rng(10)
    B, T, d, f, E = 4, 64, 64, 256, 8
    x = jnp.asarray(rng.standard_normal((B, T, d)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((d, E)) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, d, f)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((E, f, d)) * 0.1, jnp.float32)
    mesh = par.make_mesh(jax.devices("cpu")[:8], ep=8)

    def flops(cf):
        fn = jax.jit(lambda *a: moe_ffn_sharded(mesh, *a, capacity_factor=cf))
        lowered = fn.lower(x, router, w1, w2).compile()
        c = lowered.cost_analysis()
        c = c[0] if isinstance(c, (list, tuple)) else c
        return float(c.get("flops", 0.0))

    dense, cap = flops(0.0), flops(2.0)
    assert dense > 0 and cap > 0
    # dense does T*E_local expert-ffn work per chip; capacity does C*E_local
    # with C = T*cf/E -> expect ~E/cf = 4x fewer total flops (allow slack
    # for routing/scatter overhead)
    assert cap < dense / 2, (dense, cap)


def test_flash_attention_under_batch_sharded_mesh():
    """attention='flash' now runs the Pallas kernels per-shard under a
    dp x fsdp x tp mesh (batch/head sharding never crosses the attention
    reduction); must match the unsharded apply AND train with finite
    grads.  T=128: the smallest length the r6 default_blocks policy
    keeps on the tiled path (sub-128 tiles route to dense)."""
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, fsdp=2, tp=2)
    cfg = _cfg(attention="flash", max_seq=128)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(6))
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 128)), jnp.int32)
    want = model.apply(params, toks)  # unsharded (single-chip flash path)
    sharded = model.shard_params(params, mesh)
    with set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(
            sharded, par.shard_batch(mesh, toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-4)
    # one sharded train step: loss finite
    opt = optax.adamw(1e-3)
    batch = {"tokens": toks, "targets": jnp.roll(toks, -1, axis=1)}
    with set_mesh(mesh):
        step = jax.jit(model.make_train_step(opt, mesh))
        _, _, loss = step(sharded, opt.init(sharded),
                          par.shard_batch(mesh, batch))
    assert np.isfinite(float(loss))


def test_flash_mesh_uneven_heads_falls_back_to_dense():
    """attention='flash' with n_heads not divisible by tp must take the
    GSPMD dense path (which tolerates uneven sharding) instead of a
    shard_map divisibility error.  (An uneven BATCH is rejected upstream
    by shard_batch's explicit sharding — not a flash-path concern.)"""
    devs = jax.devices("cpu")[:4]
    mesh = par.make_mesh(devs, dp=2, tp=2)
    cfg = _cfg(attention="flash", max_seq=64, d_model=48, n_heads=3)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(8))
    rng = np.random.default_rng(8)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 64)), jnp.int32)
    want = Transformer(
        _cfg(max_seq=64, d_model=48, n_heads=3)
    ).apply(params, toks)  # dense, unsharded
    sharded = model.shard_params(params, mesh)
    with set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(
            sharded, par.shard_batch(mesh, toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-4)
