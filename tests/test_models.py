"""Flagship transformer tests: forward determinism, loss decreases under
training, sharded multi-device parity with the single-device model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cekirdekler_tpu import parallel as par
from cekirdekler_tpu.models import Transformer, TransformerConfig


def _cfg(**kw):
    base = dict(
        vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64, max_seq=64,
        dtype=jnp.float32,  # f32 on the CPU rig for tight parity checks
    )
    base.update(kw)
    return TransformerConfig(**base)


def _batch(rng, B, T, vocab):
    return {"tokens": jnp.asarray(rng.integers(0, vocab, (B, T + 1)), jnp.int32)}


def test_forward_shapes_and_determinism():
    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.zeros((2, 16), jnp.int32)
    a = model.apply(params, toks)
    b = model.apply(params, toks)
    assert a.shape == (2, 16, cfg.vocab)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_training_reduces_loss():
    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(model.make_train_step(opt))
    rng = np.random.default_rng(0)
    batch = _batch(rng, 4, 16, cfg.vocab)  # one fixed batch: loss must drop
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.parametrize("attention", ["dense", "ring", "ulysses"])
def test_sharded_forward_matches_single_device(attention):
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, tp=2, sp=2)
    cfg = _cfg(attention=attention)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)

    want = Transformer(_cfg()).apply(params, toks)  # dense, unsharded

    sharded = model.shard_params(params, mesh)
    toks_s = par.shard_batch(mesh, toks)
    with jax.set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(sharded, toks_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_train_step_sharded_runs_and_matches_loss():
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, fsdp=2, tp=2)
    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(2))
    opt = optax.adamw(1e-2)
    rng = np.random.default_rng(2)
    batch = _batch(rng, 4, 16, cfg.vocab)

    # unsharded reference
    step_ref = jax.jit(model.make_train_step(opt))
    p_ref, _, loss_ref = step_ref(params, opt.init(params), batch)

    sharded = model.shard_params(params, mesh)
    batch_s = par.shard_batch(mesh, batch)
    with jax.set_mesh(mesh):
        step = jax.jit(model.make_train_step(opt, mesh))
        p_new, _, loss = step(sharded, opt.init(sharded), batch_s)
    np.testing.assert_allclose(float(loss), float(loss_ref), atol=1e-4)


def test_moe_forward_and_training():
    cfg = _cfg(n_experts=4, moe_every=2)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(4))
    assert "router" in params["blocks"][1] and "router" not in params["blocks"][0]
    opt = optax.adamw(1e-2)
    step = jax.jit(model.make_train_step(opt))
    rng = np.random.default_rng(4)
    batch = _batch(rng, 4, 16, cfg.vocab)
    p, s, l0 = step(params, opt.init(params), batch)
    losses = [float(l0)]
    for _ in range(9):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_moe_sharded_matches_single_device():
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, tp=2, ep=2)
    cfg = _cfg(n_experts=4, moe_every=1)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(5))
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
    want = model.apply(params, toks)  # unsharded
    sharded = model.shard_params(params, mesh)
    with jax.set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(sharded, par.shard_batch(mesh, toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pp_pipelined_matches_sequential():
    devs = jax.devices("cpu")[:8]
    mesh = par.make_mesh(devs, dp=2, pp=2, tp=2)
    cfg = _cfg(pp_stages=2, n_microbatches=2)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(6))  # blocks stacked [L, ...]
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
    want = model.apply(params, toks)  # mesh=None: sequential over the stack
    sharded = model.shard_params(params, mesh)
    with jax.set_mesh(mesh):
        got = jax.jit(lambda p, t: model.apply(p, t, mesh))(sharded, par.shard_batch(mesh, toks))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pp_training_reduces_loss():
    devs = jax.devices("cpu")[:4]
    mesh = par.make_mesh(devs, pp=2, tp=2)
    cfg = _cfg(pp_stages=2, n_microbatches=2)
    model = Transformer(cfg)
    params = model.shard_params(model.init(jax.random.PRNGKey(7)), mesh)
    opt = optax.adamw(1e-2)
    rng = np.random.default_rng(7)
    batch = par.shard_batch(mesh, _batch(rng, 4, 16, cfg.vocab))
    with jax.set_mesh(mesh):
        step = jax.jit(model.make_train_step(opt, mesh))
        s = opt.init(params)
        losses = []
        for _ in range(8):
            params, s, loss = step(params, s, batch)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_remat_matches_no_remat():
    cfg = _cfg(remat=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(3))
    toks = jnp.zeros((2, 8), jnp.int32)
    got = model.apply(params, toks)
    want = Transformer(_cfg()).apply(params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_checkpoint_resume_is_deterministic(tmp_path):
    """Checkpoint/resume (SURVEY §5.4 — the subsystem the reference lacks
    entirely): save params+opt_state mid-train, resume in a fresh
    optimizer/step, and the remaining steps must reproduce the original
    run's losses exactly."""
    from cekirdekler_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(7))
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(model.make_train_step(opt))
    rng = np.random.default_rng(7)
    batches = [_batch(rng, 4, 16, cfg.vocab) for _ in range(4)]

    losses = []
    for i, b in enumerate(batches):
        params, opt_state, loss = step(params, opt_state, b)
        losses.append(float(loss))
        if i == 1:
            ckpt.save_pytree(str(tmp_path), 2, {"params": params, "opt": opt_state})

    state = ckpt.load_pytree(
        str(tmp_path), {"params": params, "opt": opt_state}, step=2
    )
    p2, o2 = state["params"], state["opt"]
    resumed = []
    for b in batches[2:]:
        p2, o2, loss = step(p2, o2, b)
        resumed.append(float(loss))
    np.testing.assert_allclose(resumed, losses[2:], rtol=1e-6)
