"""PLANTED BUG (never imported): the PR 6 RFC-8259 leak (json.dumps of
a payload that may carry inf/nan, no guard) plus an artifact dict whose
``headline`` key is not last."""

import json


def export(ratios):
    return json.dumps({"ratios": ratios})  # inf -> bare `Infinity`


def artifact(value):
    result = {
        "metric": "throughput",
        "headline": {"x": value},
        "errors": [],  # headline must be the LAST key
    }
    result["headline"] = {"x": value}
    result["errors"] = []  # assigned after headline: tail contract broken
    return result
