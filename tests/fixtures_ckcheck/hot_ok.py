"""Clean twin of hot_bad: the handle is cached at construction (the
PR 4 cached-handles discipline); the hot path only calls ``.inc()``."""

REGISTRY = None  # stands in for the metrics registry singleton


class Engine:
    def __init__(self):
        self._m_deferred = REGISTRY.counter(
            "ck_deferred_total", "deferrals")

    def defer(self, n):
        self._m_deferred.inc(n)
