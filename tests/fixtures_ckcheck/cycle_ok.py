"""Clean twin of cycle_bad: both flows acquire in ONE documented order
(A before B, always)."""

import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def transfer():
    with _lock_a:
        with _lock_b:
            pass


def refund():
    with _lock_a:
        with _lock_b:
            pass
