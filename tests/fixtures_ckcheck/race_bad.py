"""PLANTED BUG (never imported): the seed-era enqueue/rebalance
lost-update shape — ``pending`` is incremented under the lock on the
worker thread, but the rebalance path does a bare read-modify-write,
so a concurrent increment can be lost."""

import threading


class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        while True:
            with self._lock:
                self.pending += 1

    def rebalance(self):
        self.pending = self.pending // 2  # unlocked RMW: lost update
