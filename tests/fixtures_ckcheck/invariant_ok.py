"""Clean twin of invariant_bad: allow_nan=False fails loudly instead of
emitting invalid JSON, and ``headline`` stays the final key."""

import json


def export(ratios):
    return json.dumps({"ratios": ratios}, allow_nan=False)


def artifact(value):
    result = {
        "metric": "throughput",
        "errors": [],
        "headline": {"x": value},
    }
    result["errors"] = []
    result["headline"] = {"x": value}
    return result
