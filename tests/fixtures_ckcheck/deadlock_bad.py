"""PLANTED BUG (never imported): the PR 6 tracer deadlock shape —
``enable()`` holds the non-reentrant lock and calls ``snapshot()``,
which re-takes it via ``_sync_dropped_metric``."""

import threading


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._dropped = 0

    def _sync_dropped_metric(self):
        with self._lock:
            self._dropped += 1

    def snapshot(self):
        self._sync_dropped_metric()
        return []

    def enable(self):
        with self._lock:
            keep = self.snapshot()  # deadlock: snapshot re-takes _lock
        return keep
