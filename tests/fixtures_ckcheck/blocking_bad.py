"""Planted unbounded-blocking shapes (ckcheck pass 5): a worker loop
and a shutdown path that block forever when their counterpart thread
died — the serve-dispatcher / driver-queue shutdown-hang hazard."""

import queue
import threading


class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._cond = threading.Condition()
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def run(self):
        while True:
            item = self._q.get()  # blocks forever without a sentinel
            if item is None:
                return

    def wait_idle(self):
        with self._cond:
            self._cond.wait()  # no timeout: hangs if run() died

    def shutdown(self):
        self._thread.join()  # no timeout: hangs on a stuck run()
