"""Clean twin of race_bad: every touch of ``pending`` holds the lock."""

import threading


class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        while True:
            with self._lock:
                self.pending += 1

    def rebalance(self):
        with self._lock:
            self.pending = self.pending // 2
