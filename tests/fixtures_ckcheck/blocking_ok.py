"""Clean twin of blocking_bad: bounded waits with loop re-checks, and
an annotated sentinel-terminated daemon loop."""

import queue
import threading


class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._cond = threading.Condition()
        self._idle = False
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def run(self):
        while True:
            # ckcheck: ok sentinel-terminated daemon loop — shutdown()
            # always enqueues the None sentinel
            item = self._q.get()
            if item is None:
                return

    def wait_idle(self):
        with self._cond:
            while not self._idle:
                self._cond.wait(1.0)

    def shutdown(self):
        self._q.put(None)
        self._thread.join(timeout=5.0)
