"""Clean twin of deadlock_bad: the ``_locked`` split — the lock-holding
path calls a helper that asserts the caller holds the lock instead of
re-acquiring it (the actual PR 6 fix shape)."""

import threading


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._dropped = 0

    def _sync_dropped_metric(self):
        with self._lock:
            self._dropped += 1

    def _snapshot_locked_free(self):
        return []

    def snapshot(self):
        self._sync_dropped_metric()
        return self._snapshot_locked_free()

    def enable(self):
        with self._lock:
            keep = self._snapshot_locked_free()
        return keep
