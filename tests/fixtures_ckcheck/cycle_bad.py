"""PLANTED BUG (never imported): ABBA lock-order cycle — ``transfer``
acquires A then B, ``refund`` acquires B then A; interleaved across two
threads each holds what the other wants."""

import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def transfer():
    with _lock_a:
        with _lock_b:
            pass


def refund():
    with _lock_b:
        with _lock_a:
            pass
