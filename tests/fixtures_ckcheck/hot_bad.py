"""PLANTED BUG (never imported): the PR 4/5/6 hot-path shape — a
registry get-or-create inside the deferral fast path (fixed by hand at
least four times before ckcheck)."""

REGISTRY = None  # stands in for the metrics registry singleton


class Engine:
    def defer(self, n):
        # get-or-create per call: dict lookup + possible registry lock
        REGISTRY.counter("ck_deferred_total", "deferrals").inc(n)
