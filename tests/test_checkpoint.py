"""utils/checkpoint.py — its first direct unit tests (ISSUE 13
satellite): atomic save/load round trips for both surfaces, the
torn/corrupt-newest fallback (with the named flight event), the
explicit-step exactness contract, and stale-tmp sweeping."""

import json
import os
import time

import numpy as np
import pytest

from cekirdekler_tpu.obs.flight import FLIGHT
from cekirdekler_tpu.utils import checkpoint as ckpt


def _corrupt_step(root: str, step: int, surface: str = "arrays") -> str:
    d = os.path.join(root, f"step_{step:012d}")
    os.makedirs(d, exist_ok=True)
    name = "arrays.npz" if surface == "arrays" else "manifest.json"
    with open(os.path.join(d, name), "wb") as f:
        f.write(b"this is not a valid file")
    return d


# ---------------------------------------------------------------------------
# round trips
# ---------------------------------------------------------------------------

def test_arrays_round_trip_and_latest_step(tmp_path):
    root = str(tmp_path)
    a = np.arange(16, dtype=np.float32)
    b = np.ones(4, np.int64)
    ckpt.save_arrays(root, 3, {"a": a, "b": b})
    ckpt.save_arrays(root, 7, {"a": a * 2, "b": b * 2})
    assert ckpt.latest_step(root) == 7
    out = ckpt.load_arrays(root)
    np.testing.assert_array_equal(out["a"], a * 2)
    np.testing.assert_array_equal(out["b"], b * 2)
    old = ckpt.load_arrays(root, step=3)
    np.testing.assert_array_equal(old["a"], a)


def test_pytree_round_trip(tmp_path):
    root = str(tmp_path)
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": [np.zeros(3, np.float32), np.float32(2.5)]}
    ckpt.save_pytree(root, 1, tree)
    out = ckpt.load_pytree(root, tree)
    np.testing.assert_array_equal(out["w"], tree["w"])
    np.testing.assert_array_equal(out["b"][0], tree["b"][0])
    assert float(out["b"][1]) == 2.5


def test_empty_root_raises_file_not_found(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.load_arrays(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        ckpt.load_pytree(str(tmp_path), {"x": np.zeros(1)})


# ---------------------------------------------------------------------------
# torn-newest fallback
# ---------------------------------------------------------------------------

def test_arrays_torn_newest_falls_back_with_flight_event(tmp_path):
    root = str(tmp_path)
    ckpt.save_arrays(root, 1, {"a": np.full(4, 7.0, np.float32)})
    _corrupt_step(root, 2)
    before = len([e for e in FLIGHT.snapshot()
                  if e.kind == "checkpoint-fallback"])
    out = ckpt.load_arrays(root)
    np.testing.assert_array_equal(out["a"], 7.0)
    evs = [e for e in FLIGHT.snapshot() if e.kind == "checkpoint-fallback"]
    assert len(evs) == before + 1
    assert evs[-1].fields["bad_step"] == 2
    assert evs[-1].fields["fell_back_to"] == 1


def test_arrays_all_steps_torn_raises(tmp_path):
    root = str(tmp_path)
    _corrupt_step(root, 1)
    _corrupt_step(root, 2)
    with pytest.raises(Exception):
        ckpt.load_arrays(root)


def test_arrays_explicit_step_still_raises_on_corruption(tmp_path):
    """An explicit step pins exactness: the caller asked for THAT
    state, silently handing back an older one would be worse."""
    root = str(tmp_path)
    ckpt.save_arrays(root, 1, {"a": np.zeros(2, np.float32)})
    _corrupt_step(root, 2)
    with pytest.raises(Exception):
        ckpt.load_arrays(root, step=2)


def test_pytree_torn_newest_falls_back(tmp_path):
    root = str(tmp_path)
    tree = {"w": np.full(3, 4.0, np.float32)}
    ckpt.save_pytree(root, 5, tree)
    _corrupt_step(root, 6, surface="manifest")
    out = ckpt.load_pytree(root, tree)
    np.testing.assert_array_equal(out["w"], 4.0)


def test_pytree_leaf_count_mismatch_is_a_caller_error(tmp_path):
    """A COMPLETE dir with the wrong leaf count is the wrong 'like'
    tree, not a torn checkpoint — falling back would silently load a
    different model."""
    root = str(tmp_path)
    ckpt.save_pytree(root, 1, {"w": np.zeros(2, np.float32)})
    ckpt.save_pytree(root, 2, {"w": np.zeros(2, np.float32)})
    with pytest.raises(ValueError):
        ckpt.load_pytree(root, {"w": np.zeros(2, np.float32),
                                "b": np.zeros(1, np.float32)})


# ---------------------------------------------------------------------------
# stale tmp sweeping
# ---------------------------------------------------------------------------

def test_stale_tmp_dirs_swept_on_next_save(tmp_path):
    root = str(tmp_path)
    stale = os.path.join(root, ".ckpt_tmp_deadwriter")
    os.makedirs(stale)
    with open(os.path.join(stale, "leaf_00000.npy"), "wb") as f:
        f.write(b"abandoned")
    past = time.time() - 2 * ckpt.TMP_SWEEP_AGE_S
    os.utime(stale, (past, past))
    fresh = os.path.join(root, ".ckpt_tmp_livewriter")
    os.makedirs(fresh)  # a concurrent writer's seconds-old tmp
    ckpt.save_arrays(root, 1, {"a": np.zeros(2, np.float32)})
    assert not os.path.isdir(stale), "stale tmp survived the sweep"
    assert os.path.isdir(fresh), "the age gate must spare live writers"
    # the sweep itself is evidence
    assert any(e.kind == "checkpoint-sweep" for e in FLIGHT.snapshot())


def test_atomic_write_failure_leaves_no_tmp(tmp_path):
    root = str(tmp_path)

    class Boom(Exception):
        pass

    def bad_write(tmp):
        raise Boom()

    with pytest.raises(Boom):
        ckpt._atomic_write(root, 1, bad_write)
    assert not [n for n in os.listdir(root) if n.startswith(".ckpt_tmp_")]
    assert ckpt.latest_step(root) is None


def test_manifest_is_strict_json(tmp_path):
    """The manifest must stay loadable by strict parsers (numpy step
    scalars arrive from training loops)."""
    root = str(tmp_path)
    ckpt.save_pytree(root, np.int64(4), {"w": np.zeros(2, np.float32)})
    d = os.path.join(root, f"step_{4:012d}")
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["step"] == 4 and manifest["n_leaves"] == 1
