"""Worker process for the kill-and-rejoin DCN job (tests/test_dcn.py →
``test_kill_and_rejoin_converges_bit_identical``).  Run as
``python tests/_dcn_elastic_worker.py <pid> <nproc> <port> <counts>
<ckpt_root> <phase> <windows> <kill_after>``.

The job accumulates ``y += 2·x`` per window through the DCN tier,
checkpointing every completed window via ``cluster/elastic.py``
(process 0 writes, everyone barriers — atomic tmp+rename, so a kill
can never leave a half-window).

Phases:

- ``first`` — runs windows 1..kill_after, then every process dies via
  ``os._exit(EXIT_PREEMPTED)`` — a preemption: no cleanup, no flush,
  no dispose.  The parent then plants a TORN newest step dir so the
  resume also exercises the corrupt-checkpoint fallback.
- ``rejoin`` — a NEW job (different port, possibly different
  per-process device counts = a membership change): resumes from the
  last COMPLETE window (``DistributedAccelerator.resume_elastic`` —
  falls back past the torn step), reconciles membership (recorded
  ``member-leave``/``member-join`` decisions with the new LCM-step
  re-split), runs the remaining windows, and asserts the final image
  is BIT-IDENTICAL to the undisturbed run's (the host-side reference
  applies the same per-element f32 op sequence — window count exact,
  no lost or duplicated window updates).  The spilled decision log
  (CK_DECISION_LOG) must replay green through ``verify_records``,
  membership transitions included.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SRC = """
__kernel void accum(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = y[i] + a * x[i];
}
"""

LOCAL_RANGE = 64
N = 4096
A = 2.0
EXIT_PREEMPTED = 17


def reference_image(windows: int) -> np.ndarray:
    """The undisturbed run's image, computed with the identical
    per-element f32 op sequence (y starts at 1, gains 2x per window) —
    bit-identical to any correct run regardless of partitioning."""
    x = np.arange(N, dtype=np.float32)
    y = np.ones(N, np.float32)
    for _ in range(windows):
        y = (y + np.float32(A) * x).astype(np.float32)
    return y


def main(pid: int, nproc: int, port: int, counts: list[int],
         ckpt_root: str, phase: str, windows: int, kill_after: int) -> None:
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.cluster.dcn import DistributedAccelerator, initialize

    initialize(f"localhost:{port}", nproc, pid)
    import jax

    assert jax.local_device_count() == counts[pid]
    acc = DistributedAccelerator()
    acc.setup_nodes(SRC)
    assert acc.proc_device_counts == counts, acc.proc_device_counts

    x = ClArray(np.arange(N, dtype=np.float32), partial_read=True,
                read_only=True)
    y = ClArray(np.ones(N, np.float32), partial_read=True)
    start_window = 0

    if phase == "rejoin":
        state = acc.resume_elastic(ckpt_root, LOCAL_RANGE, total=N)
        assert state is not None, "rejoin found no checkpoint"
        # the parent planted a torn step at kill_after+1: the resume
        # must have fallen back to the last COMPLETE window
        assert state["window"] == kill_after, state["window"]
        start_window = state["window"]
        y.host()[:] = state["arrays"]["y"]
        m = state["membership"]
        assert m.epoch >= 1
        if state["member_steps"] != [c * LOCAL_RANGE for c in counts]:
            # the roster changed across the restart: transitions were
            # recorded (epoch moved past the establish)
            assert m.epoch > 1, m.snapshot()
    else:
        # fresh start still records its membership epoch
        acc.establish_membership(LOCAL_RANGE)

    for w in range(start_window + 1, windows + 1):
        acc.compute(["accum"], [x, y], compute_id=1, global_range=N,
                    local_range=LOCAL_RANGE, values=(A,))
        acc.checkpoint_window(ckpt_root, w, {"y": np.asarray(y)},
                              LOCAL_RANGE)
        acc.barrier(f"ckpt_{w}")
        if phase == "first" and w >= kill_after:
            # the preemption: die with no cleanup whatsoever
            sys.stdout.flush()
            os._exit(EXIT_PREEMPTED)

    np.testing.assert_array_equal(np.asarray(y), reference_image(windows))

    # the recorded decisions — membership transitions, checkpoint
    # restore, the balancer's re-splits — must replay bit-identically
    from cekirdekler_tpu.obs.decisions import DECISIONS, load_decision_log
    from cekirdekler_tpu.obs.replay import verify_records

    spill = DECISIONS.maybe_spill(force=True)
    if spill:
        rows = load_decision_log(spill)
        verdict = verify_records(rows)
        assert verdict["ok"], verdict["first_divergence"]
        kinds = {r.kind for r in rows}
        if phase == "rejoin":
            assert "checkpoint-restore" in kinds, kinds
            if counts != [2, 2]:  # the membership-change variant
                assert "member-leave" in kinds or "member-join" in kinds, \
                    kinds
        print(f"DCN_ELASTIC_REPLAY pid={pid} ok={verdict['ok']} "
              f"replayed={verdict['replayed']}", flush=True)
    print(f"DCN_ELASTIC_OK pid={pid} phase={phase} windows={windows}",
          flush=True)
    acc.dispose()


if __name__ == "__main__":
    main(
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
        [int(c) for c in sys.argv[4].split(",")],
        sys.argv[5], sys.argv[6], int(sys.argv[7]), int(sys.argv[8]),
    )
