"""Decision provenance (obs/decisions.py + obs/replay.py +
tools/ckreplay.py): the event-sourced controller decision log, its
replay-verify / what-if / explain consumers, the golden-log fixtures,
and the live integration (workload -> spill -> `ckreplay verify` exit 0,
`/decisionz`, postmortem v2).

Budget discipline mirrors tests/test_obs.py: the decision log is an
always-on family, so its disabled cost is pinned to the PR 4 budget
(< 100 ns marginal over the bare method-call floor), and a FULL ring
must never block an append (maxlen eviction, no lock)."""

import importlib.util
import json
import os
import sys
import threading
import time
import urllib.request
from functools import partial

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.core import balance as balance_mod
from cekirdekler_tpu.core.balance import (
    BalanceHistory,
    BalanceState,
    equal_split,
    load_balance,
)
from cekirdekler_tpu.core.stream import TransferTuner
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.obs import replay as replay_mod
from cekirdekler_tpu.obs.decisions import (
    DECISION_KINDS,
    DECISIONS,
    REPLAYABLE_KINDS,
    DecisionLog,
    DecisionRecord,
    load_decision_log,
)
from cekirdekler_tpu.obs.flight import dump_postmortem, load_postmortem
from cekirdekler_tpu.obs.health import HealthMonitor, evaluate_window
from cekirdekler_tpu.utils.jsonsafe import json_safe

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "fixtures_decisions", "golden_rebalance.jsonl")
GOLDEN_HETERO = os.path.join(
    HERE, "fixtures_decisions", "golden_hetero_prior.jsonl")

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


def _load_tool(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


ckreplay = _load_tool("ck_replay_tool", "tools/ckreplay.py")


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


def _since(mark: int) -> list:
    """Records the global log gained after seq ``mark`` — the isolation
    idiom for a shared process-global ring."""
    return [r for r in DECISIONS.snapshot() if r.seq > mark]


def _mark() -> int:
    recs = DECISIONS.snapshot()
    return recs[-1].seq if recs else 0


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(json_safe(r.to_row()), allow_nan=False)
                    + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# ring semantics + the overhead/never-blocks budget
# ---------------------------------------------------------------------------

def test_ring_bounded_seq_monotone():
    log = DecisionLog(capacity=32)
    for i in range(100):
        log.record("load-balance", {"i": i}, {})
    recs = log.snapshot()
    assert len(recs) == 32
    assert log.total_recorded == 100
    seqs = [r.seq for r in recs]
    assert seqs == sorted(seqs) and len(set(seqs)) == 32
    assert recs[-1].inputs["i"] == 99
    log.clear()
    assert log.snapshot() == [] and log.total_recorded == 0


class _NoopShape:
    """Same call shape as DecisionLog.record with the body removed —
    the interpreter's bound-method floor."""

    def record(self, kind, inputs=None, outputs=None):
        pass


def _best_pair(fn_floor, fn_probe, n=100_000, trials=10):
    best_f = best_p = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(n):
            fn_floor()
        best_f = min(best_f, (time.perf_counter() - t0) / n)
        t0 = time.perf_counter()
        for _ in range(n):
            fn_probe()
        best_p = min(best_p, (time.perf_counter() - t0) / n)
    return best_f, best_p


def test_disabled_record_overhead_under_budget():
    """The PR 4 pin applied to the new always-on family: disabled
    record() costs < 100 ns marginal over the identical no-op call."""
    log = DecisionLog()
    log.enabled = False
    noop = _NoopShape()
    floor, per = _best_pair(
        partial(noop.record, "probe"), partial(log.record, "probe"))
    net = per - floor
    assert net < 100e-9, (
        f"disabled record adds {net*1e9:.0f} ns over the call floor "
        f"({per*1e9:.0f} ns total, floor {floor*1e9:.0f} ns)")
    assert per < 1e-6
    assert log.total_recorded == 0


def test_full_ring_never_blocks_appends():
    """Property: appending to a FULL ring is eviction, not blocking —
    4 concurrent writers push 20k records each through a 64-slot ring
    with unique strictly-orderable seqs and no deadlock/timeout."""
    log = DecisionLog(capacity=64)
    for i in range(64):
        log.record("load-balance", {"warm": i}, {})
    assert len(log.snapshot()) == 64  # full from here on
    errs: list = []

    def writer():
        try:
            for i in range(20_000):
                log.record("transfer-choose", {"i": i}, {})
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(4)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    assert all(not t.is_alive() for t in threads), "an append blocked"
    assert time.perf_counter() - t0 < 30.0
    recs = log.snapshot()
    assert len(recs) == 64
    seqs = [r.seq for r in recs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


# ---------------------------------------------------------------------------
# jsonl spill: save/load round trip, tmp+rename arming, throttle
# ---------------------------------------------------------------------------

def test_jsonl_round_trip_and_torn_tail(tmp_path):
    log = DecisionLog()
    log.record("load-balance", {"benchmarks": [1.5, 2.5]}, {"ranges": [64]})
    log.record("transfer-choose", {"kernel_key": ["inc", []]}, {"chunks": 4})
    p = str(tmp_path / "log.jsonl")
    log.save_jsonl(p)
    back = load_decision_log(p)
    assert [r.to_row() for r in back] == \
        [r.to_row() for r in log.snapshot()]
    # torn tail: a dying process's half-written last line is skipped
    with open(p, "a") as f:
        f.write('{"seq": 999, "kind": "load-bal')
    assert [r.seq for r in load_decision_log(p)] == \
        [r.seq for r in back]


def test_env_spill_is_armed_throttled_and_atomic(tmp_path, monkeypatch):
    p = str(tmp_path / "spill.jsonl")
    log = DecisionLog(spill_interval_s=3600.0)
    # unarmed: nothing touches disk
    log.record("load-balance", {}, {})
    assert log.maybe_spill() is None and not os.path.exists(p)
    # review finding: a SET-BUT-EMPTY env var is "off" under ONE
    # truthiness rule — the buffer must not accumulate rows no spill
    # site would ever write
    monkeypatch.setenv("CK_DECISION_LOG", "")
    log.record("load-balance", {}, {})
    assert len(log._spill) == 0 and log.maybe_spill() is None
    monkeypatch.setenv("CK_DECISION_LOG", p)
    log.record("load-balance", {"a": 1}, {"ranges": [8]})
    assert log.maybe_spill() == p  # first spill goes through
    assert log.maybe_spill() is None  # throttled inside the interval
    assert log.maybe_spill(force=True) == p  # dispose path
    assert not os.path.exists(p + ".tmp")  # tmp+rename left no turd
    rows = load_decision_log(p)
    assert rows and rows[-1].outputs == {"ranges": [8]}
    with open(p) as f:
        header = json.loads(f.readline())
    assert header["schema"] == "ck-decision-log-v1"


def test_armed_spills_append_incrementally_and_keep_evicted_rows(
        tmp_path, monkeypatch):
    """Review finding: a sync-point spill must cost O(new rows), not a
    rewrite of the whole history — later spills APPEND past the
    persisted watermark, and rows the bounded buffer later evicts stay
    on disk (the file is a SUPERSET of the buffer)."""
    p = str(tmp_path / "incr.jsonl")
    monkeypatch.setenv("CK_DECISION_LOG", p)
    log = DecisionLog()
    log.record("load-balance", {"i": 0}, {})
    assert log.spill() == p
    size1 = os.path.getsize(p)
    for i in range(1, 4):
        log.record("load-balance", {"i": i}, {})
    log.spill()
    # appended, not rewritten: the original bytes are a prefix
    assert os.path.getsize(p) > size1
    with open(p) as f:
        assert json.loads(f.readline())["schema"] == "ck-decision-log-v1"
    rows = load_decision_log(p)
    assert [r.inputs["i"] for r in rows] == [0, 1, 2, 3]
    # no duplicate seqs across spill boundaries
    assert len({r.seq for r in rows}) == len(rows)
    # eviction (buffer wraps) cannot lose already-persisted rows
    log2 = DecisionLog()
    p2 = str(tmp_path / "evict.jsonl")
    monkeypatch.setenv("CK_DECISION_LOG", p2)
    log2._spill = type(log2._spill)(maxlen=2)
    log2.record("load-balance", {"i": 0}, {})
    log2.spill()
    for i in range(1, 5):
        log2.record("load-balance", {"i": i}, {})
    log2.spill()  # buffer holds only i=3,4 now; file kept i=0
    kept = [r.inputs["i"] for r in load_decision_log(p2)]
    assert kept[0] == 0 and kept[-1] == 4


def test_spill_path_directory_is_per_process(tmp_path, monkeypatch):
    """Review finding: N processes sharing one armed env (a DCN job,
    bench's benchrig child) must not last-writer-win one file — a
    directory value resolves to ck_decisions_<pid>.jsonl inside it."""
    d = str(tmp_path / "logs")
    os.makedirs(d)
    monkeypatch.setenv("CK_DECISION_LOG", d)
    log = DecisionLog()
    resolved = log.spill_path()
    assert resolved == os.path.join(
        d, f"ck_decisions_{os.getpid()}.jsonl")
    log.record("load-balance", {}, {})
    assert log.spill() == resolved and os.path.exists(resolved)


# ---------------------------------------------------------------------------
# load_balance emission: complete inputs, actions, floor binding
# ---------------------------------------------------------------------------

def _run_chain(steps=10, jump=True, cid=0,
               rates=(0.0010, 0.0040, 0.0008),
               t_rates=(0.0002, 0.0002, 0.0030),
               total=8192, step=64):
    """The demo generator's synthetic convergence, inline (unequal
    lanes; lane 2's link wall 3x its compute — the floor binds)."""
    n = len(rates)
    ranges = equal_split(total, n, step)
    hist = BalanceHistory(weighted=True)
    state = BalanceState()
    for _ in range(steps):
        bench = [rates[i] * max(ranges[i], step) for i in range(n)]
        transfer = [t_rates[i] * max(ranges[i], step) for i in range(n)]
        ranges = load_balance(bench, ranges, total, step, hist,
                              state=state, transfer_ms=transfer,
                              jump_start=jump, cid=cid)
    return ranges


def test_load_balance_records_complete_inputs_and_actions():
    mark = _mark()
    _run_chain(steps=10, jump=True, cid=901)
    recs = [r for r in _since(mark) if r.kind == "load-balance"
            and r.inputs.get("cid") == 901]
    assert len(recs) == 10
    inp = recs[0].inputs
    for key in ("benchmarks", "ranges", "total", "step", "damping",
                "transfer_ms", "jump_start", "cid", "history", "carry",
                "state"):
        assert key in inp, key
    assert inp["state"] == {"cont": [], "prev_delta": [], "damp": [],
                            "jumped": False, "warm": False}
    actions = [r.outputs["action"] for r in recs]
    # first measured rebalance arms (damped), second jumps, the
    # converged tail freezes
    assert actions[0] == "damped" and recs[0].outputs["jump_armed"]
    assert actions[1] == "jump"
    assert "freeze" in actions[2:]
    # the transfer floor BINDS on lane 2 (link 3x compute) and is
    # recorded as such, with the effective time equal to the floor
    jumped = recs[1]
    assert jumped.outputs["floor_bound"][2] is True
    assert jumped.outputs["effective_ms"][2] == \
        pytest.approx(jumped.inputs["transfer_ms"][2])
    # freeze records carry the quantization-floor evidence
    fz = next(r for r in recs if r.outputs["action"] == "freeze")
    assert fz.outputs["freeze"]["one_step_work_ms"] > 0
    assert fz.outputs["ranges"] == fz.inputs["ranges"]


# ---------------------------------------------------------------------------
# replay-verify: golden fixture, perturbed knobs, tampered outputs
# ---------------------------------------------------------------------------

def test_golden_fixture_replays_bit_identically():
    """The checked-in multi-lane rebalance log (a jump-start chain AND
    a damped chain, with a transfer-floor-bound lane) re-executes
    bit-identically — recorded logs ARE golden tests of the
    balancer."""
    rows = load_decision_log(GOLDEN)
    assert len(rows) >= 20
    assert any(r.outputs.get("action") == "jump" for r in rows)
    assert any(any(r.outputs.get("floor_bound") or [])
               for r in rows)
    verdict = replay_mod.verify_records(rows)
    assert verdict["ok"], verdict["first_divergence"]
    assert verdict["replayed"] == len(rows)
    assert verdict["first_divergence"] is None


def test_golden_hetero_prior_fixture_replays_and_whatif_contrast():
    """ISSUE 20 golden fixture: a prior-seeded heterogeneous chain (one
    fast + one 100x-slow lane) replays bit-identically, the chain is
    genuinely seeded FROM the prior-split record, and the what-if
    counterfactual quantifies the prior's win — prior-on converges in
    at most HALF the iterations of prior-off on the same recorded
    rates (the acceptance bar: the seed starts the chain already at
    the rate-implied split, so the damped iteration has nothing left
    to move)."""
    rows = load_decision_log(GOLDEN_HETERO)
    assert any(r.kind == "prior-split" for r in rows)
    verdict = replay_mod.verify_records(rows)
    assert verdict["ok"], verdict["first_divergence"]
    assert verdict["replayed"] == len(rows)
    # the first balance step starts FROM the prior-split output
    seed = next(r for r in rows if r.kind == "prior-split")
    first_lb = next(r for r in rows if r.kind == "load-balance")
    assert first_lb.inputs["ranges"] == seed.outputs["ranges"]
    assert first_lb.inputs["rate_prior"] == seed.inputs["priors"]
    # counterfactual: filing the prior off restarts from equal_split
    wi = replay_mod.whatif(rows, {"rate_prior": False})
    on = wi["factual"]["iterations_to_converge"]
    off = wi["counterfactual"]["iterations_to_converge"]
    assert wi["factual"]["converged"] and wi["counterfactual"]["converged"]
    assert off >= 1, "prior-off control never had to move?"
    assert on <= off / 2, (on, off)
    # both land on the SAME split — the prior buys convergence speed,
    # never a different answer
    assert wi["final_split_l1"] == 0


def test_perturbed_knob_fails_naming_first_divergent_seq(monkeypatch):
    """The acceptance contract: someone edits a balancer knob — replay
    of an old log must fail and NAME the first divergent seq."""
    rows = load_decision_log(GOLDEN)
    monkeypatch.setattr(balance_mod, "FREEZE_MARGIN", 0.3)
    verdict = replay_mod.verify_records(rows)
    assert not verdict["ok"]
    first = verdict["first_divergence"]
    assert first is not None and isinstance(first["seq"], int)
    assert first["kind"] == "load-balance"
    # it is genuinely the FIRST divergent record
    assert first["seq"] == min(d["seq"] for d in verdict["divergences"])
    # a second, orthogonal knob class: the adaptive-damping ceiling
    monkeypatch.setattr(balance_mod, "FREEZE_MARGIN", 0.6)
    monkeypatch.setattr(balance_mod, "DAMP_MAX_SMOOTHED", 0.5)
    v2 = replay_mod.verify_records(rows)
    assert not v2["ok"] and v2["first_divergence"]["seq"] > 0


def test_ckreplay_cli_verify_exit_codes(capsys, monkeypatch):
    assert ckreplay.main(["verify", GOLDEN]) == 0
    out = capsys.readouterr().out
    assert "OK" in out and "bit-identically" in out
    monkeypatch.setattr(balance_mod, "FREEZE_MARGIN", 0.3)
    assert ckreplay.main(["verify", GOLDEN]) == 1
    out = capsys.readouterr().out
    assert "first divergent seq=" in out


def test_tampered_outputs_are_divergence():
    rows = [r.to_row() for r in load_decision_log(GOLDEN)]
    tampered = json.loads(json.dumps(rows))
    victim = next(r for r in tampered if r["kind"] == "load-balance")
    victim["outputs"]["ranges"][0] += victim["inputs"]["step"]
    victim["outputs"]["ranges"][1] -= victim["inputs"]["step"]
    verdict = replay_mod.verify_records(tampered)
    assert not verdict["ok"]
    assert verdict["first_divergence"]["seq"] == victim["seq"]
    assert "ranges" in verdict["first_divergence"]["mismatch"]


def test_replay_does_not_rerecord(monkeypatch):
    rows = load_decision_log(GOLDEN)
    mark = _mark()
    assert replay_mod.verify_records(rows)["ok"]
    assert _since(mark) == [], "replay re-recorded into the live log"
    assert DECISIONS.enabled, "quiesce failed to restore"


def test_overlapping_replays_restore_only_at_outermost_exit():
    """Review finding: two concurrent replays share the process-global
    quiesce — the first to finish must NOT re-enable recording while
    the second is still re-executing (its replayed calls would land in
    the live ring as echoes)."""
    rows = load_decision_log(GOLDEN)
    mark = _mark()
    errs: list = []

    def worker():
        try:
            for _ in range(5):
                assert replay_mod.verify_records(rows)["ok"]
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
    assert DECISIONS.enabled, "outermost restore lost"
    assert _since(mark) == [], "a replay echo leaked into the live log"


def test_divergence_counts_cover_the_whole_log(monkeypatch):
    """Review finding: the divergence-DETAIL cap must not truncate the
    scan — a fully-divergent long log still reports replayed == every
    replayable record, with the overflow flagged."""
    rows = load_decision_log(GOLDEN)
    monkeypatch.setattr(balance_mod, "FREEZE_MARGIN", 0.3)
    v = replay_mod.verify_records(rows, max_divergences=2)
    assert v["replayed"] == len(rows)
    assert v["divergent"] > 2 and len(v["divergences"]) == 2
    assert v["divergences_truncated"] is True


# ---------------------------------------------------------------------------
# what-if: counterfactual chained runs
# ---------------------------------------------------------------------------

def test_whatif_jump_off_strictly_slower():
    """The acceptance pin: disabling jump-start on the jump-started
    recorded log brings back the damped crawl — strictly MORE
    iterations to converge than the factual run."""
    rows = load_decision_log(GOLDEN)
    rep = replay_mod.whatif(rows, {"jump_start": False}, cid=0)
    f, c = rep["factual"], rep["counterfactual"]
    assert f["converged"] and c["converged"]
    assert c["iterations_to_converge"] > f["iterations_to_converge"]


def test_whatif_factual_reproduces_recorded_trajectory():
    """The rate-model simulator run WITHOUT overrides must retrace the
    log exactly while the log lasts (the consistency anchor that makes
    the counterfactual comparison meaningful)."""
    rows = load_decision_log(GOLDEN)
    recs = [r.to_row() for r in rows
            if r.kind == "load-balance" and r.inputs.get("cid") == 0]
    sim = replay_mod.simulate_balance(recs, {})
    recorded = [list(r["outputs"]["ranges"]) for r in recs]
    assert sim["trajectory"][1:len(recs) + 1] == recorded


def test_whatif_transfer_floor_off_moves_the_split():
    """Lane 2's split share is floor-limited; removing the floor must
    hand it more items (its compute rate is the fastest)."""
    rows = load_decision_log(GOLDEN)
    rep = replay_mod.whatif(rows, {"transfer_floor": False}, cid=0)
    assert rep["final_split_l1"] > 0
    assert rep["counterfactual"]["final_ranges"][2] > \
        rep["factual"]["final_ranges"][2]


def test_whatif_unknown_knob_refused():
    rows = load_decision_log(GOLDEN)
    with pytest.raises(ValueError, match="unknown what-if knob"):
        replay_mod.whatif(rows, {"warp_speed": 9})
    with pytest.raises(SystemExit):
        ckreplay.parse_overrides("warp_speed=9")
    assert ckreplay.parse_overrides(
        "damping=0.1,jump_start=off,transfer_floor=on,overhead_ms=2") == {
        "damping": 0.1, "jump_start": False, "transfer_floor": True,
        "overhead_ms": 2.0}
    # review finding: coercion is typed PER KNOB — a float knob given
    # on/off must be rejected (not silently become 0.0), and a bool
    # knob given a number must not float-parse into truthy-on
    with pytest.raises(SystemExit):
        ckreplay.parse_overrides("overhead_ms=off")
    with pytest.raises(SystemExit):
        ckreplay.parse_overrides("damping=on")
    with pytest.raises(SystemExit):
        ckreplay.parse_overrides("jump_start=0.3")


# ---------------------------------------------------------------------------
# explain: the causality table
# ---------------------------------------------------------------------------

def test_explain_latest_causality_table():
    rows = load_decision_log(GOLDEN)
    doc = replay_mod.explain_latest(rows, cid=0)
    assert doc["action"] == "freeze" and "freeze" in doc
    assert len(doc["lanes"]) == 3
    lane2 = doc["lanes"][2]
    # the link-bound lane: floor margin positive (the floor BINDS),
    # effective time = the transfer wall, residue ~0 on a frozen split
    assert lane2["floor_bound"] is True
    assert lane2["floor_margin_ms"] > 0
    assert lane2["effective_ms"] == pytest.approx(lane2["transfer_ms"])
    assert doc["lanes"][0]["floor_margin_ms"] < 0  # slack lane
    # a DAMPED iteration names the per-lane binding input
    damped = next(r for r in rows if r.kind == "load-balance"
                  and r.outputs.get("action") == "damped"
                  and any(r.outputs.get("floor_bound") or []))
    d2 = replay_mod.explain_balance(damped)
    bindings = [ln["binding"] for ln in d2["lanes"]]
    assert "transfer floor (link-bound)" in bindings
    assert any(b.startswith("compute bench") for b in bindings)
    # the text renderer carries every lane row
    text = ckreplay.render_explain(d2)
    assert "binding" in text and "transfer floor" in text


def test_ckreplay_cli_explain_and_whatif(capsys):
    assert ckreplay.main(["explain", GOLDEN, "--cid", "0"]) == 0
    out = capsys.readouterr().out
    assert "action=freeze" in out and "quantization floor" in out
    assert ckreplay.main(
        ["whatif", GOLDEN, "--set", "jump_start=off", "--cid", "0"]) == 0
    out = capsys.readouterr().out
    assert "LATER" in out  # strictly-slower counterfactual, rendered


# ---------------------------------------------------------------------------
# transfer tuner decisions: choose + observe replay
# ---------------------------------------------------------------------------

def test_transfer_choose_records_and_replays():
    mark = _mark()
    t = TransferTuner()
    key = ("inc", ())
    # measuring run -> fenced observation -> model choice
    assert t.choose(0, key, 1 << 20, 16) == 1
    t.observe(0, key, 1 << 20, 4.0, 1.0, 4.0, chunks=1, fenced=True)
    c = t.choose(0, key, 1 << 20, 16)
    assert c > 1  # transfer-dominated: chunking wins
    # a no-compute key models straight from the duplex seed
    t.seed_link(1, 5.0, 5.0)
    c2 = t.choose(1, "flush-d2h", 1 << 22, 64, has_compute=False)
    assert c2 > 1
    recs = _since(mark)
    chooses = [r for r in recs if r.kind == "transfer-choose"]
    observes = [r for r in recs if r.kind == "transfer-observe"]
    assert len(chooses) == 3 and len(observes) == 1
    whys = [r.outputs["why"] for r in chooses]
    assert whys == ["measuring-run", "model", "model"]
    assert chooses[2].inputs["seed"] == {
        "h2d_ms_per_mib": 5.0, "d2h_ms_per_mib": 5.0}
    verdict = replay_mod.verify_records(recs)
    assert verdict["ok"], verdict["first_divergence"]


def test_transfer_observe_replay_exact_ema_arithmetic():
    """The EMA/clamp/overhead update arithmetic replays to exact float
    equality from the recorded pre-state (fenced EMA, unfenced clamp,
    chunked overhead-learning — all three update classes)."""
    mark = _mark()
    t = TransferTuner()
    key = ("nbody", (("dt", 0.01),))
    t.observe(0, key, 1 << 21, 8.0, 2.0, 8.0, chunks=1, fenced=True)
    t.observe(0, key, 1 << 21, 7.0, 2.5, 6.0, chunks=1, fenced=True)  # EMA
    t.observe(0, key, 1 << 21, 0.0, 0.0, 5.0, chunks=1,
              wall_ms=5.0, fenced=False)                 # clamp-only
    t.observe(0, key, 1 << 21, 1.0, 0.5, 1.0, chunks=4,
              wall_ms=9.0)                               # overhead learn
    recs = [r for r in _since(mark) if r.kind == "transfer-observe"]
    assert len(recs) == 4
    assert recs[-1].outputs["overhead_ms"] != \
        recs[0].outputs["overhead_ms"]
    verdict = replay_mod.verify_records(recs)
    assert verdict["ok"], verdict["first_divergence"]
    # tamper one stored float by 1 ulp-scale nudge: exactness means it
    # MUST diverge
    rows = [r.to_row() for r in recs]
    rows[1] = json.loads(json.dumps(rows[1]))
    rows[1]["outputs"]["obs"]["u_ms"] += 1e-9
    assert not replay_mod.verify_records(rows)["ok"]


# ---------------------------------------------------------------------------
# health decisions: pure transition, flip records, drain advisory
# ---------------------------------------------------------------------------

def test_evaluate_window_pure_transitions():
    kw = dict(threshold=3.0, confirm=2, release=1.5)
    assert evaluate_window(1.0, None, streak=0, degraded=False, **kw) == {
        "flagged": False, "ratio": None, "streak": 0, "degraded": False}
    r = evaluate_window(9.0, 1.0, streak=1, degraded=False, **kw)
    assert r == {"flagged": True, "ratio": 9.0, "streak": 2,
                 "degraded": True}
    # hysteresis: above release stays degraded, at/below releases
    assert evaluate_window(2.0, 1.0, streak=2, degraded=True,
                           **kw)["degraded"] is True
    assert evaluate_window(1.4, 1.0, streak=2, degraded=True,
                           **kw)["degraded"] is False
    # zero baseline: material sample strikes, ratio stays JSON-safe
    z = evaluate_window(0.5, 0.0, streak=0, degraded=False, **kw)
    assert z["flagged"] and z["ratio"] is None


def test_health_flip_records_decision_and_replays():
    mark = _mark()
    hm = HealthMonitor(threshold=3.0, window=4, confirm=2, min_history=2)
    steady = [0.010] * hm.window
    for _ in range(hm.min_history + 1):
        for v in steady:
            hm.observe(0, "fence", v)
    for _ in range(hm.confirm):
        for v in [0.08] * hm.window:
            hm.observe(0, "fence", v)
    assert hm.verdict(0) == "degraded"
    flips = [r for r in _since(mark) if r.kind == "health-verdict"]
    # ok -> suspect -> degraded: two flips, with the full transition
    # inputs recorded
    assert [r.outputs["state"] for r in flips] == ["suspect", "degraded"]
    assert flips[0].inputs["signal"] == "fence"
    assert flips[0].inputs["baseline_s"] == pytest.approx(0.010)
    verdict = replay_mod.verify_records(flips)
    assert verdict["ok"], verdict["first_divergence"]
    # the advisory records provenance too
    assert hm.suggest_drain() == [0]
    adv = [r for r in _since(mark) if r.kind == "drain-advisory"]
    assert adv and adv[-1].outputs["drain"] == [0]
    assert adv[-1].inputs["lanes"]["0"]["verdict"] == "degraded"


# ---------------------------------------------------------------------------
# live integration: workload -> records -> spill -> verify exit 0,
# /decisionz, fused decisions
# ---------------------------------------------------------------------------

def test_live_workload_log_verifies_and_serves_decisionz(
        devs, tmp_path):
    """The acceptance drive: a live multi-lane enqueue workload records
    decisions; the spilled log replay-verifies to exit 0 through the
    real CLI, and /decisionz renders the causality table."""
    mark = _mark()
    cr = NumberCruncher(devs.subset(2), INC)
    srv = cr.serve_debug(port=0)
    n = 4096
    a = ClArray(np.zeros(n, np.float32), name="dec_a", partial_read=True)
    try:
        cr.enqueue_mode = True
        for _w in range(6):
            for _ in range(8):
                a.compute(cr, 901, "inc", n, 64)
            cr.barrier()
        cr.enqueue_mode = False
        recs = _since(mark)
        kinds = {r.kind for r in recs}
        assert "fused-engage" in kinds or "fused-disengage" in kinds
        assert "load-balance" in kinds  # barriers armed rebalances
        lb = [r for r in recs if r.kind == "load-balance"
              and r.inputs.get("cid") == 901]
        assert lb and len(lb[0].inputs["benchmarks"]) == 2
        # the spilled log round-trips through the REAL CLI: exit 0,
        # bit-identical
        p = _write_jsonl(tmp_path / "live.jsonl", recs)
        assert ckreplay.main(["verify", p]) == 0
        # /decisionz: counts, recent rows, and the live explain table
        with urllib.request.urlopen(
                srv.url + "/decisionz", timeout=10) as r:
            body = json.loads(r.read().decode())
        assert body["counts"].get("load-balance", 0) >= 1
        assert body["total_recorded"] >= len(recs)
        assert body["recent"], "no recent decisions served"
        ex = body["explain"].get("901")
        assert ex is not None and len(ex["lanes"]) == 2
        assert all("binding" in ln for ln in ex["lanes"])
        # explain over the same records matches the endpoint's cid view
        doc = replay_mod.explain_latest(recs, cid=901)
        assert doc["cid"] == 901
    finally:
        cr.dispose()
    assert float(a.host()[0]) == float(a.host()[-1]) > 0  # bit-exact


def test_decision_kinds_vocabulary_is_total(devs):
    """Every kind the built-ins emit is declared, and the replayable
    subset is a subset of the declared vocabulary."""
    assert set(REPLAYABLE_KINDS) <= set(DECISION_KINDS)
    emitted = {r.kind for r in DECISIONS.snapshot()}
    assert emitted <= set(DECISION_KINDS), emitted - set(DECISION_KINDS)


# ---------------------------------------------------------------------------
# postmortem v2: the decision ring rides the black box; v1 still loads
# ---------------------------------------------------------------------------

def test_postmortem_v2_carries_decisions_and_replays(tmp_path):
    if not DECISIONS.snapshot():
        load_balance([1.0, 2.0], [64, 64], 128, 64,
                     None, state=BalanceState(), cid=1)
    p = str(tmp_path / "pm.json")
    dump_postmortem(p, exc=RuntimeError("boom"))
    pm = load_postmortem(p)
    assert pm["schema"] == "ck-postmortem-v2"
    assert pm["decisions"], "v2 dump carries no decision ring"
    assert pm["decisions_capacity"] == DECISIONS.capacity
    # the black box replays directly through the CLI loader
    rows = ckreplay.load_records(p)
    assert rows and replay_mod.verify_records(rows)["replayed"] >= 1


def test_postmortem_v1_files_still_load(tmp_path):
    """Round-trip pin for the additive schema bump: a v1 file (no
    decisions key) loads with decisions == [] and untouched spans."""
    v1 = {
        "schema": "ck-postmortem-v1",
        "wrote_at": 1700000000.0,
        "exc": None,
        "events": [{"t": 1.0, "kind": "barrier"}],
        "spans": [{"kind": "launch", "t0": 0.0, "t1": 0.001,
                   "cid": 1, "lane": 0, "tag": "x"}],
        "metrics": {},
        "lanes": None,
        "versions": {},
    }
    p = str(tmp_path / "v1.json")
    with open(p, "w") as f:
        json.dump(v1, f)
    pm = load_postmortem(p)
    assert pm["decisions"] == []
    assert pm["spans"][0].kind == "launch"
    assert ckreplay.load_records(p) == []


# ---------------------------------------------------------------------------
# bench artifact + regress gate
# ---------------------------------------------------------------------------

def _bench():
    sys.path.insert(0, ROOT)
    import bench

    return bench


def test_bench_artifact_embeds_decisions_and_replay_ok():
    bench = _bench()
    if not any(r.kind == "load-balance" for r in DECISIONS.snapshot()):
        _run_chain(steps=3, cid=77)
    sched = bench.SectionScheduler(100.0, {})
    result = {"headline": {"mandelbrot_mpix": 1.0}}
    out = bench.finalize_result(result, sched)
    dec = out["decisions"]
    assert dec["replay_ok"] is True
    assert dec["rebalances"] >= 1
    assert dec["counts"].get("load-balance", 0) >= 1
    assert isinstance(dec["convergence"], dict) and dec["convergence"]
    cid_rec = next(iter(dec["convergence"].values()))
    assert {"rebalances", "iterations_to_converge", "settled",
            "jumped", "final_ranges"} <= set(cid_rec)
    # the verdict rides the tail-surviving headline
    assert out["headline"]["replay_ok"] is True
    # tail order is preserved (decisions slots in BEFORE metrics, the
    # tail-critical block still closes the artifact)
    keys = list(out)
    assert keys[-4:] == ["metrics", "regression",
                         "null_sections", "headline"]
    assert keys.index("decisions") < keys.index("metrics")
    # the in-process scheduler-rotation decision is declared vocabulary
    assert all(r.kind in DECISION_KINDS for r in DECISIONS.snapshot())


def test_regress_hard_fails_replay_false():
    regress = _load_tool("ck_regress_dec", "tools/regress.py")
    base = {"path": "b", "headline": {"mandelbrot_mpix": 10.0},
            "errors": None, "null_sections": None, "sections": None}
    good = {"path": "c", "headline": {"mandelbrot_mpix": 10.0,
                                      "replay_ok": True},
            "errors": None, "null_sections": None, "sections": None}
    assert regress.diff_headlines(base, good)["exit_code"] == 0
    bad = {"path": "c", "headline": {"mandelbrot_mpix": 10.0,
                                     "replay_ok": False},
           "errors": None, "null_sections": None, "sections": {
               "decisions": {"replay": {"first_divergence": {
                   "seq": 12, "kind": "load-balance"}}}}}
    v = regress.diff_headlines(base, bad)
    assert v["exit_code"] == 3 and not v["ok"]
    finding = next(f for f in v["findings"]
                   if f["kind"] == "replay-drift")
    assert "seq" in str(finding["reason"])
    # absent (pre-provenance artifact) and None both pass
    legacy = {"path": "c", "headline": {"mandelbrot_mpix": 10.0},
              "errors": None, "null_sections": None, "sections": None}
    assert regress.diff_headlines(base, legacy)["exit_code"] == 0
