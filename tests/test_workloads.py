"""Workload self-checks: mandelbrot vs host reference, nbody tolerance
pattern, streaming add — the reference's benchmark set (Tester.cs) as
tests on the 8-virtual-device rig."""

import numpy as np
import pytest

import cekirdekler_tpu as ct
from cekirdekler_tpu.workloads import (
    MANDELBROT_SRC,
    mandelbrot_host,
    run_mandelbrot,
    run_nbody,
    run_stream,
)


def _assert_images_match(got, want, budget=1e-3):
    """Escape-time counts are chaotic at the set boundary: XLA contracts
    a*b+c into FMAs, so a handful of boundary pixels legitimately differ
    from strict-f32 numpy.  Require bitwise agreement on all but a tiny
    fraction."""
    got = np.ravel(got)
    frac = float(np.mean(got != want))
    assert frac <= budget, f"{frac * 100:.3f}% of pixels differ (budget {budget * 100}%)"


def _cpus():
    """The deterministic 8-virtual-device rig (a real TPU chip may also be
    visible through the tunnel; exact-equality tests must not mix the two
    — TPU f32 differs by 1 ULP at mandelbrot escape boundaries)."""
    return ct.all_devices().cpus().require_nonempty("cpu test rig")


def test_mandelbrot_matches_host_single_device():
    res = run_mandelbrot(
        _cpus().subset(1), width=256, height=128, max_iter=64,
        iters=1, warmup=0, keep_image=True,
    )
    want = mandelbrot_host(256, 128, -2.0, -1.25, 2.5 / 256, 2.5 / 128, 64)
    _assert_images_match(res.image, want)


def test_mandelbrot_multichip_matches_host():
    res = run_mandelbrot(
        _cpus(), width=512, height=256, max_iter=48,
        iters=4, warmup=0, keep_image=True, local_range=128,
    )
    want = mandelbrot_host(512, 256, -2.0, -1.25, 2.5 / 512, 2.5 / 256, 48)
    _assert_images_match(res.image, want)
    # the balancer actually split work across chips
    assert len(res.ranges_per_iter[-1]) == len(_cpus())
    assert sum(res.ranges_per_iter[-1]) == 512 * 256


def test_mandelbrot_pipelined_matches_host():
    res = run_mandelbrot(
        _cpus().subset(2), width=512, height=128, max_iter=32,
        iters=2, warmup=0, keep_image=True, local_range=64,
        pipeline=True, pipeline_blobs=4,
    )
    want = mandelbrot_host(512, 128, -2.0, -1.25, 2.5 / 512, 2.5 / 128, 32)
    _assert_images_match(res.image, want)


def test_nbody_self_check():
    out = run_nbody(_cpus(), n=1024, iters=3, local_range=128)
    assert out["checked"]
    assert len(out["per_iter_ms"]) == 3


def test_stream_add():
    out = run_stream(_cpus().subset(2), n=1 << 16, reps=3, blobs=4, local_range=64)
    assert out["gb_per_sec"] > 0


def test_enqueue_mode_with_pipeline_flushes_correctly():
    """Regression: pipelined computes under enqueue mode must defer readbacks
    to flush() and must not skip blob uploads after blob 1 creates the
    buffer."""
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.workloads import STREAM_SRC

    n = 1 << 14
    a = ClArray(np.arange(n, dtype=np.float32), partial_read=True, read_only=True)
    b = ClArray(np.ones(n, dtype=np.float32), partial_read=True, read_only=True)
    c = ClArray(n, np.float32, write_only=True)
    cr = NumberCruncher(_cpus().subset(4), STREAM_SRC)
    try:
        cr.enqueue_mode = True
        g = a.next_param(b, c)
        for _ in range(3):
            g.compute(cr, 1, "streamAdd", n, 64, pipeline=True, pipeline_blobs=4)
        cr.enqueue_mode = False  # leaving enqueue mode flushes
        assert np.array_equal(c.host(), a.host() + b.host())
    finally:
        cr.dispose()


def test_enqueue_write_all_single_owner_readback():
    """Regression: under enqueue mode only the owning chip defers a
    write_all readback — N racing full-array downloads are wrong and
    wasteful."""
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher

    n = 512
    out = ClArray(np.zeros(n, np.float32), read=False, write=True, write_all=True)
    cr = NumberCruncher(
        _cpus().subset(4),
        "__kernel void f(__global float* o){ int i=get_global_id(0); o[i]=o[i]+1.0f; }",
    )
    try:
        cr.enqueue_mode = True
        out.compute(cr, 3, "f", n, 64)
        assert len(cr.cores._enqueued) == 1  # one owner, one deferred record
        cr.enqueue_mode = False
    finally:
        cr.dispose()


def test_partial_range_readback_preserves_host_outside_range():
    """Regression: a single-device compute over a prefix of the array must
    not overwrite host elements beyond the computed range."""
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher

    out = ClArray(np.full(512, -7.0, np.float32), read=False, write=True)
    cr = NumberCruncher(
        _cpus().subset(1),
        "__kernel void f(__global float* o){ int i=get_global_id(0); o[i]=2.0f; }",
    )
    try:
        out.compute(cr, 2, "f", 256, 64)
        assert np.all(out.host()[:256] == 2.0)
        assert np.all(out.host()[256:] == -7.0)
    finally:
        cr.dispose()


def test_measure_stream_overlap_shape():
    """Overlap instrumentation runs end-to-end and returns a sane record;
    the >=0.9 target is asserted on real TPU hardware only (bench.py) —
    on the CPU rig 'transfers' are memcpys and overlap is meaningless."""
    from cekirdekler_tpu.workloads import measure_stream_overlap

    ov = measure_stream_overlap(_cpus(), n=1 << 14, blobs=4, reps=1)
    assert set(ov) >= {
        "t_read_ms", "t_compute_ms", "t_write_ms", "t_pipelined_ms",
        "t_serial_ms", "overlap_fraction", "rtt_ms",
    }
    # the ratio is RAW (unclipped, VERDICT r2 #3) — on the CPU rig where
    # "transfers" are memcpys it can be far outside [0, 1]; only finiteness
    # and the serial-sum identity are backend-independent
    assert np.isfinite(ov["overlap_fraction"])
    assert ov["t_serial_ms"] >= max(
        ov["t_read_ms"], ov["t_compute_ms"], ov["t_write_ms"]
    )


def test_pipelined_not_catastrophically_slower_than_plain():
    """Correctness + sanity wall-clock on the CPU rig: the pipelined path
    must stay within 3x of the plain path (the strict 'pipelined beats
    plain' claim is a device-DMA property, asserted on TPU in bench.py's
    overlap_fraction)."""
    import time as _t

    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.workloads import STREAM_SRC

    n = 1 << 16
    cr = NumberCruncher(_cpus().subset(1), STREAM_SRC)
    try:
        def run(pipe):
            a = ClArray(np.arange(n, dtype=np.float32), partial_read=True, read_only=True)
            b = ClArray(np.ones(n, np.float32), partial_read=True, read_only=True)
            c = ClArray(n, np.float32, write_only=True)
            g = a.next_param(b, c)
            g.compute(cr, 9100 + int(pipe), "streamAdd", n, 64,
                      pipeline=pipe, pipeline_blobs=8)
            t0 = _t.perf_counter()
            for _ in range(3):
                g.compute(cr, 9100 + int(pipe), "streamAdd", n, 64,
                          pipeline=pipe, pipeline_blobs=8)
            dt = _t.perf_counter() - t0
            np.testing.assert_allclose(np.asarray(c), np.arange(n) + 1)
            return dt

        t_plain = run(False)
        t_pipe = run(True)
        assert t_pipe < 3.0 * t_plain + 0.05, (t_pipe, t_plain)
    finally:
        cr.dispose()


def test_nbody_jnp_fast_path_matches_host():
    """The fused-XLA n-body (ops/nbody.py) through the compute path:
    self-check vs the host O(n^2) reference, multi-device."""
    from cekirdekler_tpu.workloads import run_nbody

    res = run_nbody(_cpus().subset(2), n=512, iters=2, check=True, use_jnp=True)
    assert res["checked"] and res["gpairs_per_sec"] > 0


def test_nbody_device_ranking_runs():
    """with_highest_nbody_performance must actually run (regression: the
    ops.nbody module it imports did not exist)."""
    devs = _cpus().subset(2)
    ranked = devs.with_highest_nbody_performance(n=128, iters=1)
    assert len(ranked) == 2


def test_compute_path_proof_invariants():
    """VERDICT r3 #1: the flagship compute() multi-chip scaling proxy —
    compile-count invariance, full dispatch concurrency, work-equal
    convergence, single-chip-exact assembly."""
    from cekirdekler_tpu.benchrig import compute_path_proof

    p = compute_path_proof(ndev=8, iters=24)
    assert p["ok"] is True
    assert p["compile_count_invariant"] is True
    # all-lanes-in-flight is a TIMING property: 8 dispatch threads on a
    # 2-core container physically cannot all dispatch before the first
    # readback completes — that's the rig, not the scheduler.  The proof
    # retries the traced call and reports lane_rig_capable (host cores
    # >= active lanes); the timing assertion gates on it, while the
    # structural invariants hold on ANY rig.
    active = sum(1 for r in p["ranges_final"] if r > 0)
    assert p["lanes_traced"] == active
    assert p["lanes_dispatched_before_first_join"] >= 1
    if p["lane_rig_capable"]:
        assert p["all_lanes_in_flight_together"] is True
    assert p["image_exact_vs_single_chip"] is True
    assert p["work_imbalance_final"] < 1.1 < p["work_imbalance_first"]
    assert p["convergence_iters"] is not None
