"""Chaos suite (ISSUE 13): the seeded fault-injection plane
(utils/faultinject.py) and the recovery behaviors it proves.

Contract under test: every injected fault either leaves results
BIT-IDENTICAL (delay-shaped faults — slow links, lane stalls — plus
the recovery machinery) or surfaces as a NAMED, non-hanging error
(submit failures, exhausted retries); the same plan string + seed
reproduces the same fault sequence; and every injected fault lands as
a ``fault-injected`` flight event + ``ck_fault_injected_total`` metric
so postmortems and these tests read one evidence stream.

The DCN process-kill scenario lives in tests/test_dcn.py
(``test_kill_and_rejoin_converges_bit_identical``) — it needs real
process lifecycle."""

import importlib.util
import os
import time

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.errors import (
    ClusterRetryExhausted,
    InjectedFaultError,
)
from cekirdekler_tpu.hardware import platforms
from cekirdekler_tpu.metrics.registry import REGISTRY
from cekirdekler_tpu.obs.flight import FLIGHT
from cekirdekler_tpu.utils.faultinject import (
    FAULTS,
    FaultPlane,
    parse_plan,
)

INC = """
__kernel void inc(__global float* a) {
    int i = get_global_id(0);
    a[i] = a[i] + 1.0f;
}
"""


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


@pytest.fixture(autouse=True)
def _disarm():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _load_resilience():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ck_resilience_test", os.path.join(here, "tools", "resilience.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# plan grammar & determinism
# ---------------------------------------------------------------------------

def test_plan_parses_points_selectors_params():
    seed, clauses = parse_plan(
        "seed=9;slow-link@lane1:factor=5,times=8;"
        "socket-drop@recv:after=2;driver-submit")
    assert seed == 9
    assert [c.point for c in clauses] == [
        "slow-link", "socket-drop", "driver-submit"]
    assert clauses[0].lane == 1 and clauses[0].factor == 5.0
    assert clauses[0].times == 8
    assert clauses[1].selector == "recv" and clauses[1].after == 2
    assert clauses[2].selector is None


def test_plan_rejects_bad_grammar():
    with pytest.raises(ValueError):
        parse_plan("not-a-point:delay_ms=1")
    with pytest.raises(ValueError):
        parse_plan("lane-stall:bogus_param=1")
    # an armed-but-ignored plan would be the worst chaos-rig failure
    with pytest.raises(ValueError):
        parse_plan("lane-stall:delay_ms")


def test_after_times_counting_is_exact():
    p = FaultPlane()
    p.arm("lane-stall@lane0:delay_ms=1,after=2,times=3")
    fired = [p.fire("lane-stall", lane=0) is not None for _ in range(8)]
    assert fired == [False, False, True, True, True, False, False, False]
    # non-matching lane never consumes the clause's budget
    assert p.fire("lane-stall", lane=1) is None


def test_probabilistic_fires_are_seed_deterministic():
    def pattern(seed: int) -> list[bool]:
        p = FaultPlane()
        p.arm(f"seed={seed};lane-stall@lane0:delay_ms=1,p=0.5")
        return [p.fire("lane-stall", lane=0) is not None
                for _ in range(64)]

    a, b = pattern(42), pattern(42)
    assert a == b                       # same seed = same fault sequence
    assert any(a) and not all(a)        # p=0.5 genuinely mixes
    assert pattern(43) != a             # the seed is load-bearing


def test_env_arming_and_disarm():
    os.environ["CK_FAULTS"] = "lane-stall@lane0:delay_ms=1,times=1"
    try:
        p = FaultPlane()
        assert p.enabled and p.plan
        p.disarm()
        assert not p.enabled
        assert p.fire("lane-stall", lane=0) is None
    finally:
        os.environ.pop("CK_FAULTS", None)


def test_fired_fault_is_evidence():
    """Every injection lands as a flight event + metric (one stream)."""
    c0 = REGISTRY.counter(
        "ck_fault_injected_total",
        "deliberately injected faults (utils/faultinject.py)",
        point="lane-stall").value
    FAULTS.arm("lane-stall@lane3:delay_ms=2,times=1")
    d = FAULTS.delay_s("lane-stall", lane=3)
    assert d == pytest.approx(0.002)
    c1 = REGISTRY.counter(
        "ck_fault_injected_total",
        "deliberately injected faults (utils/faultinject.py)",
        point="lane-stall").value
    assert c1 == c0 + 1
    evs = [e for e in FLIGHT.snapshot() if e.kind == "fault-injected"]
    assert evs and evs[-1].fields["point"] == "lane-stall"
    assert evs[-1].fields["lane"] == 3
    snap = FAULTS.snapshot()
    assert snap["clauses"][0]["fired"] == 1


# ---------------------------------------------------------------------------
# driver-submit: named, non-hanging error at the sync point
# ---------------------------------------------------------------------------

def test_driver_submit_fault_surfaces_named_at_sync_point(devs):
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    cr.enqueue_mode = True
    # let the fused window engage cleanly first
    for _ in range(3):
        x.compute(cr, 1, "inc", 1024, 64)
    FAULTS.arm("driver-submit@lane0:times=1")
    with pytest.raises(InjectedFaultError) as ei:
        # deferrals dispatch in fused_batch batches; keep calling until
        # the poisoned submit surfaces (bounded — named error, no hang)
        for _ in range(64):
            x.compute(cr, 1, "inc", 1024, 64)
        cr.barrier()
    assert ei.value.point == "driver-submit"
    FAULTS.disarm()
    cr.dispose()


# ---------------------------------------------------------------------------
# slow link: Nx degradation, bit-identical results
# ---------------------------------------------------------------------------

def test_slow_link_injection_keeps_results_bit_identical(devs):
    cr = NumberCruncher(devs.subset(2), INC)
    x = ClArray(np.zeros(1024, np.float32), name="x")
    x.partial_read = True
    FAULTS.arm("seed=3;slow-link@lane1:factor=4,delay_ms=2,times=12")
    cr.enqueue_mode = True
    iters = 10
    for _ in range(iters):
        x.compute(cr, 1, "inc", 1024, 64)
    cr.barrier()
    cr.enqueue_mode = False  # flush (its D2H drain is also instrumented)
    FAULTS.disarm()
    np.testing.assert_array_equal(np.asarray(x), float(iters))
    evs = [e for e in FLIGHT.snapshot()
           if e.kind == "fault-injected"
           and e.fields.get("point") == "slow-link"]
    assert evs, "slow-link never fired through the transfer funnels"
    assert all(e.fields["lane"] == 1 for e in evs)
    cr.dispose()


# ---------------------------------------------------------------------------
# lane stall -> automatic drain -> readmit (the closed loop, seeded)
# ---------------------------------------------------------------------------

def test_seeded_stall_is_drained_and_readmitted_exactly(devs):
    """The acceptance loop (ISSUE 13): an injected lane degradation is
    drained automatically (share redistributed, workload exact) and the
    lane is re-admitted after the injection clears — no human
    intervention, no flapping.  Runs the same scenario the bench's
    ``resilience`` section ships (tools/resilience.py)."""
    res = _load_resilience().drain_readmit_scenario(
        devs, stall_ms=400.0, max_windows=40)
    assert res.get("skipped") is None, res
    assert res["windows_to_drain"] is not None, res
    assert res["drain_recover_ms"] is not None
    assert res["ranges_after_drain"][1] == 0, res
    assert res["windows_to_readmit"] is not None, res
    assert res["drain_report"]["states"] == {"0": "active", "1": "active"}
    # exactly one drain and one readmit: no flapping
    assert res["drain_report"]["drains"] == 1
    assert res["drain_report"]["readmits"] == 1
    assert res["exact"], res


def test_mixed_kind_stall_quarantines_without_starving_fast_lanes(devs):
    """Degradation containment on a heterogeneous fleet (ISSUE 20): a
    stalled host-CPU lane in a 2-fast + 1-slow mixed Cores quarantines
    at a barrier, the fast accelerator-kind lanes absorb its share
    WITHOUT ever dipping below their rate-implied floor, the
    availability floor never engages (both fast lanes stay active),
    and the result is bit-exact.  Runs the same scenario the bench's
    ``resilience`` section ships (tools/resilience.py)."""
    res = _load_resilience().mixed_drain_scenario(
        devs, stall_ms=400.0, max_windows=40)
    assert res.get("skipped") is None, res
    assert res["lane_kinds"] == ["tpu-emu", "tpu-emu", "cpu"]
    assert res["windows_to_drain"] is not None, res
    assert res["slow_lane_drained"] is True, res
    assert res["fast_floor_ok"] is True, res
    assert res["fast_lanes_active"] is True, res
    # the rate-implied floor really is the prior-weighted share
    floor = res["rate_implied_floor"]
    assert floor[0] + floor[1] > 14 * floor[2]  # ~8x lanes vs 1x lane
    after = res["ranges_after_drain"]
    assert after[2] == 0 and sum(after) == sum(res["ranges_before"])
    assert res["windows_to_readmit"] is not None, res
    assert res["exact"], res


# ---------------------------------------------------------------------------
# socket drop: reconnect + idempotent retry / named exhaustion
# ---------------------------------------------------------------------------

def _cluster_pair(devs):
    from cekirdekler_tpu.cluster.client import CruncherClient
    from cekirdekler_tpu.cluster.server import CruncherServer

    server = CruncherServer(devices=devs.subset(1))
    client = CruncherClient(
        server.host, server.port, op_timeout=10.0,
        max_retries=3, backoff_s=0.01, backoff_max_s=0.05)
    return server, client


def test_socket_drop_mid_message_is_survived_by_reconnect(devs):
    server, client = _cluster_pair(devs)
    try:
        client.setup(INC)
        x = ClArray(np.zeros(256, np.float32))
        x.partial_read = True
        # drop the NEXT send mid-message, exactly once
        FAULTS.arm("socket-drop@send:times=1")
        client.compute(["inc"], [x], 5, 0, 256, 64)
        FAULTS.disarm()
        assert client.reconnects == 1
        np.testing.assert_array_equal(x.host(), 1.0)
        # and the connection is healthy again afterwards
        client.compute(["inc"], [x], 5, 0, 256, 64)
        np.testing.assert_array_equal(x.host(), 2.0)
    finally:
        FAULTS.disarm()
        client.close()
        server.stop()


def test_retry_reuses_the_request_sequence_number(devs):
    """Idempotency marker: the retried message carries the SAME seq it
    was first sent with — a dedup-aware peer can recognize a replay."""
    from cekirdekler_tpu.cluster.netbuffer import Command, Message

    server, client = _cluster_pair(devs)
    try:
        msg = Message(Command.CONTROL)
        FAULTS.arm("socket-drop@send:times=1")
        reply = client._roundtrip(msg)
        FAULTS.disarm()
        assert reply.command == Command.ANSWER_CONTROL
        assert client.reconnects == 1
        assert msg.meta["seq"] == 1      # assigned once, reused on retry
        assert client._seq == 1          # no fresh seq burned by the retry
    finally:
        FAULTS.disarm()
        client.close()
        server.stop()


def test_dead_node_raises_named_error_not_a_hang(devs):
    server, client = _cluster_pair(devs)
    try:
        client.setup(INC)
        server.stop()
        t0 = time.perf_counter()
        with pytest.raises(ClusterRetryExhausted) as ei:
            client.num_devices()
        wall = time.perf_counter() - t0
        assert ei.value.attempts == 4
        assert wall < 10.0  # bounded backoff, not a hang
    finally:
        client.close()
        server.stop()


def test_mid_recv_death_times_out_instead_of_hanging(devs):
    """The seed behavior this PR removes: a server dying mid-reply hung
    the client forever (only CONNECT had a timeout).  Now the
    per-operation read timeout surfaces it, the retries run, and the
    client ends with a NAMED error."""
    import socket as socketlib

    # a listener that accepts and then sends HALF a header, forever
    lst = socketlib.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    port = lst.getsockname()[1]
    import threading

    def half_replier():
        while True:
            try:
                conn, _ = lst.accept()
            except OSError:
                return
            try:
                conn.recv(1 << 20)  # swallow the request
                conn.sendall(b"\x01")  # half a header, then silence
            except OSError:
                pass

    t = threading.Thread(target=half_replier, daemon=True)
    t.start()
    from cekirdekler_tpu.cluster.client import CruncherClient

    try:
        client = CruncherClient(
            "127.0.0.1", port, op_timeout=0.2, max_retries=1,
            backoff_s=0.01, backoff_max_s=0.02)
        t0 = time.perf_counter()
        with pytest.raises(ClusterRetryExhausted):
            client.num_devices()
        assert time.perf_counter() - t0 < 5.0
        client.close()
    finally:
        lst.close()
