"""Streamed partition transfers (ISSUE 5): ladder-aligned chunk
planning, the online transfer autotuner's contract (deterministic,
monotone in link latency, re-tunes on re-partition), and the acceptance
pin that the chunked double-buffered path is BIT-identical to the
monolithic path on mandelbrot and accumulating n-body, fused dispatch on
AND off.  Tuner tests are pure host logic — timings are synthetic inputs
(`observe`), never clocks — so they are exact on any rig."""

import numpy as np
import pytest

from cekirdekler_tpu import ClArray
from cekirdekler_tpu.core import NumberCruncher
from cekirdekler_tpu.core.stream import (
    BOOTSTRAP_BYTES,
    BOOTSTRAP_CHUNKS,
    CHUNK_CANDIDATES,
    TransferTuner,
    chunk_plan,
)
from cekirdekler_tpu.hardware import platforms


@pytest.fixture(scope="module")
def devs():
    return platforms().cpus()


# ---------------------------------------------------------------------------
# chunk planning: step·2^k geometry (every chunk a ladder cache hit)
# ---------------------------------------------------------------------------

def test_chunk_plan_sizes_are_ladder_aligned():
    for size, step, target in ((4096, 64, 8), (4096, 64, 5), (832, 64, 4),
                               (256, 256, 4), (7 * 64, 64, 16)):
        plan = chunk_plan(size, step, target)
        off = 0
        for coff, csz in plan:
            assert coff == off  # ascending, gap-free
            units = csz // step
            assert csz % step == 0
            assert units & (units - 1) == 0, (csz, step)  # step·2^k
            off += csz
        assert off == size  # exact cover

def test_chunk_plan_reaches_target_when_splittable():
    plan = chunk_plan(4096, 64, 8)
    assert len(plan) == 8
    # unsplittable floor: every chunk already one step
    assert len(chunk_plan(256, 256, 4)) == 1
    assert len(chunk_plan(4 * 64, 64, 99)) == 4


def test_chunk_plan_rejects_non_multiple():
    with pytest.raises(ValueError):
        chunk_plan(100, 64, 4)
    with pytest.raises(ValueError):
        chunk_plan(128, 0, 2)


# ---------------------------------------------------------------------------
# the autotuner's contract
# ---------------------------------------------------------------------------

MIB = float(1 << 20)


def _teach(t: TransferTuner, lane=0, key=("k",), nbytes=1 << 22,
           u=10.0, c=10.0, d=10.0):
    """One monolithic measuring run's observation."""
    t.observe(lane, key, nbytes, u, c, d, chunks=1)


def test_tuner_first_contact_is_the_measuring_run():
    t = TransferTuner()
    assert t.choose(0, ("k",), 1 << 22, max_chunks=64) == 1


def test_tuner_deterministic_under_fixed_timings():
    def build():
        t = TransferTuner()
        t.seed_link(0, 2.0, 2.0)
        _teach(t, u=12.0, c=9.0, d=11.0)
        t.observe(0, ("k",), 1 << 22, 11.0, 0.0, 10.0, chunks=4,
                  wall_ms=20.0)
        return t

    a, b = build(), build()
    for _ in range(3):  # choose() has no internal state advance
        ca = a.choose(0, ("k",), 1 << 22, max_chunks=64)
        cb = b.choose(0, ("k",), 1 << 22, max_chunks=64)
        assert ca == cb
        assert ca == a.choose(0, ("k",), 1 << 22, max_chunks=64)
    assert a.lane_overhead_ms(0) == b.lane_overhead_ms(0)


def test_tuner_chunk_count_monotone_in_link_latency():
    """Scaling synthetic link latency up (U, D grow, compute fixed)
    never DECREASES the chosen chunk count — more transfer to hide
    justifies more (or equal) pipeline granularity, never less."""
    chosen = []
    for scale in (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0):
        t = TransferTuner()
        _teach(t, u=4.0 * scale, c=6.0, d=4.0 * scale)
        chosen.append(t.choose(0, ("k",), 1 << 22, max_chunks=1024))
    assert chosen == sorted(chosen), chosen
    assert chosen[-1] > chosen[0]  # the sweep actually moves the choice


def test_tuner_more_overhead_never_more_chunks():
    """The dual monotonicity: a lane whose learned per-chunk cost grows
    never gets MORE chunks out of the model."""
    chosen = []
    for ov in (0.01, 0.1, 1.0, 5.0, 50.0):
        t = TransferTuner(overhead_ms=ov)
        _teach(t, u=10.0, c=10.0, d=10.0)
        chosen.append(t.choose(0, ("k",), 1 << 22, max_chunks=1024))
    assert chosen == sorted(chosen, reverse=True), chosen
    assert chosen[0] > 1 and chosen[-1] == 1


def test_tuner_retunes_on_repartition():
    t = TransferTuner()
    t.seed_link(0, 3.0, 3.0)
    _teach(t, u=20.0, c=5.0, d=20.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) > 1
    t.on_repartition()
    assert t.retunes == 1
    # observations dropped: the compute key is back to first contact
    # (the monolithic measuring run) ...
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) == 1
    # ... but the duplex-probe link seed SURVIVES: no-compute keys keep
    # modeling from link physics (3 ms/MiB each way on 4 MiB >> any
    # per-chunk overhead, so the model still wants chunks)
    assert t.choose(0, "flush-d2h", 1 << 22, 1024, has_compute=False) > 1


def test_tuner_flip_back_to_one_chunk_remeasures():
    """The module docstring's freshness promise: when the model flips a
    key from chunked back to 1 chunk, the observation is dropped so the
    flip's run is a fresh fenced measuring run.  Without it the 1-chunk
    regime is clamp-only (estimates can only FALL) and a link that
    later slows could never re-engage streaming."""
    t = TransferTuner(overhead_ms=2.0)
    _teach(t, u=10.0, c=10.0, d=10.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) > 1
    # transfers shrink until hideable rest < per-chunk overhead: the
    # model now prefers monolithic (fenced EMA pulls U/D down)
    for _ in range(4):
        t.observe(0, ("k",), 1 << 22, 0.0, 10.0, 0.0, chunks=1,
                  fenced=True)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) == 1
    # the flip dropped the obs — next contact is a measuring run again
    assert not t.has_obs(0, ("k",), 1 << 22)
    # and re-teaching transfer-dominant numbers re-engages streaming
    _teach(t, u=50.0, c=5.0, d=50.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) > 1


def test_tuner_clamp_only_streak_remeasures():
    """A key parked at 1 chunk sees only unfenced clamp-only walls —
    blind to a link that got SLOWER.  REMEASURE_AFTER consecutive
    clamp-only observations drop the key for a fresh measuring run."""
    from cekirdekler_tpu.core.stream import REMEASURE_AFTER

    t = TransferTuner(overhead_ms=5.0)
    # compute-dominant from the start: choice is 1, no flip ever fires
    _teach(t, u=1.0, c=100.0, d=1.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) == 1
    for _ in range(REMEASURE_AFTER - 1):
        t.observe(0, ("k",), 1 << 22, 0.0, 0.0, 0.0, chunks=1,
                  wall_ms=102.0)
        assert t.has_obs(0, ("k",), 1 << 22)
    t.observe(0, ("k",), 1 << 22, 0.0, 0.0, 0.0, chunks=1, wall_ms=102.0)
    assert not t.has_obs(0, ("k",), 1 << 22)


def test_tuner_no_compute_bootstrap_without_seed():
    t = TransferTuner()
    big = t.choose(0, "flush-d2h", BOOTSTRAP_BYTES, 1024, has_compute=False)
    assert big == BOOTSTRAP_CHUNKS
    small = t.choose(
        0, "flush-d2h", BOOTSTRAP_BYTES - 1, 1024, has_compute=False)
    assert small == 1


def test_tuner_chunked_run_teaches_lane_overhead():
    """A chunked wall above the overhead-free pipeline model raises the
    lane's learned per-chunk cost; a lane whose chunks are expensive
    talks itself back down to fewer chunks."""
    t = TransferTuner()
    _teach(t, u=10.0, c=10.0, d=10.0)
    before = t.lane_overhead_ms(0)
    many = t.choose(0, ("k",), 1 << 22, max_chunks=1024)
    assert many > 1
    # model says ~ peak + rest/c; report a wall WAY above it (slow rig)
    t.observe(0, ("k",), 1 << 22, 10.0, 0.0, 10.0, chunks=many,
              wall_ms=200.0)
    assert t.lane_overhead_ms(0) > before
    for _ in range(6):  # EMA converges onto the implied cost
        t.observe(0, ("k",), 1 << 22, 10.0, 0.0, 10.0, chunks=many,
                  wall_ms=200.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) < many


def test_tuner_chunked_wall_clamps_contaminated_estimates():
    """First contact is usually also first jit compile, so the measuring
    run's C carries compile time: the inflated peak flattens the model
    curve (the first choice degenerates to the largest candidate) and
    every implied overhead clamps at 0 against the oversized base, so
    over-chunking would freeze in place.  A chunked wall upper-bounds
    every phase (all of U, C, D happen inside it) — one honest streamed
    run must snap the estimates back to physics."""
    t = TransferTuner()
    # measuring run where compile landed in C (real phases ~ 5/5/5 ms)
    _teach(t, u=5.0, c=500.0, d=5.0)
    many = t.choose(0, ("k",), 1 << 22, max_chunks=1024)
    assert many > 1  # the contaminated model wants chunks
    # one honest chunked run: a 15 ms wall bounds every phase
    t.observe(0, ("k",), 1 << 22, 2.0, 0.0, 2.0, chunks=many, wall_ms=15.0)
    est = t.estimate(0, ("k",), 1 << 22)
    assert max(est) <= 15.0
    # ... which unblocks overhead learning: on a slow-chunk rig (walls
    # stuck at 50 ms regardless of count) the implied per-chunk cost is
    # now positive — against the un-clamped ~500 ms base it would clamp
    # at 0 forever — and the choice converges back to monolithic
    for _ in range(8):
        c = t.choose(0, ("k",), 1 << 22, max_chunks=1024)
        if c == 1:
            break
        t.observe(0, ("k",), 1 << 22, 2.0, 0.0, 2.0, chunks=c, wall_ms=50.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) == 1


def test_tuner_chunked_first_contact_stores_nothing():
    """A chunked run with no monolithic baseline cannot decompose its
    own wall — it must not seed the observation table."""
    t = TransferTuner()
    t.observe(0, ("k",), 1 << 22, 5.0, 1.0, 5.0, chunks=4, wall_ms=12.0)
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) == 1  # still first contact


def test_tuner_bytes_bucket_quantization():
    """±quantization-step balancer moves stay in one bucket — the
    observation is not thrashed by a few-element range wiggle."""
    t = TransferTuner()
    assert t.bytes_bucket(1 << 20) == 1 << 20
    assert t.bytes_bucket((1 << 20) + 1) == 1 << 21
    _teach(t, nbytes=(1 << 20) + 5000, u=20.0, c=5.0, d=20.0)
    same_bucket = t.choose(0, ("k",), (1 << 20) + 9000, max_chunks=1024)
    assert same_bucket > 1  # hit the stored observation, not first contact


def test_tuner_candidates_respect_cap():
    t = TransferTuner()
    _teach(t, u=50.0, c=1.0, d=50.0)  # wants many chunks
    assert t.choose(0, ("k",), 1 << 22, max_chunks=3) <= 3
    assert t.choose(0, ("k",), 1 << 22, max_chunks=1024) in CHUNK_CANDIDATES


# ---------------------------------------------------------------------------
# acceptance pins: streamed == monolithic, element-exact
# ---------------------------------------------------------------------------

def test_streamed_bit_identical_mandelbrot_image(devs):
    """The acceptance gate, plain path: the chunked double-buffered
    wavefront produces a BIT-identical mandelbrot image (write-side
    streaming: per-chunk D2H issued behind the chunk's launch)."""
    from cekirdekler_tpu.workloads import MANDELBROT_SRC

    w = h = 256
    n = w * h
    vals = (-2.0, -1.25, 2.5 / w, 2.5 / h, w, 64)
    images = {}
    for streamed in (False, True):
        cr = NumberCruncher(devs.subset(2), MANDELBROT_SRC)
        cr.streamed_transfers = streamed
        cr.stream_chunks = 8 if streamed else 0  # pin: engage for sure
        out = ClArray(n, np.float32, name=f"s{streamed}", read=False,
                      write=True)
        for _ in range(3):
            out.compute(cr, 81, "mandelbrot", n, 256, values=vals)
        if streamed:
            assert any(
                c > 1 for c in cr.cores.last_stream_chunks.values()
            ), cr.cores.last_stream_chunks
        images[streamed] = np.asarray(out).copy()
        cr.dispose()
    np.testing.assert_array_equal(images[True], images[False])


@pytest.mark.parametrize("fused", [False, True])
def test_streamed_bit_identical_accumulating_nbody(devs, fused):
    """The acceptance gate, enqueue path × fused dispatch on AND off:
    accumulating n-body velocities (read-side chunk streaming of the
    partial-read velocity operands + chunked flush drain) are
    bit-identical to the monolithic path."""
    from cekirdekler_tpu.workloads import NBODY_SRC, _nbody_rig

    n, iters = 512, 8
    results = {}
    for streamed in (False, True):
        _, (x, y, z), vel = _nbody_rig(n, f"s{int(streamed)}f{int(fused)}")
        cr = NumberCruncher(devs.subset(2), NBODY_SRC)
        cr.fused_dispatch = fused
        cr.streamed_transfers = streamed
        cr.stream_chunks = 4 if streamed else 0
        g = x.next_param(y, z, *vel)
        cr.enqueue_mode = True
        for _ in range(iters):
            g.compute(cr, 82, "nBody", n, 64, values=(n, 1e-4))
        cr.enqueue_mode = False
        results[streamed] = [np.asarray(v).copy() for v in vel]
        cr.dispose()
    for a, b in zip(results[True], results[False]):
        np.testing.assert_array_equal(a, b)


def test_streamed_records_chunk_spans(devs):
    """The observability contract: a streamed phase emits upload-chunk /
    download-chunk spans (distinct kinds from the monolithic upload /
    download), and the chunk counters move."""
    from cekirdekler_tpu.metrics import REGISTRY
    from cekirdekler_tpu.trace.spans import TRACER

    src = """
    __kernel void tri(__global float* a, __global float* o) {
        int i = get_global_id(0);
        o[i] = a[i] * 3.0f;
    }"""
    n = 4096
    cr = NumberCruncher(devs.subset(1), src)
    cr.stream_chunks = 4
    a = ClArray(np.arange(n, dtype=np.float32), name="ta",
                partial_read=True, read_only=True)
    o = ClArray(n, np.float32, name="to", write_only=True)
    TRACER.enable(clear=True)
    try:
        a.next_param(o).compute(cr, 83, "tri", n, 64)
    finally:
        TRACER.disable()
    kinds = {s.kind for s in TRACER.snapshot()}
    assert "upload-chunk" in kinds and "download-chunk" in kinds, kinds
    chunk = {
        k: v for k, v in REGISTRY.snapshot()["counters"].items()
        if k.startswith("ck_stream_chunks_total")
    }
    assert any(v > 0 for v in chunk.values()), chunk
    np.testing.assert_array_equal(np.asarray(o), np.arange(n) * 3.0)
    cr.dispose()


def test_streamed_autotune_defaults_to_measuring_run_then_engages(devs):
    """Production default (stream_chunks=0): call 1 is the monolithic
    measuring run (chunks=1 recorded), a later call engages chunks once
    the model sees transfer worth hiding — and a forced re-partition
    resets the tuner (ck_stream_retune_total moves)."""
    src = """
    __kernel void cp(__global float* a, __global float* o) {
        int i = get_global_id(0);
        o[i] = a[i] + 1.0f;
    }"""
    n = 1 << 16
    cr = NumberCruncher(devs.subset(1), src)
    t = cr.transfer_tuner
    # a synthetic link seed makes transfers look expensive relative to
    # per-chunk overhead, so the second call must engage chunks (the
    # real link's weather would make this test flaky either way)
    t.seed_link(0, 50.0, 50.0)
    a = ClArray(np.zeros(n, np.float32), name="ca", partial_read=True,
                read_only=True)
    o = ClArray(n, np.float32, name="co", write_only=True)
    g = a.next_param(o)
    g.compute(cr, 84, "cp", n, 64)
    assert cr.cores.last_stream_chunks.get(0) == 1  # the measuring run
    # teach the model an expensive link for this key, cheap chunks
    t.observe(0, ("cp",), 8 * n, 40.0, 1.0, 40.0, chunks=1)
    g.compute(cr, 84, "cp", n, 64)
    assert cr.cores.last_stream_chunks.get(0, 1) > 1
    before = t.retunes
    t.on_repartition()
    assert t.retunes == before + 1
    g.compute(cr, 84, "cp", n, 64)  # back to a measuring run
    assert cr.cores.last_stream_chunks.get(0) == 1
    np.testing.assert_array_equal(np.asarray(o), 1.0)
    cr.dispose()


def test_tuner_key_matches_between_choose_and_observe(devs):
    """Regression: choose() and observe() must key the SAME byte count
    for one phase (Cores._stream_key_bytes is the one formula).  A
    read+write partition array rides both the upload and the download
    wavefront (counted twice); a second formula that counted it once
    landed the measuring run's observation in a different power-of-two
    bucket than the lookup — every call was a "first contact" and the
    streamed path was silently dead for such workloads."""
    src = """
    __kernel void bump(__global float* a) {
        int i = get_global_id(0);
        a[i] = a[i] + 1.0f;
    }"""
    n = 1 << 14
    cr = NumberCruncher(devs.subset(1), src)
    t = cr.transfer_tuner
    a = ClArray(np.zeros(n, np.float32), name="rw", partial_read=True)
    a.compute(cr, 85, "bump", n, 64)  # the monolithic measuring run
    w = cr.cores.workers[0]
    expect = cr.cores._stream_key_bytes(w, [a], 0, n, True)
    assert expect == 2 * n * 4  # read AND write wavefronts
    kk = cr.cores._tuner_kernel_key(("bump",), ())
    assert list(t._obs) == [(0, kk, t.bytes_bucket(expect))]
    # dict-shaped value args key on sorted ITEMS — tuple(dict) keeps
    # only the names and would collapse a 100x value change (stale C
    # estimate, no re-measure) into one key
    k1 = cr.cores._tuner_kernel_key(("bump",), {"bump": (1000,)})
    k2 = cr.cores._tuner_kernel_key(("bump",), {"bump": (10,)})
    assert k1 != k2
    assert cr.cores._tuner_kernel_key(
        ("bump",), {"bump": np.zeros(4)}) == (("bump",), None)
    np.testing.assert_array_equal(np.asarray(a), 1.0)
    cr.dispose()


def test_flush_drain_feeds_transfer_benchmarks(devs):
    """The enqueue flush drain attributes each (lane, cid)'s D2H wall
    into Worker.transfer_benchmarks — the feed that lets the balancer's
    transfer floor bind where steady-state enqueue benches carry no
    transfer term at all."""
    src = """
    __kernel void put(__global float* a) {
        int i = get_global_id(0);
        a[i] = a[i] + 2.0f;
    }"""
    n = 1 << 14
    cr = NumberCruncher(devs.subset(2), src)
    a = ClArray(np.zeros(n, np.float32), name="fa", partial_read=True)
    cr.enqueue_mode = True
    for _ in range(3):
        a.compute(cr, 86, "put", n, 64)
    # the drain normalizes by iterations since the last flush (the
    # enqueue benches it floors against are per-ITERATION) — the
    # counter must hold the window series' count here and clear after
    assert cr.cores._flush_iters.get(86) == 3
    cr.enqueue_mode = False  # flush: the drain runs here
    assert cr.cores._flush_iters == {}
    for w in cr.cores.workers[:2]:
        assert w.transfer_benchmarks.get(86, 0.0) > 0.0, (
            w.index, w.transfer_benchmarks)
    # regression: steady-state zero-transfer phases (uploads covered,
    # downloads deferred) must NOT clobber the drain's value — it is
    # the only honest link cost the next rebalance can floor against
    drained = {w.index: w.transfer_benchmarks[86]
               for w in cr.cores.workers[:2]}
    cr.enqueue_mode = True
    for _ in range(2):
        a.compute(cr, 86, "put", n, 64)
    for w in cr.cores.workers[:2]:
        assert w.transfer_benchmarks.get(86, 0.0) > 0.0, (
            "zero-transfer phase clobbered the drain value",
            w.index, drained[w.index], w.transfer_benchmarks)
    cr.enqueue_mode = False
    np.testing.assert_array_equal(np.asarray(a), 10.0)
    cr.dispose()
