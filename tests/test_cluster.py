"""Cluster tier tests: wire format round-trip, balancer math, and a real
localhost cluster (2 server nodes + mainframe) computing correctly."""

import numpy as np
import pytest

import cekirdekler_tpu as ct
from cekirdekler_tpu.arrays.clarray import ClArray
from cekirdekler_tpu.cluster import (
    ClusterAccelerator,
    ClusterLoadBalancer,
    Command,
    CruncherClient,
    CruncherServer,
    Message,
)
from cekirdekler_tpu.cluster.netbuffer import ArrayRecord

SRC = """
__kernel void saxpy(__global float* x, __global float* y, float a) {
    int i = get_global_id(0);
    y[i] = y[i] + a * x[i];
}
"""


def _cpus(n):
    return ct.all_devices().cpus().subset(n)


# -- wire format -------------------------------------------------------------

def test_message_roundtrip():
    data = np.arange(10, dtype=np.float32)
    msg = Message(
        Command.COMPUTE,
        meta={"compute_id": 7, "global_range": 1024},
        strings=["saxpy", "k2"],
        values=[3, 2.5],
        arrays=[ArrayRecord(42, data, flags=5, epw=2, offset=4)],
    )
    decoded = Message.decode(msg.command, msg.encode())
    assert decoded.meta == msg.meta
    assert decoded.strings == ["saxpy", "k2"]
    assert decoded.values == [3, 2.5]
    rec = decoded.arrays[0]
    assert (rec.array_id, rec.flags, rec.epw, rec.offset) == (42, 5, 2, 4)
    np.testing.assert_array_equal(rec.data, data)


# -- cluster balancer --------------------------------------------------------

def test_cluster_balancer_equal_split_lcm_units():
    bal = ClusterLoadBalancer(steps=[256, 512])
    ranges, rem = bal.equal_split(4096)
    assert sum(ranges) + rem == 4096
    assert all(r % 512 == 0 for r in ranges)  # LCM(256,512)=512 chunks


def test_cluster_balancer_rebalance_moves_toward_fast_node():
    bal = ClusterLoadBalancer(steps=[64, 64])
    ranges, rem = bal.equal_split(2048)
    start = list(ranges)
    # node 0 is 4x faster
    for _ in range(8):
        ranges, rem = bal.rebalance(ranges, [10.0, 40.0], 2048)
    assert ranges[0] > start[0]
    assert ranges[0] % 64 == 0 and ranges[1] % 64 == 0
    assert sum(ranges) + rem == 2048


# -- live localhost cluster --------------------------------------------------

@pytest.fixture()
def two_servers():
    s1 = CruncherServer(devices=_cpus(2))
    s2 = CruncherServer(devices=_cpus(2))
    yield s1, s2
    s1.stop()
    s2.stop()


def test_client_setup_control_numdevices(two_servers):
    s1, _ = two_servers
    c = CruncherClient(s1.host, s1.port)
    assert c.setup(SRC) == 2
    assert c.control()
    assert c.num_devices() == 2
    c.close()


def test_cluster_compute_matches_host(two_servers):
    s1, s2 = two_servers
    n = 4096
    x = ClArray(np.arange(n, dtype=np.float32), partial_read=True, read_only=True)
    y = ClArray(np.ones(n, np.float32), partial_read=True)
    cluster = ClusterAccelerator(
        [(s1.host, s1.port), (s2.host, s2.port)], local_devices=_cpus(2)
    )
    try:
        cluster.setup_nodes(SRC)
        for it in range(3):
            want = y.host() + 2.0 * x.host()
            cluster.compute("saxpy", [x, y], 900, n, 64, values=(2.0,))
            np.testing.assert_allclose(y.host(), want, rtol=1e-6)
        shares = cluster.ranges_of(900)
        assert sum(shares) == n
        assert len(shares) == 3  # 2 remote nodes + mainframe
        assert len(cluster.compute_timing(900)) == 3
    finally:
        cluster.dispose()


def test_cluster_write_all_owned_by_mainframe(two_servers):
    """write_all arrays come back from the mainframe only — remote nodes
    must not race full-array writebacks."""
    s1, s2 = two_servers
    n = 1024
    out = ClArray(np.zeros(n, np.float32), read=False, write=True, write_all=True)
    cluster = ClusterAccelerator(
        [(s1.host, s1.port), (s2.host, s2.port)], local_devices=_cpus(2)
    )
    try:
        # write_all semantics: the kernel writes the WHOLE array regardless
        # of its assigned range; exactly one owner copy must win
        cluster.setup_nodes(
            "__kernel void fill(__global float* o, int n)"
            "{ for (int j = 0; j < n; j++) { o[j] = 5.0f; } }"
        )
        cluster.compute("fill", [out], 901, n, 64, values=(n,))
        # the mainframe's chips wrote the whole array: every element set
        np.testing.assert_array_equal(out.host(), np.full(n, 5.0, np.float32))
    finally:
        cluster.dispose()


def test_cluster_balancer_starved_node_recovers():
    bal = ClusterLoadBalancer(steps=[64, 64])
    ranges, rem = bal.equal_split(2048)
    # drive node 1 to its floor with terrible times, then make it fast
    for _ in range(12):
        ranges, rem = bal.rebalance(ranges, [1.0, 1000.0], 2048)
    assert ranges[1] >= 64  # probe share survives
    for _ in range(12):
        ranges, rem = bal.rebalance(ranges, [1000.0, 1.0], 2048)
    assert ranges[1] > 512  # starved node earned its work back


def test_node_failure_mid_run_fails_over_to_mainframe(two_servers):
    """Killing a server between computes must not lose results: the
    mainframe recomputes the dead node's share and the node is dropped."""
    s1, s2 = two_servers
    n = 4096
    x = ClArray(np.arange(n, dtype=np.float32), partial_read=True, read_only=True)
    y = ClArray(np.zeros(n, np.float32), partial_read=True)
    cluster = ClusterAccelerator(
        [(s1.host, s1.port), (s2.host, s2.port)], local_devices=_cpus(2)
    )
    try:
        cluster.setup_nodes(SRC)
        cluster.compute("saxpy", [x, y], 910, n, 64, values=(1.0,))
        np.testing.assert_allclose(y.host(), x.host(), rtol=1e-6)
        s2.stop()  # node dies between iterations
        cluster.compute("saxpy", [x, y], 910, n, 64, values=(1.0,))
        np.testing.assert_allclose(y.host(), 2.0 * x.host(), rtol=1e-6)
        assert len(cluster.clients) == 1  # dead node dropped
        # next compute re-splits across survivors and stays correct
        cluster.compute("saxpy", [x, y], 910, n, 64, values=(1.0,))
        np.testing.assert_allclose(y.host(), 3.0 * x.host(), rtol=1e-6)
    finally:
        cluster.dispose()


def test_concurrent_sessions_do_not_serialize(two_servers):
    """ISSUE 11 satellite: a second concurrent session SETUPs and
    COMPUTEs while the first session is mid-conversation AND mid-compute
    — per-connection session threads, nothing serializes them."""
    import threading

    s1, _ = two_servers
    n = 4096
    a = CruncherClient(s1.host, s1.port)
    b = CruncherClient(s1.host, s1.port)
    try:
        assert a.setup(SRC) == 2
        xa = ClArray(np.arange(n, dtype=np.float32), partial_read=True,
                     read_only=True)
        ya = ClArray(np.ones(n, np.float32), partial_read=True)
        errs: list = []

        def drive_a():
            try:
                for _ in range(6):
                    a.compute(["saxpy"], [xa, ya], 20, 0, n, 64,
                              values=(1.0,))
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        ta = threading.Thread(target=drive_a)
        ta.start()
        # B's whole lifecycle runs while A's session computes
        assert b.setup(SRC) == 2
        xb = ClArray(np.arange(n, dtype=np.float32), partial_read=True,
                     read_only=True)
        yb = ClArray(np.zeros(n, np.float32), partial_read=True)
        b.compute(["saxpy"], [xb, yb], 21, 0, n, 64, values=(3.0,))
        np.testing.assert_allclose(yb.host(), 3.0 * xb.host(), rtol=1e-6)
        ta.join(timeout=60)
        assert not ta.is_alive() and not errs, errs
        np.testing.assert_allclose(
            ya.host(), 1.0 + 6.0 * xa.host(), rtol=1e-6)
    finally:
        a.close()
        b.close()


def test_session_capacity_rejected_with_named_error():
    """Beyond max_sessions a connection is answered with a NAMED error
    (never a hang), and capacity frees when a session ends."""
    import time as _t

    from cekirdekler_tpu.errors import CekirdeklerError

    server = CruncherServer(devices=_cpus(2), max_sessions=1)
    try:
        a = CruncherClient(server.host, server.port)
        assert a.setup(SRC) == 2  # occupies the one session slot
        b = CruncherClient(server.host, server.port)
        with pytest.raises(CekirdeklerError, match="capacity"):
            b.setup(SRC)
        b.close()
        a.close()
        # the freed slot admits a new session (the accept loop reaps
        # dead session threads; poll briefly for the teardown)
        deadline = _t.monotonic() + 10.0
        while True:
            c = CruncherClient(server.host, server.port)
            try:
                assert c.setup(SRC) == 2
                break
            except CekirdeklerError:
                c.close()
                if _t.monotonic() > deadline:
                    raise
                _t.sleep(0.05)
        c.close()
    finally:
        server.stop()


def test_probe_finds_live_servers(two_servers):
    s1, s2 = two_servers
    live = ClusterAccelerator.probe(
        [(s1.host, s1.port), ("127.0.0.1", 1), (s2.host, s2.port)], timeout=0.3
    )
    assert (s1.host, s1.port) in live and (s2.host, s2.port) in live
    assert ("127.0.0.1", 1) not in live


def test_discover_scans_subnet(two_servers):
    """LAN discovery parity (findServer, ClusterAccelerator.cs:77-155):
    probing all 255 host addresses of a subnet finds the live server."""
    s1, _ = two_servers
    live = ClusterAccelerator.discover(s1.port, subnet="127.0.0", timeout=0.3)
    assert ("127.0.0.1", s1.port) in live


def test_cluster_across_real_processes():
    """A server in a SEPARATE python process (true serialization + GIL
    boundary, the reference's actual deployment shape): the cluster
    computes correctly against it plus the local mainframe."""
    import os
    import subprocess
    import sys
    import time as _t

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    proc = subprocess.Popen(
        [sys.executable, "-c", (
            "from cekirdekler_tpu.cluster import CruncherServer\n"
            "import cekirdekler_tpu as ct, sys, time\n"
            "s = CruncherServer(devices=ct.all_devices().cpus().subset(2))\n"
            "print(s.port, flush=True)\n"
            "time.sleep(120)\n"
        )],
        env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, text=True,
    )
    try:
        port = int(proc.stdout.readline().strip())
        n = 2048
        x = ClArray(np.arange(n, dtype=np.float32), partial_read=True, read_only=True)
        y = ClArray(np.ones(n, np.float32), partial_read=True)
        cluster = ClusterAccelerator([("127.0.0.1", port)], local_devices=_cpus(2))
        try:
            cluster.setup_nodes(SRC)
            for _ in range(2):
                cluster.compute(["saxpy"], [x, y], compute_id=1,
                                global_range=n, local_range=64, values=(2.0,))
            np.testing.assert_allclose(
                np.asarray(y), 1.0 + 2 * 2.0 * np.arange(n), rtol=1e-6
            )
        finally:
            cluster.dispose()
    finally:
        proc.kill()
        proc.wait(timeout=10)
