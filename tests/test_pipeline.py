"""Device→device pipeline tests (reference: ClPipeline pushData semantics,
ClPipeline.cs:49-122) on the multi-virtual-device rig."""

import numpy as np

import cekirdekler_tpu as ct
from cekirdekler_tpu.arrays.clarray import ClArray
from cekirdekler_tpu.pipeline.device_pipeline import ClPipeline, DevicePipeline, PipelineStage

N = 256

S1 = """
__kernel void addOne(__global float* a, __global float* b) {
    int i = get_global_id(0);
    b[i] = a[i] + 1.0f;
}
"""
S2 = """
__kernel void timesTwo(__global float* a, __global float* b) {
    int i = get_global_id(0);
    b[i] = a[i] * 2.0f;
}
"""
S3 = """
__kernel void addHidden(__global float* a, __global float* h, __global float* b) {
    int i = get_global_id(0);
    b[i] = a[i] + h[i];
}
__kernel void initHidden(__global float* a, __global float* h, __global float* b) {
    int i = get_global_id(0);
    h[i] = 3.0f;
}
"""


def _stage(src, kernels, **kw):
    st = PipelineStage(src, kernels, global_range=N, local_range=64, **kw)
    st.add_input(ClArray(N, np.float32))
    st.add_output(ClArray(N, np.float32))
    return st


def _cpus(n):
    return ct.all_devices().cpus().subset(n)


def test_three_stage_pipeline_generations():
    """(x+1)*2+3 flows through 3 chips; data pushed at t is valid at push
    t+stages."""
    s1 = _stage(S1, "addOne")
    s2 = _stage(S2, "timesTwo")
    s3 = PipelineStage(S3, "addHidden", global_range=N, local_range=64,
                       init_kernels="initHidden")
    s3.add_input(ClArray(N, np.float32))
    s3.add_hidden(ClArray(N, np.float32))
    s3.add_output(ClArray(N, np.float32))

    pipe = ClPipeline.make([s1, s2, s3], list(_cpus(3)))
    result = np.zeros(N, np.float32)
    outputs = []
    for g in range(8):
        data = np.full(N, float(g), np.float32)
        ready = pipe.push(data, result)
        assert ready == (pipe.push_count >= 3)
        if ready:
            outputs.append(result.copy())
    # first valid result is generation 0: (0+1)*2+3 = 5, then 7, 9, ...
    for j, out in enumerate(outputs):
        want = (j + 1.0) * 2.0 + 3.0
        np.testing.assert_array_equal(out, np.full(N, want, np.float32))
    pipe.dispose()


def test_single_device_pipeline():
    s1 = _stage(S1, "addOne")
    s2 = _stage(S2, "timesTwo")
    pipe = DevicePipeline.make([s1, s2], _cpus(1)[0])
    result = np.zeros(N, np.float32)
    outs = []
    for g in range(5):
        if pipe.feed(np.full(N, float(g), np.float32), result):
            outs.append(result.copy())
    for j, out in enumerate(outs):
        np.testing.assert_array_equal(out, np.full(N, (j + 1.0) * 2.0, np.float32))
    pipe.dispose()


def test_pipeline_performance_report():
    s1 = _stage(S1, "addOne")
    pipe = ClPipeline.make([s1], list(_cpus(1)))
    pipe.push(np.zeros(N, np.float32), np.zeros(N, np.float32))
    report = pipe.performance_report()
    assert "stage 0" in report and "addOne" in report
    pipe.dispose()


def test_feed_async_matches_serial_and_overlaps():
    """feed_async_begin returns while the generation runs on a background
    thread (host-overlap surface, reference feedAsyncBegin/End,
    ClPipeline.cs:2598-2641), and async results equal serial results."""
    import time

    s1 = _stage(S1, "addOne")
    s2 = _stage(S2, "timesTwo")
    pipe = DevicePipeline.make([s1, s2], _cpus(1)[0])
    result = np.zeros(N, np.float32)
    outs = []
    t_begin_max = 0.0
    for g in range(6):
        data = np.full(N, float(g), np.float32)
        t0 = time.perf_counter()
        pipe.feed_async_begin(data)
        t_begin_max = max(t_begin_max, time.perf_counter() - t0)
        # host is free here: mutate the source buffer — the feed snapshotted
        data += 1000.0
        if pipe.feed_async_end(result):
            outs.append(result.copy())
    for j, out in enumerate(outs):
        np.testing.assert_array_equal(out, np.full(N, (j + 1.0) * 2.0, np.float32))
    pipe.dispose()


def test_transition_role_links_stages():
    """TRANSITION arrays carry data stage->stage one generation later
    (reference: DevicePipelineArrayType.TRANSITION, ClPipeline.cs:3171-3206)."""
    from cekirdekler_tpu.pipeline import ArrayRole

    trans = ClArray(N, np.float32)
    s1 = PipelineStage(S1, "addOne", global_range=N, local_range=64)
    s1.add_input(ClArray(N, np.float32))
    s1.add_array(trans, ArrayRole.TRANSITION)  # addOne writes arg 2 = trans
    s2 = PipelineStage(S2, "timesTwo", global_range=N, local_range=64)
    s2.add_array(trans, ArrayRole.INPUT)
    s2.add_array(ClArray(N, np.float32), ArrayRole.OUTPUT)

    pipe = DevicePipeline.make([s1, s2], _cpus(1)[0])
    result = np.zeros(N, np.float32)
    outs = []
    for g in range(5):
        if pipe.feed(np.full(N, float(g), np.float32), result):
            outs.append(result.copy())
    for j, out in enumerate(outs):
        np.testing.assert_array_equal(out, np.full(N, (j + 1.0) * 2.0, np.float32))
    pipe.dispose()


def test_transition_requires_binding_on_next_stage():
    import pytest

    from cekirdekler_tpu.errors import ComputeValidationError
    from cekirdekler_tpu.pipeline import ArrayRole

    s1 = PipelineStage(S1, "addOne", global_range=N, local_range=64)
    s1.add_input(ClArray(N, np.float32))
    s1.add_array(ClArray(N, np.float32), ArrayRole.TRANSITION)
    s2 = _stage(S2, "timesTwo")
    with pytest.raises(ComputeValidationError, match="not bound"):
        DevicePipeline.make([s1, s2], _cpus(1)[0])


def test_multi_chip_stage_owns_its_cruncher():
    """A stage may span multiple chips via a stage-local Cores (reference:
    per-stage cruncher over a ClDevices set, ClPipeline.cs:225-285): the
    stage's range splits across ITS devices while the pipeline still flows
    stage-to-stage."""
    s1 = PipelineStage(S1, "addOne", global_range=N, local_range=64,
                       devices=_cpus(3))
    s1.add_input(ClArray(N, np.float32, partial_read=True))
    s1.add_output(ClArray(N, np.float32))
    s2 = _stage(S2, "timesTwo")

    pipe = ClPipeline.make([s1, s2], list(_cpus(1)))
    assert s1._cores is not None and s1._cores.num_devices == 3
    assert s2._cores is None
    result = np.zeros(N, np.float32)
    outputs = []
    for g in range(6):
        ready = pipe.push(np.full(N, float(g), np.float32), result)
        if ready:
            outputs.append(result.copy())
    for j, out in enumerate(outputs):
        np.testing.assert_array_equal(out, np.full(N, (j + 1.0) * 2.0, np.float32))
    # the multi-chip stage really split its range
    r = s1._cores.ranges_of(1)
    assert len(r) == 3 and sum(r) == N
    pipe.dispose()


def test_multi_chip_final_stage_results():
    """Multi-chip stage as the LAST stage: its host-published outputs feed
    push(results=...) correctly."""
    s1 = _stage(S1, "addOne")
    s2 = PipelineStage(S2, "timesTwo", global_range=N, local_range=64,
                       devices=_cpus(2))
    s2.add_input(ClArray(N, np.float32, partial_read=True))
    s2.add_output(ClArray(N, np.float32))

    pipe = ClPipeline.make([s1, s2], list(_cpus(1)))
    result = np.zeros(N, np.float32)
    got = []
    for g in range(5):
        if pipe.push(np.full(N, float(g), np.float32), result):
            got.append(result.copy())
    for j, out in enumerate(got):
        np.testing.assert_array_equal(out, np.full(N, (j + 1.0) * 2.0, np.float32))
    pipe.dispose()


def test_multi_to_multi_stage_handoff_is_snapshot():
    """Both stages multi-chip: the generation handed to stage B must be a
    SNAPSHOT of stage A's output, not a live alias of A's host buffer
    (A's next-generation compute overwrites it concurrently)."""
    sA = PipelineStage(S1, "addOne", global_range=N, local_range=64,
                       devices=_cpus(2))
    sA.add_input(ClArray(N, np.float32, partial_read=True))
    sA.add_output(ClArray(N, np.float32))
    sB = PipelineStage(S2, "timesTwo", global_range=N, local_range=64,
                       devices=_cpus(2))
    sB.add_input(ClArray(N, np.float32, partial_read=True))
    sB.add_output(ClArray(N, np.float32))

    pipe = ClPipeline.make([sA, sB], [])
    result = np.zeros(N, np.float32)
    got = []
    for g in range(6):
        if pipe.push(np.full(N, float(g), np.float32), result):
            got.append(result.copy())
    for j, out in enumerate(got):
        np.testing.assert_array_equal(out, np.full(N, (j + 1.0) * 2.0, np.float32))
    pipe.dispose()


def test_stage_with_empty_devices_treated_as_unassigned():
    """devices=[] must mean 'unassigned' consistently — the stage draws
    from the pipeline's device list instead of raising StopIteration."""
    s1 = PipelineStage(S1, "addOne", global_range=N, local_range=64, devices=[])
    s1.add_input(ClArray(N, np.float32))
    s1.add_output(ClArray(N, np.float32))
    pipe = ClPipeline.make([s1], list(_cpus(1)))
    assert s1._cores is None and s1.device is not None
    result = np.zeros(N, np.float32)
    for g in range(2):
        pipe.push(np.full(N, float(g), np.float32), result)
    np.testing.assert_array_equal(result, np.full(N, 2.0, np.float32))
    pipe.dispose()
