"""Host array layer tests (reference: Tester.buffers() per-type checks of
ClArray/FastArr indexing, CopyFrom/CopyTo, C#<->native migration,
Tester.cs:7076-7672)."""

import os

import numpy as np
import pytest

from cekirdekler_tpu.arrays import (
    ByteArr,
    ClArray,
    DoubleArr,
    FastArr,
    FloatArr,
    IntArr,
    LongArr,
    ParameterGroup,
    UIntArr,
    wrap,
)
from cekirdekler_tpu.arrays.fastarr import ALIGNMENT, type_code_for_dtype
from cekirdekler_tpu.errors import ComputeValidationError
from cekirdekler_tpu import native


TYPED = [
    (FloatArr, np.float32),
    (DoubleArr, np.float64),
    (IntArr, np.int32),
    (UIntArr, np.uint32),
    (LongArr, np.int64),
    (ByteArr, np.uint8),
]

# env capability, not a code property: the native tier needs a toolchain
# + dlopen environment this container doesn't provide (g++ build of
# libkutuphane_tpu.so fails identically every run here).  Skip with the
# capability named so tier-1 signal stays clean; on rigs where the build
# works the condition is False and these run unchanged.  Designated
# native rigs set CK_REQUIRE_NATIVE=1 to keep the build a HARD gate
# (otherwise a broken toolchain would demote the gate to a silent skip
# everywhere — the build test below would be tautological).
requires_native = pytest.mark.skipif(
    not native.available()
    and os.environ.get("CK_REQUIRE_NATIVE") != "1",
    reason="native library (libkutuphane_tpu.so) does not build/load in "
           "this environment — FastArr falls back to numpy backing "
           "(set CK_REQUIRE_NATIVE=1 to make this a hard failure)",
)


@requires_native
def test_native_library_builds():
    # the native tier must actually build on this machine
    assert native.available()


@pytest.mark.parametrize("cls,dtype", TYPED)
def test_fastarr_roundtrip(cls, dtype):
    fa = cls(1000)
    assert fa.dtype == np.dtype(dtype)
    assert len(fa) == 1000
    fa[0] = 7
    fa[999] = 3
    assert fa[0] == 7 and fa[999] == 3
    src = np.arange(1000).astype(dtype)
    fa.copy_from(src)
    out = np.zeros(1000, dtype=dtype)
    fa.copy_to(out)
    np.testing.assert_array_equal(out, src)
    np.testing.assert_array_equal(fa.to_array(), src)
    fa.dispose()


def test_fastarr_alignment():
    fa = FloatArr(16)
    assert fa.address() % ALIGNMENT == 0
    fa.dispose()


@requires_native
def test_fastarr_native_backing_and_leak_counter():
    lib = native.load()
    assert lib is not None
    before = lib.ck_liveAllocations()
    fa = FloatArr(4096)
    assert fa.is_native
    assert lib.ck_liveAllocations() == before + 1
    fa.dispose()
    assert lib.ck_liveAllocations() == before


def test_type_codes_match_reference_layout():
    assert type_code_for_dtype(np.float32) == 0
    assert type_code_for_dtype(np.float64) == 1
    assert type_code_for_dtype(np.int32) == 2
    assert type_code_for_dtype(np.int64) == 3
    assert type_code_for_dtype(np.uint32) == 4
    assert type_code_for_dtype(np.uint8) == 5


def test_clarray_auto_alloc_and_index():
    a = ClArray(128, dtype=np.float32)
    assert a.size == 128
    a[5] = 2.5
    assert a[5] == 2.5
    assert not a.fast_arr


def test_clarray_migration_numpy_native():
    a = ClArray(64, dtype=np.int32)
    a[:] = np.arange(64, dtype=np.int32)
    a.fast_arr = True
    assert a.fast_arr
    np.testing.assert_array_equal(np.asarray(a), np.arange(64))
    a[3] = -1
    a.fast_arr = False
    assert not a.fast_arr
    assert a[3] == -1


def test_clarray_resize_preserves():
    a = ClArray(np.arange(10, dtype=np.float32))
    a.resize(20)
    assert a.size == 20
    np.testing.assert_array_equal(np.asarray(a)[:10], np.arange(10))
    a.resize(5)
    np.testing.assert_array_equal(np.asarray(a), np.arange(5))


def test_flag_mutual_exclusion():
    a = ClArray(8)
    a.read_only = True
    assert not a.flags.write
    a.write_only = True
    assert not a.flags.read
    with pytest.raises(ComputeValidationError):
        a._set_flag(read_only=True, write_only=True)


def test_read_write_string_parity():
    a = ClArray(8)
    a.partial_read = True
    a.write_all = True
    s = a.flags.read_write_string()
    assert "partial" in s and "read" in s and "write" in s and "all" in s


def test_parameter_group_chaining_order():
    a = ClArray(8, name="a")
    b = ClArray(8, name="b")
    c = np.zeros(8, dtype=np.float32)
    g = a.next_param(b).next_param(c)
    assert isinstance(g, ParameterGroup)
    names = [p.name for p in g.parameters()]
    assert names[0] == "a" and names[1] == "b" and len(names) == 3


def test_wrap_coercions():
    assert isinstance(wrap([1.0, 2.0]), ClArray)
    fa = FloatArr(4)
    w = wrap(fa)
    assert w.fast_arr
    a = ClArray(4)
    assert wrap(a) is a


def test_wrap_structs_roundtrip_through_compute():
    """Struct arrays (reference: wrapArrayOfStructs, ClArray.cs:1058-1074):
    a structured array wraps zero-copy as bytes, one work item per struct,
    and device writes land back in the original struct fields."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    dt = np.dtype([("a", "<f4"), ("b", "<i4")])
    recs = np.zeros(256, dt)
    recs["a"] = np.arange(256, dtype=np.float32)
    recs["b"] = np.arange(256)

    wrapped = ClArray.wrap_structs(recs, name="recs", partial_read=True)
    assert wrapped.size == 256 * dt.itemsize
    assert wrapped.flags.elements_per_work_item == dt.itemsize
    assert wrapped.struct_source is recs

    # one work item per STRUCT: the kernel touches all 8 of its bytes and
    # the epw flag makes transfers move byte ranges while compute ranges
    # count structs — split across 2 devices
    src = """
    __kernel void touch(__global uchar* p) {
        int i = get_global_id(0);
        for (int k = 0; k < 8; k++) {
            p[i*8 + k] = p[i*8 + k];
        }
    }"""
    cr = NumberCruncher(platforms().cpus().subset(2), src)
    try:
        wrapped2 = ClArray.wrap_structs(recs, name="r2", partial_read=True)
        wrapped2.compute(cr, 31, "touch", 256, 64)
        np.testing.assert_array_equal(recs["a"], np.arange(256, dtype=np.float32))
        np.testing.assert_array_equal(recs["b"], np.arange(256))
    finally:
        cr.dispose()

    # zero-copy aliasing: mutating the view mutates the structs
    wrapped.host()[0:4] = np.frombuffer(np.float32(99.0).tobytes(), np.uint8)
    assert recs["a"][0] == 99.0


def test_device_partition_lanes():
    """Device fission analogue (reference: createDeviceAsPartition,
    ClDevice.cs:85-95): one chip split into N scheduler lanes; the range
    splits across lanes and results stay exact."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.hardware import platforms

    dev = platforms().cpus()[0]
    parts = dev.as_partitions(4)
    assert len(parts) == 4
    assert all(p.is_partition for p in parts)
    assert len({p.name for p in parts}) == 4
    # concat dedup must keep all four lanes
    assert len(parts + parts) == 4

    src = """
    __kernel void twice(__global float* x) {
        int i = get_global_id(0);
        x[i] = x[i] * 2.0f;
    }"""
    cr = NumberCruncher(parts, src)
    try:
        x = ClArray(np.arange(1024, dtype=np.float32), name="x", partial_read=True)
        x.compute(cr, 41, "twice", 1024, 64)
        np.testing.assert_allclose(np.asarray(x), np.arange(1024) * 2.0)
        r = cr.ranges_of(41)
        assert len(r) == 4 and sum(r) == 1024
    finally:
        cr.dispose()


def test_fastarr_user_alignment():
    # reference: user-settable alignmentBytes (IBufferOptimization,
    # ClArray.cs:82-149); default stays 4096
    for align in (64, 256, 8192):
        fa = FastArr(100, np.float32, alignment=align)
        assert fa.address() % align == 0
        assert fa.alignment == align
        fa.numpy()[:] = 7.0
        assert float(fa.numpy().sum()) == 700.0
        fa.dispose()
    with pytest.raises(ValueError):
        FastArr(10, np.float32, alignment=100)  # not a power of two
    with pytest.raises(ValueError):
        FastArr(10, np.float64, alignment=4)  # smaller than item size


def test_clarray_alignment_bytes_flag_plumbed():
    from cekirdekler_tpu import ClArray

    a = ClArray(64, np.float32, fast=True, alignment_bytes=64)
    assert a.fast_arr
    assert a._fast.alignment == 64
    assert a.host().ctypes.data % 64 == 0
    # migration keeps the flag's alignment
    b = ClArray(64, np.float32, alignment_bytes=256)
    b.fast_arr = True
    assert b._fast.alignment == 256
    # resize keeps the allocation's alignment
    b.resize(128)
    assert b._fast.alignment == 256
    with pytest.raises(ComputeValidationError):
        ClArray(8, np.float32, alignment_bytes=48)
