"""Shared RFC-8259 sanitizer for the standalone tools.

The in-package version is ``cekirdekler_tpu.utils.jsonsafe`` — the
tools cannot import it (they must run on rigs where jax, and therefore
the package, is broken), so they load THIS file by path via their
``_json_safe`` shim.  Same rules: non-finite floats → ``None``, numpy
scalars → native, ndarrays → sanitized lists, keys → strings, unknown
objects → ``str``.
"""

from __future__ import annotations

import math

__all__ = ["json_safe"]


def json_safe(o):
    if isinstance(o, bool) or o is None or isinstance(o, (str, int)):
        return o
    if isinstance(o, float):
        return o if math.isfinite(o) else None
    if isinstance(o, dict):
        return {str(k): json_safe(v) for k, v in o.items()}
    if isinstance(o, (list, tuple, set, frozenset)):
        return [json_safe(v) for v in o]
    item = getattr(o, "item", None)
    if item is not None and getattr(o, "shape", None) in ((), None):
        try:
            return json_safe(item())
        except Exception:  # noqa: BLE001 - fall through to str()
            pass
    tolist = getattr(o, "tolist", None)
    if tolist is not None:
        try:
            return json_safe(tolist())
        except Exception:  # noqa: BLE001 - fall through to str()
            pass
    return str(o)
