"""Dispatch-floor microbench CLI: sweep enqueue-window size K and print
the per-dispatch overhead, per-iteration vs FUSED dispatch, from trace
spans (the measurement behind ISSUE 3's "collapse the enqueue dispatch
floor"; methodology in ``workloads.dispatch_floor_sweep``).

Run on the target chip from the repo root:

    python tools/dispatch_floor.py [--ks 1,8,32,128] [--n 16384]
                                   [--reps 3] [--json]

Per row: window wall, barrier-fence cost, derived per-dispatch
milliseconds, and the tracer's own launch-span count — the K → K/batch
dispatch-count evidence.  ``--json`` prints the raw artifact (one JSON
line, bench.py's ``dispatch_floor`` section emits the same structure).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_JSONSAFE = None


def _json_safe(o):
    """Delegates to tools/_jsonsafe.py (loaded by file path — this tool
    must run standalone, via `python tools/<name>.py`, AND as an
    importlib-loaded module with no package context)."""
    global _JSONSAFE
    if _JSONSAFE is None:
        import importlib.util

        p = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "_jsonsafe.py")
        spec = importlib.util.spec_from_file_location("ck_tools_jsonsafe", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _JSONSAFE = mod.json_safe
    return _JSONSAFE(o)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ks", default="1,8,32,128",
                    help="comma-separated window sizes")
    ap.add_argument("--n", type=int, default=1 << 14,
                    help="light-kernel array length")
    ap.add_argument("--reps", type=int, default=3,
                    help="windows per row (best kept)")
    ap.add_argument("--local", type=int, default=256, help="local range")
    ap.add_argument("--json", action="store_true",
                    help="print the raw JSON artifact only")
    args = ap.parse_args()

    from cekirdekler_tpu.workloads import dispatch_floor_sweep

    ks = tuple(int(k) for k in args.ks.split(","))
    out = dispatch_floor_sweep(
        ks=ks, n=args.n, local_range=args.local, reps=args.reps
    )
    if args.json:
        print(json.dumps(_json_safe(out), allow_nan=False))
        return
    print(out["note"])
    hdr = (f"{'mode':>10} {'K':>5} {'wall ms':>10} {'fence ms':>10} "
           f"{'per-dispatch ms':>16} {'launches':>9} {'fused wins':>10}")
    print(hdr)
    for r in out["rows"]:
        print(
            f"{'fused' if r['fused'] else 'per-iter':>10} {r['K']:>5} "
            f"{r['wall_ms']:>10.3f} {r['fence_ms']:>10.3f} "
            f"{r['per_dispatch_ms']:>16.4f} {r['launch_spans']:>9} "
            f"{r['fused_windows']:>10}"
        )
    if "floor_collapse_at_kmax" in out:
        print(f"floor collapse at K={max(ks)}: "
              f"{out['floor_collapse_at_kmax']}x")


if __name__ == "__main__":
    main()
