"""The ratchet baseline: findings may only go away.

``baseline.json`` is a checked-in inventory of grandfathered findings
(fingerprint + human-readable location).  The contract, enforced by
:func:`ratchet`:

- a finding NOT in the baseline **fails** the run (new debt is refused);
- a baseline entry with no matching finding ALSO fails ("stale
  baseline") — fixing a finding must shrink the checked-in file in the
  same commit, so the count is monotonically decreasing and reviewable
  in diffs;
- ``--update-baseline`` rewrites the file from the current findings,
  but **refuses to grow** it unless ``--allow-grow`` is also passed —
  adding debt is a deliberate, flagged act, never a reflex.

Fingerprints exclude line numbers (see :class:`~.model.Finding`), so
edits above a grandfathered finding do not churn the baseline; the
stored line is refreshed on every ``--update-baseline`` purely for
human navigation.
"""

from __future__ import annotations

import json
import os

__all__ = ["load_baseline", "save_baseline", "ratchet"]

SCHEMA = "ckcheck-baseline-v1"


def load_baseline(path: str) -> dict:
    """fingerprint → stored row.  A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    return {row["fingerprint"]: row for row in doc.get("findings", ())}


def save_baseline(path: str, findings) -> None:
    rows = sorted(
        (f.to_row() for f in findings), key=lambda r: r["fingerprint"])
    doc = {"schema": SCHEMA, "findings": rows}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
        f.write("\n")
    os.replace(tmp, path)


def ratchet(findings, baseline: dict):
    """``(new, grandfathered, stale)`` — findings not in the baseline,
    findings covered by it, and baseline rows no finding matches."""
    current = {f.fingerprint: f for f in findings}
    new = [f for fp, f in current.items() if fp not in baseline]
    grand = [f for fp, f in current.items() if fp in baseline]
    stale = [row for fp, row in baseline.items() if fp not in current]
    new.sort(key=lambda f: (f.path, f.line))
    stale.sort(key=lambda r: (r.get("path", ""), r.get("line", 0)))
    return new, grand, stale
