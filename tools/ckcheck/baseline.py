"""The ratchet baseline: findings may only go away.

``baseline.json`` is a checked-in inventory of grandfathered findings
(fingerprint + human-readable location).  The contract, enforced by
:func:`ratchet`:

- a finding NOT in the baseline **fails** the run (new debt is refused);
- a baseline entry with no matching finding ALSO fails ("stale
  baseline") — fixing a finding must shrink the checked-in file in the
  same commit, so the count is monotonically decreasing and reviewable
  in diffs;
- ``--update-baseline`` rewrites the file from the current findings,
  but **refuses to grow** it unless ``--allow-grow`` is also passed —
  adding debt is a deliberate, flagged act, never a reflex.

Fingerprints exclude line numbers (see :class:`~.model.Finding`), so
edits above a grandfathered finding do not churn the baseline; the
stored line is refreshed on every ``--update-baseline`` purely for
human navigation.

Every save stamps a **provenance header** (tool name + tool version +
the HEAD short-sha at the moment the ratchet was burned): a stale
entry failure names the commit its baseline was written at
(:func:`provenance_note`), so triage starts from an anchor instead of
``git log`` archaeology.  The header is shared by every ratcheted tool
(ckcheck, ckprove, ckmodel) and rendered by each CLI's
``--explain provenance``.
"""

from __future__ import annotations

import json
import os
import subprocess
import time

__all__ = [
    "load_baseline",
    "load_baseline_doc",
    "save_baseline",
    "ratchet",
    "provenance_note",
]

SCHEMA = "ckcheck-baseline-v1"

#: Bump when a tool's finding vocabulary/fingerprint rule changes in a
#: way that invalidates old baselines (shared counter on purpose: the
#: three ratchets ride one loader).
TOOL_VERSION = 2


def _head_sha(repo_root: str | None = None) -> str:
    """HEAD's short sha, or ``"unknown"`` outside a usable git repo —
    provenance must never fail a baseline write."""
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=10)
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else "unknown"
    except Exception:  # noqa: BLE001 - no git, no sha, no failure
        return "unknown"


def load_baseline(path: str) -> dict:
    """fingerprint → stored row.  A missing file is an empty baseline."""
    return {row["fingerprint"]: row
            for row in load_baseline_doc(path).get("findings", ())}


def load_baseline_doc(path: str) -> dict:
    """The whole baseline document (findings + provenance header).  A
    missing file is an empty doc; a pre-provenance file (PRs 7-12)
    loads with ``provenance`` absent."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def save_baseline(path: str, findings, tool: str = "ckcheck") -> None:
    rows = sorted(
        (f.to_row() for f in findings), key=lambda r: r["fingerprint"])
    doc = {
        "schema": SCHEMA,
        "provenance": {
            "tool": tool,
            "tool_version": TOOL_VERSION,
            "head": _head_sha(),
            "updated_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        },
        "findings": rows,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
        f.write("\n")
    os.replace(tmp, path)


def provenance_note(doc: dict) -> str:
    """One human line anchoring a baseline in history — appended to
    stale-entry failures so the triager knows which commit the ratchet
    was burned at, and rendered by ``--explain provenance``."""
    prov = (doc or {}).get("provenance")
    if not prov:
        return ("baseline carries no provenance header (written before "
                "PR 13) — re-burn with --update-baseline to anchor it")
    return (f"baseline burned by {prov.get('tool', '?')} "
            f"v{prov.get('tool_version', '?')} at commit "
            f"{prov.get('head', 'unknown')} "
            f"({prov.get('updated_at', 'undated')})")


def ratchet(findings, baseline: dict):
    """``(new, grandfathered, stale)`` — findings not in the baseline,
    findings covered by it, and baseline rows no finding matches."""
    current = {f.fingerprint: f for f in findings}
    new = [f for fp, f in current.items() if fp not in baseline]
    grand = [f for fp, f in current.items() if fp in baseline]
    stale = [row for fp, row in baseline.items() if fp not in current]
    new.sort(key=lambda f: (f.path, f.line))
    stale.sort(key=lambda r: (r.get("path", ""), r.get("line", 0)))
    return new, grand, stale
