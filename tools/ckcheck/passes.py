"""The five ckcheck passes over a scanned :class:`~.model.Package`.

1. **lock-order** — build the acquisition-order graph (edge ``A → B``
   when ``B`` is acquired while ``A`` is held, interprocedurally), flag
   cycles, and flag re-acquisition of a non-reentrant lock along one
   flow (the PR 6 tracer deadlock shape: ``snapshot()`` called under
   the tracer lock which ``_sync_dropped_metric`` also takes).
2. **lockset** — Eraser-style: for classes in thread-spawning modules,
   every attribute touched both under and outside any common lock is a
   candidate race (the seed-era enqueue/rebalance lost-update shape).
3. **hotpath** — functions reachable from the declared hot roots must
   not call registry get-or-create, must not take locks outside the
   allowlist, and must not compute telemetry arguments outside an
   ``.enabled`` guard (the PR 4/5/6 cached-handles review discipline).
4. **invariant** — artifact writers keep ``headline`` last; emitted
   span/flight/decision kinds are declared in their vocabulary tuples;
   ``json.dumps`` on export paths is Infinity/NaN-safe.
5. **blocking** — zero-argument ``join()``/``wait()``/``get()`` calls
   (unbounded blocking: the shutdown-hang shape) must carry a timeout
   or a ``# ckcheck: ok`` annotation naming the design.

Each pass returns ``list[Finding]``; suppression comments
(``# ckcheck: ok`` / ``guarded-by`` / ``cold``) are honored here.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .flow import entry_contexts, reachable_from
from .model import Finding, LIFECYCLE_METHODS, Package

__all__ = ["AnalyzerConfig", "run_passes", "lock_order_edges"]


@dataclass
class AnalyzerConfig:
    """Per-repo knobs.  The defaults describe cekirdekler_tpu; fixture
    tests construct their own."""

    # pass 3 roots: the declared hot set (qualnames relative to the
    # scanned package root)
    hot_roots: tuple = ()
    # locks the hot path MAY take (lock_ids)
    hot_lock_allow: tuple = ()
    # pass 4 vocabularies: (module, tuple-variable) declaring the
    # legal span/flight/decision kinds; None disables the rule
    span_vocab: tuple | None = None     # ("trace.spans", "SPAN_KINDS")
    event_vocab: tuple | None = None    # ("obs.flight", "EVENT_KINDS")
    decision_vocab: tuple | None = None  # ("obs.decisions", "DECISION_KINDS")
    req_vocab: tuple | None = None      # ("obs.reqtrace", "REQ_EVENT_KINDS")
    # passes to run (all by default)
    passes: tuple = ("lock-order", "lockset", "hotpath", "invariant",
                     "blocking")


# ---------------------------------------------------------------------------
# pass 1: lock-order graph
# ---------------------------------------------------------------------------

def lock_order_edges(pkg: Package) -> dict:
    """``{(held_id, acquired_id): (path, line)}`` — first evidence site
    per ordered pair, interprocedural (entry contexts included)."""
    ctxs = entry_contexts(pkg)
    edges: dict = {}
    for q, fi in pkg.functions.items():
        entry = ctxs.get(q) or {frozenset()}
        for site in fi.acq_sites:
            for e in entry:
                for h in set(e) | set(site.held):
                    if h == site.lock.lock_id:
                        continue
                    key = (h, site.lock.lock_id)
                    edges.setdefault(key, (fi.path, site.line))
    return edges


def _cycles(edges: dict) -> list:
    """SCCs with more than one node in the order graph (Tarjan)."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan (the package's call depth is small but the
        # analyzer must not rely on Python recursion limits)
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def pass_lock_order(pkg: Package) -> list:
    findings: list = []
    ctxs = entry_contexts(pkg)
    edges = lock_order_edges(pkg)
    for scc in _cycles(edges):
        ev_path, ev_line = None, 0
        for (a, b), (p, ln) in sorted(edges.items()):
            if a in scc and b in scc:
                ev_path, ev_line = p, ln
                break
        findings.append(Finding(
            pass_id="lock-order", rule="order-cycle",
            path=ev_path or "?", line=ev_line,
            subject="<->".join(scc),
            message=(
                "lock-order cycle: " + " -> ".join(scc + [scc[0]]) +
                " — two flows acquire these locks in opposite order "
                "(deadlock when they interleave)"),
        ))
    for q, fi in pkg.functions.items():
        entry = ctxs.get(q) or {frozenset()}
        mod = pkg.modules.get(fi.module)
        for site in fi.acq_sites:
            if site.lock.reentrant or site.conditional:
                continue
            if site.receiver not in ("self", "singleton"):
                continue  # different instances of one lock class are fine
            held_ids = set(site.held)
            entry_hit = any(site.lock.lock_id in e for e in entry)
            if site.lock.lock_id in held_ids or entry_hit:
                if mod and mod.suppressed(site.line):
                    continue
                how = ("already held on this flow" if site.lock.lock_id
                       in held_ids else "held by a caller on some flow")
                findings.append(Finding(
                    pass_id="lock-order", rule="reacquire",
                    path=fi.path, line=site.line,
                    subject=f"{q}:{site.lock.lock_id}",
                    message=(
                        f"{q} re-acquires non-reentrant "
                        f"{site.lock.lock_id} ({how}) — self-deadlock"),
                ))
    return findings


# ---------------------------------------------------------------------------
# pass 2: lockset race detection
# ---------------------------------------------------------------------------

@dataclass
class _AttrSites:
    writes: list = field(default_factory=list)   # (fi, access, locksets)
    reads: list = field(default_factory=list)


def _site_lockset(entry: set, held: tuple) -> frozenset:
    """Locks guaranteed held at a site = locks held on EVERY path:
    intersection of (entry ∪ local) over entry contexts."""
    combos = [frozenset(e | set(held)) for e in (entry or {frozenset()})]
    out = combos[0]
    for c in combos[1:]:
        out &= c
    return out


def pass_lockset(pkg: Package) -> list:
    findings: list = []
    ctxs = entry_contexts(pkg)
    per_attr: dict = {}
    for q, fi in pkg.functions.items():
        method = q.rsplit(".", 1)[-1]
        if method in LIFECYCLE_METHODS:
            continue
        mod = pkg.modules.get(fi.module)
        entry = ctxs.get(q) or {frozenset()}
        for acc in fi.attr_accesses:
            owner = acc.owner
            if owner is None:
                continue
            owner_mod = pkg.classes[owner].module
            if not pkg.modules[owner_mod].spawns_threads:
                continue
            if acc.attr.startswith("__"):
                continue
            sup = mod.suppressed(acc.line) if mod else None
            if sup and sup[0] == "ok":
                continue
            lockset = _site_lockset(entry, acc.held)
            if sup and sup[0] == "guarded-by":
                # protocol-guarded: trust the annotation, treat the
                # named lock as held
                name = sup[1].split()[0] if sup[1] else ""
                cands = pkg.locks_named(name.rsplit(".", 1)[-1]) if name else []
                lockset = lockset | {c.lock_id for c in cands[:1]} if cands \
                    else lockset | {f"<protocol:{name or 'declared'}>"}
            rec = per_attr.setdefault((owner, acc.attr), _AttrSites())
            (rec.writes if acc.is_write else rec.reads).append(
                (fi, acc, lockset))

    for (owner, attr), rec in sorted(per_attr.items()):
        if not rec.writes:
            continue
        ci = pkg.classes[owner]
        owner_module = pkg.modules.get(ci.module)
        init_line = ci.attr_init_lines.get(attr)
        if owner_module and init_line and \
                owner_module.suppressed(init_line, kinds=("ok",)):
            continue  # attribute-level suppression at its __init__ line
        # the guard set comes from WRITE sites only: a config flag read
        # under some other lock by coincidence must not make that lock
        # look like the attribute's guard
        guards = frozenset().union(*(s[2] for s in rec.writes)) \
            if rec.writes else frozenset()
        write_guards = [s[2] for s in rec.writes if s[2]]
        if not write_guards:
            continue  # never write-locked: thread-confined or by design
        sites = rec.writes + rec.reads
        common = sites[0][2]
        for s in sites[1:]:
            common = common & s[2]
        if common:
            continue  # a consistent guard exists
        guards = frozenset().union(*write_guards)
        unlocked = [s for s in sites if not (s[2] & guards)]
        if not unlocked:
            continue
        unlocked_writes = [s for s in unlocked if s[1].is_write]
        rule = "mixed-guard" if unlocked_writes else "unguarded-read"
        anchor = (unlocked_writes or unlocked)[0]
        guard_names = sorted(guards)
        un_lines = sorted({f"{s[0].path}:{s[1].line}" for s in unlocked})
        what = ("written" if unlocked_writes else "read")
        consequence = (
            "lost-update / torn-state candidate" if unlocked_writes else
            "stale/torn read candidate")
        findings.append(Finding(
            pass_id="lockset", rule=rule,
            path=anchor[0].path, line=anchor[1].line,
            subject=f"{owner}.{attr}",
            message=(
                f"{owner}.{attr} is written under {guard_names} but "
                f"{what} with no common lock at "
                f"{', '.join(un_lines[:6])}"
                f"{' …' if len(un_lines) > 6 else ''} — {consequence} "
                "(annotate `# ckcheck: ok <why>` at the site or at the "
                "attribute's __init__ line if lock-free access is by "
                "design)"),
        ))
    return findings


# ---------------------------------------------------------------------------
# pass 3: hot-path discipline
# ---------------------------------------------------------------------------

def pass_hotpath(pkg: Package, cfg: AnalyzerConfig) -> list:
    findings: list = []
    if not cfg.hot_roots:
        return findings
    hot = reachable_from(pkg, set(cfg.hot_roots))
    allow = set(cfg.hot_lock_allow)
    for q in sorted(hot):
        fi = pkg.functions[q]
        mod = pkg.modules.get(fi.module)
        for rc in fi.registry_calls:
            if mod and mod.suppressed(rc.line):
                continue
            findings.append(Finding(
                pass_id="hotpath", rule="get-or-create",
                path=fi.path, line=rc.line,
                subject=f"{q}:REGISTRY.{rc.method}:{rc.name or '?'}",
                message=(
                    f"{q} (hot path) calls REGISTRY.{rc.method}"
                    f"({rc.name!r}) — get-or-create pays a dict lookup + "
                    "possible registry lock per call; cache the handle "
                    "at construction (the PR 4 discipline)"),
            ))
        for site in fi.acq_sites:
            if site.lock.lock_id in allow:
                continue
            if mod and mod.suppressed(site.line):
                continue
            findings.append(Finding(
                pass_id="hotpath", rule="hot-lock",
                path=fi.path, line=site.line,
                subject=f"{q}:{site.lock.lock_id}",
                message=(
                    f"{q} (hot path) acquires {site.lock.lock_id}, which "
                    "is not in the hot-path lock allowlist"),
            ))
        for tc in fi.telemetry_calls:
            if not tc.computed_args or tc.enabled_guarded:
                continue
            if mod and mod.suppressed(tc.line):
                continue
            findings.append(Finding(
                pass_id="hotpath", rule="telemetry-alloc",
                path=fi.path, line=tc.line,
                subject=f"{q}:{tc.api}:{tc.kind or '?'}",
                message=(
                    f"{q} (hot path) computes arguments for a telemetry "
                    f"call ({tc.method} {tc.kind!r}) outside an "
                    "`.enabled` guard — the f-string/concat/call "
                    "allocates even when recording is off"),
            ))
    return findings


# ---------------------------------------------------------------------------
# pass 4: invariant lints
# ---------------------------------------------------------------------------

def _load_vocab(pkg: Package, spec) -> set | None:
    if spec is None:
        return None
    modname, varname = spec
    mod = pkg.modules.get(modname)
    if mod is None:
        return None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == varname:
                    try:
                        return set(ast.literal_eval(node.value))
                    except Exception:  # noqa: BLE001 - computed vocab
                        return None
    return None


def pass_invariant(pkg: Package, cfg: AnalyzerConfig) -> list:
    findings: list = []
    span_kinds = _load_vocab(pkg, cfg.span_vocab)
    event_kinds = _load_vocab(pkg, cfg.event_vocab)
    decision_kinds = _load_vocab(pkg, cfg.decision_vocab)
    req_kinds = _load_vocab(pkg, cfg.req_vocab)
    vocabs = {"span": (span_kinds, "SPAN_KINDS"),
              "event": (event_kinds, "EVENT_KINDS"),
              "decision": (decision_kinds, "DECISION_KINDS"),
              "reqevent": (req_kinds, "REQ_EVENT_KINDS")}
    for q, fi in sorted(pkg.functions.items()):
        mod = pkg.modules.get(fi.module)

        for line in fi.dict_literal_headline:
            if mod and mod.suppressed(line):
                continue
            findings.append(Finding(
                pass_id="invariant", rule="headline-last",
                path=fi.path, line=line, subject=f"{q}:dict",
                message=(
                    f"{q} builds an artifact dict whose 'headline' key "
                    "is not last — the driver's 2000-char tail recovery "
                    "depends on headline being the final key"),
            ))
        # sequenced writes: result["headline"] = ... then result[x] = ...
        by_base: dict = {}
        for sa in fi.subscript_assigns:
            by_base.setdefault(sa.base, []).append(sa)
        for base, sas in by_base.items():
            hl = [s for s in sas if s.key == "headline"]
            if not hl:
                continue
            last_hl = max(s.stmt_index for s in hl)
            after = [s for s in sas
                     if s.stmt_index > last_hl and s.key != "headline"]
            for s in after:
                if mod and mod.suppressed(s.line):
                    continue
                findings.append(Finding(
                    pass_id="invariant", rule="headline-last",
                    path=fi.path, line=s.line,
                    subject=f"{q}:{base}[{s.key!r}]",
                    message=(
                        f"{q} assigns {base}[{s.key!r}] after "
                        f"{base}['headline'] — headline must stay the "
                        "final key of the artifact"),
                ))

        for tc in fi.telemetry_calls:
            vocab, what = vocabs.get(tc.api, (None, "?"))
            if vocab is None or tc.kind is None or tc.kind in vocab:
                continue
            if mod and mod.suppressed(tc.line):
                continue
            findings.append(Finding(
                pass_id="invariant", rule="undeclared-kind",
                path=fi.path, line=tc.line,
                subject=f"{tc.api}:{tc.kind}",
                message=(
                    f"{q} emits {tc.api} kind {tc.kind!r} which is not "
                    f"declared in {what} — declare it (and document it: "
                    "lint_obs checks the doc side)"),
            ))

        for jc in fi.json_calls:
            if jc.has_allow_nan_false or jc.sanitized:
                continue
            if mod and mod.suppressed(jc.line):
                continue
            findings.append(Finding(
                pass_id="invariant", rule="json-unsafe",
                path=fi.path, line=jc.line, subject=f"{q}:json@{jc.line}",
                message=(
                    f"{q} calls json.dumps/dump without allow_nan=False "
                    "or json_safe(...) — a float('inf')/nan anywhere in "
                    "the payload serializes as bare `Infinity`/`NaN` "
                    "(RFC-8259-invalid; the PR 6 /healthz bug class), "
                    "and numpy scalars raise TypeError mid-export"),
            ))
    return findings


# ---------------------------------------------------------------------------
# pass 5: unbounded blocking
# ---------------------------------------------------------------------------

def pass_blocking(pkg: Package) -> list:
    """Zero-argument ``Thread.join()`` / ``Condition.wait()`` /
    ``Queue.get()`` waits forever when its counterpart thread died —
    the serve dispatcher and the per-device driver queues are
    shutdown-hang hazards of exactly this shape.  Every such site must
    carry a timeout (re-check the predicate in a loop) or a
    ``# ckcheck: ok <why>`` annotation naming why unbounded blocking
    is the design (sentinel-terminated daemon loops, user-triggered
    gates)."""
    findings: list = []
    for q, fi in sorted(pkg.functions.items()):
        mod = pkg.modules.get(fi.module)
        for bc in fi.blocking_calls:
            if mod and mod.suppressed(bc.line):
                continue
            findings.append(Finding(
                pass_id="blocking", rule="unbounded-blocking",
                path=fi.path, line=bc.line,
                subject=f"{q}:{bc.method}",
                message=(
                    f"{q} calls .{bc.method}() with no timeout — blocks "
                    "forever if the counterpart thread died (shutdown-"
                    "hang hazard); pass a timeout and re-check in a "
                    "loop, or annotate `# ckcheck: ok <why>`"),
            ))
    return findings


# ---------------------------------------------------------------------------

def run_passes(pkg: Package, cfg: AnalyzerConfig) -> list:
    findings: list = []
    # a file that failed to parse is a finding, not a silent skip
    for mod in pkg.modules.values():
        err = getattr(mod.tree, "_ckcheck_syntax_error", None)
        if err:
            findings.append(Finding(
                pass_id="invariant", rule="syntax-error", path=mod.path,
                line=0, subject=mod.modname, message=f"unparseable: {err}"))
    if "lock-order" in cfg.passes:
        findings.extend(pass_lock_order(pkg))
    if "lockset" in cfg.passes:
        findings.extend(pass_lockset(pkg))
    if "hotpath" in cfg.passes:
        findings.extend(pass_hotpath(pkg, cfg))
    if "invariant" in cfg.passes:
        findings.extend(pass_invariant(pkg, cfg))
    if "blocking" in cfg.passes:
        findings.extend(pass_blocking(pkg))
    order = {"lock-order": 0, "lockset": 1, "hotpath": 2, "invariant": 3,
             "blocking": 4}
    findings.sort(key=lambda f: (order.get(f.pass_id, 9), f.path, f.line))
    return findings
