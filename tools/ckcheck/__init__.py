"""ckcheck — repo-wide concurrency & hot-path static analyzer.

Four pure-``ast`` passes over ``cekirdekler_tpu/`` (lock-order graph,
Eraser-style lockset race detection, hot-path discipline, invariant
lints) against a ratcheted baseline.  See docs/STATIC_ANALYSIS.md and
``python -m tools.ckcheck --help``.
"""

from .baseline import load_baseline, ratchet, save_baseline
from .cli import analyze_repo, main, repo_config
from .model import Finding, Package, scan_package
from .passes import AnalyzerConfig, lock_order_edges, run_passes

__all__ = [
    "AnalyzerConfig",
    "Finding",
    "Package",
    "analyze_repo",
    "lock_order_edges",
    "load_baseline",
    "main",
    "ratchet",
    "repo_config",
    "run_passes",
    "save_baseline",
    "scan_package",
]
