"""ckcheck shared model: pure-``ast`` scanning of a Python package into
the structures every pass consumes.

No imports of the scanned code, ever — the same contract as
``tools/lint_obs.py``: the analyzer must run on rigs where jax (or the
package itself) is broken, because "the analyzer is down" and "the
runtime is down" must never be the same outage.

What one scan produces (:class:`Package`):

- **Lock inventory** — every ``self._x = threading.Lock()`` /
  ``RLock()`` / ``Condition()`` assignment and every module-level lock,
  as :class:`Lock` records with a stable ``lock_id``
  (``module.Class.attr``).  Lock identity is CLASS-level (lockdep-style
  lock classes): every ``Worker.lock`` instance is one node in the
  order graph.
- **Function inventory** — every function/method (including nested
  closures, which run on OTHER threads in this codebase: driver-queue
  dispatch closures must not inherit the submitter's held-set).
- **Receiver typing** — a small, deliberately under-approximate type
  resolver: ``self``, annotated parameters, ``x = ClassName(...)``
  locals, ``self.x = ClassName(...)`` attributes recorded from any
  method, module-level singletons (``TRACER = Tracer()``) resolved
  through package-internal imports, and ``for w in self.workers`` loops
  over attributes typed as lists.  Anything unresolved produces NO call
  edge / NO lock event — under-approximation keeps the passes' findings
  worth reading (a missed edge is a known blind spot the dynamic
  witness covers; a fabricated edge is analyzer noise forever).
- **Per-function flow events** — lock acquisitions with the locally
  held set at each point, call sites with targets + held set, ``self``
  attribute reads/writes, registry get-or-create calls, telemetry
  calls, ``json.dumps`` sites, zero-arg blocking calls: everything
  the five passes need, from ONE walk per function.

Suppression vocabulary (trailing comments, same line or the line
above)::

    # ckcheck: guarded-by <lock-attr>   -- this access IS protected (by
    #                                       protocol the analyzer cannot
    #                                       see); treat as locked
    # ckcheck: ok <reason>              -- finding acknowledged as
    #                                       intentional; suppressed
    # ckcheck: cold <reason>            -- on a `def` line: hot-path
    #                                       reachability stops here
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "Lock",
    "FuncInfo",
    "Module",
    "Package",
    "scan_package",
]

_SUPPRESS_RE = re.compile(
    r"#\s*ckcheck:\s*(ok|guarded-by|cold)\b[ \t]*([^\n]*)")

#: threading factory callables that create a lock-like object.
_LOCK_FACTORIES = {
    "Lock": ("lock", False),
    "RLock": ("rlock", True),
    "Condition": ("condition", False),
}

#: Registry get-or-create method names (the hot-path pass's target).
REGISTRY_FACTORIES = ("counter", "gauge", "histogram")

#: Method names whose calls mutate their receiver in place — a call
#: ``self.attr.append(x)`` is a WRITE of ``self.attr`` for the lockset
#: pass.
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "clear", "add", "discard", "update", "setdefault",
}

#: Methods excluded from the lockset pass: construction and teardown
#: run single-threaded by contract.
LIFECYCLE_METHODS = {"__init__", "__new__", "__del__", "__exit__",
                     "dispose", "close", "shutdown", "stop"}


@dataclass(frozen=True)
class Finding:
    """One analyzer finding.  The fingerprint deliberately excludes the
    line number so the ratchet baseline survives unrelated edits above
    the finding; ``subject`` carries the stable identity (lock ids,
    ``Class.attr``, callee) instead."""

    pass_id: str
    rule: str
    path: str
    line: int
    subject: str
    message: str

    @property
    def fingerprint(self) -> str:
        raw = f"{self.pass_id}:{self.rule}:{self.path}:{self.subject}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"[{self.fingerprint}] {self.pass_id}/{self.rule} "
                f"{self.path}:{self.line}: {self.message}")

    def to_row(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "pass": self.pass_id,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "subject": self.subject,
            "message": self.message,
        }


@dataclass(frozen=True)
class Lock:
    lock_id: str          # "core.worker.Worker.lock" / "native.build._lock"
    attr: str             # attribute or module-global name
    owner: str | None     # owning class qualname, None for module-level
    module: str
    path: str
    line: int
    reentrant: bool
    kind: str             # lock | rlock | condition


@dataclass
class AcqSite:
    """One lock acquisition point inside a function."""

    lock: Lock
    line: int
    held: tuple           # lock_ids locally held when acquiring
    receiver: str         # "self" | "singleton" | "name" | "attr"
    conditional: bool     # an `x if c else nullcontext()` style item


@dataclass
class CallSite:
    targets: tuple        # resolved callee qualnames (possibly empty)
    line: int
    held: tuple           # lock_ids locally held at the call


@dataclass
class AttrAccess:
    attr: str
    line: int
    held: tuple
    is_write: bool
    via_mutator: bool = False
    owner: str | None = None   # owning class qualname (self OR typed receiver)


@dataclass
class RegistryCall:
    method: str           # counter | gauge | histogram
    name: str | None      # literal first arg when present
    line: int


@dataclass
class TelemetryCall:
    api: str              # "span" (tracer) | "event" (flight) | "decision"
    method: str           # record | instant | span | event
    kind: str | None      # literal first arg
    line: int
    computed_args: bool   # any argument allocates (f-string/concat/call)
    enabled_guarded: bool # lexically inside an `if X.enabled:` branch


@dataclass
class JsonDumpCall:
    line: int
    has_allow_nan_false: bool
    sanitized: bool       # first arg wrapped in json_safe(...)


@dataclass
class BlockingCall:
    """A zero-argument ``.join()`` / ``.wait()`` / ``.get()`` call —
    the unbounded-blocking shapes (Thread.join, Condition/Event.wait,
    Queue.get) that hang shutdown when the counterpart thread died.
    Any argument bounds the wait (a timeout) or marks a non-blocking
    receiver (``str.join(parts)``, ``dict.get(key)``), so only the
    bare form is recorded."""

    method: str           # join | wait | get
    line: int


@dataclass
class SubscriptAssign:
    base: str             # name of the subscripted variable
    key: str | None       # literal string key when present
    line: int
    stmt_index: int       # order within the enclosing function body walk


@dataclass
class FuncInfo:
    qualname: str
    module: str
    cls: str | None
    path: str
    node: ast.AST
    lineno: int
    is_nested: bool = False
    cold: str | None = None          # reason when annotated `# ckcheck: cold`
    acq_sites: list = field(default_factory=list)
    call_sites: list = field(default_factory=list)
    attr_accesses: list = field(default_factory=list)
    registry_calls: list = field(default_factory=list)
    telemetry_calls: list = field(default_factory=list)
    json_calls: list = field(default_factory=list)
    blocking_calls: list = field(default_factory=list)
    subscript_assigns: list = field(default_factory=list)
    dict_literal_headline: list = field(default_factory=list)  # bad lines

    @property
    def is_public(self) -> bool:
        name = self.qualname.rsplit(".", 1)[-1]
        return not name.startswith("_") or (
            name.startswith("__") and name.endswith("__"))


@dataclass
class ClassInfo:
    qualname: str
    module: str
    bases: tuple = ()                 # package-internal base qualnames
    methods: dict = field(default_factory=dict)   # name -> FuncInfo
    attr_types: dict = field(default_factory=dict)  # attr -> ("inst"|"list", cls)
    locks: dict = field(default_factory=dict)       # attr -> Lock
    attr_init_lines: dict = field(default_factory=dict)  # attr -> first line


@dataclass
class Module:
    modname: str
    path: str             # repo-relative
    tree: ast.AST
    suppress: dict        # line -> (kind, arg)
    comment_lines: frozenset = frozenset()  # comment-only line numbers
    imports: dict = field(default_factory=dict)   # local name -> fully.qualified
    spawns_threads: bool = False

    def suppressed(self, line: int, kinds=("ok", "guarded-by")):
        """Suppression record covering ``line``: on the line itself, or
        anywhere in the contiguous block of comment-only lines directly
        above it (a multi-line justification keeps working)."""
        rec = self.suppress.get(line)
        if rec is not None and rec[0] in kinds:
            return rec
        ln = line - 1
        while ln > 0 and ln in self.comment_lines:
            rec = self.suppress.get(ln)
            if rec is not None and rec[0] in kinds:
                return rec
            ln -= 1
        return None


_THREAD_SPAWN_RE = re.compile(
    r"threading\.Thread\(|Thread\(|ThreadPoolExecutor\(|"
    r"ThreadingHTTPServer\(|_DriverQueue\(|\.start\(\)"
)


class Package:
    """Everything the passes need, from one scan."""

    def __init__(self, root: str, pkg_name: str):
        self.root = root
        self.pkg_name = pkg_name
        self.modules: dict[str, Module] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.locks: dict[str, Lock] = {}
        self.singletons: dict[str, str] = {}   # "mod.NAME" -> class qualname

    # -- lookups -------------------------------------------------------------
    def class_lock(self, cls: str, attr: str) -> Lock | None:
        """Lock ``attr`` on ``cls``, walking package-internal bases."""
        seen = set()
        while cls and cls not in seen:
            seen.add(cls)
            ci = self.classes.get(cls)
            if ci is None:
                return None
            if attr in ci.locks:
                return ci.locks[attr]
            cls = ci.bases[0] if ci.bases else None
        return None

    def class_method(self, cls: str, name: str) -> FuncInfo | None:
        seen = set()
        while cls and cls not in seen:
            seen.add(cls)
            ci = self.classes.get(cls)
            if ci is None:
                return None
            if name in ci.methods:
                return ci.methods[name]
            cls = ci.bases[0] if ci.bases else None
        return None

    def class_attr_type(self, cls: str, attr: str):
        seen = set()
        while cls and cls not in seen:
            seen.add(cls)
            ci = self.classes.get(cls)
            if ci is None:
                return None
            if attr in ci.attr_types:
                return ci.attr_types[attr]
            cls = ci.bases[0] if ci.bases else None
        return None

    def locks_named(self, attr: str, module: str | None = None) -> list[Lock]:
        out = [l for l in self.locks.values() if l.attr == attr]
        if module is not None:
            mod_out = [l for l in out if l.module == module]
            if mod_out:
                return mod_out
        return out


# ---------------------------------------------------------------------------
# scanning
# ---------------------------------------------------------------------------

def _collect_suppressions(source: str):
    """(line → suppression, comment-only line set)."""
    out = {}
    comments = set()
    for i, line in enumerate(source.splitlines(), 1):
        if line.lstrip().startswith("#"):
            comments.add(i)
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = (m.group(1), m.group(2).strip())
    return out, frozenset(comments)


def _iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _modname(root: str, path: str, pkg_name: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    if mod == "__init__":
        mod = pkg_name
    return mod


def _lock_factory(call: ast.expr):
    """(kind, reentrant) when ``call`` is threading.Lock()/RLock()/
    Condition() (or a bare imported name), else None."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        if fn.value.id == "threading":
            name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return _LOCK_FACTORIES.get(name) if name else None


def scan_package(root: str, pkg_name: str | None = None,
                 extra_paths: tuple = (), repo_root: str | None = None
                 ) -> Package:
    """Parse every ``.py`` under ``root`` (plus ``extra_paths`` files,
    scanned for the invariant pass only) into a :class:`Package`.
    ``repo_root`` anchors the repo-relative paths findings carry."""
    pkg_name = pkg_name or os.path.basename(os.path.normpath(root))
    repo_root = repo_root or os.path.dirname(os.path.normpath(root))
    pkg = Package(root, pkg_name)

    paths = [(p, _modname(root, p, pkg_name)) for p in _iter_py_files(root)]
    for p in extra_paths:
        rel = os.path.relpath(p, repo_root)
        paths.append((p, rel[:-3].replace(os.sep, ".")))

    # phase A: parse, inventory classes/locks/singletons/imports
    for path, modname in paths:
        with open(path) as f:
            source = f.read()
        try:
            tree = ast.parse(source)
        except SyntaxError as e:  # a broken file is itself a finding later
            tree = ast.Module(body=[], type_ignores=[])
            tree._ckcheck_syntax_error = str(e)  # type: ignore[attr-defined]
        suppress, comment_lines = _collect_suppressions(source)
        mod = Module(
            modname=modname,
            path=os.path.relpath(path, repo_root),
            tree=tree,
            suppress=suppress,
            comment_lines=comment_lines,
            spawns_threads=bool(_THREAD_SPAWN_RE.search(source)),
        )
        pkg.modules[modname] = mod
        _inventory_module(pkg, mod)

    # phase B: resolve singletons and attribute types now that EVERY
    # class is known (phase A's file order must not decide whether
    # `self.workers = [Worker(...)]` resolves)
    for mod in pkg.modules.values():
        _inventory_singletons(pkg, mod)
    for mod in pkg.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                qual = _class_qual_in_module(mod, node)
                ci = pkg.classes.get(qual)
                if ci is None:
                    continue
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        _inventory_attr_types(pkg, mod, ci, item)

    # phase C: per-function flow walks (needs full inventory)
    for mod in pkg.modules.values():
        _walk_module_functions(pkg, mod)
    return pkg


def _resolve_import(mod: Module, pkg: Package, node: ast.ImportFrom) -> None:
    """Map ``from ..x.y import NAME`` to ``x.y.NAME`` within the
    package (absolute or relative)."""
    if node.module is None and node.level == 0:
        return
    if node.level > 0:
        parts = mod.modname.split(".")
        # level=1 strips the module's own name, deeper levels strip
        # parents; for a package __init__ the modname IS the package
        base = parts[: len(parts) - node.level]
        target = ".".join(base + (node.module.split(".") if node.module else []))
    else:
        target = node.module or ""
        if target.startswith(pkg.pkg_name + "."):
            target = target[len(pkg.pkg_name) + 1:]
        elif target == pkg.pkg_name:
            target = ""
    for alias in node.names:
        local = alias.asname or alias.name
        mod.imports[local] = f"{target}.{alias.name}" if target else alias.name


def _inventory_module(pkg: Package, mod: Module) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.ImportFrom):
            _resolve_import(mod, pkg, node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            fac = _lock_factory(node.value)
            if isinstance(t, ast.Name) and fac:
                lock = Lock(
                    lock_id=f"{mod.modname}.{t.id}", attr=t.id, owner=None,
                    module=mod.modname, path=mod.path, line=node.lineno,
                    reentrant=fac[1], kind=fac[0],
                )
                pkg.locks[lock.lock_id] = lock
        elif isinstance(node, ast.ClassDef):
            _inventory_class(pkg, mod, node)


def _inventory_class(pkg: Package, mod: Module, node: ast.ClassDef) -> None:
    qual = f"{mod.modname}.{node.name}"
    bases = []
    for b in node.bases:
        if isinstance(b, ast.Name):
            target = mod.imports.get(b.id, b.id)
            bases.append(target if "." in target else f"{mod.modname}.{b.id}")
    ci = ClassInfo(qualname=qual, module=mod.modname, bases=tuple(bases))
    pkg.classes[qual] = ci
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FuncInfo(
                qualname=f"{qual}.{item.name}", module=mod.modname,
                cls=qual, path=mod.path, node=item, lineno=item.lineno,
            )
            rec = mod.suppress.get(item.lineno) or mod.suppress.get(
                item.lineno - 1)
            if rec and rec[0] == "cold":
                fi.cold = rec[1] or "annotated cold"
            ci.methods[item.name] = fi
            pkg.functions[fi.qualname] = fi
            _inventory_self_assigns(pkg, mod, ci, item)
        elif isinstance(item, ast.ClassDef):
            _inventory_class(pkg, mod, item)  # nested class (rare)


def _self_attr_assigns(fn: ast.AST):
    """(target_attr, value, line) for every ``self.X = ...`` /
    ``self.X: T = ...`` in ``fn``, skipping nested functions."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if isinstance(node, ast.AnnAssign):
            t, value = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t, value = node.targets[0], node.value
        else:
            continue
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == "self":
            yield t.attr, value, node.lineno


def _inventory_self_assigns(pkg: Package, mod: Module, ci: ClassInfo,
                            fn: ast.AST) -> None:
    """Phase A: lock attributes + attribute init lines (syntactic —
    needs no cross-module class knowledge)."""
    for attr, value, lineno in _self_attr_assigns(fn):
        ci.attr_init_lines.setdefault(attr, lineno)
        fac = _lock_factory(value) if value is not None else None
        if fac:
            lock = Lock(
                lock_id=f"{ci.qualname}.{attr}", attr=attr,
                owner=ci.qualname, module=mod.modname, path=mod.path,
                line=lineno, reentrant=fac[1], kind=fac[0],
            )
            ci.locks[attr] = lock
            pkg.locks[lock.lock_id] = lock


def _inventory_attr_types(pkg: Package, mod: Module, ci: ClassInfo,
                          fn: ast.AST) -> None:
    """Phase B: ``self.X = ClassName(...)`` / ``[ClassName(...)]``
    receiver types, resolved against the COMPLETE class inventory."""
    for attr, value, _lineno in _self_attr_assigns(fn):
        if value is None or attr in ci.locks:
            continue
        cls = _constructed_class(mod, pkg, value)
        if cls:
            ci.attr_types.setdefault(attr, cls)


def _constructed_class(mod: Module, pkg: Package, value: ast.expr):
    """("inst"|"list", qualname) for ``ClassName(...)`` /
    ``[ClassName(...) ...]`` / ``REGISTRY.counter(...)`` values."""
    if isinstance(value, ast.Call):
        fn = value.func
        if isinstance(fn, ast.Name):
            target = mod.imports.get(fn.id, None)
            qual = target if target else f"{mod.modname}.{fn.id}"
            if qual in pkg.classes:
                return ("inst", qual)
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            # REGISTRY.counter(...) -> metrics.registry.Counter etc.
            recv = fn.value.id
            sing = mod.imports.get(recv, f"{mod.modname}.{recv}")
            cls = pkg.singletons.get(sing)
            if cls and fn.attr in REGISTRY_FACTORIES:
                owner_mod = cls.rsplit(".", 1)[0]
                target = f"{owner_mod}.{fn.attr.capitalize()}"
                if target in pkg.classes:
                    return ("inst", target)
    if isinstance(value, (ast.List, ast.ListComp)):
        elts = value.elts if isinstance(value, ast.List) else [value.elt]
        for e in elts:
            r = _constructed_class(mod, pkg, e)
            if r and r[0] == "inst":
                return ("list", r[1])
    return None


def _class_qual_in_module(mod: Module, node: ast.ClassDef) -> str:
    return f"{mod.modname}.{node.name}"


def _inventory_singletons(pkg: Package, mod: Module) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                isinstance(node.value.func, ast.Name):
            cls = f"{mod.modname}.{node.value.func.id}"
            if cls in pkg.classes:
                pkg.singletons[f"{mod.modname}.{node.targets[0].id}"] = cls


# ---------------------------------------------------------------------------
# per-function flow walk
# ---------------------------------------------------------------------------

class _FuncWalker:
    """One walk of one function body: locally-held lock tracking,
    typed receiver resolution, event recording."""

    def __init__(self, pkg: Package, mod: Module, fi: FuncInfo,
                 outer_types: dict | None = None):
        self.pkg = pkg
        self.mod = mod
        self.fi = fi
        # local name -> class qualname (under-approximate)
        self.types: dict[str, str] = dict(outer_types or {})
        # local name -> tuple of method qualnames (bound-method aliases:
        # `engine = self._run_a if c else self._run_b; engine(...)`)
        self.method_aliases: dict[str, tuple] = {}
        self.stmt_counter = 0
        self._collect_param_types()

    # -- typing --------------------------------------------------------------
    def _class_by_name(self, name: str) -> str | None:
        target = self.mod.imports.get(name)
        qual = target if target else f"{self.mod.modname}.{name}"
        return qual if qual in self.pkg.classes else None

    def _collect_param_types(self) -> None:
        node = self.fi.node
        args = getattr(node, "args", None)
        if args is None:
            return
        for a in list(args.posonlyargs) + list(args.args) + \
                list(args.kwonlyargs):
            ann = a.annotation
            name = None
            if isinstance(ann, ast.Name):
                name = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                name = ann.value.split(".")[-1]
            elif isinstance(ann, ast.BinOp):  # "Worker | None"
                for side in (ann.left, ann.right):
                    if isinstance(side, ast.Name) and side.id != "None":
                        name = side.id
                        break
            if name:
                cls = self._class_by_name(name)
                if cls:
                    self.types[a.arg] = cls

    def expr_type(self, node: ast.expr) -> str | None:
        if isinstance(node, ast.Name):
            if node.id == "self" and self.fi.cls:
                return self.fi.cls
            if node.id in self.types:
                return self.types[node.id]
            sing = self.mod.imports.get(node.id, f"{self.mod.modname}.{node.id}")
            return self.pkg.singletons.get(sing)
        if isinstance(node, ast.Attribute):
            base = self.expr_type(node.value)
            if base:
                t = self.pkg.class_attr_type(base, node.attr)
                if t and t[0] == "inst":
                    return t[1]
            return None
        if isinstance(node, ast.IfExp):
            return self.expr_type(node.body) or self.expr_type(node.orelse)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name):
                cls = self._class_by_name(node.func.id)
                if cls:
                    return cls
        return None

    # -- lock resolution -----------------------------------------------------
    def resolve_lock(self, node: ast.expr):
        """(Lock, receiver_kind) or None for a with-item / enter_context
        argument."""
        if isinstance(node, ast.IfExp):
            for branch in (node.body, node.orelse):
                r = self.resolve_lock(branch)
                if r:
                    return (r[0], r[1], True)
            return None
        if isinstance(node, ast.Attribute):
            base_t = self.expr_type(node.value)
            if base_t:
                lock = self.pkg.class_lock(base_t, node.attr)
                if lock:
                    recv = ("self" if isinstance(node.value, ast.Name)
                            and node.value.id == "self" else
                            ("singleton" if isinstance(node.value, ast.Name)
                             and self.pkg.singletons.get(
                                 self.mod.imports.get(
                                     node.value.id,
                                     f"{self.mod.modname}.{node.value.id}"))
                             else "name"))
                    return (lock, recv, False)
            # fall back: unique attribute name (module first, package next)
            cands = self.pkg.locks_named(node.attr, self.mod.modname)
            if len(cands) == 1:
                return (cands[0], "attr", False)
            return None
        if isinstance(node, ast.Name):
            lid = f"{self.mod.modname}.{node.id}"
            if lid in self.pkg.locks:
                return (self.pkg.locks[lid], "name", False)
            imported = self.mod.imports.get(node.id)
            if imported and imported in self.pkg.locks:
                return (self.pkg.locks[imported], "name", False)
        return None

    # -- call resolution -----------------------------------------------------
    def _method_ref(self, node: ast.expr) -> tuple:
        """Qualnames a bound-method REFERENCE (no call) resolves to."""
        if isinstance(node, ast.IfExp):
            return self._method_ref(node.body) + self._method_ref(node.orelse)
        if isinstance(node, ast.Attribute):
            base_t = self.expr_type(node.value)
            if base_t:
                m = self.pkg.class_method(base_t, node.attr)
                if m is not None:
                    return (m.qualname,)
        return ()

    def resolve_call(self, node: ast.Call) -> tuple:
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id in self.method_aliases:
                return self.method_aliases[fn.id]
            qual = self.mod.imports.get(fn.id, f"{self.mod.modname}.{fn.id}")
            if qual in self.pkg.functions:
                return (qual,)
            return ()
        if isinstance(fn, ast.Attribute):
            base_t = self.expr_type(fn.value)
            if base_t:
                m = self.pkg.class_method(base_t, fn.attr)
                if m is not None:
                    return (m.qualname,)
            # ClassName.method(...) (static-style)
            if isinstance(fn.value, ast.Name):
                cls = self._class_by_name(fn.value.id)
                if cls:
                    m = self.pkg.class_method(cls, fn.attr)
                    if m is not None:
                        return (m.qualname,)
        return ()

    def registry_call(self, node: ast.Call):
        """(method, literal name) when this is a REGISTRY get-or-create."""
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in REGISTRY_FACTORIES):
            return None
        recv_is_registry = False
        if isinstance(fn.value, ast.Name):
            if fn.value.id == "REGISTRY":  # conventional singleton name
                recv_is_registry = True
            else:
                t = self.expr_type(fn.value)
                recv_is_registry = bool(t and t.endswith("MetricsRegistry"))
        if not recv_is_registry:
            return None
        name = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
        return (fn.attr, name)

    def telemetry_call(self, node: ast.Call):
        """(api, method, literal kind) for tracer/flight/decision
        record sites."""
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return None
        api = None
        if fn.attr in ("record", "instant", "span"):
            t = self.expr_type(fn.value)
            named = isinstance(fn.value, ast.Name) and fn.value.id == "TRACER"
            if named or (t and t.endswith(".Tracer")):
                api = "span"
            elif fn.attr == "record":
                # the decision log shares the tracer's method name;
                # receiver disambiguates (DECISIONS singleton / a typed
                # DecisionLog)
                named_d = isinstance(fn.value, ast.Name) and \
                    fn.value.id == "DECISIONS"
                if named_d or (t and t.endswith(".DecisionLog")):
                    api = "decision"
        elif fn.attr == "event":
            t = self.expr_type(fn.value)
            named = isinstance(fn.value, ast.Name) and \
                fn.value.id in ("FLIGHT",)
            if named or (t and t.endswith(".FlightRecorder")):
                api = "event"
            else:
                # the request-lifecycle recorder shares the method
                # name; receiver disambiguates (REQTRACE singleton / a
                # typed ReqTrace), and its kind is the SECOND
                # positional — event(rid, kind, **fields)
                named_r = isinstance(fn.value, ast.Name) and \
                    fn.value.id == "REQTRACE"
                if named_r or (t and t.endswith(".ReqTrace")):
                    api = "reqevent"
        if api is None:
            return None
        kind = None
        kind_i = 1 if api == "reqevent" else 0
        if len(node.args) > kind_i \
                and isinstance(node.args[kind_i], ast.Constant) \
                and isinstance(node.args[kind_i].value, str):
            kind = node.args[kind_i].value
        return (api, fn.attr, kind)

    # -- the walk ------------------------------------------------------------
    def walk(self) -> None:
        body = getattr(self.fi.node, "body", [])
        self._walk_stmts(body, held=(), enabled_guard=False)

    def _walk_stmts(self, stmts, held: tuple, enabled_guard: bool) -> None:
        for st in stmts:
            self.stmt_counter += 1
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._nested_function(st)
                continue
            if isinstance(st, ast.With):
                new_held = held
                for item in st.items:
                    ctx = item.context_expr
                    r = self.resolve_lock(ctx)
                    if r:
                        lock, recv, cond = (r + (False,))[:3]
                        self.fi.acq_sites.append(AcqSite(
                            lock=lock, line=ctx.lineno, held=new_held,
                            receiver=recv, conditional=bool(cond)))
                        if lock.lock_id not in new_held:
                            new_held = new_held + (lock.lock_id,)
                    else:
                        self._scan_expr(ctx, new_held, enabled_guard)
                # `stack.enter_context(<lock>)` acquisitions anywhere in
                # the body (the ExitStack all-worker-locks ladder) hold
                # for the remainder of the with block — approximated as
                # held for the WHOLE body, which only over-holds the
                # statements before the enter_context call
                for lock, recv, line in self._enter_context_locks(st.body):
                    self.fi.acq_sites.append(AcqSite(
                        lock=lock, line=line, held=new_held,
                        receiver=recv, conditional=False))
                    if lock.lock_id not in new_held:
                        new_held = new_held + (lock.lock_id,)
                self._walk_stmts(st.body, new_held, enabled_guard)
                continue
            if isinstance(st, ast.If):
                self._scan_expr(st.test, held, enabled_guard)
                guard = enabled_guard or self._is_enabled_test(st.test)
                self._walk_stmts(st.body, held, guard)
                self._walk_stmts(st.orelse, held, enabled_guard)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._type_loop_target(st)
                self._scan_expr(st.iter, held, enabled_guard)
                self._walk_stmts(st.body, held, enabled_guard)
                self._walk_stmts(st.orelse, held, enabled_guard)
                continue
            if isinstance(st, ast.While):
                self._scan_expr(st.test, held, enabled_guard)
                self._walk_stmts(st.body, held, enabled_guard)
                self._walk_stmts(st.orelse, held, enabled_guard)
                continue
            if isinstance(st, ast.Try):
                self._walk_stmts(st.body, held, enabled_guard)
                for h in st.handlers:
                    self._walk_stmts(h.body, held, enabled_guard)
                self._walk_stmts(st.orelse, held, enabled_guard)
                self._walk_stmts(st.finalbody, held, enabled_guard)
                continue
            if isinstance(st, ast.Assign):
                self._record_assign(st, held)
                self._scan_expr(st.value, held, enabled_guard)
                for t in st.targets:
                    self._scan_target(t, held)
                continue
            if isinstance(st, ast.AugAssign):
                self._scan_expr(st.value, held, enabled_guard)
                self._record_augassign(st, held)
                continue
            if isinstance(st, (ast.Expr, ast.Return)):
                if st.value is not None:
                    self._scan_expr(st.value, held, enabled_guard)
                continue
            if isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    self._scan_expr(st.value, held, enabled_guard)
                continue
            # other statements: scan child expressions generically
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._scan_expr(child, held, enabled_guard)

    def _enter_context_locks(self, body) -> list:
        """``enter_context(<resolvable lock>)`` calls in ``body``,
        skipping nested function definitions (closures run elsewhere)."""
        out = []
        stack = list(body)
        while stack:
            st = stack.pop()
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                continue
            for n in ast.iter_child_nodes(st):
                stack.append(n)
            if isinstance(st, ast.Call) and \
                    isinstance(st.func, ast.Attribute) and \
                    st.func.attr == "enter_context" and st.args:
                r = self.resolve_lock(st.args[0])
                if r:
                    out.append((r[0], r[1], st.lineno))
        return out

    @staticmethod
    def _is_enabled_test(test: ast.expr) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr == "enabled":
                return True
        return False

    def _nested_function(self, node) -> None:
        """Closures get their own FuncInfo with an EMPTY held-set: in
        this codebase nested defs are dispatch closures that run on
        driver threads, never under the definer's locks."""
        qual = f"{self.fi.qualname}.<locals>.{node.name}"
        fi = FuncInfo(
            qualname=qual, module=self.fi.module, cls=self.fi.cls,
            path=self.fi.path, node=node, lineno=node.lineno, is_nested=True,
        )
        self.pkg.functions[qual] = fi
        _FuncWalker(self.pkg, self.mod, fi, outer_types=self.types).walk()

    def _type_loop_target(self, st) -> None:
        """``for w in self.workers`` / ``for i, w in enumerate(...)``."""
        it = st.iter
        elt_cls = None
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate" and it.args:
            inner = it.args[0]
        else:
            inner = it
        t = None
        if isinstance(inner, ast.Attribute):
            base = self.expr_type(inner.value)
            if base:
                t = self.pkg.class_attr_type(base, inner.attr)
        elif isinstance(inner, ast.Name) and inner.id in self.types:
            pass  # plain instance — not iterable typing
        if t and t[0] == "list":
            elt_cls = t[1]
        if elt_cls is None:
            return
        tgt = st.target
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate" and isinstance(tgt, ast.Tuple) \
                and len(tgt.elts) == 2 and isinstance(tgt.elts[1], ast.Name):
            self.types[tgt.elts[1].id] = elt_cls
        elif isinstance(tgt, ast.Name):
            self.types[tgt.id] = elt_cls

    def _type_comp_target(self, gen: ast.comprehension) -> None:
        inner = gen.iter
        if isinstance(inner, ast.Call) and isinstance(inner.func, ast.Name) \
                and inner.func.id == "enumerate" and inner.args:
            src, tgt_idx = inner.args[0], 1
        else:
            src, tgt_idx = inner, None
        t = None
        if isinstance(src, ast.Attribute):
            base = self.expr_type(src.value)
            if base:
                t = self.pkg.class_attr_type(base, src.attr)
        if not (t and t[0] == "list"):
            return
        tgt = gen.target
        if tgt_idx is not None and isinstance(tgt, ast.Tuple) and \
                len(tgt.elts) == 2 and isinstance(tgt.elts[1], ast.Name):
            self.types[tgt.elts[1].id] = t[1]
        elif tgt_idx is None and isinstance(tgt, ast.Name):
            self.types[tgt.id] = t[1]

    def _record_assign(self, st: ast.Assign, held: tuple) -> None:
        if len(st.targets) == 1 and isinstance(st.targets[0], ast.Name):
            cls = self.expr_type(st.value)
            if cls:
                self.types[st.targets[0].id] = cls
            refs = self._method_ref(st.value)
            if refs:
                self.method_aliases[st.targets[0].id] = refs
        for t in st.targets:
            if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                key = None
                sl = t.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    key = sl.value
                self.fi.subscript_assigns.append(SubscriptAssign(
                    base=t.value.id, key=key, line=st.lineno,
                    stmt_index=self.stmt_counter))
            if isinstance(t, ast.Tuple):
                # `a, self.x = ...` swaps count as attribute writes
                for e in t.elts:
                    self._maybe_attr_write(e, held)
            else:
                self._maybe_attr_write(t, held)

    def _record_augassign(self, st: ast.AugAssign, held: tuple) -> None:
        self._maybe_attr_write(st.target, held)
        # `self.x[k] += v` / `self.x |= v` hit the same attribute
        t = st.target
        if isinstance(t, ast.Subscript):
            self._maybe_attr_write(t.value, held)

    def _attr_owner(self, node: ast.Attribute) -> str | None:
        """Owning package class of an attribute access — the receiver's
        resolved type (``self`` or a typed variable like ``w: Worker``)."""
        owner = self.expr_type(node.value)
        return owner if owner in self.pkg.classes else None

    def _maybe_attr_write(self, node: ast.expr, held: tuple) -> None:
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            owner = self._attr_owner(node)
            if owner:
                self.fi.attr_accesses.append(AttrAccess(
                    attr=node.attr, line=node.lineno, held=held,
                    is_write=True, owner=owner))

    def _scan_target(self, node: ast.expr, held: tuple) -> None:
        # subscript stores `self.x[k] = v` count as writes of self.x
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Attribute):
            owner = self._attr_owner(node.value)
            if owner:
                self.fi.attr_accesses.append(AttrAccess(
                    attr=node.value.attr, line=node.lineno, held=held,
                    is_write=True, owner=owner))

    def _scan_expr(self, node: ast.expr, held: tuple,
                   enabled_guard: bool) -> None:
        # comprehension loop vars first: `[w.x for w in self.workers]`
        # must type `w` before the body's attribute reads resolve
        for n in ast.walk(node):
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
                for gen in n.generators:
                    self._type_comp_target(gen)
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._record_call(n, held, enabled_guard)
            elif isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
                owner = self._attr_owner(n)
                if owner:
                    self.fi.attr_accesses.append(AttrAccess(
                        attr=n.attr, line=n.lineno, held=held,
                        is_write=False, owner=owner))
            elif isinstance(n, (ast.Lambda, ast.ListComp, ast.SetComp,
                                ast.DictComp, ast.GeneratorExp)):
                pass  # walked generically; held-set applies unchanged

    def _record_call(self, node: ast.Call, held: tuple,
                     enabled_guard: bool) -> None:
        fn = node.func
        # enter_context(<lock>) acquisitions are recorded by the With
        # handler's body pre-scan (they hold for the rest of the block)
        if isinstance(fn, ast.Attribute) and fn.attr == "enter_context" \
                and node.args and self.resolve_lock(node.args[0]):
            return
        # mutator calls on resolvable attributes are writes
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            tgt = fn.value
            if isinstance(tgt, ast.Subscript):
                tgt = tgt.value
            if isinstance(tgt, ast.Attribute):
                owner = self._attr_owner(tgt)
                if owner:
                    self.fi.attr_accesses.append(AttrAccess(
                        attr=tgt.attr, line=node.lineno, held=held,
                        is_write=True, via_mutator=True, owner=owner))
        reg = self.registry_call(node)
        if reg:
            self.fi.registry_calls.append(RegistryCall(
                method=reg[0], name=reg[1], line=node.lineno))
        tel = self.telemetry_call(node)
        if tel:
            computed = any(
                not isinstance(a, (ast.Constant, ast.Name, ast.Attribute))
                for a in list(node.args) + [k.value for k in node.keywords]
            )
            self.fi.telemetry_calls.append(TelemetryCall(
                api=tel[0], method=tel[1], kind=tel[2], line=node.lineno,
                computed_args=computed, enabled_guarded=enabled_guard))
        # zero-arg blocking primitives: join()/wait()/get() with no
        # timeout and no operands (pass 5, unbounded-blocking)
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("join", "wait", "get") and \
                not node.args and not node.keywords:
            self.fi.blocking_calls.append(
                BlockingCall(method=fn.attr, line=node.lineno))
        # json.dumps / json.dump
        if isinstance(fn, ast.Attribute) and fn.attr in ("dumps", "dump") \
                and isinstance(fn.value, ast.Name) and fn.value.id == "json":
            allow_nan_false = any(
                k.arg == "allow_nan" and
                isinstance(k.value, ast.Constant) and k.value.value is False
                for k in node.keywords
            )
            sanitized = bool(
                node.args and isinstance(node.args[0], ast.Call) and
                isinstance(node.args[0].func, ast.Name) and
                node.args[0].func.id in ("json_safe", "_json_safe")
            )
            self.fi.json_calls.append(JsonDumpCall(
                line=node.lineno, has_allow_nan_false=allow_nan_false,
                sanitized=sanitized))
        # dict literals with a non-final "headline" key
        for a in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(a, ast.Dict):
                self._check_headline_dict(a)
        targets = self.resolve_call(node)
        self.fi.call_sites.append(CallSite(
            targets=targets, line=node.lineno, held=held))

    def _check_headline_dict(self, node: ast.Dict) -> None:
        keys = [k.value for k in node.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)]
        if "headline" in keys and keys and keys[-1] != "headline":
            self.fi.dict_literal_headline.append(node.lineno)


def _walk_module_functions(pkg: Package, mod: Module) -> None:
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FuncInfo(
                qualname=f"{mod.modname}.{node.name}", module=mod.modname,
                cls=None, path=mod.path, node=node, lineno=node.lineno,
            )
            rec = mod.suppress.get(node.lineno) or mod.suppress.get(
                node.lineno - 1)
            if rec and rec[0] == "cold":
                fi.cold = rec[1] or "annotated cold"
            pkg.functions[fi.qualname] = fi
            _FuncWalker(pkg, mod, fi).walk()
        elif isinstance(node, ast.ClassDef):
            _walk_class_functions(pkg, mod, node)
        elif isinstance(node, (ast.Assign, ast.Expr, ast.If, ast.Try)):
            # module-level code: walk as an anonymous entry (rare)
            pass


def _walk_class_functions(pkg: Package, mod: Module,
                          node: ast.ClassDef, prefix: str = "") -> None:
    qual = f"{mod.modname}.{prefix}{node.name}"
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = pkg.functions.get(f"{qual}.{item.name}")
            if fi is None:
                fi = FuncInfo(
                    qualname=f"{qual}.{item.name}", module=mod.modname,
                    cls=qual, path=mod.path, node=item, lineno=item.lineno,
                )
                pkg.functions[fi.qualname] = fi
            _FuncWalker(pkg, mod, fi).walk()
        elif isinstance(item, ast.ClassDef):
            _walk_class_functions(pkg, mod, item, prefix=f"{prefix}{node.name}.")
