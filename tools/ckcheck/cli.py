"""``python -m tools.ckcheck`` — the repo-wide concurrency & hot-path
static analyzer with a ratcheted baseline (docs/STATIC_ANALYSIS.md).

Import-free with respect to the analyzed code (pure ``ast``, the
``lint_obs`` contract): runs anywhere, including rigs where jax is
broken.  Exit 0 = no findings beyond the checked-in baseline AND no
stale baseline entries; anything else exits 1 with the findings.

Usage::

    python -m tools.ckcheck                  # the CI gate
    python -m tools.ckcheck --explain <fp>   # one finding, full detail
    python -m tools.ckcheck --update-baseline [--allow-grow]
    python -m tools.ckcheck --json           # machine-readable dump
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import (
    load_baseline,
    load_baseline_doc,
    provenance_note,
    ratchet,
    save_baseline,
)
from .model import scan_package
from .passes import AnalyzerConfig, run_passes

__all__ = ["main", "analyze_repo", "repo_config", "REPO"]

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")

#: The declared hot set: the fused deferral path, the driver-queue
#: submit paths, the flight-ring append, the tracer record paths, and
#: the device-capture correlation marks (every ladder/chunk launch
#: calls begin/end behind a plain `.enabled` guard — annotation work
#: must stay behind that guard and never grow a lock or a registry
#: get-or-create).  Anything these reach (minus `# ckcheck: cold`
#: window boundaries) must obey the cached-handle / allowlisted-lock /
#: no-alloc-telemetry discipline.
HOT_ROOTS = (
    "core.cores.Cores._fused_defer",
    "core.worker._DriverQueue.submit",
    "core.worker.Worker.dispatch_async",
    "core.worker.Worker.stream_dispatch_async",
    "obs.flight.FlightRecorder.event",
    # the request-lifecycle append (ISSUE 19): always on, rides every
    # serve submit/dispatch — GIL-atomic deque append, no locks, no
    # registry traffic, same budget class as FlightRecorder.event
    "obs.reqtrace.ReqTrace.event",
    "trace.spans.Tracer.t0",
    "trace.spans.Tracer.record",
    "trace.spans.Tracer.instant",
    "trace.device.DeviceMarks.begin",
    "trace.device.DeviceMarks.end",
    # the serving tier's submit→coalesce path (ISSUE 11): every client
    # request pays submit; the decision-record inputs stay behind
    # DECISIONS.enabled, tenant metric handles are cached at first
    # sight, and only the allowlisted frontend/table locks may be taken
    "serve.frontend.ServeFrontend.submit",
    "serve.admission.AdmissionController.check",
    # the circuit-breaker check on the submit path (ISSUE 15): one
    # board-lock dict hit for breakerless keys; transitions use
    # handles cached at board construction and record decisions only
    # behind DECISIONS.enabled
    "serve.resilience.BreakerBoard.admit",
    # the fault-injection plane (ISSUE 13): fire() is reached from the
    # driver-queue submit path — every instrumented site guards with
    # `if FAULTS.enabled:` and the per-point metric handles are cached
    # at arm time, so the disabled plane costs one attribute read
    "utils.faultinject.FaultPlane.fire",
    "utils.faultinject.FaultPlane.delay_s",
    "utils.faultinject.FaultPlane.raise_if_fired",
    # the block autotuner's choice path (ISSUE 16): sits on the flash
    # default-argument path — metric handles cached at construction,
    # the ProfileStore read happens once per key ever (outside the
    # mutex), and decision/flight records emit only on a choice CHANGE
    # behind the recorders' enabled flags
    "core.blocktuner.BlockTuner.choose",
    # the fabric routing path (ISSUE 17): every cluster request pays
    # route() + submit() — the pure route_decision core allocates only
    # small tuples/dicts, metric handles are cached at construction,
    # diversion flight events and route decision records emit behind
    # the recorders' enabled flags, and only the router/fabric locks
    # below may be taken
    "serve.fabric.ShardRouter.route",
    "serve.fabric.ServeFabric.submit",
)

#: Locks the hot path may take: the scheduler lock + fused-window mutex
#: (one uncontended acquisition per deferral is the documented budget),
#: the driver queue's condition (submit backpressure IS its job), and
#: the per-metric update lock (exact counters are the registry's
#: design point 2).
HOT_LOCK_ALLOW = (
    "core.cores.Cores._lock",
    "core.cores.Cores._fused_mu",
    "core.worker._DriverQueue._cond",
    "metrics.registry._Metric._lock",
    # serving submit path: ONE frontend condition guards the whole
    # admit→enqueue transition (exact quota counts under contention
    # are the contract), with the tenant table's and admission
    # controller's small-state locks nested inside it — each held for
    # a few dict operations per request, the documented budget
    "serve.frontend.ServeFrontend._mu",
    "serve.tenants.TenantTable._mu",
    "serve.admission.AdmissionController._mu",
    # fault plane: taken ONLY when an armed clause matches the point —
    # test/chaos rigs; the disabled fast path never reaches it
    "utils.faultinject.FaultPlane._mu",
    # breaker board: one uncontended acquisition per submit (a dict
    # miss for keys with no breaker state), nested inside the frontend
    # condition — the documented budget
    "serve.resilience.BreakerBoard._mu",
    # block tuner: a few short value-copy critical sections per choose
    # (snapshot walls / apply choice), never held across the store
    # read or the recorders — the TransferTuner discipline
    "core.blocktuner.BlockTuner._mu",
    # fabric route/submit: one short roster+health snapshot under the
    # router lock, one in-flight bookkeeping write under the fabric
    # lock — neither is held across a shard submit or any recorder
    "serve.fabric.ShardRouter._mu",
    "serve.fabric.ServeFabric._mu",
    # retry budgets (reached from the fabric re-route path): a couple
    # of dict reads/writes per preempted request under one small-state
    # lock — preemption recovery, not the steady-state submit path
    "serve.resilience.RetryBudgets._mu",
)


def repo_config() -> AnalyzerConfig:
    return AnalyzerConfig(
        hot_roots=HOT_ROOTS,
        hot_lock_allow=HOT_LOCK_ALLOW,
        span_vocab=("trace.spans", "SPAN_KINDS"),
        event_vocab=("obs.flight", "EVENT_KINDS"),
        decision_vocab=("obs.decisions", "DECISION_KINDS"),
        req_vocab=("obs.reqtrace", "REQ_EVENT_KINDS"),
    )


def _repo_extra_paths() -> list:
    """bench.py + the standalone tools (invariant-pass coverage); the
    analyzer's own package is excluded — it lints itself via the
    package scan only when listed here, which it is."""
    out = [os.path.join(REPO, "bench.py")]
    tools_dir = os.path.join(REPO, "tools")
    for fn in sorted(os.listdir(tools_dir)):
        if fn.endswith(".py"):
            out.append(os.path.join(tools_dir, fn))
    for sub in ("ckcheck", "ckmodel"):
        ck = os.path.join(tools_dir, sub)
        if not os.path.isdir(ck):
            continue
        for fn in sorted(os.listdir(ck)):
            if fn.endswith(".py"):
                out.append(os.path.join(ck, fn))
    return [p for p in out if os.path.isfile(p)]


def analyze_repo(root: str | None = None):
    """(findings, package) for the live tree."""
    root = root or os.path.join(REPO, "cekirdekler_tpu")
    pkg = scan_package(
        root, pkg_name="cekirdekler_tpu",
        extra_paths=tuple(_repo_extra_paths()), repo_root=REPO)
    return run_passes(pkg, repo_config()), pkg


RULE_DOCS = {
    "order-cycle": (
        "Two code paths acquire the named locks in opposite orders; if the "
        "paths ever interleave across threads, each holds what the other "
        "wants — classic ABBA deadlock.  Fix: pick ONE order (document it "
        "at the lock definitions) and restructure the second path."),
    "reacquire": (
        "A flow that already holds a non-reentrant lock reaches a site "
        "that acquires it again — it blocks on itself forever (the PR 6 "
        "shape: snapshot() under the tracer lock calling "
        "_sync_dropped_metric, which takes the same lock).  Fix: split a "
        "_locked variant that asserts the caller holds the lock, or make "
        "the outer caller release first."),
    "unguarded-read": (
        "An attribute whose writes are consistently locked is READ with "
        "no common lock — the read can observe stale or half-updated "
        "state.  Often deliberate in this repo ('racy read, reporting "
        "only'): annotate `# ckcheck: ok <why>` when so, or take the "
        "writers' lock / snapshot under it when the read feeds a "
        "decision."),
    "mixed-guard": (
        "An attribute is written under a lock at some sites and touched "
        "with no common lock at others — the unlocked read-modify-write "
        "can lose the locked writer's update (the seed-era "
        "enqueue/rebalance lost-update class).  Fix: take the same lock "
        "at every site, or annotate `# ckcheck: ok <why>` when the "
        "lock-free access is a deliberate, documented design."),
    "get-or-create": (
        "REGISTRY.counter/gauge/histogram is get-or-create: a dict lookup "
        "plus a possible registry lock per call.  On the hot set this is "
        "the exact finding PRs 4-6 fixed four times by hand: cache the "
        "handle on the owning object at construction."),
    "hot-lock": (
        "A hot-path function takes a lock outside the allowlist — every "
        "deferral/submit would serialize on it.  Move the work to a "
        "window boundary (annotate the boundary `# ckcheck: cold`) or "
        "add the lock to the allowlist with a budget argument."),
    "telemetry-alloc": (
        "Arguments of a tracer/flight call are computed (f-string, "
        "concat, call) before the callee's disabled check — disabled "
        "telemetry still allocates per call.  Guard the site with "
        "`if TRACER.enabled:` / `if FLIGHT.enabled:`."),
    "headline-last": (
        "Artifact dicts must keep 'headline' as the final key: the bench "
        "driver records only the last 2000 chars of output and regress.py "
        "recovers the trailing objects from that tail (the "
        "finalize_result contract)."),
    "undeclared-kind": (
        "A span/flight-event/decision/request-lifecycle kind is "
        "emitted that is not declared in SPAN_KINDS / EVENT_KINDS / "
        "DECISION_KINDS / REQ_EVENT_KINDS — the "
        "vocabulary tuples are the contract lint_obs checks the "
        "documentation against; an undeclared kind is invisible to the "
        "doc lint."),
    "json-unsafe": (
        "json.dumps serializes float('inf')/nan as bare Infinity/NaN "
        "(invalid per RFC 8259 — the PR 6 /healthz consumer-breaking "
        "bug), and raises TypeError on numpy scalars, killing the whole "
        "export.  Route the payload through "
        "cekirdekler_tpu.utils.jsonsafe.json_safe(...) or pass "
        "allow_nan=False (fail loudly, never emit invalid JSON)."),
    "unbounded-blocking": (
        "A zero-argument .join()/.wait()/.get() blocks FOREVER when "
        "its counterpart thread died or its sentinel never arrives — "
        "the shutdown-hang shape (a serve dispatcher or driver queue "
        "stuck in close()).  Fix: pass a timeout and re-check the "
        "predicate in a loop, or annotate `# ckcheck: ok <why>` when "
        "unbounded blocking IS the design (sentinel-terminated daemon "
        "loops, user-triggered gates)."),
    "syntax-error": "The file does not parse; nothing in it was analyzed.",
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ckcheck",
        description="concurrency & hot-path static analyzer "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(refuses NEW findings without --allow-grow)")
    ap.add_argument("--allow-grow", action="store_true",
                    help="permit --update-baseline to add findings")
    ap.add_argument("--explain", metavar="FINGERPRINT",
                    help="print one finding with its rule documentation")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings dump (exit code "
                         "semantics unchanged)")
    ap.add_argument("--root", default=None,
                    help="package root to scan (default: cekirdekler_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/ckcheck/"
                         "baseline.json)")
    args = ap.parse_args(argv)

    if args.explain == "provenance":
        # derived solely from the baseline file — never pay the scan
        print(provenance_note(load_baseline_doc(args.baseline)))
        return 0

    findings, _pkg = analyze_repo(args.root)
    baseline = load_baseline(args.baseline)
    new, grand, stale = ratchet(findings, baseline)

    if args.explain:
        for f in findings:
            if f.fingerprint.startswith(args.explain):
                print(f.render())
                print()
                print(RULE_DOCS.get(f.rule, "(no rule documentation)"))
                status = ("grandfathered in baseline"
                          if f.fingerprint in baseline else
                          "NEW (not in baseline)")
                print(f"\nstatus: {status}")
                return 0
        print(f"no finding with fingerprint {args.explain!r}",
              file=sys.stderr)
        return 1

    if args.update_baseline:
        if new and not args.allow_grow:
            print(f"ckcheck: REFUSING to grow the baseline by "
                  f"{len(new)} new finding(s) (pass --allow-grow to "
                  "grandfather deliberately):")
            for f in new:
                print("  " + f.render())
            return 1
        save_baseline(args.baseline, findings, tool="ckcheck")
        print(f"ckcheck: baseline rewritten: {len(findings)} finding(s) "
              f"({len(new)} added, {len(stale)} removed)")
        return 0

    if args.json:
        print(json.dumps({
            "new": [f.to_row() for f in new],
            "grandfathered": [f.to_row() for f in grand],
            "stale_baseline": stale,
        }, indent=1, sort_keys=True, allow_nan=False))
        return 0 if not new and not stale else 1

    ok = True
    if new:
        ok = False
        print(f"ckcheck: {len(new)} NEW finding(s) (not in baseline):")
        for f in new:
            print("  " + f.render())
        print("  (fix them, annotate `# ckcheck: ok <why>`, or "
              "--update-baseline --allow-grow to grandfather)")
    if stale:
        ok = False
        print(f"ckcheck: {len(stale)} STALE baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (finding fixed but "
              "baseline not shrunk — run --update-baseline):")
        for row in stale:
            print(f"  [{row['fingerprint']}] {row.get('path')}:"
                  f"{row.get('line')} {row.get('message', '')[:80]}")
        print("  (" + provenance_note(
            load_baseline_doc(args.baseline)) + ")")
    if ok and not args.json:
        print(f"ckcheck: clean — {len(findings)} grandfathered finding(s) "
              f"remain in the baseline (ratchet: this number only goes "
              "down)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
