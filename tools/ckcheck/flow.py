"""Interprocedural held-set propagation shared by the lock-order,
lockset, and hot-path passes.

Entry-context model: a function's **entry contexts** are the lock sets
that may be held when it is entered.

- Functions with no in-package callers (thread bodies, public API
  surface, closures submitted to driver queues) are entries: they get
  the empty context.
- Everything else inherits contexts from its call sites only — a
  helper called exclusively under its class's mutex is analyzed as
  holding that mutex.  This includes PUBLIC methods with in-package
  callers (``Worker.upload`` is called only under the worker phase
  lock): the analyzer models the in-tree discipline, and a hypothetical
  external unlocked caller is out of scope by design — the dynamic
  witness (CK_LOCK_WITNESS) covers what the model cannot see.
- Call sites inside lifecycle methods (``__init__``/``dispose``/...)
  do not propagate: construction and teardown are single-threaded by
  contract, and seeding their empty held-sets into shared helpers
  would erase the guard evidence of the steady-state callers.

The propagation is a worklist fixpoint over ``caller_entry ∪
held_at_call_site``; context sets are capped (collapse to their
intersection past :data:`MAX_CONTEXTS`) so pathological fan-in cannot
blow up, at the cost of precision, never soundness of the
under-approximation.
"""

from __future__ import annotations

from .model import LIFECYCLE_METHODS, Package

__all__ = ["entry_contexts", "reachable_from"]

MAX_CONTEXTS = 12


def _is_lifecycle(qualname: str) -> bool:
    return qualname.rsplit(".", 1)[-1] in LIFECYCLE_METHODS


def _has_callers(pkg: Package) -> set:
    called = set()
    for q, fi in pkg.functions.items():
        if _is_lifecycle(q):
            continue
        for cs in fi.call_sites:
            called.update(cs.targets)
    return called


def entry_contexts(pkg: Package) -> dict[str, frozenset]:
    """qualname → set of frozenset lock-id entry contexts."""
    ctxs: dict[str, set] = {q: set() for q in pkg.functions}
    called = _has_callers(pkg)
    for q, fi in pkg.functions.items():
        if q not in called or fi.is_nested or _is_lifecycle(q):
            ctxs[q].add(frozenset())

    work = list(pkg.functions)
    rounds = 0
    while work and rounds < 50:
        rounds += 1
        next_work: list[str] = []
        for q in work:
            if _is_lifecycle(q):
                continue  # lifecycle call sites do not propagate
            fi = pkg.functions[q]
            my_ctxs = ctxs[q]
            if not my_ctxs:
                # not yet reached from any entry — propagating a default
                # empty context here would poison callees with a held-set
                # the real callers never produce; the worklist revisits
                # this function once its own contexts arrive
                continue
            for cs in fi.call_sites:
                for tgt in cs.targets:
                    if tgt not in ctxs:
                        continue
                    for e in my_ctxs:
                        new = frozenset(e | set(cs.held))
                        if new not in ctxs[tgt]:
                            ctxs[tgt].add(new)
                            next_work.append(tgt)
            if len(ctxs[q]) > MAX_CONTEXTS:
                merged = frozenset.intersection(*ctxs[q])
                ctxs[q] = {merged}
        work = next_work
    return ctxs


def reachable_from(pkg: Package, roots, respect_cold: bool = True) -> set:
    """Call-graph closure of ``roots`` (qualnames).  Functions annotated
    ``# ckcheck: cold`` stop the walk — they are batch/window-granularity
    boundaries the hot-path discipline does not cross."""
    seen: set = set()
    stack = [r for r in roots if r in pkg.functions]
    while stack:
        q = stack.pop()
        if q in seen:
            continue
        fi = pkg.functions[q]
        if respect_cold and fi.cold and q not in roots:
            continue
        seen.add(q)
        for cs in fi.call_sites:
            for tgt in cs.targets:
                if tgt in pkg.functions and tgt not in seen:
                    stack.append(tgt)
    return seen
