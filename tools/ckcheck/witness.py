"""Dynamic lock-order witness: cross-check ckcheck's STATIC acquisition
graph against the orders the test suite ACTUALLY exercises.

Opt-in via ``CK_LOCK_WITNESS=1`` (tests/conftest.py installs it before
the suite runs).  :func:`install` wraps ``threading.Lock`` / ``RLock``
/ ``Condition`` with factories that tag each lock created from a line
the static inventory knows (file+line → ``lock_id``); named locks push
and pop a thread-local held stack on acquire/release, and every
(held → acquired) pair of named locks is recorded as a dynamic edge.
Locks created anywhere else (pytest internals, jax, stdlib) pass
through unwrapped — zero overhead outside the package.

:func:`report` then compares:

- **static-only** edges — orders the analyzer believes exist but the
  suite never exercised (dead order info, or coverage gaps worth a
  test);
- **dynamic-only** edges — orders the suite EXECUTED that the static
  graph missed (analyzer blind spots: unresolved receivers, getattr
  indirection).  These are the edges that keep the static pass honest.

Disagreements are a REPORT artifact, not a failure: the witness bounds
the static analyzer's blind spots, it does not gate CI (a run's edge
set depends on which tests ran).
"""

from __future__ import annotations

import json
import os
import threading

__all__ = ["install", "Witness"]


class _Local(threading.local):
    def __init__(self):
        self.held = []


class Witness:
    def __init__(self, site_to_lock: dict):
        self._site_to_lock = site_to_lock   # (abspath, line) -> lock_id
        self._edges: set = set()            # (held_id, acquired_id)
        self._seen_locks: set = set()
        self._tl = _Local()
        self._mu = threading.Lock()
        self._orig = None

    # -- recording -----------------------------------------------------------
    def _on_acquire(self, lock_id: str) -> None:
        held = self._tl.held
        if held:
            new = {(h, lock_id) for h in held
                   if h != lock_id and (h, lock_id) not in self._edges}
            if new:
                with self._mu:
                    self._edges |= new
        held.append(lock_id)
        self._seen_locks.add(lock_id)

    def _on_release(self, lock_id: str) -> None:
        held = self._tl.held
        # remove the most recent matching entry (non-LIFO releases exist)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == lock_id:
                del held[i]
                break

    # -- results -------------------------------------------------------------
    def dynamic_edges(self) -> set:
        with self._mu:
            return set(self._edges)

    def report(self, static_edges) -> dict:
        """Compare against ``{(held, acquired), ...}`` from
        :func:`tools.ckcheck.lock_order_edges`."""
        dyn = self.dynamic_edges()
        stat = set(static_edges)
        return {
            "dynamic_edges": sorted(map(list, dyn)),
            "static_edges": sorted(map(list, stat)),
            "static_only": sorted(map(list, stat - dyn)),
            "dynamic_only": sorted(map(list, dyn - stat)),
            "locks_witnessed": sorted(self._seen_locks),
        }

    def write_report(self, static_edges, path: str) -> str:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc = self.report(static_edges)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
            f.write("\n")
        return path

    # -- teardown ------------------------------------------------------------
    def uninstall(self) -> None:
        if self._orig is not None:
            threading.Lock, threading.RLock, threading.Condition = self._orig
            self._orig = None


class _NamedLock:
    """Proxy wrapping a real lock; records order edges for its
    inventory-known creation site.  Supports the subset of the lock API
    the package uses (``with``, acquire/release, Condition wait/notify
    when wrapping a Condition)."""

    def __init__(self, real, lock_id: str, witness: Witness):
        self._real = real
        self._lock_id = lock_id
        self._witness = witness

    def acquire(self, *a, **kw):
        got = self._real.acquire(*a, **kw)
        if got:
            self._witness._on_acquire(self._lock_id)
        return got

    def release(self):
        self._witness._on_release(self._lock_id)
        return self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()

    # Condition API passthrough (wait releases/re-takes the REAL lock;
    # the held-stack intentionally keeps the entry — the waiting thread
    # still "owns" the order slot when it resumes)
    def wait(self, timeout=None):
        return self._real.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        return self._real.wait_for(predicate, timeout)

    def notify(self, n=1):
        return self._real.notify(n)

    def notify_all(self):
        return self._real.notify_all()

    def __getattr__(self, name):
        return getattr(self._real, name)


def _creation_site(depth: int = 2):
    import sys

    frame = sys._getframe(depth)
    return (os.path.abspath(frame.f_code.co_filename), frame.f_lineno)


def install(package_root: str) -> Witness:
    """Patch the threading lock factories; locks created at inventory-
    known sites under ``package_root`` come back wrapped.  Returns the
    witness (keep it; call ``uninstall()`` when done)."""
    from .model import scan_package

    pkg = scan_package(package_root)
    site_to_lock = {
        (os.path.abspath(os.path.join(os.path.dirname(package_root),
                                      lock.path)), lock.line): lock.lock_id
        for lock in pkg.locks.values()
    }
    w = Witness(site_to_lock)
    orig_lock, orig_rlock, orig_cond = (
        threading.Lock, threading.RLock, threading.Condition)
    w._orig = (orig_lock, orig_rlock, orig_cond)

    def make(factory):
        def wrapped(*a, **kw):
            real = factory(*a, **kw)
            try:
                lock_id = w._site_to_lock.get(_creation_site())
            except Exception:  # noqa: BLE001 - never break lock creation
                lock_id = None
            if lock_id is None:
                return real
            return _NamedLock(real, lock_id, w)
        return wrapped

    threading.Lock = make(orig_lock)
    threading.RLock = make(orig_rlock)
    threading.Condition = make(orig_cond)
    return w
