#!/usr/bin/env python
"""Heterogeneous-lane sweep: {fast-only, slow-only, mixed} at equal
total range — the bench's ``hetero`` section and a standalone CLI
(ISSUE 20).

The paper's headline feature is treating N *unequal* devices as ONE
device for a single kernel.  This tool proves the TPU-native port of
that claim end to end: a mixed lane set (fast kind + slow kind in one
``Cores``) must beat the best homogeneous subset at equal total range,
with the split seeded from the device-kind rate priors
(``hardware.rate_prior`` → ``core/balance.prior_split``) and attributed
per lane kind in the trace report.

Four arms, all computing the SAME bandwidth-bound kernel over the same
total range (results must be bit-identical — the exactness gate):

- **fast_only** — the best homogeneous subset's fast half alone.
- **slow_only** — the slow kind alone.
- **mixed** — both kinds in one Cores, ``rate_priors`` seeding the
  first split at the rate-implied share.
- **mixed_prior_off** — same lanes, priors forced flat: the control
  that quantifies what the prior saved (the offline twin of ``ckreplay
  whatif --set rate_prior=off``).

Rate emulation on CPU-only containers: virtual host lanes share one
silicon, so a *measured* mixed-vs-homogeneous wall comparison measures
scheduler noise, not heterogeneity.  The sweep therefore pins the
comparison via skewed virtual-device rates: the slow lane is made
honestly slow TO THE MEASUREMENT PLANE with a seeded ``slow-link``
fault (transfers run ``skew``× slower, proportional to measured wall,
so the balancer holds the skewed split), and the headline walls come
from the rate MODEL applied to each arm's actual converged split:
``wall_model = max_i(range_i / rate_i)``.  That model is deterministic
— same split, same number — which is what a regression-watched key
needs.  Measured walls ride along for reference.  On a rig with real
accelerators the same arms run un-emulated and the measured walls are
the artifact of record.

Headline (watched by tools/regress.py, exactness-gated)::

    hetero_speedup_vs_best_homog = best_homog_wall / mixed_wall

Usage::

    python tools/hetero_sweep.py [--n 262144] [--iters 6] [--skew 8]
                                 [--spill PATH] [--json]

Exit codes: 0 ok, 1 inexact (digest mismatch), 2 environment gap
(fewer than 2 lanes).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # standalone `python tools/hetero_sweep.py`
    sys.path.insert(0, REPO)

_COUNT_FLAG = "--xla_force_host_platform_device_count"

#: Emulated device-kind labels for the CPU-only pinned path.  The slow
#: kind is the honest host kind; the fast kind is labeled as emulated
#: so no artifact can read a CPU container as real TPU silicon.
EMU_FAST_KIND = "tpu-emu"
EMU_SLOW_KIND = "cpu"

_CID = 8020  # the prior-on arms' compute id
#: The flat-prior control records under its OWN cid so a spilled log's
#: `ckreplay whatif --set rate_prior=off` chain over _CID is pure
#: prior-on evidence, not polluted by the control's equal-seeded moves.
_CID_PRIOR_OFF = 8021

AXPY_SRC = """
__kernel void axpy(__global float* a, __global float* b) {
    int i = get_global_id(0);
    b[i] = a[i] * 1.5f + b[i];
}
"""


def _ensure_lanes() -> None:
    """Standalone-CLI lane guarantee (tools/resilience.py's): force the
    8-virtual-device host platform unless the caller already pinned a
    count — harmless on accelerator rigs (the flag only shapes the
    HOST platform).  Must run before the first jax import."""
    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG not in flags:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}=8").strip()


def _digest(arr) -> str:
    import numpy as np

    return hashlib.sha256(
        np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]


def _balance_moves(rows, cid: int) -> int:
    """How many recorded load-balance decisions for ``cid`` actually
    MOVED the split — the convergence-cost count the prior exists to
    shrink (a prior-seeded chain should move ~0-1 times; an
    equal-seeded chain under 8x skew re-shards for several)."""
    moves = 0
    for r in rows:
        if r.kind != "load-balance" or r.inputs.get("cid") != cid:
            continue
        if list(r.outputs.get("ranges", [])) != \
                list(r.inputs.get("ranges", [])):
            moves += 1
    return moves


def _run_arm(devs, kinds, priors, fault: str | None, n: int,
             local_range: int, iters: int, trace: bool = False,
             cid: int = _CID) -> dict:
    """One arm: build a cruncher over ``devs``, pin its lane kinds and
    rate priors (the emulation seam — on a real mixed rig both already
    hold the true values), run ``iters`` windows, return wall / final
    split / digest (+ the per-lane-kind trace rollup when asked)."""
    import numpy as np

    from cekirdekler_tpu import ClArray, trace as cktrace
    from cekirdekler_tpu.core import NumberCruncher
    from cekirdekler_tpu.obs.decisions import DECISIONS
    from cekirdekler_tpu.trace.attribution import window_report
    from cekirdekler_tpu.utils.faultinject import FAULTS

    a_host = np.ones(n, np.float32)
    b_host = np.zeros(n, np.float32)
    a = ClArray(a_host, name="ha", read_only=True)
    b = ClArray(b_host, name="hb", partial_read=True)
    cr = NumberCruncher(devs, AXPY_SRC)
    cores = cr.cores
    cores.lane_kinds = list(kinds)
    cores.rate_priors = [float(p) for p in priors]
    group = a.next_param(b)
    mark = DECISIONS.total_recorded
    if fault:
        FAULTS.arm(fault)
    rep = None
    try:
        ctx = cktrace.tracing() if trace else None
        tr = ctx.__enter__() if ctx else None
        t0 = time.perf_counter()
        try:
            for _ in range(iters):
                group.compute(cr, cid, "axpy", n, local_range)
            wall_s = time.perf_counter() - t0
        finally:
            t1 = time.perf_counter()
            if ctx:
                ctx.__exit__(None, None, None)
        if tr is not None:
            rep = window_report(
                tr.snapshot(), t0, t1,
                lane_kinds=dict(enumerate(cores.lane_kinds)))
        split = list(cores.ranges_of(cid))
        rows = [r for r in DECISIONS.snapshot()
                if r.seq >= mark]
    finally:
        if fault:
            FAULTS.disarm()
        cr.dispose()
    out = {
        "lanes": len(kinds),
        "kinds": list(kinds),
        "rate_priors": [float(p) for p in priors],
        "wall_s": round(wall_s, 4),
        "final_split": split,
        "balance_moves": _balance_moves(rows, cid),
        "digest": _digest(b_host),
        "value_ok": bool(np.all(b_host == np.float32(1.5) * iters)),
    }
    if rep is not None:
        out["per_lane_kind"] = {
            k: {"ms": round(v["ms"], 3), "count": v["count"],
                "lanes": sorted(v["lanes"])}
            for k, v in rep.per_lane_kind.items()
        }
    return out


def _model_wall(split, rates) -> float:
    """Pinned per-iteration wall under the virtual rate model: the
    slowest lane's items/rate.  Units are arbitrary (items per rate
    unit) — only ratios between arms are read."""
    return max(r / max(float(k), 1e-9) for r, k in zip(split, rates))


def hetero_section(devices=None, n: int = 262144, local_range: int = 256,
                   iters: int = 6, skew: float = 8.0,
                   spill: str | None = None) -> dict:
    """bench.py's ``hetero`` section: the four-arm sweep + the pinned
    model comparison + the per-lane-kind attribution rollup."""
    from cekirdekler_tpu.hardware import platforms, rate_prior
    from cekirdekler_tpu.obs.decisions import DECISIONS

    plats = platforms() if devices is None else None
    accels = plats.accelerators() if plats is not None else \
        devices.accelerators()
    cpus = plats.cpus() if plats is not None else devices.cpus()

    out: dict = {"skew": float(skew), "n": n, "iters": iters}
    if len(accels) >= 1 and len(cpus) >= 1:
        # real mixed rig: true kinds, true priors, measured walls are
        # the artifact of record (pinned_model False)
        fast = accels.subset(1)
        slow = cpus.subset(1)
        fast_kinds = [str(d.jax_device.device_kind) for d in fast]
        slow_kinds = [str(d.jax_device.device_kind) for d in slow]
        rates = [rate_prior(k) for k in fast_kinds + slow_kinds]
        fault = None
        out["pinned_model"] = False
    elif len(cpus) >= 2:
        # CPU-only container: 1 fast + 1 slow virtual lane, the slow
        # one made honestly slow to the measurement plane (seeded
        # slow-link), the comparison pinned via the rate model
        fast = cpus.subset(1)
        slow = cpus.subset(2)[1:2]
        fast_kinds = [EMU_FAST_KIND]
        slow_kinds = [EMU_SLOW_KIND]
        rates = [float(skew), 1.0]
        fault = f"seed=42;slow-link@lane{{i}}:factor={float(skew)}"
        out["pinned_model"] = True
    else:
        out["skipped"] = "needs >= 2 lanes (or 1 accelerator + 1 cpu)"
        return out

    mixed_devs = fast + slow
    mixed_kinds = fast_kinds + slow_kinds
    arms = {
        "fast_only": _run_arm(
            fast, fast_kinds, rates[:1], None, n, local_range, iters),
        "slow_only": _run_arm(
            slow, slow_kinds, rates[1:],
            fault.format(i=0) if fault else None,
            n, local_range, iters),
        "mixed": _run_arm(
            mixed_devs, mixed_kinds, rates,
            fault.format(i=1) if fault else None,
            n, local_range, iters, trace=True),
        "mixed_prior_off": _run_arm(
            mixed_devs, mixed_kinds, [1.0] * len(mixed_kinds),
            fault.format(i=1) if fault else None,
            n, local_range, iters, cid=_CID_PRIOR_OFF),
    }
    out["arms"] = arms

    digests = [arms[k]["digest"] for k in
               ("fast_only", "slow_only", "mixed", "mixed_prior_off")]
    exact = (len(set(digests)) == 1
             and all(a["value_ok"] for a in arms.values()))
    out["exact"] = bool(exact)

    if out["pinned_model"]:
        walls = {
            "fast_only": _model_wall(arms["fast_only"]["final_split"],
                                     rates[:1]),
            "slow_only": _model_wall(arms["slow_only"]["final_split"],
                                     rates[1:]),
            "mixed": _model_wall(arms["mixed"]["final_split"], rates),
        }
    else:
        walls = {k: arms[k]["wall_s"] for k in
                 ("fast_only", "slow_only", "mixed")}
    out["walls"] = {k: round(v, 4) for k, v in walls.items()}
    best_homog = min(walls["fast_only"], walls["slow_only"])
    out["best_homog_arm"] = ("fast_only"
                             if walls["fast_only"] <= walls["slow_only"]
                             else "slow_only")
    speedup = (round(best_homog / walls["mixed"], 3)
               if walls["mixed"] > 0 else None)
    # the watched key: minted ONLY under the exactness gate — a digest
    # mismatch starves the regress trajectory instead of feeding it a
    # number whose results differ
    out["hetero_speedup_vs_best_homog"] = speedup if exact else None

    # prior evidence: the mixed chain's re-shard count vs the flat-
    # prior control's (the in-run twin of `ckreplay whatif`)
    out["prior_on_moves"] = arms["mixed"]["balance_moves"]
    out["prior_off_moves"] = arms["mixed_prior_off"]["balance_moves"]
    # prior-seeded first split within one quantization step of the
    # rate-implied split (the ckmodel invariant, observed live)
    tot = sum(arms["mixed"]["final_split"])
    implied = [tot * r / sum(rates) for r in rates]
    first = prior_first_split(n, local_range, rates)
    out["prior_split_within_one_step"] = all(
        abs(f - i) <= local_range for f, i in zip(first, implied))
    out["per_lane_kind"] = arms["mixed"].get("per_lane_kind", {})
    if spill:
        out["spill_path"] = DECISIONS.save_jsonl(spill)
    return out


def prior_first_split(total: int, step: int, rates) -> list[int]:
    """The mixed arm's actual seed split (same function Cores uses)."""
    from cekirdekler_tpu.core.balance import prior_split

    return prior_split(total, step, [float(r) for r in rates])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/hetero_sweep.py",
        description="heterogeneous-lane sweep: mixed vs best homogeneous "
                    "subset at equal total range (docs/PARALLELISM.md)")
    ap.add_argument("--n", type=int, default=262144)
    ap.add_argument("--local-range", type=int, default=256)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--skew", type=float, default=8.0)
    ap.add_argument("--spill", default=None,
                    help="save the run's decision log (jsonl) here — "
                         "the `ckreplay verify` evidence file")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    _ensure_lanes()
    out = hetero_section(n=args.n, local_range=args.local_range,
                         iters=args.iters, skew=args.skew,
                         spill=args.spill)
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True, default=str,
                         allow_nan=False))
    else:
        if "skipped" in out:
            print(f"skipped: {out['skipped']}")
        else:
            print(f"hetero_speedup_vs_best_homog = "
                  f"{out['hetero_speedup_vs_best_homog']}")
            print(f"walls ({'model' if out['pinned_model'] else 'measured'})"
                  f" = {out['walls']}")
            print(f"mixed split            = "
                  f"{out['arms']['mixed']['final_split']}")
            print(f"prior moves on/off     = "
                  f"{out['prior_on_moves']}/{out['prior_off_moves']}")
            print(f"exact                  = {out['exact']}")
    if "skipped" in out:
        return 2
    return 0 if out["exact"] else 1


if __name__ == "__main__":
    sys.exit(main())
