#!/usr/bin/env python
"""Cold-start vs cache-warm first-call latency: the bench's
``cold_start`` section and a standalone CLI (ISSUE 18).

Three SUBPROCESS incarnations per workload, each a fresh interpreter
(process-cold is a process property — it cannot be measured in-process):

- **cold** — no ``CK_COMPILE_CACHE``: the autoscale worst case.  Times
  the first fused batch (compile + execute) and a steady-state batch.
- **populate** — same run with the cache armed: the engage-time
  recorder (``core/cores._cache_record_engaged``) persists the window
  spec and jax's persistent cache captures the XLA executables.  This
  is the PRODUCTION population flow, not a synthetic writer.
- **warm** — cache armed, ``warm_from_disk`` precompiles the full
  predicated launch ladder BEFORE traffic, then times the same first
  batch.  ``cold_start_warm_speedup = cold.first / warm.first`` is the
  regression-watched headline (higher is better).

Exactness gate: all three incarnations hash their result arrays —
the cache must be bit-invisible (``exact`` is False otherwise, and the
speedup is withheld from the watched key).  ``rejoin_converge_iters``
from the resilience section rides along in the same artifact so the
two autoscale numbers (rejoin convergence, rejoin compile cost) are
read side by side.

Workloads: the n-body ladder (``workloads.NBODY_SRC`` through
``compute_fused_batch`` — the serving tier's coalesced entry) is the
headline; the flash-attention ladder rides the XLA persistent cache +
file-backed ``BlockTuner`` profile (same tuned blocks => same
executable => disk hit) and is reported as a secondary block.

Usage::

    python tools/coldstart.py [--n 4096] [--iters 4] [--json]
    python tools/coldstart.py --child warm --workload nbody --cache DIR
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # standalone `python tools/coldstart.py`
    sys.path.insert(0, REPO)

CACHE_ENV = "CK_COMPILE_CACHE"  # mirrored from core/compilecache (child
#                                 sets env BEFORE the package import)

CHILD_TIMEOUT_S = 240.0
_CID = 9001  # fixed compute id: all incarnations coalesce identically


# ---------------------------------------------------------------- children


def _digest(*arrays) -> str:
    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:16]


def _child_nbody(args, out: dict) -> dict:
    """One incarnation of the n-body fused-batch ladder."""
    import numpy as np

    from cekirdekler_tpu import ClArray
    from cekirdekler_tpu.core import NumberCruncher
    from cekirdekler_tpu.hardware import platforms
    from cekirdekler_tpu.workloads import NBODY_SRC

    n, lr, dt = args.n, args.local_range, 0.0001
    rng = np.random.default_rng(42)
    pos = (rng.random((3, n), dtype=np.float32) - 0.5) * 2.0
    x = ClArray(pos[0].copy(), name="x", read_only=True)
    y = ClArray(pos[1].copy(), name="y", read_only=True)
    z = ClArray(pos[2].copy(), name="z", read_only=True)
    vel = [ClArray(n, np.float32, name=f"v{c}", partial_read=True)
           for c in "xyz"]
    cr = NumberCruncher(platforms().cpus().subset(1), NBODY_SRC)
    params = [x, y, z, *vel]
    vals = {"nBody": (n, dt)}
    try:
        if args.child == "warm":
            from cekirdekler_tpu.core.compilecache import warm_from_disk

            t0 = time.perf_counter()
            out["warm"] = warm_from_disk(cr.cores)
            out["warmup_s"] = round(time.perf_counter() - t0, 4)
        cr.enqueue_mode = True

        def batch() -> float:
            t0 = time.perf_counter()
            cr.cores.compute_fused_batch(
                ["nBody"], params, _CID, n, lr, args.iters,
                value_args=vals)
            cr.barrier()
            return round(time.perf_counter() - t0, 4)

        out["first_batch_s"] = batch()
        out["steady_batch_s"] = batch()
        cr.enqueue_mode = False  # flush deferred readbacks
        out["digest"] = _digest(*(np.asarray(v) for v in vel))
        out["fused_compiles"] = cr.cores.program.fused_compiled_count
        out["call_compiles"] = cr.cores.program.compiled_count
    finally:
        cr.dispose()
    return out


def _child_flash(args, out: dict) -> dict:
    """One incarnation of the flash-attention ladder.  No manifest spec
    (pure jax path) — ``warm`` differs from ``populate`` only in that
    the XLA persistent cache and the BlockTuner's profile store are
    already populated, which is exactly the production rejoin state."""
    import numpy as np

    from cekirdekler_tpu.core.compilecache import CACHE
    from cekirdekler_tpu.ops.flash_attention import flash_attention

    if CACHE.enabled:
        CACHE.arm()
    import jax.numpy as jnp

    rng = np.random.default_rng(42)
    shape = (1, args.seq, 1, 64)
    q, k, v = (jnp.asarray(rng.standard_normal(shape).astype(np.float32))
               for _ in range(3))
    t0 = time.perf_counter()
    o = flash_attention(q, k, v)
    o.block_until_ready()
    out["first_batch_s"] = round(time.perf_counter() - t0, 4)
    t0 = time.perf_counter()
    o2 = flash_attention(q, k, v)
    o2.block_until_ready()
    out["steady_batch_s"] = round(time.perf_counter() - t0, 4)
    out["digest"] = _digest(np.asarray(o))
    return out


def _child(args) -> int:
    """Run one incarnation; print exactly one JSON line on stdout."""
    if args.cache:
        os.environ[CACHE_ENV] = args.cache
    else:
        os.environ.pop(CACHE_ENV, None)
    out: dict = {"mode": args.child, "workload": args.workload,
                 "cache": bool(args.cache), "pid": os.getpid()}
    try:
        if args.workload == "flash":
            out = _child_flash(args, out)
        else:
            out = _child_nbody(args, out)
    except Exception as exc:  # a child crash is DATA for the parent
        out["error"] = f"{type(exc).__name__}: {exc}"
        print(json.dumps(out, allow_nan=False))
        return 1
    print(json.dumps(out, allow_nan=False))
    return 0


# ------------------------------------------------------------------ parent


def _spawn(mode: str, workload: str, cache: str, n: int, local_range: int,
           iters: int, seq: int, timeout: float = CHILD_TIMEOUT_S) -> dict:
    env = os.environ.copy()
    env.pop(CACHE_ENV, None)  # the child's --cache flag is authoritative
    cmd = [sys.executable, os.path.abspath(__file__),
           "--child", mode, "--workload", workload, "--cache", cache,
           "--n", str(n), "--local-range", str(local_range),
           "--iters", str(iters), "--seq", str(seq)]
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s", "mode": mode}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
    return {"error": f"no JSON from child (rc={proc.returncode}): "
                     f"{proc.stderr.strip()[-400:]}", "mode": mode}


def _trio(workload: str, root: str, n: int, local_range: int, iters: int,
          seq: int) -> dict:
    """cold -> populate -> warm for one workload over a shared cache
    root; returns the three children plus derived speedup/exactness."""
    cache = os.path.join(root, workload)
    os.makedirs(cache, exist_ok=True)
    kw = dict(workload=workload, n=n, local_range=local_range,
              iters=iters, seq=seq)
    cold = _spawn("cold", cache="", **kw)
    populate = _spawn("populate", cache=cache, **kw)
    warm = _spawn("warm", cache=cache, **kw)
    out = {"cold": cold, "populate": populate, "warm": warm}
    digests = [c.get("digest") for c in (cold, populate, warm)]
    out["exact"] = (None not in digests and len(set(digests)) == 1)
    cold_s, warm_s = cold.get("first_batch_s"), warm.get("first_batch_s")
    if out["exact"] and cold_s and warm_s:
        out["warm_speedup"] = round(cold_s / warm_s, 3)
        out["cold_first_batch_s"] = cold_s
        out["warm_first_batch_s"] = warm_s
        out["warmup_s"] = warm.get("warmup_s")
    else:
        out["warm_speedup"] = None
    return out


def coldstart_section(devices=None, resilience=None, n: int = 4096,
                      local_range: int = 256, iters: int = 4,
                      seq: int = 256, include_flash: bool = True,
                      cache_root: str | None = None) -> dict:
    """bench.py's ``cold_start`` section: process-cold vs cache-warm
    first-call latency for the n-body (headline) and flash ladders.

    ``devices`` is accepted for section-signature uniformity but the
    measurements are subprocess-scoped — a fresh interpreter per
    incarnation is the point.  ``resilience`` (the resilience section's
    result dict, when the bench already ran it) contributes
    ``rejoin_converge_iters`` to the same artifact."""
    del devices  # children own their device discovery
    root = cache_root or tempfile.mkdtemp(prefix="ck_coldstart_")
    own_root = cache_root is None
    try:
        nbody = _trio("nbody", root, n, local_range, iters, seq)
        flash = (_trio("flash", root, n, local_range, iters, seq)
                 if include_flash else {"skipped": "disabled"})
    finally:
        if own_root:
            shutil.rmtree(root, ignore_errors=True)
    out = {
        # the watched key: n-body only — the flash path's speedup is
        # tuner/interpret-mode dependent and reported, not watched
        "cold_start_warm_speedup": nbody.get("warm_speedup"),
        "rejoin_converge_iters": (
            resilience.get("rejoin_converge_iters")
            if isinstance(resilience, dict) else None),
        "exact": bool(nbody.get("exact")),
        "nbody": nbody,
        "flash": flash,
    }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/coldstart.py",
        description="process-cold vs cache-warm first-call latency "
                    "(persistent executable cache, docs/PARALLELISM.md)")
    ap.add_argument("--child", default=None,
                    choices=("cold", "populate", "warm"),
                    help=argparse.SUPPRESS)  # internal: one incarnation
    ap.add_argument("--workload", default="nbody",
                    choices=("nbody", "flash"))
    ap.add_argument("--cache", default="",
                    help=argparse.SUPPRESS)  # internal: child cache root
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--local-range", type=int, default=256)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--no-flash", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.child:
        return _child(args)
    out = coldstart_section(
        n=args.n, local_range=args.local_range, iters=args.iters,
        seq=args.seq, include_flash=not args.no_flash)
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True, default=str,
                         allow_nan=False))
    else:
        nb = out["nbody"]
        print(f"cold_start_warm_speedup = {out['cold_start_warm_speedup']}")
        print(f"cold first batch        = {nb.get('cold_first_batch_s')}s")
        print(f"warm first batch        = {nb.get('warm_first_batch_s')}s "
              f"(+{nb.get('warmup_s')}s AOT warmup)")
        print(f"exact                   = {out['exact']}")
    if not out["exact"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
