#!/usr/bin/env python
"""Per-kernel device-timeline profiles from the command line.

Three modes, all built on ``cekirdekler_tpu.trace.device``:

- **run** (default): drive an annotated framework workload (mandelbrot
  through the full ``compute()`` scheduler) under a device-attribution
  capture and print the reconciled per-kernel report — device wall, op
  counts, idle gaps, coverage fraction.  On CPU-only rigs the report is
  a NAMED absence (the capture machinery, marks included, still
  exercises end-to-end).
- **--trace-dir D**: analyze an existing Xprof/trace-event dump (a real
  rig's capture, or a synthetic fixture) without running anything.
- **--show-store**: list the persistent kernel-profile store's keys and
  each key's best row.

Options::

    python tools/kernel_profile.py [--size N] [--iters K]
        [--trace-dir D] [--chrome OUT.json] [--json]
        [--store DIR] [--show-store] [--flops F --bytes B]

``--chrome`` writes the UNIFIED Perfetto trace: host spans and device
ops side by side on one clock.  ``--flops``/``--bytes`` add a roofline
row (defaults to the v5e peaks; see ``--peak-tflops``/``--peak-gbps``).
``--store DIR`` persists one row per profiled kernel keyed by
(kernel, shape, ladder-blocks signature) — the store a block-shape
autotuner reads.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _print_report(rep, as_json: bool) -> None:
    if as_json:
        from cekirdekler_tpu.utils.jsonsafe import json_safe

        print(json.dumps(json_safe(rep.to_dict()), indent=2,
                         allow_nan=False))
    else:
        print(rep.table())
        if rep.anchor:
            print(f"clock anchor: {rep.anchor}; matched_by: "
                  f"{dict(rep.matched_by)}")


def analyze_dir(args) -> int:
    """--trace-dir mode: reduce an existing dump (no host marks — the
    dump's own ``ck|`` mark events drive the correlation)."""
    from cekirdekler_tpu.trace.device import correlate, parse_trace_dump

    dump = parse_trace_dump(args.trace_dir)
    rep = correlate(dump)
    _print_report(rep, args.json)
    _maybe_roofline(rep, args)
    _maybe_chrome(rep, [], [], args)
    _maybe_store(rep, args, shape=("trace-dir",), blocks=("as-captured",))
    return 0


def run_workload(args) -> int:
    """Default mode: annotated mandelbrot through the full scheduler
    under a capture on the current rig."""
    import numpy as np

    import cekirdekler_tpu as ct
    from cekirdekler_tpu.arrays.clarray import ClArray
    from cekirdekler_tpu.core.cruncher import NumberCruncher
    from cekirdekler_tpu.core.stream import plan_signature
    from cekirdekler_tpu.core.worker import _ladder
    from cekirdekler_tpu.trace import TRACER
    from cekirdekler_tpu.trace.device import DeviceCapture
    from cekirdekler_tpu.workloads import mandelbrot_pallas_kernel

    import jax

    devs = ct.all_devices()
    tpus = devs.tpus()
    devs = (tpus if len(tpus) else devs).subset(1)
    print("device:", devs[0].jax_device)

    n = args.size * args.size
    local = 256
    vals = (-2.0, -1.25, 2.5 / args.size, 2.5 / args.size, args.size, 64)
    cr = NumberCruncher(
        devs,
        mandelbrot_pallas_kernel(interpret=jax.default_backend() != "tpu"),
    )
    out = ClArray(n, np.float32, name="kp_out", read=False, write=True)
    try:
        out.compute(cr, 7100, "mandelbrot", n, local, values=vals)  # warm
        cr.barrier()
        TRACER.enable(clear=True)
        cap = DeviceCapture(args.capture_dir)
        with cap:
            cr.enqueue_mode = True
            for _ in range(args.iters):
                out.compute(cr, 7100, "mandelbrot", n, local, values=vals)
            cr.barrier()
            cr.enqueue_mode = False
        spans = TRACER.snapshot()
        TRACER.disable()
        rep = cap.report
        _print_report(rep, args.json)
        _maybe_roofline(rep, args)
        _maybe_chrome(rep, spans, cap.marks.snapshot(), args)
        _maybe_store(
            rep, args, shape=(n,),
            blocks=(plan_signature(_ladder(n, local)),),
        )
        return 0
    finally:
        cr.enqueue_mode = False
        cr.dispose()


def _maybe_roofline(rep, args) -> None:
    if args.flops is None or args.bytes is None or rep.absent:
        return
    from cekirdekler_tpu.trace.device import roofline_row

    for prof in sorted(rep.kernels, key=lambda k: -k.device_ms):
        row = roofline_row(args.flops, args.bytes, prof.device_ms,
                           peak_tflops=args.peak_tflops,
                           peak_gbps=args.peak_gbps)
        print(f"roofline {prof.kernel}: {row['attained_tflops']} Tflop/s "
              f"({row['bound']}-bound, intensity "
              f"{row['intensity_flop_per_byte']} flop/B, mfu {row['mfu']}, "
              f"{row['frac_of_roof']:.0%} of roof)")


def _maybe_chrome(rep, spans, marks, args) -> None:
    if not args.chrome:
        return
    from cekirdekler_tpu.trace.device import unified_chrome_trace
    from cekirdekler_tpu.utils.jsonsafe import json_safe

    doc = unified_chrome_trace(spans, rep, ops=rep.ops, marks=marks,
                               process_name="kernel_profile")
    with open(args.chrome, "w") as f:
        json.dump(json_safe(doc), f, allow_nan=False)
    print(f"unified chrome trace ({len(spans)} host spans, "
          f"{len(rep.ops)} device ops) -> {args.chrome}")


def _maybe_store(rep, args, shape, blocks) -> None:
    if not args.store or rep.absent:
        return
    from cekirdekler_tpu.trace.device import ProfileStore

    store = ProfileStore(args.store)
    for prof in rep.kernels:
        path = store.put(prof.kernel, shape, blocks, {
            "device_ms": round(prof.device_ms, 3),
            "op_count": prof.op_count,
            "launches": prof.launches,
            "idle_ms": round(prof.idle_ms, 3),
            "coverage_frac": round(rep.coverage_frac, 4),
        })
        print(f"stored {prof.kernel} -> {path}")


def _tuner_vs_best(store, best) -> str:
    """The per-key honesty column (the overlap_sweep
    ``choice_vs_optimum`` idiom): what the block tuner would ENGAGE for
    this key — store-seeded, clamped to the legal tile grid — next to
    the store's own best row, so a tuner that cannot cash in a
    persisted profile is visible right where the profile lives."""
    from cekirdekler_tpu.core.blocktuner import BlockTuner

    sig, shape = best.get("kernel_sig"), best.get("shape")
    blocks = best.get("blocks")
    if not (sig and isinstance(shape, list) and shape
            and isinstance(blocks, list) and len(blocks) >= 2
            and all(isinstance(b, int) for b in blocks[:2])):
        return "tuner: n/a (non-tile key)"
    t = int(shape[1]) if len(shape) >= 2 else int(shape[0])
    tuner = BlockTuner(store=store)
    choice = tuner.choose(sig, t, t, shape=tuple(shape))
    stored = (int(blocks[0]), int(blocks[1]))
    # disagreement is either the store's cross-key global best winning
    # over this key's row, or grid-legality clamping — both honest
    verdict = "agree" if choice == stored else (
        "dense-fallback" if choice is None else "differs")
    return f"tuner {choice} vs store best {stored} [{verdict}]"


def show_store(args) -> int:
    from cekirdekler_tpu.trace.device import ProfileStore

    store = ProfileStore(args.store)
    if not store.enabled:
        print("kernel_profile: no store configured (pass --store DIR or "
              "set CK_PROFILE_STORE)", file=sys.stderr)
        return 1
    keys = store.keys()
    print(f"store {store.root}: {len(keys)} key(s)")
    for fn in keys:
        rows = store.read_key(fn)
        if not rows:
            print(f"  {fn}: (no parseable rows)")
            continue
        best = ProfileStore.best_row(rows) or rows[-1]
        print(f"  {fn}: {len(rows)} row(s), best device_ms="
              f"{best.get('device_ms')} (kernel {best.get('kernel_sig')}, "
              f"shape {best.get('shape')}, blocks {best.get('blocks')}); "
              f"{_tuner_vs_best(store, best)}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--size", type=int, default=256,
                    help="mandelbrot width=height for run mode "
                         "(default 256)")
    ap.add_argument("--iters", type=int, default=4,
                    help="enqueue iterations under capture (default 4)")
    ap.add_argument("--trace-dir", default=None,
                    help="analyze an existing trace dump instead of "
                         "running a workload")
    ap.add_argument("--capture-dir", default="/tmp/ck_kernel_profile",
                    help="where run mode writes its capture")
    ap.add_argument("--chrome", metavar="PATH", default=None,
                    help="write the unified host+device Perfetto trace")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON")
    ap.add_argument("--store", metavar="DIR", default=None,
                    help="kernel-profile store directory (default: "
                         "$CK_PROFILE_STORE)")
    ap.add_argument("--show-store", action="store_true",
                    help="list the store's keys and best rows, then exit")
    ap.add_argument("--flops", type=float, default=None,
                    help="analytic flop count for the roofline row")
    ap.add_argument("--bytes", type=float, default=None,
                    help="analytic byte count for the roofline row")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="machine compute peak (default: v5e bf16)")
    ap.add_argument("--peak-gbps", type=float, default=None,
                    help="machine HBM bandwidth (default: v5e)")
    args = ap.parse_args(argv)

    from cekirdekler_tpu.trace.device import (
        V5E_HBM_GBPS, V5E_PEAK_BF16_TFLOPS)

    if args.peak_tflops is None:
        args.peak_tflops = V5E_PEAK_BF16_TFLOPS
    if args.peak_gbps is None:
        args.peak_gbps = V5E_HBM_GBPS
    if args.show_store:
        return show_store(args)
    if args.trace_dir:
        return analyze_dir(args)
    return run_workload(args)


if __name__ == "__main__":
    sys.exit(main())
