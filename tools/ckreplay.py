#!/usr/bin/env python
"""ckreplay: verify / what-if / explain over a recorded decision log.

The runtime event-sources every controller decision
(``cekirdekler_tpu/obs/decisions.py``): one record per ``load_balance``
iteration, transfer-tuner choice/observation, fused engage/disengage,
health verdict flip — each with the COMPLETE inputs the decision was
made from.  This tool consumes a spilled jsonl log (``CK_DECISION_LOG``,
``DECISIONS.save_jsonl``) or a ``ck-postmortem-v2`` black box:

- ``verify`` re-executes the pure decision functions from the recorded
  inputs and asserts **bit-identical** outputs.  Exit 0: the log
  replays clean (recorded logs are golden tests of the controllers).
  Exit 1: drift — the report names the FIRST divergent seq, which is
  exactly what you want when someone edits the balancer and an old
  log stops reproducing.
- ``whatif --set damping=0.1,jump_start=off,transfer_floor=off``
  re-runs the CHAINED load-balance sequence with modified knobs,
  carrying balancer state forward on the log's implied per-item rates,
  and reports the counterfactual convergence trajectory
  (iterations-to-converge, final-split L1 distance; chunk-choice
  deltas when ``overhead_ms`` is overridden).  E.g. ``jump_start=off``
  on a jump-started log demonstrates the r5-era damped crawl returning.
- ``explain`` renders the latest split's per-lane causality table —
  raw bench, transfer floor (bound or slack, with margin), damped
  move, quantization residue, and which input bound the outcome.
  The live equivalent is the debug server's ``/decisionz``.
  ``explain --rid <id>`` pivots to ONE request: every recorded
  controller decision whose inputs named that rid (admission verdict,
  coalesce wave, containment/retry, fabric route/re-route hops) in
  seq order — the decision-side complement of the ``/reqz`` phase
  timeline for the same rid.
- ``demo --out log.jsonl`` records a synthetic multi-lane convergence
  (skewed lanes, a transfer-floor-bound lane, a jump-start) — the
  generator behind ``tests/fixtures_decisions/`` and the quickest way
  to try the three verbs without a rig.

Usage::

    python -m tools.ckreplay verify run.jsonl
    python -m tools.ckreplay whatif run.jsonl --set jump_start=off
    python -m tools.ckreplay explain run.jsonl [--cid 901] [--json]
    python -m tools.ckreplay explain run.jsonl --rid r3f2a-1c
    python -m tools.ckreplay demo --out /tmp/demo.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["main", "load_records", "parse_overrides", "demo_log"]


def load_records(path: str):
    """Rows from a jsonl spill or a postmortem JSON (the v2 black box
    carries its decision ring under ``"decisions"``; v1 yields [])."""
    from cekirdekler_tpu.obs.decisions import (
        DecisionRecord,
        load_decision_log,
    )

    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                doc = json.load(f)
            except json.JSONDecodeError:
                doc = None
            if isinstance(doc, dict) and "decisions" in doc:
                rows = [DecisionRecord.from_row(r)
                        for r in doc.get("decisions") or []
                        if isinstance(r, dict) and "kind" in r]
                rows.sort(key=lambda r: r.seq)
                return rows
    return load_decision_log(path)


#: Per-knob value types: coercion is by KNOB, not by value shape —
#: `overhead_ms=off` must be rejected, not silently become 0.0, and
#: `jump_start=0.3` must not float-parse into truthy-on.
_BOOL_KNOBS = frozenset(("jump_start", "transfer_floor", "smoothing",
                         "rate_prior"))
_FLOAT_KNOBS = frozenset(("damping", "overhead_ms"))
#: x-separated int lists (``--set`` splits entries on commas, so the
#: grid knob separates its sizes with ``x``: ``block_grid=128x256x512``).
_GRID_KNOBS = frozenset(("block_grid",))


def parse_overrides(spec: str) -> dict:
    """``damping=0.1,jump_start=off,...`` → typed override dict."""
    from cekirdekler_tpu.obs.replay import WHATIF_KNOBS

    out: dict = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise SystemExit(
                f"ckreplay: bad --set entry {part!r} (want k=v); "
                f"knobs: {', '.join(sorted(WHATIF_KNOBS))}")
        k, v = part.split("=", 1)
        k = k.strip()
        v = v.strip().lower()
        if k not in WHATIF_KNOBS:
            raise SystemExit(
                f"ckreplay: unknown knob {k!r}; "
                f"knobs: {', '.join(sorted(WHATIF_KNOBS))}")
        if k in _BOOL_KNOBS:
            if v in ("on", "true", "yes", "1"):
                out[k] = True
            elif v in ("off", "false", "no", "0"):
                out[k] = False
            else:
                raise SystemExit(
                    f"ckreplay: bad value {v!r} for on/off knob {k!r}")
        elif k in _GRID_KNOBS:
            try:
                sizes = tuple(int(s) for s in v.split("x") if s.strip())
            except ValueError:
                sizes = ()
            if not sizes:
                raise SystemExit(
                    f"ckreplay: bad value {v!r} for grid knob {k!r} "
                    "(want x-separated sizes, e.g. 128x256x512)")
            out[k] = sizes
        else:
            assert k in _FLOAT_KNOBS, k  # WHATIF_KNOBS is the union
            try:
                out[k] = float(v)
            except ValueError:
                raise SystemExit(
                    f"ckreplay: bad value {v!r} for knob {k!r}")
    return out


def demo_log(path: str, lanes: int = 3, steps: int = 12,
             total: int = 8192, step: int = 64) -> str:
    """Record a synthetic multi-lane convergence: unequal per-item
    rates, one lane whose LINK wall exceeds its compute bench (the
    transfer floor binds), adaptive damping + jump-start.  Every
    iteration runs the REAL ``load_balance``, so the resulting log
    replay-verifies by construction."""
    from cekirdekler_tpu.core.balance import (
        BalanceHistory,
        BalanceState,
        equal_split,
        load_balance,
    )
    from cekirdekler_tpu.obs.decisions import DecisionLog, DECISIONS
    import cekirdekler_tpu.obs.decisions as _dmod

    # a fresh log so the demo file holds exactly this sequence
    log = DecisionLog()
    saved = DECISIONS
    _dmod.DECISIONS = log
    # the emitters imported DECISIONS by value — patch their refs too
    import cekirdekler_tpu.core.balance as _bal

    bal_saved = _bal.DECISIONS
    _bal.DECISIONS = log
    try:
        # per-item compute rates (ms/item): lane 0 fast, lane 1 slow,
        # lane 2 fast compute but a link 3x its compute wall — the
        # transfer floor must bind there
        rates = [0.0010, 0.0040, 0.0008][:lanes]
        t_rates = [0.0002, 0.0002, 0.0030][:lanes]
        while len(rates) < lanes:
            rates.append(0.0015)
            t_rates.append(0.0002)

        def chain(cid, jump):
            ranges = equal_split(total, lanes, step)
            hist = BalanceHistory(weighted=True)
            state = BalanceState()
            for _ in range(steps):
                bench = [rates[i] * max(ranges[i], step)
                         for i in range(lanes)]
                transfer = [t_rates[i] * max(ranges[i], step)
                            for i in range(lanes)]
                ranges = load_balance(
                    bench, ranges, total, step, hist, state=state,
                    transfer_ms=transfer, jump_start=jump, cid=cid,
                )

        # cid 0: the jump-started fast path (converges in ~2, freezes);
        # cid 1: the damped crawl (jump off) — this chain EXERCISES the
        # adaptive-damping constants (DAMP_GROW/DECAY/...), so a log
        # from here diverges under replay when someone retunes them
        chain(0, jump=True)
        chain(1, jump=False)
        return log.save_jsonl(path)
    finally:
        _dmod.DECISIONS = saved
        _bal.DECISIONS = bal_saved


def demo_hetero_log(path: str, total: int = 8192, step: int = 64,
                    steps: int = 10, skew: float = 100.0) -> str:
    """Record a prior-seeded heterogeneous chain: 1 fast + 1 slow lane
    (``skew``x apart, the TPU-vs-host-CPU shape), first split from
    ``prior_split`` with rate-true priors, every iteration the REAL
    ``load_balance`` with the priors on the record.  This is the
    ``tests/fixtures_decisions/golden_hetero_prior.jsonl`` generator:
    the log replay-verifies by construction, and ``ckreplay whatif
    --set rate_prior=off`` on it quantifies what the seed saved."""
    from cekirdekler_tpu.core.balance import (
        BalanceHistory,
        BalanceState,
        load_balance,
        prior_split,
    )
    from cekirdekler_tpu.obs.decisions import DecisionLog
    import cekirdekler_tpu.obs.decisions as _dmod
    import cekirdekler_tpu.core.balance as _bal

    log = DecisionLog()
    saved = _dmod.DECISIONS
    _dmod.DECISIONS = log
    bal_saved = _bal.DECISIONS
    _bal.DECISIONS = log
    try:
        # per-item compute rates (ms/item): lane 1 is `skew`x slower —
        # the prior is rate-TRUE (throughput ∝ 1/rate), the ideal-seed
        # case the prior-seeded-jump-within-one-step invariant pins
        rates = [0.001, 0.001 * skew]
        priors = [1.0 / r for r in rates]
        ranges = prior_split(total, step, priors, cid=0)
        hist = BalanceHistory(weighted=True)
        state = BalanceState()
        for _ in range(steps):
            bench = [rates[i] * max(ranges[i], step)
                     for i in range(len(ranges))]
            ranges = load_balance(
                bench, ranges, total, step, hist, state=state,
                jump_start=True, cid=0, rate_prior=priors,
            )
        return log.save_jsonl(path)
    finally:
        _dmod.DECISIONS = saved
        _bal.DECISIONS = bal_saved


def _fmt(v, nd=3):
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_explain_rid(doc: dict) -> str:
    """One request's decision history as plain text (one line per
    recorded decision, most informative output fields per kind)."""
    kinds = ", ".join(f"{k}={n}" for k, n in sorted(doc["kinds"].items()))
    lines = [f"request {doc['rid']}: {doc['decisions']} recorded "
             f"decision(s){' (' + kinds + ')' if kinds else ''}"]
    for s in doc["steps"]:
        out, kind = s["outputs"], s["kind"]
        if kind == "admission":
            detail = (f"admit={_fmt(out.get('admit'))} "
                      f"reason={out.get('reason')}")
        elif kind == "coalesce":
            detail = (f"picked={out.get('picked')} "
                      f"promoted={out.get('promoted')}")
        elif kind == "route":
            detail = (f"shard={out.get('shard')} owner={out.get('owner')} "
                      f"diverted={_fmt(out.get('diverted'))} "
                      f"hops={out.get('hops')}")
        elif kind == "retry":
            detail = (f"retry={_fmt(out.get('retry'))} "
                      f"delay_s={_fmt(out.get('delay_s'))} "
                      f"reason={out.get('reason')} "
                      f"cause={s['inputs'].get('cause')}")
        elif kind == "containment":
            detail = (f"mode={out.get('mode')} "
                      f"cause={s['inputs'].get('cause')}")
        else:
            detail = " ".join(
                f"{k}={_fmt(v)}" for k, v in list(out.items())[:4])
        lines.append(f"  seq={s['seq']} {kind}: {detail}")
    return "\n".join(lines)


def render_explain(doc: dict) -> str:
    """The causality table as plain text (one row per lane)."""
    head = (f"split seq={doc.get('seq')} cid={doc.get('cid')} "
            f"action={doc.get('action')} total={doc.get('total')} "
            f"step={doc.get('step')}")
    cols = [
        ("lane", "lane"), ("bench_ms", "bench_ms"),
        ("transfer_ms", "xfer_ms"), ("floor_margin_ms", "floor_margin"),
        ("effective_ms", "eff_ms"), ("share", "share"),
        ("damp", "damp"), ("damped_move_items", "move"),
        ("cont_items", "cont"), ("range_items", "range"),
        ("quantization_residue_items", "residue"), ("binding", "binding"),
    ]
    rows = [[_fmt(lane.get(k)) for k, _h in cols]
            for lane in doc.get("lanes", ())]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, (_k, h) in enumerate(cols)]
    lines = [head]
    if doc.get("freeze"):
        fz = doc["freeze"]
        # the margin as RECORDED (what this freeze compared against);
        # pre-margin logs fall back to naming the constant
        margin = fz.get("margin")
        margin_s = _fmt(margin, 2) if margin is not None else "FREEZE_MARGIN"
        lines.append(
            "  held: busiest lane "
            f"{fz.get('lane')} excess {_fmt(fz.get('excess_ms'))} ms < "
            f"{margin_s} x one-step work "
            f"{_fmt(fz.get('one_step_work_ms'))} ms")
    lines.append("  ".join(
        h.rjust(widths[i]) for i, (_k, h) in enumerate(cols)))
    for r in rows:
        lines.append("  ".join(c.rjust(widths[i]) for i, c in enumerate(r)))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ckreplay",
        description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_v = sub.add_parser("verify", help="replay-verify a log bit-identically")
    p_v.add_argument("log", help="decision jsonl (or ck-postmortem-v2 JSON)")
    p_v.add_argument("--json", action="store_true")

    p_w = sub.add_parser("whatif", help="counterfactual chained re-run")
    p_w.add_argument("log")
    p_w.add_argument("--set", dest="overrides", required=True,
                     help="knobs, e.g. damping=0.1,jump_start=off,"
                          "transfer_floor=off,smoothing=off,overhead_ms=2")
    p_w.add_argument("--cid", type=int, default=None,
                     help="compute id to chain (default: the first logged)")
    p_w.add_argument("--horizon", type=int, default=200,
                     help="max simulated iterations (default 200)")
    p_w.add_argument("--json", action="store_true")

    p_e = sub.add_parser("explain", help="latest split's causality table "
                                         "(--rid: one request's history)")
    p_e.add_argument("log")
    p_e.add_argument("--cid", type=int, default=None)
    p_e.add_argument("--rid", default=None,
                     help="pivot to one request id: every decision whose "
                          "inputs named this rid, in seq order")
    p_e.add_argument("--json", action="store_true")

    p_d = sub.add_parser("demo", help="record a synthetic convergence log")
    p_d.add_argument("--out", default="/tmp/ck_decision_demo.jsonl")
    p_d.add_argument("--lanes", type=int, default=3)
    p_d.add_argument("--steps", type=int, default=12)
    p_d.add_argument("--hetero", action="store_true",
                     help="prior-seeded 1 fast + 1 slow (100x) chain "
                          "instead (the golden_hetero_prior generator)")

    args = ap.parse_args(argv)

    if args.cmd == "demo":
        if args.hetero:
            path = demo_hetero_log(args.out, steps=args.steps)
        else:
            path = demo_log(args.out, lanes=args.lanes, steps=args.steps)
        print(f"ckreplay: demo log written to {path}")
        return 0

    records = load_records(args.log)
    if not records:
        print(f"ckreplay: no decision records in {args.log} — arm "
              "CK_DECISION_LOG on the run (or pass a ck-postmortem-v2 "
              "dump), or generate one with `python -m tools.ckreplay "
              "demo`", file=sys.stderr)
        return 1

    if args.cmd == "verify":
        from cekirdekler_tpu.obs.replay import verify_records

        verdict = verify_records(records)
        if args.json:
            print(json.dumps(verdict, indent=2, allow_nan=False,
                             default=str))
            return 0 if verdict["ok"] else 1
        kinds = ", ".join(f"{k}={n}" for k, n in
                          sorted(verdict["per_kind"].items()))
        if verdict["ok"]:
            print(f"ckreplay verify OK: {verdict['replayed']} replayed "
                  f"bit-identically, {verdict['skipped']} context records "
                  f"skipped ({kinds})")
            return 0
        first = verdict["first_divergence"]
        print(f"ckreplay verify FAIL: first divergent seq="
              f"{first['seq']} kind={first['kind']}")
        for field, d in (first.get("mismatch") or {}).items():
            print(f"  {field}: expected {d.get('expected')!r} "
                  f"got {d.get('got')!r}")
        more = verdict["divergent"] - 1
        if more > 0:
            print(f"  (+{more} further divergent record(s) of "
                  f"{verdict['replayed']} replayed)")
        print("  a divergence means the decision code no longer "
              "reproduces this log: a knob/algorithm change, or hidden "
              "nondeterminism")
        return 1

    if args.cmd == "whatif":
        from cekirdekler_tpu.obs.replay import whatif

        overrides = parse_overrides(args.overrides)
        if not overrides:
            raise SystemExit("ckreplay: --set parsed to no overrides")
        rep = whatif(records, overrides, cid=args.cid,
                     horizon=args.horizon)
        if args.json:
            print(json.dumps(rep, indent=2, allow_nan=False, default=str))
            return 0
        print(f"ckreplay whatif cid={rep.get('cid')} overrides="
              f"{rep.get('overrides')} "
              f"(chained over {rep.get('recorded_steps')} recorded steps)")
        f, c = rep.get("factual"), rep.get("counterfactual")
        if f and c:
            print(f"  factual:        converge@{f['iterations_to_converge']}"
                  f" (settled={f['converged']}) final={f['final_ranges']}")
            print(f"  counterfactual: converge@{c['iterations_to_converge']}"
                  f" (settled={c['converged']}) final={c['final_ranges']}")
            print(f"  final-split L1 distance: {rep.get('final_split_l1')} "
                  "items")
            d = (c["iterations_to_converge"] - f["iterations_to_converge"])
            if d > 0:
                print(f"  -> counterfactual converges {d} iteration(s) "
                      "LATER")
            elif d < 0:
                print(f"  -> counterfactual converges {-d} iteration(s) "
                      "EARLIER")
        if "chunk_choices" in rep:
            print(f"  chunk choices: {rep['chunk_choices_changed']} of "
                  f"{len(rep['chunk_choices'])} transfer-choose decisions "
                  "changed")
            for ch in rep["chunk_choices"]:
                if ch["factual"] != ch["counterfactual"]:
                    print(f"    seq={ch['seq']} lane={ch['lane']}: "
                          f"{ch['factual']} -> {ch['counterfactual']}")
        if "block_choices" in rep:
            print(f"  block choices: {rep['block_choices_changed']} of "
                  f"{len(rep['block_choices'])} block-retune decisions "
                  "changed")
            for ch in rep["block_choices"]:
                if ch["factual"] != ch["counterfactual"]:
                    print(f"    seq={ch['seq']} {ch['kernel_sig']}: "
                          f"{ch['factual']} -> {ch['counterfactual']} "
                          f"({ch['why']})")
        return 0

    if args.cmd == "explain":
        from cekirdekler_tpu.obs.replay import explain_latest, explain_rid

        if args.rid is not None:
            doc = explain_rid(records, args.rid)
            if not doc["decisions"]:
                print(f"ckreplay: no decision in this log names rid "
                      f"{args.rid!r} (rid-bearing records need the "
                      "decision log armed while the request ran)",
                      file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(doc, indent=2, allow_nan=False,
                                 default=str))
            else:
                print(render_explain_rid(doc))
            return 0
        doc = explain_latest(records, cid=args.cid)
        if doc is None:
            print("ckreplay: no load-balance records "
                  f"{'for cid ' + str(args.cid) if args.cid is not None else ''}"
                  " in this log", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(doc, indent=2, allow_nan=False, default=str))
        else:
            print(render_explain(doc))
        return 0

    return 2  # unreachable: subparsers are required


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `ckreplay ... | head` is a legit use
        sys.exit(0)
