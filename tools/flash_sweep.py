"""Flash-attention block/phase sweep on the real chip (VERDICT r4 #2).

Separates forward-only and fwd+bwd cost per (T, block_q, block_k) so the
T=8192 regression can be attributed (fwd kernel? dq kernel? dkv kernel?
block config?) instead of guessed at.

Round-6: ``default`` rows now exercise the bf16 end-to-end kernels
(f32 inputs cast once at XLA level, bf16 streamed through fwd+bwd) with
compact lse/delta operands and causal DMA elision; a third
``default-bf16io`` variant feeds bf16 inputs directly, isolating the
kernel from the one-time cast.  MFU per row against the matching
roofline so block choices compare across precisions.

Methodology (see docs + round-4 notes): the tunnel's dispatch latency is
~RTT (today's weather: can exceed 100 ms), so a python loop of jitted
calls measures the link, not the chip — every rep anomaly (bwd "faster"
than fwd) is dispatch noise.  Here the dependent chain runs INSIDE one
jitted ``lax.fori_loop`` (each step perturbs the inputs by the previous
step's output so nothing hoists or elides), one dispatch, one
materialization, measured RTT subtracted once.

Usage: python tools/flash_sweep.py [T ...]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bench_loop(step, args, reps=8, trials=3, rtt=0.0):
    """The shared dependent-chain harness — one implementation, one place
    for the elision traps (see its docstring)."""
    from cekirdekler_tpu.workloads import fori_chain_bench

    return fori_chain_bench(step, args, reps, trials=trials, rtt=rtt)


def main(Ts=(4096, 8192), B=1, H=8, D=64):
    from cekirdekler_tpu.ops.flash_attention import flash_attention
    from cekirdekler_tpu.parallel.attention import attention_reference
    from cekirdekler_tpu.workloads import measure_rtt

    rtt = measure_rtt()
    print(f"rtt_ms={rtt*1e3:.1f}  B={B} H={H} D={D}")
    rng = np.random.default_rng(0)
    for T in Ts:
        mk = lambda: jnp.asarray(
            rng.standard_normal((B, T, H, D)).astype(np.float32) * 0.3)
        q, k, v = mk(), mk(), mk()
        # causal fwd+bwd FLOPs: fwd 4*T^2*D per (b,h) + bwd 12*T^2*D,
        # halved by causality
        flops = 0.5 * 16 * B * H * T * T * D
        flops_fwd = 0.5 * 4 * B * H * T * T * D

        t = bench_loop(
            lambda q, k, v: attention_reference(q, k, v, causal=True),
            (q, k, v), rtt=rtt)
        print(f"T={T} dense fwd: {t*1e3:8.2f} ms  "
              f"{flops_fwd/t/1e12:6.2f} Tflop/s")
        t = bench_loop(
            jax.grad(lambda q, k, v: attention_reference(
                q, k, v, causal=True).sum(), argnums=(0, 1, 2)),
            (q, k, v), rtt=rtt)
        print(f"T={T} dense fwd+bwd: {t*1e3:8.2f} ms  "
              f"{flops/t/1e12:6.2f} Tflop/s")

        # MFU denominators: "highest" is true-f32 multi-pass (~peak/6),
        # the bf16 variants run against the bf16 peak — ONE source of
        # truth for the rooflines (bench.py), so sweep MFU stays
        # comparable to the bench artifact's mfu_default
        from bench import V5E_PEAK_BF16_TFLOPS, V5E_PEAK_F32_TFLOPS

        peaks = {"highest": V5E_PEAK_F32_TFLOPS,
                 "default": V5E_PEAK_BF16_TFLOPS,
                 "default-bf16io": V5E_PEAK_BF16_TFLOPS}
        qb = kb = vb = None
        for (bq, bk) in ((256, 512), (512, 512), (512, 1024), (256, 1024),
                         (1024, 512), (1024, 1024), (128, 512)):
            for prec in ("highest", "default", "default-bf16io"):
                args, p = (q, k, v), prec
                if prec == "default-bf16io":
                    # bf16 operands in HBM: isolates the kernels from the
                    # per-call f32->bf16 cast the plain default row pays
                    if qb is None:
                        qb, kb, vb = (a.astype(jnp.bfloat16)
                                      for a in (q, k, v))
                    args, p = (qb, kb, vb), "default"
                fwd = lambda q, k, v, bq=bq, bk=bk, p=p: flash_attention(
                    q, k, v, True, bq, bk, None, p)
                g = jax.grad(
                    lambda q, k, v, bq=bq, bk=bk, p=p: flash_attention(
                        q, k, v, True, bq, bk, None, p)
                    .astype(jnp.float32).sum(),
                    argnums=(0, 1, 2))
                try:
                    tf = bench_loop(fwd, args, rtt=rtt)
                    tg = bench_loop(g, args, rtt=rtt)
                except Exception as e:
                    print(f"T={T} flash {bq}/{bk} {prec}: FAIL "
                          f"{type(e).__name__}: {e}"[:120])
                    continue
                mfu = flops / tg / 1e12 / peaks[prec]
                print(f"T={T} flash {bq}/{bk} {prec:15s}: "
                      f"fwd {tf*1e3:8.2f} ms ({flops_fwd/tf/1e12:5.2f}) "
                      f"fwd+bwd {tg*1e3:8.2f} ms  "
                      f"{flops/tg/1e12:6.2f} Tflop/s  mfu={mfu:.3f}")


if __name__ == "__main__":
    Ts = tuple(int(a) for a in sys.argv[1:]) or (4096, 8192)
    main(Ts)
